"""Server-side implementations of cluster ops (twin of sky/core.py).

status / start / stop / down / autostop / queue / cancel / tail_logs —
thin orchestration over the state DB + backend + provisioner, with status
reconciliation against cloud truth (twin of
backend_utils.refresh_cluster_status_handle, SURVEY §3.5).
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional

from skypilot_tpu import check as check_lib
from skypilot_tpu import exceptions
from skypilot_tpu import provision as provision_lib
from skypilot_tpu import sky_logging
from skypilot_tpu import state
from skypilot_tpu.backends import tpu_gang_backend

logger = sky_logging.init_logger(__name__)


def _backend() -> tpu_gang_backend.TpuGangBackend:
    return tpu_gang_backend.TpuGangBackend()


def _get_handle(cluster_name: str):
    record = state.get_cluster_from_name(cluster_name)
    if record is None:
        raise exceptions.ClusterDoesNotExist(
            f'Cluster {cluster_name!r} does not exist.')
    return record


def refresh_cluster_status(cluster_name: str) -> Optional[Dict[str, Any]]:
    """Reconcile one cluster's DB status against cloud truth.

    Detects externally-terminated / preempted / stopped clusters, like the
    reference's refresh path (sky/backends/backend_utils.py, §3.5).
    """
    record = state.get_cluster_from_name(cluster_name)
    if record is None:
        return None
    handle = record['handle']
    if handle is None:
        return record
    cloud = handle.launched_resources.cloud
    try:
        statuses = provision_lib.query_instances(
            cloud.provisioner_module, cluster_name,
            handle.cluster_info.provider_config)
    except Exception as e:  # pylint: disable=broad-except
        logger.warning(f'Status refresh for {cluster_name} failed: {e}')
        return record
    if not statuses:
        # Cloud says gone: preempted or externally deleted.
        state.remove_cluster(cluster_name, terminate=True)
        return None
    # Providers report unrecoverably-dead instances (spot-preempted TPU
    # corpses, terminated EC2) as None: all-dead means the cluster can
    # never run again — same as gone, so recovery relaunches instead of
    # waiting on INIT forever.
    if all(s is None for s in statuses.values()):
        state.remove_cluster(cluster_name, terminate=True)
        return None
    if all(s == 'STOPPED' for s in statuses.values()):
        state.update_cluster_status(cluster_name,
                                    state.ClusterStatus.STOPPED)
    elif any(s != 'RUNNING' for s in statuses.values()):
        state.update_cluster_status(cluster_name, state.ClusterStatus.INIT)
    # Enforce agent-triggered autostop (pull model; see
    # TpuGangBackend.check_autostop_trigger).
    if record['status'] == state.ClusterStatus.UP:
        backend = _backend()
        try:
            trigger = backend.check_autostop_trigger(handle)
        except Exception:  # pylint: disable=broad-except
            trigger = None
        if trigger is not None:
            logger.info(f'Cluster {cluster_name}: autostop triggered '
                        f'(down={trigger.get("down", False)}).')
            try:
                backend.teardown(handle,
                                 terminate=bool(trigger.get('down')))
            except exceptions.NotSupportedError:
                # Stop unsupported (TPU pod): fall back to teardown,
                # matching the documented autostop semantics for pods.
                backend.teardown(handle, terminate=True)
            return state.get_cluster_from_name(cluster_name)
    return state.get_cluster_from_name(cluster_name)


def status(cluster_names: Optional[List[str]] = None,
           refresh: bool = False,
           workspace: Optional[str] = None,
           limit: Optional[int] = None,
           offset: int = 0) -> List[Dict[str, Any]]:
    """Cluster records, paginated.

    Name/workspace filters and limit/offset push down into SQL
    (state.get_clusters): a point `status CLUSTER` or a dashboard page
    of 100 must not scan and unpickle a 5k-cluster fleet. Page
    stability comes from the state layer's deterministic ordering
    (launched_at DESC, then name).
    """
    if workspace is None:
        # Honor a pinned workspace (XSKY_WORKSPACE); with no pin, show
        # everything — the admin-friendly default.
        import os
        workspace = os.environ.get('XSKY_WORKSPACE') or None
    records = state.get_clusters(workspace=workspace,
                                 names=list(cluster_names)
                                 if cluster_names else None,
                                 limit=limit, offset=offset)
    if refresh:
        # Each refresh is a cloud API round trip (plus an autostop
        # probe against the head host): fan the clusters out instead
        # of paying the sum of every provider's latency. Per-cluster
        # provider errors are already swallowed inside
        # refresh_cluster_status, so one unreachable cloud cannot
        # fail the whole status call.
        from skypilot_tpu.utils import parallelism
        from skypilot_tpu.utils import tracing
        with tracing.span('status_refresh', clusters=len(records)):
            refreshed = parallelism.run_in_parallel(
                lambda r: refresh_cluster_status(r['name']), records,
                phase='status_refresh', what='status refresh')
        records = [r for r in refreshed if r is not None]
    return records


def start(cluster_name: str,
          idle_minutes_to_autostop: Optional[int] = None,
          down: bool = False) -> None:
    record = _get_handle(cluster_name)
    if record['status'] == state.ClusterStatus.UP:
        return
    handle = record['handle']
    cloud = handle.launched_resources.cloud
    # Restart stopped instances through the provisioner.
    from skypilot_tpu.provision import common as provision_common
    config = provision_common.ProvisionConfig(
        provider_config=handle.cluster_info.provider_config,
        node_config=cloud.make_deploy_resources_variables(
            handle.launched_resources, cluster_name,
            handle.launched_resources.region,
            handle.launched_resources.zone),
        count=handle.num_nodes)
    record2 = provision_lib.run_instances(
        cloud.provisioner_module, handle.launched_resources.region,
        handle.launched_resources.zone, cluster_name, config)
    # Re-run runtime setup: restarted VMs may have new IPs, and the head
    # agent daemon died with the stop — refresh the handle's inventory
    # and bring the runtime back up before marking UP.
    handle.cluster_info = provision_lib.get_cluster_info(
        cloud.provisioner_module, record2.region, cluster_name,
        handle.cluster_info.provider_config)
    backend = _backend()
    backend._setup_runtime(handle)  # pylint: disable=protected-access
    state.add_or_update_cluster(cluster_name, handle, ready=True,
                                is_launch=False)
    if idle_minutes_to_autostop is not None:
        autostop(cluster_name, idle_minutes_to_autostop, down)


def stop(cluster_name: str) -> None:
    record = _get_handle(cluster_name)
    handle = record['handle']
    # Feature-check before touching the cloud (TPU pods cannot stop).
    from skypilot_tpu.clouds import CloudImplementationFeatures as F
    resources = handle.launched_resources
    type(resources.cloud).check_features_are_supported(
        resources, {F.STOP})
    _backend().teardown(handle, terminate=False)


def down(cluster_name: str, purge: bool = False) -> None:
    import filelock
    try:
        # Bounded wait: a launch may hold the cluster lock for a long
        # retry-until-up loop; surface that instead of hanging 10 min
        # and leaking a raw filelock.Timeout.
        lock = state.cluster_lock(cluster_name, timeout=60)
        with lock:
            record = _get_handle(cluster_name)
            handle = record['handle']
            if handle is None:
                state.remove_cluster(cluster_name, terminate=True)
                return
            _backend().teardown(handle, terminate=True, purge=purge)
    except filelock.Timeout as e:
        raise exceptions.ClusterNotUpError(
            f'Cluster {cluster_name!r} is busy (a launch/lifecycle '
            'operation holds its lock); retry after it finishes or '
            'cancel the pending operation.', cluster_status=None) from e


def autostop(cluster_name: str, idle_minutes: int,
             down_on_idle: bool = False) -> None:  # noqa: A002
    record = _get_handle(cluster_name)
    _backend().set_autostop(record['handle'], idle_minutes, down_on_idle)


def queue(cluster_name: str) -> List[Dict[str, Any]]:
    record = _get_handle(cluster_name)
    return _backend().get_job_queue(record['handle'])


def endpoints(cluster_name: str,
              port: Optional[int] = None) -> Dict[int, str]:
    """port → reachable URL for the cluster's opened ports (twin of
    `sky status --endpoint`, backed by the provision query_ports op —
    kubernetes resolves NodePort indirection, VM clouds map the head
    IP)."""
    record = _get_handle(cluster_name)
    handle = record['handle']
    info = getattr(handle, 'cluster_info', None)
    resources = getattr(handle, 'launched_resources', None)
    ports = list(resources.ports or []) if resources is not None else []
    if info is None or not ports:
        return {}
    from skypilot_tpu import provision as provision_lib
    out = provision_lib.query_ports(
        info.provider_name, cluster_name, ports,
        info.provider_config or {}, info)
    if port is not None:
        return {p: u for p, u in out.items() if p == port}
    return out


def cluster_hosts(cluster_name: str) -> List[Dict[str, Any]]:
    """Per-host inventory of a cluster (dashboard drill-down; twin of
    the reference's per-cluster page host table,
    sky/dashboard/src/pages/clusters/[cluster].js).

    Host identity/IPs come from the recorded handle; status is
    queried live from the provider when reachable (the handle snapshot
    is launch-time state — a stopped or preempted cluster would
    otherwise show every host RUNNING), falling back to the snapshot
    marked as such.
    """
    record = _get_handle(cluster_name)
    handle = record['handle']
    info = getattr(handle, 'cluster_info', None)
    if info is None:
        return []
    live: Dict[str, Optional[str]] = {}
    try:
        from skypilot_tpu import provision as provision_lib
        live = provision_lib.query_instances(
            info.provider_name, cluster_name, info.provider_config)
    except Exception:  # pylint: disable=broad-except
        pass  # unreachable provider: snapshot below, labeled
    def host_status(h) -> str:
        if h.instance_id in live:
            # None from query_instances means "gone" (cross-provider
            # convention for terminated/preempted corpses).
            return live[h.instance_id] or 'TERMINATED'
        return f'{h.status} (at launch)'

    return [{
        'instance_id': h.instance_id,
        'internal_ip': h.internal_ip,
        'external_ip': h.external_ip,
        'status': host_status(h),
        'slice_id': h.slice_id,
        'host_index': h.host_index,
    } for h in info.sorted_instances()]


def cancel(cluster_name: str, job_ids: Optional[List[int]] = None,
           all_jobs: bool = False) -> None:
    record = _get_handle(cluster_name)
    backend = _backend()
    if all_jobs:
        job_ids = [j['job_id'] for j in backend.get_job_queue(
            record['handle'])
            if j['status'] in ('PENDING', 'SETTING_UP', 'RUNNING')]
    backend.cancel_jobs(record['handle'], job_ids or [])


def tail_logs(cluster_name: str, job_id: Optional[int] = None,
              follow: bool = False, all_ranks: bool = False) -> str:
    record = _get_handle(cluster_name)
    return _backend().tail_logs(record['handle'], job_id, follow=follow,
                                all_ranks=all_ranks)


def profile_capture(cluster_name: str, job_id: Optional[int] = None,
                    duration_s: float = 1.0) -> Dict[int, Dict[str, Any]]:
    """On-demand deep device capture on every host of a cluster (one
    runner fan-out): {rank: capture summary}. Artifacts (jax.profiler
    trace dirs) stay on the hosts; the summaries are recorded into the
    bounded profiles table (kind='capture') so `xsky profile` shows
    them next to the always-on step-anatomy rows."""
    from skypilot_tpu.agent import profiler
    from skypilot_tpu.utils import tracing
    record = _get_handle(cluster_name)
    with tracing.span('profile.capture', cluster=cluster_name,
                      job=job_id):
        summaries = _backend().capture_device_profile(
            record['handle'], job_id=job_id, duration_s=duration_s)
        profiler.record_profiles(cluster_name, job_id, summaries,
                                 kind='capture')
    return summaries


def goodput_report(cluster_name: Optional[str] = None,
                   fleet: bool = False,
                   limit: int = 1000) -> Dict[str, Any]:
    """Goodput attribution report (`xsky goodput`).

    With a cluster name: a LIVE fold of that cluster's attribution
    ledger — every second of the job's lifetime decomposed by cause,
    chip-weighted across elastic incarnations. Without one (or with
    ``fleet=True``): the fleet rollup of the latest persisted per-job
    ledgers (loss-by-cause across live clusters). Both are pure reads
    over the bounded observability tables — no handle needed, so the
    report survives the cluster it describes."""
    from skypilot_tpu.agent import goodput
    from skypilot_tpu.utils import tracing
    if fleet or cluster_name is None:
        with tracing.span('goodput.report', fleet=True):
            report = goodput.fleet_report(limit=limit)
        return {'kind': 'fleet', 'report': report}
    with tracing.span('goodput.report', cluster=cluster_name):
        ledger = goodput.build_ledger(cluster_name)
    return {'kind': 'cluster', 'ledger': ledger}


def metrics_list(prefix: Optional[str] = None,
                 since: Optional[float] = None,
                 limit: int = 200,
                 offset: int = 0) -> List[Dict[str, Any]]:
    """Recorded metric series (`xsky metrics list`): every distinct
    (name, label set) the history recorder has sampled, with point
    counts and freshness. Pure read over the bounded metric_points
    table — works with no cluster up."""
    from skypilot_tpu.utils import tracing
    with tracing.span('metrics.query', kind='list', prefix=prefix):
        return state.list_metric_series(prefix=prefix, since=since,
                                        limit=limit, offset=offset)


def metrics_query(name: str,
                  labels: Optional[Dict[str, Any]] = None,
                  since: Optional[float] = None,
                  until: Optional[float] = None,
                  step: Optional[float] = None,
                  agg: str = 'avg',
                  res: Optional[str] = None) -> Dict[str, Any]:
    """Trend query over the metrics history plane (`xsky metrics
    query`): bucketed aggregation with counter-aware rate() and
    windowed histogram quantiles — the same metrics_history.series()
    read API the autoscaler/LB arc consumes, with wire-shaped
    metadata."""
    from skypilot_tpu.utils import metrics_history
    from skypilot_tpu.utils import tracing
    with tracing.span('metrics.query', kind='query', metric=name,
                      agg=agg):
        return metrics_history.query(name, labels=labels, since=since,
                                     until=until, step=step, agg=agg,
                                     res=res)


def watch_job_log(cluster_name: str, job_id: int,
                  offset: int = 0) -> Dict[str, Any]:
    """One incremental poll of a cluster job's run.log → {status,
    offset, log}. Powers the dashboard's live tail (one remote exec
    per poll — same hot path the launch wait loop uses)."""
    record = _get_handle(cluster_name)
    return _backend().watch_job_log(record['handle'], job_id, offset)


def sync_down_logs(cluster_name: str, job_id: Optional[int] = None,
                   local_dir: Optional[str] = None) -> str:
    """Download job logs from a cluster; returns the local directory
    (twin of `sky logs --sync-down`)."""
    record = _get_handle(cluster_name)
    return _backend().sync_down_logs(record['handle'], job_id=job_id,
                                     local_dir=local_dir)


def check(quiet: bool = False) -> Dict[str, Any]:
    """Probe credentials; persist enabled clouds (twin of sky check)."""
    results = check_lib.check_capabilities(quiet=quiet)
    enabled = [name for name, (ok, _) in results.items() if ok]
    state.set_enabled_clouds(enabled)
    check_lib.set_enabled_clouds_for_test(enabled)
    return {name: {'enabled': ok, 'reason': reason}
            for name, (ok, reason) in results.items()}


def list_accelerators(name_filter: Optional[str] = None,
                      gpus_only: bool = False) -> List[Dict[str, Any]]:
    """Accelerator offerings across every in-tree catalog, as plain
    dicts for the wire (`accelerators` verb — the dashboard infra view
    and remote `show-gpus` twins of sky/core.py list_accelerators)."""
    from skypilot_tpu import catalog
    offerings = catalog.list_accelerators(name_filter=name_filter,
                                          gpus_only=gpus_only)
    return [{
        'accelerator_name': o.accelerator_name,
        'accelerator_count': o.accelerator_count,
        'cloud': o.cloud,
        'instance_type': o.instance_type,
        'regions': list(o.regions),
        'price': o.price,
        'spot_price': o.spot_price,
        'memory_gib': o.memory_gib,
    } for name in sorted(offerings) for o in offerings[name]]


def cost_report() -> List[Dict[str, Any]]:
    """Per-cluster cost: catalog rate × billable uptime.

    Billable uptime comes from the cluster's usage intervals (the clock
    pauses while STOPPED), and torn-down clusters stay in the report
    via cluster_history — twin of the reference's duration-based
    cost_report rather than a naive price × wall-clock estimate.
    """
    def _rate_of(handle):
        if handle is None:
            return None, 0.0
        resources = handle.launched_resources
        try:
            return resources, resources.get_hourly_cost()
        except ValueError:
            return resources, 0.0

    out = []
    for record in state.get_clusters():
        resources, rate = _rate_of(record['handle'])
        if resources is None:
            continue
        intervals = record.get('usage_intervals')
        if (not intervals and record.get('launched_at')
                and record['status'] != state.ClusterStatus.STOPPED):
            # Rows created before the usage_intervals migration have no
            # recorded intervals; fall back to wall-clock since launch
            # rather than reporting a live cluster as zero-cost. STOPPED
            # rows are excluded: their clock is paused and the stop time
            # was never recorded, so an open interval would overbill.
            intervals = [(record['launched_at'], None)]
        hours = state.billed_seconds(intervals) / 3600.0
        out.append({
            'name': record['name'],
            'resources': str(resources),
            'status': record['status'].value,
            'hourly_cost': rate,
            'uptime_hours': hours,
            'total_cost': rate * hours,
        })
    for record in state.get_cluster_history():
        resources, rate = _rate_of(record['handle'])
        if resources is None:
            continue
        hours = (record['duration_s'] or 0.0) / 3600.0
        out.append({
            'name': record['name'],
            'resources': str(resources),
            'status': 'TERMINATED',
            'hourly_cost': rate,
            'uptime_hours': hours,
            'total_cost': rate * hours,
        })
    return out


def storage_ls() -> List[Dict[str, Any]]:
    """Twin of sky storage ls (server-side)."""
    out = []
    for record in state.get_storage():
        handle = record['handle'] or {}
        out.append({
            'name': record['name'],
            'status': record['status'].value,
            'stores': sorted((handle.get('stores') or {}).keys()),
            'source': handle.get('source'),
        })
    return out


def storage_ls_objects(storage_name: str, prefix: str = '',
                       limit: int = 100) -> List[str]:
    """First `limit` object keys of a storage's primary store
    (`storage.ls_objects` verb — dashboard drill + `storage ls NAME`)."""
    from skypilot_tpu.data import storage as storage_lib
    record = state.get_storage_from_name(storage_name)
    if record is None:
        raise exceptions.StorageError(f'Storage {storage_name!r} not found.')
    return storage_lib.Storage.from_handle(record['handle']).list_objects(
        prefix=prefix, limit=int(limit))


def storage_delete(storage_name: str) -> None:
    """Delete one storage (managed buckets removed; external kept)."""
    from skypilot_tpu.data import storage as storage_lib
    record = state.get_storage_from_name(storage_name)
    if record is None:
        raise exceptions.StorageError(f'Storage {storage_name!r} not found.')
    storage_lib.Storage.from_handle(record['handle']).delete()
