"""Failover engine tests (twin of tests/test_failover.py with moto)."""
import pytest

from skypilot_tpu import Resources, Task
from skypilot_tpu import exceptions
from skypilot_tpu.backends import failover


def _tpu_task():
    t = Task(run='python train.py')
    t.set_resources(Resources(accelerators='tpu-v5e-8'))
    return t


class TestZoneFailover:

    def test_capacity_error_fails_over_to_next_zone(self, fake_cluster_env):
        fake = fake_cluster_env
        fake.injector.fail_zone('fake-central1-a',
                                exceptions.CapacityError('stockout'))
        provisioner = failover.RetryingProvisioner(_tpu_task(), 'c1', 1)
        result = provisioner.provision_with_retries()
        assert result.record.zone != 'fake-central1-a'
        assert 'fake-central1-a' in fake.injector.attempts

    def test_quota_error_blocks_whole_region(self, fake_cluster_env):
        fake = fake_cluster_env
        fake.injector.fail_zone('fake-central1-a',
                                exceptions.QuotaExceededError('quota'))
        provisioner = failover.RetryingProvisioner(_tpu_task(), 'c1', 1)
        result = provisioner.provision_with_retries()
        # Region fake-central1 has zone -b too; quota must skip it.
        assert not result.record.zone.startswith('fake-central1')

    def test_all_zones_blocked_raises(self, fake_cluster_env):
        fake = fake_cluster_env
        fake.injector.fail_zone('*', exceptions.CapacityError('stockout'))
        provisioner = failover.RetryingProvisioner(_tpu_task(), 'c1', 1)
        with pytest.raises(exceptions.ResourcesUnavailableError) as e:
            provisioner.provision_with_retries()
        assert e.value.failover_history  # carries what was tried

    def test_invalid_request_no_failover(self, fake_cluster_env):
        fake = fake_cluster_env
        fake.injector.fail_zone(
            'fake-central1-a',
            exceptions.InvalidRequestError('bad runtime version'))
        provisioner = failover.RetryingProvisioner(_tpu_task(), 'c1', 1)
        with pytest.raises(exceptions.ResourcesUnavailableError) as e:
            provisioner.provision_with_retries()
        assert e.value.no_failover

    def test_gpu_to_tpu_sku_failover(self, fake_cluster_env):
        """North star: GPU blocked everywhere → lands on a TPU slice."""
        fake = fake_cluster_env
        task = Task(run='train')
        task.set_resources([
            Resources(accelerators='tpu-v5e-8'),
            Resources(accelerators='FAKEGPU:8'),
        ], ordered=True)
        # TPU (user's first choice) is stocked out once per zone; after
        # the TPU SKU exhausts all 4 zones, the GPU attempt in the first
        # zone succeeds (its one scripted error was already consumed).
        for zone in ['fake-central1-a', 'fake-central1-b', 'fake-west1-a',
                     'fake-east1-a']:
            fake.injector.fail_zone(zone,
                                    exceptions.CapacityError('tpu out'),
                                    times=1)
        provisioner = failover.RetryingProvisioner(task, 'c1', 1)
        result = provisioner.provision_with_retries()
        assert result.resources.accelerators == {'FAKEGPU': 8}
        assert len(provisioner.failover_history) == 4

    def test_reserved_to_spot_to_ondemand_walk(self, fake_cluster_env):
        """provisioning_model 'auto' + a reservation: the failover
        engine tries the reservation first (prepaid), then spot, then
        on-demand — stocking out one model must not block the others
        (VERDICT r2 #6; twin of reservation-priority the reference has
        only for GPUs)."""
        fake = fake_cluster_env
        task = Task(run='train')
        task.set_resources(Resources(
            accelerators='tpu-v5e-8',
            accelerator_args={'provisioning_model': 'auto',
                              'reservation': 'my-reservation'}))
        # Stock out the reservation everywhere and spot everywhere; the
        # on-demand attempt succeeds.
        fake.injector.fail_match(
            lambda cfg: cfg.get('provisioning_model') == 'reserved',
            exceptions.CapacityError('reservation exhausted'), times=8)
        fake.injector.fail_match(
            lambda cfg: cfg.get('provisioning_model') == 'spot',
            exceptions.CapacityError('spot stockout'), times=8)
        provisioner = failover.RetryingProvisioner(task, 'walk', 1)
        result = provisioner.provision_with_retries()
        models = [cfg.get('provisioning_model')
                  for cfg in fake.injector.attempt_configs]
        # Reserved tried before any spot, spot before any standard.
        assert 'reserved' in models and 'spot' in models
        assert models.index('reserved') < models.index('spot')
        assert models.index('spot') < models.index('standard')
        assert result.resources.effective_provisioning_model() == \
            'standard'
        # The reserved attempts carried the reservation; on-demand not.
        reserved_cfgs = [c for c in fake.injector.attempt_configs
                         if c.get('provisioning_model') == 'reserved']
        assert all(c.get('reservation') == 'my-reservation'
                   for c in reserved_cfgs)

    def test_reservation_attempt_succeeds_first(self, fake_cluster_env):
        """With capacity available, 'auto' lands on the reservation and
        never touches spot/on-demand."""
        fake = fake_cluster_env
        task = Task(run='train')
        task.set_resources(Resources(
            accelerators='tpu-v5e-8',
            accelerator_args={'provisioning_model': 'auto',
                              'reservation': 'my-reservation'}))
        provisioner = failover.RetryingProvisioner(task, 'res1', 1)
        result = provisioner.provision_with_retries()
        assert result.resources.effective_provisioning_model() == \
            'reserved'
        models = {cfg.get('provisioning_model')
                  for cfg in fake.injector.attempt_configs}
        assert models == {'reserved'}

    def test_tpu_pod_creates_hosts(self, fake_cluster_env):
        task = Task(run='train')
        task.set_resources(Resources(accelerators='tpu-v5e-32'))
        provisioner = failover.RetryingProvisioner(task, 'pod', 1)
        result = provisioner.provision_with_retries()
        # v5e-32 = 4 hosts of 8 chips.
        assert result.cluster_info.num_instances == 4
        head = result.cluster_info.get_head_instance()
        assert head is not None

    def test_multislice_hosts(self, fake_cluster_env):
        task = Task(run='train')
        task.set_resources(
            Resources(accelerators='tpu-v5e-32',
                      accelerator_args={'num_slices': 2}))
        provisioner = failover.RetryingProvisioner(task, 'ms', 1)
        result = provisioner.provision_with_retries()
        assert result.cluster_info.num_instances == 8
        slices = {i.slice_id
                  for i in result.cluster_info.instances.values()}
        assert len(slices) == 2
