"""Flight-recorder tests: ring bounds + exact seal math, the phase
brackets, black-box dump arms (exception/SIGTERM subprocess drill,
stall-verdict latch), the cross-rank gang waterfall join (straggler /
barrier-wait math, missing ranks, elastic renumbering), the bounded
train_anatomy table + pull dedup, the `xsky train trace` / `xsky top` /
`/metrics` surfaces, the data-starved detector + controller remediation
binding, the bench_flightrec overhead gate, bench.py's failure-JSON
black-box surfacing, and the tier-1 fake-cloud drill where chaos-
injected data stalls and stragglers resolve to the correct phase
attribution end-to-end."""
import json
import os
import signal
import subprocess
import sys
import time

import pytest

from skypilot_tpu.agent import flight_recorder
from skypilot_tpu.agent import telemetry
from skypilot_tpu.utils import chaos
from skypilot_tpu.utils import metrics as metrics_lib

REPO_ROOT = os.path.abspath(
    os.path.join(os.path.dirname(__file__), '..', '..'))


@pytest.fixture(autouse=True)
def _clean_flightrec(monkeypatch):
    for env in (flight_recorder.ENV_ENABLED,
                flight_recorder.ENV_RING_SIZE, flight_recorder.ENV_DIR,
                flight_recorder.ENV_TAIL,
                flight_recorder.ENV_PUSH_INTERVAL, telemetry.ENV_DIR,
                'XSKY_HOST_RANK'):
        monkeypatch.delenv(env, raising=False)
    flight_recorder.reset_for_test()
    telemetry.reset_for_test()
    metrics_lib.reset_for_test()
    chaos.clear()
    yield
    flight_recorder.reset_for_test()
    telemetry.reset_for_test()
    chaos.clear()


@pytest.fixture
def tmp_state(monkeypatch, tmp_path):
    from skypilot_tpu import state
    monkeypatch.setenv('XSKY_STATE_DB', str(tmp_path / 'state.db'))
    state.reset_for_test()
    yield state
    state.reset_for_test()


@pytest.fixture
def dumps_dir(monkeypatch, tmp_path):
    d = tmp_path / 'flightrec'
    monkeypatch.setenv(flight_recorder.ENV_DIR, str(d))
    return d


def _seal_steps(n, start=0):
    for i in range(start, start + n):
        flight_recorder.begin_step(i)
        flight_recorder.mark('data_wait', 0.001)
        flight_recorder.record_step()


# ---- ring + seal math -------------------------------------------------------


class TestRing:

    def test_ring_bounded_by_env(self, monkeypatch):
        monkeypatch.setenv(flight_recorder.ENV_RING_SIZE, '4')
        _seal_steps(10)
        rec = flight_recorder.get_recorder()
        rows = rec.records()
        assert len(rows) == 4
        # Newest-first read side; the oldest six fell off the ring.
        assert [r['step'] for r in rows] == [9, 8, 7, 6]
        assert rec._seq == 10

    def test_seal_phases_sum_exactly_to_wall(self):
        flight_recorder.begin_step(1)
        flight_recorder.mark('data_wait', 0.0103)
        flight_recorder.mark('h2d', 0.0007)
        flight_recorder.mark_compute(0.0011, 0.0502, synced=True)
        rec = flight_recorder.get_recorder()
        record = rec.seal(wall_s=0.1)
        # The acceptance contract: EXACT equality, not approx — the
        # stored wall is re-derived with the reader's accumulation
        # order so `sum(phases) == wall_s` at 0.0 error.
        assert sum(record['phases'].values()) == record['wall_s']
        assert record['phases']['other'] == pytest.approx(
            0.1 - 0.0103 - 0.0007 - 0.0011 - 0.0502)
        assert record['synced'] is True
        assert record['step'] == 1

    def test_seal_overattributed_wall_clamps_other_to_zero(self):
        flight_recorder.begin_step(2)
        flight_recorder.mark('data_wait', 0.2)
        rec = flight_recorder.get_recorder()
        record = rec.seal(wall_s=0.05)
        assert record['phases']['other'] == 0.0
        # Still exact: the wall becomes the attributed sum.
        assert sum(record['phases'].values()) == record['wall_s']

    def test_measured_wall_sums_exactly_too(self):
        flight_recorder.begin_step(3)
        with flight_recorder.phase('data_wait'):
            time.sleep(0.02)
        flight_recorder.mark_compute(0.001)
        flight_recorder.record_step()
        record = flight_recorder.get_recorder().records()[0]
        assert sum(record['phases'].values()) == record['wall_s']
        assert record['phases']['data_wait'] >= 0.02
        assert record['wall_s'] >= record['phases']['data_wait']

    def test_begin_step_drops_unsealed_predecessor(self):
        flight_recorder.begin_step(1)
        flight_recorder.mark('data_wait', 5.0)
        flight_recorder.begin_step(2)        # step 1 never sealed
        flight_recorder.record_step()
        rows = flight_recorder.get_recorder().records()
        assert [r['step'] for r in rows] == [2]
        assert rows[0]['phases']['data_wait'] == 0.0

    def test_tail_oldest_first_and_env_len(self, monkeypatch):
        monkeypatch.setenv(flight_recorder.ENV_TAIL, '3')
        _seal_steps(5)
        tail = flight_recorder.get_recorder().tail()
        assert [r['step'] for r in tail] == [2, 3, 4]

    def test_disabled_is_dict_lookup_noop(self, monkeypatch):
        monkeypatch.setenv(flight_recorder.ENV_ENABLED, '0')
        assert flight_recorder.get_recorder() is None
        # Every entry point is a no-op, never a raise.
        flight_recorder.begin_step(1)
        with flight_recorder.phase('data_wait'):
            pass
        flight_recorder.mark('h2d', 0.1)
        flight_recorder.mark_compute(0.1, 0.2)
        flight_recorder.record_step()
        assert flight_recorder.seal_dump('exception') is None

    def test_never_raises_on_garbage(self):
        # float('nan-ish') inputs must cost the record, not the step.
        flight_recorder.begin_step(1)
        flight_recorder.mark('data_wait', 'not-a-number')
        flight_recorder.record_step(phases={'h2d': 'also-bad'})
        flight_recorder.record_step(step='bogus')
        # The recorder survives and keeps sealing.
        _seal_steps(1, start=9)
        steps = [r['step']
                 for r in flight_recorder.get_recorder().records()]
        assert 9 in steps

    def test_rank_from_host_rank_env(self, monkeypatch):
        monkeypatch.setenv('XSKY_HOST_RANK', '3')
        assert flight_recorder.get_recorder().rank == 3

    def test_ride_along_lands_on_spool_sample(self, monkeypatch,
                                              tmp_path):
        monkeypatch.setenv(telemetry.ENV_DIR, str(tmp_path / 'spool'))
        monkeypatch.setenv(telemetry.ENV_RANK, '0')
        monkeypatch.setenv(telemetry.ENV_INTERVAL, '0')
        monkeypatch.setenv(flight_recorder.ENV_PUSH_INTERVAL, '0')
        _seal_steps(3)
        sample = telemetry.read_spool(str(tmp_path / 'spool'))[0]
        fr = sample['flightrec']
        assert fr['seq'] == 3
        assert [r['step'] for r in fr['tail']] == [0, 1, 2]


# ---- black-box dump arms ----------------------------------------------------


class TestBlackBoxDumps:

    def test_dump_writes_readable_blackbox(self, dumps_dir):
        _seal_steps(2)
        path = flight_recorder.seal_dump('exception',
                                         detail={'error': 'boom'})
        assert path and os.path.exists(path)
        blob = json.loads(open(path, encoding='utf-8').read())
        assert blob['reason'] == 'exception'
        assert blob['sealed'] is True
        assert blob['rank'] == 0
        assert blob['last_step'] == 1
        assert blob['detail'] == {'error': 'boom'}
        assert len(blob['records']) == 2
        for r in blob['records']:
            assert sum(r['phases'].values()) == r['wall_s']

    def test_dump_without_dir_returns_none(self):
        _seal_steps(1)
        assert flight_recorder.seal_dump('exception') is None

    def test_stall_verdict_latches_once_per_episode(self, dumps_dir):
        _seal_steps(2)
        flight_recorder.note_stall(5.0)
        flight_recorder.note_stall(6.0)     # latched: no second dump
        files = sorted(os.listdir(dumps_dir))
        assert len(files) == 1
        blob = json.loads(
            open(dumps_dir / files[0], encoding='utf-8').read())
        assert blob['reason'] == 'stall_verdict'
        assert blob['detail']['progress_age_s'] == 5.0
        # A sealed step re-arms the latch: next episode dumps again.
        _seal_steps(1, start=2)
        flight_recorder.note_stall(7.0)
        assert len(os.listdir(dumps_dir)) == 2

    @pytest.mark.parametrize('mode,reason', [
        ('exception', 'exception'), ('sigterm', 'sigterm')])
    def test_crash_arms_dump_in_subprocess(self, tmp_path, mode,
                                           reason):
        # install_crash_dumps rewires sys.excepthook and the SIGTERM
        # disposition process-wide, so both arms drill in a child: the
        # fatal path must leave a readable black box on its way down.
        d = tmp_path / 'bb'
        script = tmp_path / 'crash.py'
        script.write_text(f'''
import os, signal, sys, time
sys.path.insert(0, {json.dumps(REPO_ROOT)})
from skypilot_tpu.agent import flight_recorder
flight_recorder.install_crash_dumps()
flight_recorder.begin_step(7)
flight_recorder.mark('data_wait', 0.01)
flight_recorder.record_step()
if sys.argv[1] == 'exception':
    raise RuntimeError('boom')
os.kill(os.getpid(), signal.SIGTERM)
time.sleep(10)
''')
        env = dict(os.environ,
                   XSKY_FLIGHTREC_DIR=str(d),
                   XSKY_FLIGHTREC='1')
        proc = subprocess.run([sys.executable, str(script), mode],
                              env=env, capture_output=True, text=True,
                              timeout=120, check=False)
        assert proc.returncode != 0
        if mode == 'sigterm':
            assert proc.returncode == -signal.SIGTERM, proc.stderr
        files = [f for f in os.listdir(d) if f.endswith('.json')]
        assert len(files) == 1, (proc.stdout, proc.stderr)
        blob = json.loads(open(d / files[0], encoding='utf-8').read())
        assert blob['reason'] == reason
        assert blob['last_step'] == 7
        assert blob['records'][0]['phases']['data_wait'] >= 0.01
        if mode == 'exception':
            assert 'boom' in blob['detail']['error']


# ---- cross-rank join --------------------------------------------------------


def _row(rank, step, device=0.01, data=0.001, wall=None, started=100.0,
         dispatch=0.001):
    phases = {'data_wait': data, 'h2d': 0.001, 'dispatch': dispatch,
              'device_compute': device, 'ckpt_copy': 0.0, 'other': 0.0}
    return {'rank': rank, 'step': step, 'started_ts': started,
            'wall_s': wall if wall is not None
            else sum(phases.values()),
            'phases': phases}


class TestGangWaterfall:

    def test_straggler_and_barrier_wait_math(self):
        rows = [_row(0, 5, device=0.10), _row(1, 5, device=0.04)]
        (entry,) = flight_recorder.gang_waterfall(rows)
        assert entry['step'] == 5
        assert entry['straggler_rank'] == 0
        assert entry['skew_s'] == pytest.approx(0.06)
        # The straggler waits on nobody; the fast rank's implied
        # barrier wait is the straggler's compute minus its own.
        assert entry['barrier_wait_s'][0] == 0.0
        assert entry['barrier_wait_s'][1] == pytest.approx(0.06)
        assert entry['gang_wall_s'] == pytest.approx(
            max(r['wall_s'] for r in rows))

    def test_data_share_per_rank_and_max(self):
        rows = [_row(0, 1, data=0.08, device=0.01),
                _row(1, 1, data=0.002, device=0.01)]
        (entry,) = flight_recorder.gang_waterfall(rows)
        share0 = 0.08 / rows[0]['wall_s']
        assert entry['data_share_by_rank'][0] == pytest.approx(share0)
        assert entry['data_share'] == pytest.approx(share0)

    def test_missing_rank_tolerated(self):
        rows = [_row(0, 1), _row(1, 1), _row(0, 2)]
        steps = flight_recorder.gang_waterfall(rows)
        assert [w['step'] for w in steps] == [1, 2]
        assert set(steps[0]['ranks']) == {0, 1}
        assert set(steps[1]['ranks']) == {0}

    def test_elastic_renumbering_newest_incarnation_wins(self):
        rows = [_row(0, 1, started=100.0), _row(0, 2, started=100.0),
                _row(0, 3, started=200.0),   # relaunched rank 0
                _row(1, 3, started=100.0)]
        steps = flight_recorder.gang_waterfall(rows)
        # The prior life's steps 1/2 never join against the relaunch.
        assert [w['step'] for w in steps] == [3]
        assert set(steps[0]['ranks']) == {0, 1}

    def test_compute_falls_back_to_dispatch_when_unsynced(self):
        rows = [_row(0, 1, device=0.0, dispatch=0.09),
                _row(1, 1, device=0.0, dispatch=0.02)]
        (entry,) = flight_recorder.gang_waterfall(rows)
        assert entry['straggler_rank'] == 0
        assert entry['skew_s'] == pytest.approx(0.07)

    def test_digest_and_empty(self):
        assert flight_recorder.waterfall_digest([]) == {'steps': 0}
        rows = [_row(0, s, device=0.10) for s in (1, 2, 3)] + \
               [_row(1, s, device=0.04) for s in (1, 2, 3)]
        digest = flight_recorder.waterfall_digest(
            flight_recorder.gang_waterfall(rows))
        assert digest['steps'] == 3
        assert digest['top_straggler'] == 0
        assert digest['straggler_counts'] == {0: 3}
        assert digest['mean_skew_s'] == pytest.approx(0.06)
        assert digest['max_skew_s'] == pytest.approx(0.06)


# ---- bounded table + pull dedup ---------------------------------------------


def _pull_samples(now, steps, data=0.002, started=100.0, num_ranks=2):
    samples = {}
    for rank in range(num_ranks):
        tail = []
        for step in steps:
            phases = {'data_wait': data if rank == 0 else 0.002,
                      'h2d': 0.001, 'dispatch': 0.001,
                      'device_compute': 0.05 if rank == 1 else 0.01,
                      'ckpt_copy': 0.0, 'other': 0.0}
            tail.append({'step': step, 'ts': now,
                         'wall_s': sum(phases.values()),
                         'phases': phases, 'synced': True})
        samples[rank] = {'rank': rank, 'hb_ts': now,
                         'last_progress_ts': now, 'started_ts': started,
                         'phase': 'step', 'step': max(steps),
                         'step_time_ema_s': 0.1,
                         'tokens_per_sec': 10.0,
                         'flightrec': {'seq': len(tail), 'tail': tail}}
    return samples


class TestAnatomyTable:

    def test_roundtrip_and_filters(self, tmp_state):
        now = time.time()
        flight_recorder.record_train_anatomy(
            'c1', 1, _pull_samples(now, [1, 2]), now=now)
        rows = tmp_state.get_train_anatomy(cluster='c1')
        assert len(rows) == 4
        assert {r['rank'] for r in rows} == {0, 1}
        only = tmp_state.get_train_anatomy(cluster='c1', rank=1,
                                           step=2)
        assert len(only) == 1
        assert only[0]['phases']['device_compute'] == 0.05
        assert only[0]['detail']['synced'] is True
        assert tmp_state.get_train_anatomy(cluster='ghost') == []

    def test_retention_bound_first_batch(self, tmp_state, monkeypatch):
        monkeypatch.setattr(tmp_state, '_MAX_TRAIN_ANATOMY', 20)
        monkeypatch.setattr(tmp_state, '_train_anatomy_inserts', 0)
        rows = [dict(_row(0, s), ts=time.time()) for s in range(30)]
        tmp_state.record_train_anatomy('c1', 1, rows)
        kept = tmp_state.get_train_anatomy(cluster='c1', limit=500)
        assert len(kept) == 20
        # Newest rows survive the prune.
        assert kept[0]['step'] == 29

    def test_record_never_raises_on_db_failure(self, tmp_state,
                                               monkeypatch):
        def _boom():
            raise RuntimeError('db gone')
        monkeypatch.setattr(tmp_state, '_get_conn', _boom)
        tmp_state.record_train_anatomy('c1', 1, [_row(0, 1)])

    def test_pull_dedup_and_fresh_incarnation_cursor(self, tmp_state):
        now = time.time()
        samples = _pull_samples(now, [1, 2], num_ranks=1)
        flight_recorder.record_train_anatomy('c1', 1, samples, now=now)
        assert len(tmp_state.get_train_anatomy(cluster='c1')) == 2
        # The same spool tail re-ships on every pull: no re-inserts.
        flight_recorder.record_train_anatomy('c1', 1, samples, now=now)
        assert len(tmp_state.get_train_anatomy(cluster='c1')) == 2
        # Only the NEW step past the cursor lands.
        flight_recorder.record_train_anatomy(
            'c1', 1, _pull_samples(now, [1, 2, 3], num_ranks=1),
            now=now)
        assert len(tmp_state.get_train_anatomy(cluster='c1')) == 3
        # An elastic relaunch reusing rank 0 (new started_ts) starts a
        # fresh cursor: its step 1 is a different step 1.
        flight_recorder.record_train_anatomy(
            'c1', 1, _pull_samples(now, [1], started=200.0,
                                   num_ranks=1), now=now)
        assert len(tmp_state.get_train_anatomy(cluster='c1')) == 4

    def test_pull_feeds_phase_and_skew_histograms(self, tmp_state):
        now = time.time()
        flight_recorder.record_train_anatomy(
            'c1', 1, _pull_samples(now, [1, 2]), now=now)
        text = metrics_lib.render_registry()
        assert 'xsky_train_phase_seconds' in text
        assert 'phase="data_wait"' in text
        # Two ranks joined per step ⇒ the skew histogram observed.
        assert 'xsky_train_step_skew_seconds' in text

    def test_pull_never_raises_on_torn_samples(self, tmp_state):
        flight_recorder.record_train_anatomy('c1', 1, {
            0: 'not-a-dict',
            1: {'rank': 1, 'flightrec': 'torn'},
            2: {'rank': 2, 'flightrec': {'tail': [
                'torn', {'step': 'NaNish'}, {'step': 3}]}},
        })
        assert tmp_state.get_train_anatomy(cluster='c1') == []


# ---- surfaces: /metrics, xsky top, xsky train trace -------------------------


class TestMetricsSurface:

    def test_data_share_gauge_for_live_clusters(self, tmp_state):
        from skypilot_tpu.server import metrics as server_metrics
        tmp_state.add_or_update_cluster('live-c', None)
        now = time.time()
        telemetry.record_samples('live-c', 1,
                                 _pull_samples(now, [1, 2, 3],
                                               data=0.08), now=now)
        text = server_metrics.render()
        # rank 0: 0.08 data of 0.092 wall per step ⇒ 0.8696.
        assert ('xsky_train_data_share{cluster="live-c",job="1",'
                'rank="0"} 0.8696') in text
        assert ('xsky_train_data_share{cluster="live-c",job="1",'
                'rank="1"}') in text

    def test_gauge_skips_torn_down_clusters(self, tmp_state):
        from skypilot_tpu.server import metrics as server_metrics
        now = time.time()
        telemetry.record_samples('ghost-c', 1,
                                 _pull_samples(now, [1]), now=now)
        assert 'xsky_train_data_share{cluster="ghost-c"' \
            not in server_metrics.render()


class TestCliSurfaces:

    def _seed(self, cluster='anat-c'):
        now = time.time()
        telemetry.record_samples(
            cluster, 1, _pull_samples(now, [1, 2, 3], data=0.08),
            now=now)

    def test_train_trace_table(self, tmp_state):
        from click.testing import CliRunner

        from skypilot_tpu.client import cli as cli_mod
        self._seed()
        result = CliRunner().invoke(cli_mod.cli,
                                    ['train', 'trace', 'anat-c'])
        assert result.exit_code == 0, result.output
        assert 'TRAIN TRACE anat-c' in result.output
        assert '3 step(s)' in result.output
        # rank 1's 0.05 device vs rank 0's 0.01 ⇒ straggler rank 1,
        # and the fast rank carries the implied barrier wait.
        assert 'straggler rank 1' in result.output
        assert 'top straggler rank 1' in result.output
        assert '+wait 40.0ms' in result.output
        assert 'd=data_wait' in result.output

    def test_train_trace_json_and_step_filter(self, tmp_state):
        from click.testing import CliRunner

        from skypilot_tpu.client import cli as cli_mod
        self._seed()
        result = CliRunner().invoke(
            cli_mod.cli, ['train', 'trace', 'anat-c', '--json'])
        assert result.exit_code == 0, result.output
        lines = [json.loads(l) for l in result.output.splitlines()
                 if l.startswith('{')]
        entries = [l for l in lines if 'digest' not in l]
        digest = [l for l in lines if 'digest' in l][0]['digest']
        assert len(entries) == 3
        e = entries[0]
        # json round-trip stringifies the int rank keys.
        assert set(e['ranks']) == {'0', '1'}
        assert e['straggler_rank'] == 1
        assert e['barrier_wait_s']['0'] == pytest.approx(0.04)
        assert e['data_share'] == pytest.approx(0.08 / 0.092,
                                                abs=1e-3)
        assert digest['steps'] == 3
        assert digest['top_straggler'] == 1
        only = CliRunner().invoke(
            cli_mod.cli,
            ['train', 'trace', 'anat-c', '--step', '2', '--json'])
        steps = [json.loads(l)['step']
                 for l in only.output.splitlines()
                 if l.startswith('{') and 'digest' not in l]
        assert steps == [2]

    def test_train_trace_empty_cluster_message(self, tmp_state):
        from click.testing import CliRunner

        from skypilot_tpu.client import cli as cli_mod
        result = CliRunner().invoke(cli_mod.cli,
                                    ['train', 'trace', 'no-such'])
        assert result.exit_code == 0
        assert 'No step anatomy recorded' in result.output

    def test_top_gains_data_and_skew_columns(self, tmp_state):
        from click.testing import CliRunner

        from skypilot_tpu.client import cli as cli_mod
        self._seed()
        runner = CliRunner()
        table = runner.invoke(cli_mod.cli, ['top'])
        assert table.exit_code == 0, table.output
        assert 'DATA%' in table.output
        assert 'SKEW' in table.output
        assert '87%' in table.output          # rank 0's data share
        assert '40.0ms' in table.output       # gang mean compute skew
        as_json = runner.invoke(cli_mod.cli, ['top', '--json'])
        rows = [json.loads(l) for l in as_json.output.splitlines()
                if l.startswith('{')]
        by_rank = {r['rank']: r for r in rows}
        assert by_rank[0]['data_share'] == pytest.approx(0.08 / 0.092,
                                                         abs=1e-3)
        assert by_rank[1]['data_share'] == pytest.approx(
            0.002 / 0.054, abs=1e-3)
        assert by_rank[0]['anatomy_skew_s'] == pytest.approx(0.04)


# ---- data-starved detector + remediation binding ----------------------------


class TestDataStarvedDetector:

    def _points(self, state, values, t0, labels=None, dt=10.0):
        labels = labels or {'cluster': 'c', 'job': '1', 'rank': '0'}
        state.record_metric_points(
            [{'ts': t0 + i * dt, 'name': 'xsky_train_data_share',
              'labels': labels, 'kind': 'gauge', 'value': v}
             for i, v in enumerate(values)])

    def test_elevated_rising_share_fires_and_journals(self, tmp_state):
        from skypilot_tpu.utils import metrics_history
        metrics_history.reset_for_test()
        now = time.time()
        self._points(tmp_state,
                     [0.05, 0.06, 0.05, 0.05, 0.65, 0.7, 0.68, 0.72],
                     t0=now - 75)
        found = metrics_history.detect_anomalies(now=now)
        starved = [f for f in found if f['detector'] == 'data_starved']
        assert len(starved) == 1
        assert starved[0]['labels']['rank'] == '0'
        assert starved[0]['value'] > starved[0]['baseline']
        events = tmp_state.get_recovery_events(
            event_type='metrics.anomaly')
        assert any(e['cause'] == 'data_starved' and
                   e['scope'].startswith('metrics/data_starved/')
                   for e in events)

    def test_steady_low_share_stays_quiet(self, tmp_state):
        from skypilot_tpu.utils import metrics_history
        metrics_history.reset_for_test()
        now = time.time()
        # Rising but never elevated: a 0.2 share is a healthy input
        # pipeline, not starvation.
        self._points(tmp_state,
                     [0.05, 0.05, 0.05, 0.05, 0.2, 0.2, 0.2, 0.2],
                     t0=now - 75)
        found = metrics_history.detect_anomalies(now=now)
        assert not [f for f in found
                    if f['detector'] == 'data_starved']

    def test_controller_remediation_snapshots_digest(self, tmp_state):
        from skypilot_tpu.jobs import controller as controller_lib
        now = time.time()
        flight_recorder.record_train_anatomy(
            'xsky-jobs-7', 7, _pull_samples(now, [1, 2]), now=now)
        ctl = object.__new__(controller_lib.JobsController)
        ctl.cluster_name = 'xsky-jobs-7'
        anomaly = {'detector': 'data_starved',
                   'ident': 'cluster=xsky-jobs-7,job=7,rank=0',
                   'labels': {'cluster': 'xsky-jobs-7'}}
        out = ctl._remediate_data_starved(anomaly)
        assert out['cluster'] == 'xsky-jobs-7'
        assert out['anatomy']['steps'] == 2
        assert out['anatomy']['top_straggler'] == 1
        # Another controller's cluster: not ours, no action detail.
        other = dict(anomaly, ident='cluster=elsewhere,job=9,rank=0')
        assert ctl._remediate_data_starved(other) is None


# ---- bench gates ------------------------------------------------------------


class TestBenchFlightrecGate:
    """Tier-1 overhead gate: the recorder must cost <2% of a 4 ms
    step AND the sampled step's block_until_ready pair must be shared
    (exactly one device sync) between the profiler probe and the seal,
    proven by tools/bench_flightrec.py --smoke in a clean subprocess."""

    def test_bench_flightrec_smoke_gate(self):
        proc = subprocess.run(
            [sys.executable,
             os.path.join(REPO_ROOT, 'tools', 'bench_flightrec.py'),
             '--smoke'],
            capture_output=True, text=True, timeout=300, check=False)
        assert proc.returncode == 0, proc.stdout + proc.stderr
        result = json.loads(proc.stdout.strip().splitlines()[-1])
        assert result['pass'] is True
        assert result['overhead_pct'] < result['max_overhead_pct']
        # Satellite contract: ONE block_until_ready on a sampled step,
        # and the sealed record rode that same timestamp pair.
        assert result['single_sync']['device_syncs'] == 1
        assert result['single_sync']['sealed_synced'] is True
        assert result['single_sync']['ok'] is True


class TestBenchFailureJson:
    """bench.py's failure JSON gains the per-rank flight-recorder tail
    + any black-box dump reasons: a chaos-killed rank must leave a
    readable post-mortem in the supervisor's stall/failure output."""

    def _bench(self):
        if REPO_ROOT not in sys.path:
            sys.path.insert(0, REPO_ROOT)
        import bench
        return bench

    def test_stall_path_surfaces_tail_and_dumps(self, monkeypatch,
                                                tmp_path):
        bench = self._bench()
        spool = tmp_path / 'spool'
        spool.mkdir()
        dumps = tmp_path / 'spool' / 'flightrec'
        now = time.time()
        # The dump is written by the REAL dump arm, not hand-crafted.
        monkeypatch.setenv(flight_recorder.ENV_DIR, str(dumps))
        _seal_steps(6)
        flight_recorder.seal_dump('sigterm')
        # A spool sample whose flightrec key carries the ring tail.
        tail = flight_recorder.get_recorder().tail(5)
        (spool / 'rank-0.json').write_text(json.dumps({
            'rank': 0, 'hb_ts': now, 'last_progress_ts': now - 30,
            'started_ts': now - 60, 'phase': 'step', 'step': 5,
            'flightrec': {'seq': 6, 'tail': tail}}))
        env = {'XSKY_TELEMETRY_DIR': str(spool),
               'XSKY_FLIGHTREC_DIR': str(dumps)}
        ranks = bench._telemetry_tail(env)
        fr = ranks['0']['flightrec']
        assert fr['last_step'] == 5
        assert fr['seq'] == 6
        assert len(fr['tail']) == 4           # headline tail is capped
        assert all(sum(r['phases'].values()) == r['wall_s']
                   for r in fr['tail'])
        (dump,) = ranks['flightrec_dumps']
        assert dump['reason'] == 'sigterm'
        assert dump['rank'] == 0
        assert dump['last_step'] == 5
        assert dump['records'] == 6
        assert os.path.exists(dump['path'])

    def test_no_flightrec_keys_tolerated(self, tmp_path):
        bench = self._bench()
        spool = tmp_path / 'spool'
        spool.mkdir()
        (spool / 'rank-0.json').write_text(json.dumps(
            {'rank': 0, 'hb_ts': time.time(), 'phase': 'step'}))
        ranks = bench._telemetry_tail({
            'XSKY_TELEMETRY_DIR': str(spool)})
        assert ranks['0']['flightrec'] is None
        assert 'flightrec_dumps' not in ranks


# ---- tier-1 fake-cloud drill ------------------------------------------------


class TestFlightRecorderDrill:
    """Tier-1 acceptance: a fake-cloud 2-host gang where chaos injects
    a data stall on rank 0 (`train.data_stall` inside the data_wait
    bracket) and a straggler on rank 1 (`train.straggler_rank` inside
    mark_compute). Each injected cause must resolve to the CORRECT
    attribution end-to-end: rank 0's steps dominated by data_wait with
    the data-starved detector journalling off the scrape-time gauge,
    rank 1 flagged straggler with rank 0 carrying the implied barrier
    wait in `xsky train trace --json`."""

    def test_chaos_attribution_end_to_end(self, fake_cluster_env,
                                          monkeypatch, tmp_path):
        del fake_cluster_env
        from click.testing import CliRunner

        from skypilot_tpu import Resources, Task, core, execution
        from skypilot_tpu import state as state_lib
        from skypilot_tpu.client import cli as cli_mod
        from skypilot_tpu.server import metrics as server_metrics
        from skypilot_tpu.utils import metrics_history

        metrics_lib.reset_for_test()
        metrics_history.reset_for_test()
        monkeypatch.setenv(telemetry.ENV_INTERVAL, '0.1')
        monkeypatch.setenv(telemetry.ENV_PULL_INTERVAL, '0.3')
        monkeypatch.setenv(flight_recorder.ENV_PUSH_INTERVAL, '0')
        monkeypatch.setenv('XSKY_CHAOS_PLAN', json.dumps({'points': {
            'train.data_stall': {'match': {'rank': 0},
                                 'stall_s': 0.2},
            'train.straggler_rank': {'match': {'rank': 1},
                                     'extra_s': 0.15}}}))

        script = tmp_path / 'workload.py'
        script.write_text(f'''
import os, sys, time
sys.path.insert(0, {json.dumps(REPO_ROOT)})
from skypilot_tpu.agent import flight_recorder, telemetry
for i in range(10):
    flight_recorder.begin_step(i)
    with flight_recorder.phase('data_wait'):
        pass                      # chaos stalls rank 0 in here
    flight_recorder.mark_compute(0.001, 0.005, synced=True)
    flight_recorder.record_step()
    telemetry.emit(phase='step', step=i, step_time_s=0.05)
    time.sleep(0.05)
''')
        cluster = 'flightrec-drill'
        task = Task('flightrec-drill',
                    run=f'{sys.executable} {script}')
        # tpu-v5e-32 = 4 fake hosts (profile-smoke sizing): ranks 2/3
        # stay healthy so the straggler verdict has a real contrast.
        task.set_resources(Resources(accelerators='tpu-v5e-32'))
        job_id, handle = execution.launch(task, cluster_name=cluster)
        try:
            # Deterministic final pull (profile-smoke rationale): the
            # host spools hold the final truth and outlive the job.
            from skypilot_tpu.backends import tpu_gang_backend
            backend = tpu_gang_backend.TpuGangBackend()
            samples = backend.get_workload_telemetry(handle, job_id)
            assert set(samples) == {0, 1, 2, 3}, samples
            telemetry.record_samples(cluster, job_id, samples)

            # The joined waterfall attributes each injected cause.
            result = CliRunner().invoke(
                cli_mod.cli, ['train', 'trace', cluster, '--json'])
            assert result.exit_code == 0, result.output
            lines = [json.loads(l)
                     for l in result.output.splitlines()
                     if l.startswith('{')]
            digest = [l for l in lines if 'digest' in l][0]['digest']
            joined = [l for l in lines if 'digest' not in l
                      and {'0', '1'} <= set(l['ranks'])]
            assert joined, lines
            for entry in joined:
                # Rank 1's chaos sleep lands in device compute ⇒ it is
                # the straggler; rank 0 carries the implied wait.
                assert entry['straggler_rank'] == 1
                assert entry['skew_s'] > 0.05
                assert entry['barrier_wait_s']['0'] > 0.05
                assert entry['barrier_wait_s']['1'] == 0.0
                # Rank 0's chaos stall lands in data_wait ⇒ its share
                # of the step wall dominates.
                assert entry['data_share_by_rank']['0'] > 0.5
                assert entry['data_share_by_rank']['1'] < 0.3
                ranks = entry['ranks']
                assert ranks['0']['phases']['data_wait'] >= 0.2
                assert ranks['1']['phases']['device_compute'] >= 0.15
            assert digest['top_straggler'] == 1
            assert digest['data_share'] > 0.4

            # `xsky top` reads the same truth into DATA%/SKEW.
            as_json = CliRunner().invoke(cli_mod.cli,
                                         ['top', '--json'])
            rows = [json.loads(l)
                    for l in as_json.output.splitlines()
                    if l.startswith('{')]
            by_rank = {r['rank']: r for r in rows
                       if r['cluster'] == cluster}
            assert by_rank[0]['data_share'] > 0.5
            assert by_rank[0]['anatomy_skew_s'] > 0.05

            # /metrics while the cluster lives: the scrape-time gauge
            # + the registry histograms minted on pull.
            text = server_metrics.render()
            assert (f'xsky_train_data_share{{cluster="{cluster}"'
                    in text)
            assert 'xsky_train_phase_seconds' in text
            assert 'xsky_train_step_skew_seconds' in text

            # The data-starved detector journals off that gauge: a
            # low trail then the (real, scraped) starved window.
            now = time.time()
            state_lib.record_metric_points(
                [{'ts': now - 115 + i * 10,
                  'name': 'xsky_train_data_share',
                  'labels': {'cluster': cluster,
                             'job': str(job_id), 'rank': '0'},
                  'kind': 'gauge', 'value': 0.05} for i in range(4)])
            for offset in (45, 30, 15, 0):
                metrics_history.record_tick(now=now - offset)
            events = state_lib.get_recovery_events(
                event_type='metrics.anomaly')
            assert any(e['cause'] == 'data_starved' and
                       e['scope'].startswith('metrics/data_starved/')
                       for e in events), events

            # Workload-side chaos journalled cross-process.
            injected = {r['scope']
                        for r in state_lib.get_recovery_events(
                            event_type='chaos.injected')}
            assert 'chaos/train.data_stall' in injected
            assert 'chaos/train.straggler_rank' in injected
        finally:
            core.down(cluster)
        # Torn down ⇒ the scrape-time gauge disappears; the anatomy
        # rows remain for post-mortems.
        assert f'xsky_train_data_share{{cluster="{cluster}"' \
            not in server_metrics.render()
        assert state_lib.get_train_anatomy(cluster=cluster)
