"""TPU accelerator grammar and topology database.

This is the cornerstone of the TPU-first design: in the reference, a TPU is
"an accelerator count on a VM" (sky/resources.py:737 + per-cloud vCPU/mem
overrides at sky/clouds/gcp.py:688-739) and the multi-host asymmetry leaks
through `num_ips_per_node` (sky/backends/cloud_vm_ray_backend.py:2613).

Here `tpu-v5p-64` resolves *up front* to a :class:`SliceTopology`:
{generation, chip count, hosts, chips/host, ICI mesh shape, peak FLOPs, HBM},
so every layer (catalog pricing, optimizer feasibility/parallelism planning,
provisioner bring-up, gang launcher rank math, mesh construction in
``skypilot_tpu.parallel``) shares one consistent model of the hardware.

Naming conventions follow Cloud TPU:
  - v2/v3/v4/v5p names count **TensorCores** (v5p-128 == 64 chips).
  - v5e (aka v5litepod) and v6e names count **chips** directly.
Accepted spellings: ``tpu-v5e-8``, ``tpu-v5litepod-8``, ``tpu-v6e-16``,
``tpu-v4-32``, ``tpu-v5p-128``; with optional ``accelerator_args`` keys
``topology`` (e.g. ``4x4x8``) and ``num_slices`` (multislice over DCN).
"""
from __future__ import annotations

import dataclasses
import math
import re
from typing import Dict, List, Optional, Tuple

from skypilot_tpu import exceptions


@dataclasses.dataclass(frozen=True)
class TpuGeneration:
    """Static description of one TPU generation."""
    name: str                   # canonical short name, e.g. 'v5e'
    cores_per_chip: int         # cores counted by the product name
    max_chips_per_host: int     # chips on a fully-populated host VM
    hbm_gib_per_chip: float
    peak_bf16_tflops: float     # per chip
    # ICI dimensionality: v2/v3/v5e/v6e are 2-D tori; v4/v5p are 3-D tori.
    ici_dims: int
    # Per-link ICI bandwidth, GB/s each direction (approx, public figures).
    ici_gbps_per_link: float
    default_runtime_version: str
    aliases: Tuple[str, ...] = ()


GENERATIONS: Dict[str, TpuGeneration] = {
    'v2': TpuGeneration('v2', 2, 4, 8, 45, 2, 62.5, 'tpu-vm-base'),
    'v3': TpuGeneration('v3', 2, 4, 16, 123, 2, 81.25, 'tpu-vm-base'),
    'v4': TpuGeneration('v4', 2, 4, 32, 275, 3, 50, 'tpu-vm-v4-base'),
    'v5e': TpuGeneration('v5e', 1, 8, 16, 197, 2, 50, 'v2-alpha-tpuv5-lite',
                         aliases=('v5litepod',)),
    'v5p': TpuGeneration('v5p', 2, 4, 95, 459, 3, 100, 'v2-alpha-tpuv5'),
    'v6e': TpuGeneration('v6e', 1, 8, 32, 918, 2, 100, 'v2-alpha-tpuv6e'),
}

_ALIAS_TO_GEN = {alias: gen.name
                 for gen in GENERATIONS.values()
                 for alias in gen.aliases}

# Valid 2-D slice shapes for v5e/v6e (cols x rows), from the Cloud TPU docs.
# Keyed by chip count; value is the (x, y) accelerator topology.
_V5E_SHAPES: Dict[int, Tuple[int, int]] = {
    1: (1, 1), 4: (2, 2), 8: (2, 4), 16: (4, 4), 32: (4, 8), 64: (8, 8),
    128: (8, 16), 256: (16, 16),
}
_V6E_SHAPES = dict(_V5E_SHAPES)  # same ladder

_ACC_RE = re.compile(
    r'^(?:tpu-)?(?P<gen>v\d+(?:e|p|litepod)?)-(?P<count>\d+)$', re.IGNORECASE)


@dataclasses.dataclass(frozen=True)
class SliceTopology:
    """Fully-resolved description of one TPU slice request."""
    accelerator_name: str       # canonical, e.g. 'tpu-v5p-64'
    generation: TpuGeneration
    num_cores: int              # as counted by the product name
    num_chips: int
    topology: Tuple[int, ...]   # ICI mesh shape in chips, e.g. (4, 4, 4)
    num_hosts: int
    chips_per_host: int
    num_slices: int = 1         # >1 ⇒ multislice over DCN (megascale)

    @property
    def is_pod(self) -> bool:
        """Multi-host slice (one logical node = num_hosts VMs)."""
        return self.num_hosts > 1

    @property
    def is_multislice(self) -> bool:
        return self.num_slices > 1

    @property
    def total_chips(self) -> int:
        return self.num_chips * self.num_slices

    @property
    def total_hosts(self) -> int:
        return self.num_hosts * self.num_slices

    @property
    def peak_bf16_tflops(self) -> float:
        return self.generation.peak_bf16_tflops * self.total_chips

    @property
    def hbm_gib(self) -> float:
        return self.generation.hbm_gib_per_chip * self.total_chips

    @property
    def topology_str(self) -> str:
        return 'x'.join(str(d) for d in self.topology)

    def runtime_version(self, override: Optional[str] = None) -> str:
        return override or self.generation.default_runtime_version

    def gcp_accelerator_type(self) -> str:
        """The `acceleratorType` string for tpu.googleapis.com nodes.create.

        (Twin of the value the reference passes through config at
        sky/provision/gcp/instance_utils.py:1440.)
        """
        if self.generation.name == 'v5e':
            return f'v5litepod-{self.num_chips}'
        return f'{self.generation.name}-{self.num_cores}'


def is_tpu(accelerator_name: Optional[str]) -> bool:
    """Twin of sky/clouds/utils/gcp_utils.py:29 (is_tpu)."""
    if accelerator_name is None:
        return False
    return _ACC_RE.match(accelerator_name.strip()) is not None


def _squarest_3d(n: int) -> Tuple[int, int, int]:
    """Pick the most cube-like x<=y<=z factorization of n chips.

    Used for v4/v5p when the user gives no explicit topology. Real slices
    have doc-blessed shapes; the squarest factorization matches them for all
    standard sizes (e.g. 32→2x4x4, 64→4x4x4, 256→4x8x8).
    """
    best: Optional[Tuple[int, int, int]] = None
    for x in range(1, int(round(n ** (1 / 3))) + 1):
        if n % x:
            continue
        m = n // x
        for y in range(x, int(math.isqrt(m)) + 1):
            if m % y:
                continue
            z = m // y
            if z < y:
                continue
            cand = (x, y, z)
            if best is None or (cand[2] - cand[0]) < (best[2] - best[0]):
                best = cand
    assert best is not None, n
    return best


def parse(accelerator_name: str,
          accelerator_args: Optional[dict] = None) -> SliceTopology:
    """Parse ``tpu-v5p-64`` (+ optional args) into a SliceTopology.

    Raises InvalidRequestError for unknown generations, non-standard chip
    counts, or a user topology inconsistent with the chip count.
    """
    accelerator_args = accelerator_args or {}
    m = _ACC_RE.match(accelerator_name.strip())
    if m is None:
        raise exceptions.InvalidRequestError(
            f'Not a TPU accelerator name: {accelerator_name!r}. Expected '
            "e.g. 'tpu-v5e-8', 'tpu-v5p-64', 'tpu-v6e-16'.")
    gen_name = m.group('gen').lower()
    gen_name = _ALIAS_TO_GEN.get(gen_name, gen_name)
    if gen_name not in GENERATIONS:
        raise exceptions.InvalidRequestError(
            f'Unknown TPU generation {gen_name!r} in {accelerator_name!r}. '
            f'Known: {sorted(GENERATIONS)}.')
    gen = GENERATIONS[gen_name]
    count = int(m.group('count'))
    if count <= 0:
        raise exceptions.InvalidRequestError(
            f'Bad TPU size in {accelerator_name!r}')

    num_chips = count // gen.cores_per_chip if gen.cores_per_chip > 1 else count
    if gen.cores_per_chip > 1 and count % gen.cores_per_chip:
        raise exceptions.InvalidRequestError(
            f'{accelerator_name}: {gen_name} sizes count TensorCores and must '
            f'be a multiple of {gen.cores_per_chip}.')

    topo = _resolve_topology(gen, num_chips,
                             accelerator_args.get('topology'))
    num_hosts, chips_per_host = _host_layout(gen, num_chips)

    num_slices = int(accelerator_args.get('num_slices', 1))
    if num_slices < 1:
        raise exceptions.InvalidRequestError('num_slices must be >= 1')

    canonical = f'tpu-{gen_name}-{count}'
    return SliceTopology(accelerator_name=canonical,
                         generation=gen,
                         num_cores=count if gen.cores_per_chip > 1 else
                         count * gen.cores_per_chip,
                         num_chips=num_chips,
                         topology=topo,
                         num_hosts=num_hosts,
                         chips_per_host=chips_per_host,
                         num_slices=num_slices)


def _resolve_topology(gen: TpuGeneration, num_chips: int,
                      user_topology: Optional[str]) -> Tuple[int, ...]:
    if user_topology:
        dims = tuple(int(d) for d in str(user_topology).lower().split('x'))
        if math.prod(dims) != num_chips:
            raise exceptions.InvalidRequestError(
                f'topology {user_topology} has {math.prod(dims)} chips; '
                f'accelerator requests {num_chips}.')
        return dims
    if gen.ici_dims == 2:
        shapes = _V5E_SHAPES if gen.name == 'v5e' else (
            _V6E_SHAPES if gen.name == 'v6e' else None)
        if shapes is not None:
            if num_chips not in shapes:
                raise exceptions.InvalidRequestError(
                    f'tpu-{gen.name}-{num_chips}: valid sizes are '
                    f'{sorted(shapes)}.')
            return shapes[num_chips]
        # v2/v3: square-ish 2-D
        x = int(math.isqrt(num_chips))
        while num_chips % x:
            x -= 1
        return (x, num_chips // x)
    if num_chips not in list_standard_sizes(gen.name):
        raise exceptions.InvalidRequestError(
            f'tpu-{gen.name}: no standard {num_chips}-chip slice; valid '
            f'chip counts are {list_standard_sizes(gen.name)} (pass an '
            "explicit accelerator_args['topology'] for custom shapes).")
    return _squarest_3d(num_chips)


def _host_layout(gen: TpuGeneration, num_chips: int) -> Tuple[int, int]:
    """(num_hosts, chips_per_host) for a slice of num_chips."""
    if num_chips <= gen.max_chips_per_host:
        return 1, num_chips
    if gen.name in ('v6e',):
        # v6e multi-host slices use 4-chip hosts (v6e-16 == 4 hosts,
        # per the reference benchmark README examples/tpu/v6e/README.md:59).
        cph = 4
    else:
        cph = gen.max_chips_per_host
    if num_chips % cph:
        raise exceptions.InvalidRequestError(
            f'tpu-{gen.name}-{num_chips}: not divisible into {cph}-chip hosts')
    return num_chips // cph, cph


def list_standard_sizes(gen_name: str) -> List[int]:
    """Chip counts of catalog-listed slice sizes for a generation."""
    gen = GENERATIONS[gen_name]
    if gen.name in ('v5e', 'v6e'):
        return sorted(_V5E_SHAPES)
    if gen.ici_dims == 3:
        # 2x2x1(=4) isn't offered; ladder: 4 chips (v4-8/v5p-8) up by powers.
        return [4, 8, 16, 32, 64, 128, 256, 512, 1024]
    return [4, 8, 16, 32]
