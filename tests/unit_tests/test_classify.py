"""Sequence-classification fine-tune (BASELINE 'BERT-base GLUE
fine-tune' target; twin of examples/huggingface_glue_imdb_app.yaml).
End-to-end learnability on the synthetic set + the JSONL data path."""
import dataclasses
import json

import jax
import pytest

from skypilot_tpu.models import llama
from skypilot_tpu.train import classify

pytestmark = pytest.mark.slow  # jit compiles


def _config(**kw):
    model = dataclasses.replace(llama.LLAMA_TINY, max_seq_len=32)
    defaults = dict(model=model, num_classes=2, seq_len=32,
                    batch_size=8, learning_rate=1e-3)
    defaults.update(kw)
    return classify.ClassifyConfig(**defaults)


def test_learns_synthetic_sentiment():
    metrics = classify.train(_config(), steps=60, log_every=0)
    assert metrics['eval_accuracy'] >= 0.8, metrics


def test_head_only_freezes_trunk():
    """A truly frozen trunk: bit-identical after steps. Zeroed grads
    would NOT be enough — adamw weight decay shrinks every optimized
    param — so the optimizer must cover only the head subtree."""
    config = _config(head_only=True, weight_decay=0.1)
    params = classify.init(config, jax.random.PRNGKey(0))
    import optax
    tx = optax.adamw(1e-2, weight_decay=0.1)
    opt_state = classify.init_opt_state(config, tx, params)
    step = classify.make_train_step(config, tx)
    before = params['trunk']['lm_head']
    head_before = params['head']['w']
    batches = classify.synthetic_batches(config, jax.random.PRNGKey(1))
    for _ in range(3):
        params, opt_state, loss = step(params, opt_state,
                                       next(batches))
    assert (params['trunk']['lm_head'] == before).all()
    assert not (params['head']['w'] == head_before).all()
    assert float(loss) > 0


def test_synthetic_multiclass_labels_cover_all_classes():
    config = _config(num_classes=4, batch_size=64)
    batch = next(classify.synthetic_batches(config,
                                            jax.random.PRNGKey(0)))
    assert set(map(int, batch['label'])) == {0, 1, 2, 3}


def test_jsonl_data_path(tmp_path):
    config = _config(batch_size=4, seq_len=16)
    path = tmp_path / 'data.jsonl'
    rows = [{'tokens': [5, 6, 7][:i % 3 + 1], 'label': i % 2}
            for i in range(10)]
    path.write_text('\n'.join(json.dumps(r) for r in rows))
    batch = next(classify.jsonl_batches(config, str(path)))
    assert batch['tokens'].shape == (4, 16)
    assert batch['true_len'].min() >= 1
    assert set(map(int, batch['label'])) <= {0, 1}
    # train/eval splits hold out every 5th row and are disjoint.
    train_rows = classify.jsonl_batches(config, str(path),
                                        split='train')
    eval_rows = classify.jsonl_batches(config, str(path), split='eval')
    # Trains without shape errors on variable-length rows; eval uses
    # the held-out iterator.
    metrics = classify.train(config, steps=3, data=train_rows,
                             eval_data=eval_rows,
                             eval_batches=1, log_every=0)
    assert metrics['loss'] > 0


def test_example_yaml_is_valid():
    from skypilot_tpu import task as task_lib
    t = task_lib.Task.from_yaml(
        'examples/tpu/finetune_classifier.yaml')
    [r] = list(t.resources)
    assert r.accelerators == {'tpu-v5e-1': 1}
