"""Object-storage orchestration (twin of sky/data/storage.py, 5,111 LoC).

Redesign notes vs the reference:
  * Stores share one small ABC; bucket IO goes through each store's CLI
    (gcloud storage / aws s3) rather than SDKs, so no cloud SDK is a hard
    dependency (the reference mixes SDK + CLI).
  * A ``LocalStore`` ("file://" scheme, a plain directory) is first-class —
    it lets COPY/MOUNT be exercised end-to-end against the fake cloud with
    zero network, the harness the reference lacks (SURVEY §4.5).

Modes (reference: sky/data/storage.py:266):
  COPY          — bucket contents copied onto cluster disk at mount path.
  MOUNT         — FUSE mount; writes stream back to the bucket.
  MOUNT_CACHED  — rclone VFS cache; fast local writes, async upload.
"""
from __future__ import annotations

import enum
import os
import re
import shlex
import subprocess
from typing import Any, Dict, List, Optional, Tuple

from skypilot_tpu import exceptions
from skypilot_tpu import sky_logging
from skypilot_tpu import state
from skypilot_tpu.data import mounting_utils

logger = sky_logging.init_logger(__name__)


class StorageMode(enum.Enum):
    COPY = 'COPY'
    MOUNT = 'MOUNT'
    MOUNT_CACHED = 'MOUNT_CACHED'


class StoreType(enum.Enum):
    GCS = 'GCS'
    S3 = 'S3'
    R2 = 'R2'
    AZURE = 'AZURE'
    IBM = 'IBM'
    OCI = 'OCI'
    NEBIUS = 'NEBIUS'
    LOCAL = 'LOCAL'

    @classmethod
    def _scheme_map(cls):
        return (('gs://', cls.GCS), ('s3://', cls.S3), ('r2://', cls.R2),
                ('azure://', cls.AZURE), ('cos://', cls.IBM),
                ('oci://', cls.OCI), ('nebius://', cls.NEBIUS),
                ('file://', cls.LOCAL))

    @classmethod
    def from_url(cls, url: str) -> Tuple['StoreType', str]:
        """('gs://b/path') → (GCS, 'b/path')."""
        for scheme, st in cls._scheme_map():
            if url.startswith(scheme):
                return st, url[len(scheme):]
        schemes = ', '.join(s for s, _ in cls._scheme_map())
        raise exceptions.StorageSpecError(
            f'Unknown storage URL scheme: {url!r} (expected one of '
            f'{schemes}).')

    def url(self, bucket: str) -> str:
        scheme = {StoreType.GCS: 'gs', StoreType.S3: 's3',
                  StoreType.R2: 'r2', StoreType.AZURE: 'azure',
                  StoreType.IBM: 'cos', StoreType.OCI: 'oci',
                  StoreType.NEBIUS: 'nebius',
                  StoreType.LOCAL: 'file'}[self]
        return f'{scheme}://{bucket}'


_BUCKET_NAME_RE = re.compile(r'^[a-z0-9][a-z0-9._-]{1,253}[a-z0-9]$')


def _run(cmd: str) -> None:
    proc = subprocess.run(cmd, shell=True, capture_output=True, text=True)
    if proc.returncode != 0:
        raise exceptions.StorageUploadError(
            f'Command failed ({proc.returncode}): {cmd}\n{proc.stderr}')


class AbstractStore:
    """One bucket in one object store."""

    store_type: StoreType

    #: Injectable for tests; None = lazily constructed real client;
    #: False = construction already failed (no credentials) — cached so
    #: exists→create→upload doesn't re-pay a probe timeout per call.
    rest_client = None

    def __init__(self, name: str, source: Optional[str] = None,
                 region: Optional[str] = None) -> None:
        if self.store_type != StoreType.LOCAL and \
                not _BUCKET_NAME_RE.match(name.split('/')[0]):
            raise exceptions.StorageNameError(
                f'Invalid bucket name: {name!r}')
        self.name = name
        self.source = source
        self.region = region

    def _make_rest_client(self):
        """Build the zero-dep REST client, or raise when this store (or
        this environment) has none — the CLI then remains the transport."""
        raise exceptions.PermissionError_('no REST client for this store')

    def _rest(self):
        """Cached REST client or None (CLI fallback)."""
        if self.rest_client is not None:
            return self.rest_client or None
        if os.environ.get('XSKY_STORE_TRANSPORT') == 'cli':
            return None
        try:
            self.rest_client = self._make_rest_client()
        except Exception:  # pylint: disable=broad-except
            self.rest_client = False
        return self.rest_client or None

    # lifecycle
    def exists(self) -> bool:
        raise NotImplementedError

    def create(self) -> None:
        raise NotImplementedError

    def upload(self) -> None:
        """Sync self.source (a local dir/file) into the bucket."""
        raise NotImplementedError

    def delete(self) -> None:
        raise NotImplementedError

    def list_objects(self, prefix: str = '',
                     limit: int = 100) -> List[str]:
        """First `limit` object keys under `prefix` (dashboard /
        `storage ls NAME` drill-down). REST-transport only: stores
        without a usable zero-dep client raise StorageError rather
        than shelling out on the API-server hot path."""
        raise exceptions.StorageError(
            f'{self.store_type.value}: object listing not supported')

    @staticmethod
    def _strip_sub(keys: List[str], sub: str) -> List[str]:
        """Return keys relative to the store's sub-path so prefix-in
        and keys-out share one namespace (LocalStore is root-relative
        already)."""
        if not sub:
            return keys
        cut = sub.rstrip('/') + '/'
        return [k[len(cut):] if k.startswith(cut) else k for k in keys]

    def _rest_or_error(self):
        client = self._rest()
        if client is None:
            raise exceptions.StorageError(
                f'{self.store_type.value}: no credentials for object '
                'listing')
        return client

    # cluster-side commands
    def mount_command(self, mount_path: str) -> str:
        raise NotImplementedError

    def copy_download_command(self, dest_path: str) -> str:
        """Shell command run ON THE CLUSTER to copy bucket → dest."""
        raise NotImplementedError

    def url(self) -> str:
        return self.store_type.url(self.name)


class GcsStore(AbstractStore):
    """GCS via the in-tree JSON-API client (zero-dep), falling back to
    the `gcloud storage` CLI; mounts via gcsfuse.

    Control-plane ops prefer data/object_rest.GcsObjectClient (OAuth
    bearer from the provisioner's token chain) so no SDK/CLI is a hard
    dependency — the CLI path remains for developer machines where only
    `gcloud auth login` state exists. Cluster-side commands stay CLI:
    they run on nodes whose setup installs it.
    """
    store_type = StoreType.GCS

    def _make_rest_client(self):
        from skypilot_tpu.data import object_rest
        client = object_rest.GcsObjectClient()
        client._tokens.token()   # probe the credential chain now
        return client

    def list_objects(self, prefix: str = '',
                     limit: int = 100) -> List[str]:
        bucket, _, sub = self.name.partition('/')
        full = f'{sub}/{prefix}'.lstrip('/') if sub else prefix
        return self._strip_sub(
            self._rest_or_error().list_objects(
                bucket, prefix=full, max_results=limit), sub)

    def exists(self) -> bool:
        client = self._rest()
        if client is not None:
            try:
                return client.bucket_exists(self.name.partition('/')[0])
            except exceptions.StorageError as e:
                if not getattr(e, 'is_transient', True):
                    raise    # hard API error: don't mask as "missing"
        return subprocess.run(
            f'gcloud storage buckets describe gs://{self.name}',
            shell=True, capture_output=True).returncode == 0

    def create(self) -> None:
        client = self._rest()
        if client is not None:
            try:
                client.create_bucket(self.name.partition('/')[0],
                                     location=self.region)
                return
            except exceptions.StorageSpecError:
                # No resolvable project id: gcloud may still have a
                # configured default project — fall through to the CLI.
                pass
        loc = f' --location={self.region}' if self.region else ''
        _run(f'gcloud storage buckets create gs://{self.name}{loc}')

    def upload(self) -> None:
        client = self._rest()
        src = os.path.expanduser(self.source or '.')
        if client is not None:
            bucket, _, sub = self.name.partition('/')
            client.upload_dir(bucket, src,
                              prefix=f'{sub}/' if sub else '')
            return
        _run(f'gcloud storage rsync -r {shlex.quote(src)} '
             f'gs://{self.name}')

    def delete(self) -> None:
        client = self._rest()
        if client is not None:
            bucket, _, sub = self.name.partition('/')
            if sub:
                # Prefix-scoped store: delete only our objects — never
                # the shared bucket other prefixes live in.
                for key in client.list_objects(
                        bucket, prefix=sub.rstrip('/') + '/'):
                    client.delete_object(bucket, key)
            else:
                client.delete_bucket(bucket)
            return
        _run(f'gcloud storage rm -r gs://{self.name}')

    def mount_command(self, mount_path: str) -> str:
        bucket, _, sub = self.name.partition('/')
        return mounting_utils.gcs_mount_command(bucket, mount_path, sub)

    def copy_download_command(self, dest_path: str) -> str:
        q = shlex.quote(dest_path)
        return (f'mkdir -p {q} && gcloud storage rsync -r '
                f'gs://{self.name} {q}')


class S3Store(AbstractStore):
    """S3 via the in-tree SigV4 client (zero-dep), falling back to the
    aws CLI; mounts via goofys. Base class for every S3-API store
    (R2 / IBM COS / OCI / Nebius override the endpoint)."""
    store_type = StoreType.S3
    endpoint_url = ''

    def _ep(self) -> str:
        return (f' --endpoint-url {self.endpoint_url}'
                if self.endpoint_url else '')

    def _make_rest_client(self):
        # No static creds raises → CLI may still work (SSO, profile).
        from skypilot_tpu.data import object_rest
        return object_rest.S3ObjectClient(
            region=self.region or 'us-east-1',
            endpoint=self.endpoint_url)

    def list_objects(self, prefix: str = '',
                     limit: int = 100) -> List[str]:
        bucket, _, sub = self.name.partition('/')
        full = f'{sub}/{prefix}'.lstrip('/') if sub else prefix
        return self._strip_sub(
            self._rest_or_error().list_objects(
                bucket, prefix=full, max_keys=limit), sub)

    def exists(self) -> bool:
        client = self._rest()
        if client is not None:
            try:
                return client.bucket_exists(self.name.partition('/')[0])
            except exceptions.StorageError as e:
                if not getattr(e, 'is_transient', True):
                    raise    # hard API error: don't mask as "missing"
        return subprocess.run(
            f'aws s3api head-bucket --bucket {self.name}{self._ep()}',
            shell=True, capture_output=True).returncode == 0

    def create(self) -> None:
        client = self._rest()
        if client is not None:
            client.create_bucket(self.name.partition('/')[0])
            return
        region = f' --region {self.region}' if self.region else ''
        _run(f'aws s3 mb s3://{self.name}{region}{self._ep()}')

    def upload(self) -> None:
        src = os.path.expanduser(self.source or '.')
        client = self._rest()
        if client is not None:
            from skypilot_tpu.data import object_rest
            if object_rest.has_oversized_file(src):
                # Single-PUT cap: multipart is the CLI's job.
                logger.info(f'{self.name}: file exceeds the single-PUT '
                            'limit; using the cloud CLI multipart path')
                client = None
        if client is not None:
            bucket, _, sub = self.name.partition('/')
            client.upload_dir(bucket, src,
                              prefix=f'{sub}/' if sub else '')
            return
        _run(f'aws s3 sync {shlex.quote(src)} '
             f's3://{self.name}{self._ep()}')

    def delete(self) -> None:
        client = self._rest()
        if client is not None:
            bucket, _, sub = self.name.partition('/')
            if sub:
                # Prefix-scoped store: delete only our objects — never
                # the shared bucket other prefixes live in.
                for key in client.list_objects(
                        bucket, prefix=sub.rstrip('/') + '/'):
                    client.delete_object(bucket, key)
            else:
                client.delete_bucket(bucket)
            return
        _run(f'aws s3 rb s3://{self.name} --force{self._ep()}')

    def mount_command(self, mount_path: str) -> str:
        return mounting_utils.s3_mount_command(self.name, mount_path,
                                               self.endpoint_url)

    def copy_download_command(self, dest_path: str) -> str:
        q = shlex.quote(dest_path)
        return f'mkdir -p {q} && aws s3 sync s3://{self.name} {q}{self._ep()}'


class R2Store(S3Store):
    """Cloudflare R2: S3 API against the R2 endpoint."""
    store_type = StoreType.R2

    def __init__(self, name: str, source: Optional[str] = None,
                 region: Optional[str] = None) -> None:
        super().__init__(name, source, region)
        account = os.environ.get('R2_ACCOUNT_ID', '')
        self.endpoint_url = (
            f'https://{account}.r2.cloudflarestorage.com' if account else '')


class LocalStore(AbstractStore):
    """A directory standing in for a bucket (file:// scheme).

    Backs fake-cloud end-to-end tests of COPY/MOUNT and doubles as a
    shared-filesystem store for BYO clusters.
    """
    store_type = StoreType.LOCAL

    def _root(self) -> str:
        base = os.path.expanduser(
            os.environ.get('XSKY_LOCAL_STORE_DIR', '~/.xsky/local_store'))
        return os.path.join(base, self.name)

    def exists(self) -> bool:
        return os.path.isdir(self._root())

    def create(self) -> None:
        os.makedirs(self._root(), exist_ok=True)

    def upload(self) -> None:
        self.create()
        src = os.path.expanduser(self.source or '.')
        if os.path.isdir(src):
            src = os.path.join(src, '.')
        _run(f'cp -a {shlex.quote(src)} {shlex.quote(self._root())}/')

    def delete(self) -> None:
        _run(f'rm -rf {shlex.quote(self._root())}')

    def list_objects(self, prefix: str = '',
                     limit: int = 100) -> List[str]:
        root = self._root()
        out: List[str] = []
        # Topdown walk with in-place dirname sort: deterministic order
        # WITHOUT materializing the whole tree (sorted(os.walk(...))
        # would exhaust the generator before the limit could stop it).
        for dirpath, dirs, files in os.walk(root):
            dirs.sort()
            for f in sorted(files):
                rel = os.path.relpath(os.path.join(dirpath, f), root)
                if rel.startswith(prefix):
                    out.append(rel)
                    if len(out) >= limit:
                        return out
        return out

    def mount_command(self, mount_path: str) -> str:
        return mounting_utils.local_mount_command(self._root(), mount_path)

    def copy_download_command(self, dest_path: str) -> str:
        q = shlex.quote(dest_path)
        return (f'mkdir -p {q} && cp -a '
                f'{shlex.quote(self._root())}/. {q}/')


class AzureBlobStore(AbstractStore):
    """Azure Blob Storage via `az storage` CLI; mounts via blobfuse2.

    Twin of sky/data/storage.py:2414 (AzureBlobStore). The storage
    account comes from $AZURE_STORAGE_ACCOUNT (set by `az login` flows);
    bucket name = container name.
    """
    store_type = StoreType.AZURE

    @property
    def account(self) -> str:
        return os.environ.get('AZURE_STORAGE_ACCOUNT', '')

    @property
    def container(self) -> str:
        """Container name (self.name may carry a /sub-path suffix)."""
        return self.name.partition('/')[0]

    @property
    def sub_path(self) -> str:
        return self.name.partition('/')[2]

    def _acct(self) -> str:
        return (f' --account-name {shlex.quote(self.account)}'
                if self.account else '')

    def _make_rest_client(self):
        # No account key raises → `az` CLI login state may still work.
        from skypilot_tpu.data import object_rest
        return object_rest.AzureBlobClient()

    def list_objects(self, prefix: str = '',
                     limit: int = 100) -> List[str]:
        full = (f'{self.sub_path}/{prefix}'.lstrip('/')
                if self.sub_path else prefix)
        return self._strip_sub(
            self._rest_or_error().list_blobs(
                self.container, prefix=full, max_results=limit),
            self.sub_path)

    def exists(self) -> bool:
        client = self._rest()
        if client is not None:
            try:
                return client.container_exists(self.container)
            except exceptions.StorageError as e:
                if not getattr(e, 'is_transient', True):
                    raise    # hard API error: don't mask as "missing"
        return subprocess.run(
            f'az storage container exists --name {shlex.quote(self.container)}'
            f'{self._acct()} --query exists -o tsv | grep -q true',
            shell=True, capture_output=True).returncode == 0

    def create(self) -> None:
        client = self._rest()
        if client is not None:
            client.create_container(self.container)
            return
        _run(f'az storage container create '
             f'--name {shlex.quote(self.container)}'
             f'{self._acct()}')

    def upload(self) -> None:
        src = os.path.expanduser(self.source or '.')
        client = self._rest()
        if client is not None:
            from skypilot_tpu.data import object_rest
            if object_rest.has_oversized_file(src):
                logger.info(f'{self.name}: file exceeds the single-PUT '
                            'limit; using the az CLI block upload path')
                client = None
        if client is not None:
            prefix = f'{self.sub_path}/' if self.sub_path else ''
            client.upload_dir(self.container, src, prefix=prefix)
            return
        dest = (f' --destination-path {shlex.quote(self.sub_path)}'
                if self.sub_path else '')
        _run(f'az storage blob upload-batch '
             f'-d {shlex.quote(self.container)} -s {shlex.quote(src)}'
             f'{dest}{self._acct()}')

    def delete(self) -> None:
        client = self._rest()
        if client is not None:
            if self.sub_path:
                # Prefix-scoped store: delete only our blobs — never
                # the shared container other prefixes live in.
                prefix = self.sub_path.rstrip('/') + '/'
                for name in client.list_blobs(self.container,
                                              prefix=prefix):
                    client.delete_blob(self.container, name)
            else:
                for name in client.list_blobs(self.container):
                    client.delete_blob(self.container, name)
                client.delete_container(self.container)
            return
        _run(f'az storage container delete '
             f'--name {shlex.quote(self.container)}'
             f'{self._acct()}')

    def mount_command(self, mount_path: str) -> str:
        return mounting_utils.azure_mount_command(self.container,
                                                  self.account, mount_path)

    def copy_download_command(self, dest_path: str) -> str:
        q = shlex.quote(dest_path)
        pattern = (f' --pattern {shlex.quote(self.sub_path + "/*")}'
                   if self.sub_path else '')
        return (f'mkdir -p {q} && az storage blob download-batch '
                f'-s {shlex.quote(self.container)} -d {q}'
                f'{pattern}{self._acct()}')


class _S3CompatibleStore(S3Store):
    """Shared base for S3-API object stores behind custom endpoints
    (IBM COS, OCI, Nebius — reference classes at sky/data/storage.py:
    3763, 4227, 4689). Mounts via rclone (no native FUSE adapter)."""

    _ENDPOINT_ENV = ''       # env var holding the endpoint URL
    _RCLONE_REMOTE = ''
    #: Provider-specific HMAC key env prefix (e.g. 'IBM_COS' →
    #: $IBM_COS_ACCESS_KEY_ID / $IBM_COS_SECRET_ACCESS_KEY); falls back
    #: to the shared AWS pair when unset.
    _CRED_ENV_PREFIX = ''

    def __init__(self, name: str, source: Optional[str] = None,
                 region: Optional[str] = None) -> None:
        super().__init__(name, source, region)
        self.endpoint_url = os.environ.get(self._ENDPOINT_ENV, '')

    def _make_rest_client(self):
        access = os.environ.get(f'{self._CRED_ENV_PREFIX}_ACCESS_KEY_ID')
        secret = os.environ.get(
            f'{self._CRED_ENV_PREFIX}_SECRET_ACCESS_KEY')
        from skypilot_tpu.data import object_rest
        return object_rest.S3ObjectClient(
            region=self.region or 'us-east-1',
            endpoint=self.endpoint_url,
            creds=(access, secret, None) if access and secret else None)

    def mount_command(self, mount_path: str) -> str:
        return mounting_utils.rclone_mount_command(
            self._RCLONE_REMOTE, self.name, mount_path, self.endpoint_url)


class IBMCosStore(_S3CompatibleStore):
    """IBM Cloud Object Storage ($IBM_COS_ENDPOINT)."""
    store_type = StoreType.IBM
    _ENDPOINT_ENV = 'IBM_COS_ENDPOINT'
    _RCLONE_REMOTE = 'xsky-ibm'
    _CRED_ENV_PREFIX = 'IBM_COS'


class OciStore(_S3CompatibleStore):
    """OCI Object Storage, S3-compat API ($OCI_S3_ENDPOINT)."""
    store_type = StoreType.OCI
    _ENDPOINT_ENV = 'OCI_S3_ENDPOINT'
    _RCLONE_REMOTE = 'xsky-oci'
    _CRED_ENV_PREFIX = 'OCI_S3'


class NebiusStore(_S3CompatibleStore):
    """Nebius Object Storage ($NEBIUS_S3_ENDPOINT, default public EP)."""
    store_type = StoreType.NEBIUS
    _ENDPOINT_ENV = 'NEBIUS_S3_ENDPOINT'
    _RCLONE_REMOTE = 'xsky-nebius'
    _CRED_ENV_PREFIX = 'NEBIUS'

    def __init__(self, name: str, source: Optional[str] = None,
                 region: Optional[str] = None) -> None:
        super().__init__(name, source, region)
        if not self.endpoint_url:
            self.endpoint_url = 'https://storage.eu-north1.nebius.cloud'


_STORE_CLASSES = {
    StoreType.GCS: GcsStore,
    StoreType.S3: S3Store,
    StoreType.R2: R2Store,
    StoreType.AZURE: AzureBlobStore,
    StoreType.IBM: IBMCosStore,
    StoreType.OCI: OciStore,
    StoreType.NEBIUS: NebiusStore,
    StoreType.LOCAL: LocalStore,
}


class Storage:
    """User-facing storage object: a named dataset in ≥1 stores.

    YAML form (twin of reference file_mounts storage entries,
    sky/data/storage.py:520):

        file_mounts:
          /data:
            name: my-dataset
            source: ~/datasets/imagenet     # local path or gs://bucket
            store: gcs                      # optional; inferred from source
            mode: MOUNT                     # COPY | MOUNT | MOUNT_CACHED
            persistent: true
    """

    def __init__(self, name: Optional[str] = None,
                 source: Optional[str] = None,
                 mode: StorageMode = StorageMode.MOUNT,
                 persistent: bool = True) -> None:
        if not name and not source:
            raise exceptions.StorageSpecError(
                'Storage needs a name or a source.')
        self.source = source
        self.mode = mode
        self.persistent = persistent
        self.stores: Dict[StoreType, AbstractStore] = {}
        # Buckets this Storage actually created (vs pre-existing/external
        # buckets, which delete() must never destroy — reference
        # distinguishes sky-managed from external stores the same way).
        self.created_buckets: set = set()

        self._source_is_bucket = False
        if source and '://' in source:
            st, bucket = StoreType.from_url(source)
            self._source_is_bucket = True
            self.name = name or bucket.split('/')[0]
            self.add_store(st, bucket_name=bucket)
        else:
            if source is not None:
                expanded = os.path.expanduser(source)
                if not os.path.isabs(expanded) and not \
                        os.path.exists(expanded):
                    raise exceptions.StorageSpecError(
                        f'Storage source {source!r} not found locally and '
                        'not a bucket URL.')
            self.name = name or (os.path.basename(
                os.path.abspath(os.path.expanduser(source))).lower()
                if source else None)

    # ---- stores ----

    def add_store(self, store_type: StoreType,
                  bucket_name: Optional[str] = None,
                  region: Optional[str] = None) -> AbstractStore:
        if isinstance(store_type, str):
            store_type = StoreType[store_type.upper()]
        if store_type in self.stores:
            return self.stores[store_type]
        cls = _STORE_CLASSES[store_type]
        store = cls(bucket_name or self.name,
                    source=None if self._source_is_bucket else self.source,
                    region=region)
        self.stores[store_type] = store
        return store

    def sync_all_stores(self) -> None:
        """Create buckets and upload the local source (if any)."""
        if not self.stores and self.source is not None:
            self.add_store(_default_store_type())
        for store in self.stores.values():
            if not store.exists():
                store.create()
                self.created_buckets.add(store.store_type.value)
            if store.source and not self._source_is_bucket:
                logger.info(f'Uploading {store.source} → {store.url()}')
                store.upload()
        state.add_or_update_storage(self.name, self.handle(),
                                    state.StorageStatus.READY)

    def delete(self) -> None:
        """Delete managed buckets; leave external (pre-existing) ones.

        A bucket is deleted only if this Storage created it; buckets the
        user pointed at (gs:// source, or pre-existing names) are only
        deregistered.
        """
        for store in self.stores.values():
            if store.store_type.value in self.created_buckets:
                store.delete()
            else:
                logger.info(
                    f'Skipping deletion of external bucket {store.url()} '
                    '(not created by this tool); deregistering only.')
        state.remove_storage(self.name)

    # ---- cluster-side ----

    def list_objects(self, prefix: str = '',
                     limit: int = 100) -> List[str]:
        return self.primary_store().list_objects(prefix=prefix,
                                                 limit=limit)

    def primary_store(self) -> AbstractStore:
        if not self.stores:
            raise exceptions.StorageSpecError(
                f'Storage {self.name} has no stores; call add_store().')
        return next(iter(self.stores.values()))

    def cluster_command(self, mount_path: str) -> str:
        """The command each host runs to realize this mount."""
        store = self.primary_store()
        if self.mode == StorageMode.COPY:
            return store.copy_download_command(mount_path)
        if self.mode == StorageMode.MOUNT_CACHED:
            if store.store_type in (StoreType.LOCAL, StoreType.AZURE):
                # Azure: blobfuse2's own file cache plays this role.
                return store.mount_command(mount_path)
            # Stores declare their rclone remote name; GCS/S3/R2 use the
            # scheme-derived default.
            remote = getattr(
                store, '_RCLONE_REMOTE',
                f'xsky-{store.store_type.value.lower()}')
            endpoint = getattr(store, 'endpoint_url', '')
            return mounting_utils.rclone_mount_cached_command(
                remote, store.name, mount_path, endpoint)
        return store.mount_command(mount_path)

    # ---- (de)serialization ----

    def handle(self) -> Dict[str, Any]:
        return {
            'name': self.name,
            'source': self.source,
            'mode': self.mode.value,
            'persistent': self.persistent,
            'stores': {st.value: s.name for st, s in self.stores.items()},
            'created_buckets': sorted(self.created_buckets),
        }

    @classmethod
    def from_handle(cls, handle: Dict[str, Any]) -> 'Storage':
        storage = cls(name=handle['name'], source=handle.get('source'),
                      mode=StorageMode(handle.get('mode', 'MOUNT')),
                      persistent=handle.get('persistent', True))
        for st_name, bucket in handle.get('stores', {}).items():
            storage.add_store(StoreType[st_name], bucket_name=bucket)
        storage.created_buckets = set(handle.get('created_buckets', []))
        return storage

    @classmethod
    def from_yaml_config(cls, config: Dict[str, Any]) -> 'Storage':
        config = dict(config)
        mode_str = str(config.pop('mode', 'MOUNT')).upper()
        try:
            mode = StorageMode[mode_str]
        except KeyError:
            raise exceptions.StorageModeError(
                f'Invalid storage mode {mode_str!r}; expected one of '
                f'{[m.name for m in StorageMode]}.') from None
        storage = cls(name=config.pop('name', None),
                      source=config.pop('source', None),
                      mode=mode,
                      persistent=config.pop('persistent', True))
        store = config.pop('store', None)
        if store is not None:
            storage.add_store(StoreType[str(store).upper()])
        elif not storage.stores and storage.source is not None:
            storage.add_store(_default_store_type())
        if config:
            raise exceptions.StorageSpecError(
                f'Unknown storage fields: {list(config)}')
        return storage

    def to_yaml_config(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {'name': self.name}
        if self.source:
            out['source'] = self.source
        out['mode'] = self.mode.value
        if self.stores:
            out['store'] = self.primary_store().store_type.value.lower()
        return out


def _default_store_type() -> StoreType:
    if os.environ.get('XSKY_ENABLE_FAKE_CLOUD'):
        return StoreType.LOCAL
    return StoreType.GCS


def storage_mounts_from_file_mounts(
        file_mounts: Optional[Dict[str, Any]]
) -> Tuple[Dict[str, str], Dict[str, Storage]]:
    """Split task file_mounts into plain (str→str) and storage entries.

    Reference behavior: Task.set_file_mounts accepts str targets only;
    dict-valued entries become Storage mounts
    (sky/task.py:994,1200).
    """
    plain: Dict[str, str] = {}
    storages: Dict[str, Storage] = {}
    for target, value in (file_mounts or {}).items():
        if isinstance(value, str) and '://' in value:
            storages[target] = Storage(source=value, mode=StorageMode.COPY)
        elif isinstance(value, str):
            plain[target] = value
        elif isinstance(value, dict):
            storages[target] = Storage.from_yaml_config(value)
        else:
            raise exceptions.StorageSpecError(
                f'file_mounts[{target!r}] must be a path, URL, or '
                f'storage spec dict; got {type(value).__name__}')
    return plain, storages
