"""HTTP transport for the SDK (twin of the reference's requests-to-server
path, sky/client/sdk.py + sky/server/common.py).

Implemented against the API server in ``skypilot_tpu.server``; every verb
posts a request, receives a request id, and polls ``/api/get`` until the
request completes (the reference's async request-id model).
"""
from __future__ import annotations

import time
from typing import Any, Dict, List, Optional

from skypilot_tpu import exceptions


class RemoteClient:

    def __init__(self, endpoint: str, poll_interval_s: float = 0.2,
                 timeout_s: float = 3600.0,
                 token: Optional[str] = None) -> None:
        self.endpoint = endpoint.rstrip('/')
        self.poll_interval_s = poll_interval_s
        self.timeout_s = timeout_s
        if token is None:
            import os
            from skypilot_tpu import config as config_lib
            token = os.environ.get('XSKY_API_TOKEN') or \
                config_lib.get_nested(('api_server', 'token'))
        headers = {'Authorization': f'Bearer {token}'} if token else {}
        try:
            import httpx
            self._client = httpx.Client(base_url=self.endpoint,
                                        timeout=30, headers=headers)
        except ImportError as e:
            raise exceptions.ApiServerConnectionError(endpoint) from e

    # ---- request plumbing ----

    def _request(self, method: str, url: str, **kwargs):
        """One HTTP call, with a single OAuth refresh retry on 401:
        access tokens are short-lived (~1h), the stored refresh token
        renews them without another device login."""
        try:
            resp = getattr(self._client, method)(url, **kwargs)
        except Exception as e:
            raise exceptions.ApiServerConnectionError(self.endpoint) from e
        if resp.status_code == 401 and self._try_oauth_refresh():
            try:
                resp = getattr(self._client, method)(url, **kwargs)
            except Exception as e:
                raise exceptions.ApiServerConnectionError(
                    self.endpoint) from e
        return resp

    def _try_oauth_refresh(self) -> bool:
        """Renew the bearer token via the stored OAuth refresh token.

        Rate-limited, not latched: a successful refresh re-arms the
        retry (long poll loops outlive a single ~1h access token), but
        a failed one blocks further attempts for this client so a
        revoked refresh token can't hammer the IdP on every 401.
        """
        if getattr(self, '_refresh_blocked', False):
            return False
        from skypilot_tpu import config as config_lib
        from skypilot_tpu.users import oauth as oauth_lib
        refresh_token = config_lib.get_nested(
            ('api_server', 'refresh_token'))
        if not refresh_token or not oauth_lib.enabled():
            self._refresh_blocked = True
            return False
        try:
            tokens = oauth_lib.refresh_access_token(refresh_token)
        except oauth_lib.OAuthError:
            self._refresh_blocked = True
            return False
        access = tokens['access_token']
        self._client.headers['Authorization'] = f'Bearer {access}'
        _persist_tokens(access, tokens.get('refresh_token'))
        return True

    def _submit(self, verb: str, body: Dict[str, Any]) -> str:
        resp = self._request('post', f'/api/{verb}', json=body)
        resp.raise_for_status()
        return resp.json()['request_id']

    def _get(self, request_id: str) -> Any:
        deadline = time.time() + self.timeout_s
        while time.time() < deadline:
            resp = self._request('get', '/api/get',
                                 params={'request_id': request_id})
            resp.raise_for_status()
            payload = resp.json()
            if payload['status'] in ('PENDING', 'RUNNING'):
                time.sleep(self.poll_interval_s)
                continue
            if payload['status'] == 'FAILED':
                raise exceptions.deserialize_exception(payload['error'])
            if payload['status'] == 'CANCELLED':
                raise exceptions.RequestCancelled(request_id)
            return payload['result']
        raise TimeoutError(f'Request {request_id} timed out')

    def _call(self, verb: str, body: Dict[str, Any]) -> Any:
        return self._get(self._submit(verb, body))

    # ---- request management (xsky api status/logs/cancel) ----

    def list_api_requests(self, limit: int = 30):
        resp = self._request('get', '/api/requests',
                             params={'limit': limit})
        resp.raise_for_status()
        return resp.json().get('requests', [])[:limit]

    def get_api_request(self, request_id: str,
                        include_log: bool = False):
        """Raw request record (no polling; terminal or not)."""
        params = {'request_id': request_id}
        if include_log:
            params['include_log'] = '1'
        resp = self._request('get', '/api/get', params=params)
        if resp.status_code == 404:
            return None
        resp.raise_for_status()
        return resp.json()

    def cancel_api_request(self, request_id: str) -> bool:
        resp = self._request('post', '/api/requests/cancel',
                             json={'request_id': request_id})
        resp.raise_for_status()
        return bool(resp.json().get('cancelled'))

    def health(self) -> Dict[str, Any]:
        """GET /health — status/version/user (backs `xsky api info`)."""
        resp = self._request('get', '/health')
        try:
            resp.raise_for_status()
        except Exception as e:
            raise exceptions.ApiServerConnectionError(self.endpoint) from e
        return resp.json()

    # ---- verbs ----

    def launch(self, task, **kwargs) -> Any:
        body = {'task': task.to_yaml_config(), **_clean(kwargs)}
        result = self._call('launch', body)
        return result['job_id'], _HandleProxy(result['cluster_name'])

    def exec(self, task, cluster_name: str, **kwargs) -> Any:
        body = {'task': task.to_yaml_config(),
                'cluster_name': cluster_name, **_clean(kwargs)}
        result = self._call('exec', body)
        return result['job_id'], _HandleProxy(result['cluster_name'])

    def status(self, cluster_names=None, refresh=False, limit=None,
               offset=0):
        return self._call('status', {'cluster_names': cluster_names,
                                     'refresh': refresh,
                                     'limit': limit, 'offset': offset})

    def start(self, cluster_name, idle_minutes_to_autostop=None,
              down=False):
        return self._call('start', {
            'cluster_name': cluster_name,
            'idle_minutes_to_autostop': idle_minutes_to_autostop,
            'down': down})

    def stop(self, cluster_name):
        return self._call('stop', {'cluster_name': cluster_name})

    def down(self, cluster_name, purge=False):
        return self._call('down', {'cluster_name': cluster_name,
                                   'purge': purge})

    def autostop(self, cluster_name, idle_minutes, down_on_idle=False):
        return self._call('autostop', {'cluster_name': cluster_name,
                                       'idle_minutes': idle_minutes,
                                       'down': down_on_idle})

    def queue(self, cluster_name):
        return self._call('queue', {'cluster_name': cluster_name})

    def cluster_hosts(self, cluster_name):
        return self._call('cluster_hosts',
                          {'cluster_name': cluster_name})

    def endpoints(self, cluster_name, port=None):
        out = self._call('endpoints', {'cluster_name': cluster_name,
                                       'port': port})
        # JSON object keys arrive as strings; the SDK contract is
        # int ports.
        return {int(k): v for k, v in (out or {}).items()}

    def cancel(self, cluster_name, job_ids=None, all_jobs=False):
        return self._call('cancel', {'cluster_name': cluster_name,
                                     'job_ids': job_ids,
                                     'all_jobs': all_jobs})

    def tail_logs(self, cluster_name, job_id=None, follow=False,
                  all_ranks=False):
        return self._call('logs', {'cluster_name': cluster_name,
                                   'job_id': job_id,
                                   'all_ranks': all_ranks})

    def goodput_report(self, cluster_name=None, fleet=False,
                       limit=1000):
        return self._call('goodput.report',
                          {'cluster_name': cluster_name,
                           'fleet': fleet, 'limit': limit})

    def metrics_list(self, prefix=None, since=None, limit=200,
                     offset=0):
        return self._call('metrics.list',
                          {'prefix': prefix, 'since': since,
                           'limit': limit, 'offset': offset})

    def metrics_query(self, name, labels=None, since=None, until=None,
                      step=None, agg='avg', res=None):
        return self._call('metrics.query',
                          {'name': name, 'labels': labels,
                           'since': since, 'until': until,
                           'step': step, 'agg': agg, 'res': res})

    def profile_capture(self, cluster_name, job_id=None,
                        duration_s=1.0):
        out = self._call('profile.capture',
                         {'cluster_name': cluster_name,
                          'job_id': job_id,
                          'duration_s': duration_s})
        # JSON object keys arrive as strings; the SDK contract is
        # int ranks.
        return {int(k): v for k, v in (out or {}).items()}

    def check(self, quiet=False):
        return self._call('check', {})

    def storage_ls(self):
        return self._call('storage.ls', {})

    def storage_delete(self, storage_name):
        return self._call('storage.delete',
                          {'storage_name': storage_name})

    def storage_ls_objects(self, storage_name, prefix='', limit=100):
        return self._call('storage.ls_objects',
                          {'storage_name': storage_name,
                           'prefix': prefix, 'limit': limit})

    def cost_report(self):
        return self._call('cost_report', {})

    # ---- managed jobs ----

    def jobs_launch(self, task, name=None, priority=0):
        from skypilot_tpu import task as task_lib
        result = self._call(
            'jobs.launch',
            {'task': task_lib.Task.chain_to_config(task), 'name': name,
             'priority': int(priority)})
        return result['job_id']

    def jobs_queue(self):
        return self._call('jobs.queue', {})

    def jobs_cancel(self, job_id):
        return self._call('jobs.cancel', {'job_id': job_id})

    def jobs_logs(self, job_id):
        return self._call('jobs.logs', {'job_id': job_id})

    # ---- serve ----

    def serve_up(self, task, service_name=None):
        result = self._call('serve.up',
                            {'task': task.to_yaml_config(),
                             'service_name': service_name})
        return result['service_name']

    def serve_update(self, task, service_name, mode='rolling'):
        result = self._call('serve.update',
                            {'task': task.to_yaml_config(),
                             'service_name': service_name,
                             'mode': mode})
        return result['version']

    def serve_status(self, service_names=None):
        return self._call('serve.status',
                          {'service_names': service_names})

    # ---- users / workspaces ----

    def users_list(self):
        return self._call('users.list', {})

    def users_create(self, name, password, role='user'):
        return self._call('users.create',
                          {'name': name, 'password': password,
                           'role': role})

    def users_delete(self, name):
        return self._call('users.delete', {'name': name})

    def users_set_role(self, name, role):
        return self._call('users.set_role', {'name': name, 'role': role})

    def users_token_create(self, name, label='default'):
        return self._call('users.token_create',
                          {'name': name, 'label': label})

    def users_token_list(self, name=None):
        return self._call('users.token_list', {'name': name})

    def users_token_revoke(self, name, label):
        return self._call('users.token_revoke',
                          {'name': name, 'label': label})

    def workspaces_list(self):
        return self._call('workspaces.list', {})

    def workspaces_create(self, name):
        return self._call('workspaces.create', {'name': name})

    def workspaces_delete(self, name):
        return self._call('workspaces.delete', {'name': name})

    def workspaces_add_member(self, workspace, user_name):
        return self._call('workspaces.add_member',
                          {'workspace': workspace,
                           'user_name': user_name})

    def workspaces_remove_member(self, workspace, user_name):
        return self._call('workspaces.remove_member',
                          {'workspace': workspace,
                           'user_name': user_name})

    def workspaces_members(self, workspace):
        return self._call('workspaces.members', {'workspace': workspace})

    def workspaces_set_config(self, workspace, config):
        return self._call('workspaces.set_config',
                          {'workspace': workspace, 'config': config})

    def workspaces_get_config(self, workspace):
        return self._call('workspaces.get_config',
                          {'workspace': workspace})

    def serve_down(self, service_name):
        return self._call('serve.down', {'service_name': service_name})

    def ssh_up(self, infra=None):
        return self._call('ssh.up', {'infra': infra})

    def ssh_down(self, infra=None):
        return self._call('ssh.down', {'infra': infra})


class _HandleProxy:
    """Client-side stand-in for a ClusterHandle (server keeps the real one)."""

    def __init__(self, cluster_name: str) -> None:
        self.cluster_name = cluster_name

    def get_cluster_name(self) -> str:
        return self.cluster_name


def _clean(kwargs: Dict[str, Any]) -> Dict[str, Any]:
    return {k: v for k, v in kwargs.items() if v is not None}


def _persist_tokens(access_token: str,
                    refresh_token: Optional[str] = None) -> None:
    """Write renewed OAuth tokens back to the user config (the same
    api_server section `xsky api login` fills), so the next process
    starts with the fresh access token. Best-effort: a read-only
    config just means another refresh next run."""
    import yaml

    from skypilot_tpu import config as config_lib
    updates = {'token': access_token}
    if refresh_token:
        updates['refresh_token'] = refresh_token
    try:
        config_lib.update_user_config_section('api_server', updates)
    except (OSError, yaml.YAMLError):
        # Best-effort by contract: an unwritable or corrupted config
        # just means another refresh next run — never fail the request
        # the refresh already unblocked.
        pass
