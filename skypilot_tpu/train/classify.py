"""Sequence-classification fine-tune — the BASELINE "BERT-base GLUE
fine-tune E2E" target (twin of the reference's
examples/huggingface_glue_imdb_app.yaml, which fine-tunes a HF encoder
on IMDB sentiment).

TPU-first redesign instead of a torch/transformers port: the classifier
is a linear head over the last-token hidden state of an in-tree decoder
LM (`models/llama.py prefill_hidden`) — the standard decoder-as-encoder
classification recipe — trained with optax under jit. Runs on CPU and
on a single TPU chip unchanged (BASELINE: "runs on CPU → v5e-1").

Data: JSONL rows ``{"tokens": [...], "label": n}`` (pre-tokenized — the
zero-egress build cannot download IMDB), or a built-in synthetic
sentiment-style set for smoke runs. ``python -m
skypilot_tpu.train.classify --steps 200`` prints one JSON line with the
final eval accuracy.
"""
from __future__ import annotations

import dataclasses
import functools
import json
from typing import Dict, Iterator, List, Optional, Tuple

import jax
import jax.numpy as jnp
import optax

from skypilot_tpu.models import llama


@dataclasses.dataclass(frozen=True)
class ClassifyConfig:
    model: llama.LlamaConfig
    num_classes: int = 2
    seq_len: int = 128
    batch_size: int = 16
    learning_rate: float = 3e-4
    head_only: bool = False   # freeze the trunk, train only the head
    weight_decay: float = 0.01


Params = Dict[str, jax.Array]


def init(config: ClassifyConfig, key: jax.Array) -> Dict[str, Params]:
    """{'trunk': LM params, 'head': {'w' [D, C], 'b' [C]}}."""
    trunk_key, head_key = jax.random.split(key)
    d = config.model.d_model
    return {
        'trunk': llama.init(config.model, trunk_key),
        'head': {
            'w': (jax.random.normal(head_key, (d, config.num_classes),
                                    jnp.float32) / jnp.sqrt(d)),
            'b': jnp.zeros((config.num_classes,), jnp.float32),
        },
    }


def logits_fn(config: ClassifyConfig, params: Dict[str, Params],
              tokens: jax.Array, true_len: jax.Array) -> jax.Array:
    """[B, S] tokens (+ per-row lengths) → [B, C] fp32 class logits."""
    hidden, _ = llama.prefill_hidden(config.model, params['trunk'],
                                     tokens, true_len)
    return (hidden.astype(jnp.float32) @ params['head']['w']
            + params['head']['b'])


def _loss(config: ClassifyConfig, params, batch) -> jax.Array:
    logits = logits_fn(config, params, batch['tokens'],
                       batch['true_len'])
    return optax.softmax_cross_entropy_with_integer_labels(
        logits, batch['label']).mean()


def make_train_step(config: ClassifyConfig,
                    tx: optax.GradientTransformation):
    """head_only truly freezes the trunk: the optimizer state and
    updates cover ONLY the head subtree — zeroed trunk grads would not
    be enough, because adamw's weight decay shrinks every optimized
    param regardless of its gradient."""
    @jax.jit
    def step(params, opt_state, batch):
        if config.head_only:
            def loss_of(head):
                return _loss(config, {'trunk': params['trunk'],
                                      'head': head}, batch)
            loss, grads = jax.value_and_grad(loss_of)(params['head'])
            updates, opt_state = tx.update(grads, opt_state,
                                           params['head'])
            head = optax.apply_updates(params['head'], updates)
            return ({'trunk': params['trunk'], 'head': head},
                    opt_state, loss)
        loss, grads = jax.value_and_grad(
            lambda p: _loss(config, p, batch))(params)
        updates, opt_state = tx.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss
    return step


def init_opt_state(config: ClassifyConfig,
                   tx: optax.GradientTransformation, params):
    return tx.init(params['head'] if config.head_only else params)


@functools.partial(jax.jit, static_argnums=0)
def eval_accuracy(config: ClassifyConfig, params,
                  batch) -> jax.Array:
    logits = logits_fn(config, params, batch['tokens'],
                       batch['true_len'])
    return (jnp.argmax(logits, axis=-1) == batch['label']).mean()


# ---------------------------------------------------------------------------
# Data


def synthetic_batches(config: ClassifyConfig, key: jax.Array,
                      ) -> Iterator[Dict[str, jax.Array]]:
    """Sentiment-style synthetic set: each class draws its tokens from
    a different half of the vocabulary with 20% shared 'stopwords', so
    the task is learnable but not trivial."""
    vocab = config.model.vocab_size
    n = config.num_classes
    band = max(2, (vocab - 2) // n)   # one vocab band per class
    while True:
        key, k1, k2, k3, k4 = jax.random.split(key, 5)
        b, s = config.batch_size, config.seq_len
        label = jax.random.randint(k1, (b,), 0, n)
        class_tok = (jax.random.randint(k2, (b, s), 1, band)
                     + label[:, None] * band)
        shared = jax.random.randint(k3, (b, s), 1, band)
        use_shared = jax.random.bernoulli(k4, 0.2, (b, s))
        tokens = jnp.where(use_shared, shared, class_tok) % vocab
        true_len = jnp.full((b,), s, jnp.int32)
        yield {'tokens': tokens, 'true_len': true_len, 'label': label}


def jsonl_batches(config: ClassifyConfig, path: str,
                  split: str = 'all',
                  ) -> Iterator[Dict[str, jax.Array]]:
    """Cycle over pre-tokenized JSONL rows, padded/truncated to
    seq_len; true_len keeps the real length for last-token pooling.

    split: 'all' | 'train' | 'eval' — train/eval hold out every 5th
    row so the reported accuracy is held-out, not training-set.
    """
    import numpy as np
    rows: List[Tuple[List[int], int]] = []
    with open(path, encoding='utf-8') as f:
        for line in f:
            if not line.strip():
                continue
            row = json.loads(line)
            rows.append((list(row['tokens']), int(row['label'])))
    if split == 'train':
        rows = [r for i, r in enumerate(rows) if i % 5 != 0]
    elif split == 'eval':
        rows = [r for i, r in enumerate(rows) if i % 5 == 0]
    if not rows:
        raise ValueError(f'no rows in {path} (split={split!r})')
    i = 0
    while True:
        # Host-side numpy prep, one device transfer per batch.
        toks = np.zeros((config.batch_size, config.seq_len), np.int32)
        lens = np.empty((config.batch_size,), np.int32)
        labels = np.empty((config.batch_size,), np.int32)
        for b in range(config.batch_size):
            t, label = rows[i % len(rows)]
            i += 1
            t = t[:config.seq_len]
            toks[b, :len(t)] = t
            lens[b] = max(1, len(t))
            labels[b] = label
        yield {'tokens': jnp.asarray(toks),
               'true_len': jnp.asarray(lens),
               'label': jnp.asarray(labels)}


# ---------------------------------------------------------------------------
# Driver


def train(config: ClassifyConfig,
          steps: int,
          data: Optional[Iterator[Dict[str, jax.Array]]] = None,
          eval_data: Optional[Iterator[Dict[str, jax.Array]]] = None,
          eval_batches: int = 4,
          seed: int = 0,
          log_every: int = 20) -> Dict[str, float]:
    """eval_data defaults to fresh draws from the synthetic stream
    (held-out by construction). For file-backed data pass a held-out
    iterator (jsonl_batches(..., split='eval')) — evaluating on the
    training iterator would report training-set accuracy."""
    key = jax.random.PRNGKey(seed)
    params = init(config, key)
    tx = optax.adamw(config.learning_rate,
                     weight_decay=config.weight_decay)
    opt_state = init_opt_state(config, tx, params)
    step_fn = make_train_step(config, tx)
    batches = data if data is not None else synthetic_batches(
        config, jax.random.fold_in(key, 1))
    if eval_data is None:
        eval_data = (synthetic_batches(config, jax.random.fold_in(key, 2))
                     if data is None else batches)
    loss = None
    for i in range(steps):
        batch = next(batches)
        params, opt_state, loss = step_fn(params, opt_state, batch)
        if log_every and (i + 1) % log_every == 0:
            print(f'# step {i + 1}/{steps} loss={float(loss):.4f}',
                  flush=True)
    accs = [float(eval_accuracy(config, params, next(eval_data)))
            for _ in range(eval_batches)]
    return {'loss': float(loss) if loss is not None else float('nan'),
            'eval_accuracy': sum(accs) / len(accs)}


def main(argv: Optional[List[str]] = None) -> int:
    import argparse
    parser = argparse.ArgumentParser(
        description='Sequence-classification fine-tune (GLUE twin).')
    parser.add_argument('--steps', type=int, default=200)
    parser.add_argument('--batch-size', type=int, default=16)
    parser.add_argument('--seq-len', type=int, default=128)
    parser.add_argument('--num-classes', type=int, default=2)
    parser.add_argument('--lr', type=float, default=3e-4)
    parser.add_argument('--data', default=None,
                        help='JSONL of {"tokens": [...], "label": n}; '
                             'default: built-in synthetic set')
    parser.add_argument('--head-only', action='store_true')
    parser.add_argument('--model', default='tiny',
                        choices=['tiny', '1b', '8b'])
    args = parser.parse_args(argv)
    model = {'tiny': llama.LLAMA_TINY, '1b': llama.LLAMA3_1B,
             '8b': llama.LLAMA3_8B}[args.model]
    model = dataclasses.replace(model, max_seq_len=args.seq_len)
    config = ClassifyConfig(model=model, num_classes=args.num_classes,
                            seq_len=args.seq_len,
                            batch_size=args.batch_size,
                            learning_rate=args.lr,
                            head_only=args.head_only)
    data = eval_data = None
    if args.data:
        data = jsonl_batches(config, args.data, split='train')
        eval_data = jsonl_batches(config, args.data, split='eval')
    metrics = train(config, steps=args.steps, data=data,
                    eval_data=eval_data)
    print(json.dumps({'metric': 'classify_eval_accuracy',
                      'value': round(metrics['eval_accuracy'], 4),
                      'loss': round(metrics['loss'], 4),
                      'model': args.model, 'steps': args.steps}))
    return 0


if __name__ == '__main__':
    raise SystemExit(main())
