"""Workspace operations (twin of sky/workspaces/core.py, 679 LoC).

A workspace is a namespace over clusters: every cluster record carries a
workspace tag; status filters by workspace when one is pinned (request
body or XSKY_WORKSPACE) and shows all otherwise, and a workspace cannot
be deleted while it still owns clusters. The reference additionally
scopes config overlays per workspace; here the task `config:` overlay
plays that role.
"""
from __future__ import annotations

import re
from typing import Any, Dict, List

from skypilot_tpu import state

_NAME_RE = re.compile(r'^[a-z0-9][a-z0-9-]{0,48}$')
DEFAULT_WORKSPACE = 'default'


def get_workspaces() -> List[str]:
    return state.list_workspaces()


def create_workspace(name: str) -> Dict[str, Any]:
    if not _NAME_RE.match(name):
        raise ValueError(
            f'Invalid workspace name {name!r} (lowercase alphanumeric + '
            'dashes, max 49 chars).')
    state.add_workspace(name)
    return {'name': name}


def delete_workspace(name: str) -> Dict[str, Any]:
    if name == DEFAULT_WORKSPACE:
        raise ValueError('The default workspace cannot be deleted.')
    clusters = state.get_clusters(workspace=name)
    if clusters:
        raise ValueError(
            f'Workspace {name!r} still has {len(clusters)} cluster(s): '
            f'{[c["name"] for c in clusters]}. Tear them down first.')
    return {'deleted': state.delete_workspace(name)}


def validate_exists(name: str) -> str:
    if name not in state.list_workspaces():
        raise ValueError(f'Workspace {name!r} does not exist; create it '
                         'with `xsky workspaces create`.')
    return name


# ---- membership (per-workspace authz; ref sky/workspaces/core.py +
# sky/users/rbac.py workspace policies) -------------------------------------


def add_member(workspace: str, user_name: str) -> Dict[str, Any]:
    validate_exists(workspace)
    if state.list_users() and state.get_user(user_name) is None:
        # With a user registry in play, granting access to an unknown
        # name is a typo, not a grant (and would pre-authorize whoever
        # registers that name later).
        raise ValueError(f'Unknown user {user_name!r}; create the '
                         'account first (`xsky users create`).')
    state.add_workspace_member(workspace, user_name)
    return {'workspace': workspace, 'member': user_name}


def remove_member(workspace: str, user_name: str) -> Dict[str, Any]:
    validate_exists(workspace)
    return {'removed': state.remove_workspace_member(workspace,
                                                     user_name)}


def list_members(workspace: str) -> List[str]:
    validate_exists(workspace)
    return state.list_workspace_members(workspace)


def check_access(user: str, role: str, workspace: str) -> bool:
    """May `user` operate inside `workspace`?

    Admins everywhere; every authenticated user in 'default' (the
    single-user / pre-workspace experience stays frictionless); private
    workspaces require membership.
    """
    from skypilot_tpu.users import rbac
    if role == rbac.ADMIN_ROLE:
        return True
    if workspace == DEFAULT_WORKSPACE:
        return True
    return state.is_workspace_member(workspace, user)


# ---- per-workspace config overlays ----------------------------------------


def set_config(workspace: str, config: Dict[str, Any]) -> Dict[str, Any]:
    """Store a config overlay applied to every launch in `workspace`
    (ref: per-workspace config in sky/workspaces/core.py + the
    `workspaces:` section of the reference config schema)."""
    import json
    validate_exists(workspace)
    if not isinstance(config, dict):
        raise ValueError('workspace config must be a mapping')
    state.set_workspace_config(workspace, json.dumps(config))
    return {'workspace': workspace, 'config': config}


def get_config(workspace: str) -> Dict[str, Any]:
    import json
    validate_exists(workspace)   # 'default' is always seeded
    raw = state.get_workspace_config(workspace)
    return json.loads(raw) if raw else {}
