"""Async multi-tier checkpoint plane (agent/checkpointd.py): the
Young-cadence controller, tiered shard writes with digest manifests,
peer replication over the host fan-out, the restore ladder's fallback
arms (corrupt/torn peer manifest → older peer copy → storage tier →
cold start) each driven by its chaos point with the journalled tier
asserted, the telemetry/metrics/CLI surfaces, controller env
threading, and the tier-1 fake-cloud smoke: a chaos-stalled rank's
relaunch restores from the fast tier and `xsky goodput --json` shows
`restart_replay` bounded by the checkpoint cadence."""
import json
import os
import shutil
import time

import pytest

from skypilot_tpu.agent import checkpointd
from skypilot_tpu.agent import telemetry
from skypilot_tpu.utils import chaos
from skypilot_tpu.utils import metrics as metrics_registry

REPO_ROOT = os.path.abspath(
    os.path.join(os.path.dirname(__file__), '..', '..'))


@pytest.fixture(autouse=True)
def _clean(monkeypatch):
    for var in (checkpointd.ENV_DIR, checkpointd.ENV_PEER_DIRS,
                checkpointd.ENV_MTTF, checkpointd.ENV_SCOPE,
                telemetry.ENV_DIR):
        monkeypatch.delenv(var, raising=False)
    checkpointd.reset_for_test()
    telemetry.reset_for_test()
    metrics_registry.reset_for_test()
    chaos.clear()
    yield
    checkpointd.reset_for_test()
    telemetry.reset_for_test()
    metrics_registry.reset_for_test()
    chaos.clear()


@pytest.fixture
def tmp_state(monkeypatch, tmp_path):
    from skypilot_tpu import state
    monkeypatch.setenv('XSKY_STATE_DB', str(tmp_path / 'state.db'))
    state.reset_for_test()
    yield state
    state.reset_for_test()


def _checkpointer(tmp_path, peers=1, **kwargs):
    peer_dirs = tuple(str(tmp_path / f'peer{i}')
                      for i in range(peers))
    ck = checkpointd.Checkpointer(str(tmp_path / 'own'), rank=0,
                                  peer_dirs=peer_dirs, **kwargs)
    checkpointd.install(ck)
    return ck, peer_dirs


def _snapshot(ck, step, payload=None):
    assert checkpointd.maybe_checkpoint(
        step, lambda: payload if payload is not None
        else {'step': step}, force=True)
    assert ck.wait_idle(10)


# ---- cadence ----------------------------------------------------------------


class TestCadence:

    def test_young_interval_from_cost_and_mttf(self, monkeypatch):
        monkeypatch.setenv(checkpointd.ENV_MIN_INTERVAL, '1')
        monkeypatch.setenv(checkpointd.ENV_MAX_INTERVAL, '10000')
        monkeypatch.setenv(checkpointd.ENV_MTTF, '800')
        cadence = checkpointd.Cadence()
        cadence.observe_cost(0.5)
        # sqrt(2 * 0.5 * 800) = 28.28...
        assert cadence.interval_s() == pytest.approx(28.28, abs=0.1)

    def test_clamps(self, monkeypatch):
        monkeypatch.setenv(checkpointd.ENV_MTTF, '800')
        cadence = checkpointd.Cadence()
        cadence.observe_cost(0.5)
        monkeypatch.setenv(checkpointd.ENV_MIN_INTERVAL, '1')
        monkeypatch.setenv(checkpointd.ENV_MAX_INTERVAL, '5')
        assert cadence.interval_s() == 5.0
        monkeypatch.setenv(checkpointd.ENV_MAX_INTERVAL, '10000')
        monkeypatch.setenv(checkpointd.ENV_MIN_INTERVAL, '60')
        assert cadence.interval_s() == 60.0
        # Near-zero measured cost floors at the min clamp, not zero.
        free = checkpointd.Cadence()
        free.observe_cost(0.0)
        assert free.interval_s() == 60.0

    def test_due_and_arm(self, monkeypatch):
        monkeypatch.setenv(checkpointd.ENV_MIN_INTERVAL, '100')
        monkeypatch.setenv(checkpointd.ENV_MAX_INTERVAL, '100')
        cadence = checkpointd.Cadence()
        assert cadence.due(now=0.0)     # first checkpoint is free
        cadence.arm(now=0.0)
        assert not cadence.due(now=99.0)
        assert cadence.due(now=100.0)

    def test_step_time_quantizes_interval(self, monkeypatch):
        """The telemetry plane's step-time EMA rounds the Young
        interval up to whole steps (replay is re-bought in whole
        steps)."""
        monkeypatch.setenv(checkpointd.ENV_MIN_INTERVAL, '1')
        monkeypatch.setenv(checkpointd.ENV_MAX_INTERVAL, '10000')
        monkeypatch.setenv(checkpointd.ENV_MTTF, '800')
        cadence = checkpointd.Cadence()
        cadence.observe_cost(0.5)          # young = 28.28
        cadence.observe_step_time(3.0)
        assert cadence.interval_s() == pytest.approx(30.0)  # 10 steps
        # One step longer than the ceiling: the step wins (a snapshot
        # cannot fire mid-step).
        monkeypatch.setenv(checkpointd.ENV_MAX_INTERVAL, '2')
        slow = checkpointd.Cadence()
        slow.observe_cost(0.5)
        slow.observe_step_time(5.0)
        assert slow.interval_s() == pytest.approx(5.0)

    def test_mttf_env_hint_wins(self, monkeypatch):
        monkeypatch.setenv(checkpointd.ENV_MTTF, '123')
        assert checkpointd.mttf_s() == 123.0
        monkeypatch.delenv(checkpointd.ENV_MTTF)
        assert checkpointd.mttf_s() == 1800.0


# ---- write side -------------------------------------------------------------


class TestTieredWrite:

    def test_manifest_digest_and_prune(self, tmp_path, tmp_state,
                                       monkeypatch):
        monkeypatch.setenv(checkpointd.ENV_KEEP, '2')
        ck, _ = _checkpointer(tmp_path, peers=0)
        for step in (3, 7, 11):
            _snapshot(ck, step)
        rank_dir = tmp_path / 'own' / 'rank-0'
        names = sorted(os.listdir(rank_dir))
        # keep=2: step 3 pruned, 7 and 11 kept (manifest + shard).
        assert names == ['manifest-11.json', 'manifest-7.json',
                         'shard-11.bin', 'shard-7.bin']
        manifest = json.loads(
            (rank_dir / 'manifest-11.json').read_text())
        assert manifest['step'] == 11
        assert manifest['rank'] == 0
        assert manifest['bytes'] > 0
        import hashlib
        assert manifest['digest'] == hashlib.sha256(
            (rank_dir / 'shard-11.bin').read_bytes()).hexdigest()
        assert ck.last_step == 11

    def test_write_counters_and_freshness_emit(
            self, tmp_path, tmp_state, monkeypatch):
        spool = tmp_path / 'spool'
        monkeypatch.setenv(telemetry.ENV_DIR, str(spool))
        monkeypatch.setenv(telemetry.ENV_INTERVAL, '0')
        ck, _ = _checkpointer(tmp_path, peers=0)
        _snapshot(ck, 5)
        rendered = metrics_registry.render_registry()
        assert 'xsky_ckpt_writes_total 1' in rendered
        assert 'xsky_ckpt_bytes_total' in rendered
        # The freshness signal rides the rank's telemetry sample.
        sample = telemetry.read_spool(str(spool))[0]
        assert sample['ckpt_step'] == 5
        assert sample['ckpt_ts'] <= time.time()

    def test_chaos_write_drops_snapshot_never_raises(
            self, tmp_path, tmp_state):
        chaos.load_plan({'points': {'ckpt.write': {
            'first_n': 1, 'error': 'RuntimeError'}}})
        ck, _ = _checkpointer(tmp_path, peers=0)
        assert checkpointd.maybe_checkpoint(4, lambda: {'step': 4},
                                            force=True)
        assert ck.wait_idle(10)
        assert not os.path.exists(tmp_path / 'own' / 'rank-0')
        # The next write (rule exhausted) lands normally.
        _snapshot(ck, 8)
        assert (tmp_path / 'own' / 'rank-0' /
                'manifest-8.json').exists()

    def test_disabled_plane_is_a_noop(self, monkeypatch):
        monkeypatch.setenv(checkpointd.ENV_ENABLED, '0')
        assert not checkpointd.maybe_checkpoint(1, lambda: {})
        assert checkpointd.restore() is None
        assert checkpointd.wait_idle() is True

    def test_from_env_wiring(self, monkeypatch, tmp_path):
        monkeypatch.setenv(checkpointd.ENV_DIR, str(tmp_path / 'd'))
        monkeypatch.setenv(checkpointd.ENV_PEER_DIRS, 'p1\np2')
        monkeypatch.setenv('XSKY_HOST_RANK', '3')
        monkeypatch.setenv('XSKY_ELASTIC_GENERATION', '2')
        ck = checkpointd.Checkpointer.from_env()
        assert ck.rank == 3
        assert ck.incarnation == 2
        assert len(ck.peer_dirs) == 2


# ---- peer replication -------------------------------------------------------


class TestReplicate:

    def test_replicas_land_on_every_peer(self, tmp_path, tmp_state):
        ck, peer_dirs = _checkpointer(tmp_path, peers=2)
        _snapshot(ck, 6)
        for peer in peer_dirs:
            replica = os.path.join(peer, 'peer-rank-0')
            assert sorted(os.listdir(replica)) == [
                'manifest-6.json', 'shard-6.bin']

    def test_chaos_replicate_costs_one_peer_only(self, tmp_path,
                                                 tmp_state):
        ck, peer_dirs = _checkpointer(tmp_path, peers=2)
        chaos.load_plan({'points': {'ckpt.replicate': {
            'match': {'peer': peer_dirs[0]}, 'first_n': 1,
            'error': 'ConnectionError'}}})
        _snapshot(ck, 6)
        assert not os.path.exists(
            os.path.join(peer_dirs[0], 'peer-rank-0'))
        assert os.path.exists(
            os.path.join(peer_dirs[1], 'peer-rank-0',
                         'manifest-6.json'))
        # The local tier and the manifest survived the peer failure.
        assert (tmp_path / 'own' / 'rank-0' /
                'manifest-6.json').exists()


# ---- restore ladder ---------------------------------------------------------


class TestRestoreLadder:

    def _journalled_tiers(self, state, scope='ckpt/rank-0'):
        return [(e['detail'] or {}).get('tier')
                for e in state.get_recovery_events(scope=scope)]

    def test_local_freshest_wins(self, tmp_path, tmp_state):
        ck, _ = _checkpointer(tmp_path, peers=1)
        _snapshot(ck, 5)
        _snapshot(ck, 9)
        snap = checkpointd.restore()
        assert (snap.step, snap.tier) == (9, 'local')
        assert snap.payload == {'step': 9}
        events = tmp_state.get_recovery_events(scope='ckpt/rank-0')
        assert events[0]['event_type'] == 'job.ckpt_restored'
        assert events[0]['detail']['resume_step'] == 9
        assert events[0]['detail']['replayed_steps'] == 0
        assert events[0]['latency_s'] is not None

    def test_corrupt_then_older_then_storage_then_cold(
            self, tmp_path, tmp_state):
        """The full fallback chain, arm by arm: corrupt/torn newest
        peer copy → older peer copy → storage tier → cold start with
        resume_step=0, each journalled with its tier."""
        ck, peer_dirs = _checkpointer(tmp_path, peers=1)
        _snapshot(ck, 5)
        _snapshot(ck, 9)
        shutil.rmtree(tmp_path / 'own')   # this host is fresh
        replica = os.path.join(peer_dirs[0], 'peer-rank-0')
        # Torn manifest AND corrupt shard for the newest copy.
        with open(os.path.join(replica, 'manifest-9.json'), 'w',
                  encoding='utf-8') as f:
            f.write('{"step": 9, "digest"')    # torn mid-write
        with open(os.path.join(replica, 'shard-5.bin'), 'ab') as f:
            f.write(b'bitrot')
        # shard-5 now mismatches its digest; manifest-9 is torn: the
        # only valid copy left is... none — digest mismatch discards
        # shard-5 too, so the ladder falls through to storage.
        snap = checkpointd.restore(storage_fn=lambda: (3, {'s': 3}))
        assert (snap.step, snap.tier) == (3, 'storage')
        # Repair the older copy: older-peer-copy arm wins over
        # storage.
        ck2 = checkpointd.Checkpointer(str(tmp_path / 'own'), rank=0,
                                       peer_dirs=(peer_dirs[0],))
        checkpointd.install(ck2)
        _snapshot(ck2, 5)
        shutil.rmtree(tmp_path / 'own')
        with open(os.path.join(peer_dirs[0], 'peer-rank-0',
                               'manifest-9.json'), 'w',
                  encoding='utf-8') as f:
            f.write('not json at all')
        snap = checkpointd.restore(storage_fn=lambda: (3, {'s': 3}))
        assert (snap.step, snap.tier) == (5, 'peer')
        # Nothing anywhere and no storage: cold, resume_step 0.
        shutil.rmtree(peer_dirs[0])
        snap = checkpointd.restore()
        assert (snap.step, snap.tier) == (0, 'cold')
        tiers = self._journalled_tiers(tmp_state)
        assert tiers == ['storage', 'peer', 'cold']

    def test_chaos_forces_each_arm(self, tmp_path, tmp_state):
        """The `ckpt.restore` chaos point drives the fallback arms
        without touching files: fail the local read → peer; fail both
        → storage; fail storage too → cold. Never raises."""
        ck, peer_dirs = _checkpointer(tmp_path, peers=1)
        _snapshot(ck, 9)
        chaos.load_plan({'points': {'ckpt.restore': {
            'match': {'tier': 'local'}, 'error': 'OSError'}}})
        snap = checkpointd.restore()
        assert (snap.step, snap.tier) == (9, 'peer')
        chaos.load_plan({'points': {'ckpt.restore': [
            {'match': {'tier': 'local'}, 'error': 'OSError'},
            {'match': {'tier': 'peer'}, 'error': 'OSError'},
        ]}})
        snap = checkpointd.restore(storage_fn=lambda: (2, 'blob'))
        assert (snap.step, snap.tier) == (2, 'storage')
        chaos.load_plan({'points': {'ckpt.restore': [
            {'match': {'tier': 'local'}, 'error': 'OSError'},
            {'match': {'tier': 'peer'}, 'error': 'OSError'},
            {'match': {'tier': 'storage'}, 'error': 'OSError'},
        ]}})
        snap = checkpointd.restore(storage_fn=lambda: (2, 'blob'))
        assert (snap.step, snap.tier) == (0, 'cold')
        rendered = metrics_registry.render_registry()
        assert 'xsky_ckpt_restores_total{tier="peer"} 1' in rendered
        assert 'xsky_ckpt_restores_total{tier="cold"} 1' in rendered

    def test_restore_journal_scope_env(self, tmp_path, tmp_state,
                                       monkeypatch):
        monkeypatch.setenv(checkpointd.ENV_SCOPE, 'job/42')
        ck, _ = _checkpointer(tmp_path, peers=0)
        _snapshot(ck, 7)
        checkpointd.restore()
        events = tmp_state.get_recovery_events(scope='job/42')
        assert events[0]['event_type'] == 'job.ckpt_restored'
        assert events[0]['detail']['tier'] == 'local'
        # Trace-linked: the restore ran under the jobs.ckpt_restore
        # span.
        assert events[0]['trace_id']


# ---- controller env threading ----------------------------------------------


class TestControllerThreading:

    def test_derive_mttf_from_journal(self, tmp_state, monkeypatch):
        assert checkpointd.derive_mttf('job/9') == 1800.0
        tmp_state.heartbeat_lease('job/9', owner='test')
        for _ in range(3):
            tmp_state.record_recovery_event('job.preempted',
                                            scope='job/9')
        # Fresh lease: age/failures clamps at the 60 s floor.
        assert checkpointd.derive_mttf('job/9') == 60.0
        # A mature lease spreads the failures over its lifetime.
        assert checkpointd.derive_mttf(
            'job/9', now=time.time() + 3600) == pytest.approx(
                1200.0, rel=0.05)
        # Unreadable DB degrades to the default, never raises.
        monkeypatch.setattr(
            tmp_state, 'count_recovery_events',
            lambda *a, **kw: (_ for _ in ()).throw(
                RuntimeError('down')))
        assert checkpointd.derive_mttf('job/9') == 1800.0

    def test_controller_threads_scope_and_mttf(self, tmp_state,
                                               monkeypatch, tmp_path):
        from skypilot_tpu import Task
        from skypilot_tpu.jobs import controller as controller_lib
        from skypilot_tpu.jobs import state as jobs_state
        monkeypatch.setenv('XSKY_JOBS_DB',
                           str(tmp_path / 'jobs.db'))
        task = Task('t', run='true')
        job_id = jobs_state.add_job('t', Task.chain_to_config([task]))
        controller = controller_lib.JobsController(job_id)
        env = controller._ckpt_env()  # pylint: disable=protected-access
        assert env[checkpointd.ENV_SCOPE] == f'job/{job_id}'
        assert float(env[checkpointd.ENV_MTTF]) > 0

    def test_backend_forwards_ckpt_knobs(self, monkeypatch):
        monkeypatch.setenv(checkpointd.ENV_MIN_INTERVAL, '3')
        monkeypatch.setenv(checkpointd.ENV_ENABLED, '1')
        from skypilot_tpu import Task
        from skypilot_tpu.backends import tpu_gang_backend
        backend = tpu_gang_backend.TpuGangBackend()
        task = Task('t', run='true')
        task.update_envs({checkpointd.ENV_ENABLED: '0'})

        class _Handle:
            is_local_provider = True
            provider_name = 'fake'
            launched_resources = None

        spec = backend._job_spec(_Handle(), task)  # pylint: disable=protected-access
        # Control-plane knob forwarded; task env wins on conflict.
        assert spec['envs'][checkpointd.ENV_MIN_INTERVAL] == '3'
        assert spec['envs'][checkpointd.ENV_ENABLED] == '0'


# ---- surfaces ---------------------------------------------------------------


class TestSurfaces:

    def _record(self, state, cluster='xsky-jobs-7'):
        telemetry.record_samples(cluster, 1, {0: {
            'rank': 0, 'phase': 'step', 'step': 20,
            'step_time_ema_s': 0.1, 'started_ts': 10.0,
            'last_progress_ts': time.time(), 'hb_ts': time.time(),
            'ckpt_step': 18, 'ckpt_ts': time.time() - 4.0,
        }})

    def test_telemetry_columns_round_trip(self, tmp_state):
        self._record(tmp_state)
        row = tmp_state.get_workload_telemetry(
            cluster='xsky-jobs-7')[0]
        assert row['ckpt_step'] == 18
        assert row['ckpt_ts'] is not None

    def test_metrics_freshness_gauge_live_filtered(self, tmp_state):
        from skypilot_tpu.server import metrics as server_metrics
        self._record(tmp_state)
        out = server_metrics.render()
        assert 'xsky_ckpt_freshness_age_seconds' not in out
        tmp_state.add_or_update_cluster('xsky-jobs-7', None)
        out = server_metrics.render()
        assert ('xsky_ckpt_freshness_age_seconds{cluster='
                '"xsky-jobs-7",job="1",rank="0"}') in out

    def test_top_summary_shows_ckpt_freshness(self, tmp_state):
        from click.testing import CliRunner

        from skypilot_tpu.client import cli as cli_mod
        self._record(tmp_state)
        result = CliRunner().invoke(cli_mod.cli, ['top'])
        assert result.exit_code == 0, result.output
        assert 'ckpt=18@' in result.output
        rows = CliRunner().invoke(cli_mod.cli, ['top', '--json'])
        payload = json.loads(rows.output.splitlines()[0])
        assert payload['ckpt_step'] == 18
        assert payload['ckpt_age_s'] is not None


# ---- tier-1 fake-cloud smoke ------------------------------------------------


class TestCkptSmoke:
    """Tier-1 acceptance (ISSUE 13 satellite): a fake-cloud managed
    job whose rank is chaos-stalled relaunches (1 host — the head rank
    cannot shrink away); the relaunch restores from the fast tier
    (`job.ckpt_restored` tier=local under the job scope) and
    `xsky goodput --json` shows `restart_replay` bounded by the
    checkpoint cadence instead of rebuying all banked progress."""

    def test_relaunch_restores_and_replay_is_bounded(
            self, fake_cluster_env, monkeypatch, tmp_path):
        del fake_cluster_env
        import sys
        import threading

        from click.testing import CliRunner

        from skypilot_tpu import Resources, Task
        from skypilot_tpu import state as state_lib
        from skypilot_tpu.client import cli as cli_mod
        from skypilot_tpu.jobs import controller as controller_lib
        from skypilot_tpu.jobs import scheduler as jobs_scheduler
        from skypilot_tpu.jobs import state as jobs_state

        monkeypatch.setenv('XSKY_JOBS_DB',
                           str(tmp_path / 'managed_jobs.db'))
        monkeypatch.setenv('XSKY_JOBS_LOG_DIR', str(tmp_path / 'jl'))
        monkeypatch.setattr(controller_lib, 'POLL_INTERVAL_S', 0.2)
        monkeypatch.setenv(telemetry.ENV_INTERVAL, '0.1')
        monkeypatch.setenv(telemetry.ENV_PULL_INTERVAL, '0.15')
        monkeypatch.setenv(telemetry.ENV_PROGRESS_STALE, '0.8')
        monkeypatch.setenv(telemetry.ENV_HB_STALE, '30')

        # Cadence: snapshot every ~0.6 s (≈ 8 steps at 0.08 s/step),
        # so the relaunch may replay at most one cadence window plus
        # the stall-detection tail.
        monkeypatch.setenv(checkpointd.ENV_MIN_INTERVAL, '0.3')
        monkeypatch.setenv(checkpointd.ENV_MAX_INTERVAL, '0.6')
        # External fast-tier dir (task env overrides the gang
        # launcher's host-root default): a FULL relaunch tears the
        # fake host's filesystem down with it, and this smoke proves
        # the restore, not fake-host dir lifetimes.
        ckpt_dir = tmp_path / 'ckpt-ext'

        marker = tmp_path / 'first-incarnation'
        script = tmp_path / 'workload.py'
        script.write_text(f'''
import os, sys, time
sys.path.insert(0, {json.dumps(REPO_ROOT)})
from skypilot_tpu.agent import checkpointd
from skypilot_tpu.agent import telemetry
snap = checkpointd.restore()
start = snap.step if snap is not None else 0
telemetry.emit(phase='init', resume_step=start)
relaunch = os.path.exists({json.dumps(str(marker))})
open({json.dumps(str(marker))}, 'w').close()
end = start + 12 if relaunch else 80
for i in range(start, end):
    telemetry.emit(phase='step', step=i, step_time_s=0.08)
    checkpointd.maybe_checkpoint(i, lambda: {{'step': i}},
                                 step_time_s=0.08)
    time.sleep(0.08)
checkpointd.wait_idle(5.0)
''')
        plan_file = tmp_path / 'stall-plan.json'
        plan_file.write_text(json.dumps({'points': {
            'telemetry.stall': {'match': {'rank': 0},
                                'skip_first': 45}}}))
        monkeypatch.setenv('XSKY_CHAOS_PLAN', str(plan_file))

        task = Task('ckpt-replay',
                    run=f'{sys.executable} {script}')
        task.update_envs({checkpointd.ENV_DIR: str(ckpt_dir)})
        task.set_resources(Resources(accelerators='tpu-v5e-8',
                                     use_spot=True))
        job_id = jobs_state.add_job('ckpt-replay',
                                    Task.chain_to_config([task]))
        jobs_state.set_status(job_id,
                              jobs_state.ManagedJobStatus.SUBMITTED)
        jobs_state.set_schedule_state(
            job_id, jobs_state.ScheduleState.LAUNCHING)
        jobs_state.set_controller_pid(job_id, os.getpid())
        cluster = f'xsky-jobs-{job_id}'

        def run_controller():
            try:
                controller_lib.JobsController(job_id).run()
            finally:
                jobs_scheduler.job_done(job_id)

        thread = threading.Thread(target=run_controller, daemon=True,
                                  name='xsky-ckpt-smoke-controller')
        thread.start()
        thread.join(timeout=180)
        assert not thread.is_alive(), 'controller wedged'
        record = jobs_state.get_job(job_id)
        assert record['status'] == \
            jobs_state.ManagedJobStatus.SUCCEEDED, record
        assert record['recovery_count'] >= 1

        # The relaunch restored from the fast tier, journalled under
        # the job scope the controller threaded
        # (XSKY_CKPT_SCOPE=job/<id>).
        restores = [e for e in state_lib.get_recovery_events(
            scope=f'job/{job_id}')
            if e['event_type'] == 'job.ckpt_restored']
        assert any((e['detail'] or {}).get('tier') == 'local'
                   for e in restores), restores

        result = CliRunner().invoke(cli_mod.cli,
                                    ['goodput', cluster, '--json'])
        assert result.exit_code == 0, result.output
        ledger = json.loads(result.output)
        assert len(ledger['incarnations']) >= 2, ledger
        relaunched = ledger['incarnations'][-1]
        # The restored resume point is declared — and close to the
        # banked max: replay is bounded by the checkpoint cadence
        # (~8 steps) + the stall-detection tail, nothing like the
        # 45+ banked steps a cold restart would rebuy.
        assert relaunched['resume_step'] >= 25, ledger
        assert sum(r['replayed_steps']
                   for r in ledger['incarnations']) <= 20, ledger
