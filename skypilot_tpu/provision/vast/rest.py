"""Vast.ai REST transport (urllib + bearer key, no SDK).

Role-twin of the reference's vast SDK wrapper
(sky/provision/vast/utils.py), redesigned to match this repo's
transport pattern: a thin `call()` over the v0 REST API with typed
error classification for the failover engine. The marketplace "search
offers" query is sent as the API's structured JSON operators (e.g.
{"gpu_name": {"eq": ...}}), not the SDK's string DSL.
"""
from __future__ import annotations

import json
import os
import time
import urllib.error
import urllib.request
from typing import Any, Dict, Optional

from skypilot_tpu import exceptions

API_ENDPOINT = 'https://console.vast.ai/api/v0'
CREDENTIALS_PATH = '~/.vast_api_key'
_MAX_ATTEMPTS = 4
_BACKOFF_S = 2.0


class VastApiError(Exception):

    def __init__(self, status: int, message: str) -> None:
        super().__init__(f'{status}: {message}')
        self.status = status
        self.message = message


def load_api_key() -> Optional[str]:
    """$VAST_API_KEY, else the CLI-compatible ~/.vast_api_key file."""
    key = os.environ.get('VAST_API_KEY')
    if key:
        return key
    path = os.path.expanduser(CREDENTIALS_PATH)
    if not os.path.exists(path):
        return None
    try:
        with open(path, encoding='utf-8') as f:
            return f.read().strip() or None
    except OSError:
        return None


def classify_error(e: VastApiError,
                   region: Optional[str] = None) -> Exception:
    text = e.message.lower()
    where = f' in {region}' if region else ''
    if ('no_such_ask' in text or 'no longer available' in text
            or 'already rented' in text or 'no offer' in text):
        return exceptions.CapacityError(f'Vast capacity{where}: {e}')
    if 'credit' in text or 'balance' in text:
        return exceptions.QuotaExceededError(f'Vast balance{where}: {e}')
    if e.status in (401, 403):
        return exceptions.PermissionError_(f'Vast auth: {e}')
    if e.status == 400:
        return exceptions.InvalidRequestError(f'Vast request: {e}')
    return exceptions.ProvisionError(f'Vast API{where}: {e}')


class Transport:

    def __init__(self, api_key: Optional[str] = None) -> None:
        key = api_key or load_api_key()
        if not key:
            raise exceptions.PermissionError_(
                'Vast.ai API key not found (set $VAST_API_KEY or '
                f'populate {CREDENTIALS_PATH}).')
        self._key = key

    def call(self, method: str, path: str,
             body: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
        url = f'{API_ENDPOINT}{path}'
        data = json.dumps(body).encode() if body is not None else None
        for attempt in range(_MAX_ATTEMPTS):
            req = urllib.request.Request(
                url, data=data, method=method,
                headers={'Authorization': f'Bearer {self._key}',
                         'Content-Type': 'application/json'})
            try:
                with urllib.request.urlopen(req, timeout=60) as resp:
                    return json.loads(resp.read() or b'{}')
            except urllib.error.HTTPError as e:
                if e.code in (429, 502, 503) and attempt < _MAX_ATTEMPTS - 1:
                    time.sleep(_BACKOFF_S * (attempt + 1))
                    continue
                try:
                    payload = json.loads(e.read() or b'{}')
                    msg = payload.get('msg') or payload.get(
                        'error', str(e))
                except (ValueError, AttributeError):
                    msg = str(e)
                raise VastApiError(e.code, msg) from e
            except urllib.error.URLError as e:
                raise exceptions.ProvisionError(
                    f'Vast API unreachable: {e}') from e
        raise exceptions.ProvisionError('Vast API rate limit persisted.')
