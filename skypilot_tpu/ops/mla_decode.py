"""Pallas decode kernel for MLA (compressed-latent) attention.

The absorbed MLA decode (models/deepseek.py) scores queries directly
against the compressed cache:

    scores[b,h,t] = q_eff[b,h,:]·c_kv[b,t,:] + q_rope[b,h,:]·k_rope[b,t,:]
    out_c[b,h,:]  = softmax(scores)·c_kv[b,:,:]

The XLA path reads every slot's whole padded cache each step; like the
dense decode kernel (ops/decode_attention.py) this kernel bounds reads
per slot by its true length via scalar-prefetched lengths — past-the-
end blocks clamp to the last live block so Mosaic elides their DMAs,
and compute is @pl.when-gated on the same predicate.

The rank-side matmuls (q_eff = q_nope·W_uk before, out = out_c·W_uv
after) stay OUTSIDE the kernel: they are dense batched matmuls XLA
already tiles onto the MXU, and keeping them out keeps kernel VMEM to
one [H, r] accumulator.

Numerics follow the flash kernels (online softmax, fp32 accumulators);
tests pin equality against the masked XLA reference.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# Shared with the dense decode kernel: one source of truth for the
# backend switch, block size, and the last-live-block clamp.
from skypilot_tpu.ops.decode_attention import _LANES
from skypilot_tpu.ops.decode_attention import _last_block
from skypilot_tpu.ops.decode_attention import _NEG_INF
from skypilot_tpu.ops.decode_attention import _should_interpret
from skypilot_tpu.ops.decode_attention import DEFAULT_BLOCK_KV


def _mla_decode_kernel(lengths_ref, q_eff_ref, q_rope_ref, ckv_ref,
                       krope_ref, o_ref, acc_ref, m_ref, l_ref, *,
                       scale: float, block_kv: int):
    b = pl.program_id(0)
    ki = pl.program_id(1)
    num_ki = pl.num_programs(1)

    @pl.when(ki == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    length = lengths_ref[b]
    last = _last_block(length, block_kv)
    blk = jnp.minimum(ki, last)
    kv_start = blk * block_kv

    @pl.when(ki <= last)
    def _body():
        q_eff = q_eff_ref[0].astype(jnp.float32)       # [H, r]
        q_rope = q_rope_ref[0].astype(jnp.float32)     # [H, dr]
        ckv = ckv_ref[0].astype(jnp.float32)           # [bkv, r]
        krope = krope_ref[0].astype(jnp.float32)       # [bkv, dr]
        s = (jax.lax.dot_general(
                q_eff, ckv, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32) +
             jax.lax.dot_general(
                q_rope, krope, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)) * scale  # [H, bkv]
        pos = kv_start + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        s = jnp.where(pos < length, s, _NEG_INF)

        m_prev = m_ref[:, 0:1]
        m_cur = jnp.max(s, axis=1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)
        l_new = l_ref[:, 0:1] * alpha + jnp.sum(p, axis=1, keepdims=True)
        pv = jax.lax.dot_general(
            p, ckv, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)          # [H, r]
        acc_ref[:] = acc_ref[:] * alpha + pv
        m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[:] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(ki == num_ki - 1)
    def _finalize():
        l = l_ref[:, 0:1]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = (acc_ref[:] / l_safe).astype(o_ref.dtype)


def mla_decode_attention(q_eff: jax.Array, q_rope: jax.Array,
                         ckv_cache: jax.Array, krope_cache: jax.Array,
                         lengths: jax.Array, scale: float,
                         block_kv: int = DEFAULT_BLOCK_KV) -> jax.Array:
    """Length-bounded absorbed-MLA decode → out_c [B, H, r] (fp32).

    q_eff: [B, H, r] (q_nope already absorbed through W_uk);
    q_rope: [B, H, dr]; ckv_cache: [B, K, r]; krope_cache: [B, K, dr];
    lengths: [B] live rows per slot (the step's own entry already
    written at lengths[b]-1). The caller applies W_uv afterwards.
    """
    b, h, r = q_eff.shape
    dr = q_rope.shape[-1]
    max_len = ckv_cache.shape[1]
    block_kv = min(block_kv, max_len)
    if max_len % block_kv != 0:
        raise ValueError(f'max_len {max_len} % block_kv {block_kv} != 0')
    num_blocks = max_len // block_kv
    # Same clamp as decode_attention: lengths past the cache cap must
    # not index an out-of-range KV block.
    lengths = jnp.minimum(lengths.astype(jnp.int32), max_len)

    def q_map(bi, ki, lens):
        del ki, lens
        return (bi, 0, 0)

    def kv_map(bi, ki, lens):
        return (bi, jnp.minimum(ki, _last_block(lens[bi], block_kv)), 0)

    kernel = functools.partial(_mla_decode_kernel, scale=scale,
                               block_kv=block_kv)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(b, num_blocks),
        in_specs=[
            pl.BlockSpec((1, h, r), q_map),
            pl.BlockSpec((1, h, dr), q_map),
            pl.BlockSpec((1, block_kv, r), kv_map),
            pl.BlockSpec((1, block_kv, dr), kv_map),
        ],
        out_specs=pl.BlockSpec((1, h, r), q_map),
        scratch_shapes=[
            pltpu.VMEM((h, r), jnp.float32),
            pltpu.VMEM((h, _LANES), jnp.float32),
            pltpu.VMEM((h, _LANES), jnp.float32),
        ],
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, h, r), jnp.float32),
        interpret=_should_interpret(),
    )(lengths, q_eff, q_rope, ckv_cache, krope_cache)


def _paged_mla_adapter(lengths_ref, tables_ref, *refs, **kwargs):
    """The block table rides scalar prefetch for the index maps only —
    the kernel body is the dense MLA one (positions are LOGICAL block
    offsets either way)."""
    del tables_ref
    _mla_decode_kernel(lengths_ref, *refs, **kwargs)


def paged_mla_decode_attention(q_eff: jax.Array, q_rope: jax.Array,
                               ckv_pages: jax.Array,
                               krope_pages: jax.Array,
                               lengths: jax.Array,
                               block_tables: jax.Array,
                               scale: float) -> jax.Array:
    """Absorbed-MLA decode over the PAGED compressed cache.

    ckv_pages: [P, page_size, r]; krope_pages: [P, page_size, dr]
    shared page arenas; block_tables: [B, nblk] physical page per
    logical KV block (entries >= P are unallocated sentinels, clamped
    here — live slots' lengths bound never reaches one). Same kernel
    body as the dense path; the only paged delta is the K/V index map
    routing logical blocks through the block table.
    """
    b, h, r = q_eff.shape
    dr = q_rope.shape[-1]
    num_pages, page = ckv_pages.shape[0], ckv_pages.shape[1]
    nblk = block_tables.shape[1]
    lengths = jnp.minimum(lengths.astype(jnp.int32), nblk * page)
    tables = jnp.clip(block_tables, 0, num_pages - 1).astype(jnp.int32)

    def q_map(bi, ki, lens, tbl):
        del ki, lens, tbl
        return (bi, 0, 0)

    def kv_map(bi, ki, lens, tbl):
        blk = jnp.minimum(ki, _last_block(lens[bi], page))
        return (tbl[bi, blk], 0, 0)

    kernel = functools.partial(_paged_mla_adapter, scale=scale,
                               block_kv=page)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b, nblk),
        in_specs=[
            pl.BlockSpec((1, h, r), q_map),
            pl.BlockSpec((1, h, dr), q_map),
            pl.BlockSpec((1, page, r), kv_map),
            pl.BlockSpec((1, page, dr), kv_map),
        ],
        out_specs=pl.BlockSpec((1, h, r), q_map),
        scratch_shapes=[
            pltpu.VMEM((h, r), jnp.float32),
            pltpu.VMEM((h, _LANES), jnp.float32),
            pltpu.VMEM((h, _LANES), jnp.float32),
        ],
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, h, r), jnp.float32),
        interpret=_should_interpret(),
    )(lengths, tables, q_eff, q_rope, ckv_pages, krope_pages)
