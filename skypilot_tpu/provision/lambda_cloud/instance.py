"""Lambda Cloud provisioner op-set.

Behavioral twin of sky/provision/lambda_cloud/instance.py with one
structural change: Lambda instances carry no tags, and the reference
tracks cluster membership in a local metadata file (lambda_utils.py
Metadata — explicitly not thread safe). Here membership rides the
instance NAME (`<cluster>-<index>`), which the API stores server-side:
any process can reconstruct the cluster from a plain list_instances, so
status reconciliation works from a cold start with no local files.

Platform facts encoded below: no stop/resume (terminate-only), no
zones (regions are flat — the pseudo-zone equals the region), all
ports open by default (open_ports is a no-op), one public IP per
instance.
"""
from __future__ import annotations

import time
from typing import Any, Dict, List, Optional

from skypilot_tpu import exceptions
from skypilot_tpu import sky_logging
from skypilot_tpu.provision import common
from skypilot_tpu.provision.lambda_cloud import rest

logger = sky_logging.init_logger(__name__)

_transport_factory = rest.Transport


def set_transport_factory(factory) -> None:
    global _transport_factory
    _transport_factory = factory


def _transport(provider_config: Dict[str, Any]) -> Any:
    del provider_config
    return _transport_factory()


_STATE_MAP = {
    'booting': 'PENDING',
    'active': 'RUNNING',
    'unhealthy': 'PENDING',
    'terminating': None,
    'terminated': None,
}

_SSH_KEY_NAME = 'xsky-key'


def _instance_name(cluster_name: str, index: int) -> str:
    return f'{cluster_name}-{index}'


def _cluster_instances(t, cluster_name: str) -> List[Dict[str, Any]]:
    out = []
    for inst in t.call('GET', '/instances').get('data', []):
        name = inst.get('name') or ''
        prefix, _, idx = name.rpartition('-')
        if prefix == cluster_name and idx.isdigit():
            out.append(inst)
    return sorted(out, key=lambda i: int(i['name'].rsplit('-', 1)[1]))


def _ensure_ssh_key(t) -> str:
    """Register our public key once; Lambda injects it at boot."""
    import os
    from skypilot_tpu import authentication
    keys = t.call('GET', '/ssh-keys').get('data', [])
    if any(k.get('name') == _SSH_KEY_NAME for k in keys):
        return _SSH_KEY_NAME
    _, public_key_path = authentication.get_or_generate_keys()
    with open(os.path.expanduser(public_key_path),
              encoding='utf-8') as f:
        public_key = f.read().strip()
    t.call('POST', '/ssh-keys',
           {'name': _SSH_KEY_NAME, 'public_key': public_key})
    return _SSH_KEY_NAME


def run_instances(region: str, zone: Optional[str], cluster_name: str,
                  config: common.ProvisionConfig) -> common.ProvisionRecord:
    del zone  # flat regions
    t = _transport(config.provider_config)
    node_cfg = config.node_config
    try:
        existing = _cluster_instances(t, cluster_name)
        # Fill index GAPS, not just the tail: if node 1 of {0,1,2} died
        # out-of-band, relaunching must recreate `<cluster>-1`, not a
        # duplicate `<cluster>-2`.
        taken = {int(i['name'].rsplit('-', 1)[1]) for i in existing}
        missing_indices = sorted(set(range(config.count)) - taken)
        created: List[str] = []
        if missing_indices:
            key_name = _ensure_ssh_key(t)
            for node in missing_indices:
                reply = t.call('POST', '/instance-operations/launch', {
                    'region_name': region,
                    'instance_type_name': node_cfg['instance_type'],
                    'ssh_key_names': [key_name],
                    'quantity': 1,
                    'name': _instance_name(cluster_name, node),
                })
                ids = reply.get('data', {}).get('instance_ids', [])
                if not ids:
                    raise exceptions.CapacityError(
                        f'Lambda launch returned no instance in {region}.')
                created.extend(ids)
    except rest.LambdaApiError as e:
        raise rest.classify_error(e, region) from e
    head = None
    for inst in _cluster_instances(t, cluster_name):
        if inst['name'].endswith('-0'):
            head = inst['id']
    return common.ProvisionRecord(
        provider_name='lambda_cloud', cluster_name=cluster_name, region=region,
        zone=None, resumed_instance_ids=[], created_instance_ids=created,
        head_instance_id=head)


def wait_instances(region: str, cluster_name: str, state: str,
                   provider_config: Optional[Dict[str, Any]] = None,
                   timeout_s: float = 900.0,
                   poll_interval_s: float = 5.0) -> None:
    del region
    t = _transport(provider_config or {})
    deadline = time.time() + timeout_s
    while time.time() < deadline:
        instances = _cluster_instances(t, cluster_name)
        states = [_STATE_MAP.get(i.get('status', ''), 'PENDING')
                  for i in instances]
        if any(s is None for s in states):
            raise exceptions.CapacityError(
                f'Instance(s) of {cluster_name!r} terminated while '
                f'waiting for {state}.')
        if instances and all(s == state for s in states):
            return
        time.sleep(poll_interval_s)
    raise exceptions.ProvisionError(
        f'Cluster {cluster_name!r} did not reach {state} within '
        f'{timeout_s}s.')


def stop_instances(cluster_name: str,
                   provider_config: Dict[str, Any]) -> None:
    raise exceptions.NotSupportedError(
        'Lambda Cloud instances cannot stop; terminate instead '
        '(`xsky down`).')


def terminate_instances(cluster_name: str,
                        provider_config: Dict[str, Any]) -> None:
    t = _transport(provider_config)
    ids = [i['id'] for i in _cluster_instances(t, cluster_name)]
    if not ids:
        return
    try:
        t.call('POST', '/instance-operations/terminate',
               {'instance_ids': ids})
    except rest.LambdaApiError as e:
        raise rest.classify_error(e) from e


def query_instances(cluster_name: str, provider_config: Dict[str, Any]
                    ) -> Dict[str, Optional[str]]:
    t = _transport(provider_config)
    return {i['id']: _STATE_MAP.get(i.get('status', ''), 'PENDING')
            for i in _cluster_instances(t, cluster_name)}


def get_cluster_info(region: str, cluster_name: str,
                     provider_config: Dict[str, Any]
                     ) -> common.ClusterInfo:
    t = _transport(provider_config)
    instances: Dict[str, common.InstanceInfo] = {}
    head_id = None
    for inst in _cluster_instances(t, cluster_name):
        index = int(inst['name'].rsplit('-', 1)[1])
        state = _STATE_MAP.get(inst.get('status', ''), 'PENDING')
        info = common.InstanceInfo(
            instance_id=inst['id'],
            internal_ip=inst.get('private_ip') or inst.get('ip', ''),
            external_ip=inst.get('ip'),
            status=state or 'TERMINATED',
            tags={'cluster': cluster_name, 'node_index': str(index)},
            slice_id=inst['id'],
            host_index=0,
        )
        instances[inst['id']] = info
        if index == 0:
            head_id = inst['id']
    return common.ClusterInfo(
        instances=instances, head_instance_id=head_id,
        provider_name='lambda_cloud',
        provider_config=dict(provider_config or {}),
        ssh_user='ubuntu')


def open_ports(cluster_name: str, ports: List[str],
               provider_config: Dict[str, Any]) -> None:
    # Lambda instances expose all ports on their public IP; nothing to do.
    del cluster_name, ports, provider_config


def cleanup_ports(cluster_name: str,
                  provider_config: Dict[str, Any]) -> None:
    del cluster_name, provider_config
