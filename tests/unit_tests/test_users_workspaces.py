"""Users / RBAC / workspaces tests (state + live API server)."""
import base64
import json
import urllib.error
import urllib.request

import pytest

from skypilot_tpu import state
from skypilot_tpu.server import app as server_app
from skypilot_tpu.server import requests_db
from skypilot_tpu.users import core as users_core
from skypilot_tpu.users import rbac
from skypilot_tpu.workspaces import core as workspaces_core


@pytest.fixture
def clean_state(tmp_path, monkeypatch):
    monkeypatch.setenv('XSKY_STATE_DB', str(tmp_path / 'state.db'))
    state.reset_for_test()
    yield
    state.reset_for_test()


class TestUsers:

    def test_create_verify_roundtrip(self, clean_state):
        users_core.create_user('alice', 'hunter2', role='admin')
        assert users_core.verify_password('alice', 'hunter2') is not None
        assert users_core.verify_password('alice', 'wrong') is None
        assert users_core.verify_password('bob', 'hunter2') is None
        users = users_core.list_users()
        assert [u['name'] for u in users] == ['alice']
        assert users[0]['role'] == 'admin'
        # Password hash is salted PBKDF2, not the raw password.
        raw = state.get_user('alice')
        assert 'hunter2' not in raw['password_hash']

    def test_role_management(self, clean_state):
        users_core.create_user('bob', 'pw')
        assert users_core.set_role('bob', 'admin')['updated']
        assert state.get_user('bob')['role'] == 'admin'
        with pytest.raises(ValueError):
            users_core.set_role('bob', 'superroot')
        assert users_core.delete_user('bob')['deleted']
        assert users_core.list_users() == []

    def test_basic_auth_parsing(self, clean_state):
        users_core.create_user('carol', 's3cret')
        header = 'Basic ' + base64.b64encode(b'carol:s3cret').decode()
        assert users_core.authenticate_basic(header)['name'] == 'carol'
        assert users_core.authenticate_basic('Basic !!!') is None
        assert users_core.authenticate_basic(None) is None

    def test_rbac_rules(self):
        assert rbac.check_permission('admin', 'users.create')
        assert not rbac.check_permission('user', 'users.create')
        assert not rbac.check_permission('user', 'workspaces.delete')
        assert rbac.check_permission('user', 'launch')
        assert rbac.check_permission('user', 'status')


class TestWorkspaces:

    def test_create_list_delete(self, clean_state):
        assert workspaces_core.get_workspaces() == ['default']
        workspaces_core.create_workspace('team-a')
        assert 'team-a' in workspaces_core.get_workspaces()
        with pytest.raises(ValueError):
            workspaces_core.create_workspace('Bad Name!')
        with pytest.raises(ValueError):
            workspaces_core.delete_workspace('default')
        assert workspaces_core.delete_workspace('team-a')['deleted']

    def test_delete_refuses_with_clusters(self, clean_state):
        workspaces_core.create_workspace('team-b')
        state.add_or_update_cluster('c1', {'h': 1}, workspace='team-b')
        with pytest.raises(ValueError, match='cluster'):
            workspaces_core.delete_workspace('team-b')
        state.remove_cluster('c1', terminate=True)
        assert workspaces_core.delete_workspace('team-b')['deleted']

    def test_cluster_workspace_filter(self, clean_state):
        state.add_or_update_cluster('c1', {'h': 1}, workspace='default')
        state.add_or_update_cluster('c2', {'h': 2}, workspace='ws2')
        assert len(state.get_clusters()) == 2
        assert [c['name'] for c in state.get_clusters('ws2')] == ['c2']
        assert state.get_cluster_from_name('c2')['workspace'] == 'ws2'


@pytest.fixture
def auth_server(clean_state, monkeypatch, tmp_path):
    monkeypatch.setenv('XSKY_SERVER_DB', str(tmp_path / 'requests.db'))
    monkeypatch.setenv('XSKY_REQUIRE_AUTH', '1')
    requests_db.reset_for_test()
    users_core.create_user('root', 'rootpw', role='admin')
    users_core.create_user('dev', 'devpw', role='user')
    server, port = server_app.run_in_thread()
    yield f'http://127.0.0.1:{port}'
    server.shutdown()
    requests_db.reset_for_test()


def _post(url, verb, body=None, user=None, password=None):
    data = json.dumps(body or {}).encode()
    req = urllib.request.Request(f'{url}/api/{verb}', data=data,
                                 method='POST')
    if user is not None:
        token = base64.b64encode(f'{user}:{password}'.encode()).decode()
        req.add_header('Authorization', f'Basic {token}')
    with urllib.request.urlopen(req) as resp:
        return resp.status, json.loads(resp.read())


class TestServerAuth:

    def test_unauthenticated_rejected(self, auth_server):
        with pytest.raises(urllib.error.HTTPError) as e:
            _post(auth_server, 'status')
        assert e.value.code == 401

    def test_wrong_password_rejected(self, auth_server):
        with pytest.raises(urllib.error.HTTPError) as e:
            _post(auth_server, 'status', user='dev', password='nope')
        assert e.value.code == 401

    def test_user_role_blocked_from_admin_verbs(self, auth_server):
        with pytest.raises(urllib.error.HTTPError) as e:
            _post(auth_server, 'users.create',
                  {'name': 'x', 'password': 'y'},
                  user='dev', password='devpw')
        assert e.value.code == 403

    def test_admin_can_manage_users_and_workspaces(self, auth_server):
        code, payload = _post(auth_server, 'users.create',
                              {'name': 'newbie', 'password': 'pw'},
                              user='root', password='rootpw')
        assert code == 200 and 'request_id' in payload
        code, payload = _post(auth_server, 'workspaces.create',
                              {'name': 'team-x'},
                              user='root', password='rootpw')
        assert code == 200

    def test_user_can_run_normal_verbs(self, auth_server):
        code, payload = _post(auth_server, 'status', user='dev',
                              password='devpw')
        assert code == 200 and 'request_id' in payload


def _post_bearer(url, verb, body=None, token=None):
    data = json.dumps(body or {}).encode()
    req = urllib.request.Request(f'{url}/api/{verb}', data=data,
                                 method='POST')
    if token is not None:
        req.add_header('Authorization', f'Bearer {token}')
    with urllib.request.urlopen(req) as resp:
        return resp.status, json.loads(resp.read())


class TestBearerTokens:
    """Token auth (VERDICT r2 missing #6 — twin of the reference's
    OAuth/service-account token middlewares)."""

    def test_mint_verify_revoke(self, clean_state):
        users_core.create_user('alice', 'pw', role='admin')
        record = users_core.create_token('alice', 'laptop')
        token = record['token']
        assert token.startswith('xsky_')
        # Plaintext never lands in the DB.
        assert not any(token in str(t)
                       for t in state.list_api_tokens())
        user = users_core.authenticate_bearer(f'Bearer {token}')
        assert user is not None and user['name'] == 'alice'
        assert users_core.authenticate_bearer('Bearer xsky_nope') is None
        # Duplicate labels are revocation hazards → rejected.
        with pytest.raises(ValueError):
            users_core.create_token('alice', 'laptop')
        users_core.revoke_token('alice', 'laptop')
        assert users_core.authenticate_bearer(f'Bearer {token}') is None

    def test_token_dies_with_user(self, clean_state):
        users_core.create_user('bob', 'pw')
        token = users_core.create_token('bob')['token']
        assert users_core.authenticate_bearer(f'Bearer {token}')
        users_core.delete_user('bob')
        assert users_core.authenticate_bearer(f'Bearer {token}') is None
        assert state.list_api_tokens('bob') == []

    def test_server_accepts_bearer(self, auth_server):
        token = users_core.create_token('dev', 'ci')['token']
        code, payload = _post_bearer(auth_server, 'status', token=token)
        assert code == 200 and 'request_id' in payload
        # Role still applies: dev's token cannot mint tokens.
        with pytest.raises(urllib.error.HTTPError) as e:
            _post_bearer(auth_server, 'users.token_create',
                         {'name': 'dev'}, token=token)
        assert e.value.code == 403
        with pytest.raises(urllib.error.HTTPError) as e:
            _post_bearer(auth_server, 'status', token='xsky_garbage')
        assert e.value.code == 401

    def test_admin_token_verbs_over_wire(self, auth_server):
        code, payload = _post(auth_server, 'users.token_create',
                              {'name': 'root', 'label': 'ci'},
                              user='root', password='rootpw')
        assert code == 200


class TestServerAuthRegressions:

    def test_introspection_routes_require_auth(self, auth_server):
        # /api/requests and /api/get must not leak without credentials.
        for path in ('/api/requests', '/api/get?request_id=x'):
            with pytest.raises(urllib.error.HTTPError) as e:
                urllib.request.urlopen(f'{auth_server}{path}')
            assert e.value.code == 401, path
        req = urllib.request.Request(
            f'{auth_server}/api/requests/cancel',
            data=b'{"request_id": "x"}', method='POST')
        with pytest.raises(urllib.error.HTTPError) as e:
            urllib.request.urlopen(req)
        assert e.value.code == 401

    def test_set_role_not_clobbered_by_caller_role(self, auth_server,
                                                   clean_state):
        """Admin demoting a user must not be overridden by the admin's
        own role leaking into the body."""
        import time
        users_core.create_user('eve', 'pw', role='admin')
        code, payload = _post(auth_server, 'users.set_role',
                              {'name': 'eve', 'role': 'user'},
                              user='root', password='rootpw')
        assert code == 200
        # Wait for the async request to finish.
        deadline = time.time() + 20
        while time.time() < deadline:
            if state.get_user('eve')['role'] == 'user':
                break
            time.sleep(0.1)
        assert state.get_user('eve')['role'] == 'user'


class TestWorkspaceRegressions:

    def test_create_user_upsert_updates_role(self, clean_state):
        users_core.create_user('sam', 'pw1', role='user')
        users_core.create_user('sam', 'pw2', role='admin')
        assert state.get_user('sam')['role'] == 'admin'
        assert users_core.verify_password('sam', 'pw2') is not None

    def test_relaunch_moves_workspace(self, clean_state):
        state.add_or_update_cluster('c1', {'h': 1}, workspace='a')
        state.add_or_update_cluster('c1', {'h': 1}, workspace='b')
        assert state.get_cluster_from_name('c1')['workspace'] == 'b'

    def test_status_honors_pinned_workspace(self, clean_state,
                                            monkeypatch):
        from skypilot_tpu import core
        state.add_or_update_cluster('c1', {'h': 1}, workspace='default')
        state.add_or_update_cluster('c2', {'h': 2}, workspace='ws9')
        monkeypatch.setenv('XSKY_WORKSPACE', 'ws9')
        assert [c['name'] for c in core.status()] == ['c2']
        monkeypatch.delenv('XSKY_WORKSPACE')
        assert len(core.status()) == 2

    def test_remote_client_sends_bearer_token(self, auth_server):
        """The CLIENT side of token auth: RemoteClient attaches the
        Authorization header (explicit arg or $XSKY_API_TOKEN), so
        every SDK verb works against an auth-gated server."""
        pytest.importorskip('httpx')
        from skypilot_tpu.client import remote_client
        token = users_core.create_token('dev', 'sdk')['token']
        client = remote_client.RemoteClient(auth_server, token=token)
        assert client.status() == []
        # Without a token the same verb is rejected.
        bare = remote_client.RemoteClient(auth_server)
        with pytest.raises(Exception):
            bare.status()

    def test_remote_client_token_from_env(self, auth_server,
                                          monkeypatch):
        pytest.importorskip('httpx')
        from skypilot_tpu.client import remote_client
        token = users_core.create_token('dev', 'env')['token']
        monkeypatch.setenv('XSKY_API_TOKEN', token)
        client = remote_client.RemoteClient(auth_server)
        assert client.status() == []
