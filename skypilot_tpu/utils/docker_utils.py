"""Per-task container runtime on provisioned VMs (``image_id: docker:…``).

Behavioral twin of sky/provision/docker_utils.py:1-469, redesigned for
this repo's agent architecture: the reference initializes docker over
SSH and re-homes its whole runtime inside the container; here the host
keeps the agent/runtime (wheel venv, job queue, log watch) and only the
TASK'S setup/run commands execute inside the container via
``docker exec``. That keeps one runtime path for all image types — the
container is a task sandbox, not a second runtime to bootstrap.

Layout contract:
  * The container mounts the host ``$HOME`` at the same path and uses
    it as its working directory, so workdir rsyncs, file_mounts and
    setup artifacts (venvs under ``sky_workdir``) are shared verbatim.
  * ``--net=host`` — ports behave exactly like host execution (serve
    endpoints, jax.distributed coordinator).
  * ``--privileged`` — TPU/GPU device access (``/dev/accel*``,
    ``/dev/nvidia*``) without per-device flags.
  * Env forwarding rides ``docker exec -e KEY`` (no value): the gang
    launcher exports per-host values on the host, docker copies them
    into the container, so per-rank TPU_WORKER_ID / coordinator env
    arrives untouched.
"""
from __future__ import annotations

import shlex
from typing import Iterable, Optional

DOCKER_IMAGE_PREFIX = 'docker:'
CONTAINER_NAME = 'xsky-container'


def is_docker_image(image_id: Optional[str]) -> bool:
    return bool(image_id) and image_id.startswith(DOCKER_IMAGE_PREFIX)


def image_of(image_id: str) -> str:
    """'docker:ubuntu:22.04' → 'ubuntu:22.04'."""
    return image_id[len(DOCKER_IMAGE_PREFIX):]


def initialize_command(image: str,
                       container: str = CONTAINER_NAME) -> str:
    """Idempotent host-side init: install docker if absent, pull the
    image, (re)start the keep-alive container. Safe to re-run on every
    launch — an existing container with the right image is reused; an
    image change recreates it (rolling a new task version onto a live
    cluster)."""
    image_q = shlex.quote(image)
    c = shlex.quote(container)
    return ' && '.join([
        # Docker install (Debian/Ubuntu hosts; get.docker.com handles
        # distro detection). sudo -n: non-interactive like every other
        # runtime-setup command.
        ('command -v docker >/dev/null 2>&1 || '
         '(curl -fsSL https://get.docker.com | sudo -n sh)'),
        ('sudo -n usermod -aG docker $USER 2>/dev/null || true'),
        f'sudo -n docker pull {image_q}',
        # Recreate on image drift; keep a matching live container.
        (f'if [ "$(sudo -n docker inspect -f '
         f"'{{{{.Config.Image}}}}' {c} 2>/dev/null)\" != {image_q} ]; "
         f'then sudo -n docker rm -f {c} 2>/dev/null || true; fi'),
        # Running → keep; exited (VM reboot, dockerd restart — no
        # --restart policy) → start it; absent → create. A plain
        # `docker run --name` against an Exited container would fail
        # with a name conflict on every relaunch.
        (f'sudo -n docker ps -q -f name=^{container}$ | grep -q . || '
         f'{{ sudo -n docker ps -aq -f name=^{container}$ | grep -q . '
         f'&& sudo -n docker start {c}; }} || '
         f'sudo -n docker run -d --name {c} --net=host --privileged '
         f'-v "$HOME:$HOME" -w "$HOME" {image_q} '
         f'sh -c "sleep infinity"'),
    ])


def exec_wrap(cmd: str, env_keys: Iterable[str],
              cwd: Optional[str] = None,
              container: str = CONTAINER_NAME) -> str:
    """Wrap a task command to run inside the container.

    Env is forwarded as ``-e KEY="$KEY"`` — the HOST shell expands the
    per-host exported value before sudo runs, because sudo's default
    env_reset would strip exported variables and a bare ``-e KEY``
    would then forward nothing. One wrapped command string serves
    every rank (each host expands its own values).
    """
    flags = ' '.join(f'-e {k}="${{{k}}}"'
                     for k in sorted(set(env_keys))
                     if k.isidentifier())
    inner = cmd if cwd is None else f'cd {shlex.quote(cwd)} && {cmd}'
    return (f'sudo -n docker exec {flags} {shlex.quote(container)} '
            f'bash -c {shlex.quote(inner)}')
