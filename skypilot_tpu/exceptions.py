"""Exception hierarchy for skypilot_tpu.

Twin of the reference's ``sky/exceptions.py`` (ResourcesUnavailableError /
failover family), redesigned around TPU provisioning semantics: capacity
stockouts, queued-resource timeouts and slice-health failures are first-class.

All exceptions are picklable so they can cross the client/API-server boundary
(reference: sky/exceptions.py serializes exceptions for the request DB).
"""
from __future__ import annotations

from typing import List, Optional


class SkyTpuError(Exception):
    """Base class for all framework errors."""


# --- Resource resolution / optimizer ---------------------------------------


class ResourcesUnavailableError(SkyTpuError):
    """No cloud/zone can currently satisfy the resource request.

    Carries ``failover_history`` so the failover engine (backends/failover.py)
    and managed-jobs recovery can inspect what was already tried.
    """

    def __init__(self, message: str = '',
                 no_failover: bool = False,
                 failover_history: Optional[List[Exception]] = None) -> None:
        super().__init__(message)
        self.no_failover = no_failover
        self.failover_history: List[Exception] = failover_history or []

    def with_failover_history(
            self, history: List[Exception]) -> 'ResourcesUnavailableError':
        self.failover_history = history
        return self


class ResourcesMismatchError(SkyTpuError):
    """Requested resources do not match the existing cluster's resources."""


class NoCloudAccessError(SkyTpuError):
    """No cloud is enabled (credentials missing for all clouds)."""


class NotSupportedError(SkyTpuError):
    """The operation is not supported (e.g. stop on a TPU pod slice)."""


class InvalidSkyTpuConfigError(SkyTpuError):
    """Config file failed schema validation."""


class InvalidSchemaError(InvalidSkyTpuConfigError, ValueError):
    """User YAML (task or config) failed schema validation.

    Message is one actionable line per problem, naming the bad key
    (twin of the reference's jsonschema layer, sky/utils/schemas.py).
    Subclasses InvalidSkyTpuConfigError so existing config-error
    handlers catch schema failures too.
    """


# --- Provisioning / failover taxonomy --------------------------------------
# The failover engine classifies provisioning failures into these buckets to
# decide the retry scope (twin of the reference's FailoverCloudErrorHandlerV2,
# sky/backends/cloud_vm_ray_backend.py:876, re-architected as typed errors
# instead of per-cloud log-string parsing).


class ProvisionError(SkyTpuError):
    """Base class for provisioning failures; carries blocked scope."""


class CapacityError(ProvisionError):
    """Out of capacity (TPU STOCKOUT / GPU zonal exhaustion).

    Retry scope: next zone, then region, then next-cheapest SKU.
    """


class QuotaExceededError(ProvisionError):
    """Project quota exhausted: block the (cloud, region, SKU) for this run."""


class PermissionError_(ProvisionError):
    """IAM / API-not-enabled errors: block the whole cloud for this run."""


class InvalidRequestError(ProvisionError):
    """Malformed request (bad runtime version, bad topology): do not retry."""


class QueuedResourceTimeoutError(ProvisionError):
    """A TPU queued-resource request did not become ACTIVE within deadline."""


class ClusterOwnerIdentityMismatchError(SkyTpuError):
    """Cluster was created by a different cloud identity."""


# --- Cluster / job lifecycle ------------------------------------------------


class ClusterNotUpError(SkyTpuError):
    """Operation requires an UP cluster."""

    def __init__(self, message: str = '', cluster_status=None,
                 handle=None) -> None:
        super().__init__(message)
        self.cluster_status = cluster_status
        self.handle = handle


class ClusterDoesNotExist(SkyTpuError):
    """Named cluster not found in the state DB."""


class ClusterSetUpError(SkyTpuError):
    """Setup commands failed on the cluster."""


class MultiHostError(ClusterSetUpError):
    """A parallel per-host fan-out failed on one or more ranks.

    Aggregates every failed rank's error (not just the first), so a
    64-host bring-up that lost ranks 3 and 41 names both in one
    exception. Subclasses ClusterSetUpError: callers that caught the
    sequential loops' per-host setup errors keep working unchanged.

    Attributes:
        what: human-readable phase name ('task setup', 'runtime
            bootstrap', ...).
        failures: rank → the exception that rank raised.
        total: number of items the fan-out was asked to run.
        not_started: ranks never started because an earlier failure
            (or deadline expiry) aborted the phase — gang semantics.
    """

    def __init__(self, what: str, failures=None, total=None,
                 not_started=()) -> None:
        self.what = what
        self.failures = dict(failures or {})
        self.total = total if total is not None else len(self.failures)
        self.not_started = tuple(not_started)
        if failures is None and total is None:
            # Single-arg reconstruction (deserialize_exception calls
            # cls(message) when an error crosses the API-server wire):
            # keep the already-rendered message verbatim so remote
            # clients still see — and `except ClusterSetUpError` still
            # catches — the aggregated per-rank report.
            super().__init__(what)
            return
        parts = [
            f'[host {rank}] {type(err).__name__}: {err}'
            for rank, err in sorted(self.failures.items())
        ]
        msg = (f'{what} failed on {len(self.failures)}/{self.total} '
               f'host(s): ' + '; '.join(parts))
        if self.not_started:
            msg += (f' ({len(self.not_started)} host(s) not started: '
                    f'{list(self.not_started)})')
        super().__init__(msg)


class CommandError(SkyTpuError):
    """A remote command exited non-zero."""

    def __init__(self, returncode: int, command: str, error_msg: str = '',
                 detailed_reason: Optional[str] = None) -> None:
        self.returncode = returncode
        self.command = command
        self.error_msg = error_msg
        self.detailed_reason = detailed_reason
        if len(command) > 100:
            command = command[:100] + '...'
        super().__init__(
            f'Command {command} failed with return code {returncode}.'
            f' {error_msg}')


class JobExitNonZeroError(SkyTpuError):
    """User job exited with a non-zero code."""


class GangSchedulingError(SkyTpuError):
    """Not all hosts of a slice could start the job (all-or-nothing)."""


class SliceUnhealthyError(SkyTpuError):
    """TPU slice reported unhealthy (preempted host, ICI failure)."""


# --- Storage ---------------------------------------------------------------


class StorageError(SkyTpuError):
    pass


class StorageBucketCreateError(StorageError):
    pass


class StorageBucketGetError(StorageError):
    pass


class StorageBucketDeleteError(StorageError):
    pass


class StorageUploadError(StorageError):
    pass


class StorageModeError(StorageError):
    pass


class StorageNameError(StorageError):
    pass


class StorageSpecError(StorageError):
    pass


# --- Serve / jobs ----------------------------------------------------------


class ServeUserTerminatedError(SkyTpuError):
    pass


class ManagedJobReachedMaxRetriesError(SkyTpuError):
    pass


class ManagedJobStatusError(SkyTpuError):
    pass


# --- API server ------------------------------------------------------------


class ApiServerConnectionError(SkyTpuError):

    def __init__(self, server_url: str) -> None:
        super().__init__(
            f'Could not connect to API server at {server_url}. '
            'Start one with `xsky api start`.')


class RequestCancelled(SkyTpuError):
    pass


class UserRequestRejectedByPolicy(SkyTpuError):
    """Admin policy rejected the request."""


def serialize_exception(e: Exception) -> dict:
    """Serialize an exception for transport across the server boundary."""
    return {
        'type': type(e).__name__,
        'message': str(e),
        'args': [repr(a) for a in getattr(e, 'args', ())],
    }


def deserialize_exception(payload: dict) -> Exception:
    """Best-effort reconstruction of a serialized exception."""
    exc_type = payload.get('type', 'SkyTpuError')
    message = payload.get('message', '')
    cls = globals().get(exc_type, SkyTpuError)
    try:
        if isinstance(cls, type) and issubclass(cls, Exception):
            return cls(message)
    except TypeError:
        pass
    return SkyTpuError(f'{exc_type}: {message}')
