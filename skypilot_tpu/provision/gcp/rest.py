"""Minimal GCP REST transport with pluggable auth — no cloud SDK needed.

The reference wraps googleapiclient behind a lazy adaptor
(sky/adaptors/gcp.py:104). Here the surface we need (TPU v2 + Compute v1)
is small enough that a hand-rolled urllib client is simpler, fully
testable (inject a fake transport), and dependency-free.

Token sources, in order:
  1. ``GCP_ACCESS_TOKEN`` env (tests / CI);
  2. GCE/TPU-VM metadata server (when running inside GCP);
  3. ``gcloud auth print-access-token`` subprocess (developer laptops).
"""
from __future__ import annotations

import json
import subprocess
import time
import urllib.error
import urllib.parse
import urllib.request
from typing import Any, Dict, Optional

from skypilot_tpu import exceptions
from skypilot_tpu import sky_logging

logger = sky_logging.init_logger(__name__)

_METADATA_TOKEN_URL = ('http://metadata.google.internal/computeMetadata/v1/'
                       'instance/service-accounts/default/token')

_RETRYABLE_STATUS = (429, 500, 502, 503, 504)


class GcpApiError(exceptions.ProvisionError):
    """HTTP-level error from a GCP API, with parsed status/reason."""

    def __init__(self, status: int, reason: str, message: str,
                 body: Optional[Dict[str, Any]] = None) -> None:
        super().__init__(f'GCP API error {status} ({reason}): {message}')
        self.status = status
        self.reason = reason
        self.message = message
        self.body = body or {}


class TokenProvider:
    """Caches an OAuth2 access token from the first working source."""

    def __init__(self) -> None:
        self._token: Optional[str] = None
        self._expiry: float = 0.0

    def token(self) -> str:
        import os
        env = os.environ.get('GCP_ACCESS_TOKEN')
        if env:
            return env
        now = time.time()
        if self._token and now < self._expiry - 60:
            return self._token
        tok, ttl = self._fetch()
        self._token, self._expiry = tok, now + ttl
        return tok

    def _fetch(self) -> tuple:
        try:
            req = urllib.request.Request(
                _METADATA_TOKEN_URL, headers={'Metadata-Flavor': 'Google'})
            with urllib.request.urlopen(req, timeout=2) as resp:
                data = json.loads(resp.read())
                return data['access_token'], data.get('expires_in', 300)
        except (urllib.error.URLError, OSError, KeyError, ValueError):
            pass
        try:
            out = subprocess.run(['gcloud', 'auth', 'print-access-token'],
                                 capture_output=True, text=True, timeout=30)
            if out.returncode == 0 and out.stdout.strip():
                return out.stdout.strip(), 300
        except (OSError, subprocess.SubprocessError):
            pass
        raise exceptions.NoCloudAccessError(
            'No GCP credentials: set GCP_ACCESS_TOKEN, run on GCE, or '
            'install gcloud and run `gcloud auth login`.')


class Transport:
    """JSON-over-HTTP with auth header, retries, and error parsing.

    Tests subclass/replace this with a scripted fake (see
    tests/unit_tests/test_gcp_provisioner.py).
    """

    def __init__(self, token_provider: Optional[TokenProvider] = None,
                 max_retries: int = 4) -> None:
        self._tokens = token_provider or TokenProvider()
        self._max_retries = max_retries

    def request(self, method: str, url: str,
                params: Optional[Dict[str, str]] = None,
                body: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
        if params:
            url = url + '?' + urllib.parse.urlencode(params)
        payload = json.dumps(body).encode() if body is not None else None
        last_err: Optional[Exception] = None
        for attempt in range(self._max_retries + 1):
            req = urllib.request.Request(
                url, data=payload, method=method,
                headers={
                    'Authorization': f'Bearer {self._tokens.token()}',
                    'Content-Type': 'application/json',
                })
            try:
                with urllib.request.urlopen(req, timeout=60) as resp:
                    raw = resp.read()
                    return json.loads(raw) if raw else {}
            except urllib.error.HTTPError as e:
                err = _parse_http_error(e)
                if err.status in _RETRYABLE_STATUS and attempt < \
                        self._max_retries:
                    last_err = err
                    time.sleep(min(2 ** attempt, 16))
                    continue
                raise err from None
            except urllib.error.URLError as e:
                last_err = e
                if attempt < self._max_retries:
                    time.sleep(min(2 ** attempt, 16))
                    continue
                raise exceptions.ProvisionError(
                    f'GCP API unreachable: {e}') from e
        raise exceptions.ProvisionError(f'GCP API retries exhausted: '
                                        f'{last_err}')


def _parse_http_error(e: 'urllib.error.HTTPError') -> GcpApiError:
    try:
        body = json.loads(e.read())
        err = body.get('error', {})
        reason = err.get('status', '') or str(err.get('code', e.code))
        message = err.get('message', str(e))
    except (ValueError, AttributeError):
        body, reason, message = {}, str(e.code), str(e)
    return GcpApiError(e.code, reason, message, body)


def classify_error(err: GcpApiError, zone: str) -> Exception:
    """Map a GCP API error onto the failover taxonomy.

    Twin of FailoverCloudErrorHandlerV2._gcp_handler
    (sky/backends/cloud_vm_ray_backend.py:908) — but classification lives
    next to the API client instead of string-matching in the backend.
    """
    msg = err.message.lower()
    if err.status == 429 or 'resource_exhausted' in err.reason.lower() or \
            'no more capacity' in msg or 'stockout' in msg or \
            'resources required' in msg and 'unavailable' in msg or \
            'not enough resources' in msg:
        return exceptions.CapacityError(
            f'Out of capacity in {zone}: {err.message}')
    if 'quota' in msg or err.reason == 'QUOTA_EXCEEDED':
        return exceptions.QuotaExceededError(
            f'Quota exceeded in {zone}: {err.message}')
    if err.status in (401, 403):
        return exceptions.PermissionError_(
            f'Permission denied in {zone}: {err.message}')
    if err.status == 400 or err.status == 404:
        return exceptions.InvalidRequestError(
            f'Invalid request in {zone}: {err.message}')
    return exceptions.ProvisionError(f'{zone}: {err.message}')
