"""Async multi-tier checkpoint & peer-restore plane (agent side).

PR 11's goodput ledger put a number on the no-checkpoint tax:
``restart_replay`` — productive time re-bought because every relaunch
restarts from step 0 — dominates the relaunch arm's loss under the
``tools/bench_fleet.py`` chaos storm. This module is the pipeline that
drives it down, so no incarnation starts from zero:

  * **off-step-path snapshots** — the step loop pays ONLY the
    device→host transfer (the ``payload_fn`` it passes to
    :func:`maybe_checkpoint`); serialize + local write + peer
    replication + the storage-tier save all run in a named daemon
    background thread (``xsky-ckptd``), latest-snapshot-wins;

  * **auto-tuned cadence** — the Young/Daly interval
    ``sqrt(2 · δ · MTTF)`` (checkpoint exactly when the marginal
    expected replay loss since the last snapshot, ``t/MTTF`` per
    second, crosses the amortized snapshot cost ``δ/t``), with δ the
    measured on-step snapshot cost EMA and MTTF from the
    ``XSKY_CKPT_MTTF_S`` hint the jobs controller derives from the
    recovery journal (:func:`derive_mttf`), clamped to
    ``[XSKY_CKPT_MIN_INTERVAL_S, XSKY_CKPT_MAX_INTERVAL_S]``;

  * **peer-tier replication** — each rank's newest shard + manifest
    (step, incarnation, rank, sha256 digest, ts) is copied to K gang
    peers' runtime roots over the PR 3 fan-out
    (``parallelism.run_in_parallel``, phase ``ckpt_replicate``) — DCN
    neighbours, not cold storage. The gang launcher wires the dirs:
    ``XSKY_CKPT_DIR`` (own host) and ``XSKY_CKPT_PEER_DIRS`` (the K
    next hosts' roots). Peer copy currently requires the peer root to
    be filesystem-reachable (fake/local providers, shared mounts);
    an unreachable peer costs its replica, never the snapshot;

  * **tiered restore** — :func:`restore` walks local → peer manifests
    (freshest valid copy wins; torn/corrupt manifests and
    digest-mismatched shards are discarded, never raised on) → the
    storage tier (caller-provided, e.g. orbax in
    ``train/launch.py``) → cold start, journalling
    ``job.ckpt_restored`` (tier, latency, resumed step, replayed-step
    count) trace-linked under a ``jobs.ckpt_restore`` span. The
    workload then emits ``resume_step`` so the goodput ledger shrinks
    the ``restart_replay`` bucket automatically.

Chaos points ``ckpt.write``, ``ckpt.replicate``, ``ckpt.restore``
force each failure arm; ``/metrics`` counts
``xsky_ckpt_{writes,restores,bytes}_total`` and the server renders a
scrape-time ``xsky_ckpt_freshness_age_seconds`` gauge from the
``ckpt_step``/``ckpt_ts`` fields each snapshot stamps onto the rank's
telemetry sample.

Never-raise discipline throughout: the plane instruments the very
step loop whose goodput it protects — a full disk, a dead peer, or a
torn manifest must cost the snapshot or the tier, never the step.
"""
from __future__ import annotations

import hashlib
import json
import math
import os
import pickle
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

ENV_ENABLED = 'XSKY_CKPT'                 # "0" disables the plane
ENV_DIR = 'XSKY_CKPT_DIR'                 # local tier; unset ⇒ no-op
ENV_PEER_DIRS = 'XSKY_CKPT_PEER_DIRS'     # newline-separated peer dirs
ENV_REPLICAS = 'XSKY_CKPT_REPLICAS'       # K peers per shard
ENV_MIN_INTERVAL = 'XSKY_CKPT_MIN_INTERVAL_S'
ENV_MAX_INTERVAL = 'XSKY_CKPT_MAX_INTERVAL_S'
ENV_MTTF = 'XSKY_CKPT_MTTF_S'             # controller-derived hint
ENV_SCOPE = 'XSKY_CKPT_SCOPE'             # journal scope (job/<id>)
ENV_KEEP = 'XSKY_CKPT_KEEP'               # snapshots kept per dir

# Restore tiers, freshest-first. `cold` means nothing restorable was
# found anywhere — the incarnation starts from step 0.
TIER_LOCAL = 'local'
TIER_PEER = 'peer'
TIER_STORAGE = 'storage'
TIER_COLD = 'cold'

# Knobs the control plane forwards into the job spec env (the gang
# backend threads these; the per-rank dir/peer wiring stays with the
# gang launcher).
FORWARD_ENV = (ENV_ENABLED, ENV_MIN_INTERVAL, ENV_MAX_INTERVAL,
               ENV_MTTF, ENV_SCOPE, ENV_REPLICAS, ENV_KEEP)

_DEFAULT_MIN_INTERVAL_S = 15.0
_DEFAULT_MAX_INTERVAL_S = 600.0
# With no journal evidence and no hint: one failure per half hour —
# pessimistic enough that the Young interval stays well under the max
# clamp once a real snapshot cost is measured.
_DEFAULT_MTTF_S = 1800.0
_DEFAULT_REPLICAS = 1
_DEFAULT_KEEP = 2
# Snapshot-cost floor for the cadence math: a measured δ of ~0 (tiny
# payloads) must not drive the interval to zero before the min clamp.
_MIN_COST_S = 1e-3
_COST_EMA_ALPHA = 0.3

_MANIFEST_PREFIX = 'manifest-'
_SHARD_PREFIX = 'shard-'


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, default))
    except (TypeError, ValueError):
        return default


def _ema(prev: Optional[float], value: float,
         alpha: float = _COST_EMA_ALPHA) -> float:
    if prev is None:
        return float(value)
    return alpha * float(value) + (1.0 - alpha) * prev


def min_interval_s() -> float:
    return max(0.05, _env_float(ENV_MIN_INTERVAL,
                                _DEFAULT_MIN_INTERVAL_S))


def max_interval_s() -> float:
    return max(min_interval_s(),
               _env_float(ENV_MAX_INTERVAL, _DEFAULT_MAX_INTERVAL_S))


def replicas() -> int:
    return max(0, int(_env_float(ENV_REPLICAS, _DEFAULT_REPLICAS)))


def keep_snapshots() -> int:
    return max(1, int(_env_float(ENV_KEEP, _DEFAULT_KEEP)))


def mttf_s() -> float:
    """The MTTF the cadence plans against: the controller-threaded
    hint (``XSKY_CKPT_MTTF_S``, derived from the recovery journal on
    every (re)submit), or the pessimistic default."""
    return max(1.0, _env_float(ENV_MTTF, _DEFAULT_MTTF_S))


def derive_mttf(scope: str, now: Optional[float] = None) -> float:
    """Control-plane helper: MTTF for one job scope from the recovery
    journal (failures observed over the lease's lifetime). The jobs
    controller calls this on every (re)submit and threads the answer
    to the workload as ``XSKY_CKPT_MTTF_S``. NEVER raises — no
    evidence (fresh job, unreadable DB) returns the default."""
    try:
        from skypilot_tpu import state
        now = now if now is not None else time.time()
        # ONE unwindowed SQL COUNT (a row-limited read would count
        # only a journal-heavy job's newest failures against its
        # whole lease lifetime and overestimate MTTF), of one row per
        # INCIDENT: a shrink journals job.rank_stall AND
        # job.gang_shrunk for the same event, so counting both would
        # halve the MTTF and over-checkpoint by ~41%.
        failures = state.count_recovery_events(
            scope, event_types=('job.preempted', 'job.rank_stall',
                                'job.restarted'))
        lease = state.get_lease(scope)
        started = (lease or {}).get('started_at')
        if not failures or not started or now <= started:
            return _DEFAULT_MTTF_S
        return min(7 * 86400.0,
                   max(60.0, (now - started) / failures))
    except Exception:  # pylint: disable=broad-except
        return _DEFAULT_MTTF_S


class Snapshot:
    """One restore answer: the step to resume from, the deserialized
    payload (None for ``cold`` — and for ``storage`` the object the
    caller's ``storage_fn`` returned), and where it came from."""

    def __init__(self, step: int, payload: Any, tier: str,
                 latency_s: float, manifest: Optional[Dict[str, Any]]
                 = None) -> None:
        self.step = int(step)
        self.payload = payload
        self.tier = tier
        self.latency_s = latency_s
        self.manifest = manifest

    def __repr__(self) -> str:
        return (f'Snapshot(step={self.step}, tier={self.tier}, '
                f'latency_s={self.latency_s:.3f})')


class Cadence:
    """Checkpoint-interval controller: Young/Daly
    ``sqrt(2 · δ · MTTF)`` with δ the measured on-step snapshot cost
    EMA, clamped to the env window and quantized to whole steps of
    the telemetry plane's step-time EMA (replay is re-bought in whole
    steps, and a snapshot cannot fire mid-step anyway). ``due()`` is
    the step-path check — two float compares."""

    def __init__(self) -> None:
        self._cost_ema: Optional[float] = None
        self._step_ema: Optional[float] = None
        self._next = 0.0

    def observe_cost(self, cost_s: float) -> None:
        self._cost_ema = _ema(self._cost_ema, cost_s)

    def observe_step_time(self, step_time_s: float) -> None:
        if step_time_s and step_time_s > 0:
            self._step_ema = _ema(self._step_ema, step_time_s)

    def interval_s(self) -> float:
        delta = max(self._cost_ema or 0.0, _MIN_COST_S)
        optimal = math.sqrt(2.0 * delta * mttf_s())
        interval = min(max_interval_s(),
                       max(min_interval_s(), optimal))
        if self._step_ema:
            # Whole-step quantization, never below one step and never
            # above the ceiling (unless one step IS above it).
            steps = max(1, math.ceil(interval / self._step_ema))
            interval = min(max(max_interval_s(), self._step_ema),
                           steps * self._step_ema)
        return interval

    def due(self, now: Optional[float] = None) -> bool:
        now = now if now is not None else time.monotonic()
        return now >= self._next

    def arm(self, now: Optional[float] = None) -> None:
        now = now if now is not None else time.monotonic()
        self._next = now + self.interval_s()


class Checkpointer:
    """One rank's tiered snapshot pipeline + background writer."""

    def __init__(self, directory: str, rank: int = 0,
                 peer_dirs: Tuple[str, ...] = (),
                 incarnation: int = 0,
                 serializer: Callable[[Any], bytes] = pickle.dumps,
                 deserializer: Callable[[bytes], Any] = pickle.loads,
                 storage_save: Optional[Callable[[int, Any], None]]
                 = None) -> None:
        self.base_dir = os.path.expanduser(directory)
        self.rank = int(rank)
        self.peer_dirs = tuple(os.path.expanduser(p)
                               for p in peer_dirs if p)
        self.incarnation = int(incarnation)
        self.cadence = Cadence()
        self.last_step: Optional[int] = None
        self.last_storage_step: Optional[int] = None
        self._serializer = serializer
        self._deserializer = deserializer
        self._storage_save = storage_save
        self._cv = threading.Condition()
        self._pending: Optional[Tuple[int, Any]] = None
        self._busy = False
        self._stopped = False
        self._worker: Optional[threading.Thread] = None

    @classmethod
    def from_env(cls, fallback_dir: Optional[str] = None,
                 **overrides: Any) -> Optional['Checkpointer']:
        """Build from the gang-launcher env, or None when the plane is
        disabled (``XSKY_CKPT=0``) or no directory is configured."""
        if os.environ.get(ENV_ENABLED, '1') == '0':
            return None
        directory = os.environ.get(ENV_DIR) or fallback_dir
        if not directory:
            return None
        peers = tuple(
            p.strip() for p in
            (os.environ.get(ENV_PEER_DIRS) or '').splitlines()
            if p.strip())
        try:
            rank = int(os.environ.get('XSKY_HOST_RANK', '0') or 0)
        except ValueError:
            rank = 0
        try:
            incarnation = int(os.environ.get(
                'XSKY_ELASTIC_GENERATION', '0') or 0)
        except ValueError:
            incarnation = 0
        return cls(directory, rank=rank, peer_dirs=peers,
                   incarnation=incarnation, **overrides)

    # ---- write side --------------------------------------------------------

    def _rank_dir(self) -> str:
        return os.path.join(self.base_dir, f'rank-{self.rank}')

    def maybe_checkpoint_impl(self, step: int,
                              payload_fn: Callable[[], Any],
                              step_time_s: Optional[float] = None,
                              force: bool = False) -> bool:
        """The step-path half: cadence check, device→host transfer
        (``payload_fn``), enqueue. Everything else happens on the
        worker thread. Returns True when a snapshot was enqueued.
        Callers go through the module-level never-raise wrapper."""
        if step_time_s:
            self.cadence.observe_step_time(step_time_s)
        now = time.monotonic()
        if not force and not self.cadence.due(now):
            return False
        t0 = time.monotonic()
        payload = payload_fn()   # the device→host copy — the ONLY
        #                          cost the step path pays
        copy_s = time.monotonic() - t0
        self.cadence.observe_cost(copy_s)
        # The same copy is the step's `ckpt_copy` phase in the flight
        # recorder's seal (agent/flight_recorder.py).
        from skypilot_tpu.agent import flight_recorder
        flight_recorder.mark('ckpt_copy', copy_s)
        self.cadence.arm(time.monotonic())
        with self._cv:
            if self._stopped:
                return False
            self._pending = (int(step), payload)   # latest wins
            self._ensure_worker_locked()
            self._cv.notify_all()
        return True

    def _ensure_worker_locked(self) -> None:
        if self._worker is not None and self._worker.is_alive():
            return
        self._worker = threading.Thread(
            target=self._worker_loop, daemon=True,
            name=f'xsky-ckptd-{self.rank}')
        self._worker.start()

    def _worker_loop(self) -> None:
        """Serialize + write local + replicate + storage save, one
        snapshot at a time, newest-wins. Dies with the process (daemon)
        — a snapshot lost to a crash is exactly what the next-older
        manifest and the peer tier exist for."""
        while True:
            with self._cv:
                while self._pending is None and not self._stopped:
                    self._cv.wait(0.5)
                if self._pending is None and self._stopped:
                    return
                step, payload = self._pending
                self._pending = None
                self._busy = True
            try:
                self._write_snapshot(step, payload)
            except Exception:  # pylint: disable=broad-except
                pass   # a failed write costs the snapshot, never the
                #        loop — the cadence re-arms regardless
            finally:
                with self._cv:
                    self._busy = False
                    self._cv.notify_all()

    def _write_snapshot(self, step: int, payload: Any) -> None:
        from skypilot_tpu.utils import chaos
        # Chaos: an `error` rule drops this snapshot (the write arm of
        # the failure drills); `latency_s` models a slow disk.
        chaos.inject('ckpt.write', rank=self.rank, step=step)
        blob = self._serializer(payload)
        digest = hashlib.sha256(blob).hexdigest()
        rank_dir = self._rank_dir()
        os.makedirs(rank_dir, exist_ok=True)
        shard_name = f'{_SHARD_PREFIX}{step}.bin'
        manifest = {
            'step': int(step),
            'incarnation': self.incarnation,
            'rank': self.rank,
            'digest': digest,
            'shard': shard_name,
            'bytes': len(blob),
            'ts': time.time(),
        }
        _atomic_write(os.path.join(rank_dir, shard_name), blob)
        _atomic_write(
            os.path.join(rank_dir, f'{_MANIFEST_PREFIX}{step}.json'),
            json.dumps(manifest).encode())
        _prune_dir(rank_dir, keep_snapshots())
        self.last_step = int(step)
        self._account_write(manifest)
        self._replicate(blob, manifest)
        if self._storage_save is not None:
            self._storage_save(step, payload)
            self.last_storage_step = int(step)

    def _account_write(self, manifest: Dict[str, Any]) -> None:
        try:
            from skypilot_tpu.agent import telemetry
            from skypilot_tpu.utils import metrics
            metrics.inc_counter('xsky_ckpt_writes_total',
                                'Checkpoint snapshots written.', 1.0)
            metrics.inc_counter('xsky_ckpt_bytes_total',
                                'Checkpoint bytes written.',
                                float(manifest['bytes']))
            # The freshness signal rides the rank's telemetry sample:
            # the pull→record path persists ckpt_step/ckpt_ts and the
            # server renders the scrape-time freshness-age gauge.
            telemetry.emit(ckpt_step=manifest['step'],
                           ckpt_ts=manifest['ts'])
        except Exception:  # pylint: disable=broad-except
            pass

    def _replicate(self, blob: bytes,
                   manifest: Dict[str, Any]) -> None:
        """Copy the newest shard + manifest (the in-memory blob — no
        re-read of the file just written) to the K peer roots over
        the host fan-out. Peer failures (chaos, unreachable DCN path,
        full disk) cost that replica only."""
        if not self.peer_dirs:
            return
        from skypilot_tpu.utils import chaos
        from skypilot_tpu.utils import parallelism
        from skypilot_tpu.utils import tracing
        step = manifest['step']

        def _copy(peer_dir: str) -> bool:
            try:
                chaos.inject('ckpt.replicate', rank=self.rank,
                             step=step, peer=peer_dir)
                target = os.path.join(os.path.expanduser(peer_dir),
                                      f'peer-rank-{self.rank}')
                os.makedirs(target, exist_ok=True)
                _atomic_write(os.path.join(target, manifest['shard']),
                              blob)
                _atomic_write(
                    os.path.join(target,
                                 f'{_MANIFEST_PREFIX}{step}.json'),
                    json.dumps(manifest).encode())
                _prune_dir(target, keep_snapshots())
                return True
            except Exception:  # pylint: disable=broad-except
                return False

        try:
            with tracing.span('ckpt.replicate', rank=self.rank,
                              step=step, peers=len(self.peer_dirs)):
                parallelism.run_in_parallel(
                    _copy, list(self.peer_dirs),
                    phase='ckpt_replicate',
                    what='checkpoint replication')
        except Exception:  # pylint: disable=broad-except
            pass

    def wait_idle(self, timeout: Optional[float] = None) -> bool:
        """Block until the worker drained (tests, final-save barriers).
        Returns False on timeout."""
        deadline = (time.monotonic() + timeout
                    if timeout is not None else None)
        with self._cv:
            while self._pending is not None or self._busy:
                remaining = None
                if deadline is not None:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        return False
                self._cv.wait(remaining if remaining is not None
                              else 0.5)
        return True

    def stop(self) -> None:
        with self._cv:
            self._stopped = True
            self._cv.notify_all()

    # ---- restore side ------------------------------------------------------

    def restore_impl(self, storage_fn: Optional[
            Callable[[], Optional[Tuple[int, Any]]]] = None,
            storage_step_fn: Optional[Callable[[], Optional[int]]]
            = None) -> Snapshot:
        """Tier walk under the restore span (callers go through the
        module-level never-raise wrapper)."""
        from skypilot_tpu.utils import tracing
        with tracing.span('jobs.ckpt_restore', rank=self.rank,
                          incarnation=self.incarnation):
            return self._restore_ladder(storage_fn, storage_step_fn)

    def _restore_ladder(self, storage_fn,
                        storage_step_fn=None) -> Snapshot:
        """Freshest-first across local → peer (torn/corrupt manifests
        discarded) → storage → cold. ``storage_step_fn`` (cheap
        latest-step probe) lets a fresher storage tier outrank stale
        fast-tier copies. Each candidate read traverses the
        ``ckpt.restore`` chaos point so fault plans can force every
        arm."""
        from skypilot_tpu.utils import chaos
        t0 = time.monotonic()
        candidates = (self._scan_tier((self.base_dir,), TIER_LOCAL) +
                      self._scan_tier(self.peer_dirs, TIER_PEER))
        # Freshest first; at equal step the rank's OWN shard wins
        # over another rank's replica, then the local tier over a
        # peer copy (no transfer). Cross-rank restore stays allowed —
        # snapshots are gang-synchronized state, and after an elastic
        # shrink the renumbered rank's host holds the old rank's
        # shard by construction.
        candidates.sort(
            key=lambda c: (-c['manifest']['step'],
                           0 if c['manifest'].get('rank') == self.rank
                           else 1,
                           0 if c['tier'] == TIER_LOCAL else 1))
        best_seen = max((c['manifest']['step'] for c in candidates),
                        default=0)
        storage_step = None
        if storage_fn is not None and storage_step_fn is not None:
            try:
                storage_step = storage_step_fn()
            except Exception:  # pylint: disable=broad-except
                storage_step = None
        if storage_step is not None:
            best_seen = max(best_seen, int(storage_step))
        tried_storage = False
        for cand in candidates:
            manifest = cand['manifest']
            if not tried_storage and storage_step is not None and \
                    manifest['step'] < storage_step:
                # Storage holds something fresher than every
                # remaining fast-tier copy: try it now; on failure
                # keep walking the fast tiers.
                tried_storage = True
                snap = self._try_storage(storage_fn, t0, best_seen)
                if snap is not None:
                    return snap
            try:
                chaos.inject('ckpt.restore', tier=cand['tier'],
                             step=manifest['step'], rank=self.rank)
                blob = _read_verified(cand['dir'], manifest)
                if blob is None:
                    continue
                payload = self._deserializer(blob)
            except Exception:  # pylint: disable=broad-except
                continue   # corrupt shard / injected fault: next
                #            candidate (older copy, then next tier)
            snap = Snapshot(manifest['step'], payload, cand['tier'],
                            time.monotonic() - t0, manifest)
            self._account_restore(snap, best_seen)
            return snap
        if storage_fn is not None and not tried_storage:
            snap = self._try_storage(storage_fn, t0, best_seen)
            if snap is not None:
                return snap
        snap = Snapshot(0, None, TIER_COLD, time.monotonic() - t0)
        self._account_restore(snap, best_seen)
        return snap

    def _try_storage(self, storage_fn, t0: float,
                     best_seen: int) -> Optional[Snapshot]:
        from skypilot_tpu.utils import chaos
        try:
            chaos.inject('ckpt.restore', tier=TIER_STORAGE,
                         rank=self.rank)
            result = storage_fn()
            if result is None:
                return None
            step, payload = result
        except Exception:  # pylint: disable=broad-except
            return None
        snap = Snapshot(step, payload, TIER_STORAGE,
                        time.monotonic() - t0)
        self._account_restore(snap, max(best_seen, int(step)))
        return snap

    @staticmethod
    def _scan_tier(dirs, tier: str) -> List[Dict[str, Any]]:
        """Every parseable manifest under the tier's base dirs.
        Unreadable dirs and torn manifests are simply absent."""
        out: List[Dict[str, Any]] = []
        for base in dirs:
            base = os.path.expanduser(base)
            try:
                subdirs = [os.path.join(base, d)
                           for d in os.listdir(base)]
            except OSError:
                continue
            for sub in subdirs:
                try:
                    names = os.listdir(sub)
                except OSError:
                    continue
                for name in names:
                    if not (name.startswith(_MANIFEST_PREFIX) and
                            name.endswith('.json')):
                        continue
                    manifest = _parse_manifest(
                        os.path.join(sub, name))
                    if manifest is not None:
                        out.append({'tier': tier, 'dir': sub,
                                    'manifest': manifest})
        return out

    def _account_restore(self, snap: Snapshot,
                         best_seen: int) -> None:
        """Journal + count the restore (never raises): tier, latency,
        resumed step, and the replayed-step bound (the freshest step
        any manifest advertised minus what we actually resumed at)."""
        try:
            from skypilot_tpu import state
            from skypilot_tpu.utils import metrics
            metrics.inc_counter('xsky_ckpt_restores_total',
                                'Checkpoint restores, by tier.', 1.0,
                                tier=snap.tier)
            scope = os.environ.get(ENV_SCOPE) or \
                f'ckpt/rank-{self.rank}'
            state.record_recovery_event(
                'job.ckpt_restored', scope=scope, cause=snap.tier,
                latency_s=round(snap.latency_s, 6),
                detail={'tier': snap.tier, 'rank': self.rank,
                        'resume_step': snap.step,
                        'replayed_steps': max(0,
                                              best_seen - snap.step),
                        'incarnation': self.incarnation})
        except Exception:  # pylint: disable=broad-except
            pass


# ---- manifest/shard helpers -------------------------------------------------


def _atomic_write(path: str, blob: bytes) -> None:
    tmp = f'{path}.tmp.{os.getpid()}'
    with open(tmp, 'wb') as f:
        f.write(blob)
    os.replace(tmp, path)


def _parse_manifest(path: str) -> Optional[Dict[str, Any]]:
    """One manifest file → dict, or None when torn/invalid — a corrupt
    manifest is discarded evidence, never an error."""
    try:
        with open(path, 'rb') as f:
            manifest = json.loads(f.read().decode('utf-8'))
    except (OSError, ValueError):
        return None
    if not isinstance(manifest, dict):
        return None
    if not isinstance(manifest.get('step'), int) or \
            not isinstance(manifest.get('digest'), str) or \
            not isinstance(manifest.get('shard'), str):
        return None
    return manifest


def _read_verified(directory: str,
                   manifest: Dict[str, Any]) -> Optional[bytes]:
    """The shard bytes iff they match the manifest digest (a torn
    shard under a valid manifest is as discarded as a torn manifest)."""
    try:
        with open(os.path.join(directory, manifest['shard']),
                  'rb') as f:
            blob = f.read()
    except OSError:
        return None
    if hashlib.sha256(blob).hexdigest() != manifest['digest']:
        return None
    return blob


def _prune_dir(directory: str, keep: int) -> None:
    """Keep the newest ``keep`` (manifest, shard) pairs; older copies
    ARE the torn-write fallback, so never prune below 1."""
    try:
        steps = sorted(
            int(n[len(_MANIFEST_PREFIX):-len('.json')])
            for n in os.listdir(directory)
            if n.startswith(_MANIFEST_PREFIX) and n.endswith('.json')
            and n[len(_MANIFEST_PREFIX):-len('.json')].isdigit())
    except OSError:
        return
    for step in steps[:-keep] if len(steps) > keep else []:
        for name in (f'{_MANIFEST_PREFIX}{step}.json',
                     f'{_SHARD_PREFIX}{step}.bin'):
            try:
                os.remove(os.path.join(directory, name))
            except OSError:
                pass


# ---- process-wide checkpointer (mirrors telemetry's emitter) ---------------

_ckpt_lock = threading.Lock()
_checkpointer: Optional[Checkpointer] = None
_ckpt_key = None   # (dir, rank, peers) env values the cache was built from


def _current() -> Optional[Checkpointer]:
    """Resolve the process-wide checkpointer from the environment;
    rebuild when the gang wiring changed (a fresh incarnation in the
    same process). Steady state: two dict lookups + a tuple compare."""
    global _checkpointer, _ckpt_key
    if os.environ.get(ENV_ENABLED, '1') == '0':
        return None
    if _ckpt_key == '<installed>':
        # An explicitly installed pipeline (train/launch.py with its
        # storage tier wired) always wins over env resolution.
        return _checkpointer
    directory = os.environ.get(ENV_DIR)
    if not directory:
        return None
    key = (directory, os.environ.get('XSKY_HOST_RANK', '0'),
           os.environ.get(ENV_PEER_DIRS, ''))
    if key == _ckpt_key and _checkpointer is not None:
        return _checkpointer
    with _ckpt_lock:
        if key != _ckpt_key or _checkpointer is None:
            if _checkpointer is not None:
                _checkpointer.stop()
            _checkpointer = Checkpointer.from_env()
            _ckpt_key = key
        return _checkpointer


def install(checkpointer: Optional[Checkpointer]) -> None:
    """Install a custom-built checkpointer (``train/launch.py`` wires
    its storage tier in) as the process-wide one."""
    global _checkpointer, _ckpt_key
    with _ckpt_lock:
        if _checkpointer is not None and \
                _checkpointer is not checkpointer:
            _checkpointer.stop()
        _checkpointer = checkpointer
        _ckpt_key = '<installed>' if checkpointer is not None else None


def reset_for_test() -> None:
    install(None)


def enabled() -> bool:
    return _current() is not None


# ---- never-raise entry points (the xskylint contract map names these) ------


def maybe_checkpoint(step: int, payload_fn: Callable[[], Any],
                     step_time_s: Optional[float] = None,
                     force: bool = False) -> bool:
    """Snapshot this rank's state if the cadence says so. NEVER raises
    and with the plane disabled (``XSKY_CKPT=0`` / no dir) returns
    after one env lookup — safe on any step loop. The step path pays
    only the cadence check and ``payload_fn`` (the device→host copy);
    serialize/write/replicate/storage ride the ``xsky-ckptd`` worker.
    """
    try:
        ckpt = _current()
        if ckpt is None:
            return False
        return ckpt.maybe_checkpoint_impl(step, payload_fn,
                                          step_time_s=step_time_s,
                                          force=force)
    except Exception:  # pylint: disable=broad-except
        return False


def restore(storage_fn: Optional[
        Callable[[], Optional[Tuple[int, Any]]]] = None,
        storage_step_fn: Optional[Callable[[], Optional[int]]] = None
        ) -> Optional[Snapshot]:
    """Restore the freshest valid snapshot: local → peer → storage →
    cold (a :class:`Snapshot` with ``tier='cold'``, step 0).
    ``storage_step_fn`` is a cheap latest-step probe that lets a
    fresher storage tier outrank stale fast-tier copies. NEVER
    raises; None only when the plane is disabled entirely."""
    fallback = None
    try:
        ckpt = _current()
        if ckpt is None:
            return fallback
        return ckpt.restore_impl(storage_fn, storage_step_fn)
    except Exception:  # pylint: disable=broad-except
        return fallback


def wait_idle(timeout: Optional[float] = None) -> bool:
    """Drain the background writer (end-of-run barrier). NEVER
    raises; True when idle (or no plane is active)."""
    try:
        ckpt = _current()
        if ckpt is None:
            return True
        return ckpt.wait_idle(timeout)
    except Exception:  # pylint: disable=broad-except
        return True
