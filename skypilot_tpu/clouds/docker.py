"""Local Docker "cloud": containers as cluster hosts (dev backend).

Twin of the reference's `sky local up/down` + LocalDockerBackend
(sky/backends/local_docker_backend.py): a zero-cost cloud whose
"instances" are local containers, launched through the NORMAL
backend/gang path (provision/docker/instance.py) — no special backend
class. Gated behind `xsky local up` (writes the ~/.xsky/enable_docker
marker; `xsky local down` removes it) so a running docker daemon never
silently absorbs generic CPU tasks — the same explicit opt-in as the
reference's `sky local up`. XSKY_ENABLE_DOCKER_CLOUD=1 forces it for
tests. Priced at 0 like Kubernetes/SSH.
"""
from __future__ import annotations

import os
import subprocess
import typing
from typing import Any, Dict, Iterator, List, Optional, Tuple

from skypilot_tpu.clouds import cloud as cloud_lib
from skypilot_tpu.utils import registry

if typing.TYPE_CHECKING:
    from skypilot_tpu import resources as resources_lib


@registry.CLOUD_REGISTRY.register(aliases=['local'])
class Docker(cloud_lib.Cloud):
    _REPR = 'Docker'

    _UNSUPPORTED = {
        cloud_lib.CloudImplementationFeatures.SPOT_INSTANCE:
            'Local containers have no spot market.',
        cloud_lib.CloudImplementationFeatures.STOP:
            'Stop local clusters with `xsky down` (containers are '
            'cheap to recreate).',
        cloud_lib.CloudImplementationFeatures.OPEN_PORTS:
            'Local containers share the host network namespace.',
        cloud_lib.CloudImplementationFeatures.CUSTOM_DISK_TIER:
            'Local containers use the host disk.',
        cloud_lib.CloudImplementationFeatures.STORAGE_MOUNTING:
            'Mount host paths directly instead.',
    }

    @property
    def provisioner_module(self) -> str:
        return 'docker'

    def unsupported_features_for_resources(
            self, resources: 'resources_lib.Resources'
    ) -> Dict[cloud_lib.CloudImplementationFeatures, str]:
        return dict(self._UNSUPPORTED)

    def regions_with_offering(self, instance_type: str,
                              accelerators: Optional[Dict[str, Any]],
                              use_spot: bool, region: Optional[str],
                              zone: Optional[str]) -> List[cloud_lib.Region]:
        if use_spot or accelerators:
            return []
        if region not in (None, 'local'):
            return []
        return [cloud_lib.Region('local', ['local'])]

    def zones_provision_loop(self, region: str, num_nodes: int,
                             instance_type: str,
                             accelerators: Optional[Dict[str, Any]] = None,
                             use_spot: bool = False) -> Iterator[List[str]]:
        del region, num_nodes, instance_type, accelerators, use_spot
        yield ['local']

    def get_default_instance_type(
            self, cpus: Optional[str] = None,
            memory: Optional[str] = None) -> Optional[str]:
        del cpus, memory
        return 'container'

    def instance_type_exists(self, instance_type: str) -> bool:
        return instance_type == 'container'

    def get_feasible_launchable_resources(self, resources):
        if resources.accelerators or resources.use_spot:
            return [], []
        itype = resources.instance_type or 'container'
        if itype != 'container':
            return [], []
        return [resources.copy(cloud=self.name,
                               instance_type='container')], []

    def instance_type_to_hourly_cost(self, instance_type: str,
                                     use_spot: bool = False,
                                     region: Optional[str] = None,
                                     zone: Optional[str] = None) -> float:
        return 0.0

    def make_deploy_resources_variables(
            self, resources: 'resources_lib.Resources', cluster_name: str,
            region: str, zone: Optional[str]) -> Dict[str, Any]:
        return {
            'cluster_name': cluster_name,
            'region': 'local',
            'zone': None,
            'instance_type': 'container',
            'image_id': resources.image_id,
        }

    def provider_config_overrides(
            self, node_config: Dict[str, Any]) -> Dict[str, Any]:
        del node_config
        return {}

    MARKER_PATH = '~/.xsky/enable_docker'

    @classmethod
    def daemon_available(cls) -> Tuple[bool, Optional[str]]:
        try:
            proc = subprocess.run(['docker', 'info'],
                                  capture_output=True, timeout=10)
            if proc.returncode == 0:
                return True, None
            return False, ('docker daemon not responding '
                           '(`docker info` failed).')
        except (FileNotFoundError, subprocess.TimeoutExpired):
            return False, 'docker CLI not found or not responding.'

    def check_credentials(self) -> Tuple[bool, Optional[str]]:
        if os.environ.get('XSKY_ENABLE_DOCKER_CLOUD') == '1':
            return True, None
        if not os.path.exists(os.path.expanduser(self.MARKER_PATH)):
            return False, ('Local docker cloud is opt-in: run '
                           '`xsky local up` to enable it.')
        return self.daemon_available()

    def get_credential_file_mounts(self) -> Dict[str, str]:
        return {}

    def get_egress_cost(self, num_gigabytes: float) -> float:
        return 0.0
