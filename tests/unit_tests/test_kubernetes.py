"""Kubernetes cloud + provisioner tests (in-memory kubectl fake).

The fake kubectl plays moto's role (reference tests/test_failover.py):
every provisioner op goes through instance._run_kubectl, which we replace
with a dict-backed implementation.
"""
import json

import pytest

from skypilot_tpu.clouds import kubernetes as k8s_cloud
from skypilot_tpu.provision import common
from skypilot_tpu.provision.kubernetes import instance as k8s_instance
from skypilot_tpu.utils import command_runner


class FakeKubectl:
    """Dict-backed kubectl: supports the verbs the provisioner uses."""

    def __init__(self):
        self.pods = {}       # name -> manifest (with injected status)
        self.services = {}
        self.calls = []      # (verb, context, namespace)

    def __call__(self, args, context=None, namespace=None, input_data=None,
                 timeout=60.0):
        verb = args[0]
        self.calls.append((verb, context, namespace))
        if verb == 'apply':
            items = json.loads(input_data)
            if items.get('kind') == 'List':
                items = items['items']
            else:
                items = [items]
            for m in items:
                name = m['metadata']['name']
                if m['kind'] == 'Pod':
                    m.setdefault('status',
                                 {'phase': 'Running', 'podIP':
                                  f'10.0.0.{len(self.pods) + 1}'})
                    self.pods[name] = m
                else:
                    self.services[name] = m
            return ''
        if verb == 'get':
            selector = args[args.index('-l') + 1]
            key, value = selector.split('=')
            items = [
                p for p in self.pods.values()
                if p['metadata'].get('labels', {}).get(key) == value
            ]
            return json.dumps({'items': items})
        if verb == 'delete':
            if args[1] == 'pods,services':
                selector = args[args.index('-l') + 1]
                key, value = selector.split('=')
                self.pods = {
                    n: p for n, p in self.pods.items()
                    if p['metadata'].get('labels', {}).get(key) != value
                }
                self.services = {
                    n: s for n, s in self.services.items()
                    if s['metadata'].get('labels', {}).get(key) != value
                }
                return ''
            if args[1] == 'service':
                self.services.pop(args[2], None)
                return ''
        raise AssertionError(f'FakeKubectl: unhandled {args}')


@pytest.fixture
def fake_kubectl(monkeypatch):
    fake = FakeKubectl()
    monkeypatch.setattr(k8s_instance, '_run_kubectl', fake)
    return fake


def _tpu_config(count=1):
    cloud = k8s_cloud.Kubernetes()
    from skypilot_tpu import resources as resources_lib
    res = resources_lib.Resources(cloud='kubernetes',
                                  accelerators='tpu-v6e-16')
    node_config = cloud.make_deploy_resources_variables(
        res, 'mycluster', 'in-cluster', None)
    return common.ProvisionConfig(provider_config={
        'context': None, 'namespace': 'default'},
        node_config=node_config, count=count)


class TestKubernetesCloud:

    def test_tpu_deploy_variables(self):
        config = _tpu_config()
        node = config.node_config
        assert node['tpu_podslice'] is True
        assert node['tpu_gke_accelerator'] == 'tpu-v6e-slice'
        assert node['tpu_num_hosts'] == 4       # v6e-16 = 4 hosts x 4 chips
        assert node['tpu_chips_per_host'] == 4
        assert node['tpu_gke_topology'] == '4x4'

    def test_instance_type_roundtrip(self):
        cloud = k8s_cloud.Kubernetes()
        itype = cloud.get_default_instance_type(cpus='8', memory='32')
        assert itype == '8CPU--32GB'
        assert cloud.instance_type_exists(itype)
        assert cloud._parse_instance_type(itype) == (8.0, 32.0)

    def test_feasible_resources_keep_tpu(self):
        from skypilot_tpu import resources as resources_lib
        cloud = k8s_cloud.Kubernetes()
        res = resources_lib.Resources(cloud='kubernetes',
                                      accelerators='tpu-v5e-8')
        candidates, fuzzy = cloud.get_feasible_launchable_resources(res)
        assert len(candidates) == 1
        assert not fuzzy
        assert candidates[0].accelerators == {'tpu-v5e-8': 1}

    def test_zero_cost(self):
        cloud = k8s_cloud.Kubernetes()
        assert cloud.instance_type_to_hourly_cost('8CPU--32GB', False) == 0
        assert cloud.accelerators_to_hourly_cost({'tpu-v6e-16': 1},
                                                 False) == 0


class TestKubernetesProvisioner:

    def test_tpu_podslice_creates_one_pod_per_host(self, fake_kubectl):
        config = _tpu_config()
        record = k8s_instance.run_instances('in-cluster', None, 'mycluster',
                                            config)
        assert len(record.created_instance_ids) == 4
        assert record.head_instance_id == 'mycluster-0'
        # Pods carry GKE TPU selectors + google.com/tpu limits.
        pod = fake_kubectl.pods['mycluster-0']
        sel = pod['spec']['nodeSelector']
        assert sel['cloud.google.com/gke-tpu-accelerator'] == 'tpu-v6e-slice'
        assert sel['cloud.google.com/gke-tpu-topology'] == '4x4'
        limits = pod['spec']['containers'][0]['resources']['limits']
        assert limits['google.com/tpu'] == '4'
        # Headless service for gang DNS.
        assert 'mycluster' in fake_kubectl.services
        assert fake_kubectl.services['mycluster']['spec']['clusterIP'] == \
            'None'

    def test_idempotent_run_instances(self, fake_kubectl):
        config = _tpu_config()
        k8s_instance.run_instances('in-cluster', None, 'mycluster', config)
        record2 = k8s_instance.run_instances('in-cluster', None, 'mycluster',
                                             config)
        assert record2.created_instance_ids == []
        assert len(fake_kubectl.pods) == 4

    def test_query_and_cluster_info(self, fake_kubectl):
        config = _tpu_config()
        k8s_instance.run_instances('in-cluster', None, 'mycluster', config)
        statuses = k8s_instance.query_instances('mycluster', {})
        assert set(statuses.values()) == {'RUNNING'}
        info = k8s_instance.get_cluster_info('in-cluster', 'mycluster', {})
        assert len(info.instances) == 4
        assert info.head_instance_id == 'mycluster-0'
        hosts = info.sorted_instances()
        assert [h.host_index for h in hosts] == [0, 1, 2, 3]
        assert all(h.internal_ip for h in hosts)
        # All four hosts share one slice id (one v6e-16 slice).
        assert len({h.slice_id for h in hosts}) == 1

    def test_stop_unsupported_terminate_works(self, fake_kubectl):
        config = _tpu_config()
        k8s_instance.run_instances('in-cluster', None, 'mycluster', config)
        from skypilot_tpu import exceptions
        with pytest.raises(exceptions.NotSupportedError):
            k8s_instance.stop_instances('mycluster', {})
        k8s_instance.terminate_instances('mycluster', {})
        assert fake_kubectl.pods == {}
        assert k8s_instance.query_instances('mycluster', {}) == {}

    def test_open_and_cleanup_ports(self, fake_kubectl):
        config = _tpu_config()
        k8s_instance.run_instances('in-cluster', None, 'mycluster', config)
        k8s_instance.open_ports('mycluster', ['8080'], {})
        svc = fake_kubectl.services['mycluster-ports']
        assert svc['spec']['type'] == 'NodePort'
        assert svc['spec']['ports'][0]['port'] == 8080
        k8s_instance.cleanup_ports('mycluster', {})
        assert 'mycluster-ports' not in fake_kubectl.services


class TestKubernetesCommandRunner:

    def test_exec_command_construction(self, monkeypatch):
        captured = {}

        def fake_run(cmd, **kwargs):
            captured['cmd'] = cmd
            import subprocess as sp
            return sp.CompletedProcess(cmd, 0, stdout='hi', stderr='')

        import subprocess
        monkeypatch.setattr(subprocess, 'run', fake_run)
        runner = command_runner.KubernetesCommandRunner(
            'mycluster-0', namespace='ns1', context='ctx1')
        code, out, _ = runner.run('echo hi', require_outputs=True,
                                  env={'A': '1'})
        assert code == 0 and out == 'hi'
        cmd = captured['cmd']
        assert cmd[:7] == ['kubectl', '--context', 'ctx1', '-n', 'ns1',
                           'exec', '-i']
        assert 'mycluster-0' in cmd
        assert cmd[-1].startswith('export A=1; ')

    def test_runners_from_cluster_info(self, fake_kubectl):
        config = _tpu_config()
        k8s_instance.run_instances('in-cluster', None, 'mycluster', config)
        info = k8s_instance.get_cluster_info(
            'in-cluster', 'mycluster',
            {'namespace': 'ns2', 'context': 'ctx2'})
        runners = command_runner.runners_from_cluster_info(info, 'unused')
        assert len(runners) == 4
        assert all(isinstance(r, command_runner.KubernetesCommandRunner)
                   for r in runners)
        assert runners[0].pod_name == 'mycluster-0'
        assert runners[0].namespace == 'ns2'
        assert runners[0].context == 'ctx2'


def test_lifecycle_ops_agree_on_context_and_namespace(fake_kubectl):
    """Every lifecycle op must target the context/namespace that
    run_instances used — contexts are this cloud's regions, so a
    mismatch silently operates on the wrong cluster."""
    from skypilot_tpu import resources as resources_lib
    cloud = k8s_cloud.Kubernetes()
    res = resources_lib.Resources(
        cloud='kubernetes', instance_type='2CPU--8GB',
        labels={'kubernetes/namespace': 'ns-a'})
    node_config = cloud.make_deploy_resources_variables(
        res, 'ctxtest', 'gke-prod', None)
    # The cloud exposes the keys the failover engine merges into
    # provider_config for all later lifecycle ops.
    overrides = cloud.provider_config_overrides(node_config)
    assert overrides == {'context': 'gke-prod', 'namespace': 'ns-a'}
    provider_config = {'region': 'gke-prod', 'zone': None, **overrides}
    config = common.ProvisionConfig(provider_config=provider_config,
                                    node_config=node_config, count=1)
    k8s_instance.run_instances('gke-prod', None, 'ctxtest', config)
    k8s_instance.wait_instances('gke-prod', 'ctxtest', 'RUNNING',
                                provider_config=provider_config)
    k8s_instance.query_instances('ctxtest', provider_config)
    k8s_instance.get_cluster_info('gke-prod', 'ctxtest', provider_config)
    k8s_instance.terminate_instances('ctxtest', provider_config)
    assert fake_kubectl.calls, 'no kubectl calls recorded'
    for verb, context, namespace in fake_kubectl.calls:
        assert context == 'gke-prod', (verb, context)
        assert namespace == 'ns-a', (verb, namespace)


def test_wait_instances_derives_context_from_region(fake_kubectl):
    """A caller that lost provider_config still targets the right
    cluster: region doubles as the kubectl context."""
    config = _tpu_config()
    k8s_instance.run_instances('in-cluster', None, 'mycluster', config)
    fake_kubectl.calls.clear()
    k8s_instance.wait_instances('gke-other', 'mycluster', 'RUNNING')
    assert fake_kubectl.calls[0][1] == 'gke-other'
    fake_kubectl.calls.clear()
    k8s_instance.wait_instances('in-cluster', 'mycluster', 'RUNNING')
    assert fake_kubectl.calls[0][1] is None


def test_multislice_per_slice_host_index(fake_kubectl):
    """2 slices of tpu-v6e-16: TPU_WORKER_ID restarts at 0 per slice."""
    from skypilot_tpu import resources as resources_lib
    cloud = k8s_cloud.Kubernetes()
    res = resources_lib.Resources(
        cloud='kubernetes', accelerators='tpu-v6e-16',
        accelerator_args={'num_slices': 2})
    node_config = cloud.make_deploy_resources_variables(
        res, 'ms', 'in-cluster', None)
    config = common.ProvisionConfig(
        provider_config={'namespace': 'default', 'context': None},
        node_config=node_config, count=1)
    record = k8s_instance.run_instances('in-cluster', None, 'ms', config)
    assert len(record.created_instance_ids) == 8
    info = k8s_instance.get_cluster_info('in-cluster', 'ms', {})
    hosts = info.sorted_instances()
    assert sorted(h.host_index for h in hosts) == [0, 0, 1, 1, 2, 2, 3, 3]
    assert len({h.slice_id for h in hosts}) == 2
    # Env TPU_WORKER_ID matches the per-slice index.
    for i in range(8):
        pod = fake_kubectl.pods[f'ms-{i}']
        env = pod['spec']['containers'][0]['env']
        assert env[0]['value'] == str(i % 4)
