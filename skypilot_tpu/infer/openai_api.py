"""OpenAI-compatible request/response shaping for the serving endpoint.

The reference's serving recipes expose this exact wire surface through
vLLM/SGLang (llm/vllm/serve.yaml, llm/sglang/llama2.yaml:34 — both
serve ``/v1/completions`` + ``/v1/chat/completions``); the framework
owns its own engine here, so it implements the API natively. Pure
shaping logic lives in this module (testable without HTTP); the HTTP
routes are in ``infer/server.py``.

Supported: prompt as text / token list, ``max_tokens``, ``temperature``,
``top_p``/``top_k``, ``stop`` (string or list), ``stream`` (SSE),
``echo``. Rejected clearly: ``n > 1``, ``logprobs``, batched prompts.
"""
from __future__ import annotations

import dataclasses
import json
import time
import uuid
from typing import Any, Dict, List, Optional, Tuple

from skypilot_tpu.infer import orchestrator as orch_lib
from skypilot_tpu.infer import tokenizer as tokenizer_lib


class ApiError(Exception):
    """Maps to an OpenAI-style error body with an HTTP status."""

    def __init__(self, code: int, message: str) -> None:
        super().__init__(message)
        self.code = code

    def body(self) -> Dict[str, Any]:
        return {'error': {'message': str(self),
                          'type': 'invalid_request_error'}}


@dataclasses.dataclass
class RequestMeta:
    """Everything the response builders need beyond the orch Request."""
    kind: str                    # 'completion' | 'chat'
    model_id: str
    stream: bool
    stop: List[str]
    echo: bool
    prompt_text: str             # '' when prompt came as token ids
    prompt_tokens: List[int]
    response_id: str = ''
    created: int = 0

    def __post_init__(self) -> None:
        prefix = 'cmpl' if self.kind == 'completion' else 'chatcmpl'
        self.response_id = f'{prefix}-{uuid.uuid4().hex[:24]}'
        self.created = int(time.time())


def _parse_prompt(body: Dict[str, Any],
                  tokenizer: Any) -> Tuple[str, List[int]]:
    prompt = body.get('prompt')
    if isinstance(prompt, list) and len(prompt) == 1 and \
            isinstance(prompt[0], str):
        prompt = prompt[0]  # single-element batch: allowed
    if isinstance(prompt, str):
        return prompt, tokenizer.encode(prompt)
    if isinstance(prompt, list) and prompt and \
            all(isinstance(t, int) for t in prompt):
        return '', list(prompt)  # pre-tokenized (OpenAI allows this)
    if isinstance(prompt, list):
        raise ApiError(400, 'batched prompts are not supported; send '
                            'one request per prompt')
    raise ApiError(400, "'prompt' (string or token list) is required")


def _parse_chat_prompt(body: Dict[str, Any],
                       tokenizer: Any) -> Tuple[str, List[int]]:
    messages = body.get('messages')
    if not isinstance(messages, list) or not messages or not all(
            isinstance(m, dict) and isinstance(m.get('content'), str)
            for m in messages):
        raise ApiError(400, "'messages' must be a non-empty list of "
                            "{role, content} objects")
    text = tokenizer_lib.render_chat(messages, tokenizer)
    return text, tokenizer.encode(text)


def build_request(body: Dict[str, Any], tokenizer: Any,
                  engine_config: Any, model_id: str,
                  chat: bool) -> Tuple[orch_lib.Request, RequestMeta]:
    """Validate an API body into an orchestrator Request + meta.

    Raises ApiError on anything malformed or unsupported.
    """
    if body.get('n', 1) != 1:
        raise ApiError(400, 'n > 1 is not supported')
    if body.get('logprobs'):
        raise ApiError(400, 'logprobs are not supported')
    if chat:
        prompt_text, prompt_tokens = _parse_chat_prompt(body, tokenizer)
    else:
        prompt_text, prompt_tokens = _parse_prompt(body, tokenizer)

    limit = min(engine_config.max_prompt_len,
                engine_config.max_target_len - 1)
    if len(prompt_tokens) > limit:
        raise ApiError(400, f'prompt is {len(prompt_tokens)} tokens; '
                            f'this server accepts at most {limit}')

    budget = engine_config.max_target_len - len(prompt_tokens)
    max_tokens = body.get('max_tokens')
    if max_tokens is None:
        # OpenAI defaults completions to 16; chat fills the budget.
        max_tokens = 16 if not chat else budget
    try:
        max_tokens = int(max_tokens)
    except (TypeError, ValueError):
        raise ApiError(400, "'max_tokens' must be an integer")
    if max_tokens < 1:
        raise ApiError(400, "'max_tokens' must be ≥ 1")
    max_tokens = min(max_tokens, budget)

    stop = body.get('stop') or []
    if isinstance(stop, str):
        stop = [stop]
    if not isinstance(stop, list) or not all(
            isinstance(s, str) and s for s in stop):
        raise ApiError(400, "'stop' must be a string or list of strings")
    if len(stop) > 4:
        raise ApiError(400, "at most 4 'stop' sequences")

    try:
        temperature = float(body.get('temperature', 1.0))
        top_p = float(body.get('top_p', 1.0))
        top_k = int(body.get('top_k', 0))
    except (TypeError, ValueError):
        raise ApiError(400, 'temperature/top_p/top_k must be numbers')

    request = orch_lib.Request(
        prompt_tokens=prompt_tokens,
        max_new_tokens=max_tokens,
        eos_token_id=getattr(tokenizer, 'eos_token_id', None),
        temperature=temperature,
        top_k=top_k,
        top_p=top_p)
    meta = RequestMeta(kind='chat' if chat else 'completion',
                       model_id=model_id,
                       stream=bool(body.get('stream', False)),
                       stop=stop,
                       echo=bool(body.get('echo', False)),
                       prompt_text=prompt_text,
                       prompt_tokens=prompt_tokens)
    return request, meta


def find_stop(text: str, stops: List[str]) -> int:
    """Earliest index where any stop sequence begins, or -1."""
    best = -1
    for stop in stops:
        idx = text.find(stop)
        if idx != -1 and (best == -1 or idx < best):
            best = idx
    return best


def finalize_text(meta: RequestMeta, request: orch_lib.Request,
                  tokenizer: Any) -> Tuple[str, str]:
    """(text, finish_reason) for a finished non-streamed request."""
    text = tokenizer.decode(request.output_tokens)
    finish_reason = ('length' if len(request.output_tokens) >=
                     request.max_new_tokens else 'stop')
    idx = find_stop(text, meta.stop)
    if idx != -1:
        text, finish_reason = text[:idx], 'stop'
    if meta.echo and meta.kind == 'completion':
        # prompt_text is '' when the prompt arrived as token ids —
        # reconstruct it so echo still echoes.
        prompt_text = meta.prompt_text or \
            tokenizer.decode(meta.prompt_tokens)
        text = prompt_text + text
    return text, finish_reason


def _usage(meta: RequestMeta,
           request: orch_lib.Request) -> Dict[str, int]:
    return {'prompt_tokens': len(meta.prompt_tokens),
            'completion_tokens': len(request.output_tokens),
            'total_tokens': (len(meta.prompt_tokens) +
                             len(request.output_tokens))}


def response_body(meta: RequestMeta, request: orch_lib.Request,
                  text: str, finish_reason: str) -> Dict[str, Any]:
    if meta.kind == 'chat':
        choice: Dict[str, Any] = {
            'index': 0,
            'message': {'role': 'assistant', 'content': text},
            'finish_reason': finish_reason,
        }
        obj = 'chat.completion'
    else:
        choice = {'index': 0, 'text': text,
                  'finish_reason': finish_reason}
        obj = 'text_completion'
    return {'id': meta.response_id, 'object': obj,
            'created': meta.created, 'model': meta.model_id,
            'choices': [choice], 'usage': _usage(meta, request)}


def chunk_body(meta: RequestMeta, text: str,
               finish_reason: Optional[str],
               first: bool = False) -> Dict[str, Any]:
    if meta.kind == 'chat':
        delta: Dict[str, Any] = {}
        if first:
            delta['role'] = 'assistant'
        if text:
            delta['content'] = text
        choice: Dict[str, Any] = {'index': 0, 'delta': delta,
                                  'finish_reason': finish_reason}
        obj = 'chat.completion.chunk'
    else:
        choice = {'index': 0, 'text': text,
                  'finish_reason': finish_reason}
        obj = 'text_completion'
    return {'id': meta.response_id, 'object': obj,
            'created': meta.created, 'model': meta.model_id,
            'choices': [choice]}


def sse(payload: Dict[str, Any]) -> bytes:
    return f'data: {json.dumps(payload)}\n\n'.encode()


SSE_DONE = b'data: [DONE]\n\n'


class StreamEmitter:
    """Incremental text emission with stop-sequence hold-back.

    Deltas are only released once they can no longer be a prefix of a
    stop sequence still in flight; on a stop hit, the text before the
    stop is emitted and ``finished`` flips so the caller can cancel
    the underlying request.
    """

    def __init__(self, tokenizer: Any, stops: List[str]) -> None:
        self._decoder = tokenizer_lib.IncrementalDecoder(tokenizer)
        self._stops = stops
        self._holdback = max((len(s) for s in stops), default=1) - 1
        self._text = ''
        self._sent = 0
        self.finished = False
        self.finish_reason: Optional[str] = None

    def push(self, tokens: List[int], final: bool = False) -> str:
        """Feed the full token list so far; returns newly safe text."""
        if self.finished:
            return ''
        self._text += self._decoder.delta(tokens, final=final)
        idx = find_stop(self._text, self._stops)
        if idx != -1:
            self.finished = True
            self.finish_reason = 'stop'
            out = self._text[self._sent:idx]
            self._sent = idx
            return out
        safe_upto = len(self._text) if final else \
            max(self._sent, len(self._text) - self._holdback)
        out = self._text[self._sent:safe_upto]
        self._sent = safe_upto
        return out
