"""Model forward/backward + sharded trainer tests on the 8-device CPU mesh."""
import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from skypilot_tpu.models import llama
from skypilot_tpu.ops import attention as attention_ops
from skypilot_tpu.parallel import mesh as mesh_lib
from skypilot_tpu.train import trainer as trainer_lib


pytestmark = pytest.mark.slow  # heavy tier: subprocess e2e / jit compiles


@pytest.fixture(scope='module')
def tiny():
    return llama.LLAMA_TINY


class TestAttention:

    def test_causal_matches_manual(self):
        key = jax.random.PRNGKey(0)
        q = jax.random.normal(key, (2, 16, 4, 8))
        k = jax.random.normal(jax.random.PRNGKey(1), (2, 16, 2, 8))
        v = jax.random.normal(jax.random.PRNGKey(2), (2, 16, 2, 8))
        out = attention_ops.xla_attention(q, k, v, causal=True)
        assert out.shape == (2, 16, 4, 8)
        # Position 0 attends only to itself: out[:,0] == v[:,0] repeated.
        np.testing.assert_allclose(out[:, 0, 0], v[:, 0, 0], rtol=1e-5)
        np.testing.assert_allclose(out[:, 0, 1], v[:, 0, 0], rtol=1e-5)
        np.testing.assert_allclose(out[:, 0, 2], v[:, 0, 1], rtol=1e-5)

    def test_gqa_group_mapping(self):
        # With 4 q-heads and 2 kv-heads, heads (0,1)->kv0, (2,3)->kv1.
        q = jnp.ones((1, 4, 4, 8))
        k = jnp.ones((1, 4, 2, 8))
        v = jnp.arange(2.0)[None, None, :, None] * jnp.ones((1, 4, 2, 8))
        out = attention_ops.xla_attention(q, k, v, causal=False)
        np.testing.assert_allclose(out[0, 0, 0], np.zeros(8), atol=1e-6)
        np.testing.assert_allclose(out[0, 0, 3], np.ones(8), atol=1e-6)


class TestModel:

    def test_forward_shapes(self, tiny):
        params = llama.init(tiny, jax.random.PRNGKey(0))
        tokens = jnp.zeros((2, 16), jnp.int32)
        logits = llama.forward(tiny, params, tokens)
        assert logits.shape == (2, 16, tiny.vocab_size)
        assert logits.dtype == jnp.float32

    def test_causality(self, tiny):
        """Changing a future token must not affect past logits."""
        params = llama.init(tiny, jax.random.PRNGKey(0))
        t1 = jnp.zeros((1, 16), jnp.int32)
        t2 = t1.at[0, 10].set(7)
        l1 = llama.forward(tiny, params, t1)
        l2 = llama.forward(tiny, params, t2)
        np.testing.assert_allclose(l1[0, :10], l2[0, :10], atol=1e-4)
        assert not np.allclose(l1[0, 10:], l2[0, 10:], atol=1e-4)

    def test_loss_decreases(self, tiny):
        cfg = trainer_lib.TrainConfig(
            model=tiny, global_batch_size=8, seq_len=32,
            learning_rate=1e-2, warmup_steps=1,
            mesh_plan=mesh_lib.MeshPlan())
        tr = trainer_lib.Trainer(cfg)
        state = tr.init_state()
        batch = tr.synthetic_batch()
        losses = []
        for _ in range(5):
            state, metrics = tr.step(state, batch)
            losses.append(float(metrics['loss']))
        assert losses[-1] < losses[0]

    def test_param_count_formula(self, tiny):
        params = llama.init(tiny, jax.random.PRNGKey(0))
        actual = sum(x.size for x in jax.tree.leaves(params))
        assert actual == tiny.num_params()


class TestMesh:

    def test_plan_resolution(self):
        plan = mesh_lib.MeshPlan(fsdp=4).resolve(8)
        assert plan.data == 2 and plan.fsdp == 4

    def test_plan_mismatch_raises(self):
        with pytest.raises(ValueError):
            mesh_lib.MeshPlan(data=3, fsdp=3).resolve(8)

    def test_build_mesh_8dev(self):
        mesh = mesh_lib.build_mesh(mesh_lib.MeshPlan(fsdp=4, tensor=2))
        assert mesh.shape['fsdp'] == 4
        assert mesh.shape['tensor'] == 2
        assert mesh.shape['data'] == 1

    def test_logical_to_spec(self):
        spec = mesh_lib.logical_to_spec(('batch', None, 'embed'))
        assert spec == mesh_lib.PartitionSpec(('data', 'fsdp'), None, None)
        # 'embed' dropped because fsdp already used by batch.
        spec2 = mesh_lib.logical_to_spec(('vocab', 'embed'))
        assert spec2 == mesh_lib.PartitionSpec('tensor', 'fsdp')

    def test_build_mesh_multislice_layout(self):
        """num_slices=2 on virtual devices: the slice index must be the
        outermost stride of the 'data' axis (only gradient reduce
        crosses the DCN boundary), with each slice's devices contiguous
        in the inner mesh."""
        devices = jax.devices()[:8]
        mesh = mesh_lib.build_mesh(
            mesh_lib.MeshPlan(data=2, fsdp=2, tensor=2),
            devices=devices, num_slices=2)
        assert mesh.shape['data'] == 2
        arr = mesh.devices
        # data index 0 → slice A devices (first half of the ordered
        # list), data index 1 → slice B, regardless of inner layout.
        first = {d.id for d in arr[0].flatten()}
        second = {d.id for d in arr[1].flatten()}
        assert first == {d.id for d in devices[:4]}
        assert second == {d.id for d in devices[4:]}

    def test_build_mesh_multislice_indivisible_raises(self):
        with pytest.raises(ValueError):
            mesh_lib.build_mesh(mesh_lib.MeshPlan(data=3, fsdp=2),
                                devices=jax.devices()[:6], num_slices=2)


class TestShardedTraining:

    @pytest.mark.parametrize('plan', [
        mesh_lib.MeshPlan(fsdp=8),
        mesh_lib.MeshPlan(fsdp=4, tensor=2),
        mesh_lib.MeshPlan(data=2, fsdp=2, tensor=2),
        mesh_lib.MeshPlan(data=2, fsdp=2, sequence=1, tensor=2),
    ])
    def test_step_runs_sharded(self, tiny, plan):
        cfg = trainer_lib.TrainConfig(model=tiny, global_batch_size=8,
                                      seq_len=32, mesh_plan=plan)
        tr = trainer_lib.Trainer(cfg)
        state = tr.init_state()
        batch = tr.synthetic_batch()
        state, metrics = tr.step(state, batch)
        assert np.isfinite(float(metrics['loss']))

    def test_sharded_matches_single_device(self, tiny):
        """FSDP-sharded step must be numerically equal to unsharded."""
        model = dataclasses.replace(tiny, remat=False)
        cfg1 = trainer_lib.TrainConfig(model=model, global_batch_size=8,
                                       seq_len=32,
                                       mesh_plan=mesh_lib.MeshPlan(fsdp=8))
        cfg2 = trainer_lib.TrainConfig(model=model, global_batch_size=8,
                                       seq_len=32,
                                       mesh_plan=mesh_lib.MeshPlan(data=1))
        tr1 = trainer_lib.Trainer(cfg1)
        tr2 = trainer_lib.Trainer(
            cfg2, mesh=mesh_lib.build_mesh(cfg2.mesh_plan,
                                           devices=jax.devices()[:1]))
        s1, s2 = tr1.init_state(), tr2.init_state()
        b1, b2 = tr1.synthetic_batch(), tr2.synthetic_batch()
        _, m1 = tr1.step(s1, b1)
        _, m2 = tr2.step(s2, b2)
        assert float(m1['loss']) == pytest.approx(float(m2['loss']),
                                                  rel=1e-4)


class TestPackedSequences:
    """packing_reset_eos: EOS-derived segment masks + position resets."""

    def test_segments_from_eos(self):
        toks = jnp.asarray([[5, 7, 0, 9, 11, 0, 13, 15]])  # EOS = 0
        seg, pos = llama.segments_from_eos(toks, 0)
        assert seg[0].tolist() == [1, 1, 1, 2, 2, 2, 3, 3]
        assert pos[0].tolist() == [0, 1, 2, 0, 1, 2, 0, 1]

    def test_packed_forward_equals_per_document(self):
        """Each document in a packed row must see exactly the logits it
        would get alone: no cross-document attention, RoPE restarting
        at each boundary."""
        c = dataclasses.replace(llama.LLAMA_TINY, packing_reset_eos=0)
        params = llama.init(c, jax.random.PRNGKey(0))
        doc1 = [5, 7, 9, 0]                    # closes with EOS
        doc2 = [11, 13, 17, 19, 23]
        packed = jnp.asarray([doc1 + doc2], jnp.int32)
        out = llama.forward(c, params, packed)
        alone1 = llama.forward(c, params, jnp.asarray([doc1], jnp.int32))
        alone2 = llama.forward(c, params, jnp.asarray([doc2], jnp.int32))
        np.testing.assert_allclose(np.asarray(out[0, :4]),
                                   np.asarray(alone1[0]),
                                   rtol=2e-2, atol=2e-2)
        np.testing.assert_allclose(np.asarray(out[0, 4:]),
                                   np.asarray(alone2[0]),
                                   rtol=2e-2, atol=2e-2)
        # And without the flag, the packed row DOES leak across the
        # boundary (cross-document attention changes doc2's logits).
        out_leaky = llama.forward(llama.LLAMA_TINY, params, packed)
        assert float(jnp.abs(out_leaky[0, 4:] -
                             alone2[0]).max()) > 1e-2

    @pytest.mark.parametrize('family', ['qwen', 'gemma', 'moe'])
    def test_packed_forward_isolates_documents_all_families(self, family):
        import importlib
        mod = importlib.import_module(f'skypilot_tpu.models.{family}')
        cfg = {'qwen': 'QWEN3_TINY', 'gemma': 'GEMMA_TINY',
               'moe': 'MOE_TINY'}[family]
        base = getattr(mod, cfg)
        overrides = {'packing_reset_eos': 0}
        if family == 'moe':
            # Expert capacity is shared across the whole [B, S] token
            # set, so packed-vs-alone equality only holds when nothing
            # is capacity-dropped; attention isolation is what this
            # test pins.
            overrides['capacity_factor'] = 8.0
        c = dataclasses.replace(base, **overrides)
        params = mod.init(c, jax.random.PRNGKey(0))
        doc1 = [5, 7, 0]
        doc2 = [11, 13, 17]
        packed = jnp.asarray([doc1 + doc2], jnp.int32)
        out = mod.forward(c, params, packed)
        if isinstance(out, tuple):
            out = out[0]
        alone2 = mod.forward(c, params, jnp.asarray([doc2], jnp.int32))
        if isinstance(alone2, tuple):
            alone2 = alone2[0]
        np.testing.assert_allclose(np.asarray(out[0, 3:]),
                                   np.asarray(alone2[0]),
                                   rtol=3e-2, atol=3e-2)

    def test_packed_loss_trains(self):
        """loss_fn with packing set: finite loss, gradients flow."""
        c = dataclasses.replace(llama.LLAMA_TINY, packing_reset_eos=0)
        params = llama.init(c, jax.random.PRNGKey(0))
        toks = jnp.asarray([[5, 7, 0, 9, 11, 0, 13, 15]], jnp.int32)
        tgts = jnp.asarray([[7, 0, 9, 11, 0, 13, 15, 1]], jnp.int32)
        loss, grads = jax.value_and_grad(
            lambda p: llama.loss_fn(c, p, toks, tgts))(params)
        assert bool(jnp.isfinite(loss))
        gnorm = jax.tree_util.tree_reduce(
            lambda a, g: a + float(jnp.abs(g).sum()), grads, 0.0)
        assert gnorm > 0

    def test_packing_rejected_under_pipeline(self):
        """packing_reset_eos + stage>1 must fail at Trainer
        construction: the GPipe layer body has no segment masks, so
        letting it run would silently train with cross-document
        attention (ADVICE r3, medium)."""
        c = dataclasses.replace(llama.LLAMA_TINY, n_layers=4,
                                packing_reset_eos=0)
        config = trainer_lib.TrainConfig(
            model=c, global_batch_size=4, seq_len=16,
            n_microbatches=2,
            mesh_plan=mesh_lib.MeshPlan(data=2, stage=2, tensor=2))
        with pytest.raises(NotImplementedError, match='packing_reset_eos'):
            trainer_lib.Trainer(config)


class TestGradAccumulation:

    def _trainer(self, accum):
        config = trainer_lib.TrainConfig(
            model=llama.LLAMA_TINY, global_batch_size=4, seq_len=16,
            optimizer='adafactor', accum_steps=accum,
            mesh_plan=mesh_lib.MeshPlan(data=1))
        return trainer_lib.Trainer(
            config, mesh=mesh_lib.build_mesh(
                mesh_lib.MeshPlan(data=1).resolve(1),
                devices=jax.devices()[:1]))

    def test_accum_matches_single_step(self):
        """accum_steps=2 over the same global batch must produce the
        same loss and (numerically) the same updated params as one
        unaccumulated step."""
        t1, t2 = self._trainer(1), self._trainer(2)
        batch = t1.synthetic_batch()
        s1, m1 = t1.step(t1.init_state(), dict(batch))
        s2, m2 = t2.step(t2.init_state(), dict(batch))
        assert float(m1['loss']) == pytest.approx(float(m2['loss']),
                                                  rel=1e-5)
        flat1 = jax.tree_util.tree_leaves(s1['params'])
        flat2 = jax.tree_util.tree_leaves(s2['params'])
        for a, b in zip(flat1, flat2):
            np.testing.assert_allclose(
                np.asarray(a, np.float32), np.asarray(b, np.float32),
                atol=5e-3)

    def test_accum_validation(self):
        def build(**kwargs):
            return trainer_lib.Trainer(
                trainer_lib.TrainConfig(
                    model=llama.LLAMA_TINY, global_batch_size=4,
                    seq_len=16, mesh_plan=mesh_lib.MeshPlan(data=1),
                    **kwargs),
                mesh=mesh_lib.build_mesh(
                    mesh_lib.MeshPlan(data=1).resolve(1),
                    devices=jax.devices()[:1]))

        with pytest.raises(ValueError, match='divisible'):
            build(accum_steps=3)
        with pytest.raises(ValueError, match='>= 1'):
            build(accum_steps=0)

    def test_accum_weighted_mask_matches_unaccumulated(self):
        """An unbalanced loss mask must produce the same loss under
        accumulation as in one step (token-weighted combination)."""
        t1, t2 = self._trainer(1), self._trainer(2)
        batch = t1.synthetic_batch()
        mask = np.ones((4, 16), np.float32)
        mask[0, 4:] = 0.0            # row 0 nearly all masked
        batch = dict(batch, mask=jnp.asarray(mask))
        _, m1 = t1.step(t1.init_state(), dict(batch))
        _, m2 = t2.step(t2.init_state(), dict(batch))
        assert float(m1['loss']) == pytest.approx(float(m2['loss']),
                                                  rel=1e-5)

    def test_accum_on_data_sharded_mesh(self):
        """Strided microbatching keeps every data shard populated."""
        config = trainer_lib.TrainConfig(
            model=llama.LLAMA_TINY, global_batch_size=8, seq_len=16,
            optimizer='adafactor', accum_steps=2,
            mesh_plan=mesh_lib.MeshPlan(data=4, tensor=2))
        tr = trainer_lib.Trainer(config)
        state, metrics = tr.step(tr.init_state(), tr.synthetic_batch())
        assert np.isfinite(float(metrics['loss']))

    def test_accum_fully_masked_batch_is_harmless(self):
        """All-zero loss mask under accumulation: zero loss, finite
        params (the w_sum division is guarded like the family loss)."""
        t2 = self._trainer(2)
        batch = dict(t2.synthetic_batch(),
                     mask=jnp.zeros((4, 16), jnp.float32))
        state, metrics = t2.step(t2.init_state(), batch)
        assert float(metrics['loss']) == 0.0
        assert all(bool(jnp.isfinite(x).all())
                   for x in jax.tree_util.tree_leaves(state['params']))
