"""Resources parsing/validation tests (twin of tests/unit_tests/test_resources.py)."""
import pytest

from skypilot_tpu import Resources
from skypilot_tpu import exceptions


class TestAccelerators:

    def test_gpu_string(self):
        r = Resources(accelerators='A100:8')
        assert r.accelerators == {'A100': 8}
        assert not r.is_tpu

    def test_gpu_default_count(self):
        assert Resources(accelerators='A100').accelerators == {'A100': 1}

    def test_tpu_name(self):
        r = Resources(accelerators='tpu-v5p-64')
        assert r.is_tpu
        assert r.accelerators == {'tpu-v5p-64': 1}
        assert r.tpu_topology.num_chips == 32
        assert r.num_hosts_per_node == 8

    def test_tpu_with_count_raises(self):
        with pytest.raises(ValueError):
            Resources(accelerators='tpu-v5e-8:2')

    def test_tpu_multislice_hosts(self):
        r = Resources(accelerators='tpu-v5e-32',
                      accelerator_args={'num_slices': 2})
        assert r.num_hosts_per_node == 8  # 4 hosts x 2 slices

    def test_dict(self):
        assert Resources(accelerators={'H100': 4}).accelerators == {'H100': 4}


class TestValidation:

    def test_unknown_cloud(self):
        with pytest.raises(ValueError):
            Resources(cloud='nonexistent')

    def test_zone_infers_region(self):
        r = Resources(cloud='gcp', zone='us-central1-a')
        assert r.region == 'us-central1'

    def test_bad_zone(self):
        with pytest.raises(ValueError):
            Resources(cloud='gcp', zone='mars-central1-a')

    def test_bad_instance_type(self):
        with pytest.raises(ValueError):
            Resources(cloud='gcp', instance_type='bogus-128xlarge')

    def test_cpus_plus_syntax(self):
        assert Resources(cpus='4+').cpus == '4+'
        assert Resources(cpus=4).cpus == '4'
        with pytest.raises(ValueError):
            Resources(cpus='four')


class TestCost:

    def test_tpu_hourly_cost(self):
        r = Resources(cloud='gcp', accelerators='tpu-v5e-8')
        assert r.get_hourly_cost() == pytest.approx(8 * 1.20)

    def test_tpu_spot_cheaper(self):
        od = Resources(cloud='gcp', accelerators='tpu-v5e-8')
        spot = Resources(cloud='gcp', accelerators='tpu-v5e-8', use_spot=True)
        assert spot.get_hourly_cost() < od.get_hourly_cost()

    def test_vm_cost(self):
        r = Resources(cloud='gcp', instance_type='a2-highgpu-8g')
        assert r.get_hourly_cost() == pytest.approx(29.387)


class TestSemantics:

    def test_less_demanding_than(self):
        small = Resources(accelerators='A100:4')
        big = Resources(cloud='gcp', instance_type='a2-highgpu-8g',
                        accelerators='A100:8')
        assert small.less_demanding_than(big)
        assert not Resources(accelerators='H100:8').less_demanding_than(big)

    def test_copy_override(self):
        r = Resources(accelerators='tpu-v5e-8')
        r2 = r.copy(cloud='gcp', use_spot=True)
        assert r2.cloud_name == 'gcp'
        assert r2.use_spot
        assert r.cloud_name is None  # original untouched

    def test_yaml_roundtrip(self):
        r = Resources(cloud='gcp', accelerators='tpu-v5p-64', use_spot=True,
                      disk_size=100, ports=8080,
                      accelerator_args={'runtime_version': 'v2-alpha-tpuv5'})
        r2 = Resources.from_yaml_config(r.to_yaml_config())
        assert r == r2

    def test_any_of(self):
        out = Resources.from_yaml_config({
            'any_of': [{'accelerators': 'A100:8'},
                       {'accelerators': 'tpu-v5e-8'}],
            'use_spot': True,
        })
        assert isinstance(out, list) and len(out) == 2
        assert all(r.use_spot for r in out)

    def test_autostop_forms(self):
        assert Resources(autostop=10).autostop == {'idle_minutes': 10,
                                                   'down': False}
        assert Resources(autostop=True).autostop['idle_minutes'] == 5
        assert Resources(autostop=False).autostop is None
        assert Resources(
            autostop={'idle_minutes': 3, 'down': True}).autostop == {
                'idle_minutes': 3, 'down': True}

    def test_launchable(self):
        assert not Resources(accelerators='A100').is_launchable()
        assert Resources(cloud='gcp', accelerators='tpu-v5e-8').is_launchable()
        assert Resources(cloud='gcp',
                         instance_type='n2-standard-8').is_launchable()
        with pytest.raises(exceptions.ResourcesUnavailableError):
            Resources().assert_launchable()


class TestFeatures:

    def test_tpu_pod_features(self):
        from skypilot_tpu.clouds import CloudImplementationFeatures as F
        r = Resources(accelerators='tpu-v5p-64', use_spot=True)
        feats = r.get_required_cloud_features()
        assert F.TPU_POD in feats
        assert F.SPOT_INSTANCE in feats

    def test_gcp_pod_cannot_stop(self):
        from skypilot_tpu.clouds import GCP, CloudImplementationFeatures as F
        r = Resources(cloud='gcp', accelerators='tpu-v5p-64')
        with pytest.raises(exceptions.NotSupportedError):
            GCP.check_features_are_supported(r, {F.STOP})
        # Single-host v5e-8 can stop fine.
        r2 = Resources(cloud='gcp', accelerators='tpu-v5e-8')
        GCP.check_features_are_supported(r2, {F.STOP})
