"""Managed-jobs API (twin of sky/jobs/server/core.py + scheduler).

Controller placement: two modes, matching the reference
(sky/templates/jobs-controller.yaml.j2, sky/jobs/scheduler.py):

  * local (default) — controller processes run on the API-server host,
    scheduled by jobs.scheduler under launching/alive parallelism caps.
  * remote — XSKY_JOBS_CONTROLLER_REMOTE=1 provisions a dedicated
    controller cluster and every jobs verb (launch/queue/cancel/logs)
    is forwarded to it over the backend command runner (jobs.remote),
    like the reference's ManagedJobCodeGen-over-SSH.
"""
from __future__ import annotations

import os
import signal
import time
from typing import Any, Dict, List, Optional

from skypilot_tpu import sky_logging
from skypilot_tpu import task as task_lib
from skypilot_tpu.jobs import scheduler as jobs_scheduler
from skypilot_tpu.jobs import state as jobs_state

logger = sky_logging.init_logger(__name__)


def _remote_mode() -> bool:
    return os.environ.get('XSKY_JOBS_CONTROLLER_REMOTE', '') not in (
        '', '0')


def launch(task, name: Optional[str] = None,
           wait: bool = False, timeout_s: float = 600.0,
           priority: int = 0) -> int:
    """Submit a managed job; returns the managed job id.

    `task` is one Task, or a SEQUENCE of Tasks — a pipeline the
    controller runs as a sequential chain, each task on its own
    cluster with its own recovery budget. ``priority``: fleet-scheduler
    admission priority (higher first; weighted fair-share across
    workspaces and starvation aging apply on top — see jobs/fleet.py).
    """
    if _remote_mode():
        from skypilot_tpu.jobs import remote as jobs_remote
        return jobs_remote.launch(task, name=name, wait=wait,
                                  timeout_s=timeout_s,
                                  priority=priority)
    tasks = list(task) if isinstance(task, (list, tuple)) else [task]
    config = task_lib.Task.chain_to_config(tasks)
    # Record the submitting workspace: jobs.cancel/jobs.logs authz
    # resolves ownership from this column (server/app.py
    # _target_workspace).
    from skypilot_tpu.workspaces import context as ws_context
    job_id = jobs_state.add_job(name or tasks[0].name, config,
                                workspace=ws_context.get_active(),
                                priority=priority)
    jobs_state.set_status(job_id, jobs_state.ManagedJobStatus.SUBMITTED)
    jobs_scheduler.submit_job(job_id)
    if wait:
        wait_for_terminal(job_id, timeout_s)
    return job_id


def wait_for_terminal(job_id: int, timeout_s: float = 600.0
                      ) -> jobs_state.ManagedJobStatus:
    deadline = time.time() + timeout_s
    while time.time() < deadline:
        record = jobs_state.get_job(job_id)
        if record and record['status'].is_terminal():
            return record['status']
        time.sleep(0.3)
    raise TimeoutError(f'Managed job {job_id} not terminal '
                       f'after {timeout_s}s')


def queue(limit: Optional[int] = None,
          offset: int = 0) -> List[Dict[str, Any]]:
    if _remote_mode():
        from skypilot_tpu.jobs import remote as jobs_remote
        from skypilot_tpu.utils import db_utils
        # The remote-controller wire protocol predates pagination:
        # page here, with the same clamping as the SQL path, so
        # callers get one contract either way.
        return db_utils.page_rows(jobs_remote.queue(), limit, offset)
    rows = jobs_state.get_jobs(limit=limit, offset=offset)
    return [{
        'job_id': r['job_id'],
        'name': r['name'],
        'status': r['status'].value,
        'schedule_state': r['schedule_state'].value,
        'cluster_name': r['cluster_name'],
        'recovery_count': r['recovery_count'],
        'failure_reason': r['failure_reason'],
        'submitted_at': r['submitted_at'],
        'ended_at': r['ended_at'],
        # Fleet scheduler: admission priority + elastic gang state
        # ("3/4" while shrunk — survivors over full gang size).
        'priority': r.get('priority', 0),
        'gang': _gang_summary(r),
        # Pipelines: which chain link is running (1-based).
        'task': (f"{min(r['current_task'] + 1, r['num_tasks'])}"
                 f"/{r['num_tasks']}" if r['num_tasks'] > 1 else None),
    } for r in rows]


def _gang_summary(record: Dict[str, Any]) -> Optional[str]:
    """'survivors/full' while elastically shrunk, else None."""
    detail = record.get('gang_detail') or {}
    if record.get('gang_status') != 'SHRUNK':
        return None
    full = detail.get('full_hosts') or 0
    excluded = len(detail.get('excluded') or ())
    if not full:
        return 'SHRUNK'
    return f'{full - excluded}/{full}'


def cancel(job_id: int) -> None:
    if _remote_mode():
        from skypilot_tpu.jobs import remote as jobs_remote
        jobs_remote.cancel(job_id)
        return
    # Under the scheduler lock so the cancel cannot interleave with a
    # concurrent WAITING→LAUNCHING claim (which would spawn a controller
    # for an already-cancelled job).
    with jobs_scheduler.schedule_lock():
        record = jobs_state.get_job(job_id)
        if record is None or record['status'].is_terminal():
            return
        pid = record['controller_pid']
        if pid:
            try:
                os.kill(pid, signal.SIGTERM)
            except (ProcessLookupError, PermissionError):
                pass
        jobs_state.set_status(job_id,
                              jobs_state.ManagedJobStatus.CANCELLED)
        jobs_state.set_schedule_state(job_id,
                                      jobs_state.ScheduleState.DONE)
    # Outside the lock: wake the queue (SIGTERM'd controllers cannot
    # report job_done themselves).
    jobs_scheduler.maybe_schedule_next_jobs()
    # Reap the task cluster if it exists.
    cluster_name = record['cluster_name']
    if cluster_name:
        from skypilot_tpu import core as core_lib
        from skypilot_tpu import exceptions
        try:
            core_lib.down(cluster_name, purge=True)
        except exceptions.ClusterDoesNotExist:
            pass


def watch_logs(job_id: int, offset: int = 0) -> Dict[str, Any]:
    """One incremental poll of a managed job's task log → {status,
    offset, data}. Status is the MANAGED job status (so tails stop on
    SUCCEEDED/FAILED/CANCELLED, not on a mid-recovery cluster swap);
    offset resets naturally when recovery moves the job to a fresh
    cluster log. Powers the dashboard live tail + `jobs logs
    --follow`."""
    if _remote_mode():
        from skypilot_tpu.jobs import remote as jobs_remote
        return jobs_remote.watch_logs(job_id, offset)
    record = jobs_state.get_job(job_id)
    if record is None:
        return {'status': 'NOT_FOUND', 'offset': offset, 'data': '',
                'done': True}
    # `done` is the single source of truth for "stop tailing" —
    # clients must not hand-copy the terminal-status list (it would go
    # stale the day the enum grows).
    done = record['status'].is_terminal()
    status = record['status'].value
    cluster_name = record['cluster_name']
    cluster_job_id = record.get('cluster_job_id')
    if not cluster_name or cluster_job_id is None:
        return {'status': status, 'offset': offset, 'data': '',
                'done': done}
    # Recovery moves the task to a fresh cluster/log whose file is
    # shorter than the caller's offset; `epoch` lets the client detect
    # the swap and restart its offset at 0. Task index is part of the
    # epoch: pipeline tasks reuse the cluster NAME and restart cluster
    # job ids at 1, so name#cjid alone wouldn't reset the offset.
    task_index = record.get('current_task') or 0
    epoch = f'{cluster_name}#task{task_index}#{cluster_job_id}'
    from skypilot_tpu import core as core_lib
    try:
        poll = core_lib.watch_job_log(cluster_name, cluster_job_id,
                                      offset)
        return {'status': status, 'offset': poll.get('offset', offset),
                'data': poll.get('log') or '',
                'epoch': epoch, 'done': done}
    except Exception:  # pylint: disable=broad-except
        # Cluster torn down (job done, or mid-recovery): serve the
        # controller-side archive — a byte-identical copy of the same
        # rank-0 run.log (fetched over the base64 watch channel), so
        # the caller's offset carries straight over and the final
        # chunk never races the reap.
        data, new_offset = _read_archive(job_id, task_index, offset)
        return {'status': status, 'offset': new_offset, 'data': data,
                'epoch': epoch, 'done': done}


def _read_archive(job_id: int, task_index: int,
                  offset: int) -> tuple:
    path = jobs_state.task_log_archive_path(job_id, task_index)
    try:
        with open(path, 'rb') as f:
            f.seek(max(0, offset))
            chunk = f.read(262144)
        return chunk.decode('utf-8', errors='replace'), \
            max(0, offset) + len(chunk)
    except OSError:
        return '', offset


def tail_logs(job_id: int) -> str:
    if _remote_mode():
        from skypilot_tpu.jobs import remote as jobs_remote
        return jobs_remote.tail_logs(job_id)
    record = jobs_state.get_job(job_id)
    if record is None:
        return ''
    cluster_name = record['cluster_name']
    if not cluster_name:
        return ''
    from skypilot_tpu import core as core_lib
    from skypilot_tpu import exceptions
    try:
        return core_lib.tail_logs(cluster_name)
    except (exceptions.ClusterDoesNotExist, exceptions.ClusterNotUpError):
        # Reaped cluster: the controller archived the task log before
        # teardown.
        data, _ = _read_archive(job_id,
                                record.get('current_task') or 0, 0)
        if data:
            return data
        return f'(cluster {cluster_name} is gone; job status: ' \
               f'{record["status"].value})'
