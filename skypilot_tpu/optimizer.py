"""Optimizer: pick cheapest/fastest feasible resources per task.

Twin of sky/optimizer.py:71 (optimize:109, _optimize_by_dp:429,
_optimize_by_ilp:490, _fill_in_launchable_resources:1256), with one
architectural change: the ILP (reference uses pulp) is replaced by an exact
enumerator for small DAGs plus coordinate-descent refinement for large ones —
dependency-free and exact for every DAG the reference's own tests exercise.

The GPU→TPU failover north star lives here: a request for A100s yields TPU
candidates too (both are catalog offerings), cost-ranked together, so the
failover engine naturally falls from GPUs onto TPU slices when blocked.
"""
from __future__ import annotations

import collections
import enum
import itertools
from typing import Dict, Iterable, List, Optional, Set, Tuple

from skypilot_tpu import check as check_lib
from skypilot_tpu import dag as dag_lib
from skypilot_tpu import exceptions
from skypilot_tpu import resources as resources_lib
from skypilot_tpu import sky_logging
from skypilot_tpu import task as task_lib
from skypilot_tpu.utils import registry

logger = sky_logging.init_logger(__name__)

_DEFAULT_RUNTIME_ESTIMATE_S = 3600.0
# DAGs up to this many assignment combinations are solved exactly.
_EXACT_SEARCH_LIMIT = 200_000


class OptimizeTarget(enum.Enum):
    COST = 'cost'
    TIME = 'time'


class Optimizer:

    @staticmethod
    def optimize(dag: dag_lib.Dag,
                 minimize: OptimizeTarget = OptimizeTarget.COST,
                 blocked_resources: Optional[Iterable[
                     resources_lib.Resources]] = None,
                 quiet: bool = False) -> dag_lib.Dag:
        """Assign ``task.best_resources`` for every task in the DAG."""
        dag.validate()
        candidates = _fill_in_launchable_resources(dag, blocked_resources)
        assignment = _solve(dag, candidates, minimize)
        for t, (chosen, cost) in assignment.items():
            t.best_resources = chosen
            if not quiet:
                logger.info(
                    f'Task {t.name or "<unnamed>"}: {chosen} '
                    f'(${cost:.2f}/hr x {t.num_nodes} node(s))')
        return dag


def _estimate_runtime(task: task_lib.Task) -> float:
    est = getattr(task, 'estimated_runtime_seconds', None)
    return float(est) if est else _DEFAULT_RUNTIME_ESTIMATE_S


def _is_blocked(candidate: resources_lib.Resources,
                blocked: List[resources_lib.Resources]) -> bool:
    """A candidate is blocked if some blocked entry 'covers' it.

    Blocked entries are partial Resources (e.g. cloud+region only); the
    blocked entry's specified fields must all match the candidate.
    """
    for b in blocked:
        if b.cloud_name is not None and b.cloud_name != candidate.cloud_name:
            continue
        if b.region is not None and b.region != candidate.region:
            continue
        if b.zone is not None and b.zone != candidate.zone:
            continue
        if b.instance_type is not None and \
                b.instance_type != candidate.instance_type:
            continue
        if b.accelerators is not None and \
                b.accelerators != candidate.accelerators:
            continue
        # Capacity is per provisioning model: a stocked-out reservation
        # says nothing about spot or on-demand of the same SKU. Blocked
        # entries that name a model only cover candidates on that model.
        b_model = (b.accelerator_args or {}).get('provisioning_model')
        if b_model is not None and \
                candidate.effective_provisioning_model() != b_model:
            continue
        return True
    return False


def _fill_in_launchable_resources(
    dag: dag_lib.Dag,
    blocked_resources: Optional[Iterable[resources_lib.Resources]],
) -> Dict[task_lib.Task, List[Tuple[resources_lib.Resources, float]]]:
    """Per task: launchable (resources, $/hr) candidates.

    Cost-ranked unless the task used `ordered:` (user ranking wins).
    Twin of sky/optimizer.py:1256.
    """
    blocked = list(blocked_resources or [])
    enabled = check_lib.get_cached_enabled_clouds_or_refresh(
        raise_if_no_cloud_access=True)
    result: Dict[task_lib.Task, List[Tuple[resources_lib.Resources,
                                           float]]] = {}
    for t in dag.tasks:
        all_candidates: List[Tuple[resources_lib.Resources, float]] = []
        all_fuzzy: List[str] = []
        for request in t.resources:
            clouds = [request.cloud_name] if request.cloud_name else enabled
            per_request: List[Tuple[resources_lib.Resources, float]] = []
            for cloud_name in clouds:
                if cloud_name not in enabled:
                    continue
                cloud = registry.CLOUD_REGISTRY.from_str(cloud_name)
                feasible, fuzzy = cloud.get_feasible_launchable_resources(
                    request)
                all_fuzzy.extend(fuzzy)
                for cand in feasible:
                    if _is_blocked(cand, blocked):
                        continue
                    try:
                        cost = cand.get_hourly_cost()
                    except ValueError:
                        continue
                    per_request.append((cand, cost))
            if not t.resources_ordered:
                per_request.sort(key=_rank_key)
            all_candidates.extend(per_request)
        if not all_candidates:
            hint = ''
            if all_fuzzy:
                hint = (' Did you mean: '
                        f'{", ".join(sorted(set(all_fuzzy))[:8])}?')
            raise exceptions.ResourcesUnavailableError(
                f'No launchable resource found for task '
                f'{t.name or "<unnamed>"} '
                f'(requested: {t.resources}).{hint}')
        if not t.resources_ordered:
            all_candidates.sort(key=_rank_key)
        result[t] = all_candidates
    return result


def _rank_key(rc):
    """Cost ranking with two zero-price meanings kept apart:
    BYO capacity (ssh/k8s/docker/vsphere — genuinely free) ranks
    FIRST; a 0 catalog price elsewhere means 'unpublished' (e.g. v6e
    in some regions) and ranks after every known price."""
    cand, cost = rc
    cloud = cand.cloud
    free = bool(cloud and cloud.is_free_capacity)
    unpublished = cost == 0 and not free
    return (unpublished, cost)


def _node_objective(task: task_lib.Task, cost_per_hr: float,
                    minimize: OptimizeTarget) -> float:
    runtime = _estimate_runtime(task)
    if minimize is OptimizeTarget.TIME:
        return runtime
    return cost_per_hr * task.num_nodes * runtime / 3600.0


def _egress_cost(src: resources_lib.Resources,
                 dst: resources_lib.Resources,
                 gigabytes: float) -> float:
    """Cost of moving a task's outputs between the two placements.

    Cloud-granularity like the reference (sky/optimizer.py:239): intra-cloud
    transfer is free; cross-cloud pays the source cloud's egress rate.
    """
    if gigabytes <= 0:
        return 0.0
    if src.cloud_name == dst.cloud_name:
        return 0.0
    cloud = src.cloud
    return cloud.get_egress_cost(gigabytes) if cloud else 0.0


def _edge_gigabytes(task: task_lib.Task) -> float:
    return float(getattr(task, 'estimated_outputs_gigabytes', None) or 0.0)


def _solve(
    dag: dag_lib.Dag,
    candidates: Dict[task_lib.Task, List[Tuple[resources_lib.Resources,
                                               float]]],
    minimize: OptimizeTarget,
) -> Dict[task_lib.Task, Tuple[resources_lib.Resources, float]]:
    tasks = dag.topological_order()
    if len(tasks) == 1 or all(_edge_gigabytes(t) == 0 for t in tasks):
        # No egress coupling: each task independently takes its best.
        return {t: candidates[t][0] for t in tasks}
    if dag.is_chain():
        return _solve_chain_dp(tasks, dag, candidates, minimize)
    total = 1
    for t in tasks:
        total *= len(candidates[t])
        if total > _EXACT_SEARCH_LIMIT:
            return _solve_local_search(tasks, dag, candidates, minimize)
    return _solve_exact(tasks, dag, candidates, minimize)


def _assignment_objective(tasks, dag, chosen, minimize) -> float:
    total = 0.0
    for t in tasks:
        res, cost = chosen[t]
        total += _node_objective(t, cost, minimize)
        for child in dag.downstream(t):
            total += _egress_cost(res, chosen[child][0], _edge_gigabytes(t))
    return total


def _solve_chain_dp(tasks, dag, candidates, minimize):
    """DP over the chain (twin of sky/optimizer.py:429)."""
    # dp[i][j] = min objective of prefix ending with tasks[i] using cand j.
    dp: List[List[float]] = []
    parent_choice: List[List[int]] = []
    for i, t in enumerate(tasks):
        row, back = [], []
        for j, (res, cost) in enumerate(candidates[t]):
            node = _node_objective(t, cost, minimize)
            if i == 0:
                row.append(node)
                back.append(-1)
                continue
            prev_t = tasks[i - 1]
            # is_chain() also admits disconnected forests; only charge
            # egress when an actual edge links the consecutive tasks.
            has_edge = t in dag.downstream(prev_t)
            best, best_k = float('inf'), -1
            for k, (prev_res, _) in enumerate(candidates[prev_t]):
                egress = _egress_cost(prev_res, res,
                                      _edge_gigabytes(prev_t)) \
                    if has_edge else 0.0
                val = dp[i - 1][k] + egress
                if val < best:
                    best, best_k = val, k
            row.append(best + node)
            back.append(best_k)
        dp.append(row)
        parent_choice.append(back)
    # Backtrack.
    j = min(range(len(dp[-1])), key=dp[-1].__getitem__)
    out: Dict = {}
    for i in range(len(tasks) - 1, -1, -1):
        out[tasks[i]] = candidates[tasks[i]][j]
        j = parent_choice[i][j]
    return out


def _solve_exact(tasks, dag, candidates, minimize):
    """Exhaustive search (replaces the reference's pulp ILP :490 for the
    DAG sizes its own tests exercise)."""
    best_obj, best_choice = float('inf'), None
    index_ranges = [range(len(candidates[t])) for t in tasks]
    for combo in itertools.product(*index_ranges):
        chosen = {t: candidates[t][j] for t, j in zip(tasks, combo)}
        obj = _assignment_objective(tasks, dag, chosen, minimize)
        if obj < best_obj:
            best_obj, best_choice = obj, chosen
    assert best_choice is not None
    return best_choice


def _solve_local_search(tasks, dag, candidates, minimize):
    """Multi-start coordinate descent for DAGs too large to enumerate.

    Starts: the independent optimum, plus one colocation seed per cloud
    (each task's cheapest candidate on that cloud, if any). Egress
    coupling makes whole-DAG colocation the usual global optimum, and
    descent from the independent optimum alone can stall one hop away
    from it on multi-parent nodes (e.g. a diamond's sink)."""
    def _descend(chosen):
        improved, sweeps = True, 0
        while improved and sweeps < 10:
            improved = False
            sweeps += 1
            for t in tasks:
                best = chosen[t]
                best_obj = _assignment_objective(tasks, dag, chosen,
                                                 minimize)
                for cand in candidates[t]:
                    chosen[t] = cand
                    obj = _assignment_objective(tasks, dag, chosen,
                                                minimize)
                    if obj < best_obj - 1e-12:
                        best, best_obj = cand, obj
                        improved = True
                chosen[t] = best
        return chosen, _assignment_objective(tasks, dag, chosen, minimize)

    starts = [{t: candidates[t][0] for t in tasks}]
    clouds = {rc[0].cloud_name for t in tasks for rc in candidates[t]}
    for cloud in sorted(c for c in clouds if c):
        seed = {}
        for t in tasks:
            on_cloud = [rc for rc in candidates[t]
                        if rc[0].cloud_name == cloud]
            seed[t] = on_cloud[0] if on_cloud else candidates[t][0]
        starts.append(seed)

    best_choice, best_obj = None, float('inf')
    for seed in starts:
        chosen, obj = _descend(dict(seed))
        if obj < best_obj:
            best_choice, best_obj = chosen, obj
    assert best_choice is not None
    return best_choice


def _expand_provisioning_models(
        candidates: List[resources_lib.Resources],
        blocked: List[resources_lib.Resources]
) -> List[resources_lib.Resources]:
    """`provisioning_model: auto` → an ordered reserved → spot →
    on-demand walk (reservation is prepaid so it is always tried first;
    spot beats on-demand on price). Twin of the reference's
    reservation-priority + spot-first candidate ordering."""
    out: List[resources_lib.Resources] = []
    for r in candidates:
        args = dict(r.accelerator_args or {})
        if args.get('provisioning_model') != 'auto':
            out.append(r)
            continue
        args.pop('provisioning_model')
        reservation = args.pop('reservation', None)
        variants = []
        if reservation:
            variants.append(r.copy(
                accelerator_args={**args, 'reservation': reservation,
                                  'provisioning_model': 'reserved'},
                use_spot=False))
        variants.append(r.copy(
            accelerator_args={**args, 'provisioning_model': 'spot'},
            use_spot=True))
        variants.append(r.copy(
            accelerator_args={**args, 'provisioning_model': 'standard'},
            use_spot=False))
        out.extend(v for v in variants if not _is_blocked(v, blocked))
    return out


def candidates_for_failover(
        task: task_lib.Task,
        blocked_resources: Optional[Iterable[resources_lib.Resources]] = None
) -> List[resources_lib.Resources]:
    """Ordered launchable candidates for one task (used by the failover
    engine to walk to the next-cheapest SKU, incl. GPU→TPU)."""
    d = dag_lib.Dag()
    d.add(task)
    blocked = list(blocked_resources or [])
    cands = _fill_in_launchable_resources(d, blocked)[task]
    expanded = _expand_provisioning_models([r for r, _ in cands], blocked)
    if not expanded:
        # Every provisioning-model variant of every candidate is blocked.
        raise exceptions.ResourcesUnavailableError(
            f'No launchable resource left for task '
            f'{task.name or "<unnamed>"}: all provisioning models of '
            'every candidate are blocked.')
    return expanded
