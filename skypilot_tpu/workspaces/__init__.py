"""Workspaces: multi-tenant isolation of clusters (twin of sky/workspaces/)."""
