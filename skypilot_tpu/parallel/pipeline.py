"""GPipe-style pipeline parallelism over the mesh's 'stage' axis.

Capability twin of the reference's pipeline-parallel recipes (SURVEY
§2.12: DeepSpeed PP via examples/deepspeed-multinode/sky.yaml), built the
TPU way as a pure SPMD "shift-register" pipeline (the MaxText approach):

  * Layer params are viewed as [P, L/P, ...] with the leading stage dim
    sharded over the 'stage' mesh axis — each stage's devices hold only
    their own block of layers.
  * A state buffer [P, mb, ...] (stage-sharded) holds the activation
    currently *at* each stage. Every tick, a vmap over the stage dim
    applies each stage's layer block to its lane — pure data parallelism
    over 'stage', no manual collectives.
  * `jnp.roll(state, 1, axis=0)` hands each stage's output to its
    successor; XLA lowers the roll of a stage-sharded array to a
    collective-permute over ICI/DCN neighbors.
  * Everything is ordinary jnp under jit: AD, remat, and the other mesh
    axes (data/fsdp/tensor/...) compose with no special cases.

Schedule: classic GPipe fill-drain. For M microbatches and P stages the
loop runs M + P - 1 ticks; bubble fraction is (P-1)/(M+P-1). Reverse-mode
AD through the scan + roll yields the backward sweep automatically.
"""
from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def pipeline_apply(layer_fn: Callable[[jax.Array, Any], jax.Array],
                   stacked_params: Any,
                   x: jax.Array,
                   mesh: Mesh,
                   n_microbatches: int,
                   stage_axis: str = 'stage',
                   remat: bool = False,
                   with_aux: bool = False):
    """Apply L stacked layers to x, pipelined over the stage axis.

    Args:
      layer_fn: (x_mb [mb, ...], one_layer_params) -> x_mb — one layer;
        with with_aux=True it returns (x_mb, aux_scalar) instead (MoE
        load-balance loss).
      stacked_params: pytree whose leaves have leading dim L (the layer
        axis), sharded over `stage_axis` (use mesh.PIPELINE_RULES so
        'layers' maps to 'stage').
      x: [B, ...] activations; B % n_microbatches == 0.
      mesh: mesh containing `stage_axis`.
      n_microbatches: GPipe microbatch count M (bubble = (P-1)/(M+P-1)).
      remat: checkpoint each stage block (recompute in backward).
      with_aux: accumulate the per-layer aux scalar. Fill/drain lanes
        (holding no real microbatch) are masked out, so the returned
        mean is over real (microbatch, layer) pairs only.

    Returns [B, ...] (replicated over the stage axis, ordinary SPMD
    downstream); with with_aux=True, the tuple (out, aux_mean).
    """
    n_stages = int(mesh.shape[stage_axis])
    if x.shape[0] % n_microbatches:
        raise ValueError(f'Batch {x.shape[0]} not divisible by '
                         f'n_microbatches={n_microbatches}.')

    def stage_block(params_block, x_in):
        def one(carry, lp):
            if with_aux:
                return layer_fn(carry, lp)   # (y, aux)
            return layer_fn(carry, lp), None
        y, auxes = jax.lax.scan(one, x_in, params_block)
        if with_aux:
            return y, jnp.sum(auxes)
        return y, jnp.zeros((), jnp.float32)

    if remat:
        stage_block = jax.checkpoint(
            stage_block,
            policy=jax.checkpoint_policies.dots_with_no_batch_dims_saveable)

    n_layers = jax.tree.leaves(stacked_params)[0].shape[0]

    if n_stages == 1:
        y, aux_sum = stage_block(stacked_params, x)
        if with_aux:
            return y, aux_sum / n_layers
        return y

    if n_layers % n_stages:
        raise ValueError(f'{n_layers} layers not divisible by '
                         f'{n_stages} pipeline stages.')

    m = n_microbatches
    mb = x.shape[0] // m
    xs = x.reshape((m, mb) + x.shape[1:])

    # [P, L/P, ...] with the stage dim pinned to the stage mesh axis.
    staged_spec = P(stage_axis)
    params_staged = jax.tree.map(
        lambda a: jax.lax.with_sharding_constraint(
            a.reshape((n_stages, n_layers // n_stages) + a.shape[1:]),
            NamedSharding(mesh, staged_spec)),
        stacked_params)

    state_sharding = NamedSharding(mesh, P(stage_axis))

    def constrain(s):
        return jax.lax.with_sharding_constraint(s, state_sharding)

    state0 = constrain(jnp.zeros((n_stages,) + xs.shape[1:], x.dtype))
    out0 = jnp.zeros_like(xs)
    lanes = jnp.arange(n_stages)

    def tick(carry, t):
        state, out, aux_total = carry
        # Inject the next microbatch into the stage-0 lane.
        mb_t = xs[jnp.clip(t, 0, m - 1)].astype(x.dtype)
        state = state.at[0].set(mb_t)
        # Each stage advances its lane by its own layer block (vmap over
        # the stage-sharded dim → per-stage compute, zero communication).
        state, lane_aux = jax.vmap(stage_block)(params_staged, state)
        state = constrain(state)
        # Lane p holds microbatch t-p; fill/drain lanes hold zeros whose
        # aux must not pollute the statistics.
        valid = ((t - lanes >= 0) & (t - lanes <= m - 1)).astype(
            jnp.float32)
        aux_total = aux_total + jnp.sum(lane_aux * valid)
        # The last lane just finished microbatch t-(P-1): emit it.
        y = state[n_stages - 1]
        oidx = jnp.clip(t - (n_stages - 1), 0, m - 1)
        write = t >= n_stages - 1
        out = jax.lax.dynamic_update_index_in_dim(
            out, jnp.where(write, y, out[oidx]), oidx, 0)
        # Hand each lane to its successor (collective-permute over ICI).
        state = constrain(jnp.roll(state, 1, axis=0))
        return (state, out, aux_total), None

    (_, out, aux_total), _ = jax.lax.scan(
        tick, (state0, out0, jnp.zeros((), jnp.float32)),
        jnp.arange(m + n_stages - 1))
    out = out.reshape(x.shape)
    if with_aux:
        # Every real (microbatch, layer) pair contributed exactly once.
        return out, aux_total / (m * n_layers)
    return out


def pipelined_aux_lm_loss(params, stacked_layers, one_layer, tokens,
                          targets, mesh, n_microbatches, *, dtype,
                          norm_eps: float, remat: bool, ce_chunk: int,
                          aux_coef: float, loss_mask=None):
    """Shared GPipe LM-loss skeleton for routed-expert families.

    embed → pipeline_apply(with_aux) → final RMSNorm → chunked CE +
    aux term. moe.pipelined_loss_fn and deepseek.pipelined_loss_fn are
    thin wrappers over this (one source of truth for the pipeline
    semantics; the family contributes only its layer body).
    """
    from skypilot_tpu.models import llama
    x = llama._embed_lookup(params['embed'], tokens, mesh).astype(dtype)
    x, aux_mean = pipeline_apply(one_layer, stacked_layers, x, mesh,
                                 n_microbatches, remat=remat,
                                 with_aux=True)
    x = llama._rms_norm(x, params['final_norm'], norm_eps)
    ce = llama._chunked_ce(x, params['lm_head'], targets, loss_mask,
                           ce_chunk)
    return ce + aux_coef * aux_mean
