"""Ring attention + Ulysses: sequence/context parallelism over the ICI torus.

Long-context attention where the sequence axis is sharded across devices.
This capability is *absent* from the reference (SURVEY §5: no ring
attention/Ulysses/CP anywhere in sky/ — its longest-context recipes just
pick bigger GPUs), so this module is greenfield TPU-native design:

  * ``ring_attention`` — blockwise online-softmax attention. Each device
    holds one sequence shard of Q and streams K/V blocks around the
    'sequence' mesh axis with ``lax.ppermute`` (one ICI neighbor hop per
    step, bandwidth-optimal on the torus). Per-step HBM footprint is
    O(S_local²) and nothing global is ever materialized, so max context
    scales linearly with the number of devices on the axis.
  * ``ulysses_attention`` — all-to-all head scatter (DeepSpeed-Ulysses
    style): switch from sequence-sharded to head-sharded layout with one
    ``all_to_all``, run dense local attention over the full sequence,
    and switch back. Cheaper than ring for moderate S when heads ≥ axis
    size; ring wins when S_local² dominates.

Both are pure-JAX (einsum + collectives) so XLA schedules the permute
against the matmuls; reverse-mode AD works through the scan/ppermute.
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from skypilot_tpu.ops import jax_compat
from skypilot_tpu.ops.jax_compat import shard_map as _shard_map

from skypilot_tpu.ops.attention import _repeat_kv

_NEG_INF = -1e30  # finite: keeps online-softmax free of NaN on masked rows


DEFAULT_BLOCK_Q = 512


def _chunked_attend(q, kb, vb, o, l, m, scale: float, block_q: int,
                    q_pos=None, k_pos=None):
    """Online-softmax update of (o, l, m) with one K/V block, walking q
    in chunks so the logits transient is O(block_q · S_kv) instead of
    O(S² ) — the difference between ring attention scaling to long
    contexts and OOMing on its own scratch. q [B,S,H,D];
    kb/vb [B,Sk,H,D]; o [B,H,S,D] fp32; l/m [B,H,S] fp32; q_pos/k_pos
    enable the causal mask (diagonal block only).
    """
    s = q.shape[1]
    n_chunks = s // block_q

    def chunk_step(carry, ci):
        o, l, m = carry
        start = ci * block_q
        qs = jax.lax.dynamic_slice_in_dim(q, start, block_q, axis=1)
        logits = jnp.einsum('bqhd,bkhd->bhqk', qs, kb,
                            preferred_element_type=jnp.float32) * scale
        if q_pos is not None:
            qp = jax.lax.dynamic_slice_in_dim(q_pos, start, block_q, 0)
            mask = qp[:, None] >= k_pos[None, :]
            logits = jnp.where(mask[None, None], logits, _NEG_INF)
        m_prev = jax.lax.dynamic_slice_in_dim(m, start, block_q, axis=2)
        l_prev = jax.lax.dynamic_slice_in_dim(l, start, block_q, axis=2)
        o_prev = jax.lax.dynamic_slice_in_dim(o, start, block_q, axis=2)
        m_new = jnp.maximum(m_prev, logits.max(axis=-1))
        corr = jnp.exp(m_prev - m_new)
        p = jnp.exp(logits - m_new[..., None])
        l_new = l_prev * corr + p.sum(axis=-1)
        # P in bf16 onto the MXU (fp32 accumulation via
        # preferred_element_type) — the fp32 P×V einsum doubled the
        # dominant matmul's input traffic for no accuracy gain.
        o_new = o_prev * corr[..., None] + jnp.einsum(
            'bhqk,bkhd->bhqd', p.astype(vb.dtype), vb,
            preferred_element_type=jnp.float32)
        o = jax.lax.dynamic_update_slice_in_dim(o, o_new, start, axis=2)
        l = jax.lax.dynamic_update_slice_in_dim(l, l_new, start, axis=2)
        m = jax.lax.dynamic_update_slice_in_dim(m, m_new, start, axis=2)
        return (o, l, m), None

    (o, l, m), _ = jax.lax.scan(chunk_step, (o, l, m),
                                jnp.arange(n_chunks))
    return o, l, m


def ring_attention_local(q: jax.Array,
                         k: jax.Array,
                         v: jax.Array,
                         axis_name: str = 'sequence',
                         causal: bool = True,
                         block_q: int = DEFAULT_BLOCK_Q) -> jax.Array:
    """Ring attention body — call inside shard_map over `axis_name`.

    q: [B, S_local, H, D]; k/v: [B, S_local, Hkv, D] (GQA ok). The device's
    shard covers global positions [idx*S_local, (idx+1)*S_local).

    Schedule: the diagonal block runs first (statically causal-masked,
    so the finite _NEG_INF trick stays exact), then size-1 ring hops.
    Under causality a hop's block is either fully visible (source rank
    below this device) or fully dead (above it) — dead hops skip ALL
    compute via lax.cond (the scalar core branches per device; only
    the ppermute still runs to keep the ring rotating), which halves
    the causal FLOPs the previous revision spent exp()-ing fully
    masked logits.
    """
    size = jax_compat.axis_size(axis_name)
    idx = jax.lax.axis_index(axis_name)
    groups = q.shape[2] // k.shape[2]
    k = _repeat_kv(k, groups)
    v = _repeat_kv(v, groups)
    b, s, h, d = q.shape
    scale = d ** -0.5
    block_q = min(block_q, s)
    while s % block_q:
        # Largest divisor of s that fits: falling back to block_q = s
        # would silently reinstate the O(S_local²) logits transient
        # chunking exists to avoid.
        block_q -= 1
    positions = jnp.arange(s)

    o0 = jnp.zeros((b, h, s, d), jnp.float32)
    l0 = jnp.zeros((b, h, s), jnp.float32)
    m0 = jnp.full((b, h, s), _NEG_INF, jnp.float32)
    perm = [(j, (j + 1) % size) for j in range(size)]

    # Diagonal block (statically i == 0 on every device).
    olm = _chunked_attend(q, k, v, o0, l0, m0, scale, block_q,
                          q_pos=positions if causal else None,
                          k_pos=positions if causal else None)
    kb = jax.lax.ppermute(k, axis_name, perm)
    vb = jax.lax.ppermute(v, axis_name, perm)

    def step(carry, i):
        olm, kb, vb = carry
        if causal:
            # Hop i holds rank (idx - i) % size's block: visible iff
            # that rank is below this device — i.e. idx >= i.
            olm = jax.lax.cond(
                idx >= i,
                lambda olm: _chunked_attend(q, kb, vb, *olm, scale,
                                            block_q),
                lambda olm: olm,
                olm)
        else:
            olm = _chunked_attend(q, kb, vb, *olm, scale, block_q)
        kb = jax.lax.ppermute(kb, axis_name, perm)
        vb = jax.lax.ppermute(vb, axis_name, perm)
        return (olm, kb, vb), None

    (olm, _, _), _ = jax.lax.scan(step, (olm, kb, vb),
                                  jnp.arange(1, size))
    o, l, _ = olm
    out = o / jnp.maximum(l[..., None], 1e-30)
    return out.transpose(0, 2, 1, 3).astype(v.dtype)


def ulysses_attention_local(q: jax.Array,
                            k: jax.Array,
                            v: jax.Array,
                            axis_name: str = 'sequence',
                            causal: bool = True) -> jax.Array:
    """Ulysses body — call inside shard_map over `axis_name`.

    all_to_all swaps the sharded dimension from sequence to heads, dense
    local attention runs over the full sequence, and one more all_to_all
    swaps back. Head counts must be divisible by the axis size; GQA K/V
    are repeated up to full heads first when they are not.
    """
    size = jax_compat.axis_size(axis_name)
    h, h_kv = q.shape[2], k.shape[2]
    if h % size:
        raise ValueError(f'n_heads ({h}) must be divisible by the sequence '
                         f'axis size ({size}) for Ulysses.')
    if h_kv % size:
        k = _repeat_kv(k, h // h_kv)
        v = _repeat_kv(v, h // h_kv)

    def scatter_heads(x):
        return jax.lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                                  tiled=True)

    q, k, v = scatter_heads(q), scatter_heads(k), scatter_heads(v)
    # Dense local attention over the full sequence, local head shard.
    from skypilot_tpu.ops.attention import xla_attention
    out = xla_attention(q, k, v, causal=causal)
    return jax.lax.all_to_all(out, axis_name, split_axis=1, concat_axis=2,
                              tiled=True)


def _sharded(fn, mesh: Mesh, seq_axis: str, causal: bool):
    qspec = P(('data', 'fsdp'), seq_axis, 'tensor', None)
    return _shard_map(
        functools.partial(fn, axis_name=seq_axis, causal=causal),
        mesh=mesh,
        in_specs=(qspec, qspec, qspec),
        out_specs=qspec,
        check_vma=False)


def ring_attention(q: jax.Array, k: jax.Array, v: jax.Array, mesh: Mesh,
                   causal: bool = True,
                   seq_axis: str = 'sequence') -> jax.Array:
    """Sequence-parallel ring attention over `mesh`'s sequence axis.

    Global shapes; batch is sharded over (data, fsdp), heads over tensor,
    sequence over `seq_axis` — matching parallel.mesh.DEFAULT_RULES.
    """
    return _sharded(ring_attention_local, mesh, seq_axis, causal)(q, k, v)


def ulysses_attention(q: jax.Array, k: jax.Array, v: jax.Array, mesh: Mesh,
                      causal: bool = True,
                      seq_axis: str = 'sequence') -> jax.Array:
    """Sequence-parallel Ulysses attention over `mesh`'s sequence axis."""
    return _sharded(ulysses_attention_local, mesh, seq_axis, causal)(q, k, v)


def sequence_parallel_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                                mesh: Optional[Mesh],
                                implementation: str = 'ring',
                                causal: bool = True) -> jax.Array:
    """Dispatch used by models when the mesh has a sequence axis > 1."""
    if implementation == 'ulysses':
        return ulysses_attention(q, k, v, mesh, causal=causal)
    return ring_attention(q, k, v, mesh, causal=causal)
