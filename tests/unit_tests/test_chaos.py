"""Chaos-layer tests: plan mechanics, the zero-overhead-when-disabled
guarantee, the no-raw-``time.sleep``-in-retry-loops lint, and the
tier-1 preemption-storm smoke (docs/robustness.md's worked example)."""
import ast
import json
import os
import re
import time

import pytest

from skypilot_tpu import exceptions
from skypilot_tpu.utils import chaos


@pytest.fixture(autouse=True)
def _clean_chaos():
    chaos.clear()
    yield
    chaos.clear()


class TestChaosPlan:

    def test_disabled_is_zero_overhead(self):
        assert 'XSKY_CHAOS_PLAN' not in os.environ
        assert not chaos.enabled()
        assert chaos.inject('jobs.status_probe', job_id=1) is None
        # The acceptance-criteria assertion: with no plan loaded the
        # instrumented hot paths leave no trace — not even hit counts.
        assert chaos.counters() == {}
        assert chaos.fired() == {}

    def test_first_n_and_skip_first(self):
        chaos.load_plan({'points': {
            'p': {'skip_first': 1, 'first_n': 2}}})
        fires = [chaos.inject('p') is not None for _ in range(5)]
        assert fires == [False, True, True, False, False]
        assert chaos.hits('p') == 5
        assert chaos.fired()['p'] == 2

    def test_every_kth(self):
        chaos.load_plan({'points': {'p': {'every_kth': 3}}})
        fires = [chaos.inject('p') is not None for _ in range(7)]
        assert fires == [False, False, True, False, False, True, False]

    def test_match_selector_filters_on_context(self):
        chaos.load_plan({'points': {
            'gang.host_start': {'match': {'rank': 1}, 'first_n': 1}}})
        assert chaos.inject('gang.host_start', rank=0) is None
        # Non-matching hits don't consume the rule's first_n budget.
        assert chaos.inject('gang.host_start', rank=1) is not None
        assert chaos.inject('gang.host_start', rank=1) is None
        assert chaos.hits('gang.host_start') == 3

    def test_seeded_probability_is_deterministic(self):
        def run():
            chaos.load_plan({'seed': 11, 'points': {
                'p': {'probability': 0.5}}})
            return [chaos.inject('p') is not None for _ in range(20)]

        first, second = run(), run()
        assert first == second
        assert any(first) and not all(first)

    def test_rule_list_first_match_wins(self):
        chaos.load_plan({'points': {'p': [
            {'first_n': 1, 'returncode': 255},
            {'skip_first': 1, 'first_n': 1, 'error': 'RuntimeError'},
        ]}})
        assert chaos.inject('p')['returncode'] == 255
        with pytest.raises(RuntimeError):
            chaos.inject('p')
        assert chaos.inject('p') is None

    def test_error_resolution_prefers_xsky_exceptions(self):
        chaos.load_plan({'points': {
            'a': {'error': 'CapacityError'},
            'b': {'error': 'TimeoutError'},
            'c': {'error': 'NoSuchErrorType'}}})
        with pytest.raises(exceptions.CapacityError):
            chaos.inject('a')
        with pytest.raises(TimeoutError):
            chaos.inject('b')
        with pytest.raises(chaos.ChaosError):
            chaos.inject('c')

    def test_signal_action_delivers_to_self(self):
        """The `signal` action (crash drills: SIGKILL a controller
        mid-flight) sends the configured signal to the injecting
        process — verified with a catchable signal."""
        import signal as signal_lib
        received = []
        old = signal_lib.signal(signal_lib.SIGUSR1,
                                lambda *a: received.append(1))
        try:
            chaos.load_plan({'points': {
                'p': {'first_n': 1, 'signal': 'SIGUSR1'}}})
            chaos.inject('p')
            assert received == [1]
            assert chaos.inject('p') is None   # rule spent
        finally:
            signal_lib.signal(signal_lib.SIGUSR1, old)

    def test_unknown_signal_name_raises_chaos_error(self):
        chaos.load_plan({'points': {'p': {'signal': 'SIGNOPE'}}})
        with pytest.raises(chaos.ChaosError):
            chaos.inject('p')

    def test_latency_action_sleeps(self):
        chaos.load_plan({'points': {'p': {'latency_s': 0.05}}})
        start = time.monotonic()
        assert chaos.inject('p') is not None
        assert time.monotonic() - start >= 0.05

    def test_latency_action_journals_measured_duration(
            self, fake_cluster_env):
        """The journal row records the MEASURED sleep, not the plan's
        configured value (an oversleeping host is the signal), and the
        fire lands on the active trace span with that latency."""
        del fake_cluster_env
        from skypilot_tpu import state as state_lib
        from skypilot_tpu.utils import tracing
        chaos.load_plan({'points': {'p': {'latency_s': 0.05}}})
        with tracing.span('chaos.host') as sp:
            chaos.inject('p')
        rows = state_lib.get_recovery_events(
            event_type='chaos.injected')
        assert len(rows) == 1
        measured = rows[0]['latency_s']
        assert measured is not None and measured >= 0.05
        # Measured, not configured: a real sleep always overshoots.
        assert measured != 0.05
        span_row = state_lib.get_spans(sp.trace_id)[0]
        fires = span_row['attrs']['chaos_fires']
        assert fires[0]['point'] == 'p'
        assert fires[0]['latency_s'] >= 0.05
        # Journal row cross-links to the span's trace.
        assert rows[0]['trace_id'] == sp.trace_id

    def test_plan_from_env_json_and_file(self, monkeypatch, tmp_path):
        monkeypatch.setenv('XSKY_CHAOS_PLAN',
                           '{"points": {"p": {"first_n": 1}}}')
        assert chaos.enabled()
        assert chaos.inject('p') is not None
        plan_file = tmp_path / 'plan.json'
        plan_file.write_text(json.dumps(
            {'points': {'q': {'first_n': 1}}}))
        monkeypatch.setenv('XSKY_CHAOS_PLAN', str(plan_file))
        # New env value → fresh plan (counters reset with it).
        assert chaos.inject('q') is not None
        assert chaos.hits('p') == 0
        monkeypatch.delenv('XSKY_CHAOS_PLAN')
        assert not chaos.enabled()
        assert chaos.counters() == {}

    def test_invalid_plan_disables_chaos_not_recovery(
            self, monkeypatch, tmp_path):
        """A typo'd plan must never crash the instrumented recovery
        paths: it is logged and ignored (and the empty counters make a
        test driving a broken plan fail loudly on its hit asserts)."""
        monkeypatch.setenv('XSKY_CHAOS_PLAN', '{not json')
        assert chaos.inject('p') is None
        assert not chaos.enabled()
        assert chaos.counters() == {}
        monkeypatch.setenv('XSKY_CHAOS_PLAN',
                           str(tmp_path / 'missing.json'))
        assert chaos.inject('p') is None
        # A corrected plan takes effect without a restart.
        monkeypatch.setenv('XSKY_CHAOS_PLAN',
                           '{"points": {"p": {"first_n": 1}}}')
        assert chaos.inject('p') is not None

    def test_fire_journals_recovery_event(self, fake_cluster_env):
        del fake_cluster_env
        from skypilot_tpu import state as state_lib
        chaos.load_plan({'points': {
            'runner.run': {'first_n': 1, 'latency_s': 0.0}}})
        chaos.inject('runner.run', node='h0')
        rows = state_lib.get_recovery_events(
            event_type='chaos.injected')
        assert len(rows) == 1
        assert rows[0]['scope'] == 'chaos/runner.run'
        assert rows[0]['detail'] == {'node': 'h0'}


class TestInstrumentedHotPaths:
    """The chaos points actually sit on the paths they claim to."""

    def test_command_runner_subclasses_are_instrumented(self, tmp_path):
        from skypilot_tpu.utils import command_runner as runner_lib
        chaos.load_plan({'points': {
            'runner.run': {'first_n': 1, 'error': 'ConnectionError'}}})
        runner = runner_lib.LocalProcessCommandRunner(
            'h0', host_root=str(tmp_path / 'h0'))
        with pytest.raises(ConnectionError):
            runner.run('true')
        assert runner.run('true') == 0   # second run: rule spent
        assert chaos.hits('runner.run') == 2

    def test_serve_probe_tolerates_one_injected_drop(
            self, monkeypatch, tmp_path):
        """A single dropped readiness request must not flap the replica
        to NOT_READY: the probe's retry_transient absorbs it."""
        import http.server
        import threading

        from skypilot_tpu.serve import replica_managers
        from skypilot_tpu.serve import service_spec as spec_lib
        from skypilot_tpu.serve import state as serve_state

        monkeypatch.setenv('XSKY_SERVE_DB', str(tmp_path / 'serve.db'))

        class _OK(http.server.BaseHTTPRequestHandler):

            def do_GET(self):
                self.send_response(200)
                self.end_headers()

            def log_message(self, *args):
                pass

        server = http.server.HTTPServer(('127.0.0.1', 0), _OK)
        threading.Thread(target=server.serve_forever,
                         daemon=True).start()
        try:
            serve_state.add_service('flap', {}, 0)
            mgr = replica_managers.ReplicaManager(
                'flap', {}, spec_lib.SkyServiceSpec(readiness_path='/'))
            chaos.load_plan({'points': {
                'serve.probe': {'first_n': 1,
                                'error': 'ConnectionError'}}})
            endpoint = '127.0.0.1:%d' % server.server_address[1]
            assert mgr._probe(endpoint) is True
            assert chaos.hits('serve.probe') == 2
            # A persistent fault (every attempt) does fail the probe.
            chaos.load_plan({'points': {
                'serve.probe': {'error': 'ConnectionError'}}})
            assert mgr._probe(endpoint) is False
        finally:
            server.shutdown()

    def test_disabled_instrumented_paths_leave_no_trace(self, tmp_path):
        """End-to-end form of the zero-overhead guarantee: drive real
        instrumented code (runner + gang fan-out) with no plan loaded
        and assert the chaos layer recorded nothing."""
        from skypilot_tpu.agent import gang
        from skypilot_tpu.utils import command_runner as runner_lib
        runner = runner_lib.LocalProcessCommandRunner(
            'h0', host_root=str(tmp_path / 'h0'))
        runner.run('true')
        result = gang.gang_launch([runner], [{}], 'echo quiet',
                                  str(tmp_path / 'logs'),
                                  poll_interval_s=0.05)
        assert result.success
        assert chaos.counters() == {}


class TestNoRawSleepLint:
    """No instrumented module may call ``time.sleep`` inside a loop:
    retry/poll cadence must go through the resilience helpers
    (resilience.sleep / Deadline.sleep / Backoff) so it stays
    deadline-bounded and jittered."""

    INSTRUMENTED = [
        'skypilot_tpu/utils/command_runner.py',
        'skypilot_tpu/agent/gang.py',
        'skypilot_tpu/backends/failover.py',
        'skypilot_tpu/jobs/controller.py',
        'skypilot_tpu/serve/replica_managers.py',
        'skypilot_tpu/provision/do/rest.py',
        'skypilot_tpu/provision/lambda_cloud/rest.py',
        'skypilot_tpu/utils/parallelism.py',
        'skypilot_tpu/utils/resilience.py',
    ]
    # resilience.py IS the choke point: its Deadline.sleep / module
    # sleep() wrappers are the two allowed raw-sleep call sites.
    ALLOWED = {('skypilot_tpu/utils/resilience.py', 'sleep')}

    @staticmethod
    def _raw_sleeps_in_loops(tree):
        """(lineno, enclosing-function) of every time.sleep inside a
        while/for body."""
        offenders = []

        def walk(node, in_loop, func):
            for child in ast.iter_child_nodes(node):
                child_func = func
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef)):
                    child_func = child.name
                child_in_loop = in_loop or isinstance(
                    child, (ast.While, ast.For, ast.AsyncFor))
                if (child_in_loop and isinstance(child, ast.Call) and
                        isinstance(child.func, ast.Attribute) and
                        child.func.attr == 'sleep' and
                        isinstance(child.func.value, ast.Name) and
                        child.func.value.id == 'time'):
                    offenders.append((child.lineno, child_func))
                walk(child, child_in_loop, child_func)

        walk(tree, False, None)
        return offenders

    def test_instrumented_modules_use_resilience_helpers(self):
        repo_root = os.path.join(os.path.dirname(__file__), '..', '..')
        violations = []
        for rel in self.INSTRUMENTED:
            path = os.path.join(repo_root, rel)
            with open(path, encoding='utf-8') as f:
                tree = ast.parse(f.read(), filename=rel)
            for lineno, func in self._raw_sleeps_in_loops(tree):
                if (rel, func) in self.ALLOWED:
                    continue
                violations.append(f'{rel}:{lineno} (in {func})')
        assert not violations, (
            'raw time.sleep in a retry/poll loop — use '
            'resilience.sleep/Deadline/Backoff instead:\n  ' +
            '\n  '.join(violations))

    def test_lint_catches_a_raw_sleep(self):
        """The lint itself works: a synthetic retry loop is flagged."""
        tree = ast.parse(
            'import time\n'
            'def poll():\n'
            '    while True:\n'
            '        time.sleep(1)\n')
        assert self._raw_sleeps_in_loops(tree) == [(4, 'poll')]
        clean = ast.parse('import time\ntime.sleep(1)\n')   # not a loop
        assert self._raw_sleeps_in_loops(clean) == []


class TestNoSequentialRunnerLoopLint:
    """Control-plane code must not fan per-host work out with a
    sequential ``for ... in ...runners...`` loop: every such loop is
    O(num_hosts) launch latency at pod scale. Host fan-out goes
    through ``parallelism.run_in_parallel`` (bounded concurrency,
    aggregated MultiHostError, deadline, chaos point, trace events).

    The lint flags any ``for`` loop in ``backends/`` or ``serve/``
    whose iterable mentions a ``runners`` collection and whose body
    calls ``<runner>.run`` / ``<runner>.rsync`` / ``<runner>.run_async``
    directly."""

    SCANNED_DIRS = ['skypilot_tpu/backends', 'skypilot_tpu/serve']
    RUNNER_OPS = {'run', 'rsync', 'run_async'}

    @classmethod
    def _sequential_runner_loops(cls, tree):
        """(lineno, op) of every for-loop over a runners collection
        whose body drives a runner method directly."""
        offenders = []
        for node in ast.walk(tree):
            if not isinstance(node, (ast.For, ast.AsyncFor)):
                continue
            iter_names = set()
            for sub in ast.walk(node.iter):
                if isinstance(sub, ast.Name):
                    iter_names.add(sub.id)
                elif isinstance(sub, ast.Attribute):
                    iter_names.add(sub.attr)
            if not any('runners' in name.lower()
                       for name in iter_names):
                continue
            for stmt in node.body:
                for sub in ast.walk(stmt):
                    if (isinstance(sub, ast.Call) and
                            isinstance(sub.func, ast.Attribute) and
                            sub.func.attr in cls.RUNNER_OPS and
                            isinstance(sub.func.value, ast.Name) and
                            'runner' in sub.func.value.id.lower()):
                        offenders.append((sub.lineno, sub.func.attr))
        return offenders

    def test_no_sequential_runner_loops_in_control_plane(self):
        repo_root = os.path.join(os.path.dirname(__file__), '..', '..')
        violations = []
        for rel_dir in self.SCANNED_DIRS:
            abs_dir = os.path.join(repo_root, rel_dir)
            for dirpath, _, filenames in os.walk(abs_dir):
                for fname in sorted(filenames):
                    if not fname.endswith('.py'):
                        continue
                    path = os.path.join(dirpath, fname)
                    rel = os.path.relpath(path, repo_root)
                    with open(path, encoding='utf-8') as f:
                        tree = ast.parse(f.read(), filename=rel)
                    violations.extend(
                        f'{rel}:{line} (runner.{op})'
                        for line, op in
                        self._sequential_runner_loops(tree))
        assert not violations, (
            'sequential per-host runner loop — use '
            'parallelism.run_in_parallel for host fan-out:\n  ' +
            '\n  '.join(violations))

    def test_lint_catches_a_sequential_runner_loop(self):
        tree = ast.parse(
            'def setup(runners):\n'
            '    for rank, runner in enumerate(runners):\n'
            '        runner.run("true")\n')
        assert self._sequential_runner_loops(tree) == [(3, 'run')]
        # Fan-out through the primitive (runner driven inside a helper
        # fn, not a for-body) passes.
        clean = ast.parse(
            'def setup(runners):\n'
            '    def _one(pair):\n'
            '        rank, runner = pair\n'
            '        runner.run("true")\n'
            '    run_in_parallel(_one, list(enumerate(runners)))\n')
        assert self._sequential_runner_loops(clean) == []
        # A loop over something else entirely is not flagged.
        other = ast.parse(
            'for job_id in job_ids:\n'
            '    head.run(str(job_id))\n')
        assert self._sequential_runner_loops(other) == []


class TestLeaseHeartbeatLint:
    """Every lease-holding module's long-lived loop must renew its
    liveness lease: a loop that spins without heartbeating looks dead
    to the reconciler after one TTL and gets its scope 'repaired' out
    from under it. The list below names the loops that hold leases;
    each must contain a call whose name mentions ``heartbeat``."""

    REQUIRED = [
        # jobs controller: monitor loop (scope job/<id>)
        ('skypilot_tpu/jobs/controller.py', '_run_task'),
        # controller queued for a launch slot still holds its lease
        ('skypilot_tpu/jobs/scheduler.py', 'acquire_launch_slot'),
        # serve controller: autoscaler tick loop (scope service/<name>)
        ('skypilot_tpu/serve/controller.py', 'run'),
        # API-server watchdog renews every in-flight request lease
        ('skypilot_tpu/server/executor.py', '_watchdog'),
    ]

    @staticmethod
    def _loops_missing_heartbeat(tree, func_name):
        """Line numbers of OUTERMOST while/for loops inside
        `func_name` whose body (nested loops included) never calls a
        *heartbeat* helper. Returns None when the function has no loop
        at all (itself a lint failure: the listed functions are
        long-lived loops by contract)."""

        def has_heartbeat(node):
            for child in ast.walk(node):
                if not isinstance(child, ast.Call):
                    continue
                func = child.func
                name = func.attr if isinstance(func, ast.Attribute) \
                    else getattr(func, 'id', '')
                if 'heartbeat' in (name or ''):
                    return True
            return False

        def outer_loops(node):
            loops = []
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.While, ast.For)):
                    loops.append(child)   # nested loops ride along
                else:
                    loops.extend(outer_loops(child))
            return loops

        found_func = False
        offenders = []
        saw_loop = False
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef,
                                 ast.AsyncFunctionDef)) and \
                    node.name == func_name:
                found_func = True
                for loop in outer_loops(node):
                    saw_loop = True
                    if not has_heartbeat(loop):
                        offenders.append(loop.lineno)
        assert found_func, f'lint list is stale: no function {func_name}'
        return None if not saw_loop else offenders

    def test_lease_holding_loops_heartbeat(self):
        repo_root = os.path.join(os.path.dirname(__file__), '..', '..')
        violations = []
        for rel, func in self.REQUIRED:
            path = os.path.join(repo_root, rel)
            with open(path, encoding='utf-8') as f:
                tree = ast.parse(f.read(), filename=rel)
            missing = self._loops_missing_heartbeat(tree, func)
            if missing is None:
                violations.append(f'{rel}:{func} has no loop (stale '
                                  'lint list?)')
            else:
                violations.extend(f'{rel}:{line} (in {func})'
                                  for line in missing)
        assert not violations, (
            'long-lived loop in a lease-holding module never calls a '
            'heartbeat helper — the reconciler will declare it dead '
            'after one TTL:\n  ' + '\n  '.join(violations))

    def test_lint_catches_a_heartbeatless_loop(self):
        tree = ast.parse(
            'def run(self):\n'
            '    while True:\n'
            '        self.tick()\n')
        assert self._loops_missing_heartbeat(tree, 'run') == [2]
        clean = ast.parse(
            'def run(self):\n'
            '    while True:\n'
            '        self._heartbeat()\n'
            '        self.tick()\n')
        assert self._loops_missing_heartbeat(clean, 'run') == []


class TestTelemetryStalenessLint:
    """Every loop that polls rank/job state must consult workload
    telemetry (heartbeat staleness) — a poll loop that only watches
    the job status can't tell a hung rank from a slow one and degrades
    to raw time-based hang guesses. The listed functions are the
    rank-state poll loops; each loop must contain a call whose name
    mentions ``telemetry``."""

    REQUIRED = [
        # jobs controller monitor loop: stall verdicts feed recovery.
        ('skypilot_tpu/jobs/controller.py', '_run_task'),
        # backend launch-wait loop: records samples for `xsky top`.
        ('skypilot_tpu/backends/tpu_gang_backend.py', '_wait_job'),
    ]

    @staticmethod
    def _loops_missing_telemetry(tree, func_name):
        """Line numbers of OUTERMOST while/for loops inside `func_name`
        whose body never calls a *telemetry* helper; None when the
        function has no loop at all (stale lint list)."""

        def consults_telemetry(node):
            for child in ast.walk(node):
                if not isinstance(child, ast.Call):
                    continue
                func = child.func
                name = func.attr if isinstance(func, ast.Attribute) \
                    else getattr(func, 'id', '')
                if 'telemetry' in (name or ''):
                    return True
            return False

        def outer_loops(node):
            loops = []
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.While, ast.For)):
                    loops.append(child)
                else:
                    loops.extend(outer_loops(child))
            return loops

        found_func = False
        saw_loop = False
        offenders = []
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef,
                                 ast.AsyncFunctionDef)) and \
                    node.name == func_name:
                found_func = True
                for loop in outer_loops(node):
                    saw_loop = True
                    if not consults_telemetry(loop):
                        offenders.append(loop.lineno)
        assert found_func, f'lint list is stale: no function {func_name}'
        return None if not saw_loop else offenders

    def test_rank_state_poll_loops_consult_telemetry(self):
        repo_root = os.path.join(os.path.dirname(__file__), '..', '..')
        violations = []
        for rel, func in self.REQUIRED:
            path = os.path.join(repo_root, rel)
            with open(path, encoding='utf-8') as f:
                tree = ast.parse(f.read(), filename=rel)
            missing = self._loops_missing_telemetry(tree, func)
            if missing is None:
                violations.append(f'{rel}:{func} has no loop (stale '
                                  'lint list?)')
            else:
                violations.extend(f'{rel}:{line} (in {func})'
                                  for line in missing)
        assert not violations, (
            'rank-state poll loop never consults workload telemetry — '
            'heartbeat staleness, not raw time-based guesses, decides '
            'whether a rank hung:\n  ' + '\n  '.join(violations))

    def test_lint_catches_a_telemetry_blind_loop(self):
        blind = ast.parse(
            'def _run_task(self):\n'
            '    while True:\n'
            '        self._job_status()\n')
        assert self._loops_missing_telemetry(blind, '_run_task') == [2]
        clean = ast.parse(
            'def _run_task(self):\n'
            '    while True:\n'
            '        self._check_workload_telemetry()\n')
        assert self._loops_missing_telemetry(clean, '_run_task') == []


class TestTelemetryRetentionLint:
    """Every observability table in state.py must declare a retention
    bound: these tables take one row per poll/span/event forever, and
    an unbounded one turns the shared state DB into the outage. A
    bounded table needs (a) a module-level ``_MAX_*`` constant and (b)
    a ``DELETE FROM <table>`` prune referencing it."""

    # table → its retention constant. A NEW observability table must be
    # added here (and the lint below fails if it is created without a
    # bound).
    BOUNDED = {
        'recovery_events': '_MAX_RECOVERY_EVENTS',
        'spans': '_MAX_SPANS',
        'workload_telemetry': '_MAX_WORKLOAD_TELEMETRY',
        'profiles': '_MAX_PROFILES',
    }
    # CREATE TABLE names matching this are observability tables.
    OBSERVABILITY_RE = re.compile(r'events|spans|telemetry|profiles')
    CREATE_RE = re.compile(r'CREATE TABLE IF NOT EXISTS (\w+)')

    @classmethod
    def _check_source(cls, source):
        """Violation strings for a state.py-shaped module source."""
        violations = []
        tables = set(cls.CREATE_RE.findall(source))
        for table in sorted(tables):
            if not cls.OBSERVABILITY_RE.search(table):
                continue
            if table not in cls.BOUNDED:
                violations.append(
                    f'table {table} looks like an observability table '
                    'but declares no retention bound (add it to '
                    'BOUNDED + a _MAX_* prune)')
                continue
            if f'DELETE FROM {table}' not in source:
                violations.append(
                    f'table {table} has no DELETE FROM prune')
        tree = ast.parse(source)
        constants = {
            t.id: node.value.value
            for node in tree.body if isinstance(node, ast.Assign)
            for t in node.targets if isinstance(t, ast.Name)
            and isinstance(node.value, ast.Constant)
        }
        for table, const in cls.BOUNDED.items():
            if table not in tables:
                continue
            value = constants.get(const)
            if not isinstance(value, int) or value <= 0:
                violations.append(
                    f'{const} (retention bound for {table}) is not a '
                    'positive module-level int constant')
        return violations

    def test_state_observability_tables_are_bounded(self):
        repo_root = os.path.join(os.path.dirname(__file__), '..', '..')
        path = os.path.join(repo_root, 'skypilot_tpu', 'state.py')
        with open(path, encoding='utf-8') as f:
            source = f.read()
        violations = self._check_source(source)
        assert not violations, (
            'unbounded observability table in state.py:\n  ' +
            '\n  '.join(violations))

    def test_lint_catches_an_unbounded_table(self):
        unbounded = (
            'CREATE = """CREATE TABLE IF NOT EXISTS foo_telemetry '
            '(x INT);"""\n')
        assert any('foo_telemetry' in v
                   for v in self._check_source(unbounded))
        # Profile tables are observability tables too.
        unbounded_profiles = (
            'CREATE = """CREATE TABLE IF NOT EXISTS gpu_profiles '
            '(x INT);"""\n')
        assert any('gpu_profiles' in v
                   for v in self._check_source(unbounded_profiles))
        bounded = (
            '_MAX_SPANS = 100\n'
            'CREATE = """CREATE TABLE IF NOT EXISTS spans (x INT);"""\n'
            'PRUNE = "DELETE FROM spans WHERE 1"\n')
        assert self._check_source(bounded) == []
        bad_const = (
            '_MAX_SPANS = None\n'
            'CREATE = """CREATE TABLE IF NOT EXISTS spans (x INT);"""\n'
            'PRUNE = "DELETE FROM spans WHERE 1"\n')
        assert any('_MAX_SPANS' in v
                   for v in self._check_source(bad_const))


class TestSpanCoverageLint:
    """Observability coverage lints: (1) every
    ``parallelism.run_in_parallel`` call site in the tree must execute
    under an active tracing span (a ``with tracing.span(...)`` block
    lexically enclosing the call, within the same function) — an
    untraced fan-out is invisible to `xsky trace` and to the
    `/metrics` phase histograms; (2) every failover retry loop (a
    loop driving ``_try_resources`` / ``_try_zone``) must likewise run
    under a span, so failed attempts land on the trace."""

    SKIPPED_FILES = {
        # The primitive's own definition site (it opens the
        # fanout.<phase> span internally).
        'skypilot_tpu/utils/parallelism.py',
    }
    RETRY_CALLEES = {'_try_resources', '_try_zone'}

    @staticmethod
    def _is_span_with(node):
        if not isinstance(node, ast.With):
            return False
        for item in node.items:
            expr = item.context_expr
            if isinstance(expr, ast.Call):
                func = expr.func
                name = func.attr if isinstance(func, ast.Attribute) \
                    else getattr(func, 'id', '')
                if 'span' in (name or ''):
                    return True
        return False

    @classmethod
    def _uncovered_fanout_calls(cls, tree):
        """Line numbers of run_in_parallel calls NOT lexically inside
        a span-With. Coverage resets at function boundaries: a nested
        def runs when called, not where a span happens to enclose its
        definition."""
        offenders = []

        def walk(node, covered):
            for child in ast.iter_child_nodes(node):
                child_covered = covered
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef)):
                    child_covered = False
                elif cls._is_span_with(child):
                    child_covered = True
                if (isinstance(child, ast.Call) and
                        isinstance(child.func, ast.Attribute) and
                        child.func.attr == 'run_in_parallel' and
                        not covered):
                    offenders.append(child.lineno)
                walk(child, child_covered)

        walk(tree, False)
        return offenders

    @classmethod
    def _uncovered_retry_loops(cls, tree):
        """Line numbers of failover retry loops (loops whose body
        calls a RETRY_CALLEES member) not under a span-With."""
        offenders = []

        def drives_retry(loop):
            for sub in ast.walk(loop):
                if isinstance(sub, ast.Call):
                    func = sub.func
                    name = func.attr if isinstance(func, ast.Attribute) \
                        else getattr(func, 'id', '')
                    if name in cls.RETRY_CALLEES:
                        return True
            return False

        def walk(node, covered):
            for child in ast.iter_child_nodes(node):
                child_covered = covered
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef)):
                    child_covered = False
                elif cls._is_span_with(child):
                    child_covered = True
                if (isinstance(child, (ast.For, ast.While)) and
                        not covered and drives_retry(child)):
                    offenders.append(child.lineno)
                walk(child, child_covered)

        walk(tree, False)
        return offenders

    def test_every_fanout_call_site_runs_under_a_span(self):
        repo_root = os.path.join(os.path.dirname(__file__), '..', '..')
        pkg_root = os.path.join(repo_root, 'skypilot_tpu')
        violations = []
        for dirpath, _, filenames in os.walk(pkg_root):
            for fname in sorted(filenames):
                if not fname.endswith('.py'):
                    continue
                path = os.path.join(dirpath, fname)
                rel = os.path.relpath(path, repo_root)
                if rel in self.SKIPPED_FILES:
                    continue
                with open(path, encoding='utf-8') as f:
                    tree = ast.parse(f.read(), filename=rel)
                violations.extend(
                    f'{rel}:{line}'
                    for line in self._uncovered_fanout_calls(tree))
        assert not violations, (
            'run_in_parallel call site outside a tracing span — wrap '
            'it in `with tracing.span(...)` so the fan-out lands on '
            'the trace:\n  ' + '\n  '.join(violations))

    def test_failover_retry_loops_run_under_a_span(self):
        repo_root = os.path.join(os.path.dirname(__file__), '..', '..')
        path = os.path.join(repo_root,
                            'skypilot_tpu/backends/failover.py')
        with open(path, encoding='utf-8') as f:
            tree = ast.parse(f.read(), filename='failover.py')
        missing = self._uncovered_retry_loops(tree)
        assert not missing, (
            'failover retry loop outside a tracing span (lines '
            f'{missing}) — failed attempts must land on the trace.')

    def test_lint_catches_an_uncovered_fanout_call(self):
        bad = ast.parse(
            'def setup(runners):\n'
            '    parallelism.run_in_parallel(f, runners)\n')
        assert self._uncovered_fanout_calls(bad) == [2]
        clean = ast.parse(
            'def setup(runners):\n'
            '    with tracing.span("setup"):\n'
            '        parallelism.run_in_parallel(f, runners)\n')
        assert self._uncovered_fanout_calls(clean) == []
        # A span enclosing only the DEFINITION of a nested function
        # does not cover calls inside it.
        leaky = ast.parse(
            'def outer():\n'
            '    with tracing.span("outer"):\n'
            '        def inner():\n'
            '            parallelism.run_in_parallel(f, [])\n'
            '        inner()\n')
        assert self._uncovered_fanout_calls(leaky) == [4]

    def test_lint_catches_an_uncovered_retry_loop(self):
        bad = ast.parse(
            'def provision(self):\n'
            '    for _ in range(3):\n'
            '        self._try_resources(r)\n')
        assert self._uncovered_retry_loops(bad) == [2]
        clean = ast.parse(
            'def provision(self):\n'
            '    with tracing.span("failover.provision"):\n'
            '        for _ in range(3):\n'
            '            self._try_resources(r)\n')
        assert self._uncovered_retry_loops(clean) == []


class TestProfilerSpanLint:
    """Every profiler capture/pull site must run under a tracing span:
    a deep capture fans out a device probe to every host (expensive,
    operator-triggered — it must land on the trace), and profile
    recording rides the telemetry pull whose latency `xsky trace`
    attributes. Calls to the profiler-plane entry points
    (``capture_device_profile``, ``record_profiles``) anywhere in the
    tree must be lexically inside a ``with tracing.span(...)`` block,
    same contract as the fan-out span lint."""

    SKIPPED_FILES = {
        # The plane's own definition site (record_profiles delegates
        # to state.record_profiles internally; callers hold the span).
        'skypilot_tpu/agent/profiler.py',
    }
    PROFILER_SITES = {'capture_device_profile', 'record_profiles'}

    @classmethod
    def _uncovered_profiler_calls(cls, tree):
        """Line numbers of profiler capture/pull calls NOT lexically
        inside a span-With (function boundaries reset coverage, same
        as the fan-out lint)."""
        is_span_with = TestSpanCoverageLint._is_span_with
        offenders = []

        def walk(node, covered):
            for child in ast.iter_child_nodes(node):
                child_covered = covered
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef)):
                    child_covered = False
                elif is_span_with(child):
                    child_covered = True
                if (isinstance(child, ast.Call) and
                        isinstance(child.func, ast.Attribute) and
                        child.func.attr in cls.PROFILER_SITES and
                        not covered):
                    offenders.append(child.lineno)
                walk(child, child_covered)

        walk(tree, False)
        return offenders

    def test_every_profiler_site_runs_under_a_span(self):
        repo_root = os.path.join(os.path.dirname(__file__), '..', '..')
        pkg_root = os.path.join(repo_root, 'skypilot_tpu')
        violations = []
        for dirpath, _, filenames in os.walk(pkg_root):
            for fname in sorted(filenames):
                if not fname.endswith('.py'):
                    continue
                path = os.path.join(dirpath, fname)
                rel = os.path.relpath(path, repo_root)
                if rel in self.SKIPPED_FILES:
                    continue
                with open(path, encoding='utf-8') as f:
                    tree = ast.parse(f.read(), filename=rel)
                violations.extend(
                    f'{rel}:{line}'
                    for line in self._uncovered_profiler_calls(tree))
        assert not violations, (
            'profiler capture/pull site outside a tracing span — wrap '
            'it in `with tracing.span(...)` so the capture/pull lands '
            'on the trace:\n  ' + '\n  '.join(violations))

    def test_lint_catches_an_uncovered_profiler_site(self):
        bad = ast.parse(
            'def cap(backend, handle):\n'
            '    backend.capture_device_profile(handle)\n')
        assert self._uncovered_profiler_calls(bad) == [2]
        bad_pull = ast.parse(
            'def pull(cluster, samples):\n'
            '    profiler.record_profiles(cluster, 1, samples)\n')
        assert self._uncovered_profiler_calls(bad_pull) == [2]
        clean = ast.parse(
            'def cap(backend, handle):\n'
            '    with tracing.span("profile.capture"):\n'
            '        backend.capture_device_profile(handle)\n')
        assert self._uncovered_profiler_calls(clean) == []


class TestListingLimitLint:
    """Every listing function (``.fetchall()`` over a SELECT) in the
    shared state modules must page — carry a ``LIMIT`` in its SQL — or
    declare why a full scan is safe with a ``# full-scan ok:`` comment
    naming the bound. The state DB serves a 5k-cluster fleet at QPS:
    an unpaged listing added casually is the next `status` full-scan
    regression (see docs/performance.md, control-plane scale)."""

    MODULES = [
        'skypilot_tpu/state.py',
        'skypilot_tpu/server/requests_db.py',
    ]
    EXEMPT_MARK = '# full-scan ok'

    # Calls that mark a function as a multi-row listing: a direct
    # cursor fetchall, or the state modules' _read()/fetchall facade
    # (every listing in state.py/requests_db.py routes through it —
    # a fetchall-only lint would inspect zero functions there).
    LISTING_CALLS = {'fetchall', '_read'}

    @classmethod
    def _unpaged_listing_functions(cls, source):
        """(name, lineno) of module-level functions that run a
        multi-row SELECT with no LIMIT and no declared full-scan
        exemption."""
        tree = ast.parse(source)
        lines = source.splitlines()
        offenders = []
        for node in tree.body:
            if not isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                continue
            if node.name in cls.LISTING_CALLS:
                continue   # the facade's own definition
            is_listing = False
            sql_chunks = []
            for child in ast.walk(node):
                if isinstance(child, ast.Call):
                    func = child.func
                    name = func.attr if isinstance(func, ast.Attribute) \
                        else getattr(func, 'id', '')
                    if name in cls.LISTING_CALLS:
                        is_listing = True
                if isinstance(child, ast.Constant) and \
                        isinstance(child.value, str):
                    sql_chunks.append(child.value)
            sql = ' '.join(sql_chunks)
            # Both tokens: a docstring mentioning SELECT (the _read
            # helper's contract) is not a query.
            if not is_listing or 'SELECT' not in sql \
                    or 'FROM' not in sql:
                continue
            # _page_sql() appends the LIMIT clause at runtime; its
            # presence in the function body counts as paged.
            calls_page_sql = any(
                isinstance(child, ast.Call) and (
                    getattr(child.func, 'id', '') == '_page_sql' or
                    getattr(child.func, 'attr', '') == '_page_sql')
                for child in ast.walk(node))
            body_src = '\n'.join(
                lines[node.lineno - 1:node.end_lineno])
            if ('LIMIT' in sql or calls_page_sql or
                    cls.EXEMPT_MARK in body_src):
                continue
            offenders.append((node.name, node.lineno))
        return offenders

    def test_state_listing_functions_are_paged_or_exempt(self):
        repo_root = os.path.join(os.path.dirname(__file__), '..', '..')
        violations = []
        for rel in self.MODULES:
            with open(os.path.join(repo_root, rel),
                      encoding='utf-8') as f:
                source = f.read()
            violations.extend(
                f'{rel}:{line} ({name})'
                for name, line in
                self._unpaged_listing_functions(source))
        assert not violations, (
            'SELECT listing without a LIMIT (or a `# full-scan ok:` '
            'exemption naming the bound) — unpaged listings are how '
            'status full-scans come back:\n  ' + '\n  '.join(violations))

    def test_lint_catches_an_unpaged_listing(self):
        bad = ('def list_things(conn):\n'
               "    return conn.execute('SELECT x FROM t').fetchall()\n")
        assert self._unpaged_listing_functions(bad) == \
            [('list_things', 1)]
        # The facade form the state modules actually use is covered
        # too (a fetchall-only lint would miss every one of them).
        bad_facade = ('def list_things():\n'
                      "    return _read('SELECT x FROM t')\n")
        assert self._unpaged_listing_functions(bad_facade) == \
            [('list_things', 1)]
        paged = ('def list_things(conn):\n'
                 "    return conn.execute('SELECT x FROM t LIMIT 5')"
                 '.fetchall()\n')
        assert self._unpaged_listing_functions(paged) == []
        helper = ('def list_things(conn):\n'
                  "    q = 'SELECT x FROM t' + _page_sql(None)\n"
                  '    return conn.execute(q).fetchall()\n')
        assert self._unpaged_listing_functions(helper) == []
        exempt = ('def list_things(conn):\n'
                  '    # full-scan ok: one row per enabled cloud.\n'
                  "    return conn.execute('SELECT x FROM t')"
                  '.fetchall()\n')
        assert self._unpaged_listing_functions(exempt) == []
        point = ('def get_thing(conn):\n'
                 "    return conn.execute('SELECT x FROM t')"
                 '.fetchone()\n')
        assert self._unpaged_listing_functions(point) == []


class TestChaosSmoke:
    """The acceptance scenario, deterministic and hermetic (tier-1):
    a seeded plan injects (a) an rc-255 SSH drop on a gang host during
    fan-out, (b) a hung status probe, and (c) one mid-run preemption —
    the managed job must recover end-to-end and the journal must hold
    the full fault→recovery timeline."""

    STORM_PLAN = {
        'seed': 7,
        'points': {
            # (a) First host start of the run fan-out dies like a
            # dropped SSH transport; the gang launcher retries it.
            'gang.host_start': {'first_n': 1, 'returncode': 255},
            # (b) The third status probe hangs briefly, then errors.
            'jobs.status_probe': {'skip_first': 2, 'first_n': 1,
                                  'latency_s': 0.05,
                                  'error': 'TimeoutError'},
            # (c) The probe failure makes the controller consult cloud
            # truth — the first such query preempts the cluster
            # out-of-band (the fake cloud acting as a chaotic provider).
            'fake.preempt': {'first_n': 1},
        },
    }

    def test_preemption_storm_recovers_end_to_end(
            self, fake_cluster_env, monkeypatch, tmp_path):
        del fake_cluster_env
        from skypilot_tpu import Resources, Task
        from skypilot_tpu import state as state_lib
        from skypilot_tpu.jobs import controller as controller_lib
        from skypilot_tpu.jobs import scheduler as jobs_scheduler
        from skypilot_tpu.jobs import state as jobs_state

        monkeypatch.setenv('XSKY_JOBS_DB',
                           str(tmp_path / 'managed_jobs.db'))
        monkeypatch.setenv('XSKY_JOBS_LOG_DIR', str(tmp_path / 'jlogs'))
        # The env var is read at module import, which may predate this
        # test — pin the attribute so the third probe lands while the
        # sleep-1 task is still running.
        monkeypatch.setattr(controller_lib, 'POLL_INTERVAL_S', 0.2)
        plan_file = tmp_path / 'storm.json'
        plan_file.write_text(json.dumps(self.STORM_PLAN))
        # Via the env var (not load_plan) so the whole process tree —
        # the job_runner on the fake head host included — sees the plan.
        monkeypatch.setenv('XSKY_CHAOS_PLAN', str(plan_file))

        # Long enough that the third probe (the injected failure) always
        # lands while the task is still mid-run, even on a loaded box.
        task = Task('storm', run='sleep 3; echo storm-ok')
        task.set_resources(Resources(accelerators='tpu-v5e-8',
                                     use_spot=True))
        job_id = jobs_state.add_job('storm', Task.chain_to_config([task]))
        jobs_state.set_status(job_id,
                              jobs_state.ManagedJobStatus.SUBMITTED)
        # Run the controller in-process (the scheduler would exec it as
        # a subprocess): deterministic, and the controller-side chaos
        # hit counters stay visible to the test.
        jobs_state.set_schedule_state(job_id,
                                      jobs_state.ScheduleState.LAUNCHING)
        # Claim the controller slot for THIS process, or the scheduler's
        # dead-controller reconciler (pid None ≙ dead) would re-exec a
        # competing subprocess controller mid-test.
        jobs_state.set_controller_pid(job_id, os.getpid())
        try:
            controller_lib.JobsController(job_id).run()
        finally:
            jobs_scheduler.job_done(job_id)

        record = jobs_state.get_job(job_id)
        assert record['status'] == \
            jobs_state.ManagedJobStatus.SUCCEEDED, record
        assert record['recovery_count'] >= 1

        # Every injected fault is journalled with its point as scope...
        injected = {r['scope'] for r in state_lib.get_recovery_events(
            event_type='chaos.injected')}
        assert 'chaos/jobs.status_probe' in injected
        assert 'chaos/fake.preempt' in injected
        # (the gang.host_start row is written by the job_runner process
        # on the fake head host — cross-process via the shared state DB)
        assert 'chaos/gang.host_start' in injected

        # ...and the preemption→recovery story is one readable timeline
        # with a measured recovery latency.
        job_events = state_lib.get_recovery_events(scope=f'job/{job_id}')
        types = [r['event_type'] for r in job_events]
        assert 'job.preempted' in types
        assert 'job.recovered' in types
        recovered = job_events[types.index('job.recovered')]
        assert recovered['latency_s'] is not None
        assert recovered['latency_s'] > 0
        assert job_events[types.index('job.preempted')]['cause']

        # Controller-side points were traversed in this process.
        assert chaos.hits('jobs.status_probe') >= 3
        assert chaos.hits('fake.preempt') >= 1
