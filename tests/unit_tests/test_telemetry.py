"""Workload-telemetry tests: spool round-trip + heartbeat thread, EMA
math, stall/dead verdicts, bounded-table retention, goodput against a
synthetic recovery journal, the `telemetry.stall` chaos point, the
`xsky top` / `xsky status` / `/metrics` surfaces, and the tier-1
fake-cloud smoke where a chaos-stalled rank is detected and triggers a
journalled, trace-linked recovery."""
import json
import os
import sys
import time

import pytest

from skypilot_tpu.agent import telemetry
from skypilot_tpu.utils import chaos
from skypilot_tpu.utils import metrics as metrics_lib

REPO_ROOT = os.path.abspath(
    os.path.join(os.path.dirname(__file__), '..', '..'))


@pytest.fixture(autouse=True)
def _clean_telemetry(monkeypatch):
    monkeypatch.delenv(telemetry.ENV_DIR, raising=False)
    telemetry.reset_for_test()
    chaos.clear()
    yield
    telemetry.reset_for_test()
    chaos.clear()


@pytest.fixture
def spool(monkeypatch, tmp_path):
    d = tmp_path / 'spool'
    monkeypatch.setenv(telemetry.ENV_DIR, str(d))
    monkeypatch.setenv(telemetry.ENV_RANK, '0')
    # Interval 0: every emit writes, so reads see the sample
    # immediately (production default is 2 s, interval-driven — the
    # <2% gate in tools/bench_telemetry.py depends on that).
    monkeypatch.setenv(telemetry.ENV_INTERVAL, '0')
    return d


@pytest.fixture
def tmp_state(monkeypatch, tmp_path):
    from skypilot_tpu import state
    monkeypatch.setenv('XSKY_STATE_DB', str(tmp_path / 'state.db'))
    state.reset_for_test()
    yield state
    state.reset_for_test()


class TestSpool:

    def test_emit_round_trip(self, spool):
        telemetry.emit(phase=telemetry.PHASE_INIT)
        telemetry.emit(phase=telemetry.PHASE_STEP, step=1,
                       step_time_s=0.1, tokens_per_sec=100.0)
        samples = telemetry.read_spool(str(spool))
        assert set(samples) == {0}
        s = samples[0]
        assert s['phase'] == 'step'
        assert s['step'] == 1
        assert s['pid'] == os.getpid()
        assert s['step_time_ema_s'] == pytest.approx(0.1)
        assert s['tokens_per_sec'] == pytest.approx(100.0)
        assert s['hb_ts'] >= s['started_ts']
        assert s['last_progress_ts'] > 0

    def test_emit_without_spool_dir_is_noop(self, tmp_path):
        assert telemetry.ENV_DIR not in os.environ
        telemetry.emit(phase='step', step=1)
        assert telemetry.read_spool(str(tmp_path)) == {}

    def test_emit_never_raises(self, monkeypatch, tmp_path):
        # Spool dir path collides with an existing FILE: every write
        # fails — emit must swallow it (it sits on the step loop).
        blocker = tmp_path / 'blocker'
        blocker.write_text('x')
        monkeypatch.setenv(telemetry.ENV_DIR, str(blocker / 'sub'))
        telemetry.emit(phase='step', step=1)   # must not raise

    def test_ema_step_time(self, spool):
        telemetry.emit(step=1, step_time_s=1.0)
        telemetry.emit(step=2, step_time_s=2.0)
        s = telemetry.read_spool(str(spool))[0]
        expected = telemetry.ema(1.0, 2.0)
        assert s['step_time_ema_s'] == pytest.approx(expected)

    def test_heartbeat_thread_beats_without_progress(
            self, spool, monkeypatch):
        monkeypatch.setenv(telemetry.ENV_INTERVAL, '0.05')
        telemetry.emit(phase='step', step=5)
        first = telemetry.read_spool(str(spool))[0]
        time.sleep(0.3)
        later = telemetry.read_spool(str(spool))[0]
        # The heartbeat advanced on its own thread...
        assert later['hb_ts'] > first['hb_ts']
        # ...while progress stayed frozen (no new emit).
        assert later['step'] == 5
        assert later['last_progress_ts'] == \
            pytest.approx(first['last_progress_ts'])

    def test_chaos_stall_freezes_progress_not_heartbeat(
            self, spool, monkeypatch):
        monkeypatch.setenv(telemetry.ENV_INTERVAL, '0.05')
        chaos.load_plan({'points': {
            'telemetry.stall': {'match': {'rank': 0},
                                'skip_first': 1}}})
        telemetry.emit(phase='step', step=1)
        telemetry.emit(phase='step', step=2)   # frozen by chaos
        s = telemetry.read_spool(str(spool))[0]
        assert s['step'] == 1
        assert chaos.hits('telemetry.stall') == 2
        time.sleep(0.15)
        later = telemetry.read_spool(str(spool))[0]
        assert later['step'] == 1               # still frozen
        assert later['hb_ts'] > s['hb_ts']      # still alive

    def test_tokens_increments_become_a_rate(self, spool, monkeypatch):
        monkeypatch.setenv(telemetry.ENV_INTERVAL, '0.05')
        telemetry.emit(phase='step', step=1, tokens=50)
        time.sleep(0.1)
        telemetry.emit(phase='step', step=2, tokens=50)
        s = telemetry.read_spool(str(spool))[0]
        assert s['tokens_per_sec'] is not None
        assert s['tokens_per_sec'] > 0


class TestVerdicts:

    def _sample(self, now, hb_age=0.0, progress_age=0.0, phase='step'):
        return {'hb_ts': now - hb_age,
                'last_progress_ts': now - progress_age,
                'started_ts': now - 100,
                'phase': phase}

    def test_ema_seed_and_decay(self):
        assert telemetry.ema(None, 3.0) == 3.0
        assert telemetry.ema(1.0, 2.0) == pytest.approx(
            telemetry.EMA_ALPHA * 2.0 + (1 - telemetry.EMA_ALPHA) * 1.0)

    def test_ok_hung_dead(self):
        now = time.time()
        ok = self._sample(now)
        hung = self._sample(now, hb_age=1.0, progress_age=500.0)
        dead = self._sample(now, hb_age=500.0)
        assert telemetry.verdict(ok, now) == 'ok'
        assert telemetry.verdict(hung, now) == 'hung'
        assert telemetry.verdict(dead, now) == 'dead'
        assert telemetry.verdict(None, now) == 'dead'
        # dead outranks hung: a stale heartbeat implies stale progress.
        both = self._sample(now, hb_age=500.0, progress_age=500.0)
        assert telemetry.verdict(both, now) == 'dead'

    def test_thresholds_from_env(self, monkeypatch):
        now = time.time()
        s = self._sample(now, hb_age=1.0, progress_age=3.0)
        assert telemetry.verdict(s, now) == 'ok'
        monkeypatch.setenv(telemetry.ENV_PROGRESS_STALE, '1.5')
        assert telemetry.verdict(s, now) == 'hung'
        monkeypatch.setenv(telemetry.ENV_HB_STALE, '0.5')
        assert telemetry.verdict(s, now) == 'dead'

    def test_progress_staleness_is_clock_skew_free(self):
        """Hung detection compares last_progress_ts against the rank's
        OWN heartbeat timestamp (same host clock): a rank whose clock
        is far behind the control plane's must not read as hung."""
        now = time.time()
        skewed = {'hb_ts': now - 25, 'last_progress_ts': now - 26,
                  'started_ts': now - 100, 'phase': 'step'}
        # 25 s of skew on both fields: progress is 1 s behind the
        # heartbeat — healthy (hb itself stays within hb_stale).
        assert telemetry.verdict(skewed, now) == 'ok'

    def test_idle_phase_is_exempt_from_hung(self):
        """A declared-idle rank (serving replica with no traffic) is
        not a hang, no matter how stale its progress."""
        now = time.time()
        idle = self._sample(now, hb_age=1.0, progress_age=10_000,
                            phase='idle')
        assert telemetry.verdict(idle, now) == 'ok'
        # ...but a dead idle rank is still dead.
        gone = self._sample(now, hb_age=10_000, phase='idle')
        assert telemetry.verdict(gone, now) == 'dead'

    def test_stalled_filters_ok_ranks(self):
        now = time.time()
        samples = {0: self._sample(now),
                   1: self._sample(now, hb_age=1.0, progress_age=900.0)}
        assert telemetry.stalled(samples, now) == {1: 'hung'}

    def test_rank_skew_and_stragglers(self):
        samples = {r: {'step': 10 + r, 'step_time_ema_s': 0.1}
                   for r in range(4)}
        samples[3]['step'] = 4
        samples[3]['step_time_ema_s'] = 1.0
        assert telemetry.rank_skew(samples) == 8
        assert telemetry.stragglers(samples) == {3}
        # <3 reporting ranks: no meaningful median, no stragglers.
        assert telemetry.stragglers({0: samples[0], 3: samples[3]}) \
            == set()
        assert telemetry.rank_skew({0: {'step': None}}) is None


class TestGoodput:

    def test_productive_over_wall(self):
        now = time.time()
        samples = {0: {'step': 100, 'step_time_ema_s': 0.5,
                       'started_ts': now - 100}}
        g = telemetry.goodput(samples, now=now)
        assert g['productive_s'] == pytest.approx(50.0)
        assert g['wall_s'] == pytest.approx(100.0, abs=1.0)
        assert g['goodput'] == pytest.approx(0.5, abs=0.02)

    def test_recovery_time_counts_against_goodput(self):
        now = time.time()
        samples = {0: {'step': 100, 'step_time_ema_s': 0.5,
                       'started_ts': now - 50}}
        g = telemetry.goodput(samples, recovery_s=50.0, now=now)
        assert g['wall_s'] == pytest.approx(100.0, abs=1.0)
        assert g['goodput'] == pytest.approx(0.5, abs=0.02)
        assert g['recovery_s'] == 50.0

    def test_no_samples_means_no_ratio(self):
        g = telemetry.goodput({}, now=time.time())
        assert g['goodput'] is None
        assert g['productive_s'] == 0.0

    def test_synthetic_journal_extends_wall(self, tmp_state):
        """goodput_for_cluster folds the recovery journal's measured
        latencies into wall time: a job that lost 60 s to recoveries
        gets charged for them."""
        now = time.time()
        tmp_state.record_recovery_event('job.recovered', scope='job/7',
                                        latency_s=40.0)
        tmp_state.record_recovery_event('job.restarted', scope='job/7',
                                        latency_s=20.0)
        tmp_state.record_recovery_event('job.preempted', scope='job/7')
        samples = {0: {'step': 100, 'step_time_ema_s': 0.4,
                       'started_ts': now - 40}}
        g = telemetry.goodput_for_cluster('xsky-jobs-7', samples,
                                          now=now)
        # productive 40s over (40s current incarnation + 60s recovery).
        assert g['recovery_s'] == pytest.approx(60.0)
        assert g['wall_s'] == pytest.approx(100.0, abs=1.0)
        assert g['goodput'] == pytest.approx(0.4, abs=0.02)
        # Unmanaged cluster names skip the journal entirely.
        g2 = telemetry.goodput_for_cluster('my-train', samples, now=now)
        assert g2['recovery_s'] == 0.0

    def test_lease_history_supplies_wall(self, tmp_state):
        """With a live lease (PR 2), wall time is the lease age — it
        survives relaunches, unlike the current incarnation's
        started_ts."""
        tmp_state.heartbeat_lease('job/9', owner='test', ttl_s=3600)
        now = time.time() + 200
        samples = {0: {'step': 100, 'step_time_ema_s': 1.0,
                       'started_ts': now - 10}}
        g = telemetry.goodput_for_cluster('xsky-jobs-9', samples,
                                          now=now)
        assert g['wall_s'] == pytest.approx(200.0, abs=2.0)
        assert g['goodput'] == pytest.approx(0.5, abs=0.02)


class TestStateTable:

    def _rows(self, n_ranks, step=1, verdict='ok'):
        return [{'rank': r, 'phase': 'step', 'step': step,
                 'step_time_ema_s': 0.1, 'tokens_per_sec': 10.0,
                 'host_mem_mb': 100.0, 'started_ts': 1.0,
                 'last_progress_ts': 2.0, 'hb_ts': 3.0,
                 'verdict': verdict} for r in range(n_ranks)]

    def test_round_trip_and_latest_only(self, tmp_state):
        tmp_state.record_workload_telemetry('c1', 1, self._rows(2),
                                            ts=100.0)
        tmp_state.record_workload_telemetry('c1', 1,
                                            self._rows(2, step=5),
                                            ts=200.0)
        tmp_state.record_workload_telemetry('c2', 1, self._rows(1),
                                            ts=150.0)
        latest = tmp_state.get_workload_telemetry()
        assert len(latest) == 3
        c1 = [r for r in latest if r['cluster'] == 'c1']
        assert all(r['ts'] == 200.0 and r['step'] == 5 for r in c1)
        only_c1 = tmp_state.get_workload_telemetry(cluster='c1')
        assert {r['rank'] for r in only_c1} == {0, 1}
        history = tmp_state.get_workload_telemetry(cluster='c1',
                                                   latest_only=False)
        assert len(history) == 4

    def test_retention_bound(self, tmp_state, monkeypatch):
        monkeypatch.setattr(tmp_state, '_MAX_WORKLOAD_TELEMETRY', 10)
        monkeypatch.setattr(tmp_state, '_workload_inserts', 0)
        tmp_state.record_workload_telemetry('c1', 1, self._rows(40))
        rows = tmp_state.get_workload_telemetry(latest_only=False,
                                                limit=1000)
        assert len(rows) == 10
        # Newest rows survive the prune.
        assert {r['rank'] for r in rows} == set(range(30, 40))

    def test_record_never_raises(self, tmp_state, monkeypatch):
        def _boom():
            raise RuntimeError('db down')

        monkeypatch.setattr(tmp_state, '_get_conn', _boom)
        tmp_state.record_workload_telemetry('c1', 1, self._rows(1))
        telemetry.record_samples('c1', 1, {0: {'hb_ts': time.time()}})


class TestRecordSamplesMetrics:

    def test_stall_counter_counts_transitions(self, tmp_state):
        metrics_lib.reset_for_test()
        now = time.time()
        hung = {0: {'hb_ts': now, 'last_progress_ts': now - 10_000,
                    'started_ts': now - 10_000}}
        verdicts = telemetry.record_samples('c1', 1, hung, now=now)
        assert verdicts == {0: 'hung'}
        telemetry.record_samples('c1', 1, hung, now=now)   # same state
        text = metrics_lib.render_registry()
        assert ('xsky_workload_rank_stalls_total{verdict="hung"} 1'
                in text)
        rows = tmp_state.get_workload_telemetry(cluster='c1')
        assert rows and rows[0]['verdict'] == 'hung'

    def test_step_histogram_on_progress(self, tmp_state):
        metrics_lib.reset_for_test()
        now = time.time()
        ok = {0: {'hb_ts': now, 'last_progress_ts': now,
                  'started_ts': now - 10, 'step': 3,
                  'step_time_ema_s': 0.2}}
        telemetry.record_samples('c1', 1, ok, now=now)
        text = metrics_lib.render_registry()
        assert 'xsky_workload_step_seconds_count 1' in text
        # Same step again: no new observation.
        telemetry.record_samples('c1', 1, ok, now=now)
        assert 'xsky_workload_step_seconds_count 1' in \
            metrics_lib.render_registry()

    def test_server_metrics_workload_gauges(self, tmp_state):
        from skypilot_tpu.server import metrics as server_metrics
        tmp_state.add_or_update_cluster('gauge-c', None)
        now = time.time()
        sample = {0: {'hb_ts': now - 2, 'last_progress_ts': now - 2,
                      'started_ts': now - 100, 'step': 50,
                      'step_time_ema_s': 1.0}}
        telemetry.record_samples('gauge-c', 1, sample, now=now)
        # A second job on the same cluster: per-(cluster,job,rank)
        # series, no duplicate sample lines.
        telemetry.record_samples('gauge-c', 2, sample, now=now + 1)
        text = server_metrics.render()
        assert ('xsky_workload_last_heartbeat_age_seconds{'
                'cluster="gauge-c",job="1",rank="0"}') in text
        assert ('xsky_workload_last_heartbeat_age_seconds{'
                'cluster="gauge-c",job="2",rank="0"}') in text
        # Goodput stays one series per cluster (newest job's samples).
        assert text.count('xsky_goodput_ratio{cluster="gauge-c"}') == 1

    def test_gauges_skip_torn_down_clusters(self, tmp_state):
        """Telemetry rows outlive their cluster (size-pruned, not
        liveness-pruned): /metrics must not export gauges — or grow
        label cardinality — for clusters that no longer exist."""
        from skypilot_tpu.server import metrics as server_metrics
        now = time.time()
        sample = {0: {'hb_ts': now, 'last_progress_ts': now,
                      'started_ts': now - 10}}
        telemetry.record_samples('ghost-c', 1, sample, now=now)
        text = server_metrics.render()
        assert 'ghost-c' not in text


class TestCliSurfaces:

    def _seed(self, tmp_state, verdict='ok'):
        now = time.time()
        sample = {r: {'hb_ts': now - 3, 'last_progress_ts': now - 4,
                      'started_ts': now - 60, 'step': 7 + r,
                      'step_time_ema_s': 0.25, 'tokens_per_sec': 1000.0,
                      'host_mem_mb': 512.0, 'phase': 'step'}
                  for r in range(2)}
        if verdict == 'hung':
            sample[0]['last_progress_ts'] = now - 10_000
        telemetry.record_samples('top-c', 3, sample, now=now)

    def test_top_json_and_table(self, tmp_state):
        from click.testing import CliRunner

        from skypilot_tpu.client import cli as cli_mod
        self._seed(tmp_state, verdict='hung')
        runner = CliRunner()
        result = runner.invoke(cli_mod.cli, ['top', '--json'])
        assert result.exit_code == 0, result.output
        rows = [json.loads(line) for line in result.output.splitlines()
                if line.startswith('{')]
        assert len(rows) == 2
        by_rank = {r['rank']: r for r in rows}
        assert by_rank[0]['verdict'] == 'hung'
        assert by_rank[1]['verdict'] == 'ok'
        assert by_rank[1]['step'] == 8
        assert 'goodput' in by_rank[0]
        table = runner.invoke(cli_mod.cli, ['top'])
        assert table.exit_code == 0, table.output
        assert 'VERDICT' in table.output
        assert 'hung' in table.output
        assert 'skew=' in table.output
        filtered = runner.invoke(cli_mod.cli, ['top', 'no-such'])
        assert 'No workload telemetry' in filtered.output

    def test_status_shows_heartbeat_age(self, tmp_state):
        from click.testing import CliRunner

        from skypilot_tpu.client import cli as cli_mod
        tmp_state.add_or_update_cluster('top-c', None)
        self._seed(tmp_state)
        result = CliRunner().invoke(cli_mod.cli, ['status'])
        assert result.exit_code == 0, result.output
        assert 'HEARTBEAT' in result.output
        line = [l for l in result.output.splitlines()
                if l.startswith('top-c')][0]
        # Age column shows a small seconds value, not '-'.
        assert line.rstrip()[-1] == 's'

    def test_job_cli_gang_tail_tags_ranks(self, monkeypatch, tmp_path,
                                          capsys):
        from skypilot_tpu.agent import job_cli
        root = tmp_path / 'root'
        log_dir = root / 'logs' / 'job-1'
        log_dir.mkdir(parents=True)
        (log_dir / 'host-0.log').write_text('alpha\n')
        (log_dir / 'host-1.log').write_text('beta\n')
        monkeypatch.setenv('XSKY_CLUSTER_ROOT', str(root))
        assert job_cli.main(['tail', '1', 'gang']) == 0
        out = capsys.readouterr().out
        assert '[rank 0] alpha' in out
        assert '[rank 1] beta' in out
        # Default tail stays the rank-0 run.log view.
        (log_dir / 'run.log').write_text('zeroth\n')
        assert job_cli.main(['tail', '1']) == 0
        assert capsys.readouterr().out == 'zeroth\n'


class TestStallRecoverySmoke:
    """Tier-1 acceptance: a fake-cloud managed job whose rank 0 is
    chaos-stalled (`telemetry.stall` freezes its emit; the heartbeat
    thread keeps beating) is flagged `hung` within a poll interval,
    surfaced via `xsky top --json` and `/metrics`, and triggers a
    journalled, trace-linked recovery after which the job succeeds."""

    def test_chaos_stalled_rank_recovers_end_to_end(
            self, fake_cluster_env, monkeypatch, tmp_path):
        del fake_cluster_env
        import threading

        from click.testing import CliRunner

        from skypilot_tpu import Resources, Task
        from skypilot_tpu import state as state_lib
        from skypilot_tpu.client import cli as cli_mod
        from skypilot_tpu.jobs import controller as controller_lib
        from skypilot_tpu.jobs import scheduler as jobs_scheduler
        from skypilot_tpu.jobs import state as jobs_state
        from skypilot_tpu.server import metrics as server_metrics
        from skypilot_tpu.utils import tracing

        metrics_lib.reset_for_test()
        monkeypatch.setenv('XSKY_JOBS_DB',
                           str(tmp_path / 'managed_jobs.db'))
        monkeypatch.setenv('XSKY_JOBS_LOG_DIR', str(tmp_path / 'jlogs'))
        monkeypatch.setattr(controller_lib, 'POLL_INTERVAL_S', 0.2)
        # Fast telemetry: spool writes + heartbeats every 0.1 s, pulls
        # every 0.3 s, hung after 0.8 s without progress. The heartbeat
        # threshold stays high — the drill is a HUNG rank, not a dead
        # one.
        monkeypatch.setenv(telemetry.ENV_INTERVAL, '0.1')
        monkeypatch.setenv(telemetry.ENV_PULL_INTERVAL, '0.3')
        monkeypatch.setenv(telemetry.ENV_PROGRESS_STALE, '0.8')
        monkeypatch.setenv(telemetry.ENV_HB_STALE, '30')

        # Workload: the first incarnation steps until chaos freezes its
        # emit (skip_first=3 ⇒ frozen from the 4th emit); the relaunch
        # (marker present) does 3 un-frozen emits and exits 0.
        marker = tmp_path / 'first-incarnation'
        script = tmp_path / 'workload.py'
        script.write_text(f'''
import os, sys, time
sys.path.insert(0, {json.dumps(REPO_ROOT)})
from skypilot_tpu.agent import telemetry
relaunch = os.path.exists({json.dumps(str(marker))})
open({json.dumps(str(marker))}, 'w').close()
steps = 3 if relaunch else 80
for i in range(steps):
    telemetry.emit(phase='step', step=i, step_time_s=0.05)
    time.sleep(0.1)
''')
        plan_file = tmp_path / 'stall-plan.json'
        plan_file.write_text(json.dumps({'points': {
            'telemetry.stall': {'match': {'rank': 0},
                                'skip_first': 3}}}))
        # Env var (not load_plan): the workload process on the fake
        # host must see the plan too.
        monkeypatch.setenv('XSKY_CHAOS_PLAN', str(plan_file))

        task = Task('stall', run=f'{sys.executable} {script}')
        task.set_resources(Resources(accelerators='tpu-v5e-8',
                                     use_spot=True))
        job_id = jobs_state.add_job('stall',
                                    Task.chain_to_config([task]))
        jobs_state.set_status(job_id,
                              jobs_state.ManagedJobStatus.SUBMITTED)
        jobs_state.set_schedule_state(job_id,
                                      jobs_state.ScheduleState.LAUNCHING)
        jobs_state.set_controller_pid(job_id, os.getpid())
        cluster = f'xsky-jobs-{job_id}'

        def run_controller():
            try:
                controller_lib.JobsController(job_id).run()
            finally:
                jobs_scheduler.job_done(job_id)

        thread = threading.Thread(target=run_controller, daemon=True)
        thread.start()
        try:
            # The stalled rank surfaces in `xsky top --json` (verdict
            # from the controller's pull) within ~a poll interval of
            # going stale.
            runner = CliRunner()
            hung_row = None
            saw_hb_gauge = False
            deadline = time.time() + 60
            while hung_row is None and time.time() < deadline:
                result = runner.invoke(cli_mod.cli, ['top', '--json'])
                for line in result.output.splitlines():
                    if not line.startswith('{'):
                        continue
                    row = json.loads(line)
                    if row['cluster'] == cluster and \
                            row['verdict'] == 'hung':
                        hung_row = row
                # Scrape-time gauges exist while the cluster is live
                # (they are filtered out after teardown).
                if not saw_hb_gauge:
                    saw_hb_gauge = (
                        'xsky_workload_last_heartbeat_age_seconds{'
                        f'cluster="{cluster}"'
                        in server_metrics.render())
                time.sleep(0.05)
            assert hung_row is not None, \
                'stalled rank never surfaced in xsky top --json'
            assert hung_row['rank'] == 0
            assert saw_hb_gauge, \
                'heartbeat-age gauge never appeared on /metrics'
        finally:
            thread.join(timeout=120)
        assert not thread.is_alive(), 'controller wedged'

        # The job recovered from the stall and finished.
        record = jobs_state.get_job(job_id)
        assert record['status'] == \
            jobs_state.ManagedJobStatus.SUCCEEDED, record
        assert record['recovery_count'] >= 1

        # Journalled + trace-linked: the stall event carries the
        # jobs.stall_recover trace, whose tree holds the recovery.
        events = state_lib.get_recovery_events(scope=f'job/{job_id}')
        types = [e['event_type'] for e in events]
        assert 'job.rank_stall' in types
        stall_event = events[types.index('job.rank_stall')]
        assert stall_event['cause'].startswith('rank 0:')
        assert stall_event['detail']['ranks'] == {'0': 'hung'}
        assert stall_event['trace_id'], 'stall event not trace-linked'
        assert 'job.recovered' in types
        recovered = events[types.index('job.recovered')]
        assert recovered['cause'] == 'relaunched after rank stall'
        assert recovered['latency_s'] and recovered['latency_s'] > 0
        tracing.flush()
        span_names = {s['name']
                      for s in state_lib.get_spans(
                          stall_event['trace_id'])}
        assert 'jobs.stall_recover' in span_names
        assert 'jobs.recover' in span_names

        # /metrics: the registry series survive the run (the
        # scrape-time gauges were asserted live, above — they
        # correctly disappear with the torn-down cluster).
        text = server_metrics.render()
        assert 'xsky_workload_rank_stalls_total{verdict="hung"}' in text
        assert 'xsky_workload_step_seconds_count' in text

        # Workload chaos fired in the workload process, journalled
        # cross-process through the shared state DB.
        injected = {r['scope'] for r in state_lib.get_recovery_events(
            event_type='chaos.injected')}
        assert 'chaos/telemetry.stall' in injected
