"""TPU accelerator grammar / topology resolution tests."""
import pytest

from skypilot_tpu import exceptions
from skypilot_tpu.utils import tpu_topology as tt


class TestParse:

    def test_v5e_single_host(self):
        t = tt.parse('tpu-v5e-8')
        assert t.num_chips == 8
        assert t.num_hosts == 1
        assert t.chips_per_host == 8
        assert t.topology == (2, 4)
        assert not t.is_pod

    def test_v5e_pod(self):
        t = tt.parse('tpu-v5e-32')
        assert t.num_chips == 32
        assert t.num_hosts == 4
        assert t.chips_per_host == 8
        assert t.is_pod

    def test_v5p_counts_cores(self):
        t = tt.parse('tpu-v5p-64')
        assert t.num_chips == 32
        assert t.num_hosts == 8
        assert t.chips_per_host == 4
        assert t.topology == (2, 4, 4)

    def test_v5p_128_cube(self):
        t = tt.parse('tpu-v5p-128')
        assert t.num_chips == 64
        assert t.topology == (4, 4, 4)
        assert t.gcp_accelerator_type() == 'v5p-128'

    def test_v6e_multihost_uses_4_chip_hosts(self):
        # examples/tpu/v6e/README.md:59 — v6e-16 is 4 hosts.
        t = tt.parse('tpu-v6e-16')
        assert t.num_hosts == 4
        assert t.chips_per_host == 4

    def test_v5litepod_alias(self):
        t = tt.parse('tpu-v5litepod-8')
        assert t.accelerator_name == 'tpu-v5e-8'
        assert t.gcp_accelerator_type() == 'v5litepod-8'

    def test_case_insensitive_and_no_prefix(self):
        assert tt.parse('TPU-V5E-8').accelerator_name == 'tpu-v5e-8'
        assert tt.parse('v5e-8').accelerator_name == 'tpu-v5e-8'

    def test_multislice(self):
        t = tt.parse('tpu-v5e-256', {'num_slices': 4})
        assert t.is_multislice
        assert t.total_chips == 1024
        assert t.total_hosts == 4 * 32

    def test_explicit_topology(self):
        t = tt.parse('tpu-v5p-128', {'topology': '2x4x8'})
        assert t.topology == (2, 4, 8)

    def test_topology_mismatch_raises(self):
        with pytest.raises(exceptions.InvalidRequestError):
            tt.parse('tpu-v5p-128', {'topology': '4x4x8'})

    def test_invalid_size_raises(self):
        with pytest.raises(exceptions.InvalidRequestError):
            tt.parse('tpu-v5e-7')
        with pytest.raises(exceptions.InvalidRequestError):
            tt.parse('tpu-v5p-6')  # not divisible by 2 cores/chip... 6/2=3
        with pytest.raises(exceptions.InvalidRequestError):
            tt.parse('tpu-v9-8')

    def test_is_tpu(self):
        assert tt.is_tpu('tpu-v5e-8')
        assert tt.is_tpu('v6e-256')
        assert not tt.is_tpu('A100')
        assert not tt.is_tpu(None)

    def test_hbm_and_flops(self):
        t = tt.parse('tpu-v6e-8')
        assert t.hbm_gib == 8 * 32
        assert t.peak_bf16_tflops == 8 * 918
