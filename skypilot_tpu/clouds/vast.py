"""Vast.ai: marketplace GPU containers for cross-cloud optimization.

Lean twin of sky/clouds/vast.py:1-288 — catalog-backed feasibility via
CatalogCloud, deploy variables for the 'vast' provisioner
(provision/vast/instance.py), key-file credential probing. Platform
facts: hosts are a live marketplace (the catalog is a cached
approximation; the provisioner re-searches offers at launch),
instances are docker containers with SSH on a mapped port, stop/start
supported, spot rides a bid, regions are two-letter country codes.
"""
from __future__ import annotations

import os
import typing
from typing import Any, Dict, Optional, Tuple

from skypilot_tpu.clouds import catalog_cloud
from skypilot_tpu.clouds import cloud as cloud_lib
from skypilot_tpu.utils import registry

if typing.TYPE_CHECKING:
    from skypilot_tpu import resources as resources_lib

# Catalog accelerator name → Vast gpu_name (their marketplace ids).
ACC_TO_GPU_NAME = {
    'RTX3090': 'RTX 3090',
    'RTX4090': 'RTX 4090',
    'RTX5090': 'RTX 5090',
    'RTXA6000': 'RTX A6000',
    'A100-80GB': 'A100 SXM4',
    'H100': 'H100 PCIE',
    'H100-SXM': 'H100 SXM',
    'H200-SXM': 'H200',
    'L40S': 'L40S',
}

DEFAULT_IMAGE = 'vastai/base-image:cuda-12.4.1-auto'


@registry.CLOUD_REGISTRY.register()
class Vast(catalog_cloud.CatalogCloud):
    _REPR = 'Vast'

    _UNSUPPORTED = {
        cloud_lib.CloudImplementationFeatures.OPEN_PORTS:
            'Vast container port mappings are fixed at rent time.',
        cloud_lib.CloudImplementationFeatures.CUSTOM_DISK_TIER:
            'Vast hosts have no disk tiers.',
    }

    @property
    def provisioner_module(self) -> str:
        return 'vast'

    def unsupported_features_for_resources(
            self, resources: 'resources_lib.Resources'
    ) -> Dict[cloud_lib.CloudImplementationFeatures, str]:
        return dict(self._UNSUPPORTED)

    def make_deploy_resources_variables(
            self, resources: 'resources_lib.Resources', cluster_name: str,
            region: str, zone: Optional[str]) -> Dict[str, Any]:
        from skypilot_tpu import authentication
        itype = resources.instance_type
        count_s, _, acc = itype.partition('x_')
        entries = self._match_entries(itype, None, region, None)
        memory_gb = entries[0].memory_gib if entries else 0
        _, public_key_path = authentication.get_or_generate_keys()
        # An unreadable key must fail HERE, before anything is rented:
        # renting with an empty PUBLIC_KEY bills an unreachable box.
        with open(os.path.expanduser(public_key_path),
                  encoding='utf-8') as f:
            public_key = f.read().strip()
        vars: Dict[str, Any] = {
            'cluster_name': cluster_name,
            'region': region,
            'zone': None,
            'instance_type': itype,
            'gpu_name': ACC_TO_GPU_NAME.get(acc, acc.replace('-', ' ')),
            'gpu_count': int(count_s),
            'memory_gb': memory_gb,
            'image_name': resources.image_id or DEFAULT_IMAGE,
            'disk_size': resources.disk_size,
            'use_spot': resources.use_spot,
            'public_key': public_key,
        }
        if resources.use_spot:
            vars['bid'] = self.instance_type_to_hourly_cost(
                itype, use_spot=True, region=region, zone=None)
        if resources.accelerators:
            name, count = next(iter(resources.accelerators.items()))
            vars.update({'gpu_type': name, 'acc_count': count})
        return vars

    def provider_config_overrides(
            self, node_config: Dict[str, Any]) -> Dict[str, Any]:
        del node_config
        return {}

    def check_credentials(self) -> Tuple[bool, Optional[str]]:
        from skypilot_tpu.provision.vast import rest
        if rest.load_api_key() is not None:
            return True, None
        return False, (
            'Vast.ai API key not found. Set $VAST_API_KEY or populate '
            f'{rest.CREDENTIALS_PATH}.')

    def get_credential_file_mounts(self) -> Dict[str, str]:
        from skypilot_tpu.provision.vast import rest
        if os.path.exists(os.path.expanduser(rest.CREDENTIALS_PATH)):
            return {rest.CREDENTIALS_PATH: rest.CREDENTIALS_PATH}
        return {}

    def get_egress_cost(self, num_gigabytes: float) -> float:
        # Bandwidth pricing is host-set and tiny; not modeled.
        return 0.0
