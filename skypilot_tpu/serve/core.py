"""Serve API: up / status / down (twin of sky/serve/server/core.py).

Controller placement: by default the controller+LB process runs on the
API-server host; with XSKY_SERVE_CONTROLLER_REMOTE set, every verb is
relayed to a dedicated provisioned controller cluster (serve.remote,
twin of sky-serve-controller.yaml.j2) that survives API-server
restarts. Replicas are ordinary clusters launched through the engine
either way.
"""
from __future__ import annotations

import os
import signal
import socket
import subprocess
import sys
import time
from typing import Any, Dict, List, Optional

from skypilot_tpu import exceptions
from skypilot_tpu import sky_logging
from skypilot_tpu import task as task_lib
from skypilot_tpu.serve import state as serve_state

logger = sky_logging.init_logger(__name__)


def _remote_mode() -> bool:
    return bool(os.environ.get('XSKY_SERVE_CONTROLLER_REMOTE'))


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(('127.0.0.1', 0))
        return s.getsockname()[1]


def controller_log_path(service_name: str) -> str:
    root = os.path.expanduser(
        os.environ.get('XSKY_SERVE_LOG_DIR', '~/.xsky/serve'))
    return os.path.join(root, service_name, 'controller.log')


def controller_logs(service_name: str) -> str:
    """The service controller's own stdout/stderr (crash diagnostics)."""
    if _remote_mode():
        from skypilot_tpu.serve import remote as serve_remote
        return serve_remote.controller_logs(service_name)
    path = controller_log_path(service_name)
    if not os.path.exists(path):
        return ''
    with open(path, encoding='utf-8', errors='replace') as f:
        return f.read()


def _check_fallback_knobs(task: task_lib.Task) -> None:
    """Mixed-fleet knobs only make sense on a spot task: on an
    on-demand task the spot-labeled replicas would never be 'spot',
    and dynamic fallback would double the fleet at every cold start."""
    from skypilot_tpu.serve import service_spec as spec_lib
    spec = spec_lib.SkyServiceSpec.from_yaml_config(
        task.to_yaml_config().get('service', {}))
    if not (spec.base_ondemand_fallback_replicas or
            spec.dynamic_ondemand_fallback):
        return
    if not any(r.use_spot for r in task.resources):
        raise ValueError(
            'base_ondemand_fallback_replicas / dynamic_ondemand_fallback '
            'require spot resources (use_spot: true) — on-demand '
            'fallback of an already-on-demand fleet would just double '
            'it.')


def _spawn_controller(name: str) -> int:
    """Start a detached controller process for `name` → pid.

    Controller stdio goes to a per-service log file, not DEVNULL — a
    crashed controller must leave more than a FAILED status row.
    """
    log_path = controller_log_path(name)
    os.makedirs(os.path.dirname(log_path), exist_ok=True)
    from skypilot_tpu.utils import tracing
    from skypilot_tpu.workspaces import context as ws_context
    record = serve_state.get_service(name)
    env = ws_context.controller_env(
        record.get('workspace') if record else None)
    # Hand the `serve.up` request's trace to the controller so its
    # replica launches/recoveries cross-link to the submitting request.
    env = tracing.env_for_child(env)
    with open(log_path, 'ab') as logf:
        proc = subprocess.Popen(
            [sys.executable, '-m', 'skypilot_tpu.serve.controller', name],
            env=env, start_new_session=True,
            stdout=logf, stderr=subprocess.STDOUT)
    serve_state.set_service_controller_pid(name, proc.pid)
    return proc.pid


def max_controller_respawns() -> int:
    return int(os.environ.get('XSKY_SERVE_MAX_CONTROLLER_RESPAWNS',
                              '3'))


def recover_controllers() -> List[str]:
    """Re-exec controllers for live services whose process is gone.

    HA (VERDICT r3 #9): service + replica state live in sqlite (under
    the helm chart's PVC); after an API-server/pod restart this brings
    every non-terminal service's control loop back. The restarted
    controller reconciles desired replicas against recorded state, so
    a rolling update or autoscale decision in flight simply resumes.
    Respawns are bounded (a controller crashing on its own bug must
    not be re-execed every reconcile tick forever; reaching READY
    resets the budget); past the budget the service is marked FAILED.
    Serialized by an inter-process lock: the background reconciler
    and a concurrent `xsky doctor --fix` must not both observe the
    same dead pid and double-spawn one service's controller (the jobs
    path gets the same guarantee from the scheduler filelock).
    Returns the recovered service names.
    """
    import filelock
    from skypilot_tpu import state as global_state
    from skypilot_tpu.utils import common_utils
    lock_path = os.path.join(
        os.path.dirname(os.path.expanduser(
            os.environ.get('XSKY_SERVE_DB', '~/.xsky/serve.db'))),
        'serve_recover.lock')
    os.makedirs(os.path.dirname(lock_path), exist_ok=True)
    try:
        lock = filelock.FileLock(lock_path, timeout=10)
        lock.acquire()
    except filelock.Timeout:
        # Another process is recovering; it owns this pass.
        return []
    try:
        recovered, dead_replicas = _recover_controllers_locked(
            global_state, common_utils)
    finally:
        lock.release()
    # Outside the lock (teardown is slow and must not block a
    # concurrent doctor): reap the replica clusters of services whose
    # respawn budget is exhausted — their controller and LB are dead,
    # nothing serves traffic, and nothing else will ever down them
    # (jobs-side twin: the scheduler reaps on budget exhaustion too).
    from skypilot_tpu import core as core_lib
    for service_name, cluster in dead_replicas:
        try:
            core_lib.down(cluster, purge=True)
        except exceptions.ClusterDoesNotExist:
            continue
        except Exception as e:  # pylint: disable=broad-except
            logger.warning(f'Failed to reap replica cluster '
                           f'{cluster!r} of failed service: {e}')
            continue
        global_state.record_recovery_event(
            'reconcile.replica_teardown', scope=f'cluster/{cluster}',
            cause='service respawn budget exhausted',
            detail={'service': service_name})
    return recovered


def _recover_controllers_locked(global_state, common_utils):
    from skypilot_tpu.utils import ownership
    recovered = []
    dead_replicas = []
    for record in serve_state.get_services():
        if record['status'] in (serve_state.ServiceStatus.SHUTTING_DOWN,
                                serve_state.ServiceStatus.FAILED):
            continue
        pid = record['controller_pid']
        if pid and common_utils.pid_alive(pid):
            continue
        if not pid and time.time() - (record['created_at'] or 0) < 10:
            # `serve up` writes the record an instant before spawning
            # the controller; the periodic reconciler must not race
            # that window into a duplicate spawn.
            continue
        name = record['name']
        if not ownership.owns(f'service/{name}'):
            # Multi-server sharding: this service's takeover belongs
            # to a peer server's reconcile tick.
            continue
        if not ownership.claim_repair(f'service/{name}',
                                      'controller process died'):
            # A racing peer claimed this respawn first (yield
            # journalled); re-execing here too would duplicate the
            # controller.
            continue
        respawns = serve_state.bump_controller_respawns(name)
        if respawns > max_controller_respawns():
            logger.warning(
                f'Service {name!r} controller died {respawns} times; '
                'respawn budget exhausted — marking FAILED.')
            serve_state.set_service_status(
                name, serve_state.ServiceStatus.FAILED)
            # The record stays (post-mortem via `serve status`), but
            # its lease and chip-holding replicas must not linger.
            global_state.release_lease(f'service/{name}')
            dead_replicas.extend(
                (name, rep['cluster_name'])
                for rep in serve_state.get_replicas(name))
            global_state.record_recovery_event(
                'reconcile.respawn_budget_exhausted',
                scope=f'service/{name}',
                cause=f'controller died {respawns} times')
            continue
        logger.warning(f'Service {name!r} controller (pid {pid}) is '
                       f'gone; re-execing (respawn {respawns}/'
                       f'{max_controller_respawns()}).')
        _spawn_controller(name)
        global_state.record_recovery_event(
            'reconcile.service_respawn', scope=f'service/{name}',
            cause='controller process died',
            detail={'pid': pid or 0, 'respawn': respawns})
        recovered.append(name)
    return recovered, dead_replicas


def up(task: task_lib.Task, service_name: Optional[str] = None,
       wait_ready: bool = True, timeout_s: float = 120.0) -> str:
    if task.service is None:
        raise ValueError("Task has no 'service:' section.")
    _check_fallback_knobs(task)
    if _remote_mode():
        from skypilot_tpu.serve import remote as serve_remote
        return serve_remote.up(task, service_name, wait_ready, timeout_s)
    name = service_name or task.name or 'service'
    if serve_state.get_service(name) is not None:
        raise ValueError(f'Service {name!r} already exists.')
    lb_port = _free_port()
    from skypilot_tpu.workspaces import context as ws_context
    serve_state.add_service(name, task.to_yaml_config(), lb_port,
                            workspace=ws_context.get_active())
    _spawn_controller(name)
    if wait_ready:
        deadline = time.time() + timeout_s
        while time.time() < deadline:
            record = serve_state.get_service(name)
            if record['status'] == serve_state.ServiceStatus.READY:
                return name
            if record['status'] == serve_state.ServiceStatus.FAILED:
                raise exceptions.SkyTpuError(f'Service {name} failed.')
            time.sleep(0.3)
        raise TimeoutError(f'Service {name} not ready in {timeout_s}s')
    return name


def update(task: task_lib.Task, service_name: str,
           wait_done: bool = False, timeout_s: float = 120.0,
           mode: str = 'rolling') -> int:
    """Update to a new task version (twin of `sky serve update
    --mode`). Returns the new version.

    mode='rolling': new-version replicas launch alongside the old
    fleet and serve as they come READY; old replicas drain only after
    the new fleet passes readiness — traffic never drops.
    mode='blue_green': the old fleet keeps ALL traffic until the full
    new fleet is READY, then the LB cuts over in one step and the old
    fleet drains — no mixed-version responses.

    Async by default (like the reference): the version bump is durable
    once this returns and the controller rolls in the background; pass
    wait_done=True to block until the old fleet has drained (replica
    provisioning on real clouds routinely exceeds small timeouts).
    """
    if task.service is None:
        raise ValueError("Task has no 'service:' section.")
    if mode not in ('rolling', 'blue_green'):
        raise ValueError(
            f"update mode must be 'rolling' or 'blue_green', "
            f'got {mode!r}')
    _check_fallback_knobs(task)
    if _remote_mode():
        from skypilot_tpu.serve import remote as serve_remote
        return serve_remote.update(task, service_name, wait_done,
                                   timeout_s, mode)
    record = serve_state.get_service(service_name)
    if record is None:
        raise ValueError(f'Service {service_name!r} not found.')
    if record['status'] in (serve_state.ServiceStatus.FAILED,
                            serve_state.ServiceStatus.SHUTTING_DOWN):
        raise ValueError(
            f'Service {service_name!r} is {record["status"].value}; its '
            'controller is no longer rolling updates. Tear it down '
            '(`serve down`) and `serve up` the new version instead.')
    from skypilot_tpu.utils import common_utils
    pid = record['controller_pid']
    if pid and not common_utils.pid_alive(pid):
        raise ValueError(
            f'Service {service_name!r} controller (pid {pid}) is dead; '
            'no process would apply the update. `serve down` and '
            '`serve up` the new version instead.')
    new_version = serve_state.bump_service_version(
        service_name, task.to_yaml_config(), mode=mode)
    if wait_done:
        deadline = time.time() + timeout_s
        while time.time() < deadline:
            replicas = serve_state.get_replicas(service_name)
            ready_new = [r for r in replicas
                         if r['version'] == new_version and
                         r['status'] == serve_state.ReplicaStatus.READY]
            old_left = [r for r in replicas
                        if r['version'] < new_version]
            if ready_new and not old_left:
                return new_version
            time.sleep(0.3)
        raise TimeoutError(
            f'Update of {service_name} to v{new_version} not complete '
            f'in {timeout_s}s')
    return new_version


def slo_summary(service_name: str) -> Optional[Dict[str, Any]]:
    """The newest service-level SLO evaluation for one service →
    {ttft_p99_ms, burn (worst, short window first), verdict} or None
    when the SLO monitor has not written yet. Never raises — status
    must render even when the state DB is unreadable."""
    try:
        from skypilot_tpu import state as global_state
        rows = global_state.get_serve_slo(service=service_name,
                                          kind='service')
        if not rows:
            return None
        row = rows[0]
        worst = None
        for per in (row.get('burns') or {}).values():
            for burn in per.values():
                if burn == 'inf':
                    burn = float('inf')
                if burn is not None and (worst is None or
                                         burn > worst):
                    worst = burn
        return {
            'ttft_p99_ms': row.get('ttft_p99_ms'),
            'tpot_p50_ms': row.get('tpot_p50_ms'),
            'burn_rate': worst,
            'verdict': row.get('verdict'),
        }
    except Exception:  # pylint: disable=broad-except
        return None


def status(service_names: Optional[List[str]] = None,
           limit: Optional[int] = None,
           offset: int = 0) -> List[Dict[str, Any]]:
    if _remote_mode():
        from skypilot_tpu.serve import remote as serve_remote
        from skypilot_tpu.utils import db_utils
        # Remote-controller wire protocol predates pagination: page
        # here, with the same clamping as the SQL path, so callers
        # get one contract either way.
        return db_utils.page_rows(serve_remote.status(service_names),
                                  limit, offset)
    records = serve_state.get_services(names=service_names,
                                       limit=limit, offset=offset)
    out = []
    for r in records:
        replicas = serve_state.get_replicas(r['name'])
        # TLS-terminating LBs serve HTTPS; say so in the endpoint.
        tls = bool((r.get('task_config') or {}).get(
            'service', {}).get('tls'))
        scheme = 'https://' if tls else ''
        out.append({
            'name': r['name'],
            'status': r['status'].value,
            'version': r['version'],
            'endpoint': f"{scheme}127.0.0.1:{r['lb_port']}",
            'workspace': r.get('workspace'),
            'qps': r.get('qps'),
            'target_replicas': r.get('target_replicas'),
            # Latency/burn columns (the SLO monitor's newest verdict;
            # None until its first evaluation lands).
            'slo': slo_summary(r['name']),
            'replicas': [{
                'replica_id': rep['replica_id'],
                'status': rep['status'].value,
                'endpoint': rep['endpoint'],
                'version': rep['version'],
            } for rep in replicas],
        })
    return out


def down(service_name: str) -> None:
    if _remote_mode():
        from skypilot_tpu.serve import remote as serve_remote
        serve_remote.down(service_name)
        return
    record = serve_state.get_service(service_name)
    if record is None:
        raise ValueError(f'Service {service_name!r} not found.')
    serve_state.set_service_status(service_name,
                                   serve_state.ServiceStatus.SHUTTING_DOWN)
    pid = record['controller_pid']
    if pid:
        try:
            os.kill(pid, signal.SIGTERM)
        except (ProcessLookupError, PermissionError):
            pass
    # Reap replica clusters.
    from skypilot_tpu import core as core_lib
    for rep in serve_state.get_replicas(service_name):
        try:
            core_lib.down(rep['cluster_name'], purge=True)
        except exceptions.ClusterDoesNotExist:
            pass
    serve_state.remove_service(service_name)
    # The service is gone; its liveness lease must not linger as a
    # phantom for the reconciler/doctor.
    from skypilot_tpu import state as global_state
    global_state.release_lease(f'service/{service_name}')


def metrics_history(service_name: str,
                    limit: int = 720) -> List[Dict[str, Any]]:
    """Per-tick QPS/target/ready trend for the dashboard chart
    (`serve.history` verb; the reference dashboard charts the same
    series from its controller DB). Oldest-first, bounded."""
    if _remote_mode():
        from skypilot_tpu.serve import remote as serve_remote
        return serve_remote.metrics_history(service_name, limit)
    if serve_state.get_service(service_name) is None:
        raise ValueError(f'Service {service_name!r} not found.')
    return serve_state.get_metrics_history(service_name, limit=limit)


def watch_replica_logs(service_name: str, replica_id: int,
                       offset: int = 0) -> Dict[str, Any]:
    """One incremental poll of a replica's task log → {status, offset,
    data, epoch, done} (same contract as jobs.watch_logs; powers the
    dashboard replica tail + `serve logs --follow`)."""
    if _remote_mode():
        from skypilot_tpu.serve import remote as serve_remote
        return serve_remote.watch_replica_logs(service_name,
                                               replica_id, offset)
    if serve_state.get_service(service_name) is None:
        return {'status': 'NOT_FOUND', 'offset': offset, 'data': '',
                'done': True}
    match = [r for r in serve_state.get_replicas(service_name)
             if r['replica_id'] == replica_id]
    if not match:
        return {'status': 'NOT_FOUND', 'offset': offset, 'data': '',
                'done': True}
    replica = match[0]
    status = replica['status'].value
    done = replica['status'].is_terminal()
    cluster_name = replica['cluster_name']
    from skypilot_tpu import core as core_lib
    try:
        # The launch-time job id on the replica record makes each poll
        # ONE remote exec; pre-migration rows fall back to a queue
        # lookup once per poll.
        job_id = replica.get('job_id')
        if job_id is None:
            jobs = core_lib.queue(cluster_name)
            if not jobs:
                return {'status': status, 'offset': offset, 'data': '',
                        'done': done}
            job_id = max(j['job_id'] for j in jobs)
        epoch = f'{cluster_name}#{job_id}'
        poll = core_lib.watch_job_log(cluster_name, job_id, offset)
        return {'status': status, 'offset': poll.get('offset', offset),
                'data': poll.get('log') or '',
                'epoch': epoch, 'done': done}
    except Exception:  # pylint: disable=broad-except
        # Cluster mid-provision or torn down: status-only poll.
        return {'status': status, 'offset': offset, 'data': '',
                'done': done}


def tail_logs(service_name: str, replica_id: int,
              job_id: Optional[int] = None) -> str:
    """Log tail of one replica's cluster (twin of `sky serve logs`)."""
    if _remote_mode():
        from skypilot_tpu.serve import remote as serve_remote
        return serve_remote.tail_logs(service_name, replica_id, job_id)
    if serve_state.get_service(service_name) is None:
        raise ValueError(f'Service {service_name!r} not found.')
    replicas = serve_state.get_replicas(service_name)
    match = [r for r in replicas if r['replica_id'] == replica_id]
    if not match:
        known = sorted(r['replica_id'] for r in replicas)
        raise ValueError(
            f'Service {service_name!r} has no replica {replica_id} '
            f'(known: {known}).')
    from skypilot_tpu import core as core_lib
    from skypilot_tpu import exceptions
    try:
        return core_lib.tail_logs(match[0]['cluster_name'],
                                  job_id=job_id)
    except exceptions.ClusterDoesNotExist:
        # FAILED replicas keep their DB row but have no live cluster.
        raise ValueError(
            f'Replica {replica_id} of {service_name!r} has no live '
            f'cluster (status: {match[0]["status"].value}).') from None
