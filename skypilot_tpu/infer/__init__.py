"""TPU-native LLM inference engine (JetStream twin).

The reference serves LLMs by orchestrating external engines (vLLM/SGLang
recipes; JetStream on TPU, examples/tpu/v6e/README.md:92-121 — the
BASELINE serving numbers). Here the engine is in-tree and TPU-first:
prefill/decode split, slot-based continuous batching, jitted decode step
over a sharded KV cache.
"""
from skypilot_tpu.infer.engine import InferenceEngine, EngineConfig
from skypilot_tpu.infer.orchestrator import Orchestrator, Request

__all__ = ['InferenceEngine', 'EngineConfig', 'Orchestrator', 'Request']
