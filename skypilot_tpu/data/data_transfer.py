"""Cross-cloud bucket transfer (twin of sky/data/data_transfer.py).

Two paths, like the reference:
  * **GCP Storage Transfer Service** for S3 → GCS at scale (server-side,
    no egress through the client) — built as a REST request via the same
    gcp REST client the provisioner uses.
  * **CLI relay** for every other pair: stream through the local machine
    with the source store's download CLI piped into the destination's
    upload CLI (the reference shells out similarly for small transfers).
"""
from __future__ import annotations

import shlex
import subprocess
from typing import TYPE_CHECKING

from skypilot_tpu import exceptions
from skypilot_tpu import sky_logging

if TYPE_CHECKING:
    from skypilot_tpu.data import storage as storage_lib

logger = sky_logging.init_logger(__name__)

_STS_ENDPOINT = 'https://storagetransfer.googleapis.com/v1'


def s3_to_gcs_transfer_job(project_id: str, s3_bucket: str,
                           gcs_bucket: str,
                           aws_access_key_id: str,
                           aws_secret_access_key: str) -> dict:
    """Build the Storage Transfer Service transferJobs.create body.

    (sky/data/data_transfer.py uses the same service; we expose the body
    builder separately so it is testable without credentials.)
    """
    return {
        'description': f'xsky transfer s3://{s3_bucket} -> '
                       f'gs://{gcs_bucket}',
        'status': 'ENABLED',
        'projectId': project_id,
        'transferSpec': {
            'awsS3DataSource': {
                'bucketName': s3_bucket,
                'awsAccessKey': {
                    'accessKeyId': aws_access_key_id,
                    'secretAccessKey': aws_secret_access_key,
                },
            },
            'gcsDataSink': {'bucketName': gcs_bucket},
        },
    }


def run_s3_to_gcs_transfer(project_id: str, s3_bucket: str,
                           gcs_bucket: str, aws_access_key_id: str,
                           aws_secret_access_key: str) -> dict:
    """Kick off a server-side S3→GCS transfer via STS."""
    from skypilot_tpu.provision.gcp import rest
    body = s3_to_gcs_transfer_job(project_id, s3_bucket, gcs_bucket,
                                  aws_access_key_id,
                                  aws_secret_access_key)
    transport = rest.Transport()
    return transport.request('POST', f'{_STS_ENDPOINT}/transferJobs',
                             body=body)


def _download_to_local_cmd(store: 'storage_lib.AbstractStore',
                           local_dir: str) -> str:
    return store.copy_download_command(local_dir)


def cli_relay_transfer(src: 'storage_lib.AbstractStore',
                       dst: 'storage_lib.AbstractStore',
                       scratch_dir: str) -> None:
    """Generic pairwise transfer: src → local scratch → dst."""
    q = shlex.quote(scratch_dir)
    down = src.copy_download_command(scratch_dir)
    proc = subprocess.run(down, shell=True, capture_output=True, text=True)
    if proc.returncode != 0:
        raise exceptions.StorageUploadError(
            f'Download from {src.url()} failed: {proc.stderr[:500]}')
    old_source = dst.source
    try:
        dst.source = scratch_dir
        if not dst.exists():
            dst.create()
        dst.upload()
    finally:
        dst.source = old_source
    logger.info(f'Transferred {src.url()} → {dst.url()} via {q}')


def transfer(src: 'storage_lib.AbstractStore',
             dst: 'storage_lib.AbstractStore',
             scratch_dir: str = '/tmp/xsky-transfer') -> None:
    """Move bucket contents between any two stores.

    S3 → GCS prefers the server-side Storage Transfer Service when GCP
    credentials + project are discoverable; everything else relays
    through the local machine.
    """
    from skypilot_tpu.data import storage as storage_lib
    if (src.store_type == storage_lib.StoreType.S3 and
            dst.store_type == storage_lib.StoreType.GCS):
        try:
            import os
            project = os.environ.get('GOOGLE_CLOUD_PROJECT')
            key_id = os.environ.get('AWS_ACCESS_KEY_ID')
            secret = os.environ.get('AWS_SECRET_ACCESS_KEY')
            if project and key_id and secret:
                run_s3_to_gcs_transfer(project, src.name, dst.name,
                                       key_id, secret)
                return
        except Exception as e:  # pylint: disable=broad-except
            logger.warning(
                f'Storage Transfer Service unavailable ({e}); falling '
                'back to CLI relay.')
    cli_relay_transfer(src, dst, scratch_dir)
