"""Sequence-parallel attention (ring + Ulysses) vs the dense reference.

Runs on the virtual 8-device CPU mesh from conftest — the multi-chip
context-parallel path without TPUs (SURVEY §7: local-process harness).
"""
import dataclasses
import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from skypilot_tpu.models import llama
from skypilot_tpu.ops import attention as attention_ops
from skypilot_tpu.ops import ring_attention as ring_ops
from skypilot_tpu.parallel import mesh as mesh_lib


pytestmark = pytest.mark.slow  # heavy tier: subprocess e2e / jit compiles


def _qkv(b=2, s=64, h=8, h_kv=4, d=16, dtype=jnp.float32, seed=0):
    keys = jax.random.split(jax.random.PRNGKey(seed), 3)
    q = jax.random.normal(keys[0], (b, s, h, d), dtype)
    k = jax.random.normal(keys[1], (b, s, h_kv, d), dtype)
    v = jax.random.normal(keys[2], (b, s, h_kv, d), dtype)
    return q, k, v


def _seq_mesh(sequence=8, tensor=1):
    plan = mesh_lib.MeshPlan(data=1, sequence=sequence, tensor=tensor)
    return mesh_lib.build_mesh(plan.resolve(8))


@pytest.mark.parametrize('causal', [True, False])
def test_ring_matches_dense(causal):
    q, k, v = _qkv()
    mesh = _seq_mesh()
    ref = attention_ops.xla_attention(q, k, v, causal=causal)
    out = jax.jit(functools.partial(
        ring_ops.ring_attention, mesh=mesh, causal=causal))(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_ring_with_tensor_axis():
    q, k, v = _qkv(h=8, h_kv=4)
    mesh = _seq_mesh(sequence=4, tensor=2)
    ref = attention_ops.xla_attention(q, k, v, causal=True)
    out = jax.jit(functools.partial(
        ring_ops.ring_attention, mesh=mesh))(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize('causal', [True, False])
def test_ulysses_matches_dense(causal):
    q, k, v = _qkv()
    mesh = _seq_mesh()
    ref = attention_ops.xla_attention(q, k, v, causal=causal)
    out = jax.jit(functools.partial(
        ring_ops.ulysses_attention, mesh=mesh, causal=causal))(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_ring_gradients_flow():
    q, k, v = _qkv(s=32)
    mesh = _seq_mesh()

    def loss(q, k, v):
        return jnp.mean(ring_ops.ring_attention(q, k, v, mesh) ** 2)

    ref_loss = jnp.mean(attention_ops.xla_attention(q, k, v) ** 2)
    val, grads = jax.jit(jax.value_and_grad(loss, argnums=(0, 1, 2)))(
        q, k, v)
    np.testing.assert_allclose(float(val), float(ref_loss), rtol=1e-5)
    ref_grads = jax.grad(
        lambda q, k, v: jnp.mean(
            attention_ops.xla_attention(q, k, v) ** 2),
        argnums=(0, 1, 2))(q, k, v)
    for g, rg in zip(grads, ref_grads):
        np.testing.assert_allclose(np.asarray(g), np.asarray(rg),
                                   rtol=1e-4, atol=1e-5)


@pytest.mark.parametrize('impl', ['ring', 'ulysses'])
def test_llama_forward_sequence_parallel(impl):
    mesh = _seq_mesh(sequence=4, tensor=2)
    config = dataclasses.replace(
        llama.LLAMA_TINY, dtype=jnp.float32, attention_impl=impl,
        n_heads=8, n_kv_heads=4)
    dense_config = dataclasses.replace(config, attention_impl='xla')
    params = llama.init(config, jax.random.PRNGKey(0))
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 32), 0,
                                config.vocab_size, dtype=jnp.int32)
    sp = jax.jit(lambda p, t: llama.forward(config, p, t, mesh=mesh))(
        params, tokens)
    dense = llama.forward(dense_config, params, tokens)
    np.testing.assert_allclose(np.asarray(sp), np.asarray(dense),
                               rtol=2e-4, atol=2e-4)


@pytest.mark.parametrize('causal', [True, False])
def test_ring_chunked_q_matches_dense(causal):
    """Multi-chunk q path (block_q < S_local) must equal the dense
    reference exactly like the single-chunk path."""
    mesh = _seq_mesh(4, tensor=2)
    b, s, h, d = 2, 64, 4, 16
    q = jax.random.normal(jax.random.PRNGKey(0), (b, s, h, d))
    k = jax.random.normal(jax.random.PRNGKey(1), (b, s, h, d))
    v = jax.random.normal(jax.random.PRNGKey(2), (b, s, h, d))
    ref = attention_ops.xla_attention(q, k, v, causal=causal)

    import functools
    local = functools.partial(ring_ops.ring_attention_local,
                              axis_name='sequence', causal=causal,
                              block_q=8)    # 16-token shard → 2 chunks
    from jax.sharding import PartitionSpec as P
    spec = P(('data', 'fsdp'), 'sequence', 'tensor', None)
    out = jax.shard_map(local, mesh=mesh, in_specs=(spec,) * 3,
                        out_specs=spec, check_vma=False)(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-3)


def test_ring_dead_block_skip_gradients():
    """Gradients flow through the lax.cond dead-block skip and match
    the dense reference."""
    mesh = _seq_mesh(4, tensor=2)
    b, s, h, d = 1, 32, 2, 8
    q = jax.random.normal(jax.random.PRNGKey(3), (b, s, h, d))
    k = jax.random.normal(jax.random.PRNGKey(4), (b, s, h, d))
    v = jax.random.normal(jax.random.PRNGKey(5), (b, s, h, d))

    def ring_loss(q, k, v):
        return jnp.sum(ring_ops.ring_attention(q, k, v, mesh,
                                               causal=True) ** 2)

    def ref_loss(q, k, v):
        return jnp.sum(attention_ops.xla_attention(
            q, k, v, causal=True) ** 2)

    g_ring = jax.grad(ring_loss, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(ref_loss, argnums=(0, 1, 2))(q, k, v)
    for gr, gd in zip(g_ring, g_ref):
        np.testing.assert_allclose(np.asarray(gr), np.asarray(gd),
                                   atol=5e-3)
