"""Checkpoint/resume through the real training entrypoint.

The spot-recovery story (jobs relaunch + `--resume auto` against a
bucket mount) depends on orbax restoring sharded train state correctly;
this drives train.launch as real subprocesses — save, die, resume —
like a preempted job would (SURVEY §5 checkpoint/resume)."""
import os
import re
import subprocess
import sys

import pytest


def _run_launch(tmp_path, extra, timeout=280):
    env = dict(os.environ, JAX_PLATFORMS='cpu',
               XLA_FLAGS='--xla_force_host_platform_device_count=2')
    cmd = [
        sys.executable, '-m', 'skypilot_tpu.train.launch',
        '--model', 'tiny', '--global-batch-size', '2',
        '--seq-len', '32', '--log-every', '1',
        '--optimizer', 'adafactor',
        '--checkpoint-dir', str(tmp_path / 'ckpt'),
    ] + extra
    proc = subprocess.run(cmd, env=env, capture_output=True, text=True,
                          timeout=timeout)
    assert proc.returncode == 0, proc.stderr[-2000:]
    return proc.stdout + proc.stderr


@pytest.mark.slow
class TestCheckpointResume:

    def test_resume_continues_from_saved_step(self, tmp_path):
        out1 = _run_launch(tmp_path,
                           ['--steps', '3', '--checkpoint-every', '2'])
        assert 'step 3/3' in out1
        ckpt_root = tmp_path / 'ckpt'
        saved = sorted(int(p) for p in os.listdir(ckpt_root)
                       if p.isdigit())
        assert 3 in saved  # final step always checkpointed

        out2 = _run_launch(tmp_path,
                           ['--steps', '5', '--resume', 'auto',
                            '--checkpoint-every', '2'])
        assert 'Resumed from checkpoint step 3' in out2
        # Only steps 4..5 run; step 1-3 logs must not reappear.
        assert 'step 4/5' in out2
        assert 'step 5/5' in out2
        assert 'step 1/5' not in out2

    def test_resume_losses_continue_not_restart(self, tmp_path):
        """The restored state must carry optimizer momentum + params:
        the resumed first-step loss matches an uninterrupted run's
        loss at that step, not the from-scratch loss."""
        def losses(text):
            return [float(m) for m in re.findall(
                r'loss=([0-9.]+)', text)]

        # Uninterrupted 4 steps.
        solid = _run_launch(tmp_path / 'solid',
                            ['--steps', '4', '--checkpoint-every', '99'])
        # 2 steps, save, resume to 4.
        _run_launch(tmp_path / 'split',
                    ['--steps', '2', '--checkpoint-every', '2'])
        resumed = _run_launch(tmp_path / 'split',
                              ['--steps', '4', '--resume', 'auto',
                               '--checkpoint-every', '99'])
        solid_losses = losses(solid)
        resumed_losses = losses(resumed)
        assert len(solid_losses) == 4
        assert len(resumed_losses) == 2  # steps 3 and 4 only
        # Synthetic batches are step-seeded, so the trajectories line
        # up exactly when state round-trips correctly.
        assert solid_losses[2] == pytest.approx(resumed_losses[0],
                                                rel=1e-4)
        assert solid_losses[3] == pytest.approx(resumed_losses[1],
                                                rel=1e-4)


@pytest.mark.slow
def test_metrics_file_emitted(tmp_path):
    """--metrics-file appends one JSON line per log window with the
    observability fields the dashboard/CI can consume."""
    import json
    _run_launch(tmp_path, ['--steps', '3',
                           '--metrics-file',
                           str(tmp_path / 'metrics.jsonl')])
    lines = [json.loads(ln) for ln in
             (tmp_path / 'metrics.jsonl').read_text().splitlines()]
    assert len(lines) == 3
    for row in lines:
        assert {'step', 'loss', 'tokens_per_sec',
                'model_tflops_per_chip', 'grad_norm'} <= set(row)
    assert [r['step'] for r in lines] == [1, 2, 3]


@pytest.mark.slow
class TestEvalLoop:

    def test_eval_loss_logged_and_recorded(self, tmp_path):
        """--eval-data drives periodic grad-free eval passes: logged
        and written to the metrics file alongside train metrics."""
        import json
        import numpy as np
        shard = tmp_path / 'tok.bin'
        np.random.default_rng(0).integers(
            0, 256, size=4000, dtype=np.uint32).astype('<u4').tofile(
                shard)
        metrics_file = tmp_path / 'metrics.jsonl'
        env = dict(os.environ, JAX_PLATFORMS='cpu',
                   XLA_FLAGS='--xla_force_host_platform_device_count=2')
        cmd = [
            sys.executable, '-m', 'skypilot_tpu.train.launch',
            '--model', 'tiny', '--global-batch-size', '2',
            '--seq-len', '32', '--log-every', '2', '--steps', '4',
            '--optimizer', 'adafactor',
            '--data', str(shard), '--data-loader', 'python',
            '--eval-data', str(shard), '--eval-every', '2',
            '--eval-batches', '2',
            '--metrics-file', str(metrics_file),
        ]
        proc = subprocess.run(cmd, env=env, capture_output=True,
                              text=True, timeout=280)
        assert proc.returncode == 0, proc.stderr[-2000:]
        log = proc.stdout + proc.stderr
        assert 'eval_loss=' in log
        entries = [json.loads(line) for line in
                   metrics_file.read_text().splitlines()]
        eval_entries = [e for e in entries if 'eval_loss' in e]
        assert len(eval_entries) == 2          # steps 2 and 4
        assert {e['step'] for e in eval_entries} == {2, 4}
        assert all(e['eval_loss'] > 0 for e in eval_entries)
        # Same eval slice both times, params changed → losses differ.
        losses = [e['eval_loss'] for e in eval_entries]
        assert losses[0] != losses[1]
