"""TPU v2 REST API client: nodes, queued resources, operations.

Behavioral twin of GCPTPUVMInstance (sky/provision/gcp/instance_utils.py:
1205-1670) with two greenfield additions the reference lacks (noted absent
at SURVEY §2.3): **queued resources** (the modern capacity-request path,
required for reservations/spot on v5p+) and **multislice** (N cooperating
slices joined over DCN via one queued resource).
"""
from __future__ import annotations

import time
from typing import Any, Dict, List, Optional

from skypilot_tpu import exceptions
from skypilot_tpu import sky_logging
from skypilot_tpu.provision.gcp import rest

logger = sky_logging.init_logger(__name__)

BASE = 'https://tpu.googleapis.com/v2'

# TPU node lifecycle states (reference: instance_utils.py:1207-1214).
PENDING_STATES = ('CREATING', 'STARTING', 'RESTARTING', 'REPAIRING')
RUNNING_STATE = 'READY'
STOPPING_STATES = ('STOPPING',)
STOPPED_STATES = ('STOPPED', 'SUSPENDED')
# States a node can never leave: spot preemption / external kill. The
# node object lingers in the API until deleted.
DEAD_STATES = ('PREEMPTED', 'TERMINATED', 'DELETING')

# Queued-resource lifecycle states.
QR_PENDING = ('CREATING', 'ACCEPTED', 'PROVISIONING', 'WAITING_FOR_RESOURCES')
QR_ACTIVE = 'ACTIVE'
QR_TERMINAL_BAD = ('FAILED', 'SUSPENDED', 'SUSPENDING')

CLUSTER_LABEL = 'xsky-cluster'
HEAD_LABEL = 'xsky-head'


class TpuClient:

    def __init__(self, project: str, zone: str,
                 transport: Optional[rest.Transport] = None) -> None:
        self.project = project
        self.zone = zone
        self.t = transport or rest.Transport()
        self.parent = f'projects/{project}/locations/{zone}'

    # ---- nodes ----

    def create_node(self, node_id: str, body: Dict[str, Any]
                    ) -> Dict[str, Any]:
        return self.t.request('POST', f'{BASE}/{self.parent}/nodes',
                              params={'nodeId': node_id}, body=body)

    def get_node(self, node_id: str) -> Dict[str, Any]:
        return self.t.request('GET', f'{BASE}/{self.parent}/nodes/{node_id}')

    def list_nodes(self, cluster_name: Optional[str] = None
                   ) -> List[Dict[str, Any]]:
        nodes: List[Dict[str, Any]] = []
        page: Optional[str] = None
        while True:
            params = {'pageSize': '100'}
            if page:
                params['pageToken'] = page
            resp = self.t.request('GET', f'{BASE}/{self.parent}/nodes',
                                  params=params)
            nodes.extend(resp.get('nodes', []))
            page = resp.get('nextPageToken')
            if not page:
                break
        if cluster_name is not None:
            nodes = [n for n in nodes
                     if n.get('labels', {}).get(CLUSTER_LABEL) ==
                     cluster_name]
        return nodes

    def delete_node(self, node_id: str) -> Dict[str, Any]:
        return self.t.request('DELETE',
                              f'{BASE}/{self.parent}/nodes/{node_id}')

    def stop_node(self, node_id: str) -> Dict[str, Any]:
        return self.t.request(
            'POST', f'{BASE}/{self.parent}/nodes/{node_id}:stop')

    def start_node(self, node_id: str) -> Dict[str, Any]:
        return self.t.request(
            'POST', f'{BASE}/{self.parent}/nodes/{node_id}:start')

    # ---- queued resources ----

    def create_queued_resource(self, qr_id: str, body: Dict[str, Any]
                               ) -> Dict[str, Any]:
        return self.t.request('POST',
                              f'{BASE}/{self.parent}/queuedResources',
                              params={'queuedResourceId': qr_id}, body=body)

    def get_queued_resource(self, qr_id: str) -> Dict[str, Any]:
        return self.t.request(
            'GET', f'{BASE}/{self.parent}/queuedResources/{qr_id}')

    def delete_queued_resource(self, qr_id: str,
                               force: bool = True) -> Dict[str, Any]:
        return self.t.request(
            'DELETE', f'{BASE}/{self.parent}/queuedResources/{qr_id}',
            params={'force': 'true'} if force else None)

    def list_queued_resources(self, cluster_name: Optional[str] = None
                              ) -> List[Dict[str, Any]]:
        resp = self.t.request('GET',
                              f'{BASE}/{self.parent}/queuedResources')
        qrs = resp.get('queuedResources', [])
        if cluster_name is not None:
            qrs = [q for q in qrs
                   if q.get('tpu', {}).get('nodeSpec', [{}])[0]
                   .get('node', {}).get('labels', {})
                   .get(CLUSTER_LABEL) == cluster_name]
        return qrs

    # ---- operations ----

    def wait_operation(self, op: Dict[str, Any],
                       timeout: float = 1800.0,
                       poll_interval: float = 5.0) -> Dict[str, Any]:
        """Poll a long-running operation until done; raise on error."""
        name = op.get('name')
        if not name or op.get('done'):
            return op
        deadline = time.time() + timeout
        while time.time() < deadline:
            cur = self.t.request('GET', f'{BASE}/{name}')
            if cur.get('done'):
                err = cur.get('error')
                if err:
                    api_err = rest.GcpApiError(
                        int(err.get('code', 500)),
                        str(err.get('status', err.get('code', ''))),
                        err.get('message', 'operation failed'))
                    raise rest.classify_error(api_err, self.zone)
                return cur
            time.sleep(poll_interval)
        raise exceptions.ProvisionError(
            f'Timed out waiting for TPU operation {name}')


def cluster_tag(cluster_name: str) -> str:
    """Per-cluster network tag: open_ports firewall rules target it,
    so opened ports hit only this cluster's hosts (twin of the
    reference's cluster-tag-scoped allow rules,
    sky/provision/gcp/config.py). Network tags must be RFC1035
    (lowercase, ≤63 chars)."""
    return f'xsky-{cluster_name}'[:63].rstrip('-')


def node_body(node_config: Dict[str, Any], cluster_name: str,
              is_head: bool, node_index: int) -> Dict[str, Any]:
    """Build a TPU node resource from deploy variables.

    Deploy-variable names come from GCP.make_deploy_resources_variables
    (skypilot_tpu/clouds/gcp.py) — the twin of the reference's TPU
    resource vars (sky/clouds/gcp.py:495-527).
    """
    labels = dict(node_config.get('labels', {}))
    labels[CLUSTER_LABEL] = cluster_name
    labels[HEAD_LABEL] = 'true' if is_head else 'false'
    labels['xsky-node-index'] = str(node_index)
    body: Dict[str, Any] = {
        'acceleratorType': node_config['tpu_accelerator_type'],
        'runtimeVersion': node_config['tpu_runtime_version'],
        'labels': labels,
        'networkConfig': {
            'enableExternalIps':
                node_config.get('enable_external_ips', True),
        },
        'metadata': dict(node_config.get('metadata', {})),
        # Cluster tag scopes open_ports firewall rules to this
        # cluster's hosts.
        'tags': ['xsky', cluster_tag(cluster_name)],
    }
    network = node_config.get('network')
    subnetwork = node_config.get('subnetwork')
    if network:
        body['networkConfig']['network'] = network
    if subnetwork:
        body['networkConfig']['subnetwork'] = subnetwork
    if node_config.get('use_spot'):
        body['schedulingConfig'] = {'preemptible': True}
    if node_config.get('reservation'):
        body['schedulingConfig'] = {
            'reserved': True,
            'reservationName': node_config['reservation'],
        }
    if node_config.get('service_account'):
        body['serviceAccount'] = {
            'email': node_config['service_account'],
            'scope': ['https://www.googleapis.com/auth/cloud-platform'],
        }
    if node_config.get('volumes'):
        # TPU VMs take persistent disks via dataDisks at create time
        # (no post-hoc attach like compute VMs). READ_ONLY_MANY allows
        # one disk across all hosts/slices; READ_WRITE is single-host.
        body['dataDisks'] = [{
            'sourceDisk': vol.get('source', vol['name']),
            'mode': ('READ_ONLY_MANY'
                     if vol.get('attach_mode') == 'read_only'
                     else 'READ_WRITE'),
        } for vol in node_config['volumes']]
    return body


def queued_resource_body(node_config: Dict[str, Any], cluster_name: str,
                         qr_id: str, node_index: int,
                         num_slices: int) -> Dict[str, Any]:
    """Queued-resource request; multislice via multiNodeParams."""
    parent_body = node_body(node_config, cluster_name, node_index == 0, 0)
    # Queued-resource node spec disallows these on the inner node.
    node_spec: Dict[str, Any] = {
        'parent': '',  # filled by API from the QR parent
        'node': {k: v for k, v in parent_body.items()
                 if k != 'schedulingConfig'},
    }
    if num_slices > 1:
        node_spec['multiNodeParams'] = {
            'nodeCount': num_slices,
            'nodeIdPrefix': qr_id,
        }
    else:
        node_spec['nodeId'] = qr_id
    body: Dict[str, Any] = {'tpu': {'nodeSpec': [node_spec]}}
    if node_config.get('use_spot'):
        body['spot'] = {}
    elif node_config.get('reservation'):
        body['guaranteed'] = {'reserved': True}
        body['reservationName'] = node_config['reservation']
    valid_until = node_config.get('provision_timeout_s')
    if valid_until:
        body['queueingPolicy'] = {
            'validUntilDuration': f'{int(valid_until)}s'}
    return body


def node_instance_infos(node: Dict[str, Any]) -> List[Dict[str, Any]]:
    """One InstanceInfo dict per host from a node's networkEndpoints.

    Reference behavior: per-host IPs from networkEndpoints
    (sky/provision/gcp/instance_utils.py:1649-1670).
    """
    name = node.get('name', '')
    node_id = name.split('/')[-1]
    state = node.get('state', 'UNKNOWN')
    endpoints = node.get('networkEndpoints') or [{}]
    infos = []
    for idx, ep in enumerate(endpoints):
        infos.append({
            'instance_id': f'{node_id}-host{idx}',
            'internal_ip': ep.get('ipAddress', ''),
            'external_ip': (ep.get('accessConfig') or {}).get('externalIp'),
            'status': state,
            'tags': dict(node.get('labels', {})),
            'slice_id': node_id,
            'host_index': idx,
        })
    return infos
