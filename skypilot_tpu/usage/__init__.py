"""Usage telemetry (twin of sky/usage/)."""
