"""vSphere provisioner op-set (VMs cloned from a template, via the
nodepool base).

Behavioral twin of sky/provision/vsphere/instance.py. Platform facts:
on-prem vCenter — "instances" are VMs cloned from a template VM named
in the provider config (``template_vm``, default ``xsky-template``;
same role as the reference's content-library images), powered on/off
via the power API, reached at the guest IP VMware Tools reports.
Instance types (cpu-N-mem-M) resize the clone's CPU/memory. Cost 0:
like SSH pools and Kubernetes, BYO capacity ranks first when it fits.
"""
from __future__ import annotations

import time
from typing import Any, Dict, List, Optional

from skypilot_tpu import exceptions
from skypilot_tpu.provision import common
from skypilot_tpu.provision import nodepool
from skypilot_tpu.provision.vsphere import rest

_transport_factory = rest.Transport


def set_transport_factory(factory) -> None:
    global _transport_factory
    _transport_factory = factory


class VsphereApi(nodepool.NodeApi):
    provider_name = 'vsphere'
    ssh_user = 'ubuntu'
    supports_stop = True
    state_map = {
        'powered_on': 'RUNNING',
        'poweredon': 'RUNNING',
        'powered_off': 'STOPPED',
        'poweredoff': 'STOPPED',
        'suspended': 'STOPPED',
    }

    def __init__(self, provider_config: Dict[str, Any]) -> None:
        self.t = _transport_factory()
        self.config = provider_config or {}

    def _vm_ip(self, vm_id: str) -> Optional[str]:
        try:
            nics = self.t.call(
                'GET',
                f'/api/vcenter/vm/{vm_id}/guest/networking/interfaces')
        except rest.VsphereApiError:
            return None  # VMware Tools not up yet
        for nic in nics or []:
            for addr in (nic.get('ip', {}) or {}).get(
                    'ip_addresses', []):
                ip = addr.get('ip_address', '')
                if ip and ':' not in ip and not ip.startswith('169.254'):
                    return ip
        return None

    def list_nodes(self) -> List[Dict[str, Any]]:
        vms = self.t.call('GET', '/api/vcenter/vm') or []
        out = []
        for vm in vms:
            name = vm.get('name', '')
            if not name.startswith('xsky-'):
                continue
            vm_id = vm.get('vm')
            state = str(vm.get('power_state', '')).lower()
            ip = self._vm_ip(vm_id) if state == 'powered_on' else None
            out.append({'id': vm_id,
                        # nodepool membership matches '<cluster>-<i>';
                        # the vSphere VM name carries an xsky- prefix to
                        # keep unrelated inventory out.
                        'name': name[len('xsky-'):],
                        'status': state,
                        'public_ip': ip, 'private_ip': ip})
        return out

    def _template_id(self) -> str:
        template = self.config.get('template_vm', 'xsky-template')
        vms = self.t.call('GET', '/api/vcenter/vm',
                          query=f'names={template}') or []
        if not vms:
            raise exceptions.ProvisionError(
                f'vSphere template VM {template!r} not found; create an '
                'Ubuntu template VM (with VMware Tools + your SSH key) '
                'or set provider config template_vm.')
        return vms[0]['vm']

    def create_node(self, name: str, region: str, zone: Optional[str],
                    node_config: Dict[str, Any]) -> str:
        del region, zone  # placement follows the template's cluster
        body: Dict[str, Any] = {
            'source': self._template_id(),
            'name': f'xsky-{name}',
            'power_on': True,
        }
        itype = node_config.get('instance_type') or ''
        # Grammar cpu-<N>-mem-<GiB>: resize the clone's hardware.
        parts = itype.split('-')
        if len(parts) == 4 and parts[0] == 'cpu' and parts[2] == 'mem':
            body['hardware_customization'] = {
                'cpu_update': {'num_cpus': int(parts[1])},
                'memory_update': {'memory': int(parts[3]) * 1024},
            }
        reply = self.t.call('POST', '/api/vcenter/vm', body=body,
                            query='action=clone')
        return str(reply if isinstance(reply, str) else
                   reply.get('value', reply))

    def delete_node(self, node_id: str) -> None:
        # Power off first: vCenter refuses to delete a running VM.
        try:
            self.t.call('POST',
                        f'/api/vcenter/vm/{node_id}/power',
                        query='action=stop')
        except rest.VsphereApiError:
            pass  # already off
        self.t.call('DELETE', f'/api/vcenter/vm/{node_id}')

    def stop_node(self, node_id: str) -> None:
        self.t.call('POST', f'/api/vcenter/vm/{node_id}/power',
                    query='action=stop')

    def start_node(self, node_id: str) -> None:
        self.t.call('POST', f'/api/vcenter/vm/{node_id}/power',
                    query='action=start')

    def classify(self, e: Exception,
                 region: Optional[str] = None) -> Exception:
        if isinstance(e, rest.VsphereApiError):
            return rest.classify_error(e, region)
        return e


def _api(provider_config: Dict[str, Any]) -> VsphereApi:
    return VsphereApi(provider_config)


def run_instances(region: str, zone: Optional[str], cluster_name: str,
                  config: common.ProvisionConfig) -> common.ProvisionRecord:
    return nodepool.run_instances(_api(config.provider_config), region,
                                  zone, cluster_name, config)


def wait_instances(region: str, cluster_name: str, state: str,
                   provider_config: Optional[Dict[str, Any]] = None,
                   timeout_s: float = 900.0,
                   poll_interval_s: float = 5.0) -> None:
    del region
    api = _api(provider_config or {})
    nodepool.wait_instances(api, cluster_name, state, timeout_s,
                            poll_interval_s)
    if state == 'RUNNING':
        # RUNNING means powered on; SSH needs the guest IP, which only
        # appears once VMware Tools is up — wait for it too.
        deadline = time.time() + timeout_s
        while time.time() < deadline:
            nodes = nodepool._cluster_nodes(api, cluster_name)
            if nodes and all(n.get('public_ip') for n in nodes):
                return
            time.sleep(poll_interval_s)
        raise exceptions.ProvisionError(
            f'vSphere cluster {cluster_name!r} has no guest IPs after '
            f'{timeout_s}s (is VMware Tools installed in the '
            'template?).')


def stop_instances(cluster_name: str,
                   provider_config: Dict[str, Any]) -> None:
    nodepool.stop_instances(_api(provider_config), cluster_name)


def terminate_instances(cluster_name: str,
                        provider_config: Dict[str, Any]) -> None:
    nodepool.terminate_instances(_api(provider_config), cluster_name)


def query_instances(cluster_name: str, provider_config: Dict[str, Any]
                    ) -> Dict[str, Optional[str]]:
    return nodepool.query_instances(_api(provider_config), cluster_name)


def get_cluster_info(region: str, cluster_name: str,
                     provider_config: Dict[str, Any]
                     ) -> common.ClusterInfo:
    del region
    return nodepool.get_cluster_info(_api(provider_config), cluster_name,
                                     provider_config)


def open_ports(cluster_name: str, ports: List[str],
               provider_config: Dict[str, Any]) -> None:
    # On-prem networking: reachability is the site's own policy.
    del cluster_name, ports, provider_config


def cleanup_ports(cluster_name: str,
                  provider_config: Dict[str, Any]) -> None:
    del cluster_name, provider_config
