"""In-memory provisioner for the fake cloud — the failover test harness.

Plays moto's role from the reference's tests (tests/test_failover.py:34-60):
clusters live in a module-level store; capacity/quota errors are scripted
per zone via :class:`FailureInjector`; preemption is simulated by calling
:func:`preempt_cluster` out-of-band (the reference smoke tests terminate
instances manually, smoke_tests_utils.py:33-36).

TPU semantics modeled faithfully:
  * a TPU node_config (tpu_vm=True) creates `tpu_num_hosts × num_slices`
    host InstanceInfos sharing slice ids;
  * multi-host slices refuse stop_instances (NotSupportedError), like
    the real TPU API.
"""
from __future__ import annotations

import dataclasses
import threading
import uuid
from typing import Any, Dict, List, Optional

from skypilot_tpu import exceptions
from skypilot_tpu.provision import common

_lock = threading.RLock()
# cluster_name → {'zone': str, 'region': str, 'instances': {id: InstanceInfo},
#                 'head_id': str, 'node_config': dict}
_clusters: Dict[str, Dict[str, Any]] = {}
_ip_counter = [10]


class FailureInjector:
    """Scripted provisioning failures, keyed by zone (or '*')."""

    def __init__(self) -> None:
        self._errors: Dict[str, List[Exception]] = {}
        self.attempts: List[str] = []   # zones tried, in order

    def fail_zone(self, zone: str, error: Exception,
                  times: int = 10**9) -> None:
        self._errors.setdefault(zone, []).extend([error] * min(times, 1000))

    def check(self, zone: str) -> None:
        self.attempts.append(zone)
        for key in (zone, '*'):
            queue = self._errors.get(key)
            if queue:
                raise queue.pop(0)

    def reset(self) -> None:
        self._errors.clear()
        self.attempts.clear()


injector = FailureInjector()


def reset() -> None:
    with _lock:
        _clusters.clear()
        injector.reset()


def _next_ip() -> str:
    with _lock:
        _ip_counter[0] += 1
        n = _ip_counter[0]
    return f'10.0.{n // 256}.{n % 256}'


def run_instances(region: str, zone: Optional[str], cluster_name: str,
                  config: common.ProvisionConfig) -> common.ProvisionRecord:
    zone = zone or f'{region}-a'
    with _lock:
        injector.check(zone)
        existing = _clusters.get(cluster_name)
        if existing is not None:
            resumed = []
            for info in existing['instances'].values():
                if info.status == 'STOPPED':
                    info.status = 'RUNNING'
                    resumed.append(info.instance_id)
            return common.ProvisionRecord(
                provider_name='fake', cluster_name=cluster_name,
                region=existing['region'], zone=existing['zone'],
                resumed_instance_ids=resumed, created_instance_ids=[],
                head_instance_id=existing['head_id'])

        node_cfg = config.node_config
        is_tpu = node_cfg.get('tpu_vm', False)
        hosts_per_slice = node_cfg.get('tpu_num_hosts', 1) if is_tpu else 1
        num_slices = node_cfg.get('tpu_num_slices', 1) if is_tpu else 1
        instances: Dict[str, common.InstanceInfo] = {}
        head_id = None
        for node in range(config.count):
            for s in range(num_slices):
                slice_id = (f'{cluster_name}-n{node}-slice{s}'
                            if is_tpu else None)
                for h in range(hosts_per_slice):
                    iid = f'fake-{uuid.uuid4().hex[:8]}'
                    ip = _next_ip()
                    instances[iid] = common.InstanceInfo(
                        instance_id=iid, internal_ip=ip, external_ip=ip,
                        status='RUNNING',
                        tags={'cluster_name': cluster_name,
                              'node_index': str(node)},
                        slice_id=slice_id,
                        host_index=s * hosts_per_slice + h)
                    if head_id is None:
                        head_id = iid
        _clusters[cluster_name] = {
            'region': region, 'zone': zone, 'instances': instances,
            'head_id': head_id, 'node_config': dict(node_cfg),
        }
        return common.ProvisionRecord(
            provider_name='fake', cluster_name=cluster_name, region=region,
            zone=zone, resumed_instance_ids=[],
            created_instance_ids=list(instances), head_instance_id=head_id)


def stop_instances(cluster_name: str,
                   provider_config: Dict[str, Any]) -> None:
    with _lock:
        cluster = _clusters.get(cluster_name)
        if cluster is None:
            return
        if cluster['node_config'].get('tpu_vm') and \
                cluster['node_config'].get('tpu_num_hosts', 1) > 1:
            raise exceptions.NotSupportedError(
                'Multi-host TPU slices cannot be stopped.')
        for info in cluster['instances'].values():
            info.status = 'STOPPED'


def terminate_instances(cluster_name: str,
                        provider_config: Dict[str, Any]) -> None:
    with _lock:
        _clusters.pop(cluster_name, None)


def query_instances(cluster_name: str, provider_config: Dict[str, Any]
                    ) -> Dict[str, Optional[str]]:
    with _lock:
        cluster = _clusters.get(cluster_name)
        if cluster is None:
            return {}
        return {iid: info.status
                for iid, info in cluster['instances'].items()}


def wait_instances(region: str, cluster_name: str, state: str) -> None:
    return  # fake instances transition instantly


def get_cluster_info(region: str, cluster_name: str,
                     provider_config: Dict[str, Any]) -> common.ClusterInfo:
    with _lock:
        cluster = _clusters.get(cluster_name)
        if cluster is None:
            raise exceptions.ClusterDoesNotExist(cluster_name)
        return common.ClusterInfo(
            instances={k: dataclasses.replace(v)
                       for k, v in cluster['instances'].items()},
            head_instance_id=cluster['head_id'],
            provider_name='fake',
            provider_config=dict(provider_config or {}),
            ssh_user='fake-user')


# ---- test helpers ----------------------------------------------------------


def preempt_cluster(cluster_name: str) -> None:
    """Simulate a spot preemption: instances vanish out-of-band."""
    terminate_instances(cluster_name, {})


def cluster_exists(cluster_name: str) -> bool:
    with _lock:
        return cluster_name in _clusters
