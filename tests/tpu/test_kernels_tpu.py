"""On-silicon Pallas kernel tier (VERDICT r3 #3).

Runs every Pallas kernel through REAL Mosaic lowering + execution on the
attached TPU and pins numerics against the XLA reference path. Interpret
mode (the fast tier) cannot catch Mosaic lowering failures — the decode
kernel shipped un-lowerable for two sessions because only interpret mode
ever ran it (CHANGES_r03.md §Session-3).

Invocation (before bench, whenever the chip is reachable):

    XSKY_TPU_TESTS=1 python -m pytest tests/tpu -m tpu -q

Off-TPU (or with the tunnel down) every test skips cleanly. Shapes are
kept small so each kernel compiles in seconds over the axon tunnel.
"""
import os

import numpy as np
import pytest

pytestmark = pytest.mark.tpu

if not os.environ.get('XSKY_TPU_TESTS'):
    pytest.skip('tpu tier: set XSKY_TPU_TESTS=1 (off-TPU run)',
                allow_module_level=True)

import jax                                                  # noqa: E402
import jax.numpy as jnp                                     # noqa: E402

_DEVICE = jax.devices()[0]
if not getattr(_DEVICE, 'device_kind', '').startswith('TPU'):
    pytest.skip(f'tpu tier: no TPU attached (device '
                f'{getattr(_DEVICE, "device_kind", "?")})',
                allow_module_level=True)

from skypilot_tpu.models import llama                       # noqa: E402
from skypilot_tpu.ops import attention as attention_ops     # noqa: E402
from skypilot_tpu.ops import decode_attention as decode_ops  # noqa: E402
from skypilot_tpu.ops import flash_attention as flash_ops   # noqa: E402
from skypilot_tpu.ops import mla_decode as mla_ops          # noqa: E402
from skypilot_tpu.ops import quantization as qops           # noqa: E402


def _rand(shape, seed, dtype=jnp.float32):
    return jax.random.normal(jax.random.PRNGKey(seed), shape, dtype)


def _assert_close(out, ref, atol):
    np.testing.assert_allclose(np.asarray(out, np.float32),
                               np.asarray(ref, np.float32), atol=atol)


# ---------------------------------------------------------------------------
# Flash attention fwd/bwd (training hot path)
# ---------------------------------------------------------------------------


class TestFlashOnSilicon:
    B, S, H, HKV, D = 1, 512, 4, 2, 64

    def _qkv(self, dtype=jnp.bfloat16):
        q = _rand((self.B, self.S, self.H, self.D), 0, dtype)
        k = _rand((self.B, self.S, self.HKV, self.D), 1, dtype)
        v = _rand((self.B, self.S, self.HKV, self.D), 2, dtype)
        return q, k, v

    def _xla(self, q, k, v, **kw):
        return attention_ops.dot_product_attention(
            q, k, v, causal=True, implementation='xla', **kw)

    def test_fwd_causal_gqa(self):
        q, k, v = self._qkv()
        out = jax.jit(lambda *a: flash_ops.flash_attention(
            *a, causal=True, block_q=128, block_kv=128))(q, k, v)
        _assert_close(out, self._xla(q, k, v), atol=3e-2)

    def test_fwd_windowed_softcap_scale(self):
        """Gemma-2 shape: sliding window + tanh softcap + explicit
        scale, all inside the kernel."""
        q, k, v = self._qkv()
        kw = dict(window=128, logit_softcap=50.0, scale=0.125)
        out = jax.jit(lambda *a: flash_ops.flash_attention(
            *a, causal=True, block_q=128, block_kv=128, **kw))(q, k, v)
        _assert_close(out, self._xla(q, k, v, **kw), atol=3e-2)

    def test_fwd_packed_segments(self):
        q, k, v = self._qkv()
        seg = jnp.concatenate([
            jnp.full((self.B, self.S // 2), 1, jnp.int32),
            jnp.full((self.B, self.S - self.S // 2), 2, jnp.int32),
        ], axis=1)
        out = jax.jit(lambda *a: flash_ops.flash_attention(
            *a, causal=True, block_q=128, block_kv=128,
            segment_ids=seg))(q, k, v)
        ref = self._xla(q, k, v, segment_ids=seg)
        _assert_close(out, ref, atol=3e-2)

    def test_bwd_grads(self):
        """Custom-VJP backward kernels lower + match XLA grads."""
        q, k, v = self._qkv(jnp.float32)

        def loss_flash(q, k, v):
            return flash_ops.flash_attention(
                q, k, v, causal=True, block_q=128,
                block_kv=128).astype(jnp.float32).sum()

        def loss_xla(q, k, v):
            return self._xla(q, k, v).astype(jnp.float32).sum()

        g_flash = jax.jit(jax.grad(loss_flash, argnums=(0, 1, 2)))(
            q, k, v)
        g_xla = jax.jit(jax.grad(loss_xla, argnums=(0, 1, 2)))(q, k, v)
        for gf, gx in zip(g_flash, g_xla):
            _assert_close(gf, gx, atol=5e-2)


# ---------------------------------------------------------------------------
# Decode attention (serving hot path)
# ---------------------------------------------------------------------------


class TestDecodeOnSilicon:

    def _ref(self, q, ck, cv, lengths, window=None, **kw):
        if isinstance(ck, (tuple, list)):
            ck = llama.dequantize_kv(*ck, q.dtype)
            cv = llama.dequantize_kv(*cv, q.dtype)
        kv_pos = jnp.arange(ck.shape[1])[None, None, :]
        q_pos = (lengths - 1)[:, None]
        valid = kv_pos <= q_pos[..., None]
        if window is not None:
            valid = valid & (kv_pos > q_pos[..., None] - window)
        return attention_ops.xla_attention_with_mask(
            q, ck, cv, valid[:, None], **kw)

    def test_decode_bf16_ragged(self):
        b, h_kv, d, max_len = 4, 2, 64, 256
        q = _rand((b, 1, h_kv * 4, d), 0, jnp.bfloat16)
        ck = _rand((b, max_len, h_kv, d), 1, jnp.bfloat16)
        cv = _rand((b, max_len, h_kv, d), 2, jnp.bfloat16)
        lengths = jnp.array([1, max_len, 100, 129], jnp.int32)
        out = jax.jit(lambda *a: decode_ops.decode_attention(
            *a, block_kv=128))(q, ck, cv, lengths)
        _assert_close(out, self._ref(q, ck, cv, lengths), atol=3e-2)

    def test_decode_int8_cache(self):
        b, h_kv, d, max_len = 2, 2, 64, 128
        q = _rand((b, 1, h_kv * 2, d), 3, jnp.bfloat16)
        ck = llama.quantize_kv(_rand((b, max_len, h_kv, d), 4))
        cv = llama.quantize_kv(_rand((b, max_len, h_kv, d), 5))
        lengths = jnp.array([5, 128], jnp.int32)
        out = jax.jit(lambda q, lens: decode_ops.decode_attention(
            q, ck, cv, lens, block_kv=128))(q, lengths)
        _assert_close(out, self._ref(q, ck, cv, lengths), atol=3e-2)

    def test_decode_windowed_softcap(self):
        """Gemma-2 serving: window + softcap + scale in-kernel."""
        b, h_kv, d, max_len = 2, 2, 64, 256
        q = _rand((b, 1, h_kv * 2, d), 6, jnp.bfloat16)
        ck = _rand((b, max_len, h_kv, d), 7, jnp.bfloat16)
        cv = _rand((b, max_len, h_kv, d), 8, jnp.bfloat16)
        lengths = jnp.array([77, 200], jnp.int32)
        kw = dict(window=64, logit_softcap=30.0, scale=0.2)
        out = jax.jit(lambda *a: decode_ops.decode_attention(
            *a, block_kv=128, **kw))(q, ck, cv, lengths)
        ref = self._ref(q, ck, cv, lengths, window=kw['window'],
                        logit_softcap=kw['logit_softcap'],
                        scale=kw['scale'])
        _assert_close(out, ref, atol=3e-2)


# ---------------------------------------------------------------------------
# MLA decode (DeepSeek serving)
# ---------------------------------------------------------------------------


def test_mla_decode_on_silicon():
    b, h, r, dr, max_len = 2, 4, 128, 64, 256
    q_eff = _rand((b, h, r), 0, jnp.bfloat16)
    q_rope = _rand((b, h, dr), 1, jnp.bfloat16)
    ckv = _rand((b, max_len, r), 2, jnp.bfloat16)
    krope = _rand((b, max_len, dr), 3, jnp.bfloat16)
    lengths = jnp.array([33, 250], jnp.int32)
    scale = (r + dr) ** -0.5
    out = jax.jit(lambda *a: mla_ops.mla_decode_attention(
        *a, scale=scale, block_kv=128))(q_eff, q_rope, ckv, krope,
                                        lengths)
    # XLA reference: scores over the latent cache with length mask.
    scores = (jnp.einsum('bhr,bkr->bhk', q_eff.astype(jnp.float32),
                         ckv.astype(jnp.float32))
              + jnp.einsum('bhd,bkd->bhk', q_rope.astype(jnp.float32),
                           krope.astype(jnp.float32))) * scale
    mask = jnp.arange(max_len)[None, None, :] < lengths[:, None, None]
    scores = jnp.where(mask, scores, -jnp.inf)
    probs = jax.nn.softmax(scores, axis=-1)
    ref = jnp.einsum('bhk,bkr->bhr', probs, ckv.astype(jnp.float32))
    _assert_close(out, ref, atol=3e-2)


# ---------------------------------------------------------------------------
# Quantized matmuls (int8 / int4 weights)
# ---------------------------------------------------------------------------


class TestQuantizedOnSilicon:

    @staticmethod
    def _rel(out, ref) -> float:
        out = np.asarray(out, np.float32)
        ref = np.asarray(ref, np.float32)
        return float(np.max(np.abs(out - ref)) / np.max(np.abs(ref)))

    def test_int8_matmul(self):
        x = _rand((8, 256), 0, jnp.bfloat16)
        w = _rand((256, 512), 1, jnp.bfloat16)
        qw = qops.quantize(w)
        out = jax.jit(qops.matmul)(x, qw)
        ref = x @ qops.dequantize(qw, jnp.bfloat16)
        assert self._rel(out, ref) < 0.05

    def test_int4_matmul(self):
        x = _rand((8, 256), 2, jnp.bfloat16)
        w = _rand((256, 512), 3, jnp.bfloat16)
        qw = qops.quantize4(w)
        out = jax.jit(qops.matmul)(x, qw)
        ref = x @ qops.dequantize4(qw, jnp.bfloat16)
        assert self._rel(out, ref) < 0.1


# ---------------------------------------------------------------------------
# Ring attention (context parallelism) — single-device degenerate ring
# ---------------------------------------------------------------------------


def test_ring_attention_single_device_mesh():
    """The ring kernel's shard_map path must lower on the real chip;
    with a 1-device mesh the ring is a no-op and equals plain causal
    attention."""
    from jax.sharding import Mesh
    from skypilot_tpu.ops import ring_attention as ring_ops
    import numpy as onp
    devices = onp.asarray(jax.devices()[:1]).reshape(
        (1, 1, 1, 1, 1, 1))
    mesh = Mesh(devices, ('data', 'stage', 'fsdp', 'sequence',
                          'expert', 'tensor'))
    b, s, h, d = 1, 256, 4, 64
    q = _rand((b, s, h, d), 0, jnp.bfloat16)
    k = _rand((b, s, 2, d), 1, jnp.bfloat16)
    v = _rand((b, s, 2, d), 2, jnp.bfloat16)
    out = ring_ops.sequence_parallel_attention(
        q, k, v, mesh, implementation='ring', causal=True)
    ref = attention_ops.dot_product_attention(
        q, k, v, causal=True, implementation='xla')
    _assert_close(out, ref, atol=3e-2)
