"""Realize storage mounts on every host of a cluster.

Bridge between the backend's sync_file_mounts stage and the data layer
(reference equivalent: CloudVmRayBackend file-mount handling at
sky/backends/cloud_vm_ray_backend.py:3289 + sky/data/mounting_utils.py
command execution).
"""
from __future__ import annotations

from typing import Any, Dict

from skypilot_tpu import exceptions
from skypilot_tpu import sky_logging
from skypilot_tpu.data import storage as storage_lib

logger = sky_logging.init_logger(__name__)


def mount_storage_on_cluster(handle: Any,
                             storage_mounts: Dict[str, Any]) -> None:
    """Run each storage mount's realize command on all hosts."""
    runners = handle.get_command_runners()
    storages = []
    for mount_path, storage in storage_mounts.items():
        if not isinstance(storage, storage_lib.Storage):
            storage = storage_lib.Storage.from_yaml_config(dict(storage))
        storages.append((mount_path, storage))
    # Unprivileged pods need the per-node fusermount broker before any
    # FUSE mount command runs (addons/fuse-proxy; twin of the
    # reference's fusermount-server DaemonSet deploy).
    if (getattr(handle.cluster_info, 'provider_name', None) ==
            'kubernetes' and
            any(s.mode in (storage_lib.StorageMode.MOUNT,
                           storage_lib.StorageMode.MOUNT_CACHED)
                for _, s in storages)):
        from skypilot_tpu.provision.kubernetes import (
            instance as k8s_instance)
        k8s_instance.deploy_fuse_proxy(
            handle.cluster_info.provider_config or {})
    from skypilot_tpu.utils import parallelism
    for mount_path, storage in storages:
        cmd = storage.cluster_command(mount_path)
        logger.info(f'Mounting {storage.name} at {mount_path} '
                    f'({storage.mode.value}) on {len(runners)} host(s)')

        def _mount(pair, cmd=cmd, storage=storage,
                   mount_path=mount_path):
            rank, runner = pair
            rc, _, stderr = runner.run(cmd, require_outputs=True)
            if rc != 0:
                raise exceptions.StorageError(
                    f'Mounting {storage.name} at {mount_path} failed '
                    f'on host {rank} (rc={rc}): {stderr}')

        from skypilot_tpu.utils import tracing
        with tracing.span('backend.storage_mount',
                          cluster=getattr(handle, 'cluster_name', ''),
                          storage=storage.name):
            parallelism.run_in_parallel(
                _mount, list(enumerate(runners)),
                phase='storage_mount',
                what=f'storage mount ({storage.name} at {mount_path})')
