#!/usr/bin/env python3
"""Decode-tick host-cost micro-benchmark (fast vs legacy tick).

Runs the SAME continuous-batching workload through the orchestrator
twice — once with `XSKY_DECODE_FAST_TICK=0` (the legacy tick: per-tick
sampling-param rebuild, per-tick `jax.random.split`, host-side finish
scan over every slot × every fused row) and once with the fused masked
fast path (device-resident params rebuilt only on occupancy change,
pooled step keys, one device_get per tick, device-side finish masking)
— and prints ONE JSON line comparing host cost per committed token:

    {"metric": "decode_tick_host_cost", "decode_steps": 8,
     "legacy_us_per_token": ..., "fast_us_per_token": ...,
     "speedup": ..., "pass": true}

The engine is a deterministic host-side fake (`_FakeEngine`): decode
"compute" is instant, token streams are a pure function of
(slot, position), and `decode_steps_masked` implements exactly the
engine's device-mask semantics (EOS row invalid, budget-exhaust row
valid then deactivate). That isolates the quantity under test — the
ORCHESTRATOR's per-tick host overhead — from model compute, and makes
the two arms' outputs byte-comparable: the bench asserts both arms
commit identical tokens, that the fused arm wastes ZERO post-finish
decode rows, and that the legacy arm (finishing mid-fused-batch)
wastes some.

A second paired-difference rung re-runs the fast arm with the
per-request anatomy recorder (`XSKY_ANATOMY`) on vs off and gates the
recorder's added tick cost under --anatomy-threshold % (default 2%) —
the observability plane must not tax the path it observes.

Each arm's per-token cost also lands in the metrics-history plane as
`xsky_bench_decode_tick_cost_us{arm=...}` so repeated runs against the
same XSKY_STATE_DB build a before/after trend readable via
`metrics_history.series()` — the JSON reports the trend the store
returns.

Usage:
    python tools/bench_decode.py [--slots 8] [--requests 48]
                                 [--max-new 37] [--decode-steps 8]
                                 [--repeats 3] [--threshold 1.5]
                                 [--smoke]
"""
import argparse
import gc
import json
import os
import statistics
import sys
import tempfile
import time

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO_ROOT)

_METRIC = 'xsky_bench_decode_tick_cost_us'


def _setup_env(workdir: str) -> None:
    os.environ.setdefault('JAX_PLATFORMS', 'cpu')
    os.environ.setdefault('XSKY_STATE_DB',
                          os.path.join(workdir, 'state.db'))


class _FakeConfig:
    """The EngineConfig surface the orchestrator reads."""

    def __init__(self, max_slots: int, max_target_len: int):
        self.max_slots = max_slots
        self.max_target_len = max_target_len
        self.prefill_buckets = (max_target_len // 2,)
        self.batched_admission = False
        self.paged = False

    @property
    def max_prompt_len(self) -> int:
        return self.prefill_buckets[-1]


class _FakeEngine:
    """Deterministic host-side engine: decode compute is free, token
    streams are `_tok(slot, position)`, and the masked fused loop
    reproduces the real engine's device-mask semantics exactly — so
    the bench measures the orchestrator's host overhead, nothing else.
    Returns jnp arrays where the real engine would, so both ticks pay
    their genuine `jax.device_get` / `jax.random` costs."""

    supports_chunked_prefill = False
    supports_batched_prefill = False
    supports_verify = False
    kv_page_stats = None

    def __init__(self, config: _FakeConfig):
        self.config = config
        self.max_admit_len = config.max_prompt_len

    # ---- token stream: pure function of (slot, position) ----

    @staticmethod
    def _rows(counts, n):
        """[n, slots] tokens for positions counts..counts+n-1."""
        s = np.arange(counts.shape[0], dtype=np.int64)[None, :]
        c = counts[None, :] + np.arange(n, dtype=np.int64)[:, None]
        return ((s * 131 + c * 31) % 97 + 3).astype(np.int32)

    # ---- engine API used by the orchestrator ----

    def init_decode_state(self):
        s = self.config.max_slots
        return {'counts': np.zeros((s,), np.int64),
                'active': np.zeros((s,), bool)}

    def bucket_for(self, length: int) -> int:
        return self.config.prefill_buckets[-1]

    def reserve_kv(self, slot, prompt_len, max_new) -> bool:
        return True

    def release_kv(self, slot) -> None:
        pass

    def kv_admissible(self, prompt_len, max_new) -> bool:
        return True

    def prefill_any(self, prompt_tokens, sampling_params=None,
                    key=None, logprobs_k: int = 0):
        first = (sum(prompt_tokens) % 97) + 3
        out = (first, None, len(prompt_tokens))
        if logprobs_k:
            lp = (np.zeros((1,), np.float32),
                  np.zeros((1, logprobs_k), np.float32),
                  np.zeros((1, logprobs_k), np.int32))
            return out + (lp,)
        return out

    def insert(self, state, kv, first_token, true_len, slot):
        state = dict(state)
        counts = state['counts'].copy()
        active = state['active'].copy()
        counts[slot] = 0
        active[slot] = True
        state['counts'], state['active'] = counts, active
        return state

    def release_slot(self, state, slot):
        state = dict(state)
        active = state['active'].copy()
        active[slot] = False
        state['active'] = active
        return state

    def _lp(self, n, k):
        # numpy throughout: a real engine's outputs are already device
        # arrays (jit results — no host→device put on return), so the
        # fake must not charge either arm put costs for return values;
        # the orchestrator's device_get is a no-op on numpy for both.
        s = self.config.max_slots
        return (np.zeros((n, s), np.float32),
                np.zeros((n, s, k), np.float32),
                np.zeros((n, s, k), np.int32))

    def decode_step(self, state, temperatures=None, top_k=None,
                    top_p=None, key=None, logprobs_k=0, penalties=None):
        state, toks, lp = self.decode_steps(
            state, 1, temperatures, top_k, top_p, key,
            logprobs_k=logprobs_k, penalties=penalties) \
            if logprobs_k else \
            self.decode_steps(state, 1, temperatures, top_k, top_p,
                              key) + (None,)
        toks = toks[0]
        if logprobs_k:
            return state, toks, tuple(a[0] for a in lp)
        return state, toks

    def decode_steps(self, state, n, temperatures=None, top_k=None,
                     top_p=None, key=None, logprobs_k=0,
                     penalties=None):
        # The real legacy call ships these host numpy arrays to device
        # EVERY tick (the fused-masked path keeps them device-resident
        # and ships only on occupancy change) — charge that put cost
        # here or the bench hides the fast path's biggest win.
        for a in (temperatures, top_k, top_p) + (penalties or ()):
            if a is not None:
                jnp.asarray(a).block_until_ready()
        toks = self._rows(state['counts'], n)
        state = dict(state)
        state['counts'] = state['counts'] + n
        out = (state, toks)
        if logprobs_k:
            return out + (self._lp(n, logprobs_k),)
        return out

    def decode_steps_masked(self, state, n, temperatures, top_k, top_p,
                            eos_ids, remaining, keys, logprobs_k=0,
                            penalties=None):
        toks = self._rows(state['counts'], n)
        eos = np.asarray(eos_ids)
        rem = np.asarray(remaining).astype(np.int64).copy()
        active = state['active'].copy()
        valid = np.zeros((n, active.shape[0]), bool)
        for i in range(n):
            hit = active & (eos >= 0) & (toks[i] == eos)
            keep = active & ~hit
            rem -= keep
            exhausted = keep & (rem <= 0)
            active = keep & ~exhausted
            valid[i] = keep
        state = dict(state)
        state['counts'] = state['counts'] + n
        state['active'] = active
        lp = self._lp(n, logprobs_k) if logprobs_k else None
        return state, rem.astype(np.int32), toks, valid, lp


def _run_arm(fast: bool, args) -> dict:
    """One full drain of the workload through one tick arm."""
    from skypilot_tpu.infer import orchestrator as orch_lib
    os.environ['XSKY_DECODE_FAST_TICK'] = '1' if fast else '0'
    engine = _FakeEngine(_FakeConfig(args.slots, args.max_new * 4))
    orch = orch_lib.Orchestrator(engine, decode_steps=args.decode_steps)
    # Staggered budgets (max_new + i % n): finishes land at different
    # fused-row offsets, exercising the mid-batch finish the device
    # mask removes from the host scan; budgets are long relative to
    # decode_steps so most ticks are steady-state (occupancy stable —
    # the regime serving decode actually lives in).
    # Sampled decode with top-k/top-p/penalties — the full per-slot
    # param surface the legacy tick rebuilds and ships to device every
    # tick and the fast tick caches device-side. (The fake's token
    # stream ignores sampling params, so outputs stay comparable.)
    reqs = [orch.submit(orch_lib.Request(
        prompt_tokens=[1 + (i % 7), 2, 3],
        max_new_tokens=args.max_new + (i % args.decode_steps),
        temperature=0.8, top_k=40, top_p=0.95,
        presence_penalty=0.1, frequency_penalty=0.1))
        for i in range(args.requests)]
    # GC quiescence: a collection landing inside one arm's drain is
    # the dominant noise source at the few-us-per-token scale the
    # paired rungs resolve.
    gc.collect()
    gc.disable()
    try:
        t0 = time.perf_counter()
        orch.run_until_drained(max_steps=200_000)
        elapsed = time.perf_counter() - t0
    finally:
        gc.enable()
    bad = [r.error for r in reqs if r.error]
    assert not bad, bad
    tokens = sum(len(r.output_tokens) for r in reqs)
    return {'elapsed_s': elapsed, 'tokens': tokens,
            'wasted': orch.wasted_decode_steps,
            'outputs': [r.output_tokens for r in reqs]}


def _record_trend(fast_us: float, legacy_us: float) -> list:
    """Persist both arms' per-token cost and read the trend back —
    repeated runs against one XSKY_STATE_DB accumulate history."""
    from skypilot_tpu.utils import metrics_history
    now = time.time()
    metrics_history.record_points(
        [{'ts': now, 'name': _METRIC, 'labels': {'arm': 'fast'},
          'kind': 'gauge', 'value': fast_us},
         {'ts': now, 'name': _METRIC, 'labels': {'arm': 'legacy'},
          'kind': 'gauge', 'value': legacy_us}], ts=now)
    trend = metrics_history.series(
        _METRIC, labels={'arm': 'fast'}, since=now - 3600.0,
        until=now + 1.0, res='raw')
    return [(round(ts, 1), v) for ts, v in trend if v is not None]


def main() -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument('--slots', type=int, default=8)
    parser.add_argument('--requests', type=int, default=16)
    parser.add_argument('--max-new', type=int, default=120)
    parser.add_argument('--decode-steps', type=int, default=8)
    parser.add_argument('--repeats', type=int, default=3)
    parser.add_argument('--threshold', type=float, default=1.5,
                        help='minimum legacy/fast host-cost ratio')
    parser.add_argument('--anatomy-threshold', type=float, default=2.0,
                        help='max %% tick cost the anatomy recorder '
                             'may add on the fast path (paired '
                             'on/off difference)')
    parser.add_argument('--smoke', action='store_true',
                        help='small workload for the tier-1 gate')
    args = parser.parse_args()
    if args.smoke:
        args.requests = min(args.requests, 12)
        args.repeats = min(args.repeats, 3)

    scratch = tempfile.mkdtemp(prefix='xsky-bench-decode-')
    _setup_env(scratch)
    global np, jnp  # after JAX_PLATFORMS is pinned
    import numpy as np                     # noqa: E402
    import jax.numpy as jnp                # noqa: E402

    # Untimed warmup: first-call costs (jax dispatch caches, lazy
    # imports) must not land on whichever measured arm goes first.
    _run_arm(False, args)
    warm = _run_arm(True, args)
    # Interleaved best-of-N: min-of-N per arm suppresses scheduler
    # jitter that dwarfs the per-tick effect under test.
    legacy_runs, fast_runs = [], []
    legacy = fast = None
    for _ in range(args.repeats):
        legacy = _run_arm(False, args)
        fast = _run_arm(True, args)
        legacy_runs.append(legacy['elapsed_s'] / legacy['tokens'])
        fast_runs.append(fast['elapsed_s'] / fast['tokens'])

    same_outputs = (fast['outputs'] == legacy['outputs']
                    and warm['outputs'] == fast['outputs'])
    legacy_us = min(legacy_runs) * 1e6
    fast_us = min(fast_runs) * 1e6
    speedup = legacy_us / fast_us
    trend = _record_trend(fast_us, legacy_us)

    # Anatomy-recorder overhead rung (paired difference): the SAME
    # fast-tick workload with the per-request phase accumulators on
    # vs off. The recorder amortizes ONE timestamp pair per fused
    # batch plus per-resident float adds — the gate holds that under
    # --anatomy-threshold % of tick cost, with a 0.5 us/token
    # absolute floor. The floor matters because the fake engine's
    # tick is far cheaper than a real model's: the recorder's fixed
    # ~5 us/tick amortizes to a visible fraction here but to noise on
    # a real tick, while the regression this rung exists to catch —
    # per-TOKEN timestamping creeping into the commit loop — costs
    # >= 1 us/token and clears the floor regardless. ABBA order
    # alternation keeps warmup drift off either arm, and longer
    # decode budgets stretch each timed drain past the timer-noise
    # floor.
    anat_args = argparse.Namespace(**vars(args))
    anat_args.max_new = args.max_new * 4
    anat_on_runs, anat_off_runs = [], []
    anat_on = anat_off = None
    # More pairs than the speedup rung: the effect under test is an
    # order of magnitude smaller, and each drain is ~100 ms — seven
    # pairs buy a stable min/median for ~1 s of wall clock.
    for i in range(max(7, args.repeats)):
        order = (True, False) if i % 2 == 0 else (False, True)
        for on in order:
            os.environ['XSKY_ANATOMY'] = '1' if on else '0'
            res = _run_arm(True, anat_args)
            if on:
                anat_on = res
                anat_on_runs.append(res['elapsed_s'] / res['tokens'])
            else:
                anat_off = res
                anat_off_runs.append(
                    res['elapsed_s'] / res['tokens'])
    os.environ.pop('XSKY_ANATOMY', None)
    anat_off_us = statistics.median(anat_off_runs) * 1e6
    anat_on_us = statistics.median(anat_on_runs) * 1e6
    # Two upper-biased estimators of the added cost: the median of
    # back-to-back pair differences (shared thermal/frequency state
    # resolves a ~1% effect) and min-vs-min (each arm's quietest run;
    # scheduler noise is strictly additive). A noise burst inflates
    # either one, but rarely both the same way — the smaller is the
    # tighter bound on the true recorder cost.
    paired_us = statistics.median(
        (on_r - off_r) * 1e6
        for on_r, off_r in zip(anat_on_runs, anat_off_runs))
    best_us = (min(anat_on_runs) - min(anat_off_runs)) * 1e6
    anat_added_us = max(0.0, min(paired_us, best_us))
    anat_pct = anat_added_us / anat_off_us * 100.0
    anatomy_ok = (anat_pct < args.anatomy_threshold
                  or anat_added_us < 0.5)
    anatomy_same = anat_on['outputs'] == anat_off['outputs']

    ok = (speedup >= args.threshold
          and same_outputs
          and fast['wasted'] == 0
          and legacy['wasted'] > 0
          and anatomy_ok
          and anatomy_same)
    print(json.dumps({
        'metric': 'decode_tick_host_cost',
        'decode_steps': args.decode_steps,
        'slots': args.slots,
        'requests': args.requests,
        'tokens_per_arm': fast['tokens'],
        'legacy_us_per_token': round(legacy_us, 2),
        'fast_us_per_token': round(fast_us, 2),
        'legacy_runs_us': [round(r * 1e6, 2) for r in legacy_runs],
        'fast_runs_us': [round(r * 1e6, 2) for r in fast_runs],
        'speedup': round(speedup, 2),
        'identical_outputs': same_outputs,
        'fast_wasted_steps': fast['wasted'],
        'legacy_wasted_steps': legacy['wasted'],
        'trend_points': trend,
        'threshold': args.threshold,
        'anatomy_on_us_per_token': round(anat_on_us, 2),
        'anatomy_off_us_per_token': round(anat_off_us, 2),
        'anatomy_added_us_per_token': round(anat_added_us, 3),
        'anatomy_overhead_pct': round(anat_pct, 2),
        'anatomy_threshold_pct': args.anatomy_threshold,
        'anatomy_identical_outputs': anatomy_same,
        'anatomy_pass': anatomy_ok,
        'pass': ok,
    }))
    return 0 if ok else 1


if __name__ == '__main__':
    sys.exit(main())
