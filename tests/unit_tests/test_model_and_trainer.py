"""Model forward/backward + sharded trainer tests on the 8-device CPU mesh."""
import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from skypilot_tpu.models import llama
from skypilot_tpu.ops import attention as attention_ops
from skypilot_tpu.parallel import mesh as mesh_lib
from skypilot_tpu.train import trainer as trainer_lib


pytestmark = pytest.mark.slow  # heavy tier: subprocess e2e / jit compiles


@pytest.fixture(scope='module')
def tiny():
    return llama.LLAMA_TINY


class TestAttention:

    def test_causal_matches_manual(self):
        key = jax.random.PRNGKey(0)
        q = jax.random.normal(key, (2, 16, 4, 8))
        k = jax.random.normal(jax.random.PRNGKey(1), (2, 16, 2, 8))
        v = jax.random.normal(jax.random.PRNGKey(2), (2, 16, 2, 8))
        out = attention_ops.xla_attention(q, k, v, causal=True)
        assert out.shape == (2, 16, 4, 8)
        # Position 0 attends only to itself: out[:,0] == v[:,0] repeated.
        np.testing.assert_allclose(out[:, 0, 0], v[:, 0, 0], rtol=1e-5)
        np.testing.assert_allclose(out[:, 0, 1], v[:, 0, 0], rtol=1e-5)
        np.testing.assert_allclose(out[:, 0, 2], v[:, 0, 1], rtol=1e-5)

    def test_gqa_group_mapping(self):
        # With 4 q-heads and 2 kv-heads, heads (0,1)->kv0, (2,3)->kv1.
        q = jnp.ones((1, 4, 4, 8))
        k = jnp.ones((1, 4, 2, 8))
        v = jnp.arange(2.0)[None, None, :, None] * jnp.ones((1, 4, 2, 8))
        out = attention_ops.xla_attention(q, k, v, causal=False)
        np.testing.assert_allclose(out[0, 0, 0], np.zeros(8), atol=1e-6)
        np.testing.assert_allclose(out[0, 0, 3], np.ones(8), atol=1e-6)


class TestModel:

    def test_forward_shapes(self, tiny):
        params = llama.init(tiny, jax.random.PRNGKey(0))
        tokens = jnp.zeros((2, 16), jnp.int32)
        logits = llama.forward(tiny, params, tokens)
        assert logits.shape == (2, 16, tiny.vocab_size)
        assert logits.dtype == jnp.float32

    def test_causality(self, tiny):
        """Changing a future token must not affect past logits."""
        params = llama.init(tiny, jax.random.PRNGKey(0))
        t1 = jnp.zeros((1, 16), jnp.int32)
        t2 = t1.at[0, 10].set(7)
        l1 = llama.forward(tiny, params, t1)
        l2 = llama.forward(tiny, params, t2)
        np.testing.assert_allclose(l1[0, :10], l2[0, :10], atol=1e-4)
        assert not np.allclose(l1[0, 10:], l2[0, 10:], atol=1e-4)

    def test_loss_decreases(self, tiny):
        cfg = trainer_lib.TrainConfig(
            model=tiny, global_batch_size=8, seq_len=32,
            learning_rate=1e-2, warmup_steps=1,
            mesh_plan=mesh_lib.MeshPlan())
        tr = trainer_lib.Trainer(cfg)
        state = tr.init_state()
        batch = tr.synthetic_batch()
        losses = []
        for _ in range(5):
            state, metrics = tr.step(state, batch)
            losses.append(float(metrics['loss']))
        assert losses[-1] < losses[0]

    def test_param_count_formula(self, tiny):
        params = llama.init(tiny, jax.random.PRNGKey(0))
        actual = sum(x.size for x in jax.tree.leaves(params))
        assert actual == tiny.num_params()


class TestMesh:

    def test_plan_resolution(self):
        plan = mesh_lib.MeshPlan(fsdp=4).resolve(8)
        assert plan.data == 2 and plan.fsdp == 4

    def test_plan_mismatch_raises(self):
        with pytest.raises(ValueError):
            mesh_lib.MeshPlan(data=3, fsdp=3).resolve(8)

    def test_build_mesh_8dev(self):
        mesh = mesh_lib.build_mesh(mesh_lib.MeshPlan(fsdp=4, tensor=2))
        assert mesh.shape['fsdp'] == 4
        assert mesh.shape['tensor'] == 2
        assert mesh.shape['data'] == 1

    def test_logical_to_spec(self):
        spec = mesh_lib.logical_to_spec(('batch', None, 'embed'))
        assert spec == mesh_lib.PartitionSpec(('data', 'fsdp'), None, None)
        # 'embed' dropped because fsdp already used by batch.
        spec2 = mesh_lib.logical_to_spec(('vocab', 'embed'))
        assert spec2 == mesh_lib.PartitionSpec('tensor', 'fsdp')


class TestShardedTraining:

    @pytest.mark.parametrize('plan', [
        mesh_lib.MeshPlan(fsdp=8),
        mesh_lib.MeshPlan(fsdp=4, tensor=2),
        mesh_lib.MeshPlan(data=2, fsdp=2, tensor=2),
        mesh_lib.MeshPlan(data=2, fsdp=2, sequence=1, tensor=2),
    ])
    def test_step_runs_sharded(self, tiny, plan):
        cfg = trainer_lib.TrainConfig(model=tiny, global_batch_size=8,
                                      seq_len=32, mesh_plan=plan)
        tr = trainer_lib.Trainer(cfg)
        state = tr.init_state()
        batch = tr.synthetic_batch()
        state, metrics = tr.step(state, batch)
        assert np.isfinite(float(metrics['loss']))

    def test_sharded_matches_single_device(self, tiny):
        """FSDP-sharded step must be numerically equal to unsharded."""
        model = dataclasses.replace(tiny, remat=False)
        cfg1 = trainer_lib.TrainConfig(model=model, global_batch_size=8,
                                       seq_len=32,
                                       mesh_plan=mesh_lib.MeshPlan(fsdp=8))
        cfg2 = trainer_lib.TrainConfig(model=model, global_batch_size=8,
                                       seq_len=32,
                                       mesh_plan=mesh_lib.MeshPlan(data=1))
        tr1 = trainer_lib.Trainer(cfg1)
        tr2 = trainer_lib.Trainer(
            cfg2, mesh=mesh_lib.build_mesh(cfg2.mesh_plan,
                                           devices=jax.devices()[:1]))
        s1, s2 = tr1.init_state(), tr2.init_state()
        b1, b2 = tr1.synthetic_batch(), tr2.synthetic_batch()
        _, m1 = tr1.step(s1, b1)
        _, m2 = tr2.step(s2, b2)
        assert float(m1['loss']) == pytest.approx(float(m2['loss']),
                                                  rel=1e-4)
