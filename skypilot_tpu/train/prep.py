"""Corpus prep: text files → token shards the data loader reads.

    python -m skypilot_tpu.train.prep --out corpus.bin \
        --tokenizer byte --vocab-size 32768 docs/*.txt

Output is the loader's shard format (train/data.py: raw little-endian
uint32 token stream). Documents are separated by the tokenizer's EOS
token, which pairs with training's ``--packing-reset-eos``: attention
and RoPE then reset at exactly these boundaries. `--tokenizer` takes
``byte`` (the built-in reversible byte-level tokenizer — no files, no
egress) or a local HuggingFace tokenizer directory.

Role-twin of the corpus-prep step the reference's training recipes
assume has already happened upstream (their token datasets arrive
preprocessed); here it is a first-class verb so the end-to-end
text → tokens → packed pretraining path needs nothing external.
"""
from __future__ import annotations

import argparse
import json
import sys
from typing import List

import numpy as np

from skypilot_tpu.infer import tokenizer as tokenizer_lib


def prep_files(paths: List[str], out: str, tokenizer,
               append_eos: bool = True,
               vocab_size: int = 0) -> dict:
    """Tokenize `paths` into one shard at `out`; returns a summary.

    With vocab_size > 0, ids outside the model vocab fail fast: the
    training loader clamps out-of-range ids silently (data.batches'
    vocab guard), so an HF tokenizer larger than the model's embedding
    would otherwise corrupt the corpus with no error anywhere.
    """
    n_tokens = 0
    n_docs = 0
    eos = getattr(tokenizer, 'eos_token_id', None)
    with open(out, 'wb') as sink:
        for path in paths:
            with open(path, 'r', encoding='utf-8', errors='replace') as f:
                text = f.read()
            if not text:
                continue
            tokens = tokenizer.encode(text)
            if append_eos and eos is not None:
                tokens = list(tokens) + [eos]
            arr = np.asarray(tokens, dtype=np.uint32)
            if arr.size == 0:
                continue   # text normalized/encoded to nothing
            if vocab_size and int(arr.max()) >= vocab_size:
                raise ValueError(
                    f'{path}: token id {int(arr.max())} >= model vocab '
                    f'{vocab_size} — this tokenizer does not fit the '
                    'target model (the loader would silently clamp '
                    'these ids at training time).')
            arr.astype('<u4').tofile(sink)
            n_tokens += arr.size
            n_docs += 1
    return {'out': out, 'documents': n_docs, 'tokens': n_tokens,
            'eos_separated': bool(append_eos and eos is not None)}


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        description='Tokenize text files into training shards.')
    parser.add_argument('inputs', nargs='+', help='UTF-8 text files')
    parser.add_argument('--out', required=True,
                        help='Output shard path (*.bin)')
    parser.add_argument('--tokenizer', default='byte',
                        help="'byte' or a local HF tokenizer dir")
    parser.add_argument('--vocab-size', type=int, default=32_768,
                        help='Model vocab (byte tokenizer bound check)')
    parser.add_argument('--no-eos', action='store_true',
                        help='Do not separate documents with EOS '
                             '(disables packing_reset_eos pairing)')
    args = parser.parse_args(argv)
    tokenizer = tokenizer_lib.get_tokenizer(args.tokenizer,
                                            args.vocab_size)
    summary = prep_files(args.inputs, args.out, tokenizer,
                         append_eos=not args.no_eos,
                         vocab_size=args.vocab_size)
    print(json.dumps(summary))
    return 0


if __name__ == '__main__':
    sys.exit(main())
