"""Speculative decoding: draft proposes γ tokens, target verifies in
one multi-token pass. The invariant under test everywhere: speculative
greedy output EXACTLY equals plain greedy output, no matter how good or
bad the draft is (draft quality may only change the acceptance rate).

Exactness holds per numeric path: the single-token decode kernel and
the multi-token verify pass are different reduction orders, so with
random weights a near-tied argmax can flip between them (~1e-3 logit
gaps). The tests pin both reference and speculative decoding to the
XLA attention path so token-for-token equality is well-defined.
"""
from __future__ import annotations

import dataclasses
import os

import jax
import numpy as np
import pytest


@pytest.fixture(scope='module', autouse=True)
def _xla_decode_path():
    """Pin decode attention to the XLA path for this module only (the
    engines here are module-scoped, so they trace under it; restoring
    on teardown keeps decode-kernel coverage in other modules)."""
    prev = os.environ.get('XSKY_DECODE_ATTN')
    os.environ['XSKY_DECODE_ATTN'] = 'xla'
    yield
    if prev is None:
        os.environ.pop('XSKY_DECODE_ATTN', None)
    else:
        os.environ['XSKY_DECODE_ATTN'] = prev

from skypilot_tpu.infer import engine as engine_lib
from skypilot_tpu.infer import orchestrator as orch_lib
from skypilot_tpu.models import llama

pytestmark = pytest.mark.slow  # jit compiles

TARGET = dataclasses.replace(llama.LLAMA_TINY, vocab_size=512)
DRAFT = dataclasses.replace(llama.LLAMA_TINY, vocab_size=512,
                            n_layers=1, d_model=32, n_heads=2,
                            n_kv_heads=2, d_ff=64)


def _engine(model, seed, **over):
    config = engine_lib.EngineConfig(
        model=model, max_slots=over.pop('max_slots', 4),
        max_target_len=over.pop('max_target_len', 96),
        prefill_buckets=over.pop('prefill_buckets', (16, 32)))
    params = llama.init(model, jax.random.PRNGKey(seed))
    return engine_lib.InferenceEngine(config, params)


@pytest.fixture(scope='module')
def target_engine():
    return _engine(TARGET, seed=0)


@pytest.fixture(scope='module')
def draft_engine():
    return _engine(DRAFT, seed=7)


PROMPTS = [[5, 17, 3, 99, 42], [1, 2, 3], [7] * 11, [250, 9]]


def _plain_greedy(engine, prompts, n_new):
    orch = orch_lib.Orchestrator(engine)
    return orch.generate([list(p) for p in prompts],
                         max_new_tokens=n_new)


class TestVerifyStep:

    def test_perfect_proposals_all_accepted(self, target_engine):
        """Feeding the true greedy continuation as proposals accepts
        all γ and the bonus continues the chain."""
        n_new = 8
        expected = _plain_greedy(target_engine, [PROMPTS[0]], n_new)[0]

        orch = orch_lib.Orchestrator(target_engine)
        request = orch.submit(orch_lib.Request(
            prompt_tokens=list(PROMPTS[0]), max_new_tokens=n_new))
        orch._admit_one()  # emits expected[0]
        assert request.output_tokens == expected[:1]
        slot = next(iter(orch._slot_req))
        gamma = 4
        proposals = np.zeros((4, gamma), np.int32)
        proposals[slot] = expected[1:1 + gamma]
        state, emitted, n_emitted = target_engine.verify_step(
            orch.state, proposals)
        emitted = np.asarray(jax.device_get(emitted))
        n_emitted = np.asarray(jax.device_get(n_emitted))
        assert int(n_emitted[slot]) == gamma + 1
        assert list(emitted[slot][:gamma + 1]) == expected[1:gamma + 2]

    def test_garbage_proposals_still_advance_correctly(self,
                                                       target_engine):
        """All-rejected proposals emit exactly the plain-greedy next
        token (the bonus)."""
        n_new = 4
        expected = _plain_greedy(target_engine, [PROMPTS[0]], n_new)[0]
        orch = orch_lib.Orchestrator(target_engine)
        orch.submit(orch_lib.Request(prompt_tokens=list(PROMPTS[0]),
                                     max_new_tokens=n_new))
        orch._admit_one()
        slot = next(iter(orch._slot_req))
        bad = np.full((4, 3), 499, np.int32)  # near-certainly wrong
        if expected[1] == 499:
            pytest.skip('model actually predicts the "garbage" token')
        state, emitted, n_emitted = target_engine.verify_step(
            orch.state, bad)
        emitted = np.asarray(jax.device_get(emitted))
        n_emitted = np.asarray(jax.device_get(n_emitted))
        assert int(n_emitted[slot]) == 1
        assert int(emitted[slot][0]) == expected[1]


class TestSpeculativeOrchestrator:

    def test_self_draft_full_acceptance(self, target_engine):
        """Draft == target: outputs identical, acceptance 100%."""
        n_new = 10
        expected = _plain_greedy(target_engine, PROMPTS, n_new)
        spec = orch_lib.SpeculativeOrchestrator(
            target_engine, target_engine, gamma=3)
        outputs = spec.generate([list(p) for p in PROMPTS],
                                max_new_tokens=n_new)
        assert outputs == expected
        stats = spec.accept_stats
        assert stats['rounds'] > 0
        assert stats['accepted'] / stats['proposed'] > 0.9

    def test_random_draft_exact_output(self, target_engine,
                                       draft_engine):
        """A draft with unrelated random weights must not change the
        output by a single token."""
        n_new = 12
        expected = _plain_greedy(target_engine, PROMPTS, n_new)
        spec = orch_lib.SpeculativeOrchestrator(
            target_engine, draft_engine, gamma=4)
        outputs = spec.generate([list(p) for p in PROMPTS],
                                max_new_tokens=n_new)
        assert outputs == expected

    def test_budget_respected(self, target_engine, draft_engine):
        spec = orch_lib.SpeculativeOrchestrator(
            target_engine, draft_engine, gamma=4)
        outputs = spec.generate([list(PROMPTS[0])], max_new_tokens=7)
        assert len(outputs[0]) == 7

    def test_mixed_batch_falls_back_and_finishes(self, target_engine,
                                                 draft_engine):
        n_new = 6
        expected = _plain_greedy(target_engine, [PROMPTS[0]], n_new)[0]
        spec = orch_lib.SpeculativeOrchestrator(
            target_engine, draft_engine, gamma=3)
        greedy = spec.submit(orch_lib.Request(
            prompt_tokens=list(PROMPTS[0]), max_new_tokens=n_new))
        sampled = spec.submit(orch_lib.Request(
            prompt_tokens=list(PROMPTS[1]), max_new_tokens=n_new,
            temperature=0.9))
        spec.run_until_drained()
        assert greedy.done and sampled.done
        assert greedy.output_tokens == expected
        assert len(sampled.output_tokens) == n_new

    def test_speculation_resumes_after_mixed_phase(self, target_engine,
                                                   draft_engine):
        """After sampled requests drain, later greedy requests go back
        through speculative rounds (stale draft cache costs only
        acceptance, not correctness)."""
        n_new = 8
        spec = orch_lib.SpeculativeOrchestrator(
            target_engine, draft_engine, gamma=3)
        spec.generate([list(PROMPTS[1])], max_new_tokens=4,
                      temperature=0.8)
        rounds_before = spec.accept_stats['rounds']
        expected = _plain_greedy(target_engine, [PROMPTS[2]], n_new)[0]
        outputs = spec.generate([list(PROMPTS[2])],
                                max_new_tokens=n_new)
        assert outputs[0] == expected
        assert spec.accept_stats['rounds'] > rounds_before

    @pytest.mark.parametrize('family', ['qwen', 'gemma', 'moe'])
    def test_other_families_speculate_exactly(self, family,
                                              draft_engine):
        """qwen/gemma/moe targets verify against the llama draft and
        still emit exactly the plain-greedy output."""
        from skypilot_tpu.models import gemma, moe, qwen
        model = {
            'qwen': dataclasses.replace(qwen.QWEN3_TINY,
                                        vocab_size=512),
            'gemma': dataclasses.replace(gemma.GEMMA_TINY,
                                         vocab_size=512),
            'moe': dataclasses.replace(moe.MOE_TINY, vocab_size=512),
        }[family]
        module = {'qwen': qwen, 'gemma': gemma, 'moe': moe}[family]
        config = engine_lib.EngineConfig(
            model=model, max_slots=4, max_target_len=96,
            prefill_buckets=(16, 32))
        params = module.init(model, jax.random.PRNGKey(3))
        target = engine_lib.InferenceEngine(config, params)
        assert target.supports_verify
        n_new = 10
        expected = _plain_greedy(target, PROMPTS[:2], n_new)
        spec = orch_lib.SpeculativeOrchestrator(target, draft_engine,
                                                gamma=3)
        outputs = spec.generate([list(p) for p in PROMPTS[:2]],
                                max_new_tokens=n_new)
        assert outputs == expected

    def test_config_mismatches_rejected(self, target_engine):
        bad_slots = _engine(DRAFT, seed=1, max_slots=2)
        with pytest.raises(ValueError, match='max_slots'):
            orch_lib.SpeculativeOrchestrator(target_engine, bad_slots)
        bad_vocab = _engine(
            dataclasses.replace(DRAFT, vocab_size=300), seed=1)
        with pytest.raises(ValueError, match='vocab'):
            orch_lib.SpeculativeOrchestrator(target_engine, bad_vocab)


class TestNgramSpeculator:

    @pytest.fixture(autouse=True)
    def _pin_xla_attend(self, monkeypatch):
        # Same rationale as the module docstring: verify and decode
        # use different reduction orders; pin one attend path so
        # token-for-token equality is well-defined.
        monkeypatch.setenv('XSKY_DECODE_ATTN', 'xla')

    def _engines(self):
        from skypilot_tpu.models import llama
        params = llama.init(llama.LLAMA_TINY, jax.random.PRNGKey(0))
        mk = lambda: engine_lib.InferenceEngine(
            engine_lib.EngineConfig(model=llama.LLAMA_TINY, max_slots=2,
                                    max_target_len=64,
                                    prefill_buckets=(16, 32)), params)
        return mk

    def test_outputs_equal_plain_greedy(self):
        mk = self._engines()
        prompts = [[5, 17, 3, 99, 42], [7, 8, 9, 7, 8, 9, 7, 8]]
        expected = orch_lib.Orchestrator(mk()).generate(
            prompts, max_new_tokens=10)
        ng = orch_lib.NgramSpeculator(mk(), gamma=3, match_len=2)
        assert ng.generate(prompts, max_new_tokens=10) == expected
        assert ng.accept_stats['rounds'] > 0

    def test_copyable_history_gets_accepted(self):
        """A prompt whose greedy continuation repeats (tiny random
        models loop hard) must yield a positive acceptance rate."""
        mk = self._engines()
        plain = orch_lib.Orchestrator(mk()).generate(
            [[5, 17, 3]], max_new_tokens=16)[0]
        # Only meaningful if the continuation actually repeats.
        repeats = len(plain) - len(set(zip(plain, plain[1:])))
        ng = orch_lib.NgramSpeculator(mk(), gamma=4, match_len=2)
        out = ng.generate([[5, 17, 3]], max_new_tokens=16)
        assert out[0] == plain
        if repeats > 4:
            assert ng.accept_stats['accepted'] > 0

    def test_propose_prefers_most_recent_match(self):
        ng = orch_lib.NgramSpeculator(self._engines()(), gamma=3,
                                      match_len=2)
        request = orch_lib.Request(prompt_tokens=[1, 2, 7, 1, 2, 9, 1])
        request.output_tokens = [2]
        # tail (1,2): most recent earlier occurrence at index 3 → the
        # continuation starts with 9.
        assert ng._propose(0, request)[0] == 9

    def test_mixed_batch_falls_back(self):
        mk = self._engines()
        ng = orch_lib.NgramSpeculator(mk(), gamma=3)
        greedy = ng.submit(orch_lib.Request(prompt_tokens=[5, 17, 3],
                                            max_new_tokens=6))
        ng.submit(orch_lib.Request(prompt_tokens=[9, 8, 7],
                                   max_new_tokens=6, temperature=1.0))
        ng.run_until_drained()
        expected = orch_lib.Orchestrator(mk()).generate(
            [[5, 17, 3]], max_new_tokens=6)[0]
        assert greedy.output_tokens == expected


def test_gemma2_target_speculative_exact(monkeypatch):
    """Speculation with a Gemma-2 TARGET: the pair-scan verify path
    (alternating windows + softcap in the multi-token attend) must
    keep outputs exactly equal to plain greedy decoding."""
    monkeypatch.setenv('XSKY_DECODE_ATTN', 'xla')
    from skypilot_tpu.models import gemma
    params = gemma.init(gemma.GEMMA2_TINY, jax.random.PRNGKey(0))
    mk = lambda: engine_lib.InferenceEngine(
        engine_lib.EngineConfig(model=gemma.GEMMA2_TINY, max_slots=2,
                                max_target_len=64,
                                prefill_buckets=(16, 32)), params)
    prompt = [5, 17, 3, 99, 42, 7, 8, 9, 10, 11, 12, 13]
    expected = orch_lib.Orchestrator(mk()).generate(
        [prompt], max_new_tokens=10)
    spec = orch_lib.SpeculativeOrchestrator(mk(), mk(), gamma=3)
    assert spec.generate([prompt], max_new_tokens=10) == expected
    ng = orch_lib.NgramSpeculator(mk(), gamma=3)
    assert ng.generate([prompt], max_new_tokens=10) == expected


def test_stale_draft_partial_dropped_on_slot_reuse(target_engine,
                                                   draft_engine):
    """A chunked draft prefill whose owning request finished must be
    discarded when its slot is re-admitted to a NEW request in the same
    tick — not stepped and finalized over the new request's draft cache
    (ADVICE r3: identity check, not just slot occupancy)."""
    spec = orch_lib.SpeculativeOrchestrator(target_engine, draft_engine,
                                            gamma=3)
    old = orch_lib.Request(prompt_tokens=[1, 2, 3], max_new_tokens=4)
    new = orch_lib.Request(prompt_tokens=[4, 5, 6], max_new_tokens=4)
    spec.submit(new)
    spec._admit_one()
    slot = next(iter(spec._slot_req))
    assert spec._slot_req[slot] is new
    old.done = True

    class _MustNotStep:
        def step(self):
            raise AssertionError('stale draft partial was stepped')

    # Simulate the race: the stale partial still keyed to `slot`, now
    # owned by `new`.
    spec._draft_partials[slot] = (old, _MustNotStep())
    spec._advance_draft_partials()
    assert slot not in spec._draft_partials
