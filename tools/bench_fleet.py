#!/usr/bin/env python3
"""Chaos preemption storm: elastic fleet recovery vs full relaunch.

The PR 10 acceptance gate. Runs the SAME storm twice on the fake
cloud — a 4-host spot gang whose rank 2 is chaos-stalled in its first
incarnation (``telemetry.stall`` keyed on the elastic generation), with
``CapacityError`` injected into every post-launch provisioning attempt
(the drought that makes relaunching expensive) — in two isolated arm
subprocesses:

  * **elastic** (``XSKY_FLEET_ELASTIC=1``): the jobs controller cancels
    the cluster job and resubmits over the 3 surviving hosts (no
    teardown, no provisioning — the capacity storm never fires), then
    grows back to the full gang once the journalled placement pressure
    decays below the block threshold.
  * **baseline** (``XSKY_FLEET_ELASTIC=0``): today's path — teardown,
    reprovision (eating the injected capacity errors), resubmit; zero
    ranks productive throughout.

The workload is LONG-RUNNING (a training job does not finish inside a
recovery incident); each arm measures a fixed WINDOW of wall time,
then releases the gang via a stop marker and computes **chip-weighted
goodput** from the workload-telemetry table: per-rank productive step
time (final ``step × step_time_ema`` of each incarnation, incarnations
split by the sample's own ``started_ts``) summed over incarnations,
divided by ``full_gang × window``. Gates:

  * goodput(elastic) strictly > goodput(baseline);
  * the elastic arm's journal holds ``job.gang_shrunk`` AND
    ``job.gang_regrown``, both trace-linked (non-null trace_id);
  * the grow decision in ``fleet_decisions`` carries the decayed
    placement score that admitted it.

``--decompose`` (the goodput-attribution gate) adds a THIRD arm —
**ckpt** (``XSKY_CKPT=1``): the same storm and the same elastic
recovery with the PR 13 async checkpoint plane on, so the goodput
delta vs the unchecked elastic arm is attributable to checkpointing
alone. Its gates: goodput strictly above the elastic arm,
``restart_replay`` share strictly below it, a journalled
``job.ckpt_restored`` from a live tier (local/peer), measured
step-path checkpoint overhead <2% of step time, and (full mode)
absolute goodput >= 0.6.

Prints ONE JSON line; exit 1 on any gate failure. ``--smoke`` (short
window) is the tier-1 gate run by tests/unit_tests/test_fleet.py;
``--decompose --smoke`` runs in tier-1 via
tests/unit_tests/test_goodput.py.

Usage:
    python tools/bench_fleet.py [--smoke] [--window S] [--step-s S]
"""
from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import tempfile
import threading
import time

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO_ROOT)

_HOSTS = 4          # tpu-v5e-32 on the fake catalog = 4 hosts
_VICTIM_RANK = 2    # never the head (rank 0 cannot shrink away)


def _workload_script(path: str, marker: str, step_s: float,
                     overhead_prefix: str) -> None:
    """The gang workload: an effectively-endless telemetry-emitting
    step loop. With the checkpoint plane off (``XSKY_CKPT=0`` — the
    elastic and baseline arms) every incarnation restarts from step 0
    — checkpoint-free, exactly the work a relaunch loses and a shrink
    preserves. The ckpt arm restores the freshest tier at init (the
    goodput ledger then shrinks restart_replay against the declared
    ``resume_step``) and snapshots at the auto-tuned cadence,
    accounting the step-path cost into a per-rank overhead file the
    arm's <2%-of-step-time gate reads. Exits cleanly once the bench's
    stop marker appears (fake-cloud hosts share the local
    filesystem), so the measurement window — not the workload length —
    bounds the run."""
    with open(path, 'w', encoding='utf-8') as f:
        f.write(f'''
import json, os, sys, time
sys.path.insert(0, {json.dumps(_REPO_ROOT)})
from skypilot_tpu.agent import checkpointd
from skypilot_tpu.agent import telemetry
start = 0
snap = checkpointd.restore()   # None when the plane is disabled
if snap is not None:
    start = snap.step
# The declared resume point: 0 (checkpoint-free) charges every re-run
# step to restart_replay; a restored step shrinks the bucket.
telemetry.emit(phase='init', resume_step=start)
overhead_s, done = 0.0, 0
ov_path = ({json.dumps(overhead_prefix)} + '-' +
           os.environ.get('XSKY_HOST_RANK', '0') + '.json')
def _flush_overhead():
    try:
        with open(ov_path + '.tmp', 'w', encoding='utf-8') as fh:
            json.dump({{'overhead_s': overhead_s, 'steps': done,
                       'step_s': {step_s}}}, fh)
        os.replace(ov_path + '.tmp', ov_path)
    except OSError:
        pass
for i in range(start, 1000000):
    if os.path.exists({json.dumps(marker)}):
        break
    telemetry.emit(phase='step', step=i, step_time_s={step_s})
    t0 = time.monotonic()
    checkpointd.maybe_checkpoint(i, lambda: {{'step': i}},
                                 step_time_s={step_s})
    overhead_s += time.monotonic() - t0
    done += 1
    if done % 25 == 0:
        _flush_overhead()
    time.sleep({step_s})
_flush_overhead()
checkpointd.wait_idle(5.0)
telemetry.emit(phase='idle')
''')


def _chaos_plan(path: str, decompose: bool = False) -> None:
    """One plan for BOTH arms (fairness): stall rank 2's emit in
    generation 0 only, and fail provisioning attempts after the initial
    launch with CapacityError (6 attempts, 1.5 s each — a capacity
    drought; the failover engine walks the whole spot zone ladder and
    into on-demand before an attempt lands. This is the storm the
    baseline's relaunch must provision through; the elastic arm never
    reprovisions — shrink and grow-back resubmit over the healthy
    cluster — so the same rules simply never fire there).

    ``decompose`` reshapes the same storm for the attribution gate:
    the stall fires LATE (the gang banks real progress first, so a
    checkpoint-free restart visibly rebuys it — restart_replay must
    dominate the relaunch arm's loss) and the drought is short (the
    gate proves WHERE the time went, not that relaunches are slow)."""
    with open(path, 'w', encoding='utf-8') as f:
        json.dump({'points': {
            'telemetry.stall': {
                'match': {'rank': _VICTIM_RANK, 'generation': '0'},
                'skip_first': 80 if decompose else 3,
            },
            'failover.wait_instances': {
                'skip_first': 1,   # the arm's initial launch succeeds
                'first_n': 2 if decompose else 6,
                'error': 'CapacityError',
                'latency_s': 0.5 if decompose else 1.5,
            },
        }}, f)


# ---- one arm (runs in its own subprocess with isolated state) --------------


def _productive_rank_seconds(state_lib, cluster: str) -> float:
    """Σ over (rank, incarnation) of final step × step-time EMA.

    Incarnations come from ``telemetry.split_incarnations`` — the
    started_ts split this bench introduced, now promoted into
    telemetry proper (the goodput ledger folds with the SAME split,
    so bench and runtime agree by construction), NOT cluster job id,
    which restarts at 1 after a relaunch and would merge incarnations.
    """
    from skypilot_tpu.agent import telemetry
    rows = state_lib.get_workload_telemetry(cluster=cluster,
                                            latest_only=False,
                                            limit=20000)
    total = 0.0
    for inc in telemetry.split_incarnations(rows):
        for rank_rows in inc['ranks'].values():
            total += max((r['step'] * r['step_time_ema_s']
                          for r in rank_rows
                          if r.get('step') is not None and
                          r.get('step_time_ema_s')), default=0.0)
    return total


def _decompose_arm(state_lib, cluster: str, window_start: float,
                   window_s: float) -> dict:
    """Arm-side attribution: fold the goodput ledger over EXACTLY the
    goodput window the arm measured (same data, same split — the gate
    compares the decomposition against the ratio), plus the fold/record
    overhead the controller tick pays (best-of-5 fold + one persisted
    record, amortized over the record interval — the bench_telemetry
    overhead-gate pattern)."""
    from skypilot_tpu.agent import goodput as goodput_lib
    window = (window_start, window_start + window_s)
    ledger = goodput_lib.build_ledger(cluster, window=window)
    fold_times = []
    for _ in range(5):
        t0 = time.perf_counter()
        goodput_lib.build_ledger(cluster, window=window)
        fold_times.append(time.perf_counter() - t0)
    # Read BEFORE the overhead-timing record below writes its own
    # kind='job' row: the gate must prove the CONTROLLER's monitor
    # loop persisted during the run, not this bench process.
    persisted = state_lib.get_goodput_ledger(cluster=cluster,
                                             kind='job', limit=1)
    t0 = time.perf_counter()
    goodput_lib.record_ledger(cluster)
    record_s = time.perf_counter() - t0
    fold_s = min(fold_times)
    tick_s = float(os.environ.get('XSKY_JOBS_POLL_INTERVAL', '2.0'))
    interval_s = goodput_lib.record_interval_s()
    # One fold+record per record interval, amortized per controller
    # tick: the share of each tick the ledger costs.
    amortized = (fold_s + record_s) * tick_s / max(interval_s, 1e-9)
    return {
        'ledger': ledger,
        'fold': {
            'fold_s': round(fold_s, 6),
            'record_s': round(record_s, 6),
            'tick_s': tick_s,
            'record_interval_s': interval_s,
            'amortized_per_tick': round(amortized, 6),
            'overhead_ratio': round(amortized / tick_s, 6),
        },
        'controller_recorded': bool(persisted),
    }


def run_arm(arm: str, window_s: float, step_s: float,
            out_path: str, decompose: bool = False) -> int:
    from skypilot_tpu import Resources, Task
    from skypilot_tpu import check as check_lib
    from skypilot_tpu import state as state_lib
    from skypilot_tpu.jobs import controller as controller_lib
    from skypilot_tpu.jobs import scheduler as jobs_scheduler
    from skypilot_tpu.jobs import state as jobs_state

    check_lib.set_enabled_clouds_for_test(['fake'])
    scratch = tempfile.mkdtemp(prefix='xsky-fleet-')
    workload = os.path.join(scratch, 'workload.py')
    marker = os.path.join(scratch, 'stop-marker')
    overhead_prefix = os.path.join(scratch, 'ckpt-overhead')
    _workload_script(workload, marker, step_s, overhead_prefix)

    task = Task('fleet-storm', run=f'{sys.executable} {workload}')
    task.set_resources(Resources(accelerators=f'tpu-v5e-{_HOSTS * 8}',
                                 use_spot=True))
    job_id = jobs_state.add_job('fleet-storm',
                                Task.chain_to_config([task]))
    jobs_state.set_status(job_id, jobs_state.ManagedJobStatus.SUBMITTED)
    jobs_state.set_schedule_state(job_id,
                                  jobs_state.ScheduleState.LAUNCHING)
    jobs_state.set_controller_pid(job_id, os.getpid())
    cluster = f'xsky-jobs-{job_id}'

    def run_controller():
        try:
            controller_lib.JobsController(job_id).run()
        finally:
            jobs_scheduler.job_done(job_id)

    thread = threading.Thread(target=run_controller, daemon=True,
                              name='xsky-fleet-bench-controller')
    # The window opens when the first rank reports a step (launch
    # overhead is identical across arms and not what the gate
    # measures), bounded by a bring-up timeout.
    thread.start()
    bringup_deadline = time.time() + 120
    window_start = None
    while time.time() < bringup_deadline and window_start is None:
        if _productive_rank_seconds(state_lib, cluster) > 0:
            window_start = time.time()
            break
        time.sleep(0.2)
    if window_start is not None:
        while time.time() - window_start < window_s and \
                thread.is_alive():
            time.sleep(0.2)
    # Measure AT the window edge, then release the gang.
    productive = _productive_rank_seconds(state_lib, cluster)
    goodput = (productive / (_HOSTS * window_s)
               if window_start is not None else 0.0)
    with open(marker, 'w', encoding='utf-8') as f:
        f.write('stop')
    thread.join(timeout=120)
    wedged = thread.is_alive()

    record = jobs_state.get_job(job_id) or {}
    status = record.get('status')
    events = state_lib.get_recovery_events(scope=f'job/{job_id}',
                                           limit=200)
    grow_decisions = state_lib.get_fleet_decisions(kind='grow',
                                                   job_id=job_id)
    result = {
        'arm': arm,
        'status': getattr(status, 'value', str(status)),
        'wedged': wedged,
        'window_s': window_s,
        'window_opened': window_start is not None,
        'productive_rank_s': round(productive, 2),
        'goodput': round(goodput, 4),
        'recovery_count': record.get('recovery_count') or 0,
        'events': [{'type': e['event_type'],
                    'latency_s': e['latency_s'],
                    'trace_id': e['trace_id'],
                    'detail': e['detail']} for e in events],
        'grow_decisions': grow_decisions,
    }
    if decompose and window_start is not None:
        result.update(_decompose_arm(state_lib, cluster, window_start,
                                     window_s))
    if arm == 'ckpt':
        result['ckpt_overhead'] = _read_ckpt_overhead(overhead_prefix)
    with open(out_path, 'w', encoding='utf-8') as f:
        json.dump(result, f)
    ok = (not wedged and
          status == jobs_state.ManagedJobStatus.SUCCEEDED)
    return 0 if ok else 1


def _read_ckpt_overhead(prefix: str) -> dict:
    """Per-rank checkpoint step-path overhead (written by the
    workload): worst rank's overhead as a fraction of its productive
    step time — the bench_telemetry/bench_profile <2% gate pattern."""
    worst = None
    ranks = 0
    directory = os.path.dirname(prefix)
    base = os.path.basename(prefix)
    try:
        names = os.listdir(directory)
    except OSError:
        names = []
    for name in names:
        if not (name.startswith(f'{base}-') and
                name.endswith('.json')):
            continue
        try:
            with open(os.path.join(directory, name),
                      encoding='utf-8') as f:
                row = json.load(f)
            ratio = (row['overhead_s'] /
                     (row['steps'] * row['step_s']))
        except (OSError, ValueError, KeyError, ZeroDivisionError):
            continue
        ranks += 1
        worst = ratio if worst is None else max(worst, ratio)
    return {'ratio': worst, 'ranks_reporting': ranks}


# ---- orchestration ---------------------------------------------------------


def _arm_env(arm: str, base_dir: str, plan: str,
             decompose: bool = False) -> dict:
    env = dict(os.environ)
    env.update({
        'XSKY_ENABLE_FAKE_CLOUD': '1',
        'XSKY_FAKE_CLOUD_DIR': os.path.join(base_dir, 'fake_cloud'),
        'XSKY_STATE_DB': os.path.join(base_dir, 'state.db'),
        'XSKY_JOBS_DB': os.path.join(base_dir, 'jobs.db'),
        'XSKY_JOBS_LOG_DIR': os.path.join(base_dir, 'jobs_logs'),
        'XSKY_CHAOS_PLAN': plan,
        'JAX_PLATFORMS': 'cpu',
        # Fast detection: spool writes every 0.1 s, pulls every 0.4 s,
        # a rank is HUNG after 1 s without progress (hb threshold stays
        # high — the drill is a hung rank, not a dead one).
        'XSKY_TELEMETRY_INTERVAL_S': '0.1',
        'XSKY_TELEMETRY_PULL_INTERVAL_S': '0.4',
        'XSKY_TELEMETRY_PROGRESS_STALE_S': '1.0',
        'XSKY_TELEMETRY_HB_STALE_S': '30',
        'XSKY_JOBS_POLL_INTERVAL': '0.3',
        # Fleet: probe grow-back every second; the shrink's own
        # journalled pressure (weight 1.0) gates it until one ~6 s
        # half-life decays it under the 0.5 threshold — "capacity
        # returned", scored, not timed — so the shrunk gang runs long
        # enough to amortize the resubmit it paid.
        'XSKY_FLEET_GROWBACK_S': '1.0',
        'XSKY_FLEET_DECAY_S': '6.0',
        'XSKY_FLEET_BLOCK_THRESHOLD': '0.5',
        'XSKY_FLEET_MIN_SURVIVORS': '0.5',
        'XSKY_FLEET_ELASTIC': '0' if arm == 'baseline' else '1',
        # The checkpoint plane is the ONLY difference between the
        # ckpt and elastic arms: same storm, same elastic recovery,
        # with/without snapshots — so the goodput delta and the
        # restart_replay shrink are attributable to checkpointing
        # alone.
        'XSKY_CKPT': '1' if arm == 'ckpt' else '0',
    })
    if arm == 'ckpt':
        env.update({
            # Smoke-scale cadence: snapshot every 1-2 s so the stall
            # at ~8 s of banked progress loses at most one cadence
            # window to replay. Two peers: a survivor can restore a
            # dead host's shard.
            'XSKY_CKPT_MIN_INTERVAL_S': '1.0',
            'XSKY_CKPT_MAX_INTERVAL_S': '2.0',
            'XSKY_CKPT_REPLICAS': '2',
        })
    if decompose:
        env.update({
            # The attribution gate measures a SHRUNK steady state: a
            # grow-back mid-window would resubmit the full gang and
            # restart from step 0 again, drowning shrunk_capacity in a
            # second helping of restart_replay. Pressure decays far
            # outside the window, so the elastic arm stays shrunk.
            'XSKY_FLEET_DECAY_S': '600',
            # The controller folds + persists the ledger during the
            # run (the gate asserts a persisted roll-up exists).
            'XSKY_GOODPUT_RECORD_INTERVAL_S': '2.0',
        })
    return env


def _loss_shares(ledger: dict) -> dict:
    """Each loss cause's share of the arm's total loss."""
    totals = (ledger or {}).get('totals') or {}
    loss_causes = [c for c in totals
                   if c not in ('productive', 'idle')]
    loss = sum(totals.get(c) or 0.0 for c in loss_causes)
    if loss <= 0:
        return {}
    return {c: (totals.get(c) or 0.0) / loss for c in loss_causes}


# The dominance gates compare shares over the attribution-STRUCTURE
# buckets only. The wall-clock recovery buckets (stall detection,
# journalled recovery windows, provisioning, bootstrap, queue) scale
# with box load — under a loaded CI host they balloon and dilute the
# replay share, flaking a whole-loss threshold — while what the gates
# actually prove (replay vs shrunk vs unattributed) is structural.
# The recovery buckets have their own structural gates (journalled
# shrink/relaunch events, arms' exit codes).
_STRUCTURAL_CAUSES = ('restart_replay', 'shrunk_capacity',
                      'unattributed')


def _structural_shares(ledger: dict) -> dict:
    totals = (ledger or {}).get('totals') or {}
    loss = sum(totals.get(c) or 0.0 for c in _STRUCTURAL_CAUSES)
    if loss <= 0:
        return {}
    return {c: (totals.get(c) or 0.0) / loss
            for c in _STRUCTURAL_CAUSES}


def _decompose_gates(results: dict, arm_rcs: dict,
                     window: float, smoke: bool = False) -> int:
    """The attribution gates: the ledger must explain the storm, not
    just survive it. Categories sum to measured wall within ±2% for
    every arm; the relaunch arm's structural loss (replay vs shrunk
    vs unattributed — see ``_structural_shares``) is dominated
    (>=50%) by restart_replay — a checkpoint-free relaunch rebuys all
    banked progress; the elastic arm shifts that loss toward
    shrunk_capacity (it keeps the survivors' progress and pays a
    missing-chip fraction instead); fold + record overhead stays
    under 2% of a controller tick, amortized over the record
    interval.

    The PR 13 checkpoint gates ride the same storm: the ckpt arm
    (elastic + async checkpointing) must strictly beat the unchecked
    elastic arm's goodput with a strictly smaller restart_replay
    share, restore from a live tier (local/peer — journalled
    ``job.ckpt_restored``), and pay <2% of step time on the step path
    (full mode additionally gates absolute goodput >= 0.6)."""
    elastic, baseline = results['elastic'], results['baseline']
    ckpt = results.get('ckpt') or {}
    summaries = {}
    gates = {'arms_succeeded':
             all(rc == 0 for rc in arm_rcs.values()) and
             set(arm_rcs) >= {'ckpt', 'elastic', 'baseline'}}
    for arm, result in results.items():
        ledger = result.get('ledger') or {}
        wall = ledger.get('wall_s') or 0.0
        attributed = ledger.get('attributed_s') or 0.0
        fold = result.get('fold') or {}
        shares = _loss_shares(ledger)
        summaries[arm] = {
            'goodput': ledger.get('goodput'),
            'wall_s': wall,
            'attributed_s': attributed,
            'sum_error': (round(abs(attributed - wall) / wall, 4)
                          if wall > 0 else None),
            'incarnations': len(ledger.get('incarnations') or ()),
            'replayed_steps': sum(
                r.get('replayed_steps') or 0
                for r in ledger.get('incarnations') or ()),
            'loss_shares': {k: round(v, 4)
                            for k, v in sorted(shares.items())
                            if v > 0},
            'fold_overhead_ratio': fold.get('overhead_ratio'),
        }
        gates[f'{arm}_sums_to_wall'] = (
            wall > 0 and abs(attributed - wall) / wall <= 0.02)
        gates[f'{arm}_fold_overhead_under_2pct'] = (
            fold.get('overhead_ratio') is not None and
            fold['overhead_ratio'] < 0.02)
    baseline_shares = _structural_shares(baseline.get('ledger') or {})
    elastic_shares = _structural_shares(elastic.get('ledger') or {})
    ckpt_shares = _structural_shares(ckpt.get('ledger') or {})
    gates['baseline_loss_mostly_restart_replay'] = (
        baseline_shares.get('restart_replay', 0.0) >= 0.5)
    gates['elastic_loss_shifts_to_shrunk_capacity'] = (
        elastic_shares.get('shrunk_capacity', 0.0) > 0.05 and
        elastic_shares.get('restart_replay', 1.0) <
        baseline_shares.get('restart_replay', 0.0))
    gates['elastic_shrunk_journalled'] = any(
        e['type'] == 'job.gang_shrunk'
        for e in elastic.get('events', ()))
    gates['baseline_relaunched'] = any(
        e['type'] == 'job.recovered'
        for e in baseline.get('events', ()))
    gates['controller_recorded_ledger'] = bool(
        elastic.get('controller_recorded') and
        baseline.get('controller_recorded'))
    # ---- checkpoint-arm gates (PR 13) ----
    ckpt_goodput = (ckpt.get('ledger') or {}).get('goodput') or 0.0
    elastic_goodput = (elastic.get('ledger') or {}).get('goodput') \
        or 0.0
    gates['ckpt_goodput_gt_elastic'] = ckpt_goodput > elastic_goodput
    # Replay must strictly shrink against the unchecked (elastic)
    # arm — same recovery shape, checkpointing is the only delta.
    gates['ckpt_replay_share_lt_unchecked'] = (
        elastic_shares.get('restart_replay', 0.0) > 0.0 and
        ckpt_shares.get('restart_replay', 1.0) <
        elastic_shares.get('restart_replay', 0.0))
    gates['ckpt_restored_from_live_tier'] = any(
        e['type'] == 'job.ckpt_restored' and
        (e.get('detail') or {}).get('tier') in ('local', 'peer')
        for e in ckpt.get('events', ()))
    overhead = (ckpt.get('ckpt_overhead') or {}).get('ratio')
    gates['ckpt_overhead_under_2pct'] = (overhead is not None and
                                         overhead < 0.02)
    if not smoke:
        # Full-scale target from the ROADMAP arc: 0.225 → >= 0.6.
        gates['ckpt_goodput_ge_target'] = ckpt_goodput >= 0.6
    ok = all(gates.values())
    print(json.dumps({
        'metric': 'fleet_goodput_attribution_decompose',
        'window_s': window,
        'hosts': _HOSTS,
        'ckpt': summaries.get('ckpt'),
        'elastic': summaries.get('elastic'),
        'baseline': summaries.get('baseline'),
        'ckpt_goodput': round(ckpt_goodput, 4),
        'ckpt_overhead_ratio': overhead,
        'gates': gates,
        'pass': ok,
    }))
    if not ok:
        for arm in sorted(results):
            print(json.dumps({'arm_debug': results[arm]},
                             default=str), file=sys.stderr)
    return 0 if ok else 1


def main() -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument('--smoke', action='store_true',
                        help='Short window (the tier-1 gate).')
    parser.add_argument('--decompose', action='store_true',
                        help='Goodput-attribution gate: fold the '
                             'ledger over each arm\'s exact goodput '
                             'window and assert the decomposition '
                             '(categories sum to wall, restart_replay '
                             'dominates the relaunch arm, the elastic '
                             'arm\'s loss shifts to shrunk_capacity, '
                             'fold overhead <2% of a controller tick).')
    parser.add_argument('--window', type=float, default=None,
                        help='Measurement window per arm, seconds.')
    parser.add_argument('--step-s', type=float, default=0.1)
    parser.add_argument('--run-arm', default=None,
                        help='(internal) run one arm in this process')
    parser.add_argument('--out', default=None,
                        help='(internal) arm result JSON path')
    args = parser.parse_args()
    if args.window is not None:
        window = args.window
    elif args.decompose:
        # The attribution storm banks ~8 s of progress before the
        # stall so the restart visibly rebuys it; the window must
        # cover stall + recovery + the full replay.
        window = 30.0 if args.smoke else 45.0
    else:
        window = 18.0 if args.smoke else 40.0

    if args.run_arm:
        return run_arm(args.run_arm, window, args.step_s, args.out,
                       decompose=args.decompose)

    results = {}
    arm_rcs = {}
    # --decompose adds the PR 13 checkpoint arm: the same storm and
    # the same elastic recovery, with async checkpointing on — the
    # goodput delta vs the unchecked elastic arm is the checkpoint
    # plane's contribution alone.
    arms = (('ckpt', 'elastic', 'baseline') if args.decompose
            else ('elastic', 'baseline'))
    with tempfile.TemporaryDirectory(prefix='xsky-bench-fleet-') as tmp:
        plan = os.path.join(tmp, 'storm.json')
        _chaos_plan(plan, decompose=args.decompose)
        for arm in arms:
            base = os.path.join(tmp, arm)
            os.makedirs(base, exist_ok=True)
            out = os.path.join(base, 'result.json')
            argv = [sys.executable, os.path.abspath(__file__),
                    '--run-arm', arm, '--window', str(window),
                    '--step-s', str(args.step_s), '--out', out]
            if args.decompose:
                argv.append('--decompose')
            proc = subprocess.run(argv,
                                  env=_arm_env(
                                      arm, base, plan,
                                      decompose=args.decompose),
                                  capture_output=True, text=True,
                                  timeout=420, check=False)
            arm_rcs[arm] = proc.returncode
            try:
                with open(out, encoding='utf-8') as f:
                    results[arm] = json.load(f)
            except (OSError, ValueError):
                results[arm] = {'arm': arm, 'goodput': 0.0,
                                'events': [],
                                'error': (proc.stderr or '')[-2000:]}

    if args.decompose:
        return _decompose_gates(results, arm_rcs, window,
                                smoke=args.smoke)

    elastic, baseline = results['elastic'], results['baseline']
    etypes = {e['type']: e for e in elastic.get('events', ())}
    shrunk = etypes.get('job.gang_shrunk')
    regrown = etypes.get('job.gang_regrown')
    gates = {
        'arms_succeeded': arm_rcs == {'elastic': 0, 'baseline': 0},
        'goodput_elastic_gt_baseline':
            elastic.get('goodput', 0) > baseline.get('goodput', 0),
        'gang_shrunk_journalled': shrunk is not None,
        'gang_regrown_journalled': regrown is not None,
        'shrink_trace_linked': bool(shrunk and shrunk.get('trace_id')),
        'regrow_trace_linked': bool(regrown and
                                    regrown.get('trace_id')),
        'grow_decision_scored': any(
            d.get('score') is not None
            for d in elastic.get('grow_decisions', ())),
        'baseline_relaunched': any(
            e['type'] == 'job.recovered'
            for e in baseline.get('events', ())),
    }
    ok = all(gates.values())
    print(json.dumps({
        'metric': 'fleet_elastic_vs_relaunch_goodput',
        'window_s': window,
        'hosts': _HOSTS,
        'elastic': {k: elastic.get(k) for k in
                    ('status', 'productive_rank_s',
                     'goodput', 'recovery_count')},
        'baseline': {k: baseline.get(k) for k in
                     ('status', 'productive_rank_s',
                      'goodput', 'recovery_count')},
        'goodput_delta': round(
            elastic.get('goodput', 0) - baseline.get('goodput', 0), 4),
        'shrink_latency_s': shrunk and shrunk.get('latency_s'),
        'regrow_after_s': regrown and regrown.get('latency_s'),
        'gates': gates,
        'pass': ok,
    }))
    if not ok:
        for arm in ('elastic', 'baseline'):
            print(json.dumps({'arm_debug': results[arm]}),
                  file=sys.stderr)
    return 0 if ok else 1


if __name__ == '__main__':
    sys.exit(main())
