"""Parallel host fan-out: run_in_parallel mechanics (ordering,
multi-rank failure aggregation, deadlines, sequential degeneration,
chaos/timeline interplay), the catalog instance-type index, the gang
start-loop ACTIVE_PROCS cleanup, and the tier-1 fan-out-abort smoke."""
import json
import threading
import time

import pytest

from skypilot_tpu import exceptions
from skypilot_tpu.utils import chaos
from skypilot_tpu.utils import parallelism
from skypilot_tpu.utils import resilience
from skypilot_tpu.utils import timeline


@pytest.fixture(autouse=True)
def _clean_chaos():
    chaos.clear()
    yield
    chaos.clear()


class _ConcurrencyProbe:
    """Callable tracking peak concurrent executions."""

    def __init__(self, delay: float = 0.0, fail_ranks=()):
        self.delay = delay
        self.fail_ranks = set(fail_ranks)
        self.started = []
        self.cur = 0
        self.peak = 0
        self._lock = threading.Lock()

    def __call__(self, rank):
        with self._lock:
            self.started.append(rank)
            self.cur += 1
            self.peak = max(self.peak, self.cur)
        try:
            if self.delay:
                time.sleep(self.delay)
            if rank in self.fail_ranks:
                raise RuntimeError(f'boom-{rank}')
            return rank * 10
        finally:
            with self._lock:
                self.cur -= 1


class TestRunInParallel:

    def test_empty_args(self):
        assert parallelism.run_in_parallel(lambda x: x, []) == []

    def test_ordered_results_under_out_of_order_completion(self):
        # Rank 0 finishes LAST; results must still be in input order.
        delays = [0.2, 0.0, 0.1, 0.05]
        order = []
        lock = threading.Lock()

        def fn(pair):
            rank, delay = pair
            time.sleep(delay)
            with lock:
                order.append(rank)
            return rank * 10

        results = parallelism.run_in_parallel(
            fn, list(enumerate(delays)), max_workers=4)
        assert results == [0, 10, 20, 30]
        assert order != [0, 1, 2, 3]       # completion really reordered
        assert order[-1] == 0

    def test_multi_rank_failure_aggregation(self):
        """Ranks 1 and 3 both fail: the MultiHostError names BOTH, not
        just the first, and carries each rank's exception."""
        probe = _ConcurrencyProbe(delay=0.05, fail_ranks={1, 3})
        with pytest.raises(exceptions.MultiHostError) as ei:
            parallelism.run_in_parallel(
                probe, [0, 1, 2, 3], max_workers=4, what='unit phase')
        err = ei.value
        assert set(err.failures) == {1, 3}
        assert isinstance(err.failures[1], RuntimeError)
        assert 'host 1' in str(err) and 'host 3' in str(err)
        assert 'boom-1' in str(err) and 'boom-3' in str(err)
        assert err.total == 4
        # It is also a ClusterSetUpError: sequential-era callers still
        # catch it.
        assert isinstance(err, exceptions.ClusterSetUpError)

    def test_failure_aborts_unstarted_ranks(self):
        """Gang semantics: ranks still queued when a failure lands
        never start (and are reported as not_started)."""
        probe = _ConcurrencyProbe(delay=0.15, fail_ranks={0})
        with pytest.raises(exceptions.MultiHostError) as ei:
            parallelism.run_in_parallel(
                probe, list(range(8)), max_workers=2)
        err = ei.value
        assert 0 in err.failures
        # Whatever was cancelled truly never ran.
        assert set(err.not_started).isdisjoint(set(probe.started))
        # With 2 workers and rank 0 failing early, the tail of the
        # queue must have been cancelled.
        assert err.not_started

    def test_deadline_expiry_kills_stragglers(self):
        """Budget spent with ranks still running: they are recorded as
        DeadlineExceeded failures and the call returns promptly
        instead of waiting out the stragglers."""
        t0 = time.monotonic()
        with pytest.raises(exceptions.MultiHostError) as ei:
            parallelism.run_in_parallel(
                lambda x: time.sleep(1.0), [1, 2, 3], max_workers=3,
                deadline=resilience.Deadline(0.25), what='slowphase')
        elapsed = time.monotonic() - t0
        assert elapsed < 0.9, elapsed
        err = ei.value
        assert set(err.failures) == {0, 1, 2}
        assert all(isinstance(e, resilience.DeadlineExceeded)
                   for e in err.failures.values())

    def test_deadline_expiry_cancels_queued_ranks(self):
        with pytest.raises(exceptions.MultiHostError) as ei:
            parallelism.run_in_parallel(
                lambda x: time.sleep(1.0), list(range(6)), max_workers=2,
                deadline=resilience.Deadline(0.3))
        err = ei.value
        assert set(err.failures) == {0, 1}      # the two in flight
        assert sorted(err.not_started) == [2, 3, 4, 5]

    def test_workers_1_is_sequential_fail_fast(self):
        """max_workers=1 degenerates to the old sequential loop: ranks
        run strictly in order, one at a time, and the first failure
        aborts before the next rank starts."""
        probe = _ConcurrencyProbe(delay=0.02, fail_ranks={1})
        with pytest.raises(exceptions.MultiHostError) as ei:
            parallelism.run_in_parallel(probe, [0, 1, 2, 3],
                                        max_workers=1)
        assert probe.started == [0, 1]          # 2, 3 never ran
        assert probe.peak == 1
        err = ei.value
        assert set(err.failures) == {1}
        assert sorted(err.not_started) == [2, 3]

    def test_env_var_sets_default_width(self, monkeypatch):
        monkeypatch.setenv('XSKY_FANOUT_WORKERS', '1')
        probe = _ConcurrencyProbe(delay=0.02)
        assert parallelism.run_in_parallel(probe, [0, 1, 2]) == \
            [0, 10, 20]
        assert probe.peak == 1
        assert probe.started == [0, 1, 2]
        monkeypatch.setenv('XSKY_FANOUT_WORKERS', '4')
        probe2 = _ConcurrencyProbe(delay=0.1)
        parallelism.run_in_parallel(probe2, [0, 1, 2, 3])
        assert probe2.peak > 1
        # Garbage falls back to the default instead of crashing a
        # launch.
        monkeypatch.setenv('XSKY_FANOUT_WORKERS', 'lots')
        assert parallelism.fanout_workers() == \
            parallelism.DEFAULT_FANOUT_WORKERS

    def test_chaos_point_fails_individual_rank(self):
        """A chaos rule matched on (phase, rank) fails exactly that
        rank mid-fan-out; every rank traverses the point."""
        # latency_s keeps rank 2's failure from landing before the
        # last worker has dequeued: an instant raise may gang-cancel a
        # still-queued rank (legal per the abort contract), and this
        # test asserts point coverage, not cancellation timing.
        chaos.load_plan({'points': {'fanout.worker': {
            'match': {'phase': 'unitboot', 'rank': 2},
            'first_n': 1, 'latency_s': 0.05,
            'error': 'ConnectionError'}}})
        probe = _ConcurrencyProbe(delay=0.1)
        with pytest.raises(exceptions.MultiHostError) as ei:
            parallelism.run_in_parallel(probe, [0, 1, 2, 3],
                                        max_workers=4, phase='unitboot')
        err = ei.value
        assert set(err.failures) == {2}
        assert isinstance(err.failures[2], ConnectionError)
        assert chaos.hits('fanout.worker') == 4
        # Rank 2 failed at the chaos point, before fn ran.
        assert 2 not in probe.started

    def test_chaos_latency_is_absorbed_in_parallel(self, monkeypatch,
                                                   tmp_path):
        """The micro form of the bench claim: injected per-rank setup
        latency OVERLAPS under the parallel fan-out and serializes at
        max_workers=1 — gated on the timeline's per-rank interval
        structure, not wall-clock ratios. (The old
        `parallel < sequential * 0.75` — and an absolute-margin
        variant — both flaked under full-suite load: scheduler noise
        and contended journal fsyncs inflate the parallel run's wall
        clock while the injected sleeps still overlap perfectly.
        Overlap and monotonic phase ordering are structural and
        load-insensitive.)"""
        # Fresh sqlite for the chaos journal (fires commit rows under
        # a module-wide lock); tracing off so span-buffer fsyncs stay
        # out of the intervals (the tracing overhead gate lives in
        # tools/bench_fanout.py --trace-overhead).
        monkeypatch.setenv('XSKY_STATE_DB', str(tmp_path / 'state.db'))
        monkeypatch.setenv('XSKY_TRACING', '0')
        trace = tmp_path / 'trace.json'
        monkeypatch.setenv('XSKY_TIMELINE_FILE', str(trace))
        timeline.reset_for_test()
        chaos.load_plan({'points': {'fanout.worker': {
            'latency_s': 0.3}}})
        items = list(range(4))
        parallelism.run_in_parallel(lambda x: x, items, max_workers=4,
                                    phase='absorb_par')
        parallelism.run_in_parallel(lambda x: x, items, max_workers=1,
                                    phase='absorb_seq')
        timeline.save(str(trace))
        events = json.loads(trace.read_text())['traceEvents']

        def intervals(phase):
            mine = [e for e in events
                    if e['name'] == f'fanout.{phase}']
            begins = sorted(e['ts'] for e in mine if e['ph'] == 'B')
            ends = sorted(e['ts'] for e in mine if e['ph'] == 'E')
            assert len(begins) == 4 and len(ends) == 4, mine
            return begins, ends

        # Parallel: the injected sleeps overlap — several ranks have
        # ENTERED (B, before their 0.3 s chaos sleep) before the
        # first rank's sleep finished (E). The sleep dwarfs scheduler
        # noise, so this holds on a loaded box.
        par_b, par_e = intervals('absorb_par')
        assert sum(1 for b in par_b if b < par_e[0]) >= 2, \
            (par_b, par_e)
        # Sequential degeneration: monotonic phase ordering — rank
        # N+1 begins only after rank N ended, so the sleeps are paid
        # end to end...
        seq_b, seq_e = intervals('absorb_seq')
        for nxt_begin, prev_end in zip(seq_b[1:], seq_e):
            assert nxt_begin >= prev_end, (seq_b, seq_e)
        # ...and each interval really absorbed its injected sleep
        # (timeline ts are microseconds; a lower bound cannot flake
        # under load).
        for begin, end in zip(seq_b, seq_e):
            assert end - begin >= 0.28e6, (seq_b, seq_e)
        timeline.reset_for_test()

    def test_timeline_events_show_phase_concurrency(self, monkeypatch,
                                                    tmp_path):
        trace = tmp_path / 'trace.json'
        monkeypatch.setenv('XSKY_TIMELINE_FILE', str(trace))
        timeline.reset_for_test()
        parallelism.run_in_parallel(
            lambda x: time.sleep(0.1), list(range(4)), max_workers=4,
            phase='traced')
        timeline.save(str(trace))
        events = json.loads(trace.read_text())['traceEvents']
        mine = [e for e in events if e['name'] == 'fanout.traced']
        begins = [e for e in mine if e['ph'] == 'B']
        ends = [e for e in mine if e['ph'] == 'E']
        assert len(begins) == 4 and len(ends) == 4
        assert sorted(b['args']['rank'] for b in begins) == [0, 1, 2, 3]
        # Concurrency is visible: intervals overlap (>=2 begins before
        # the first end).
        first_end = min(e['ts'] for e in ends)
        assert sum(1 for b in begins if b['ts'] < first_end) >= 2
        timeline.reset_for_test()


class TestCatalogIndex:
    """The per-cloud {instance_type: [entries]} index: same answers as
    the linear scans, invalidated by clear_cache."""

    @pytest.fixture(autouse=True)
    def _fresh_index(self):
        from skypilot_tpu.catalog import common as catalog_common
        catalog_common.instance_type_index.cache_clear()
        yield
        catalog_common.instance_type_index.cache_clear()

    @staticmethod
    def _entry(instance_type, region='r1', zone='r1-a', price=1.0,
               spot=0.5, vcpus=8, mem=32):
        from skypilot_tpu.catalog import common as catalog_common
        return catalog_common.CatalogEntry(
            instance_type=instance_type, accelerator_name='',
            accelerator_count=0, vcpus=vcpus, memory_gib=mem,
            accelerator_memory_gib=0, price=price, spot_price=spot,
            region=region, zone=zone)

    def _install(self, monkeypatch, entries):
        import functools

        from skypilot_tpu.catalog import common as catalog_common

        @functools.lru_cache(maxsize=None)
        def fake_load(cloud):
            return list(entries) if cloud == 'idxcloud' else []

        monkeypatch.setattr(catalog_common, 'load_catalog', fake_load)
        catalog_common.instance_type_index.cache_clear()

    def test_query_helpers_answer_from_index(self, monkeypatch):
        from skypilot_tpu.catalog import common as catalog_common
        self._install(monkeypatch, [
            self._entry('m1', region='r1', price=2.0, spot=0.8),
            self._entry('m1', region='r2', price=1.5, spot=0.0),
            self._entry('m2', vcpus=16, mem=64, price=4.0),
        ])
        assert catalog_common.instance_type_exists('idxcloud', 'm1')
        assert not catalog_common.instance_type_exists('idxcloud', 'nope')
        assert catalog_common.get_vcpus_mem_from_instance_type(
            'idxcloud', 'm2') == (16, 64)
        assert catalog_common.get_vcpus_mem_from_instance_type(
            'idxcloud', 'nope') is None
        # Cheapest across regions; region filter narrows.
        assert catalog_common.get_hourly_cost(
            'idxcloud', 'm1', use_spot=False) == 1.5
        assert catalog_common.get_hourly_cost(
            'idxcloud', 'm1', use_spot=False, region='r1') == 2.0
        # Zero spot prices are "no offer", not free.
        assert catalog_common.get_hourly_cost(
            'idxcloud', 'm1', use_spot=True) == 0.8
        with pytest.raises(ValueError):
            catalog_common.get_hourly_cost('idxcloud', 'nope',
                                           use_spot=False)
        with pytest.raises(ValueError):
            catalog_common.get_hourly_cost('idxcloud', 'm2',
                                           use_spot=False, region='r9')

    def test_clear_cache_invalidates_index(self, monkeypatch):
        from skypilot_tpu.catalog import common as catalog_common
        self._install(monkeypatch, [self._entry('m1')])
        assert catalog_common.instance_type_exists('idxcloud', 'm1')
        self._install(monkeypatch, [self._entry('m9')])
        # _install clears; a query after clear_cache sees the new world.
        catalog_common.clear_cache()
        assert not catalog_common.instance_type_exists('idxcloud', 'm1')
        assert catalog_common.instance_type_exists('idxcloud', 'm9')

    def test_index_matches_linear_scan_on_real_catalog(self):
        from skypilot_tpu.catalog import common as catalog_common
        entries = catalog_common.load_catalog('gcp')
        assert entries, 'gcp catalog missing'
        seen = []
        for e in entries:
            if e.instance_type and e.instance_type not in seen:
                seen.append(e.instance_type)
            if len(seen) >= 5:
                break
        for itype in seen:
            scan = [e for e in entries if e.instance_type == itype]
            assert catalog_common.instance_type_exists('gcp', itype)
            assert catalog_common.get_vcpus_mem_from_instance_type(
                'gcp', itype) == (scan[0].vcpus, scan[0].memory_gib)
            expected = min([p for p in (e.price for e in scan) if p > 0],
                           default=0.0)
            assert catalog_common.get_hourly_cost(
                'gcp', itype, use_spot=False) == expected


class TestGangStartCleanup:
    """A mid-fan-out start failure must deregister the already-started
    (and killed) host processes from ACTIVE_PROCS — otherwise every
    later kill_active() re-signals their recycled pids."""

    def test_start_failure_leaves_no_active_procs(self, tmp_path):
        from skypilot_tpu.agent import gang
        from skypilot_tpu.utils import command_runner
        chaos.load_plan({'points': {'gang.host_start': {
            'match': {'rank': 2}, 'first_n': 1,
            'error': 'ConnectionError'}}})
        runners = [
            command_runner.LocalProcessCommandRunner(
                f'h{i}', host_root=str(tmp_path / f'h{i}'))
            for i in range(4)
        ]
        assert gang.ACTIVE_PROCS == []
        with pytest.raises(ConnectionError):
            gang.gang_launch(runners, [{} for _ in range(4)],
                             'sleep 30', str(tmp_path / 'logs'),
                             poll_interval_s=0.05)
        assert gang.ACTIVE_PROCS == []


class TestFanoutSmoke:
    """Tier-1 acceptance smoke: a fake-cloud multi-host launch with a
    chaos rule failing one rank's bring-up mid-fan-out must abort the
    launch with that rank named, clean up the provisioned cluster, and
    strand no host processes."""

    def test_rank_failure_aborts_launch_and_cleans_up(
            self, fake_cluster_env, tmp_path):
        from skypilot_tpu import Resources, Task
        from skypilot_tpu import execution
        from skypilot_tpu import state
        from skypilot_tpu.agent import gang
        chaos.load_plan({'points': {'fanout.worker': {
            'match': {'phase': 'mount', 'rank': 2},
            'first_n': 1, 'error': 'ClusterSetUpError'}}})
        mnt = tmp_path / 'mnt' / 'vol'
        task = Task('smoke', run='echo never')
        task.set_resources(Resources(
            accelerators='tpu-v5e-32',      # 4 hosts
            volumes=[{'name': 'v1', 'path': str(mnt)}]))
        with pytest.raises(exceptions.ClusterSetUpError) as ei:
            execution.launch(task, cluster_name='smoke')
        err = ei.value
        # The failed rank is named (and only that rank failed).
        assert isinstance(err, exceptions.MultiHostError)
        assert set(err.failures) == {2}
        assert 'host 2' in str(err)
        # Mid-fan-out: every rank (minus any cancelled tail) traversed
        # the chaos point concurrently.
        assert chaos.hits('fanout.worker') >= 3
        # The launch aborted before any job could start: no gang
        # processes exist and the cluster never reached UP.
        assert gang.ACTIVE_PROCS == []
        record = state.get_cluster_from_name('smoke')
        assert record is not None
        assert record['status'] == state.ClusterStatus.INIT
        # Nothing is stranded: the half-set-up cluster tears down
        # cleanly (terminate overlapped with port cleanup),
        # reclaiming every fake host process and instance.
        from skypilot_tpu import core
        core.down('smoke')
        assert state.get_cluster_from_name('smoke') is None
        assert not fake_cluster_env.cluster_exists('smoke')
