"""Serve tests: real controller process, HTTP replicas, LB, autoscaler."""
import textwrap
import time
import urllib.request

import pytest

from skypilot_tpu import task as task_lib
from skypilot_tpu.serve import autoscalers as autoscalers_lib
from skypilot_tpu.serve import core as serve_core
from skypilot_tpu.serve import load_balancing_policies as lb_policies
from skypilot_tpu.serve import service_spec as spec_lib
from skypilot_tpu.serve import spot_placer as spot_placer_lib
from skypilot_tpu.serve import state as serve_state

SERVICE_YAML = textwrap.dedent("""\
    name: echo
    resources:
      accelerators: tpu-v5e-8
    service:
      readiness_probe: /
      replica_policy:
        min_replicas: {min_replicas}
        max_replicas: {max_replicas}
    run: |
      python -c "
      import http.server, os, json
      class H(http.server.BaseHTTPRequestHandler):
          def do_GET(self):
              body = json.dumps({{'rank': os.environ.get('XSKY_HOST_RANK'),
                                  'port': os.environ['PORT']}}).encode()
              self.send_response(200)
              self.send_header('Content-Length', str(len(body)))
              self.end_headers()
              self.wfile.write(body)
          def log_message(self, *a): pass
      http.server.HTTPServer(('127.0.0.1', int(os.environ['PORT'])),
                             H).serve_forever()"
    """)


@pytest.fixture
def serve_env(fake_cluster_env, monkeypatch, tmp_path):
    monkeypatch.setenv('XSKY_SERVE_DB', str(tmp_path / 'serve.db'))
    monkeypatch.setenv('XSKY_SERVE_INTERVAL', '0.5')
    yield fake_cluster_env


def _service_task(min_replicas=1, max_replicas=2):
    import io
    import yaml
    config = yaml.safe_load(io.StringIO(
        SERVICE_YAML.format(min_replicas=min_replicas,
                            max_replicas=max_replicas)))
    return task_lib.Task.from_yaml_config(config)


class TestServeE2E:

    def test_up_serve_traffic_down(self, serve_env):
        task = _service_task(min_replicas=2)
        name = serve_core.up(task, 'echo1', timeout_s=90)
        record = serve_core.status(['echo1'])[0]
        assert record['status'] == 'READY'
        # Wait for both replicas READY (min_replicas=2).
        deadline = time.time() + 60
        while time.time() < deadline:
            record = serve_core.status(['echo1'])[0]
            ready = [r for r in record['replicas']
                     if r['status'] == 'READY']
            if len(ready) == 2:
                break
            time.sleep(0.5)
        assert len(ready) == 2
        # Traffic through the LB round-robins across replica ports.
        endpoint = record['endpoint']
        seen_ports = set()
        for _ in range(6):
            with urllib.request.urlopen(f'http://{endpoint}/',
                                        timeout=10) as resp:
                import json
                seen_ports.add(json.loads(resp.read())['port'])
        assert len(seen_ports) == 2
        serve_core.down('echo1')
        assert serve_core.status(['echo1']) == []

    def test_replica_preemption_recovery(self, serve_env):
        task = _service_task(min_replicas=1)
        serve_core.up(task, 'echo2', timeout_s=90)
        replicas = serve_state.get_replicas('echo2')
        cluster = replicas[0]['cluster_name']
        serve_env.preempt_cluster(cluster)
        # Controller must detect and replace the replica.
        deadline = time.time() + 60
        recovered = False
        while time.time() < deadline:
            reps = serve_state.get_replicas('echo2')
            if reps and all(
                    r['cluster_name'] != cluster for r in reps) and any(
                    r['status'] == serve_state.ReplicaStatus.READY
                    for r in reps):
                recovered = True
                break
            time.sleep(0.5)
        serve_core.down('echo2')
        assert recovered

    def test_duplicate_service_rejected(self, serve_env):
        task = _service_task()
        serve_core.up(task, 'dup', timeout_s=90)
        with pytest.raises(ValueError):
            serve_core.up(task, 'dup')
        serve_core.down('dup')


class TestAutoscaler:

    def _spec(self, **kwargs):
        defaults = dict(min_replicas=1, max_replicas=4,
                        target_qps_per_replica=1.0,
                        upscale_delay_seconds=0.0,
                        downscale_delay_seconds=0.0)
        defaults.update(kwargs)
        return spec_lib.SkyServiceSpec(**defaults)

    def test_scales_with_qps(self):
        scaler = autoscalers_lib.RequestRateAutoscaler(self._spec())
        # 180 requests in the 60s window → 3 qps → 3 replicas.
        scaler.collect_request_information(180, 0)
        assert scaler.evaluate(1).target_num_replicas == 3

    def test_clamped_to_max(self):
        scaler = autoscalers_lib.RequestRateAutoscaler(self._spec())
        scaler.collect_request_information(6000, 0)
        assert scaler.evaluate(1).target_num_replicas == 4

    def test_upscale_hysteresis(self):
        scaler = autoscalers_lib.RequestRateAutoscaler(
            self._spec(upscale_delay_seconds=3600))
        scaler.collect_request_information(600, 0)
        # Desired is 10 but the delay hasn't elapsed: stay at 1.
        assert scaler.evaluate(1).target_num_replicas == 1

    def test_downscale_hysteresis(self):
        scaler = autoscalers_lib.RequestRateAutoscaler(
            self._spec(downscale_delay_seconds=3600))
        scaler.collect_request_information(240, 0)
        assert scaler.evaluate(1).target_num_replicas == 4
        # QPS drops to 0; downscale delayed → stays 4.
        scaler._request_timestamps.clear()
        assert scaler.evaluate(4).target_num_replicas == 4

    def test_fixed_when_no_target_qps(self):
        spec = spec_lib.SkyServiceSpec(min_replicas=2)
        scaler = autoscalers_lib.make_autoscaler(spec)
        assert isinstance(scaler, autoscalers_lib.FixedReplicaAutoscaler)
        assert scaler.evaluate(2).target_num_replicas == 2

    def test_autoscaling_requires_max(self):
        with pytest.raises(ValueError):
            spec_lib.SkyServiceSpec(target_qps_per_replica=1.0)


class TestLbPolicies:

    def test_round_robin(self):
        p = lb_policies.RoundRobinPolicy()
        p.set_ready_replicas(['a', 'b'])
        assert [p.select_replica() for _ in range(4)] == \
            ['a', 'b', 'a', 'b']

    def test_least_load(self):
        p = lb_policies.LeastLoadPolicy()
        p.set_ready_replicas(['a', 'b'])
        r1 = p.select_replica()
        r2 = p.select_replica()
        assert {r1, r2} == {'a', 'b'}
        p.request_done(r1)
        assert p.select_replica() == r1

    def test_empty(self):
        p = lb_policies.RoundRobinPolicy()
        p.set_ready_replicas([])
        assert p.select_replica() is None


class TestSpotPlacer:

    def test_preemptive_zone_avoided(self):
        placer = spot_placer_lib.SpotPlacer(['z1', 'z2'])
        placer.handle_preemption('z1')
        for _ in range(10):
            assert placer.select_zone() == 'z2'

    def test_reset_when_all_preemptive(self):
        placer = spot_placer_lib.SpotPlacer(['z1'])
        placer.handle_preemption('z1')
        assert placer.select_zone() == 'z1'  # sets reset
