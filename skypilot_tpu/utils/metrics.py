"""Lock-cheap in-process metrics registry (Prometheus exposition).

The server-specific HTTP/verb metrics live in
``skypilot_tpu/server/metrics.py``; this module is the generic
substrate the rest of the control plane records into — launch-phase
latency histograms (fed by ``utils/tracing`` at span end), failover
attempts by cause, chaos fires, reconciler repairs, fan-out straggler
counts. ``server/metrics.render()`` appends :func:`render_registry` to
its own output, so everything lands on the API server's ``/metrics``
endpoint in one scrape.

Design constraints:
  * **Lock-cheap** — one module lock around plain dict bumps; no
    per-metric objects to allocate on the hot path.
  * **Never raises** — a metrics bump sits inside recovery and launch
    paths; observability must not take them down.
  * **Bounded cardinality is the CALLER's contract** — label values
    must come from closed sets (phase names, exception class names,
    chaos point names), never user input.
"""
from __future__ import annotations

import threading
from typing import Dict, List, Optional, Tuple

_lock = threading.Lock()

# Shared latency bucket ladder: wide enough for sub-second fan-out
# ranks and multi-minute provision attempts alike.
DEFAULT_BUCKETS: Tuple[float, ...] = (
    0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 15.0, 60.0, 300.0, 900.0,
    float('inf'))

# name -> (help, type)
_meta: Dict[str, Tuple[str, str]] = {}
# name -> {(label_items sorted tuple): value}
_counters: Dict[str, Dict[Tuple, float]] = {}
# name -> {labels: [bucket_counts, sum, count]}; buckets per name
_hist_buckets: Dict[str, Tuple[float, ...]] = {}
_hists: Dict[str, Dict[Tuple, List]] = {}


def _label_key(labels: Dict[str, object]) -> Tuple:
    return tuple(sorted((k, str(v)) for k, v in labels.items()))


def inc_counter(name: str, help_text: str, value: float = 1.0,
                **labels: object) -> None:
    """Bump a counter. Never raises."""
    try:
        key = _label_key(labels)
        with _lock:
            _meta.setdefault(name, (help_text, 'counter'))
            series = _counters.setdefault(name, {})
            series[key] = series.get(key, 0.0) + value
    except Exception:  # pylint: disable=broad-except
        pass


def observe(name: str, help_text: str, value: float,
            buckets: Optional[Tuple[float, ...]] = None,
            **labels: object) -> None:
    """Record one histogram observation. Never raises."""
    try:
        key = _label_key(labels)
        with _lock:
            _meta.setdefault(name, (help_text, 'histogram'))
            bks = _hist_buckets.setdefault(name,
                                           buckets or DEFAULT_BUCKETS)
            series = _hists.setdefault(name, {})
            entry = series.get(key)
            if entry is None:
                entry = series[key] = [[0] * len(bks), 0.0, 0]
            counts, _, _ = entry
            for i, le in enumerate(bks):
                if value <= le:
                    counts[i] += 1
            entry[1] += value
            entry[2] += 1
    except Exception:  # pylint: disable=broad-except
        pass


def reset_for_test() -> None:
    with _lock:
        _meta.clear()
        _counters.clear()
        _hist_buckets.clear()
        _hists.clear()


def snapshot() -> List[Tuple[str, str, Tuple, float]]:
    """Structured sample of every registry series — the metrics-history
    recorder's fast path (rendering 5k series to exposition text and
    reparsing it was measured ~3× the cost of the whole recorder
    tick). Returns ``(name, kind, label_items, value)`` tuples;
    histograms expand to their cumulative ``_bucket``/``_sum``/
    ``_count`` component series exactly as :func:`render_registry`
    spells them (``le`` formatted via :func:`fmt_le`, so text-scrape
    and snapshot consumers agree on series identity)."""
    out: List[Tuple[str, str, Tuple, float]] = []
    with _lock:
        for name in sorted(_meta):
            mtype = _meta[name][1]
            if mtype == 'counter':
                for key, value in _counters.get(name, {}).items():
                    out.append((name, 'counter', key, value))
            else:
                bks = _hist_buckets[name]
                for key, (counts, total, count) in \
                        _hists.get(name, {}).items():
                    for i, le in enumerate(bks):
                        out.append((f'{name}_bucket', 'counter',
                                    key + (('le', fmt_le(le)),),
                                    float(counts[i])))
                    out.append((f'{name}_sum', 'counter', key,
                                float(total)))
                    out.append((f'{name}_count', 'counter', key,
                                float(count)))
    return out


# ---- exposition ------------------------------------------------------------


def escape_label(value: str) -> str:
    """Prometheus label-value escaping (shared with server/metrics —
    ONE implementation so the merged /metrics output can't drift)."""
    return value.replace('\\', r'\\').replace('"', r'\"').replace(
        '\n', r'\n')


def fmt_le(le: float) -> str:
    """Bucket upper-bound formatting (`+Inf` per the exposition
    format); shared with server/metrics."""
    return '+Inf' if le == float('inf') else f'{le:g}'


def _fmt_labels(key: Tuple, extra: str = '') -> str:
    parts = [f'{k}="{escape_label(v)}"' for k, v in key]
    if extra:
        parts.append(extra)
    return '{' + ','.join(parts) + '}' if parts else ''


def _fmt_value(value: float) -> str:
    return f'{value:g}' if value == int(value) else f'{value:.6f}'


def name_matches(name: str, prefix: Optional[str]) -> bool:
    """The `/metrics?name=<prefix>` filter contract: a series renders
    when its name starts with the prefix, OR the prefix extends the
    name (so `?name=xsky_foo_seconds_bucket` still selects the parent
    histogram `xsky_foo_seconds`). No prefix renders everything."""
    return (not prefix or name.startswith(prefix)
            or prefix.startswith(name))


def render_registry(name_prefix: Optional[str] = None) -> str:
    """The generic registry in text exposition format (0.0.4). Empty
    string when nothing has been recorded. `name_prefix` filters to
    matching series (see :func:`name_matches`)."""
    with _lock:
        lines: List[str] = []
        for name in sorted(_meta):
            if not name_matches(name, name_prefix):
                continue
            help_text, mtype = _meta[name]
            lines.append(f'# HELP {name} {help_text}')
            lines.append(f'# TYPE {name} {mtype}')
            if mtype == 'counter':
                for key, value in sorted(_counters.get(name, {}).items()):
                    lines.append(
                        f'{name}{_fmt_labels(key)} {_fmt_value(value)}')
            else:
                bks = _hist_buckets[name]
                for key, (counts, total, count) in sorted(
                        _hists.get(name, {}).items()):
                    for i, le in enumerate(bks):
                        le_label = 'le="%s"' % fmt_le(le)
                        lines.append(
                            f'{name}_bucket{_fmt_labels(key, le_label)} '
                            f'{counts[i]}')
                    lines.append(
                        f'{name}_sum{_fmt_labels(key)} {total:.6f}')
                    lines.append(f'{name}_count{_fmt_labels(key)} {count}')
        return '\n'.join(lines) + ('\n' if lines else '')
