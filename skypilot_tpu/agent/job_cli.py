"""Tiny CLI the backend invokes on the cluster head (one code path for
local/fake and SSH clusters). Twin of the reference's codegen-over-SSH
pattern (sky/skylet/job_lib.py codegen + sky/jobs/utils.py ManagedJobCodeGen).

Commands: add | status | queue | cancel | tail | watch | run-detached.
Spec payloads travel base64(json) to survive shell quoting.

`watch JOB OFFSET` is the launch-wait hot path: one invocation returns
the job status AND the next chunk of run.log past OFFSET (base64, so
arbitrary bytes survive the SSH text channel) in a single JSON line —
the backend's wait loop costs one remote exec per poll instead of one
for status plus one for logs.
"""
from __future__ import annotations

import base64
import json
import os
import subprocess
import sys

from skypilot_tpu.agent import job_lib


def _decode_spec(b64: str) -> dict:
    return json.loads(base64.b64decode(b64).decode())


def main(argv=None) -> int:
    argv = argv if argv is not None else sys.argv[1:]
    cmd = argv[0]
    root = job_lib.cluster_root()

    if cmd == 'add':
        name, user, spec_b64 = argv[1], argv[2], argv[3]
        job_id = job_lib.add_job(None if name == '-' else name, user,
                                 _decode_spec(spec_b64), root)
        print(job_id)
        return 0

    if cmd == 'run-detached':
        job_id = int(argv[1])
        # Atomic claim: only starts if the FIFO scheduler agrees it is
        # this job's turn, and no other scheduler claimed it first.
        claimed = job_lib.claim_and_spawn(root, job_id)
        print('started' if claimed == job_id else 'queued')
        return 0

    if cmd == 'status':
        job = job_lib.get_job(int(argv[1]), root)
        print(job['status'].value if job else 'NOT_FOUND')
        return 0

    if cmd == 'queue':
        jobs = job_lib.get_jobs(root)
        for j in jobs:
            j['status'] = j['status'].value
        print(json.dumps(jobs))
        return 0

    if cmd == 'cancel':
        ok = job_lib.cancel_job(int(argv[1]), root)
        print('cancelled' if ok else 'noop')
        return 0

    if cmd == 'watch':
        job_id, offset = int(argv[1]), int(argv[2])
        job = job_lib.get_job(job_id, root)
        status = job['status'].value if job else 'NOT_FOUND'
        log_path = os.path.join(job_lib.log_dir_for(job_id, root),
                                'run.log')
        chunk = b''
        if os.path.exists(log_path) and offset >= 0:
            with open(log_path, 'rb') as f:
                f.seek(offset)
                chunk = f.read(262144)
        print(json.dumps({
            'status': status,
            'offset': offset + len(chunk),
            'log': base64.b64encode(chunk).decode(),
        }))
        return 0

    if cmd == 'tail':
        job_id = int(argv[1])
        log_dir = job_lib.log_dir_for(job_id, root)
        if len(argv) > 2 and argv[2] == 'gang':
            # Rank-attributed view: regenerate the [rank N]-tagged
            # multiplex from the per-host logs (always fresh — the
            # gang.log written at job end misses a still-running or
            # killed-mid-run gang).
            from skypilot_tpu.agent import gang
            try:
                log_path = gang.aggregate_logs(log_dir)
            except OSError:
                return 0
        else:
            log_path = os.path.join(log_dir, 'run.log')
        if os.path.exists(log_path):
            with open(log_path, encoding='utf-8', errors='replace') as f:
                sys.stdout.write(f.read())
        return 0

    print(f'unknown command {cmd}', file=sys.stderr)
    return 2


if __name__ == '__main__':
    sys.exit(main())
