"""HTTP serving entrypoint: the slot engine behind a JSON API.

    python -m skypilot_tpu.infer.server --model llama3-8b --port 8080

Endpoints (JetStream-twin wire surface for `xsky serve` replicas):
  GET  /health              → 200 once the engine is compiled (readiness
                              probe target for the serve controller)
  POST /generate            → {"prompt_tokens": [...], "max_new_tokens",
                              "temperature", "top_k", "top_p"}
                              ⇒ {"output_tokens": [...]}.
  GET  /v1/models           → OpenAI-style model listing.
  POST /v1/completions      → OpenAI-compatible text completion
  POST /v1/chat/completions   (+ SSE streaming, stop sequences, echo) —
                              the wire surface the reference's serving
                              recipes get from vLLM (llm/vllm/serve.yaml);
                              shaping logic in infer/openai_api.py,
                              tokenizers in infer/tokenizer.py.

The orchestrator thread runs continuous batching across concurrent
requests; HTTP handlers block on their request's completion event.
"""
from __future__ import annotations

import argparse
import dataclasses
import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import jax

from skypilot_tpu import models
from skypilot_tpu import sky_logging
from skypilot_tpu.infer import engine as engine_lib
from skypilot_tpu.infer import orchestrator as orch_lib
from skypilot_tpu.parallel import mesh as mesh_lib

logger = sky_logging.init_logger(__name__)


class ServingLoop:
    """Owns the orchestrator; steps continuously while work exists.

    HTTP handler threads submit under the lock and then poll their own
    Request.done flag (set by the orchestrator thread) — the decode step
    dominates latency, so 5 ms polling adds nothing measurable.
    """

    def __init__(self, orch: orch_lib.Orchestrator) -> None:
        self.orch = orch
        self._wake = threading.Event()
        self._lock = threading.Lock()
        threading.Thread(target=self._loop, name='xsky-infer-loop',
                         daemon=True).start()

    def submit(self, request: orch_lib.Request) -> orch_lib.Request:
        """Enqueue without blocking (streaming handlers poll the
        request's output_tokens/done themselves)."""
        # Phase flips to `step` at request ARRIVAL, not completion: an
        # engine that wedges on the very first request after an idle
        # stretch must sit in phase=step (hung-detectable), not hide
        # behind the idle exemption. Emitted INSIDE the lock, after
        # the enqueue: the serving loop's idle emit shares the lock,
        # so it can never land after this one and re-mask the phase.
        from skypilot_tpu.agent import telemetry
        with self._lock:
            self.orch.submit(request)
            telemetry.emit(phase=telemetry.PHASE_STEP)
        self._wake.set()
        return request

    def submit_and_wait(self, request: orch_lib.Request,
                        timeout: float = 600.0,
                        on_progress=None) -> orch_lib.Request:
        """Blocking submit. `on_progress(request)` runs whenever new
        tokens have landed (callers use it for stop-sequence checks —
        it may set request.cancel_requested)."""
        self.submit(request)
        deadline = time.time() + timeout
        seen = -1
        while not request.done and time.time() < deadline:
            if on_progress is not None:
                n = len(request.output_tokens)
                if n > seen:
                    seen = n
                    on_progress(request)
            time.sleep(0.005)
        if not request.done:
            request.error = request.error or 'server timeout'
            # The caller is gone: stop decoding for it, free the slot.
            request.cancel_requested = True
        return request

    def _loop(self) -> None:
        while True:
            self._wake.wait(timeout=1.0)
            while True:
                with self._lock:
                    try:
                        self.orch.step()
                        busy = bool(self.orch._slot_req or
                                    self.orch._partials or
                                    not self.orch._pending.empty())
                    except Exception as e:  # pylint: disable=broad-except
                        # A dead serving loop must not strand waiting
                        # handlers (they poll request.done): fail every
                        # in-flight request loudly and keep serving.
                        logger.exception('serving loop step failed')
                        self.orch.fail_all(f'engine step failed: {e}')
                        busy = False
                    if not busy:
                        # Declared idle: no slots, no partials, empty
                        # queue — checked and emitted under the SAME
                        # lock submit() emits phase=step under, so an
                        # arriving request's step emit can never be
                        # overwritten by a racing idle emit. The stall
                        # detector exempts phase=idle from the hung
                        # verdict, so a traffic-less replica is never
                        # mistaken for a wedged one.
                        from skypilot_tpu.agent import telemetry
                        telemetry.emit(phase=telemetry.PHASE_IDLE)
                if not busy:
                    self._wake.clear()
                    break


def build_handler(loop: ServingLoop, config: engine_lib.EngineConfig,
                  tokenizer=None, model_id: str = 'model',
                  metrics=None, max_queue: int = 0):
    """max_queue > 0 sheds load: requests beyond that many pending
    admissions get 429 instead of unbounded queueing (an overloaded
    replica should fail fast so the serve LB retries a healthier one).
    """
    from skypilot_tpu.infer import anatomy as anatomy_lib
    from skypilot_tpu.infer import metrics as metrics_lib
    from skypilot_tpu.infer import openai_api
    from skypilot_tpu.utils import tracing
    if metrics is None:
        metrics = metrics_lib.ServeMetrics()
    anatomy_log = anatomy_lib.get_log()

    class Handler(BaseHTTPRequestHandler):
        def log_message(self, fmt, *args):
            logger.debug(fmt % args)

        def _attach_trace(self, request):
            """Adopt the LB relay's cross-hop context (trace id, LB
            request id, remaining deadline) onto the orchestrator
            Request BEFORE submit — the deadline admission gate and
            the anatomy join key both read it from there. Direct
            (relay-less) callers simply carry no headers."""
            trace_id, req_id, deadline_s = tracing.extract_headers(
                self.headers)
            request.trace_id = trace_id
            request.client_request_id = req_id
            if deadline_s is not None:
                request.deadline_at = time.perf_counter() + deadline_s
            return request

        def _seal(self, request, outcome):
            """Fold the finished request into the anatomy ring and
            journal a trace-linked deadline rejection — handler
            thread, off the tick path. Never lets observability take
            down the response path."""
            try:
                if anatomy_lib.enabled():
                    rec = anatomy_log.seal(request, outcome=outcome)
                    if rec is not None:
                        metrics.observe_phases(rec['phases'])
                if request.error and \
                        request.error.startswith('deadline exceeded'):
                    from skypilot_tpu import state as state_lib
                    state_lib.record_recovery_event(
                        'serve.deadline_reject',
                        scope=f'replica/{model_id}',
                        cause=request.error,
                        detail={
                            'request_id': (request.client_request_id
                                           or request.request_id),
                            'max_new_tokens': request.max_new_tokens,
                        },
                        trace_id=request.trace_id)
            except Exception:  # pylint: disable=broad-except
                logger.debug('anatomy seal failed', exc_info=True)

        def _json(self, code, payload):
            data = json.dumps(payload).encode()
            self.send_response(code)
            self.send_header('Content-Type', 'application/json')
            self.send_header('Content-Length', str(len(data)))
            self.end_headers()
            self.wfile.write(data)

        def do_GET(self):  # noqa: N802
            if self.path == '/health':
                self._json(200, {'status': 'healthy',
                                 'max_slots': config.max_slots})
            elif self.path == '/v1/models':
                self._json(200, {'object': 'list', 'data': [
                    {'id': model_id, 'object': 'model',
                     'owned_by': 'xsky'}]})
            elif self.path == '/metrics':
                data = metrics.render(orch=loop.orch).encode()
                self.send_response(200)
                self.send_header('Content-Type', 'text/plain; '
                                 'version=0.0.4; charset=utf-8')
                self.send_header('Content-Length', str(len(data)))
                self.end_headers()
                self.wfile.write(data)
            elif self.path.startswith('/anatomy'):
                # Replica-side anatomy records, newest-first
                # (?limit=&request_id=) — the SLO monitor fetches
                # these each scrape to join with the LB lifecycle
                # ring into cross-hop waterfalls.
                from urllib.parse import parse_qs, urlparse
                q = parse_qs(urlparse(self.path).query)
                try:
                    limit = int(q.get('limit', ['200'])[0])
                except ValueError:
                    limit = 200
                req_id = (q.get('request_id', [None])[0]) or None
                self._json(200, anatomy_log.records(
                    limit=limit, request_id=req_id))
            else:
                self._json(404, {'error': 'not found'})

        def do_POST(self):  # noqa: N802
            if max_queue and loop.orch._pending.qsize() >= max_queue:
                self._json(429, {'error': {
                    'message': 'server overloaded: admission queue is '
                               'full, retry another replica',
                    'type': 'overloaded_error'}})
                return
            if self.path == '/generate':
                self._generate()
            elif self.path == '/v1/completions':
                self._openai(chat=False)
            elif self.path == '/v1/chat/completions':
                self._openai(chat=True)
            else:
                self._json(404, {'error': 'not found'})

        def _read_json(self):
            """Body as a dict, or None (invalid JSON *or* a JSON
            scalar/array — handlers need .get to work)."""
            length = int(self.headers.get('Content-Length') or 0)
            try:
                body = json.loads(self.rfile.read(length))
            except json.JSONDecodeError:
                return None
            return body if isinstance(body, dict) else None

        def _generate(self):
            """Legacy token-ids wire surface (JetStream-twin)."""
            body = self._read_json()
            if body is None:
                self._json(400, {'error': 'bad json'})
                return
            prompt = body.get('prompt_tokens')
            if not isinstance(prompt, list) or not prompt:
                self._json(400, {'error': 'prompt_tokens required'})
                return
            request = orch_lib.Request(
                prompt_tokens=[int(t) for t in prompt],
                max_new_tokens=int(body.get('max_new_tokens', 128)),
                eos_token_id=body.get('eos_token_id'),
                temperature=float(body.get('temperature', 0.0)),
                top_k=int(body.get('top_k', 0)),
                top_p=float(body.get('top_p', 1.0)))
            self._attach_trace(request)
            t0 = time.perf_counter()
            loop.submit_and_wait(request)
            metrics.observe_request('/generate', request)
            self._seal(request,
                       outcome='error' if request.error else 'ok')
            if request.error:
                self._json(400, {'error': request.error})
                return
            self._json(200, {
                'output_tokens': request.output_tokens,
                'latency_s': round(time.perf_counter() - t0, 3),
            })

        def _openai(self, chat: bool):
            if tokenizer is None:
                self._json(503, {'error': {
                    'message': 'no tokenizer configured on this server',
                    'type': 'server_error'}})
                return
            body = self._read_json()
            if body is None:
                self._json(400, {'error': {
                    'message': 'request body is not valid JSON',
                    'type': 'invalid_request_error'}})
                return
            try:
                request, meta = openai_api.build_request(
                    body, tokenizer, config, model_id, chat,
                    admit_limit=loop.orch._admit_limit())
            except openai_api.ApiError as e:
                self._json(e.code, e.body())
                return
            endpoint = ('/v1/chat/completions' if chat
                        else '/v1/completions')
            self._attach_trace(request)
            if meta.stream:
                outcome = 'cancelled'
                try:
                    outcome = self._stream(request, meta)
                finally:
                    metrics.observe_request(endpoint, request,
                                            outcome=outcome)
                    self._seal(request, outcome=outcome)
                return
            siblings = [openai_api.clone_request(request)
                        for _ in range(meta.n - 1)]
            for sib in siblings:
                loop.submit(sib)
            self._await_with_stops(request, meta)
            # Siblings need the same stop-sequence cancellation as the
            # primary — without it a stopped sibling decodes its whole
            # budget, burning slots and stalling this response.
            deadline = time.time() + 600
            seen = {id(s): -1 for s in siblings}
            while (any(not s.done for s in siblings)
                   and time.time() < deadline):
                if meta.stop:
                    for sib in siblings:
                        if sib.done or sib.cancel_requested:
                            continue
                        # Decode only on new tokens (same guard as
                        # submit_and_wait): a per-tick full decode
                        # would be O(T²) detokenization at 200 Hz.
                        m = len(sib.output_tokens)
                        if m == seen[id(sib)]:
                            continue
                        seen[id(sib)] = m
                        sib_text = tokenizer.decode(
                            list(sib.output_tokens))
                        if openai_api.find_stop(sib_text,
                                                meta.stop) != -1:
                            sib.cancel_requested = True
                time.sleep(0.005)
            for sib in siblings:
                if not sib.done:
                    # Do not assemble a response from a request the
                    # orchestrator thread is still appending to.
                    sib.error = sib.error or 'server timeout'
                    sib.cancel_requested = True
            metrics.observe_request(endpoint, request)
            self._seal(request,
                       outcome='error' if request.error else 'ok')
            for sib in siblings:
                # Token counters must see every choice's generation
                # (but one HTTP request stays ONE request in the
                # count/latency series) — and, like the counters, one
                # HTTP request seals ONE anatomy record.
                metrics.observe_choice_tokens(sib)
            failed = request.error or next(
                (s.error for s in siblings if s.error), None)
            if failed:
                self._json(400, {'error': {'message': failed,
                                           'type': 'engine_error'}})
                return
            text, finish_reason = openai_api.finalize_text(
                meta, request, tokenizer)
            extra = []
            for sib in siblings:
                sib_text, sib_reason = openai_api.finalize_text(
                    meta, sib, tokenizer)
                extra.append((sib, sib_text, sib_reason))
            self._json(200, openai_api.response_body(
                meta, request, text, finish_reason, tokenizer=tokenizer,
                extra_choices=extra))

        def _await_with_stops(self, request, meta):
            """Blocking wait that still cancels on a stop-sequence hit —
            without this, a stopped request would keep burning its
            decode slot until max_tokens even though the text past the
            stop is discarded."""

            def check_stop(req):
                if req.cancel_requested:
                    return
                text = tokenizer.decode(list(req.output_tokens))
                if openai_api.find_stop(text, meta.stop) != -1:
                    req.cancel_requested = True

            loop.submit_and_wait(
                request,
                on_progress=check_stop if meta.stop else None)

        def _stream(self, request, meta) -> str:
            """Server-sent events; one chunk per newly safe text delta.
            Returns the metrics outcome ('ok'/'error'/'cancelled')."""
            self.send_response(200)
            self.send_header('Content-Type', 'text/event-stream')
            self.send_header('Cache-Control', 'no-cache')
            self.send_header('Connection', 'close')
            self.end_headers()
            emitter = openai_api.StreamEmitter(tokenizer, meta.stop)
            loop.submit(request)
            first = True
            deadline = time.time() + 600.0
            seen = -1
            try:
                if meta.echo and meta.kind == 'completion':
                    # OpenAI streams the echoed prompt as the first
                    # chunk (same divergence fix as finalize_text).
                    prompt_text = meta.prompt_text or \
                        tokenizer.decode(meta.prompt_tokens)
                    self.wfile.write(openai_api.sse(
                        openai_api.chunk_body(meta, prompt_text, None,
                                              first=True)))
                    self.wfile.flush()
                    first = False
                timed_out = False
                while True:
                    if time.time() > deadline:
                        request.cancel_requested = True
                        timed_out = True
                        break
                    done = request.done
                    # Snapshot: the orchestrator thread appends
                    # concurrently; list() pins a consistent view.
                    tokens = list(request.output_tokens)
                    if len(tokens) == seen and not done:
                        time.sleep(0.005)  # nothing new: don't re-decode
                        continue
                    seen = len(tokens)
                    delta = emitter.push(tokens, final=done)
                    if delta or (first and meta.kind == 'chat'):
                        self.wfile.write(openai_api.sse(
                            openai_api.chunk_body(meta, delta, None,
                                                  first=first)))
                        self.wfile.flush()
                        first = False
                    if emitter.finished:  # stop-sequence hit
                        request.cancel_requested = True
                        break
                    if done:
                        break
                    time.sleep(0.005)
                error = request.error or \
                    ('server timeout' if timed_out else None)
                if error and not emitter.finished:
                    # Engine died / deadline: tell the client instead of
                    # dressing a truncation up as a clean finish.
                    self.wfile.write(openai_api.sse(
                        {'error': {'message': error,
                                   'type': 'engine_error'}}))
                    self.wfile.write(openai_api.SSE_DONE)
                    self.wfile.flush()
                    return 'error'
                finish_reason = emitter.finish_reason or (
                    'length' if len(request.output_tokens) >=
                    request.max_new_tokens else 'stop')
                self.wfile.write(openai_api.sse(openai_api.chunk_body(
                    meta, '', finish_reason)))
                self.wfile.write(openai_api.SSE_DONE)
                self.wfile.flush()
                return 'ok'
            except (BrokenPipeError, ConnectionResetError):
                # Client went away: free the slot at the next token.
                request.cancel_requested = True
                return 'cancelled'

    return Handler


def main() -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument('--model', default='llama3-1b')
    parser.add_argument('--hf-checkpoint', default=None,
                        help='Local HuggingFace checkpoint dir '
                             '(llama/mistral/qwen/gemma): real weights '
                             'are converted on the host and served; '
                             'overrides --model. Point --tokenizer at '
                             'the same dir for text endpoints.')
    parser.add_argument('--port', type=int, default=8080)
    parser.add_argument('--max-slots', type=int, default=16)
    parser.add_argument('--max-target-len', type=int, default=2048)
    parser.add_argument('--kv-dtype', default='bf16',
                        choices=['bf16', 'int8'],
                        help='int8 halves KV-cache HBM (per-head scales)')
    parser.add_argument('--weight-dtype', default='bf16',
                        choices=['bf16', 'int8', 'int4'],
                        help='int8 halves weight HBM (per-channel '
                             'scales, dequant fused into each matmul; '
                             'fits 8B on one 16 GB chip); int4 halves '
                             'it again (packed nibbles, group-128 '
                             'scales)')
    parser.add_argument('--mesh', default=None,
                        help="e.g. 'tensor=4' to shard across chips")
    parser.add_argument('--tokenizer', default='byte',
                        help="'byte' (built-in reversible byte-level) "
                             'or a local HuggingFace tokenizer path '
                             '(enables the /v1 text endpoints)')
    parser.add_argument('--draft-model', default=None,
                        help='Enable speculative decoding with this '
                             'draft model (same vocab; e.g. llama3-1b '
                             'drafting for llama3-8b)')
    parser.add_argument('--spec-gamma', type=int, default=4,
                        help='Draft tokens proposed per speculative '
                             'round')
    parser.add_argument('--spec-ngram', action='store_true',
                        help='Draft-model-free speculation: propose '
                             'continuations by prompt-lookup (n-gram '
                             'match against the request history), '
                             'verified in one target pass. Wins on '
                             'copy-heavy generation; no extra HBM.')
    parser.add_argument('--model-id', default=None,
                        help='Model id reported by /v1/models '
                             '(default: --model)')
    parser.add_argument('--max-queue', type=int, default=64,
                        help='Pending-admission cap: beyond this the '
                             'replica sheds load with 429 so the serve '
                             'LB retries elsewhere. 0 = unbounded.')
    parser.add_argument('--decode-steps', type=int, default=4,
                        help='Decode steps fused per device dispatch '
                             '(amortizes dispatch latency; streaming '
                             'granularity and EOS latency grow by the '
                             'same factor). 1 = per-token.')
    parser.add_argument('--no-batched-admission', action='store_true',
                        help='Per-prompt prefill admission. Batched '
                             'admission (default) fuses a wave into '
                             'one dispatch — right when dispatch '
                             'latency dominates (remote TPU); disable '
                             'on compute-bound deployments where '
                             'prefill FLOPs dominate and pow2 wave '
                             'padding wastes forward work.')
    parser.add_argument('--kv-page-size', type=int, default=0,
                        help='Paged KV cache: tokens per page (must '
                             'divide max-target-len and every prefill '
                             'bucket; llama/deepseek families only). '
                             'Admission is then gated by free-page '
                             'headroom for each request\'s actual '
                             'prompt+max_new budget instead of a '
                             'worst-case slot reservation. '
                             '0 (default) keeps the dense slot cache')
    parser.add_argument('--kv-num-pages', type=int, default=0,
                        help='Pages in the paged-KV arena. 0 sizes it '
                             'to the dense cache footprint '
                             '(max_slots * max_target_len / page)')
    parser.add_argument('--prefix-cache', type=int, default=0,
                        help='Prefix-cache entries (device-resident KV '
                             'reuse for shared prompt prefixes; entry '
                             'bytes are bounded, but entries are bf16 '
                             'KV — budget HBM before enabling). '
                             '0 (default) disables')
    args = parser.parse_args()

    import jax.numpy as jnp
    hf_params = None
    if args.hf_checkpoint:
        # Convert on the HOST (CPU): real checkpoints are often larger
        # than a chip's HBM at bf16, and quantization below must see
        # the bf16 tree before anything ships to the device.
        from skypilot_tpu.models import convert as convert_lib
        cpu = jax.local_devices(backend='cpu')[0]
        with jax.default_device(cpu):
            model, hf_params = convert_lib.from_hf(args.hf_checkpoint)
        logger.info(f'Converted {args.hf_checkpoint}: '
                    f'{type(model).__name__}, '
                    f'{model.num_params() / 1e9:.2f}B params')
    else:
        model = models.get_config(args.model)
    model = dataclasses.replace(model, remat=False)
    prefix_entries = args.prefix_cache
    if not engine_lib.supports_chunked_prefill(models.module_for(model)):
        prefix_entries = 0   # family lacks the chunked-prefill path
    config = engine_lib.EngineConfig(
        model=model, max_slots=args.max_slots,
        max_target_len=args.max_target_len,
        kv_dtype=jnp.int8 if args.kv_dtype == 'int8' else jnp.bfloat16,
        weight_dtype={'int8': jnp.int8, 'int4': 'int4',
                      'bf16': jnp.bfloat16}[args.weight_dtype],
        prefix_cache_entries=prefix_entries,
        batched_admission=not args.no_batched_admission,
        kv_page_size=args.kv_page_size,
        kv_num_pages=args.kv_num_pages)
    mesh = None
    if args.mesh:
        from skypilot_tpu.train.launch import parse_mesh
        mesh = mesh_lib.build_mesh(
            parse_mesh(args.mesh).resolve(jax.device_count()))
    logger.info(f'Initializing {args.model} on '
                f'{jax.devices()[0].device_kind} x{jax.device_count()}')
    model_lib = models.module_for(model)
    from jax.sharding import NamedSharding, PartitionSpec
    replicated = (NamedSharding(mesh, PartitionSpec())
                  if mesh is not None else jax.devices()[0])
    if args.weight_dtype in ('int8', 'int4'):
        # Init/convert + quantize on HOST: the whole point of quantized
        # weights is serving a model whose bf16 tree does not fit the
        # chip (8B = 16 GB bf16 on a 16 GB chip), so the bf16 tree must
        # never touch device HBM. Only the quantized tree is shipped.
        from skypilot_tpu.ops import quantization as qops
        cpu = jax.local_devices(backend='cpu')[0]
        with jax.default_device(cpu):
            params = (hf_params if hf_params is not None
                      else model_lib.init(model, jax.random.PRNGKey(0)))
            params = (qops.quantize_params(params)
                      if args.weight_dtype == 'int8'
                      else qops.quantize_params_int4(params))
        params = jax.device_put(params, replicated)
    elif hf_params is not None:
        params = jax.device_put(hf_params, replicated)
    else:
        params = model_lib.init(model, jax.random.PRNGKey(0))
    engine = engine_lib.InferenceEngine(config, params, mesh=mesh)
    if args.draft_model:
        draft_cfg = dataclasses.replace(
            models.get_config(args.draft_model), remat=False)
        draft_engine_config = engine_lib.EngineConfig(
            model=draft_cfg, max_slots=args.max_slots,
            max_target_len=args.max_target_len)
        draft_lib = models.module_for(draft_cfg)
        draft_params = draft_lib.init(draft_cfg, jax.random.PRNGKey(1))
        draft_engine = engine_lib.InferenceEngine(
            draft_engine_config, draft_params, mesh=mesh)
        orch = orch_lib.SpeculativeOrchestrator(
            engine, draft_engine, gamma=args.spec_gamma)
        logger.info(f'Speculative decoding: draft={args.draft_model} '
                    f'gamma={args.spec_gamma}')
        if args.decode_steps != 1:
            logger.warning('--decode-steps is ignored with '
                           '--draft-model: speculation already '
                           'amortizes dispatch per round (γ+1 tokens).')
        if args.spec_ngram:
            logger.warning('--spec-ngram is ignored with '
                           '--draft-model: draft-model speculation '
                           'takes precedence.')
    elif args.spec_ngram:
        orch = orch_lib.NgramSpeculator(engine, gamma=args.spec_gamma)
        logger.info(f'Prompt-lookup speculation: gamma='
                    f'{args.spec_gamma}')
        if args.decode_steps != 1:
            logger.warning('--decode-steps is ignored with '
                           '--spec-ngram: speculation already '
                           'amortizes dispatch per round (γ+1 tokens).')
    else:
        orch = orch_lib.Orchestrator(engine,
                                     decode_steps=args.decode_steps)
    # Warm the compile caches before declaring healthy — including the
    # logprobs decode variant, or the first logprobs request would
    # trigger a mid-serving XLA compile that stalls every active slot.
    orch.generate([[1, 2, 3]], max_new_tokens=2)
    orch.submit(orch_lib.Request(prompt_tokens=[1, 2, 3],
                                 max_new_tokens=2, logprobs=1))
    orch.run_until_drained()
    # Penalties select a distinct compiled decode variant — warm it too,
    # or the first penalized request stalls every slot on an XLA compile.
    orch.submit(orch_lib.Request(prompt_tokens=[1, 2, 3],
                                 max_new_tokens=2,
                                 presence_penalty=0.1,
                                 frequency_penalty=0.1))
    orch.run_until_drained()
    # Admission waves: batched prefill compiles one variant per
    # power-of-two wave size per bucket — warm every size (greedy and
    # sampled trace signatures both), or the first odd-sized wave
    # mid-serving stalls every active slot on an XLA compile.
    pow2 = 2
    while True:
        # min() mirrors the engine's padding rule, so a non-pow2
        # max_slots still gets its capped full-wave variant warmed.
        wave = min(pow2, engine.config.max_slots)
        orch.generate([[1, 2, 3]] * wave, max_new_tokens=2)
        for _ in range(wave):
            orch.submit(orch_lib.Request(prompt_tokens=[1, 2, 3],
                                         max_new_tokens=2,
                                         temperature=0.8,
                                         top_k=5, top_p=0.9))
        orch.run_until_drained()
        if wave == engine.config.max_slots:
            break
        pow2 *= 2
    loop = ServingLoop(orch)

    from skypilot_tpu.infer import tokenizer as tokenizer_lib
    try:
        tokenizer = tokenizer_lib.get_tokenizer(args.tokenizer,
                                                model.vocab_size)
    except ValueError as e:
        # Tiny-vocab models can't host the byte tokenizer; token-ids
        # endpoint still works, /v1 routes report 503.
        logger.warning(f'No tokenizer: {e}')
        tokenizer = None
    import os
    default_id = (os.path.basename(args.hf_checkpoint.rstrip('/'))
                  if args.hf_checkpoint else args.model)
    server = ThreadingHTTPServer(
        ('0.0.0.0', args.port),
        build_handler(loop, config, tokenizer=tokenizer,
                      model_id=args.model_id or default_id,
                      max_queue=args.max_queue))
    logger.info(f'Serving on :{args.port}')
    server.serve_forever()
    return 0


if __name__ == '__main__':
    raise SystemExit(main())
