"""Rule registry: ``all_rules()`` returns a fresh instance of every
registered rule (rules hold per-run state, so instances are never
shared between runs).

Adding a rule: subclass :class:`tools.xskylint.engine.Rule` in the
topical module, append the class to that module's ``RULES``, give it a
positive + negative fixture in tests/unit_tests/test_xskylint.py (a
self-check fails the suite if you forget), and document it in
docs/static-analysis.md.
"""
from typing import List

from tools.xskylint import engine
from tools.xskylint.rules import concurrency
from tools.xskylint.rules import contracts
from tools.xskylint.rules import crossfile
from tools.xskylint.rules import interproc
from tools.xskylint.rules import observability
from tools.xskylint.rules import statedb

_RULE_CLASSES = (concurrency.RULES + observability.RULES +
                 statedb.RULES + contracts.RULES + crossfile.RULES +
                 interproc.RULES)


def all_rules() -> List[engine.Rule]:
    rules = [cls() for cls in _RULE_CLASSES]
    ids = [r.id for r in rules]
    assert len(ids) == len(set(ids)), f'duplicate rule ids: {ids}'
    return rules
