"""Agent-side autostop teardown unit tests (agent/self_teardown).

The e2e fake-cloud path lives in test_launch_e2e.py::test_autostop_*;
these cover the dispatch/fallback logic and the GCP wiring against the
injected provisioner entry points.
"""
import json
import os

import pytest

from skypilot_tpu.agent import self_teardown


def _write_info(root, provider='gcp', cluster_name='c1', config=None):
    os.makedirs(root, exist_ok=True)
    with open(os.path.join(root, 'cluster_info.json'), 'w') as f:
        json.dump({
            'instances': {}, 'head_instance_id': None,
            'provider_name': provider,
            'provider_config': config or {'project_id': 'p',
                                          'zone': 'us-central2-b'},
            'cluster_name': cluster_name,
        }, f)


class _Recorder:
    def __init__(self, fail=False):
        self.calls = []
        self.fail = fail

    def __call__(self, provider, cluster_name, provider_config):
        self.calls.append((provider, cluster_name, provider_config))
        if self.fail:
            raise RuntimeError('simulated API failure')


def test_gcp_down_dispatches_terminate(tmp_path):
    _write_info(tmp_path)
    term, stop = _Recorder(), _Recorder()
    ok = self_teardown.attempt_self_teardown(
        str(tmp_path), down=True, terminate_fn=term, stop_fn=stop)
    assert ok
    assert term.calls == [('gcp', 'c1',
                           {'project_id': 'p', 'zone': 'us-central2-b'})]
    assert stop.calls == []


def test_gcp_stop_dispatches_stop(tmp_path):
    _write_info(tmp_path)
    term, stop = _Recorder(), _Recorder()
    ok = self_teardown.attempt_self_teardown(
        str(tmp_path), down=False, terminate_fn=term, stop_fn=stop)
    assert ok
    assert stop.calls and not term.calls


def test_api_failure_falls_back(tmp_path):
    """An API error (missing scopes, transient) must degrade to the
    marker-file pull model, never raise out of the daemon tick."""
    _write_info(tmp_path)
    ok = self_teardown.attempt_self_teardown(
        str(tmp_path), down=True, terminate_fn=_Recorder(fail=True),
        stop_fn=_Recorder())
    assert not ok


def test_non_self_service_provider_falls_back(tmp_path):
    _write_info(tmp_path, provider='aws')
    term = _Recorder()
    ok = self_teardown.attempt_self_teardown(
        str(tmp_path), down=True, terminate_fn=term, stop_fn=term)
    assert not ok and not term.calls


def test_missing_identity_falls_back(tmp_path):
    ok = self_teardown.attempt_self_teardown(
        str(tmp_path), down=True, terminate_fn=_Recorder(),
        stop_fn=_Recorder())
    assert not ok


def test_env_gate_disables(tmp_path, monkeypatch):
    _write_info(tmp_path)
    monkeypatch.setenv('XSKY_AGENT_NO_SELF_TEARDOWN', '1')
    term = _Recorder()
    ok = self_teardown.attempt_self_teardown(
        str(tmp_path), down=True, terminate_fn=term, stop_fn=term)
    assert not ok and not term.calls


def test_legacy_info_without_cluster_name_falls_back(tmp_path):
    """cluster_info.json written by a pre-r4 backend has no
    cluster_name key — the agent must fall back, not guess."""
    os.makedirs(tmp_path, exist_ok=True)
    with open(os.path.join(tmp_path, 'cluster_info.json'), 'w') as f:
        json.dump({'instances': {}, 'head_instance_id': None,
                   'provider_name': 'gcp', 'provider_config': {}}, f)
    ok = self_teardown.attempt_self_teardown(
        str(tmp_path), down=True, terminate_fn=_Recorder(),
        stop_fn=_Recorder())
    assert not ok


def test_gcp_terminate_rides_instance_identity(tmp_path, monkeypatch):
    """End-to-end through the real provisioner dispatch with a fake
    REST transport: DELETE calls for the cluster's queued resources and
    nodes, authenticated by the metadata-server token chain (the
    instance's own identity)."""
    from skypilot_tpu.provision.gcp import instance as gcp_instance

    calls = []

    class _FakeTransport:
        def request(self, method, url, params=None, body=None,
                    timeout=60):
            calls.append((method, url))
            if method == 'GET' and 'queuedResources' in url:
                return {'queuedResources': [
                    {'name': 'projects/p/locations/z/queuedResources/qr1',
                     'state': {'state': 'ACTIVE'},
                     'tpu': {'nodeSpec': [{'node': {
                         'labels': {'xsky-cluster': 'c1'}}}]}}]}
            if method == 'GET' and url.endswith('/nodes'):
                return {'nodes': [
                    {'name': 'projects/p/locations/z/nodes/c1-0',
                     'state': 'READY',
                     'labels': {'xsky-cluster': 'c1'}}]}
            if method == 'GET' and 'instanceGroupManagers' in url:
                # No DWS MIG for this cluster (terminate probes it).
                from skypilot_tpu.provision.gcp import rest
                raise rest.GcpApiError(404, 'notFound', 'no mig')
            if method == 'GET' and 'instances' in url:
                return {'items': []}
            if method == 'DELETE':
                return {'name': 'operations/op1', 'done': True}
            return {'done': True}

    monkeypatch.setattr(gcp_instance, '_transport_factory',
                        _FakeTransport)
    _write_info(tmp_path, config={'project_id': 'p', 'zone': 'z'})
    ok = self_teardown.attempt_self_teardown(str(tmp_path), down=True)
    assert ok
    deletes = [u for m, u in calls if m == 'DELETE']
    assert any('queuedResources/qr1' in u for u in deletes)
    assert any('/nodes/c1-0' in u for u in deletes)
