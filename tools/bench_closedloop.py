#!/usr/bin/env python3
"""Closed-loop serving control referee: chaos load drill, controlled
vs no-control baseline.

Two arms of the SAME fake-cloud serve stack run the SAME fault
schedule under an **open-loop** load generator (absolute arrival
schedule — queueing delay counts; Pareto prompt/output lengths):

  * fault 1 — ``lb.proxy`` latency pinned to one replica (the slow
    replica);
  * fault 2 — forced metric anomalies (``metrics.detector`` chaos:
    dispatch-gap trend, burn-rate acceleration, then heartbeat-age
    drift);
  * fault 3 — spot preemption of a healthy replica (``fake.preempt``);
  * fault 4 — a 2x traffic spike for the rest of the drill.

The **baseline** arm is the no-control stack: round-robin routing,
fixed replicas, remediation engine disabled. The **controlled** arm is
the closed loop: ``telemetry_routed`` routing, ``burn_rate``
autoscaling, and the anomaly→remediation engine riding the controller
tick (deprioritize / graceful drain / autoscaler fast-path).

Exit 0 only if, end to end:

  * the controlled arm's steady-state p99 TTFT (final load block,
    spike rate, after remediation) beats the baseline's — the SLO held
    because the loop closed;
  * EVERY injected fault detector (dispatch_gap_trend,
    burn_rate_accel, heartbeat_age_drift, preemption) produced a
    remediation that was applied AND resolved, the applied/resolved
    pair sharing one non-null trace id with the triggering anomaly;
  * the remediations are visible via ``xsky remediations --json``.

Prints ONE JSON line; exit 1 on any gate failure. ``--smoke`` is the
tier-1 subprocess gate (reduced counts, same gates).

Usage:
    python tools/bench_closedloop.py [--smoke]
"""
import argparse
import json
import os
import random
import shutil
import sys
import tempfile
import textwrap
import threading
import time
import urllib.request

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO_ROOT)

_FAULT_DETECTORS = ('dispatch_gap_trend', 'burn_rate_accel',
                    'heartbeat_age_drift', 'preemption')

# The slow replica's injected upstream latency: far past the 100 ms
# TTFT target, so routing around it is visible in p99.
_SLOW_S = 0.25

_REPLICA_SCRIPT = textwrap.dedent('''\
    import http.server, os, sys, time, urllib.parse
    sys.path.insert(0, {repo_root!r})
    from skypilot_tpu.infer import metrics as metrics_lib
    metrics = metrics_lib.ServeMetrics()

    class H(http.server.BaseHTTPRequestHandler):
        def log_message(self, *a):
            pass
        def do_GET(self):
            if self.path == '/metrics':
                body = metrics.render().encode()
            else:
                q = urllib.parse.urlparse(self.path).query
                params = dict(urllib.parse.parse_qsl(q))
                gen = int(params.get('g', 16))
                body = b'x' * min(65536, gen * 4)
                metrics.observe('/gen', 'ok',
                                int(params.get('p', 32)), gen,
                                ttft_s=0.005,
                                e2e_s=0.005 + gen * 2e-4,
                                tpot_s=0.004)
            self.send_response(200)
            self.send_header('Content-Length', str(len(body)))
            self.end_headers()
            self.wfile.write(body)

    http.server.ThreadingHTTPServer(
        ('127.0.0.1', int(os.environ['PORT'])), H).serve_forever()
''')

_BASELINE_YAML = textwrap.dedent('''\
    name: {name}
    resources:
      accelerators: tpu-v5e-8
      use_spot: true
    service:
      readiness_probe: /
      replica_policy:
        min_replicas: 2
    run: |
      python {script}
''')

_CONTROLLED_YAML = textwrap.dedent('''\
    name: {name}
    resources:
      accelerators: tpu-v5e-8
      use_spot: true
    service:
      readiness_probe: /
      load_balancing_policy: telemetry_routed
      replica_policy:
        min_replicas: 2
        max_replicas: 4
        autoscaler: burn_rate
      slo:
        ttft_p99_ms: 100
        availability: 0.99
    run: |
      python {script}
''')


def _open_loop(lb_port: int, rate_qps: float, duration_s: float,
               rng: random.Random) -> dict:
    """Open-loop block: arrivals on an absolute schedule, latency
    measured from the SCHEDULED arrival (coordinated-omission guard);
    heavy-tail Pareto prompt/output lengths."""
    n = int(rate_qps * duration_s)
    t_start = time.perf_counter() + 0.1
    schedule = [t_start + i / rate_qps for i in range(n)]
    latencies = []
    errors = [0]
    lock = threading.Lock()

    def fire(at: float) -> None:
        gen = int(min(2000, rng.paretovariate(1.5) * 16))
        prompt = int(min(4000, rng.paretovariate(1.2) * 64))
        try:
            with urllib.request.urlopen(
                    f'http://127.0.0.1:{lb_port}/gen?p={prompt}'
                    f'&g={gen}', timeout=30) as resp:
                resp.read()
            lat = time.perf_counter() - at
            with lock:
                latencies.append(lat)
        except Exception:  # pylint: disable=broad-except
            with lock:
                errors[0] += 1

    threads = []
    for at in schedule:
        delay = at - time.perf_counter()
        if delay > 0:
            time.sleep(delay)
        thread = threading.Thread(target=fire, args=(at,),
                                  name='xsky-bench-loadgen',
                                  daemon=True)
        thread.start()
        threads.append(thread)
    for thread in threads:
        thread.join(timeout=60)
    latencies.sort()

    def pctl(q: float):
        if not latencies:
            return None
        return round(
            latencies[min(len(latencies) - 1,
                          int(q * len(latencies)))] * 1000, 2)

    return {'offered': n, 'completed': len(latencies),
            'errors': errors[0], 'p50_ms': pctl(0.5),
            'p99_ms': pctl(0.99)}


def _slow_rule(endpoint: str) -> dict:
    return {'match': {'replica': endpoint}, 'latency_s': _SLOW_S}


def _force_rules(detectors) -> list:
    return [{'match': {'detector': d}, 'force': 'anomaly'}
            for d in detectors]


class _Arm:
    """One service (controlled or baseline) through the fault
    schedule. Shares the process-wide state DBs — rows are scoped by
    service name."""

    def __init__(self, name: str, yaml_tpl: str, script: str,
                 args) -> None:
        self.name = name
        self.scope = f'service/{name}'
        self.args = args
        import io

        import yaml

        from skypilot_tpu import task as task_lib
        from skypilot_tpu.serve import state as serve_state
        config = yaml.safe_load(io.StringIO(yaml_tpl.format(
            name=name, script=script)))
        self.task = task_lib.Task.from_yaml_config(config)
        import socket
        with socket.socket() as s:
            s.bind(('127.0.0.1', 0))
            self.lb_port = s.getsockname()[1]
        serve_state.add_service(name, self.task.to_yaml_config(),
                                self.lb_port)
        from skypilot_tpu.serve import controller as controller_lib
        self.controller = controller_lib.SkyServeController(name)
        self.thread = threading.Thread(
            target=self.controller.run,
            name=f'xsky-bench-controller-{name}', daemon=True)

    def start_and_wait_ready(self, min_replicas: int = 2) -> bool:
        from skypilot_tpu.serve import state as serve_state
        self.thread.start()
        deadline = time.time() + 150
        while time.time() < deadline:
            record = serve_state.get_service(self.name)
            if record['status'] == serve_state.ServiceStatus.FAILED:
                return False
            ready = self.controller.replica_manager.ready_endpoints()
            if len(ready) >= min_replicas:
                return True
            time.sleep(0.3)
        return False

    def replica_map(self) -> dict:
        """replica_id → (cluster_name, endpoint) for READY replicas."""
        from skypilot_tpu.serve import state as serve_state
        return {r['replica_id']: (r['cluster_name'], r['endpoint'])
                for r in serve_state.get_replicas(self.name)
                if r['status'] == serve_state.ReplicaStatus.READY}

    def stop(self) -> None:
        from skypilot_tpu.serve import core as serve_core
        self.controller.stop()
        self.thread.join(timeout=30)
        try:
            serve_core.down(self.name)
        except Exception:  # pylint: disable=broad-except
            pass


def _wait(predicate, deadline_s: float, interval: float = 0.3) -> bool:
    deadline = time.time() + deadline_s
    while time.time() < deadline:
        if predicate():
            return True
        time.sleep(interval)
    return False


def _applied(scope: str, detector: str) -> bool:
    from skypilot_tpu import state
    return any(r['status'] in ('applied', 'resolved')
               for r in state.get_remediations(
                   scope=scope, detector=detector, latest_only=False))


def _pair_trace(scope: str, detector: str):
    """The (applied, resolved) journal/state pair's shared trace id,
    or None if the pair is incomplete or trace-broken."""
    from skypilot_tpu import state
    rows = state.get_remediations(scope=scope, detector=detector,
                                  latest_only=False)
    applied = [r for r in rows if r['status'] == 'applied']
    resolved = [r for r in rows if r['status'] == 'resolved']
    if not applied or not resolved:
        return None
    trace = resolved[0]['trace_id']
    if not trace or not any(r['trace_id'] == trace for r in applied):
        return None
    # The journal twin must carry the SAME trace id on both events.
    kinds = {e['event_type'] for e in state.get_recovery_events(
        scope=f'{scope}/remediation/{detector}', limit=200)
        if e.get('trace_id') == trace}
    if not {'remediation.applied', 'remediation.resolved'} <= kinds:
        return None
    return trace


def _run_arm(arm: '_Arm', controlled: bool, args) -> dict:
    from skypilot_tpu import state
    from skypilot_tpu.utils import chaos
    from skypilot_tpu.utils import metrics_history

    result: dict = {'service': arm.name, 'controlled': controlled}
    os.environ['XSKY_REMEDIATION_ENABLED'] = '1' if controlled else '0'

    detect_stop = threading.Event()

    def detect_loop() -> None:
        # The metrics recorder's detector pass, at drill cadence.
        while not detect_stop.is_set():
            metrics_history.detect_anomalies()
            detect_stop.wait(0.3)

    detector_thread = threading.Thread(
        target=detect_loop, name='xsky-bench-detect', daemon=True)
    if controlled:
        detector_thread.start()

    try:
        if not arm.start_and_wait_ready():
            result['error'] = 'service never reached 2 READY replicas'
            result['pass'] = False
            return result
        replicas = arm.replica_map()
        rids = sorted(replicas)
        slow_ep = replicas[rids[0]][1]
        preempt_cluster = replicas[rids[1]][0]
        result['slow_replica'] = slow_ep
        result['preempted_cluster'] = preempt_cluster

        rate = 10.0 if args.smoke else 20.0
        dur = 5.0 if args.smoke else 8.0
        rng = random.Random(11)

        # Phase 1: slow replica + (controlled) forced dispatch-gap and
        # burn-accel anomalies, under normal load.
        plan = {'points': {'lb.proxy': _slow_rule(slow_ep)}}
        if controlled:
            plan['points']['metrics.detector'] = _force_rules(
                ['dispatch_gap_trend', 'burn_rate_accel'])
        chaos.load_plan(plan)
        block1 = threading.Thread(
            target=lambda: result.update(block1=_open_loop(
                arm.lb_port, rate, dur, rng)),
            name='xsky-bench-block1', daemon=True)
        block1.start()
        if controlled:
            result['phase1_applied'] = _wait(
                lambda: _applied(arm.scope, 'dispatch_gap_trend') and
                _applied(arm.scope, 'burn_rate_accel'), 30)
        block1.join(timeout=120)

        # Phase 2: spot preemption of a healthy replica + (controlled)
        # forced heartbeat drift, under the 2x traffic spike. Loading
        # the new plan stops forcing phase 1's anomalies — they clear,
        # and the engine resolves them.
        plan = {'points': {
            'lb.proxy': _slow_rule(slow_ep),
            'fake.preempt': {'match': {'cluster_name': preempt_cluster},
                             'first_n': 1},
        }}
        if controlled:
            plan['points']['metrics.detector'] = _force_rules(
                ['heartbeat_age_drift'])
        chaos.load_plan(plan)
        block2 = threading.Thread(
            target=lambda: result.update(block2=_open_loop(
                arm.lb_port, rate * 2, dur, rng)),
            name='xsky-bench-block2', daemon=True)
        block2.start()
        result['preemption_applied'] = _wait(
            lambda: _applied(arm.scope, 'preemption'), 40)
        if controlled:
            result['phase2_applied'] = _wait(
                lambda: _applied(arm.scope, 'heartbeat_age_drift'), 30)
        block2.join(timeout=120)

        # Phase 3: stop forcing anomalies (they clear → resolutions),
        # keep the slow rule (its replica was drained in the
        # controlled arm; the baseline still routes to it), wait for
        # the fleet to re-stabilize, then measure the steady-state
        # block at spike rate — the held-p99 gate.
        chaos.load_plan({'points': {'lb.proxy': _slow_rule(slow_ep)}})
        if controlled:
            result['drained_slow'] = _wait(
                lambda: slow_ep not in
                arm.controller.replica_manager.ready_endpoints(), 30)
            result['all_resolved'] = _wait(
                lambda: all(_pair_trace(arm.scope, d) is not None
                            for d in _FAULT_DETECTORS), 45)
        result['refleet'] = _wait(
            lambda: len(arm.controller.replica_manager
                        .ready_endpoints()) >= 2, 60)
        result['block3'] = _open_loop(arm.lb_port, rate * 2,
                                      dur + 1.0, rng)
        if controlled:
            result['remediations'] = {
                d: _pair_trace(arm.scope, d) for d in _FAULT_DETECTORS}
        return result
    finally:
        detect_stop.set()
        if controlled:
            detector_thread.join(timeout=5)
        chaos.clear()
        # Flush forced-anomaly state so the next arm starts clean.
        metrics_history.detect_anomalies()
        arm.stop()


def bench(args) -> dict:
    scratch = tempfile.mkdtemp(prefix='xsky-bench-closedloop-')
    os.environ['XSKY_STATE_DB'] = os.path.join(scratch, 'state.db')
    os.environ['XSKY_SERVE_DB'] = os.path.join(scratch, 'serve.db')
    os.environ['XSKY_FAKE_CLOUD_DIR'] = os.path.join(scratch, 'fake')
    os.environ['XSKY_SERVE_LOG_DIR'] = os.path.join(scratch, 'logs')
    os.environ['XSKY_ENABLE_FAKE_CLOUD'] = '1'
    os.environ['XSKY_SERVE_INTERVAL'] = '0.25'
    os.environ['XSKY_SLO_SCRAPE_INTERVAL_S'] = '1'
    os.environ['XSKY_SLO_BURN_WINDOWS'] = '5,30'
    os.environ['XSKY_DRAIN_DEADLINE_S'] = '5'
    # Keep the preemption arms symmetric between the two services:
    # peer drain is covered by unit tests, not this referee.
    os.environ['XSKY_DRAIN_ON_PREEMPTION'] = '0'
    # Each fault applies exactly once per arm here; a long cooldown
    # keeps re-fires out of the drill's bookkeeping.
    os.environ['XSKY_REMEDIATION_COOLDOWN_S'] = '300'

    from click.testing import CliRunner

    from skypilot_tpu import check as check_lib
    from skypilot_tpu import state
    from skypilot_tpu.client import cli as cli_mod

    check_lib.set_enabled_clouds_for_test(['fake'])
    state.reset_for_test()

    script = os.path.join(scratch, 'replica.py')
    with open(script, 'w', encoding='utf-8') as f:
        f.write(_REPLICA_SCRIPT.format(repo_root=_REPO_ROOT))

    result: dict = {}
    try:
        baseline_arm = _Arm('clbase', _BASELINE_YAML, script, args)
        result['baseline'] = _run_arm(baseline_arm, False, args)
        controlled_arm = _Arm('clctl', _CONTROLLED_YAML, script, args)
        result['controlled'] = _run_arm(controlled_arm, True, args)

        base3 = (result['baseline'].get('block3') or {})
        ctl3 = (result['controlled'].get('block3') or {})
        base_p99 = base3.get('p99_ms')
        ctl_p99 = ctl3.get('p99_ms')
        held = (base_p99 is not None and ctl_p99 is not None and
                ctl_p99 < base_p99)
        result['p99_held'] = {'baseline_ms': base_p99,
                              'controlled_ms': ctl_p99, 'pass': held}

        pairs = result['controlled'].get('remediations') or {}
        traced = {d: bool(pairs.get(d)) for d in _FAULT_DETECTORS}
        result['fault_remediations'] = {**traced,
                                        'pass': all(traced.values())}

        cli = CliRunner().invoke(
            cli_mod.cli,
            ['remediations', '--scope', 'service/clctl', '--json'])
        cli_rows = [json.loads(line) for line in
                    cli.output.strip().splitlines()] \
            if cli.exit_code == 0 and cli.output.strip() else []
        cli_detectors = {r['detector'] for r in cli_rows}
        result['cli'] = {
            'rows': len(cli_rows),
            'pass': set(_FAULT_DETECTORS) <= cli_detectors,
        }

        result['pass'] = (held and result['fault_remediations']['pass']
                          and result['cli']['pass'])
        return result
    finally:
        check_lib.set_enabled_clouds_for_test(None)
        shutil.rmtree(scratch, ignore_errors=True)


def main() -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument('--smoke', action='store_true',
                        help='Reduced counts for the tier-1 '
                             'subprocess gate (same gates).')
    args = parser.parse_args()
    out = {'metric': 'closedloop_control', 'smoke': args.smoke}
    out.update(bench(args))
    print(json.dumps(out))
    return 0 if out.get('pass') else 1


if __name__ == '__main__':
    sys.exit(main())
