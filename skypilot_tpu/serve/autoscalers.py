"""Autoscalers (twin of sky/serve/autoscalers.py: Autoscaler:116,
RequestRateAutoscaler:441, hysteresis :357).

:class:`BurnRateAutoscaler` closes the SLO loop: instead of scaling on
raw request counts it consumes the SLO monitor's multi-window burn
rates (serve/slo.py) — scale out when the FAST window says the error
budget is being spent faster than it accrues, scale in only on a
sustained budget surplus across EVERY window.
"""
from __future__ import annotations

import collections
import dataclasses
import math
import threading
import time
from typing import Any, Deque, Dict, Optional

from skypilot_tpu.serve import service_spec as spec_lib


@dataclasses.dataclass
class AutoscalerDecision:
    target_num_replicas: int


class Autoscaler:

    def __init__(self, spec: spec_lib.SkyServiceSpec) -> None:
        self.spec = spec
        self.target_num_replicas = spec.min_replicas

    def collect_request_information(self, num_requests: int,
                                    window_seconds: float) -> None:
        pass

    def evaluate(self, num_ready_replicas: int) -> AutoscalerDecision:
        return AutoscalerDecision(self.spec.min_replicas)

    def split_targets(self, target: int,
                      num_ready_spot: int) -> 'tuple[int, int]':
        """(spot_target, ondemand_target) for a mixed fleet.

        Twin of the reference's FallbackRequestRateAutoscaler
        (sky/serve/autoscalers.py:557): `base_ondemand_fallback_replicas`
        are always on-demand; with `dynamic_ondemand_fallback`,
        not-yet-ready spot replicas are covered by temporary on-demand
        ones (the fleet temporarily overprovisions to target + gap) that
        scale back down as spot capacity recovers.
        """
        spec = self.spec
        base = min(target, spec.base_ondemand_fallback_replicas)
        spot_target = target - base
        ondemand = base
        if spec.dynamic_ondemand_fallback:
            ondemand += max(0, spot_target - num_ready_spot)
        return spot_target, ondemand

    def inherit_state(self, old: 'Autoscaler') -> None:
        """Carry scaling state across a rolling update.

        A `serve update` must not collapse a scaled-up service back to
        min_replicas: the new autoscaler adopts the old target (clamped
        to the new spec's bounds) and, when both sides track QPS, the
        request window — so reconcile_versions drains the old fleet
        only after a same-sized new fleet is ready.
        """
        target = max(self.spec.min_replicas, old.target_num_replicas)
        if self.spec.max_replicas is not None:
            target = min(target, self.spec.max_replicas)
        self.target_num_replicas = target


class FixedReplicaAutoscaler(Autoscaler):
    """No autoscaling: hold min_replicas."""


class RequestRateAutoscaler(Autoscaler):
    """QPS-based scaling with upscale/downscale hysteresis delays.

    Target count = ceil(qps / target_qps_per_replica), clamped to
    [min, max]. A scale decision only takes effect after the respective
    delay has continuously elapsed — preventing flapping (twin of the
    reference's upscale/downscale counters).
    """

    QPS_WINDOW_SECONDS = 60.0

    def __init__(self, spec: spec_lib.SkyServiceSpec) -> None:
        super().__init__(spec)
        # Appended from every LB handler thread, trimmed from the
        # controller tick thread — guard with a lock; a deque keeps the
        # trim O(expired) instead of rebuilding the whole window.
        self._request_timestamps: Deque[float] = collections.deque()
        self._window_lock = threading.Lock()
        self._upscale_since: Optional[float] = None
        self._downscale_since: Optional[float] = None

    def collect_request_information(self, num_requests: int,
                                    window_seconds: float = 0.0) -> None:
        now = time.time()
        cutoff = now - self.QPS_WINDOW_SECONDS
        with self._window_lock:
            ts = self._request_timestamps
            ts.extend([now] * num_requests)
            while ts and ts[0] < cutoff:
                ts.popleft()

    def inherit_state(self, old: 'Autoscaler') -> None:
        super().inherit_state(old)
        if isinstance(old, RequestRateAutoscaler):
            with old._window_lock:
                snapshot = list(old._request_timestamps)
            with self._window_lock:
                self._request_timestamps = collections.deque(snapshot)

    def current_qps(self) -> float:
        self.collect_request_information(0)
        with self._window_lock:
            return len(self._request_timestamps) / self.QPS_WINDOW_SECONDS

    def evaluate(self, num_ready_replicas: int) -> AutoscalerDecision:
        spec = self.spec
        qps = self.current_qps()
        desired = math.ceil(qps / spec.target_qps_per_replica) \
            if spec.target_qps_per_replica else spec.min_replicas
        desired = max(spec.min_replicas,
                      min(desired, spec.max_replicas or desired))
        now = time.time()

        if desired > self.target_num_replicas:
            self._downscale_since = None
            if self._upscale_since is None:
                self._upscale_since = now
            if now - self._upscale_since >= spec.upscale_delay_seconds:
                self.target_num_replicas = desired
                self._upscale_since = None
        elif desired < self.target_num_replicas:
            self._upscale_since = None
            if self._downscale_since is None:
                self._downscale_since = now
            if now - self._downscale_since >= spec.downscale_delay_seconds:
                self.target_num_replicas = desired
                self._downscale_since = None
        else:
            self._upscale_since = None
            self._downscale_since = None
        return AutoscalerDecision(self.target_num_replicas)


class BurnRateAutoscaler(Autoscaler):
    """Multi-window SLO-burn-driven scaling.

    The controller feeds each SLO evaluation's burns
    (``collect_burn_info``, shape ``{window: {objective: burn}}``
    from serve/slo.py). Scale OUT one step when the FAST (shortest)
    window's worst burn reaches UPSCALE_BURN — the budget is being
    spent faster than it accrues and waiting for the slow window to
    agree just spends more of it. Scale IN one step only when EVERY
    window's worst burn has stayed at or under DOWNSCALE_SURPLUS for
    the spec's downscale delay — a sustained budget surplus, so a
    momentary lull can't shed the capacity a breach just proved
    necessary.

    Every decision — including a scale-out SUPPRESSED by the cooldown
    — is journalled as a scored fleet decision
    (``fleet_decisions`` kind ``serve.burn_scale``, score = the burn
    that drove it), so an incident review can see what the autoscaler
    saw and why it held.

    ``request_fastpath`` is the remediation engine's hook (burn-rate
    acceleration anomaly): the next evaluation bypasses the upscale
    cooldown once.
    """

    UPSCALE_BURN = 1.0
    DOWNSCALE_SURPLUS = 0.5
    UPSCALE_COOLDOWN_S = 30.0

    def __init__(self, spec: spec_lib.SkyServiceSpec) -> None:
        super().__init__(spec)
        # Set by the controller (specs don't know their service name);
        # journalled decisions carry it as the cluster column.
        self.service_name: Optional[str] = None
        self._burns: Optional[Dict[str, Dict[str, Any]]] = None
        self._last_upscale = 0.0
        self._surplus_since: Optional[float] = None
        self._fastpath = False

    def collect_burn_info(self, burns: Optional[
            Dict[str, Dict[str, Any]]]) -> None:
        if burns:
            self._burns = burns

    def request_fastpath(self) -> None:
        self._fastpath = True

    @staticmethod
    def _worst(per_objective: Dict[str, Any]) -> Optional[float]:
        burns = [float(b) for b in per_objective.values()
                 if b is not None]
        return max(burns) if burns else None

    def _digest(self) -> 'tuple[Optional[float], Optional[float]]':
        """(fast-window worst burn, worst burn across ALL windows).
        None when no burn data exists yet (no declared objective got
        enough traffic)."""
        if not self._burns:
            return None, None
        try:
            by_window = sorted(self._burns.items(),
                               key=lambda kv: float(kv[0]))
        except ValueError:
            return None, None
        worsts = [self._worst(per) for _, per in by_window]
        known = [w for w in worsts if w is not None]
        return worsts[0], (max(known) if known else None)

    def _journal(self, decision: str, score: Optional[float],
                 detail: Dict[str, Any]) -> None:
        from skypilot_tpu.jobs import fleet
        fleet.record_decision(
            kind='serve.burn_scale', cluster=self.service_name,
            score=score,
            detail={'decision': decision,
                    'target': self.target_num_replicas, **detail})

    def evaluate(self, num_ready_replicas: int) -> AutoscalerDecision:
        spec = self.spec
        now = time.time()
        fast_burn, worst_burn = self._digest()
        max_replicas = spec.max_replicas or self.target_num_replicas
        fastpath, self._fastpath = self._fastpath, False
        if fast_burn is not None and fast_burn >= self.UPSCALE_BURN:
            self._surplus_since = None
            if self.target_num_replicas >= max_replicas:
                pass   # pinned at max: nothing to journal every tick
            elif fastpath or \
                    now - self._last_upscale >= self.UPSCALE_COOLDOWN_S:
                self.target_num_replicas += 1
                self._last_upscale = now
                self._journal('scale_out', fast_burn,
                              {'fast_burn': fast_burn,
                               'fastpath': fastpath})
            else:
                # The cooldown held a wanted scale-out: journal it
                # scored, so the suppression is reviewable.
                self._journal(
                    'cooldown_hold', fast_burn,
                    {'fast_burn': fast_burn,
                     'cooldown_remaining_s': round(
                         self.UPSCALE_COOLDOWN_S -
                         (now - self._last_upscale), 3)})
        elif worst_burn is not None and \
                worst_burn <= self.DOWNSCALE_SURPLUS:
            if self.target_num_replicas <= spec.min_replicas:
                self._surplus_since = None
            elif self._surplus_since is None:
                self._surplus_since = now
            elif now - self._surplus_since >= \
                    spec.downscale_delay_seconds:
                self.target_num_replicas -= 1
                self._surplus_since = None
                self._journal('scale_in', worst_burn,
                              {'worst_burn': worst_burn})
        else:
            self._surplus_since = None
        return AutoscalerDecision(self.target_num_replicas)


def make_autoscaler(spec: spec_lib.SkyServiceSpec) -> Autoscaler:
    if spec.autoscaler == 'burn_rate':
        return BurnRateAutoscaler(spec)
    if spec.autoscaling_enabled:
        return RequestRateAutoscaler(spec)
    return FixedReplicaAutoscaler(spec)
