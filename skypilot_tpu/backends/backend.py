"""Backend ABC + ResourceHandle (twin of sky/backends/backend.py)."""
from __future__ import annotations

import typing
from typing import Any, Dict, Generic, List, Optional, TypeVar

if typing.TYPE_CHECKING:
    from skypilot_tpu import task as task_lib


class ResourceHandle:
    """Pickled into the cluster table; identifies a live cluster."""

    def get_cluster_name(self) -> str:
        raise NotImplementedError


_HandleT = TypeVar('_HandleT', bound=ResourceHandle)


class Backend(Generic[_HandleT]):
    """Cluster lifecycle + job execution contract."""

    NAME = 'backend'

    # ---- lifecycle ----

    def provision(self,
                  task: 'task_lib.Task',
                  to_provision: Optional[Any],
                  dryrun: bool = False,
                  stream_logs: bool = True,
                  cluster_name: Optional[str] = None,
                  retry_until_up: bool = False,
                  blocked_resources: Optional[List[Any]] = None
                  ) -> Optional[_HandleT]:
        raise NotImplementedError

    def sync_workdir(self, handle: _HandleT, workdir: str) -> None:
        raise NotImplementedError

    def sync_file_mounts(self, handle: _HandleT,
                         all_file_mounts: Optional[Dict[str, str]],
                         storage_mounts: Optional[Dict[str, Any]]) -> None:
        raise NotImplementedError

    def setup(self, handle: _HandleT, task: 'task_lib.Task',
              detach_setup: bool = False) -> None:
        raise NotImplementedError

    def execute(self, handle: _HandleT, task: 'task_lib.Task',
                detach_run: bool = False,
                dryrun: bool = False) -> Optional[int]:
        """Submit the task as a job; returns job id."""
        raise NotImplementedError

    def teardown(self, handle: _HandleT, terminate: bool,
                 purge: bool = False) -> None:
        raise NotImplementedError

    # ---- job ops ----

    def cancel_jobs(self, handle: _HandleT, job_ids) -> None:
        raise NotImplementedError

    def get_job_status(self, handle: _HandleT, job_id: int):
        raise NotImplementedError

    def tail_logs(self, handle: _HandleT, job_id: Optional[int],
                  follow: bool = True, all_ranks: bool = False) -> str:
        raise NotImplementedError

    def get_workload_telemetry(self, handle: _HandleT,
                               job_id: int) -> dict:
        """Per-rank workload telemetry samples ({rank: sample}), or
        empty for backends without rank-level telemetry."""
        del handle, job_id
        return {}
