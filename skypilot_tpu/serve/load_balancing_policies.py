"""LB policies + per-replica rolling stats (twin of
sky/serve/load_balancing_policies.py).

:class:`ReplicaStatsTracker` lives here (not in the load balancer) on
purpose: rolling TTFT/error/inflight per replica is routing signal —
:class:`TelemetryRoutedPolicy` reads it from ``self.stats`` to weight
replicas, the way LeastLoad reads its in-flight counts.
"""
from __future__ import annotations

import collections
import random
import threading
import time
from typing import Any, Dict, List, Optional

# Rolling-window samples kept per replica (latency percentiles and
# error rate are computed over these, newest-N not wall-clock — a
# traffic lull must not empty the window).
_STATS_WINDOW = 512


class ReplicaStats:
    """One replica's rolling view: in-flight count plus a bounded
    deque of (ts, ok, ttft_s, e2e_s) outcomes."""

    def __init__(self, window: int = _STATS_WINDOW) -> None:
        self.inflight = 0
        self.requests = 0
        self.errors = 0
        self.samples: collections.deque = collections.deque(
            maxlen=window)

    def snapshot(self) -> Dict[str, Any]:
        from skypilot_tpu.serve import slo as slo_lib
        ttfts = sorted(s[2] for s in self.samples if s[2] is not None)
        e2es = sorted(s[3] for s in self.samples if s[3] is not None)
        recent = list(self.samples)
        errors_recent = len([s for s in recent if not s[1]])
        return {
            'inflight': self.inflight,
            'requests_total': self.requests,
            'errors_total': self.errors,
            'error_rate': (errors_recent / len(recent)
                           if recent else None),
            'ttft_p50_ms': slo_lib.pctl_ms(ttfts, 0.50),
            'ttft_p99_ms': slo_lib.pctl_ms(ttfts, 0.99),
            'e2e_p50_ms': slo_lib.pctl_ms(e2es, 0.50),
            'e2e_p99_ms': slo_lib.pctl_ms(e2es, 0.99),
        }


class ReplicaStatsTracker:
    """Thread-safe per-replica rolling stats, fed by the load
    balancer's request records and pruned with the ready set."""

    def __init__(self, window: int = _STATS_WINDOW) -> None:
        self._lock = threading.Lock()
        self._window = window
        self._stats: Dict[str, ReplicaStats] = {}

    def _get(self, replica: str) -> ReplicaStats:
        stats = self._stats.get(replica)
        if stats is None:
            stats = self._stats[replica] = ReplicaStats(self._window)
        return stats

    def request_started(self, replica: str) -> None:
        with self._lock:
            self._get(replica).inflight += 1

    def request_finished(self, replica: str) -> None:
        with self._lock:
            stats = self._stats.get(replica)
            if stats is not None and stats.inflight > 0:
                stats.inflight -= 1

    def observe(self, replica: str, ok: bool,
                ttft_s: Optional[float] = None,
                e2e_s: Optional[float] = None) -> None:
        with self._lock:
            stats = self._get(replica)
            stats.requests += 1
            if not ok:
                stats.errors += 1
            stats.samples.append((time.time(), ok, ttft_s, e2e_s))

    def prune(self, live_replicas: List[str]) -> None:
        """Drop replicas no longer in the ready set (a drained
        replica's stats must not linger as routing signal)."""
        live = set(live_replicas)
        with self._lock:
            for gone in set(self._stats) - live:
                del self._stats[gone]

    def snapshot(self) -> Dict[str, Dict[str, Any]]:
        with self._lock:
            return {replica: stats.snapshot()
                    for replica, stats in sorted(self._stats.items())}

    def inflight_by_replica(self) -> Dict[str, int]:
        with self._lock:
            return {replica: stats.inflight
                    for replica, stats in self._stats.items()}


class LoadBalancingPolicy:

    # Rolling per-replica stats, attached by the load balancer; a
    # telemetry-routing policy reads this in select_replica.
    stats: Optional[ReplicaStatsTracker] = None

    def set_ready_replicas(self, replicas: List[str]) -> None:
        raise NotImplementedError

    def select_replica(self) -> Optional[str]:
        raise NotImplementedError

    def request_done(self, replica: str) -> None:
        pass


class RoundRobinPolicy(LoadBalancingPolicy):

    def __init__(self) -> None:
        self._replicas: List[str] = []
        self._index = 0
        self._lock = threading.Lock()

    def set_ready_replicas(self, replicas: List[str]) -> None:
        with self._lock:
            if replicas != self._replicas:
                self._replicas = list(replicas)
                self._index = 0

    def select_replica(self) -> Optional[str]:
        with self._lock:
            if not self._replicas:
                return None
            replica = self._replicas[self._index % len(self._replicas)]
            self._index += 1
            return replica


class LeastLoadPolicy(LoadBalancingPolicy):
    """Pick the replica with fewest in-flight requests."""

    def __init__(self) -> None:
        self._replicas: List[str] = []
        self._load: Dict[str, int] = collections.defaultdict(int)
        self._lock = threading.Lock()

    def set_ready_replicas(self, replicas: List[str]) -> None:
        with self._lock:
            self._replicas = list(replicas)
            for gone in set(self._load) - set(replicas):
                del self._load[gone]

    def select_replica(self) -> Optional[str]:
        with self._lock:
            if not self._replicas:
                return None
            replica = min(self._replicas, key=lambda r: self._load[r])
            self._load[replica] += 1
            return replica

    def request_done(self, replica: str) -> None:
        with self._lock:
            if self._load.get(replica, 0) > 0:
                self._load[replica] -= 1


class TelemetryRoutedPolicy(LoadBalancingPolicy):
    """Weighted-random routing on live per-replica telemetry.

    Every replica carries a routing weight in [FLOOR, 1.0]. A periodic
    reweight (at most every REWEIGHT_INTERVAL_S) folds the stats
    tracker's rolling signals into a target weight — p99 TTFT relative
    to the fleet median, in-flight depth relative to the least-loaded
    replica, and recent error rate — and the applied weight moves
    toward the target by exponential smoothing (ALPHA). That smoothing
    IS the hysteresis: one slow sample cannot swing routing, and a
    recovered replica earns its share back over a few reweights
    instead of instantly.

    The FLOOR is the never-starve guarantee: a down-weighted replica
    keeps receiving a trickle of traffic, so its rolling window keeps
    refreshing and can prove recovery — a zero weight would freeze its
    stats at their worst and deprioritize it forever.

    ``deprioritize`` is the remediation engine's routing hook: it caps
    the replica's weight at the FLOOR until the given expiry (or until
    ``undeprioritize``), independent of what the telemetry says.
    """

    REWEIGHT_INTERVAL_S = 1.0
    ALPHA = 0.3
    FLOOR = 0.05

    def __init__(self) -> None:
        self._replicas: List[str] = []
        self._weights: Dict[str, float] = {}
        self._load: Dict[str, int] = collections.defaultdict(int)
        self._deprioritized: Dict[str, float] = {}   # replica → until
        self._last_reweight = 0.0
        self._lock = threading.Lock()

    def set_ready_replicas(self, replicas: List[str]) -> None:
        with self._lock:
            self._replicas = list(replicas)
            live = set(replicas)
            for gone in set(self._weights) - live:
                del self._weights[gone]
            for gone in set(self._load) - live:
                del self._load[gone]
            for gone in set(self._deprioritized) - live:
                del self._deprioritized[gone]
            for replica in replicas:
                # A new replica starts at full share: no telemetry
                # means no evidence against it.
                self._weights.setdefault(replica, 1.0)

    def deprioritize(self, replica: str,
                     duration_s: float = 120.0) -> None:
        with self._lock:
            self._deprioritized[replica] = time.time() + duration_s

    def undeprioritize(self, replica: str) -> None:
        with self._lock:
            self._deprioritized.pop(replica, None)

    def weights(self) -> Dict[str, float]:
        """Effective weights (tests + LB /metrics introspection)."""
        with self._lock:
            now = time.time()
            return {r: self._effective_weight(r, now)
                    for r in self._replicas}

    def _effective_weight(self, replica: str, now: float) -> float:
        weight = max(self.FLOOR,
                     min(1.0, self._weights.get(replica, 1.0)))
        until = self._deprioritized.get(replica)
        if until is not None and now < until:
            return self.FLOOR
        return weight

    def _target_weight(self, replica: str,
                       snap: Dict[str, Dict[str, Any]],
                       median_p99: Optional[float],
                       min_load: int) -> float:
        stats = snap.get(replica)
        weight = 1.0
        if stats is not None:
            p99 = stats.get('ttft_p99_ms')
            if p99 and median_p99:
                # Slower than the fleet median → proportionally less
                # traffic (a 2x-median replica gets half a share).
                weight *= min(1.0, median_p99 / p99)
            error_rate = stats.get('error_rate')
            if error_rate:
                weight *= max(0.0, 1.0 - 2.0 * error_rate)
        # In-flight depth relative to the least-loaded replica: the
        # policy's own counters, so the signal survives with LB
        # record-keeping disabled.
        weight *= (1.0 + min_load) / (1.0 + self._load[replica])
        return max(self.FLOOR, min(1.0, weight))

    def _maybe_reweight(self, now: float) -> None:
        if now - self._last_reweight < self.REWEIGHT_INTERVAL_S:
            return
        self._last_reweight = now
        snap = self.stats.snapshot() if self.stats is not None else {}
        p99s = sorted(
            s['ttft_p99_ms'] for s in snap.values()
            if s.get('ttft_p99_ms') is not None)
        median_p99 = p99s[len(p99s) // 2] if p99s else None
        min_load = min(
            (self._load[r] for r in self._replicas), default=0)
        for replica in self._replicas:
            target = self._target_weight(replica, snap, median_p99,
                                         min_load)
            old = self._weights.get(replica, 1.0)
            self._weights[replica] = \
                old + self.ALPHA * (target - old)

    def select_replica(self) -> Optional[str]:
        with self._lock:
            if not self._replicas:
                return None
            now = time.time()
            self._maybe_reweight(now)
            weights = [self._effective_weight(r, now)
                       for r in self._replicas]
            point = random.random() * sum(weights)
            choice = self._replicas[-1]
            for replica, weight in zip(self._replicas, weights):
                point -= weight
                if point <= 0:
                    choice = replica
                    break
            self._load[choice] += 1
            return choice

    def request_done(self, replica: str) -> None:
        with self._lock:
            if self._load.get(replica, 0) > 0:
                self._load[replica] -= 1


POLICIES = {
    'round_robin': RoundRobinPolicy,
    'least_load': LeastLoadPolicy,
    'telemetry_routed': TelemetryRoutedPolicy,
}


def make_policy(name: str = 'round_robin') -> LoadBalancingPolicy:
    return POLICIES[name]()
