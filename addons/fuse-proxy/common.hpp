// fuse-proxy wire protocol + unix-socket helpers.
//
// C++ twin of the reference's Go fuse-proxy (addons/fuse-proxy/pkg/
// common/common.go): a shim/wrapper client talks to a privileged server
// over an AF_UNIX socket; FUSE device file descriptors travel back via
// SCM_RIGHTS.
//
// Framing (all integers little-endian u32):
//   request :=  MAGIC  mode(u32: 's' | 'm')  want_fd(u32)  argc(u32)
//               { len(u32) bytes }*argc
//   response := code(i32 as u32)  msg_len(u32)  msg bytes
//               fd_marker(u32: 'F' | 'N')   -- 'F' carries one SCM_RIGHTS fd
#pragma once

#include <cstdint>
#include <cstdlib>
#include <string>
#include <vector>

#include <unistd.h>

namespace fuseproxy {

constexpr uint32_t kMagic = 0x46505258;  // "FPRX"
constexpr uint32_t kModeShim = 's';      // forward fusermount argv
constexpr uint32_t kModeMount = 'm';     // mount + return fuse fd (wrapper)

inline const char* DefaultSocketPath() {
  const char* p = ::getenv("FUSE_PROXY_SOCKET");
  return p && *p ? p : "/var/run/fusermount/server.sock";
}

// All return false on error (errno left set / message in *err).

bool WriteAll(int fd, const void* buf, size_t n);
bool ReadAll(int fd, void* buf, size_t n);
bool WriteU32(int fd, uint32_t v);
bool ReadU32(int fd, uint32_t* v);
bool WriteString(int fd, const std::string& s);
bool ReadString(int fd, std::string* s, uint32_t max_len = 1u << 20);

// SCM_RIGHTS: send/receive one fd alongside a single marker byte.
bool SendFd(int sock, int fd);
int RecvFd(int sock);  // returns fd or -1

struct Request {
  uint32_t mode = kModeShim;
  bool want_fd = false;
  std::vector<std::string> args;
};

struct Response {
  int32_t code = -1;
  std::string message;
  int fd = -1;  // valid when >= 0
};

bool SendRequest(int sock, const Request& req);
bool RecvRequest(int sock, Request* req);
bool SendResponse(int sock, const Response& resp);
bool RecvResponse(int sock, Response* resp);

// Connect to the server socket; -1 on failure.
int ConnectTo(const std::string& path);

}  // namespace fuseproxy
