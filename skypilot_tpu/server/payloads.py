"""Verb → engine-function resolution + body validation (twin of
sky/server/requests/payloads.py, sans pydantic).

Each verb maps to a resolver that turns the JSON body into (func, kwargs)
for the executor. Task payloads travel as task-YAML config dicts.
"""
from __future__ import annotations

from typing import Any, Callable, Dict, Tuple


class BadRequest(Exception):
    pass


def _task_from_body(body: Dict[str, Any]):
    from skypilot_tpu import task as task_lib
    config = body.get('task')
    if not isinstance(config, dict):
        raise BadRequest("body must include a 'task' config object")
    try:
        return task_lib.Task.from_yaml_config(config)
    except (ValueError, KeyError) as e:
        raise BadRequest(f'invalid task: {e}') from e


def _require(body: Dict[str, Any], key: str) -> Any:
    if key not in body or body[key] is None:
        raise BadRequest(f"missing required field '{key}'")
    return body[key]


def _in_workspace(workspace, fn, *args, **kwargs):
    """Run `fn` with the request's workspace active (validated first),
    shared by every submission resolver (launch/jobs.launch/serve.up)."""
    from skypilot_tpu.workspaces import context as ws_context
    if workspace is not None:
        from skypilot_tpu.workspaces import core as workspaces_core
        workspaces_core.validate_exists(workspace)
    with ws_context.active(workspace):
        return fn(*args, **kwargs)


def _launch(body: Dict[str, Any]) -> Tuple[Callable, Dict[str, Any]]:
    from skypilot_tpu import execution
    task = _task_from_body(body)
    workspace = body.get('workspace')

    def run_launch(**kwargs):
        job_id, handle = _in_workspace(workspace, execution.launch,
                                       task, **kwargs)
        return {'job_id': job_id,
                'cluster_name': handle.get_cluster_name()
                if handle else None}

    kwargs = {
        'cluster_name': body.get('cluster_name'),
        'retry_until_up': bool(body.get('retry_until_up', False)),
        'idle_minutes_to_autostop': body.get('idle_minutes_to_autostop'),
        'down': bool(body.get('down', False)),
        'dryrun': bool(body.get('dryrun', False)),
        'detach_run': bool(body.get('detach_run', False)),
        # Streamed job output lands in the request's captured log
        # (`xsky api logs REQUEST_ID`); clients may turn it off for
        # chatty jobs.
        'stream_logs': bool(body.get('stream_logs', True)),
        'no_setup': bool(body.get('no_setup', False)),
    }
    return run_launch, kwargs


def _exec(body: Dict[str, Any]) -> Tuple[Callable, Dict[str, Any]]:
    from skypilot_tpu import execution
    task = _task_from_body(body)
    cluster_name = _require(body, 'cluster_name')

    def run_exec(**kwargs):
        job_id, handle = execution.exec(task, cluster_name, **kwargs)
        return {'job_id': job_id,
                'cluster_name': handle.get_cluster_name()}

    return run_exec, {'detach_run': bool(body.get('detach_run', False)),
                      'dryrun': bool(body.get('dryrun', False))}


def _core_verb(fn_name: str, *fields, **defaults):
    def resolver(body: Dict[str, Any]) -> Tuple[Callable, Dict[str, Any]]:
        from skypilot_tpu import core
        kwargs = {}
        for field in fields:
            kwargs[field] = _require(body, field)
        for key, default in defaults.items():
            kwargs[key] = body.get(key, default)
        return getattr(core, fn_name), kwargs
    return resolver


_VERBS: Dict[str, Callable[[Dict[str, Any]],
                           Tuple[Callable, Dict[str, Any]]]] = {
    'launch': _launch,
    'exec': _exec,
    'status': _core_verb('status', cluster_names=None, refresh=False,
                         workspace=None, limit=None, offset=0),
    'start': _core_verb('start', 'cluster_name',
                        idle_minutes_to_autostop=None, down=False),
    'stop': _core_verb('stop', 'cluster_name'),
    'down': _core_verb('down', 'cluster_name', purge=False),
    'autostop': _core_verb('autostop', 'cluster_name', 'idle_minutes',
                           down_on_idle=False),
    'queue': _core_verb('queue', 'cluster_name'),
    'cluster_hosts': _core_verb('cluster_hosts', 'cluster_name'),
    'profile.capture': _core_verb('profile_capture', 'cluster_name',
                                  job_id=None, duration_s=1.0),
    'goodput.report': _core_verb('goodput_report', cluster_name=None,
                                 fleet=False, limit=1000),
    'metrics.list': _core_verb('metrics_list', prefix=None, since=None,
                               limit=200, offset=0),
    'metrics.query': _core_verb('metrics_query', 'name', labels=None,
                                since=None, until=None, step=None,
                                agg='avg', res=None),
    'endpoints': _core_verb('endpoints', 'cluster_name', port=None),
    'cancel': _core_verb('cancel', 'cluster_name', job_ids=None,
                         all_jobs=False),
    'logs': _core_verb('tail_logs', 'cluster_name', job_id=None,
                       all_ranks=False),
    'check': _core_verb('check', quiet=True),
    'cost_report': _core_verb('cost_report'),
    'accelerators': _core_verb('list_accelerators', name_filter=None,
                               gpus_only=False),
    'storage.ls': _core_verb('storage_ls'),
    'storage.delete': _core_verb('storage_delete', 'storage_name'),
    'storage.ls_objects': _core_verb('storage_ls_objects',
                                     'storage_name', prefix='',
                                     limit=100),
}


def _jobs_launch(body: Dict[str, Any]) -> Tuple[Callable, Dict[str, Any]]:
    from skypilot_tpu import task as task_lib
    from skypilot_tpu.jobs import core as jobs_core
    config = body.get('task')
    if isinstance(config, list):     # pipeline: chain of task configs
        if not config:
            raise BadRequest("'task' pipeline list must be non-empty")
        try:
            task = [task_lib.Task.from_yaml_config(c) for c in config]
        except (ValueError, KeyError) as e:
            raise BadRequest(f'invalid pipeline task: {e}') from e
    else:
        task = _task_from_body(body)
    workspace = body.get('workspace')

    def run(**kwargs):
        return {'job_id': _in_workspace(workspace, jobs_core.launch,
                                        task, **kwargs)}

    try:
        priority = int(body.get('priority') or 0)
    except (TypeError, ValueError) as e:
        raise BadRequest(f'invalid priority: {e}') from e
    return run, {'name': body.get('name'), 'priority': priority}


def _jobs_verb(fn_name: str, *fields, **defaults):
    def resolver(body: Dict[str, Any]) -> Tuple[Callable, Dict[str, Any]]:
        from skypilot_tpu.jobs import core as jobs_core
        kwargs = {f: _require(body, f) for f in fields}
        for key, default in defaults.items():
            kwargs[key] = body.get(key, default)
        return getattr(jobs_core, fn_name), kwargs
    return resolver


def _serve_up(body: Dict[str, Any]) -> Tuple[Callable, Dict[str, Any]]:
    from skypilot_tpu.serve import core as serve_core
    task = _task_from_body(body)
    workspace = body.get('workspace')

    def run(**kwargs):
        return {'service_name': _in_workspace(workspace, serve_core.up,
                                              task, **kwargs)}

    return run, {'service_name': body.get('service_name')}


def _serve_update(body: Dict[str, Any]) -> Tuple[Callable, Dict[str, Any]]:
    from skypilot_tpu.serve import core as serve_core
    task = _task_from_body(body)

    def run(**kwargs):
        return {'version': serve_core.update(task, **kwargs)}

    return run, {'service_name': _require(body, 'service_name'),
                 'mode': body.get('mode', 'rolling')}


def _serve_verb(fn_name: str, *fields, **defaults):
    def resolver(body: Dict[str, Any]) -> Tuple[Callable, Dict[str, Any]]:
        from skypilot_tpu.serve import core as serve_core
        kwargs = {f: _require(body, f) for f in fields}
        for key, default in defaults.items():
            kwargs[key] = body.get(key, default)
        return getattr(serve_core, fn_name), kwargs
    return resolver


def _module_verb(module_path: str, fn_name: str, *fields, **defaults):
    def resolver(body: Dict[str, Any]) -> Tuple[Callable, Dict[str, Any]]:
        import importlib
        mod = importlib.import_module(module_path)
        kwargs = {f: _require(body, f) for f in fields}
        for key, default in defaults.items():
            kwargs[key] = body.get(key, default)
        return getattr(mod, fn_name), kwargs
    return resolver


_USERS = 'skypilot_tpu.users.core'
_WORKSPACES = 'skypilot_tpu.workspaces.core'

_VERBS.update({
    'jobs.launch': _jobs_launch,
    'jobs.queue': _jobs_verb('queue', limit=None, offset=0),
    'jobs.cancel': _jobs_verb('cancel', 'job_id'),
    'jobs.logs': _jobs_verb('tail_logs', 'job_id'),
    'jobs.watch_logs': lambda body: (
        __import__('skypilot_tpu.jobs.core',
                   fromlist=['watch_logs']).watch_logs,
        {'job_id': _require(body, 'job_id'),
         'offset': body.get('offset', 0)}),
    'serve.up': _serve_up,
    'serve.update': _serve_update,
    'serve.status': lambda body: (
        __import__('skypilot_tpu.serve.core', fromlist=['status']).status,
        {'service_names': body.get('service_names'),
         'limit': body.get('limit'),
         'offset': body.get('offset', 0)}),
    'serve.down': _serve_verb('down', 'service_name'),
    'serve.logs': _serve_verb('tail_logs', 'service_name', 'replica_id',
                              job_id=None),
    'serve.controller_logs': _serve_verb('controller_logs',
                                         'service_name'),
    'serve.history': _serve_verb('metrics_history', 'service_name',
                                 limit=720),
    'serve.watch_logs': _serve_verb('watch_replica_logs',
                                    'service_name', 'replica_id',
                                    offset=0),
    # User management (admin-only via users.rbac).
    'users.list': _module_verb(_USERS, 'list_users'),
    'users.create': _module_verb(_USERS, 'create_user', 'name', 'password',
                                 role='user'),
    'users.delete': _module_verb(_USERS, 'delete_user', 'name'),
    'users.set_role': _module_verb(_USERS, 'set_role', 'name', 'role'),
    'users.token_create': _module_verb(_USERS, 'create_token', 'name',
                                       label='default'),
    'users.token_list': _module_verb(_USERS, 'list_tokens', name=None),
    'users.token_revoke': _module_verb(_USERS, 'revoke_token', 'name',
                                       'label'),
    # Workspaces (membership + config overlays are admin-only,
    # users/rbac.py).
    'workspaces.list': _module_verb(_WORKSPACES, 'get_workspaces'),
    'workspaces.create': _module_verb(_WORKSPACES, 'create_workspace',
                                      'name'),
    'workspaces.delete': _module_verb(_WORKSPACES, 'delete_workspace',
                                      'name'),
    'workspaces.add_member': _module_verb(_WORKSPACES, 'add_member',
                                          'workspace', 'user_name'),
    'workspaces.remove_member': _module_verb(
        _WORKSPACES, 'remove_member', 'workspace', 'user_name'),
    'workspaces.members': _module_verb(_WORKSPACES, 'list_members',
                                       'workspace'),
    'workspaces.set_config': _module_verb(_WORKSPACES, 'set_config',
                                          'workspace', 'config'),
    'workspaces.get_config': _module_verb(_WORKSPACES, 'get_config',
                                          'workspace'),
    # SSH node pools (twin of `sky ssh up/down`).
    'ssh.up': _module_verb('skypilot_tpu.clouds.ssh', 'pool_up',
                           infra=None),
    'ssh.down': _module_verb('skypilot_tpu.clouds.ssh', 'pool_down',
                             infra=None),
})


def known_verb(verb: str) -> bool:
    return verb in _VERBS


def resolve(verb: str, body: Dict[str, Any]
            ) -> Tuple[Callable, Dict[str, Any]]:
    # `autostop` maps the wire field 'down' onto core's down_on_idle.
    if verb == 'autostop' and 'down' in body:
        body = dict(body)
        body['down_on_idle'] = body.pop('down')
    return _VERBS[verb](body)


def jsonify(obj: Any) -> Any:
    """Make engine results JSON-safe (enums → value, handles → summary)."""
    import enum
    if isinstance(obj, dict):
        return {k: jsonify(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [jsonify(v) for v in obj]
    if isinstance(obj, enum.Enum):
        return obj.value
    if hasattr(obj, 'get_cluster_name'):   # ResourceHandle
        return {'cluster_name': obj.get_cluster_name(),
                'resources': str(getattr(obj, 'launched_resources', '')),
                'num_hosts': getattr(
                    getattr(obj, 'cluster_info', None), 'num_instances',
                    None)}
    if obj is None or isinstance(obj, (str, int, float, bool)):
        return obj
    return str(obj)
