"""Logging agent ABC (twin of sky/logs/agent.py)."""
from __future__ import annotations

from typing import Any, Dict


class LoggingAgent:
    """Renders per-host setup for shipping ~/.xsky/logs to a store."""

    def __init__(self, config: Dict[str, Any]) -> None:
        self.config = config

    def get_setup_command(self, cluster_name: str) -> str:
        """Shell run on every host to install + start the shipper."""
        raise NotImplementedError

    def get_credential_file_mounts(self) -> Dict[str, str]:
        return {}
