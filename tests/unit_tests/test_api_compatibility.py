"""Wire-format stability of the client/server API (twin of the
reference's tests/test_api_compatibility.py).

These tests pin the JSON shapes a vN client depends on; changing them
breaks deployed CLIs/SDKs talking to a newer server. Extending payloads
is fine — removing/renaming pinned fields is a compatibility break that
must bump API_VERSION.
"""
import json
import urllib.request

import pytest

from skypilot_tpu.client import remote_client
from skypilot_tpu.server import app as server_app
from skypilot_tpu.server import requests_db


@pytest.fixture
def api(fake_cluster_env, monkeypatch, tmp_path):
    monkeypatch.setenv('XSKY_SERVER_DB', str(tmp_path / 'req.db'))
    requests_db.reset_for_test()
    server, port = server_app.run_in_thread()
    yield f'http://127.0.0.1:{port}'
    server.shutdown()
    requests_db.reset_for_test()


def _get(url):
    with urllib.request.urlopen(url) as resp:
        return resp.status, json.loads(resp.read())


def _post(url, body):
    req = urllib.request.Request(
        url, data=json.dumps(body).encode(),
        headers={'Content-Type': 'application/json'}, method='POST')
    with urllib.request.urlopen(req) as resp:
        return resp.status, json.loads(resp.read())


class TestWireFormat:

    def test_health_shape(self, api):
        status, payload = _get(f'{api}/health')
        assert status == 200
        assert payload['status'] == 'healthy'
        assert isinstance(payload['api_version'], int)
        assert payload['api_version'] >= 1

    def test_submit_returns_request_id(self, api):
        status, payload = _post(f'{api}/api/status', {})
        assert status == 200
        assert set(payload) >= {'request_id'}
        assert isinstance(payload['request_id'], str)

    def test_get_request_lifecycle_shape(self, api):
        _, submitted = _post(f'{api}/api/status', {})
        rid = submitted['request_id']
        import time
        deadline = time.time() + 30
        while time.time() < deadline:
            status, payload = _get(f'{api}/api/get?request_id={rid}')
            assert status == 200
            # Pinned envelope for every state.
            assert set(payload) >= {'request_id', 'name', 'status'}
            assert payload['name'] == 'status'
            if payload['status'] == 'SUCCEEDED':
                assert 'result' in payload
                break
            if payload['status'] == 'FAILED':
                raise AssertionError(payload.get('error'))
            time.sleep(0.1)
        else:
            raise AssertionError('request never finished')

    def test_unknown_request_404_shape(self, api):
        try:
            urllib.request.urlopen(f'{api}/api/get?request_id=nope')
            raise AssertionError('expected 404')
        except urllib.error.HTTPError as e:
            assert e.code == 404
            assert 'error' in json.loads(e.read())

    def test_launch_result_shape(self, api):
        """launch → request → result carries job_id + cluster_name."""
        client = remote_client.RemoteClient(api, poll_interval_s=0.05,
                                            timeout_s=120)
        from skypilot_tpu import Resources, Task
        task = Task('compat', run='echo shape')
        task.set_resources(Resources(accelerators='tpu-v5e-8'))
        job_id, handle = client.launch(task, cluster_name='compat-c')
        assert job_id is not None
        assert handle.cluster_name == 'compat-c'
        # status rows: pinned cluster fields.
        rows = client.status()
        row = [r for r in rows if r['name'] == 'compat-c'][0]
        assert set(row) >= {'name', 'status', 'launched_at'}
        assert row['status'] == 'UP'
        client.down('compat-c')

    def test_jobs_queue_row_shape(self, api, monkeypatch, tmp_path):
        monkeypatch.setenv('XSKY_JOBS_DB', str(tmp_path / 'jobs.db'))
        client = remote_client.RemoteClient(api, poll_interval_s=0.05,
                                            timeout_s=120)
        from skypilot_tpu import Resources, Task
        task = Task('mj', run='echo q')
        task.set_resources(Resources(accelerators='tpu-v5e-8'))
        client.jobs_launch(task)
        rows = client.jobs_queue()
        assert rows
        assert set(rows[0]) >= {'job_id', 'name', 'status',
                                'recovery_count', 'submitted_at'}

    def test_error_serialization_across_wire(self, api):
        """Server-side exceptions surface as typed, readable errors."""
        client = remote_client.RemoteClient(api, poll_interval_s=0.05,
                                            timeout_s=60)
        from skypilot_tpu import exceptions
        with pytest.raises(Exception) as exc:
            client.down('never-existed')
        assert 'never-existed' in str(exc.value)
        # The wire carries the exception class name for typed re-raise.
        assert isinstance(exc.value, exceptions.ClusterDoesNotExist) or \
            'ClusterDoesNotExist' in str(type(exc.value).__name__) or \
            'ClusterDoesNotExist' in str(exc.value)


def test_verb_surface_is_append_only():
    """The wire verb set may only grow: removing or renaming a verb
    breaks older clients. Started as the round-2 list, extended every
    round since — add new verbs here; never delete from this set."""
    from skypilot_tpu.server import payloads
    pinned = {
        'launch', 'exec', 'status', 'start', 'stop', 'down', 'autostop',
        'queue', 'cancel', 'logs', 'check', 'cost_report',
        'storage.ls', 'storage.delete',
        'jobs.launch', 'jobs.queue', 'jobs.cancel', 'jobs.logs',
        'serve.up', 'serve.update', 'serve.status', 'serve.down',
        'serve.logs',
        'users.list', 'users.create', 'users.delete', 'users.set_role',
        # round 5 additions (append-only from here on too):
        'cluster_hosts', 'endpoints', 'accelerators',
        'jobs.watch_logs', 'serve.history', 'serve.watch_logs',
        'serve.controller_logs',
        'workspaces.list', 'workspaces.create', 'workspaces.delete',
        'workspaces.members', 'workspaces.add_member',
        'workspaces.remove_member', 'workspaces.get_config',
        'workspaces.set_config',
        'users.token_create', 'users.token_list', 'users.token_revoke',
        'ssh.up', 'ssh.down', 'storage.ls_objects',
    }
    known = {v for v in pinned if payloads.known_verb(v)}
    missing = pinned - known
    assert not missing, f'wire verbs removed/renamed: {sorted(missing)}'
