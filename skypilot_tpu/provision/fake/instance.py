"""Disk-backed provisioner for the fake cloud — the failover test harness.

Plays moto's role from the reference's tests (tests/test_failover.py:34-60):
capacity/quota errors are scripted per zone via :class:`FailureInjector`;
preemption is simulated by calling :func:`preempt_cluster` out-of-band (the
reference smoke tests terminate instances manually,
smoke_tests_utils.py:33-36).

The cluster store persists to JSON under ``$XSKY_FAKE_CLOUD_DIR`` (default
``~/.xsky/fake_cloud``) guarded by a file lock, so separate CLI processes
see one consistent "cloud" — like a real provider API would behave.

TPU semantics modeled faithfully:
  * a TPU node_config (tpu_vm=True) creates `tpu_num_hosts × num_slices`
    host InstanceInfos sharing slice ids;
  * multi-host slices refuse stop_instances (NotSupportedError), like
    the real TPU API.
"""
from __future__ import annotations

import contextlib
import dataclasses
import json
import os
import shutil
import tempfile
import threading
import uuid
from typing import Any, Dict, Iterator, List, Optional

import filelock

from skypilot_tpu import exceptions
from skypilot_tpu.provision import common
from skypilot_tpu.utils import chaos

_local = threading.RLock()


def _store_dir() -> str:
    return os.path.expanduser(
        os.environ.get('XSKY_FAKE_CLOUD_DIR', '~/.xsky/fake_cloud'))


def _store_path() -> str:
    return os.path.join(_store_dir(), 'clusters.json')


@contextlib.contextmanager
def _store() -> Iterator[Dict[str, Any]]:
    """Load → yield (mutable) → save, under process + thread locks."""
    os.makedirs(_store_dir(), exist_ok=True)
    lock = filelock.FileLock(os.path.join(_store_dir(), '.lock'))
    with _local, lock:
        try:
            with open(_store_path(), encoding='utf-8') as f:
                data = json.load(f)
        except (FileNotFoundError, json.JSONDecodeError):
            data = {'clusters': {}, 'ip_counter': 10}
        yield data
        tmp = _store_path() + '.tmp'
        with open(tmp, 'w', encoding='utf-8') as f:
            json.dump(data, f)
        os.replace(tmp, _store_path())


def _load() -> Dict[str, Any]:
    """Read-only snapshot (no lock, no rewrite): os.replace makes the
    store file atomically consistent for readers."""
    try:
        with open(_store_path(), encoding='utf-8') as f:
            return json.load(f)
    except (FileNotFoundError, json.JSONDecodeError):
        return {'clusters': {}, 'ip_counter': 10}


def _infos_from(cluster: Dict[str, Any]) -> Dict[str, common.InstanceInfo]:
    return {k: common.InstanceInfo(**v)
            for k, v in cluster['instances'].items()}


class FailureInjector:
    """Scripted provisioning failures, keyed by zone (or '*') or by a
    node_config predicate (capacity-model failover tests).

    In-process only (tests script failures and provision in-process); the
    persisted store is for cross-process cluster visibility.
    """

    def __init__(self) -> None:
        self._errors: Dict[str, List[Exception]] = {}
        self._matchers: List[tuple] = []   # (predicate, [errors])
        self.attempts: List[str] = []      # zones tried, in order
        self.attempt_configs: List[Dict[str, Any]] = []

    def fail_zone(self, zone: str, error: Exception,
                  times: int = 10**9) -> None:
        self._errors.setdefault(zone, []).extend([error] * min(times, 1000))

    def fail_match(self, predicate, error: Exception,
                   times: int = 1) -> None:
        """Fail attempts whose node_config satisfies `predicate` — e.g.
        stock out only the 'reserved' provisioning model."""
        self._matchers.append((predicate, [error] * times))

    def check(self, zone: str,
              node_config: Optional[Dict[str, Any]] = None) -> None:
        self.attempts.append(zone)
        self.attempt_configs.append(dict(node_config or {}))
        for predicate, queue in self._matchers:
            if queue and node_config is not None and \
                    predicate(node_config):
                raise queue.pop(0)
        for key in (zone, '*'):
            queue = self._errors.get(key)
            if queue:
                raise queue.pop(0)

    def reset(self) -> None:
        self._errors.clear()
        self._matchers.clear()
        self.attempts.clear()
        self.attempt_configs.clear()


injector = FailureInjector()


def _kill_host_processes(host_root: str) -> None:
    """Kill every process the fake host's agent spawned.

    Real clouds reclaim processes when the VM dies; fake hosts are
    directories on THIS machine, so without this, each e2e test leaks
    its job process trees (agent daemons, job_runners, user servers) —
    enough leaked jax-importing children can even wedge a single-client
    accelerator tunnel for the whole machine.
    """
    import signal
    import sqlite3
    for root in (host_root, os.path.join(host_root, '.xsky')):
        db = os.path.join(root, 'jobs.db')
        if not os.path.exists(db):
            continue
        try:
            # xskylint: disable=db-discipline -- read-only peek into an
            # AGENT host's jobs.db (to kill leaked workload pids), not
            # a control-plane state DB; the WAL pool has no business
            # here.
            conn = sqlite3.connect(db, timeout=5)
            rows = conn.execute(
                'SELECT pid FROM jobs WHERE pid IS NOT NULL').fetchall()
            conn.close()
        except sqlite3.Error:
            continue
        for (pid,) in rows:
            try:
                os.killpg(os.getpgid(int(pid)), signal.SIGTERM)
            except (ProcessLookupError, PermissionError, OSError):
                try:
                    os.kill(int(pid), signal.SIGTERM)
                except (ProcessLookupError, PermissionError, OSError):
                    pass


def reset() -> None:
    with _store() as data:
        for cluster in data['clusters'].values():
            for info in cluster['instances'].values():
                root = info.get('tags', {}).get('host_root')
                if root:
                    _kill_host_processes(root)
                    shutil.rmtree(root, ignore_errors=True)
        data['clusters'] = {}
        data['provision_regions'] = {}
        data['open_ports'] = {}
    injector.reset()


def run_instances(region: str, zone: Optional[str], cluster_name: str,
                  config: common.ProvisionConfig) -> common.ProvisionRecord:
    zone = zone or f'{region}-a'
    with _store() as data:
        data.setdefault('provision_regions', {}).setdefault(
            cluster_name, []).append(region)
        injector.check(zone, config.node_config)
        existing = data['clusters'].get(cluster_name)
        if existing is not None:
            resumed = []
            for info in existing['instances'].values():
                if info['status'] == 'STOPPED':
                    info['status'] = 'RUNNING'
                    resumed.append(info['instance_id'])
            return common.ProvisionRecord(
                provider_name='fake', cluster_name=cluster_name,
                region=existing['region'], zone=existing['zone'],
                resumed_instance_ids=resumed, created_instance_ids=[],
                head_instance_id=existing['head_id'])

        node_cfg = config.node_config
        is_tpu = node_cfg.get('tpu_vm', False)
        hosts_per_slice = node_cfg.get('tpu_num_hosts', 1) if is_tpu else 1
        num_slices = node_cfg.get('tpu_num_slices', 1) if is_tpu else 1
        instances: Dict[str, Dict[str, Any]] = {}
        head_id = None
        for node in range(config.count):
            for s in range(num_slices):
                slice_id = (f'{cluster_name}-n{node}-slice{s}'
                            if is_tpu else None)
                for h in range(hosts_per_slice):
                    iid = f'fake-{uuid.uuid4().hex[:8]}'
                    data['ip_counter'] += 1
                    n = data['ip_counter']
                    ip = f'10.0.{n // 256}.{n % 256}'
                    # Each fake host gets a scratch dir standing in for
                    # its filesystem (used by LocalProcessCommandRunner).
                    host_root = tempfile.mkdtemp(prefix=f'xsky-{iid}-')
                    instances[iid] = dataclasses.asdict(
                        common.InstanceInfo(
                            instance_id=iid, internal_ip=ip,
                            external_ip=ip, status='RUNNING',
                            tags={'cluster_name': cluster_name,
                                  'node_index': str(node),
                                  'host_root': host_root},
                            slice_id=slice_id,
                            host_index=s * hosts_per_slice + h))
                    if head_id is None:
                        head_id = iid
        data['clusters'][cluster_name] = {
            'region': region, 'zone': zone, 'instances': instances,
            'head_id': head_id, 'node_config': dict(node_cfg),
        }
        return common.ProvisionRecord(
            provider_name='fake', cluster_name=cluster_name, region=region,
            zone=zone, resumed_instance_ids=[],
            created_instance_ids=list(instances), head_instance_id=head_id)


def stop_instances(cluster_name: str,
                   provider_config: Dict[str, Any]) -> None:
    with _store() as data:
        cluster = data['clusters'].get(cluster_name)
        if cluster is None:
            return
        if cluster['node_config'].get('tpu_vm') and \
                cluster['node_config'].get('tpu_num_hosts', 1) > 1:
            raise exceptions.NotSupportedError(
                'Multi-host TPU slices cannot be stopped.')
        for info in cluster['instances'].values():
            info['status'] = 'STOPPED'


def terminate_instances(cluster_name: str,
                        provider_config: Dict[str, Any]) -> None:
    with _store() as data:
        cluster = data['clusters'].pop(cluster_name, None)
    if cluster:
        for info in cluster['instances'].values():
            root = info.get('tags', {}).get('host_root')
            if root:
                _kill_host_processes(root)
                shutil.rmtree(root, ignore_errors=True)


def open_ports(cluster_name: str, ports: List[str],
               provider_config: Dict[str, Any]) -> None:
    """Record the request so e2e tests can assert the launch path
    actually exposes Resources(ports=…) (real clouds create firewall
    rules here)."""
    with _store() as data:
        opened = data.setdefault('open_ports', {})
        have = set(opened.get(cluster_name, []))
        opened[cluster_name] = sorted(have | {str(p) for p in ports})


def cleanup_ports(cluster_name: str,
                  provider_config: Dict[str, Any]) -> None:
    with _store() as data:
        data.setdefault('open_ports', {}).pop(cluster_name, None)


def opened_ports(cluster_name: str) -> List[str]:
    """Test helper: the ports open_ports recorded for the cluster."""
    return list(_load().get('open_ports', {}).get(cluster_name, []))


def query_instances(cluster_name: str, provider_config: Dict[str, Any]
                    ) -> Dict[str, Optional[str]]:
    # Runtime chaos: the `fake.preempt` point makes the instances vanish
    # out-of-band on the Nth status query — exactly preempt_cluster(),
    # but driven deterministically from an XSKY_CHAOS_PLAN instead of a
    # test calling in. This is the fake cloud acting as a chaotic
    # provider, so recovery paths can be exercised end-to-end.
    if chaos.inject('fake.preempt', cluster_name=cluster_name) is not None:
        terminate_instances(cluster_name, provider_config)
        return {}
    cluster = _load()['clusters'].get(cluster_name)
    if cluster is None:
        return {}
    return {iid: info['status']
            for iid, info in cluster['instances'].items()}


def wait_instances(region: str, cluster_name: str, state: str,
                   provider_config=None) -> None:
    return  # fake instances transition instantly


def get_cluster_info(region: str, cluster_name: str,
                     provider_config: Dict[str, Any]) -> common.ClusterInfo:
    cluster = _load()['clusters'].get(cluster_name)
    if cluster is None:
        raise exceptions.ClusterDoesNotExist(cluster_name)
    # Volumes on the fake cloud: hosts are local processes, so a
    # "mount" is a marker directory — which exercises the real
    # resources → deploy-vars → ClusterInfo.mount_commands → backend
    # execution path end-to-end without root or real disks.
    import shlex
    mount_commands = [
        f'mkdir -p {shlex.quote(vol["path"])} && '
        f'touch {shlex.quote(vol["path"] + "/.xsky-vol-" + vol["name"])}'
        for vol in (provider_config or {}).get('volumes') or []
    ]
    return common.ClusterInfo(
        instances=_infos_from(cluster),
        head_instance_id=cluster['head_id'],
        provider_name='fake',
        provider_config=dict(provider_config or {}),
        ssh_user='fake-user',
        mount_commands=mount_commands)


# ---- test helpers ----------------------------------------------------------


def provision_regions(cluster_name: str) -> List[str]:
    """Regions of every run_instances call for a cluster, in order
    (test observability: where did launches/relaunches land)."""
    with _store() as data:
        return list(data.get('provision_regions', {}).get(
            cluster_name, []))


def preempt_cluster(cluster_name: str) -> None:
    """Simulate a spot preemption: instances vanish out-of-band."""
    terminate_instances(cluster_name, {})


def cluster_exists(cluster_name: str) -> bool:
    return cluster_name in _load()['clusters']
