"""Cudo Compute provisioner op-set (via the nodepool base).

Behavioral twin of sky/provision/cudo/instance.py. Platform facts: VMs
live in a project and a data center (the catalog region IS the data
center id, e.g. gb-bournemouth-1), instance types encode machine class
+ GPU model, stop/start supported ("suspend"/"resume" in their
vocabulary maps to poweroff/start here), one public IP, all ports
open, no spot market.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional

from skypilot_tpu.provision import common
from skypilot_tpu.provision import nodepool
from skypilot_tpu.provision.cudo import rest

_transport_factory = rest.Transport


def set_transport_factory(factory) -> None:
    global _transport_factory
    _transport_factory = factory


DEFAULT_IMAGE = 'ubuntu-2204-nvidia-535-docker-v20240214'


class CudoApi(nodepool.NodeApi):
    provider_name = 'cudo'
    ssh_user = 'root'
    supports_stop = True
    state_map = {
        'pending': 'PENDING',
        'prep': 'PENDING',
        'creating': 'PENDING',
        'booting': 'PENDING',
        'starting': 'PENDING',
        'active': 'RUNNING',
        'running': 'RUNNING',
        'stopping': 'STOPPING',
        'suspended': 'STOPPED',
        'stopped': 'STOPPED',
        'deleting': None,
        'deleted': None,
        'failed': None,
    }

    def __init__(self) -> None:
        self.t = _transport_factory()

    @property
    def _base(self) -> str:
        return f'/projects/{self.t.project}/vms'

    @staticmethod
    def _row(vm: Dict[str, Any]) -> Dict[str, Any]:
        nic = (vm.get('nics') or [{}])[0]
        return {'id': vm.get('id') or vm.get('vmId'),
                'name': vm.get('id') or vm.get('vmId', ''),
                'status': (vm.get('shortState') or
                           vm.get('state', '')),
                'public_ip': nic.get('externalIpAddress') or
                vm.get('externalIpAddress'),
                'private_ip': nic.get('internalIpAddress') or
                vm.get('internalIpAddress')}

    def list_nodes(self) -> List[Dict[str, Any]]:
        reply = self.t.call('GET', self._base)
        return [self._row(vm) for vm in reply.get('VMs', [])]

    def create_node(self, name: str, region: str, zone: Optional[str],
                    node_config: Dict[str, Any]) -> str:
        del zone
        import os
        from skypilot_tpu import authentication
        _, public_key_path = authentication.get_or_generate_keys()
        with open(os.path.expanduser(public_key_path),
                  encoding='utf-8') as f:
            public_key = f.read().strip()
        itype = node_config['instance_type']
        # Grammar `<machine_type>_<gpus>x<GPU>` (e.g.
        # epyc-rome-rtx-a5000_2xRTXA5000); CPU-only types carry no
        # suffix.
        machine_type, _, gpu_part = itype.partition('_')
        gpus = int(gpu_part.split('x')[0]) if gpu_part else 0
        self.t.call('POST', self._base, {
            'vmId': name,
            'dataCenterId': region,
            'machineType': machine_type,
            'gpus': gpus,
            'vcpus': int(node_config.get('vcpus', 4)),
            'memoryGib': int(node_config.get('memory_gib', 16)),
            'bootDisk': {'sizeGib': node_config.get('disk_size', 100)},
            'bootDiskImageId': node_config.get('image_id') or
            DEFAULT_IMAGE,
            'sshKeySource': 'SSH_KEY_SOURCE_NONE',
            'customSshKeys': [public_key],
        })
        return name  # Cudo vmId is caller-chosen: id == name

    def delete_node(self, node_id: str) -> None:
        self.t.call('POST', f'{self._base}/{node_id}/terminate')

    def stop_node(self, node_id: str) -> None:
        self.t.call('POST', f'{self._base}/{node_id}/stop')

    def start_node(self, node_id: str) -> None:
        self.t.call('POST', f'{self._base}/{node_id}/start')

    def classify(self, e: Exception,
                 region: Optional[str] = None) -> Exception:
        if isinstance(e, rest.CudoApiError):
            return rest.classify_error(e, region)
        return e


def _api(provider_config: Dict[str, Any]) -> CudoApi:
    del provider_config
    return CudoApi()


def run_instances(region: str, zone: Optional[str], cluster_name: str,
                  config: common.ProvisionConfig) -> common.ProvisionRecord:
    return nodepool.run_instances(_api(config.provider_config), region,
                                  zone, cluster_name, config)


def wait_instances(region: str, cluster_name: str, state: str,
                   provider_config: Optional[Dict[str, Any]] = None,
                   timeout_s: float = 900.0,
                   poll_interval_s: float = 5.0) -> None:
    del region
    nodepool.wait_instances(_api(provider_config or {}), cluster_name,
                            state, timeout_s, poll_interval_s)


def stop_instances(cluster_name: str,
                   provider_config: Dict[str, Any]) -> None:
    nodepool.stop_instances(_api(provider_config), cluster_name)


def terminate_instances(cluster_name: str,
                        provider_config: Dict[str, Any]) -> None:
    nodepool.terminate_instances(_api(provider_config), cluster_name)


def query_instances(cluster_name: str, provider_config: Dict[str, Any]
                    ) -> Dict[str, Optional[str]]:
    return nodepool.query_instances(_api(provider_config), cluster_name)


def get_cluster_info(region: str, cluster_name: str,
                     provider_config: Dict[str, Any]
                     ) -> common.ClusterInfo:
    del region
    return nodepool.get_cluster_info(_api(provider_config), cluster_name,
                                     provider_config)


def open_ports(cluster_name: str, ports: List[str],
               provider_config: Dict[str, Any]) -> None:
    # Cudo VMs expose all ports on their public IP by default.
    del cluster_name, ports, provider_config


def cleanup_ports(cluster_name: str,
                  provider_config: Dict[str, Any]) -> None:
    del cluster_name, provider_config
