"""Device performance profiling: step anatomy, compiles, HBM, capture.

The telemetry plane (``agent/telemetry.py``) says *whether* a rank is
making progress; this module says *why a step is slow on the device*.
BENCH_LOCAL_r03_serve measured serving at 0.53x the JetStream baseline
with per-token host dispatch dominating at 113 ms/step against ~3 ms of
HBM traffic — a diagnosis that required hand-instrumenting the loop.
This is that instrument, made permanent:

  * an **always-on step-anatomy sampler**: the workload's step loop
    (``train/trainer.py``, ``infer/orchestrator.py``) brackets every
    Nth step with a :func:`step_probe` — host **dispatch gap** (time
    for the jitted call to return) split from **device compute** (timed
    around ``block_until_ready``). Unsampled steps pay two dict lookups
    and an increment; sampled steps pay one device sync — the
    ``tools/bench_profile.py`` gate holds the blend under 2% of step
    time;
  * a **compile listener** (``jax.monitoring`` duration events): count
    + seconds of XLA backend compiles, with a separate count of
    compiles that fire *after* the warmup window — the recompile-storm
    signal (a shape leak re-tracing the step forever);
  * **HBM watermarks** from ``device.memory_stats()`` (bytes in use /
    limit / peak seen);
  * an **on-demand deep capture** (``python -m
    skypilot_tpu.agent.profiler capture``): a self-contained device
    probe run per-rank over the PR 3 runner fan-out
    (``backend.capture_device_profile``) — dispatch RTT, device matmul
    step time, compile probe, HBM stats, plus a ``jax.profiler`` trace
    directory for offline tooling.

The sampler's summary rides the existing telemetry spool as the
``profile`` key of each rank's sample (one spool, one pull path), so
the control plane gets it for free with every telemetry pull. Pulled
summaries land in the bounded ``profiles`` table (``state.py``) with
derived **verdicts**:

  - ``host-bound``        dispatch gap dominates device compute (the
                          113 ms/step case);
  - ``recompile-storm``   compiles still firing after warmup;
  - ``hbm-pressure``      peak bytes-in-use near the device limit;
  - ``stale``             the summary is old relative to the rank's
                          OWN heartbeat (same host clock — cross-host
                          clock skew can neither fabricate nor mask
                          staleness).

Surfaces: ``xsky profile <cluster> [--job] [--rank] [--capture]
[--json]``, DISPATCH%/HBM in ``xsky top``, and ``/metrics`` gauges
(``xsky_dispatch_gap_ratio``, ``xsky_compiles_total``,
``xsky_compile_seconds_total``, ``xsky_hbm_bytes_in_use``).

**Fake-profiler seam**: with ``XSKY_PROFILER_FAKE=1`` every device
touch (block_until_ready, memory_stats, jax.profiler trace) is
replaced by synthetic values (env-tunable), so the fake cloud — and
tier-1 — exercises the full plane without jax in the workload. Chaos:
``profiler.dispatch_stall`` fires inside a sampled probe and inflates
the measured dispatch gap (rule key ``gap_s``, default 0.25), driving
the host-bound verdict end-to-end without slowing anything.

Never-raise discipline throughout: the sampler instruments the very
step loop whose throughput it measures.
"""
from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Dict, List, Optional

ENV_ENABLED = 'XSKY_PROFILE'                  # "0" disables the sampler
ENV_SAMPLE_EVERY = 'XSKY_PROFILE_SAMPLE_EVERY'
ENV_WARMUP_STEPS = 'XSKY_PROFILE_WARMUP_STEPS'
ENV_STALE = 'XSKY_PROFILE_STALE_S'
ENV_HOSTBOUND_RATIO = 'XSKY_PROFILE_HOSTBOUND_RATIO'
ENV_RECOMPILE_N = 'XSKY_PROFILE_RECOMPILE_N'
ENV_HBM_PRESSURE = 'XSKY_PROFILE_HBM_PRESSURE'
# Fake-profiler seam (fake cloud / CPU tests): synthetic device values.
ENV_FAKE = 'XSKY_PROFILER_FAKE'
ENV_FAKE_DISPATCH = 'XSKY_PROFILER_FAKE_DISPATCH_S'
ENV_FAKE_DEVICE = 'XSKY_PROFILER_FAKE_DEVICE_S'
ENV_FAKE_HBM_USE = 'XSKY_PROFILER_FAKE_HBM_USE'
ENV_FAKE_HBM_LIMIT = 'XSKY_PROFILER_FAKE_HBM_LIMIT'

VERDICT_HOST_BOUND = 'host-bound'
VERDICT_RECOMPILE_STORM = 'recompile-storm'
VERDICT_HBM_PRESSURE = 'hbm-pressure'
VERDICT_STALE = 'stale'

# Sample every Nth step: sampled steps pay one device sync (the
# block_until_ready that splits dispatch from device time), so the
# default keeps the sync amortized far under the 2% gate while the
# EMAs still converge within ~100 steps.
_DEFAULT_SAMPLE_EVERY = 16
# Steps before compiles stop being "warmup": a healthy jit workload
# compiles a handful of programs up front and then never again.
_DEFAULT_WARMUP_STEPS = 8
# Summary older than this relative to the rank's own heartbeat is
# stale (sampler wedged or workload no longer stepping).
_DEFAULT_STALE_S = 600.0
# dispatch_gap / (dispatch_gap + device) above this ⇒ host-bound.
_DEFAULT_HOSTBOUND_RATIO = 0.5
# Compiles after warmup at/above this ⇒ recompile storm.
_DEFAULT_RECOMPILE_N = 3
# Peak bytes_in_use / bytes_limit at/above this ⇒ HBM pressure.
_DEFAULT_HBM_PRESSURE = 0.92
# Sampled steps needed before the anatomy supports a verdict.
MIN_SAMPLED_STEPS = 3

_DEFAULT_FAKE_DISPATCH_S = 0.001
_DEFAULT_FAKE_DEVICE_S = 0.004
_DEFAULT_FAKE_HBM_USE = 2 << 30
_DEFAULT_FAKE_HBM_LIMIT = 16 << 30


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, default))
    except (TypeError, ValueError):
        return default


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, default))
    except (TypeError, ValueError):
        return default


def warmup_steps() -> int:
    return _env_int(ENV_WARMUP_STEPS, _DEFAULT_WARMUP_STEPS)


def stale_s() -> float:
    return _env_float(ENV_STALE, _DEFAULT_STALE_S)


def hostbound_ratio() -> float:
    return _env_float(ENV_HOSTBOUND_RATIO, _DEFAULT_HOSTBOUND_RATIO)


def recompile_n() -> int:
    return _env_int(ENV_RECOMPILE_N, _DEFAULT_RECOMPILE_N)


def hbm_pressure() -> float:
    return _env_float(ENV_HBM_PRESSURE, _DEFAULT_HBM_PRESSURE)


def fake_mode() -> bool:
    return os.environ.get(ENV_FAKE, '0') not in ('0', '')


# ---- step-anatomy sampler (workload-process side) --------------------------


class _Anatomy:
    """One process's accumulated step anatomy (all ranks in a gang run
    one workload process per host, so one singleton per rank)."""

    def __init__(self) -> None:
        self.lock = threading.Lock()
        # Bumped un-locked on every step_probe() call (the hot path);
        # a rare GIL-raced loss of one increment only shifts which
        # step gets sampled.
        self.steps_seen = 0
        self.steps_sampled = 0
        self.dispatch_gap_ema_s: Optional[float] = None
        self.device_ema_s: Optional[float] = None
        self.compiles_total = 0
        self.compile_seconds_total = 0.0
        self.compiles_after_warmup = 0
        self.hbm_bytes_in_use: Optional[int] = None
        self.hbm_bytes_limit: Optional[int] = None
        self.hbm_peak_bytes: Optional[int] = None

    def note_compile(self, seconds: float) -> None:
        with self.lock:
            self.compiles_total += 1
            self.compile_seconds_total += float(seconds)
            if self.steps_seen > warmup_steps():
                self.compiles_after_warmup += 1

    def observe_step(self, dispatch_gap_s: float, device_s: float) -> None:
        from skypilot_tpu.agent import telemetry
        hbm = _hbm_stats()
        with self.lock:
            self.steps_sampled += 1
            self.dispatch_gap_ema_s = telemetry.ema(
                self.dispatch_gap_ema_s, dispatch_gap_s)
            self.device_ema_s = telemetry.ema(self.device_ema_s, device_s)
            in_use = hbm.get('bytes_in_use')
            if in_use is not None:
                self.hbm_bytes_in_use = int(in_use)
                self.hbm_peak_bytes = max(self.hbm_peak_bytes or 0,
                                          int(in_use))
            limit = hbm.get('bytes_limit')
            if limit is not None:
                self.hbm_bytes_limit = int(limit)
            snap = self._snapshot_locked()
        # Outside the lock: emit serializes + may write the spool.
        telemetry.emit(profile=snap)

    def _snapshot_locked(self) -> Dict[str, Any]:
        gap, dev = self.dispatch_gap_ema_s, self.device_ema_s
        ratio = None
        if gap is not None and dev is not None and gap + dev > 0:
            ratio = gap / (gap + dev)
        return {
            'ts': time.time(),
            'steps_seen': self.steps_seen,
            'steps_sampled': self.steps_sampled,
            'dispatch_gap_ema_s': gap,
            'device_ema_s': dev,
            'dispatch_gap_ratio': ratio,
            'compiles_total': self.compiles_total,
            'compile_seconds_total': round(self.compile_seconds_total, 6),
            'compiles_after_warmup': self.compiles_after_warmup,
            'hbm_bytes_in_use': self.hbm_bytes_in_use,
            'hbm_bytes_limit': self.hbm_bytes_limit,
            'hbm_peak_bytes': self.hbm_peak_bytes,
        }

    def snapshot(self) -> Dict[str, Any]:
        with self.lock:
            return self._snapshot_locked()


_anatomy_lock = threading.Lock()
_anatomy: Optional[_Anatomy] = None
# (ENV_ENABLED, ENV_SAMPLE_EVERY) raw values the cached config was
# built from: step_probe() is on the step loop, so the steady-state
# resolve must be two dict lookups, a tuple compare, and a modulo.
_cfg_key = None
_cfg: Optional[int] = None   # sample-every, or None when disabled


def _get_anatomy() -> _Anatomy:
    global _anatomy
    if _anatomy is None:
        with _anatomy_lock:
            if _anatomy is None:
                _anatomy = _Anatomy()
    return _anatomy


def _sample_every() -> Optional[int]:
    """Sampling cadence, or None when the sampler is disabled."""
    global _cfg_key, _cfg
    key = (os.environ.get(ENV_ENABLED),
           os.environ.get(ENV_SAMPLE_EVERY))
    if key == _cfg_key:
        return _cfg
    if key[0] == '0':
        cfg = None
    else:
        try:
            cfg = max(1, int(key[1])) if key[1] else _DEFAULT_SAMPLE_EVERY
        except ValueError:
            cfg = _DEFAULT_SAMPLE_EVERY
    _cfg, _cfg_key = cfg, key
    return cfg


def _hbm_stats() -> Dict[str, Any]:
    """bytes_in_use / bytes_limit of device 0 (best effort — the axon
    tunnel sometimes returns None from memory_stats)."""
    if fake_mode():
        return {
            'bytes_in_use': _env_int(ENV_FAKE_HBM_USE,
                                     _DEFAULT_FAKE_HBM_USE),
            'bytes_limit': _env_int(ENV_FAKE_HBM_LIMIT,
                                    _DEFAULT_FAKE_HBM_LIMIT),
        }
    try:
        import jax
        stats = jax.local_devices()[0].memory_stats() or {}
        return {'bytes_in_use': stats.get('bytes_in_use'),
                'bytes_limit': stats.get('bytes_limit')}
    except Exception:  # pylint: disable=broad-except
        return {}


class _StepProbe:
    """Brackets ONE sampled step: dispatch gap vs device compute."""

    __slots__ = ('_anatomy', '_t0', '_t1')

    def __init__(self, anatomy: _Anatomy) -> None:
        self._anatomy = anatomy
        self._t0 = time.perf_counter()
        self._t1: Optional[float] = None

    def dispatched(self) -> None:
        """Mark the jitted call returning (host dispatch done). Callers
        whose device wait is a separate blocking call (device_get in
        the serving loop) call this, then :meth:`done` after the wait;
        callers with the step output in hand just call ``done(out)``."""
        self._t1 = time.perf_counter()

    def done(self, out: Any = None) -> Optional[tuple]:
        """Finish the probe. NEVER raises — it sits on the step loop.

        ``out`` (the step's output pytree) is block_until_ready'd to
        time device compute; with ``dispatched()`` already called and
        no ``out``, device time is the wall since the dispatch mark.

        Returns the measured ``(dispatch_gap_s, device_s)`` pair (None
        on failure) so the flight recorder's step seal shares THIS
        probe's timestamps — one device sync per sampled step, never a
        second ``block_until_ready`` for the recorder.
        """
        try:
            t1 = self._t1 if self._t1 is not None else time.perf_counter()
            if out is not None and not fake_mode():
                try:
                    import jax
                    jax.block_until_ready(out)
                except Exception:  # pylint: disable=broad-except
                    pass
            t2 = time.perf_counter()
            gap = t1 - self._t0
            device = t2 - t1
            if fake_mode():
                # Synthetic anatomy: the fake cloud runs no device, so
                # the seam supplies the split (env-tunable per test).
                gap = _env_float(ENV_FAKE_DISPATCH,
                                 _DEFAULT_FAKE_DISPATCH_S)
                device = _env_float(ENV_FAKE_DEVICE,
                                    _DEFAULT_FAKE_DEVICE_S)
            try:
                from skypilot_tpu.utils import chaos
                rule = chaos.inject(
                    'profiler.dispatch_stall',
                    rank=_env_int('XSKY_HOST_RANK', 0))
                if rule is not None:
                    # Inject a host-bound anatomy without slowing the
                    # step: the measured gap grows by the rule's gap_s.
                    gap += float(rule.get('gap_s', 0.25))
            except Exception:  # pylint: disable=broad-except
                pass
            self._anatomy.observe_step(gap, device)
            return (gap, device)
        except Exception:  # pylint: disable=broad-except
            return None


def step_probe() -> Optional[_StepProbe]:
    """Begin one step's anatomy probe, or None when this step is not
    sampled (the common path: two dict lookups, an increment and a
    modulo). Call right before dispatching the step; call ``.done(out)``
    right after. NEVER raises."""
    try:
        every = _sample_every()
        if every is None:
            return None
        anatomy = _get_anatomy()
        anatomy.steps_seen += 1
        if anatomy.steps_seen % every:
            return None
        return _StepProbe(anatomy)
    except Exception:  # pylint: disable=broad-except
        return None


def record_compile(seconds: float) -> None:
    """Count one compile event (the jax.monitoring listener's entry
    point; also the fake seam's — fake workloads call it directly).
    NEVER raises."""
    try:
        _get_anatomy().note_compile(seconds)
    except Exception:  # pylint: disable=broad-except
        pass


_listener_installed = False


def ensure_compile_listener() -> None:
    """Register the jax.monitoring duration listener once per process
    (idempotent, never raises). Counts ``backend_compile`` events —
    one per compiled executable — into the anatomy. In fake mode the
    listener is skipped: fake workloads drive :func:`record_compile`
    directly, and importing jax there would defeat the seam."""
    global _listener_installed
    if _listener_installed:
        return
    try:
        if fake_mode():
            # Do NOT latch the flag: a process that leaves fake mode
            # (test harness) must still be able to install the real
            # listener.
            return
        _listener_installed = True
        from jax import monitoring

        def _on_event(event: str, duration: float, **kwargs: Any) -> None:
            del kwargs
            if event.endswith('backend_compile_duration'):
                record_compile(duration)

        monitoring.register_event_duration_secs_listener(_on_event)
    except Exception:  # pylint: disable=broad-except
        pass


# ---- verdicts + control-plane recording ------------------------------------


def hbm_watermark(prof: Dict[str, Any]) -> Optional[int]:
    """The profile's HBM high-water mark: the tracked peak, falling
    back to the latest in-use reading when no peak was recorded. One
    definition shared by verdict scoring, `xsky top`/`xsky profile`
    rendering and bench.py's failure dump — four copies would drift."""
    return prof.get('hbm_peak_bytes') or prof.get('hbm_bytes_in_use')


def summary_ratio(prof: Dict[str, Any]) -> Optional[float]:
    """dispatch_gap / (dispatch_gap + device) — recomputed from the
    EMAs when the summary predates (or dropped) the stored ratio."""
    ratio = prof.get('dispatch_gap_ratio')
    if ratio is not None:
        return float(ratio)
    gap, dev = prof.get('dispatch_gap_ema_s'), prof.get('device_ema_s')
    if gap is None or dev is None or gap + dev <= 0:
        return None
    return gap / (gap + dev)


def verdicts_for(prof: Dict[str, Any]) -> List[str]:
    """Derive the verdict list from one profile summary (pure math;
    thresholds env-tunable). Tolerates truncated/partial summaries —
    a missing field simply cannot contribute its verdict."""
    out: List[str] = []
    try:
        sampled = int(prof.get('steps_sampled') or 0)
        ratio = summary_ratio(prof)
        if ratio is not None and sampled >= MIN_SAMPLED_STEPS and \
                ratio > hostbound_ratio():
            out.append(VERDICT_HOST_BOUND)
        if int(prof.get('compiles_after_warmup') or 0) >= recompile_n():
            out.append(VERDICT_RECOMPILE_STORM)
        peak = hbm_watermark(prof)
        limit = prof.get('hbm_bytes_limit')
        if peak and limit and float(peak) / float(limit) >= hbm_pressure():
            out.append(VERDICT_HBM_PRESSURE)
    except (TypeError, ValueError):
        # A torn summary (strings where numbers belong) yields whatever
        # verdicts were derived before the bad field — never a raise.
        pass
    return out


def summary_is_stale(sample: Dict[str, Any],
                     prof: Dict[str, Any]) -> bool:
    """Whether the profile summary lags the rank's OWN heartbeat by
    more than the staleness window. Both timestamps come from the same
    host clock, so cross-host clock skew (rank hours behind the control
    plane) can neither fabricate nor mask staleness."""
    try:
        hb = sample.get('hb_ts')
        ts = prof.get('ts')
        if hb is None or ts is None:
            return False
        return float(hb) - float(ts) > stale_s()
    except (TypeError, ValueError):
        return False


# (cluster, job_id, rank) → (compiles_total, compile_seconds_total) at
# the previous pull: the registry counters count deltas, not snapshots.
# Mutated by every puller thread (jobs controller monitor loop,
# _wait_job) — the lock makes each delta+floor update atomic so two
# concurrent pulls can neither double-count a delta nor corrupt the
# floor (lock-discipline).
_last_compiles: Dict[Any, Any] = {}
_last_compiles_lock = threading.Lock()


def record_profiles(cluster: str, job_id: Optional[int],
                    samples: Dict[int, Dict[str, Any]],
                    kind: str = 'summary',
                    now: Optional[float] = None) -> Dict[int, List[str]]:
    """Persist pulled profile data to the bounded ``profiles`` table
    and feed the metrics registry; returns per-rank verdicts. NEVER
    raises.

    ``kind='summary'``: ``samples`` are telemetry spool samples — the
    ``profile`` block of each is extracted (ranks without one, or with
    a torn one, are skipped). ``kind='capture'``: ``samples`` are the
    per-rank deep-capture summaries themselves.
    """
    result: Dict[int, List[str]] = {}
    rows = []
    incarnations: Dict[int, Any] = {}
    try:
        now = now if now is not None else time.time()
        for rank, sample in sorted(samples.items()):
            if not isinstance(sample, dict):
                continue
            if kind == 'summary':
                prof = sample.get('profile')
                if not isinstance(prof, dict):
                    continue
                incarnations[rank] = sample.get('started_ts')
                stale = summary_is_stale(sample, prof)
            else:
                prof = sample
                stale = False
            verdicts = ([VERDICT_STALE] if stale else verdicts_for(prof))
            result[rank] = verdicts
            detail = None
            if kind != 'summary':
                detail = {k: v for k, v in prof.items()
                          if isinstance(v, (str, int, float, bool, list))}
            rows.append({
                'rank': rank,
                'kind': kind,
                'steps': prof.get('steps_seen'),
                'steps_sampled': prof.get('steps_sampled'),
                'dispatch_gap_ema_s': prof.get('dispatch_gap_ema_s'),
                'device_ema_s': prof.get('device_ema_s'),
                'dispatch_gap_ratio': summary_ratio(prof),
                'compiles_total': prof.get('compiles_total'),
                'compile_seconds_total': prof.get('compile_seconds_total'),
                'compiles_after_warmup': prof.get('compiles_after_warmup'),
                'hbm_bytes_in_use': prof.get('hbm_bytes_in_use'),
                'hbm_bytes_limit': prof.get('hbm_bytes_limit'),
                'hbm_peak_bytes': prof.get('hbm_peak_bytes'),
                'verdicts': verdicts,
                'detail': detail,
            })
    except Exception:  # pylint: disable=broad-except
        return result
    if not rows:
        return result
    try:
        from skypilot_tpu import state
        state.record_profiles(cluster, job_id, rows, ts=now)
    except Exception:  # pylint: disable=broad-except
        pass
    try:
        from skypilot_tpu.utils import metrics
        for row in rows:
            if row['kind'] != 'summary':
                # Only summary counters are cumulative; a capture's
                # compile_seconds_total is one probe's fresh
                # measurement, not a running total the delta math
                # could difference.
                continue
            key = (cluster, job_id, row['rank'], row['kind'])
            total = row.get('compiles_total')
            seconds = row.get('compile_seconds_total')
            if total is None and seconds is None:
                continue
            gen = incarnations.get(row['rank'])
            with _last_compiles_lock:
                prev_gen, prev_total, prev_seconds = _last_compiles.get(
                    key, (None, 0, 0.0))
                if gen is not None and prev_gen is not None \
                        and gen != prev_gen:
                    if gen < prev_gen:
                        # Out-of-order pull from an older workload
                        # incarnation: its totals are stale, skip.
                        continue
                    # New incarnation (relaunch/resubmit): its counters
                    # restarted at zero, so the floor must too.
                    prev_total, prev_seconds = 0, 0.0
                d_total = max(0, int(total or 0) - prev_total)
                d_seconds = max(0.0,
                                float(seconds or 0.0) - prev_seconds)
                # Within one incarnation keep the floor monotone: a
                # puller committing an older snapshot after a newer one
                # must not lower it, or the next pull re-counts the
                # difference.
                _last_compiles[key] = (
                    gen if gen is not None else prev_gen,
                    max(prev_total, int(total or 0)),
                    max(prev_seconds, float(seconds or 0.0)))
            if d_total:
                metrics.inc_counter(
                    'xsky_compiles_total',
                    'XLA compiles observed by workload profilers.',
                    float(d_total))
            if d_seconds:
                metrics.inc_counter(
                    'xsky_compile_seconds_total',
                    'Seconds spent in XLA backend compiles.',
                    d_seconds)
    except Exception:  # pylint: disable=broad-except
        pass
    return result


# ---- on-demand deep capture ------------------------------------------------


def run_capture(out_dir: str, duration_s: float = 1.0) -> Dict[str, Any]:
    """Self-contained device deep-probe for one host.

    Measures the three numbers the step-anatomy verdicts hinge on,
    independently of any running workload: per-dispatch host→device
    RTT (the 113 ms/step signal on tunneled terminals), device matmul
    step time, and a cold compile — plus HBM stats and, in real mode,
    a ``jax.profiler`` trace of the probe written under ``out_dir``
    for offline tooling (xprof/tensorboard). In fake mode every device
    touch is synthetic (env-tunable) and a ``capture.json`` stands in
    for the trace. Raises only on an unwritable ``out_dir``.
    """
    os.makedirs(os.path.expanduser(out_dir), exist_ok=True)
    summary: Dict[str, Any] = {
        'ts': time.time(),
        'duration_s': duration_s,
        'out_dir': out_dir,
        'fake': fake_mode(),
    }
    if fake_mode():
        dispatch_s = _env_float(ENV_FAKE_DISPATCH,
                                _DEFAULT_FAKE_DISPATCH_S)
        device_s = _env_float(ENV_FAKE_DEVICE, _DEFAULT_FAKE_DEVICE_S)
        summary.update({
            'device_kind': 'fake-tpu',
            'num_devices': 1,
            'dispatch_rtt_ms': dispatch_s * 1000.0,
            'device_matmul_ms': device_s * 1000.0,
            'probe_compile_s': 0.01,
            'dispatch_probes': 16,
            **_hbm_stats(),
        })
        path = os.path.join(os.path.expanduser(out_dir), 'capture.json')
        with open(path, 'w', encoding='utf-8') as f:
            f.write(json.dumps(summary, default=str))
        summary['trace_files'] = ['capture.json']
        return summary
    return _real_capture(out_dir, duration_s, summary)


def _real_capture(out_dir: str, duration_s: float,
                  summary: Dict[str, Any]) -> Dict[str, Any]:
    import jax
    import jax.numpy as jnp
    devices = jax.local_devices()
    summary['device_kind'] = getattr(devices[0], 'device_kind', '?')
    summary['num_devices'] = len(devices)
    traced = False
    try:
        jax.profiler.start_trace(os.path.expanduser(out_dir))
        traced = True
    except Exception:  # pylint: disable=broad-except
        pass
    try:
        budget = max(float(duration_s), 0.2)
        # Cold compile probe (a shape no workload uses).
        tiny = jax.jit(lambda v: v * 2 + 1)
        x = jnp.zeros((3,), jnp.float32)
        t0 = time.perf_counter()
        jax.block_until_ready(tiny(x))
        summary['probe_compile_s'] = round(time.perf_counter() - t0, 6)
        # Dispatch RTT: tiny synced dispatches — on a healthy local
        # PJRT client this is sub-ms; over a tunneled terminal it IS
        # the serving bottleneck.
        n = 0
        t0 = time.perf_counter()
        while time.perf_counter() - t0 < budget / 2 and n < 200:
            jax.block_until_ready(tiny(x))
            n += 1
        summary['dispatch_probes'] = n
        summary['dispatch_rtt_ms'] = round(
            (time.perf_counter() - t0) / max(n, 1) * 1000.0, 3)
        # Device step time: a bandwidth-ish matmul.
        m = jnp.ones((1024, 1024), jnp.bfloat16)
        mm = jax.jit(lambda a: (a @ a).sum())
        jax.block_until_ready(mm(m))   # compile outside the timing
        k = 0
        t0 = time.perf_counter()
        while time.perf_counter() - t0 < budget / 2 and k < 100:
            jax.block_until_ready(mm(m))
            k += 1
        summary['device_matmul_ms'] = round(
            (time.perf_counter() - t0) / max(k, 1) * 1000.0, 3)
        summary.update(_hbm_stats())
    except Exception as e:  # pylint: disable=broad-except
        summary['error'] = f'{type(e).__name__}: {e}'
    finally:
        if traced:
            try:
                jax.profiler.stop_trace()
            except Exception:  # pylint: disable=broad-except
                pass
    try:
        files = []
        for root, _, names in os.walk(os.path.expanduser(out_dir)):
            for name in names:
                rel = os.path.relpath(os.path.join(root, name),
                                      os.path.expanduser(out_dir))
                files.append(rel)
        summary['trace_files'] = sorted(files)[:50]
    except OSError:
        pass
    return summary


def capture_summary_row(summary: Dict[str, Any]) -> Dict[str, Any]:
    """Map a capture summary onto the anatomy vocabulary so the same
    verdict math applies (dispatch RTT ~ dispatch gap, matmul ~ device
    compute): what record_profiles(kind='capture') persists."""
    out = dict(summary)
    rtt_ms = summary.get('dispatch_rtt_ms')
    mm_ms = summary.get('device_matmul_ms')
    if rtt_ms is not None:
        out['dispatch_gap_ema_s'] = float(rtt_ms) / 1000.0
    if mm_ms is not None:
        out['device_ema_s'] = float(mm_ms) / 1000.0
    out['steps_sampled'] = summary.get('dispatch_probes')
    out['compile_seconds_total'] = summary.get('probe_compile_s')
    out['hbm_bytes_in_use'] = summary.get('bytes_in_use')
    out['hbm_bytes_limit'] = summary.get('bytes_limit')
    return out


def reset_for_test() -> None:
    global _anatomy, _cfg, _cfg_key
    with _anatomy_lock:
        _anatomy = None
    _cfg, _cfg_key = None, None
    with _last_compiles_lock:
        _last_compiles.clear()


# ---- CLI (`python -m skypilot_tpu.agent.profiler capture ...`) -------------


def main(argv: Optional[List[str]] = None) -> int:
    import argparse
    parser = argparse.ArgumentParser(
        prog='python -m skypilot_tpu.agent.profiler',
        description='Per-host device profiling agent.')
    sub = parser.add_subparsers(dest='cmd', required=True)
    cap = sub.add_parser('capture', help='Run one deep device capture; '
                                         'prints a one-line JSON summary.')
    cap.add_argument('--out', required=True,
                     help='Directory for the capture artifacts.')
    cap.add_argument('--duration', type=float, default=1.0)
    args = parser.parse_args(argv)
    if args.cmd == 'capture':
        summary = run_capture(args.out, args.duration)
        print(json.dumps(summary, default=str))
        return 0
    return 2


if __name__ == '__main__':
    import sys
    sys.exit(main())
