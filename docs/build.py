#!/usr/bin/env python3
"""Static docs site builder + link checker (docs CI job).

The image bakes neither mkdocs nor sphinx, so this is the build
pipeline (role of the reference's docs/build.sh): python-markdown →
one HTML page per .md with a shared nav sidebar, plus a link checker
that fails the build on any intra-docs link that does not resolve.

Usage:
  python docs/build.py [--out docs/_build]     # build + check
  python docs/build.py --check-only            # links only (CI fast path)
"""
from __future__ import annotations

import argparse
import pathlib
import re
import sys

DOCS = pathlib.Path(__file__).resolve().parent

# Nav order; every tracked page must be listed (build fails otherwise
# so a new page cannot silently miss the sidebar).
NAV = [
    ('index.md', 'Overview'),
    ('quickstart.md', 'Quickstart'),
    ('cli.md', 'CLI reference'),
    ('architecture.md', 'Architecture'),
    ('parallelism.md', 'Parallelism'),
    ('finetuning.md', 'Fine-tuning'),
    ('serving.md', 'Serving'),
    ('jobs.md', 'Managed jobs'),
    ('robustness.md', 'Robustness'),
    ('observability.md', 'Observability'),
    ('storage.md', 'Storage'),
    ('clouds.md', 'Clouds'),
    ('server.md', 'API server'),
    ('performance.md', 'Performance'),
    ('static-analysis.md', 'Static analysis'),
    ('reference/environment.md', 'Env variables'),
    ('reference/observability-names.md', 'Observability names'),
]

_TEMPLATE = """<!DOCTYPE html>
<html lang="en"><head><meta charset="utf-8">
<title>{title} — xsky docs</title>
<style>
  body {{ font: 15px/1.6 system-ui, sans-serif; color: #1a1d21;
         margin: 0; display: flex; }}
  nav {{ width: 220px; min-height: 100vh; border-right: 1px solid
        #e5e7eb; padding: 24px 0; background: #f8fafc;
        flex-shrink: 0; }}
  nav a {{ display: block; padding: 6px 24px; color: #374151;
          text-decoration: none; font-size: 14px; }}
  nav a.active {{ color: #2563eb; font-weight: 600;
                 border-left: 3px solid #2563eb; }}
  main {{ max-width: 760px; padding: 32px 48px; }}
  pre {{ background: #0f172a; color: #e2e8f0; padding: 12px 16px;
        border-radius: 6px; overflow-x: auto; font-size: 13px; }}
  code {{ font-size: 13px; background: #f1f5f9; padding: 1px 4px;
         border-radius: 3px; }}
  pre code {{ background: none; padding: 0; }}
  table {{ border-collapse: collapse; }}
  th, td {{ border: 1px solid #e5e7eb; padding: 6px 10px;
           font-size: 14px; text-align: left; }}
  h1, h2, h3 {{ line-height: 1.3; }}
  a {{ color: #2563eb; }}
</style></head><body>
<nav>{nav}</nav>
<main>{body}</main>
</body></html>
"""


def _nav_html(active: str) -> str:
    # Nav links are relative to the ACTIVE page's directory (pages may
    # live in subdirectories, e.g. reference/environment.md).
    depth = active.count('/')
    prefix = '../' * depth
    items = []
    for fname, title in NAV:
        href = prefix + fname.replace('.md', '.html')
        cls = ' class="active"' if fname == active else ''
        items.append(f'<a href="{href}"{cls}>{title}</a>')
    return '\n'.join(items)


def _tracked_pages() -> set:
    """Every .md under docs/ (subdirectories included, build output
    excluded), as posix-relative names."""
    return {
        f.relative_to(DOCS).as_posix()
        for f in DOCS.rglob('*.md')
        if '_build' not in f.relative_to(DOCS).parts
    }


def _check_links() -> list:
    """Every relative intra-docs link must point at a real page."""
    errors = []
    pages = _tracked_pages()
    nav_pages = {fname for fname, _ in NAV}
    for missing in nav_pages - pages:
        errors.append(f'NAV lists missing page: {missing}')
    for stray in pages - nav_pages:
        errors.append(f'page not in NAV (add to docs/build.py): {stray}')
    link_re = re.compile(r'\[[^\]]*\]\(([^)#\s]+)(#[^)\s]*)?\)')
    for page in sorted(DOCS / p for p in pages):
        for match in link_re.finditer(page.read_text(encoding='utf-8')):
            target = match.group(1)
            if target.startswith(('http://', 'https://', 'mailto:')):
                continue
            resolved = (page.parent / target).resolve()
            if not resolved.exists():
                errors.append(f'{page.name}: broken link → {target}')
    return errors


def build(out_dir: pathlib.Path) -> None:
    import markdown
    out_dir.mkdir(parents=True, exist_ok=True)
    for fname, title in NAV:
        text = (DOCS / fname).read_text(encoding='utf-8')
        # .md links become .html links in the rendered site.
        text = re.sub(r'\(([\w\-./]+)\.md(#[^)\s]*)?\)',
                      r'(\1.html\2)', text)
        body = markdown.markdown(
            text, extensions=['fenced_code', 'tables'])
        html = _TEMPLATE.format(title=title, nav=_nav_html(fname),
                                body=body)
        out_path = out_dir / fname.replace('.md', '.html')
        out_path.parent.mkdir(parents=True, exist_ok=True)
        out_path.write_text(html, encoding='utf-8')
    print(f'built {len(NAV)} pages → {out_dir}')


def main() -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument('--out', default=str(DOCS / '_build'))
    parser.add_argument('--check-only', action='store_true')
    args = parser.parse_args()
    errors = _check_links()
    if errors:
        for e in errors:
            print(f'LINK ERROR: {e}', file=sys.stderr)
        return 1
    if not args.check_only:
        build(pathlib.Path(args.out))
    return 0


if __name__ == '__main__':
    raise SystemExit(main())
