"""Azure cloud: VM GPU/CPU offerings for cross-cloud optimization.

Lean twin of sky/clouds/azure.py — catalog-backed feasibility via
CatalogCloud, ARM deploy variables for the 'azure' provisioner
(provision/azure/instance.py), service-principal credential probing.
Third compute cloud next to GCP and AWS, so optimizer failover can walk
GCP TPU → AWS GPU → Azure GPU.
"""
from __future__ import annotations

import os
import typing
from typing import Any, Dict, Optional, Tuple

from skypilot_tpu.clouds import catalog_cloud
from skypilot_tpu.clouds import cloud as cloud_lib
from skypilot_tpu.utils import registry

if typing.TYPE_CHECKING:
    from skypilot_tpu import resources as resources_lib


@registry.CLOUD_REGISTRY.register(aliases=['az'])
class Azure(catalog_cloud.CatalogCloud):
    _REPR = 'Azure'
    # Azure VM names cap at 64, but NIC/IP names get suffixes appended.
    _MAX_CLUSTER_NAME_LEN_LIMIT = 42

    def unsupported_features_for_resources(
        self, resources: 'resources_lib.Resources'
    ) -> Dict[cloud_lib.CloudImplementationFeatures, str]:
        del resources
        return {
            cloud_lib.CloudImplementationFeatures.TPU_POD:
                'Azure has no TPUs.',
            cloud_lib.CloudImplementationFeatures.TPU_MULTISLICE:
                'Azure has no TPUs.',
        }

    def make_deploy_resources_variables(
            self, resources: 'resources_lib.Resources', cluster_name: str,
            region: str, zone: Optional[str]) -> Dict[str, Any]:
        from skypilot_tpu import authentication
        vars: Dict[str, Any] = {
            'cluster_name': cluster_name,
            'region': region,
            'zone': zone,
            'instance_type': resources.instance_type,
            'use_spot': resources.use_spot,
            'disk_size': resources.disk_size,
            'ports': resources.ports,
            'labels': dict(resources.labels or {}),
            'image_id': resources.image_id,
            # ARM rejects a Linux VM with password auth disabled and no
            # key, and the lifecycle ops all reach nodes over SSH.
            'ssh_user': 'azureuser',
            'ssh_public_key': authentication.public_key_content(),
        }
        if resources.accelerators:
            name, count = next(iter(resources.accelerators.items()))
            vars.update({'gpu_type': name, 'gpu_count': count})
        return vars

    def provider_config_overrides(
            self, node_config: Dict[str, Any]) -> Dict[str, Any]:
        # get_cluster_info builds runners with provider_config's
        # ssh_user; keep it in lockstep with the osProfile adminUsername.
        return {'ssh_user': node_config.get('ssh_user', 'azureuser')}

    def check_credentials(self) -> Tuple[bool, Optional[str]]:
        from skypilot_tpu.provision.azure import rest as azure_rest
        if azure_rest.load_credentials() is not None:
            return True, None
        return False, (
            'Azure credentials not found. Set AZURE_TENANT_ID / '
            'AZURE_CLIENT_ID / AZURE_CLIENT_SECRET / '
            'AZURE_SUBSCRIPTION_ID or populate ~/.azure/credentials.')

    def get_credential_file_mounts(self) -> Dict[str, str]:
        path = os.path.expanduser('~/.azure/credentials')
        if os.path.exists(path):
            return {'~/.azure/credentials': '~/.azure/credentials'}
        return {}

    def get_egress_cost(self, num_gigabytes: float) -> float:
        if num_gigabytes <= 0:
            return 0.0
        return 0.087 * num_gigabytes
