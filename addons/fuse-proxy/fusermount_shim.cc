// fusermount-shim: masks `fusermount` in unprivileged containers.
//
// C++ twin of addons/fuse-proxy/cmd/fusermount-shim/main.go (reference).
// A FUSE adapter (gcsfuse, goofys, ...) execs this in place of the real
// fusermount; we forward argv to the privileged fusermount-server over a
// unix socket. If the adapter expects the mounted /dev/fuse fd back via
// the _FUSE_COMMFD protocol, the server relays that fd to us with
// SCM_RIGHTS and we pass it on to our parent the same way the real
// fusermount would.
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <vector>

#include <sys/socket.h>
#include <unistd.h>

#include "common.hpp"

namespace fp = fuseproxy;

int main(int argc, char** argv) {
  fp::Request req;
  req.mode = fp::kModeShim;
  for (int i = 1; i < argc; ++i) req.args.emplace_back(argv[i]);

  // libfuse sets _FUSE_COMMFD to a socket over which fusermount must
  // send the mounted fd.
  const char* commfd_env = ::getenv("_FUSE_COMMFD");
  req.want_fd = commfd_env != nullptr;

  int sock = fp::ConnectTo(fp::DefaultSocketPath());
  if (sock < 0) {
    std::fprintf(stderr,
                 "fusermount-shim: cannot connect to %s: %s\n",
                 fp::DefaultSocketPath(), std::strerror(errno));
    return 1;
  }
  if (!fp::SendRequest(sock, req)) {
    std::fprintf(stderr, "fusermount-shim: send failed\n");
    return 1;
  }
  fp::Response resp;
  if (!fp::RecvResponse(sock, &resp)) {
    std::fprintf(stderr, "fusermount-shim: bad response\n");
    return 1;
  }
  if (!resp.message.empty()) {
    std::fprintf(stderr, "%s\n", resp.message.c_str());
  }
  if (resp.fd >= 0 && commfd_env != nullptr) {
    int commfd = std::atoi(commfd_env);
    if (!fp::SendFd(commfd, resp.fd)) {
      std::fprintf(stderr, "fusermount-shim: fd relay failed\n");
      return 1;
    }
  }
  ::close(sock);
  return resp.code;
}
