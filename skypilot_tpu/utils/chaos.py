"""Runtime fault injection: named chaos points on recovery hot paths.

The fake cloud's provision-time ``FailureInjector`` scripts *provisioning*
failures; this module covers everything after bring-up — SSH transport,
gang fan-out, the control plane's parallel host fan-out
(``fanout.worker``, with ``phase``/``rank`` context), status probes,
serve readiness probes, workload telemetry (``telemetry.stall``
freezes a rank's emit without killing the process — the hung-rank
drill) — so the recovery
machinery (jobs controller, gang retry, serve replica recovery, failover
engine) can be driven under fault deterministically.

A *chaos point* is a named call site::

    chaos.inject('jobs.status_probe', job_id=self.job_id)

With no plan loaded the call is a no-op (one dict lookup; hit counters
stay untouched, nothing allocates). A plan comes from ``XSKY_CHAOS_PLAN``
— a JSON object, or a path to a JSON file (handy for subprocess trees:
the env var is inherited by spawned controllers/job runners)::

    {
      "seed": 7,
      "points": {
        "gang.host_start":   {"first_n": 1, "returncode": 255},
        "jobs.status_probe": {"skip_first": 2, "first_n": 3,
                              "error": "TimeoutError", "latency_s": 0.05},
        "runner.run":        {"probability": 0.05,
                              "error": "ConnectionError"},
        "failover.wait_instances": [{"every_kth": 3,
                                     "error": "CapacityError"}]
      }
    }

Each point maps to one rule or a list of rules (evaluated in order; the
first rule whose selectors match fires). Hit numbers are 1-based and
per-process.

Selectors (ANDed within a rule):
  ``probability``  fire with this probability (seeded RNG → deterministic)
  ``first_n``      fire only on the first N eligible hits
  ``every_kth``    fire when the eligible hit number is a multiple of K
  ``skip_first``   the first N hits are never eligible
  ``match``        ``{ctx_key: value}`` — only hits whose call-site
                   context matches (e.g. ``{"rank": 0}``). Non-matching
                   hits do not advance the rule's hit numbering, so
                   ``{"match": {"rank": 1}, "first_n": 1}`` fires on
                   rank 1's first traversal no matter how many other
                   ranks hit the point before it.

Actions (applied when a rule fires):
  ``latency_s``    sleep this long before returning/raising
  ``error``        raise this exception type (resolved from
                   ``skypilot_tpu.exceptions``, then builtins; unknown
                   names raise :class:`ChaosError`)
  ``signal``       send this signal (name like ``"SIGKILL"`` or a
                   number) to the CURRENT process — a ``kill -9`` of a
                   controller mid-flight, for crash-safety drills. The
                   journal row is written before the signal lands, so a
                   SIGKILL still leaves its trace.
  anything else    returned to the call site in the fired rule dict for
                   site-specific handling (e.g. ``returncode`` makes the
                   gang launcher start ``exit <rc>`` instead of the real
                   command; ``fake.preempt`` terminates the cluster).

Every fire is appended to the recovery-event journal
(``state.record_recovery_event``) as ``chaos.injected`` with the point
name as scope, so tests and ``xsky events`` can correlate injected
faults with the recovery they triggered.
"""
from __future__ import annotations

import json
import logging
import os
import random
import threading
import time
from typing import Any, Dict, List, Optional

logger = logging.getLogger(__name__)

_lock = threading.Lock()
_plan: Optional['_Plan'] = None
_plan_src: Optional[str] = None   # env value the cached plan was parsed from
_direct = False                   # plan installed via load_plan(), not env
_bad_src: Optional[str] = None    # env value that failed to parse


class ChaosError(Exception):
    """Injected failure whose rule names no (or an unknown) error type."""


class ChaosPlanError(ValueError):
    """XSKY_CHAOS_PLAN is not valid JSON / not readable."""


def _resolve_signal(sig) -> int:
    import signal as signal_lib
    if isinstance(sig, str):
        num = getattr(signal_lib, sig, None)
        if num is None:
            raise ChaosError(f'unknown signal name {sig!r}')
        return int(num)
    return int(sig)


def _resolve_error(name: str) -> type:
    from skypilot_tpu import exceptions as exceptions_lib
    cls = getattr(exceptions_lib, name, None)
    if cls is None:
        import builtins
        cls = getattr(builtins, name, None)
    if not (isinstance(cls, type) and issubclass(cls, BaseException)):
        return ChaosError
    return cls


class _Plan:

    def __init__(self, config: Dict[str, Any]) -> None:
        points = config.get('points') or {}
        self.rules: Dict[str, List[Dict[str, Any]]] = {
            point: list(rule) if isinstance(rule, list) else [rule]
            for point, rule in points.items()
        }
        self.rng = random.Random(config.get('seed'))
        self._lock = threading.Lock()
        self.hit_counts: Dict[str, int] = {}
        self.fired_counts: Dict[str, int] = {}
        # (point, rule index) → hits whose `match` selector passed; this
        # is the hit number skip_first/first_n/every_kth count against.
        self._rule_hits: Dict[Any, int] = {}

    def fire(self, point: str, ctx: Dict[str, Any]
             ) -> Optional[Dict[str, Any]]:
        with self._lock:
            hit = self.hit_counts.get(point, 0) + 1
            self.hit_counts[point] = hit
            rule = None
            for idx, r in enumerate(self.rules.get(point, ())):
                m = r.get('match')
                if m and any(ctx.get(k) != v for k, v in m.items()):
                    continue
                # Every matching rule's numbering advances on every
                # matching hit, fired or not — rule order never warps
                # another rule's skip_first/every_kth arithmetic.
                rhit = self._rule_hits.get((point, idx), 0) + 1
                self._rule_hits[(point, idx)] = rhit
                if rule is None and self._selected(r, rhit):
                    rule = r
            if rule is not None:
                self.fired_counts[point] = \
                    self.fired_counts.get(point, 0) + 1
        if rule is None:
            return None
        latency = rule.get('latency_s')
        measured_s = None
        if latency:
            # Journal the MEASURED delay, not the configured one: an
            # oversleeping host (cgroup throttling, a loaded box) is
            # exactly the signal a latency drill exists to surface.
            t0 = time.monotonic()
            time.sleep(float(latency))
            measured_s = time.monotonic() - t0
        _journal(point, rule, ctx, measured_s)
        sig = rule.get('signal')
        if sig is not None:
            # Crash drill: the journal row above is already committed,
            # so even SIGKILL (unhandleable) leaves its trace.
            os.kill(os.getpid(), _resolve_signal(sig))
        error = rule.get('error')
        if error:
            raise _resolve_error(error)(
                f'chaos: injected {error} at {point} (hit {hit})')
        return dict(rule)

    def _selected(self, rule: Dict[str, Any], hit: int) -> bool:
        eligible = hit - int(rule.get('skip_first', 0))
        if eligible < 1:
            return False
        if 'first_n' in rule and eligible > int(rule['first_n']):
            return False
        if 'every_kth' in rule and eligible % int(rule['every_kth']) != 0:
            return False
        if 'probability' in rule and \
                self.rng.random() >= float(rule['probability']):
            return False
        return True


def _journal(point: str, rule: Dict[str, Any], ctx: Dict[str, Any],
             measured_latency_s: Optional[float] = None) -> None:
    """Record the injected fault; never let observability kill the path.

    ``measured_latency_s`` is the actually-injected sleep (measured at
    the call site), journalled as the row's latency and attached to
    the active trace span — NOT the plan's configured value.
    """
    if rule.get('error'):
        cause = rule['error']
    elif 'signal' in rule:
        cause = f'signal={rule["signal"]}'
    elif 'returncode' in rule:
        cause = f'returncode={rule["returncode"]}'
    else:
        cause = 'latency' if rule.get('latency_s') else 'fired'
    try:
        from skypilot_tpu import state
        state.record_recovery_event(
            'chaos.injected', scope=f'chaos/{point}', cause=cause,
            latency_s=measured_latency_s,
            detail={k: v for k, v in ctx.items()
                    if isinstance(v, (str, int, float, bool))} or None)
    except Exception:  # pylint: disable=broad-except
        pass
    try:
        # Cross-link: the span this fault fired under carries every
        # chaos fire (point, cause, measured latency), and /metrics
        # counts fires by point.
        from skypilot_tpu.utils import metrics
        from skypilot_tpu.utils import tracing
        fire = {'point': point, 'cause': cause}
        if measured_latency_s is not None:
            fire['latency_s'] = round(measured_latency_s, 6)
        tracing.annotate_append('chaos_fires', fire)
        metrics.inc_counter('xsky_chaos_fires_total',
                            'Chaos rules fired, by point.', 1.0,
                            point=point)
    except Exception:  # pylint: disable=broad-except
        pass


def _parse(src: str) -> '_Plan':
    text = src.strip()
    if not text.startswith('{'):
        try:
            with open(os.path.expanduser(text), encoding='utf-8') as f:
                text = f.read()
        except OSError as e:
            raise ChaosPlanError(
                f'XSKY_CHAOS_PLAN file unreadable: {e}') from e
    try:
        config = json.loads(text)
    except ValueError as e:
        raise ChaosPlanError(f'XSKY_CHAOS_PLAN is not valid JSON: {e}') \
            from e
    if not isinstance(config, dict):
        raise ChaosPlanError('XSKY_CHAOS_PLAN must be a JSON object.')
    return _Plan(config)


def _current_plan() -> Optional['_Plan']:
    global _plan, _plan_src, _bad_src
    if _direct:
        return _plan
    src = os.environ.get('XSKY_CHAOS_PLAN')
    if not src:
        if _plan is not None:
            with _lock:
                if not _direct:
                    _plan, _plan_src = None, None
        return None
    if src == _bad_src:
        return None
    if src != _plan_src:
        with _lock:
            if src != _plan_src and src != _bad_src and not _direct:
                try:
                    _plan = _parse(src)
                    _plan_src = src
                except ChaosPlanError as e:
                    # A typo'd plan must never take down the recovery
                    # paths it instruments: log once, run chaos-free.
                    # (Counters stay empty, so a test driving a broken
                    # plan still fails loudly on its hit assertions.)
                    _bad_src = src
                    _plan, _plan_src = None, None
                    logger.error('Ignoring XSKY_CHAOS_PLAN: %s', e)
    return _plan


# ---- call-site API ---------------------------------------------------------


def inject(point: str, **ctx: Any) -> Optional[Dict[str, Any]]:
    """Evaluate the chaos point. Returns the fired rule dict (after
    applying latency and raising any configured error), or None.

    With no plan loaded this returns immediately without touching
    counters — instrumented hot paths pay one env lookup.
    """
    plan = _current_plan()
    if plan is None:
        return None
    return plan.fire(point, ctx)


def enabled() -> bool:
    return _current_plan() is not None


# ---- test / observability API ---------------------------------------------


def load_plan(config: Dict[str, Any]) -> None:
    """Install a plan programmatically (in-process tests). Pair with
    :func:`clear` — a directly-loaded plan shadows the env var."""
    global _plan, _plan_src, _direct
    with _lock:
        _plan = _Plan(config)
        _plan_src = None
        _direct = True


def clear() -> None:
    """Drop any loaded plan and all counters."""
    global _plan, _plan_src, _direct, _bad_src
    with _lock:
        _plan, _plan_src, _direct, _bad_src = None, None, False, None


def counters() -> Dict[str, int]:
    """Point → times the point was traversed (this process). Empty when
    no plan is loaded — the zero-overhead-when-disabled assertion."""
    plan = _current_plan()
    if plan is None:
        return {}
    with plan._lock:  # pylint: disable=protected-access
        return dict(plan.hit_counts)


def fired() -> Dict[str, int]:
    """Point → times a rule actually fired (this process)."""
    plan = _current_plan()
    if plan is None:
        return {}
    with plan._lock:  # pylint: disable=protected-access
        return dict(plan.fired_counts)


def hits(point: str) -> int:
    return counters().get(point, 0)
