"""TLS termination (twin of the reference's service-spec `tls:` →
sky/serve/load_balancer.py:251 uvicorn ssl kwargs, and api-server
HTTPS). Real sockets: a self-signed cert, a real replica process, and
an https:// client round trip."""
import json
import ssl
import subprocess
import threading
import urllib.request
from http.server import BaseHTTPRequestHandler, HTTPServer

import pytest

from skypilot_tpu.serve import load_balancer as lb_lib
from skypilot_tpu.serve import service_spec as spec_lib


@pytest.fixture(scope='module')
def cert_pair(tmp_path_factory):
    d = tmp_path_factory.mktemp('tls')
    cert, key = str(d / 'cert.pem'), str(d / 'key.pem')
    subprocess.run(
        ['openssl', 'req', '-x509', '-newkey', 'rsa:2048', '-nodes',
         '-keyout', key, '-out', cert, '-days', '1', '-subj',
         '/CN=localhost'], check=True, capture_output=True)
    return cert, key


def _client_ctx():
    ctx = ssl.create_default_context()
    ctx.check_hostname = False
    ctx.verify_mode = ssl.CERT_NONE   # self-signed test cert
    return ctx


def _upstream():
    class H(BaseHTTPRequestHandler):
        def log_message(self, *a):
            pass

        def do_GET(self):
            body = b'{"ok": true}'
            self.send_response(200)
            self.send_header('Content-Length', str(len(body)))
            self.end_headers()
            self.wfile.write(body)

    server = HTTPServer(('127.0.0.1', 0), H)
    threading.Thread(target=server.serve_forever, daemon=True).start()
    return server


def test_spec_tls_round_trip_and_validation():
    spec = spec_lib.SkyServiceSpec.from_yaml_config({
        'readiness_probe': '/health',
        'tls': {'certfile': '~/c.pem', 'keyfile': '~/k.pem'},
    })
    assert spec.tls_enabled
    config = spec.to_yaml_config()
    assert config['tls'] == {'certfile': '~/c.pem',
                             'keyfile': '~/k.pem'}
    again = spec_lib.SkyServiceSpec.from_yaml_config(config)
    assert again.tls_certfile == '~/c.pem'
    with pytest.raises(ValueError, match='BOTH'):
        spec_lib.SkyServiceSpec.from_yaml_config(
            {'tls': {'certfile': 'only.pem'}})


def test_load_balancer_terminates_tls(cert_pair):
    cert, key = cert_pair
    upstream = _upstream()
    lb = lb_lib.SkyServeLoadBalancer()
    lb.set_ready_replicas(
        [f'127.0.0.1:{upstream.server_address[1]}'])
    port = lb.run_in_thread(certfile=cert, keyfile=key)
    try:
        with urllib.request.urlopen(f'https://127.0.0.1:{port}/x',
                                    context=_client_ctx(),
                                    timeout=10) as resp:
            assert json.load(resp) == {'ok': True}
        # Plain HTTP against the TLS port must fail, not silently work.
        with pytest.raises(Exception):
            urllib.request.urlopen(f'http://127.0.0.1:{port}/x',
                                   timeout=5)
    finally:
        lb.shutdown()
        upstream.shutdown()


def test_api_server_https(cert_pair):
    cert, key = cert_pair
    from skypilot_tpu.server import app as server_app
    server = server_app.make_server('127.0.0.1', 0,
                                    tls_certfile=cert,
                                    tls_keyfile=key)
    port = server.server_address[1]
    threading.Thread(target=server.serve_forever, daemon=True).start()
    try:
        with urllib.request.urlopen(f'https://127.0.0.1:{port}/health',
                                    context=_client_ctx(),
                                    timeout=10) as resp:
            payload = json.load(resp)
        assert payload['status'] == 'healthy'
    finally:
        server.shutdown()


def test_stalled_handshake_does_not_block_other_clients(cert_pair):
    """A client that opens TCP and never sends a ClientHello must not
    freeze the accept loop (do_handshake_on_connect=False defers the
    handshake into the per-connection handler thread)."""
    import socket
    cert, key = cert_pair
    from skypilot_tpu.server import app as server_app
    server = server_app.make_server('127.0.0.1', 0,
                                    tls_certfile=cert,
                                    tls_keyfile=key)
    port = server.server_address[1]
    threading.Thread(target=server.serve_forever, daemon=True).start()
    stalled = socket.create_connection(('127.0.0.1', port), timeout=10)
    try:
        # With handshake-on-accept this urlopen would hang behind the
        # stalled connection and time out.
        with urllib.request.urlopen(f'https://127.0.0.1:{port}/health',
                                    context=_client_ctx(),
                                    timeout=10) as resp:
            assert json.load(resp)['status'] == 'healthy'
    finally:
        stalled.close()
        server.shutdown()


def test_serve_status_reports_https_endpoint(monkeypatch, tmp_path):
    from skypilot_tpu.serve import core as serve_core
    from skypilot_tpu.serve import state as serve_state
    monkeypatch.setenv('XSKY_SERVE_DB', str(tmp_path / 's.db'))
    serve_state.add_service(
        'tls-svc',
        {'run': 'x', 'service': {
            'tls': {'certfile': 'c.pem', 'keyfile': 'k.pem'}}},
        8443)
    serve_state.add_service('plain-svc', {'run': 'x', 'service': {}},
                            8080)
    by_name = {s['name']: s for s in serve_core.status()}
    assert by_name['tls-svc']['endpoint'] == 'https://127.0.0.1:8443'
    assert by_name['plain-svc']['endpoint'] == '127.0.0.1:8080'
