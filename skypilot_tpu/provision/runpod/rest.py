"""RunPod GraphQL transport (urllib, no SDK).

Role-twin of the reference's runpod SDK usage
(sky/provision/runpod/utils.py, sky/provision/runpod/api/commands.py),
redesigned to match this repo's transport pattern
(provision/{aws,azure,gcp,lambda_cloud}/rest.py): one `call()` with
typed error classification the failover engine consumes directly.
RunPod's API is GraphQL-over-HTTP; queries are sent with JSON
variables (not string-interpolated into the document) so values never
need GraphQL escaping.
"""
from __future__ import annotations

import json
import os
import time
import urllib.error
import urllib.parse
import urllib.request
from typing import Any, Dict, Optional

from skypilot_tpu import exceptions

API_URL = 'https://api.runpod.io/graphql'
CONFIG_PATH = '~/.runpod/config.toml'
_MAX_ATTEMPTS = 4
_BACKOFF_S = 2.0


class RunPodApiError(Exception):

    def __init__(self, status: int, message: str) -> None:
        super().__init__(f'{status}: {message}')
        self.status = status
        self.message = message


def load_api_key() -> Optional[str]:
    """$RUNPOD_API_KEY, else the SDK-compatible config file
    (`api_key = "..."` in ~/.runpod/config.toml)."""
    key = os.environ.get('RUNPOD_API_KEY')
    if key:
        return key
    path = os.path.expanduser(CONFIG_PATH)
    if not os.path.exists(path):
        return None
    try:
        with open(path, encoding='utf-8') as f:
            for line in f:
                field, sep, value = line.partition('=')
                if sep and field.strip() == 'api_key':
                    return value.strip().strip('"\'') or None
    except OSError:
        return None
    return None


def classify_error(e: RunPodApiError,
                   region: Optional[str] = None) -> Exception:
    """Map RunPod errors onto the failover engine's taxonomy."""
    text = e.message.lower()
    where = f' in {region}' if region else ''
    if ('no longer any instances available' in text
            or 'no instances' in text or 'not enough' in text
            or 'no gpu found' in text or 'unavailable' in text):
        return exceptions.CapacityError(f'RunPod capacity{where}: {e}')
    if 'quota' in text or 'limit' in text and 'spend' in text:
        return exceptions.QuotaExceededError(f'RunPod quota{where}: {e}')
    if (e.status in (401, 403) or 'unauthorized' in text
            or 'not authenticated' in text):
        return exceptions.PermissionError_(f'RunPod auth: {e}')
    if e.status == 400:
        return exceptions.InvalidRequestError(f'RunPod request: {e}')
    return exceptions.ProvisionError(f'RunPod API{where}: {e}')


class Transport:

    def __init__(self, api_key: Optional[str] = None) -> None:
        key = api_key or load_api_key()
        if not key:
            raise exceptions.PermissionError_(
                'RunPod API key not found (set $RUNPOD_API_KEY or '
                f'populate {CONFIG_PATH}).')
        self._key = key

    def call(self, query: str,
             variables: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
        """POST one GraphQL document; return its `data` object.

        GraphQL transports errors two ways — HTTP status for transport
        problems and a 200 + `errors` array for field errors — both are
        normalized to RunPodApiError here.
        """
        body = json.dumps({'query': query,
                           'variables': variables or {}}).encode()
        url = f'{API_URL}?api_key={urllib.parse.quote(self._key)}'
        for attempt in range(_MAX_ATTEMPTS):
            req = urllib.request.Request(
                url, data=body, method='POST',
                headers={'Content-Type': 'application/json'})
            try:
                with urllib.request.urlopen(req, timeout=60) as resp:
                    payload = json.loads(resp.read() or b'{}')
            except urllib.error.HTTPError as e:
                if e.code in (429, 502, 503) and attempt < _MAX_ATTEMPTS - 1:
                    time.sleep(_BACKOFF_S * (attempt + 1))
                    continue
                raise RunPodApiError(e.code, str(e)) from e
            except urllib.error.URLError as e:
                raise exceptions.ProvisionError(
                    f'RunPod API unreachable: {e}') from e
            errors = payload.get('errors')
            if errors:
                raise RunPodApiError(
                    200, '; '.join(err.get('message', str(err))
                                   for err in errors))
            return payload.get('data', {})
        raise exceptions.ProvisionError('RunPod API rate limit persisted.')
