// xsky native token loader.
//
// Keeps the MXU fed: memory-maps binary token shards (little-endian
// uint32 token streams), builds a seeded-shuffled sample order each
// epoch, and fills batches [batch, seq+1] (inputs + next-token targets
// share the buffer) from background worker threads into a bounded ring
// so host-side input prep overlaps device steps.
//
// The reference framework leaves data loading to user recipes; this is
// the in-tree native equivalent (SURVEY: runtime/IO components are
// native where the reference's are). Exposed via a C ABI for ctypes —
// no pybind11 in the image.
//
// Build: g++ -O2 -shared -fPIC -std=c++17 -pthread dataloader.cc \
//        -o libxsky_dataloader.so
#include <algorithm>
#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <deque>
#include <mutex>
#include <numeric>
#include <random>
#include <thread>
#include <vector>

#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

struct Shard {
  const uint32_t* tokens = nullptr;
  size_t n_tokens = 0;
  int fd = -1;
  size_t map_bytes = 0;
};

struct Loader {
  std::vector<Shard> shards;
  std::vector<size_t> shard_offset;  // global token offset per shard
  size_t total_tokens = 0;

  int batch = 0;
  int seq = 0;
  long long seed = 0;
  int host_rank = 0;
  int num_hosts = 1;

  // Sample i = tokens [i*seq, i*seq + seq + 1).
  size_t n_samples = 0;

  // Bounded queue of ready batches.
  std::deque<std::vector<uint32_t>> ready;
  size_t max_ready = 4;
  std::mutex mu;
  std::condition_variable cv_ready;   // consumer waits
  std::condition_variable cv_space;   // producer waits
  std::vector<std::thread> workers;
  std::atomic<bool> stop{false};

  // Producer-side epoch state (guarded by prod_mu).
  std::mutex prod_mu;
  std::vector<uint64_t> order;
  size_t next_in_epoch = 0;
  long long epoch = 0;

  ~Loader() {
    for (auto& s : shards) {
      if (s.tokens) munmap(const_cast<uint32_t*>(s.tokens), s.map_bytes);
      if (s.fd >= 0) close(s.fd);
    }
  }
};

uint32_t token_at(const Loader& L, size_t idx) {
  // Global index -> (shard, local) via linear scan from a cached hint;
  // shards are few, samples are read as contiguous ranges below, so
  // this path is only a fallback for range-crossing reads.
  for (size_t s = 0; s < L.shards.size(); ++s) {
    size_t off = L.shard_offset[s];
    if (idx < off + L.shards[s].n_tokens)
      return L.shards[s].tokens[idx - off];
  }
  return 0;
}

void copy_range(const Loader& L, size_t start, size_t count,
                uint32_t* out) {
  // Fast path: whole range inside one shard -> memcpy.
  for (size_t s = 0; s < L.shards.size(); ++s) {
    size_t off = L.shard_offset[s];
    if (start >= off && start + count <= off + L.shards[s].n_tokens) {
      std::memcpy(out, L.shards[s].tokens + (start - off),
                  count * sizeof(uint32_t));
      return;
    }
  }
  for (size_t i = 0; i < count; ++i) out[i] = token_at(L, start + i);
}

void reshuffle_locked(Loader& L) {
  // Host-sharded epoch order: every host shuffles the same permutation
  // (same seed+epoch) and takes its strided slice, so data-parallel
  // hosts see disjoint samples without communication.
  std::vector<uint64_t> all(L.n_samples);
  std::iota(all.begin(), all.end(), 0);
  std::mt19937_64 rng(static_cast<uint64_t>(L.seed) * 1000003ull +
                      static_cast<uint64_t>(L.epoch));
  std::shuffle(all.begin(), all.end(), rng);
  L.order.clear();
  for (size_t i = L.host_rank; i < all.size();
       i += static_cast<size_t>(L.num_hosts))
    L.order.push_back(all[i]);
  L.next_in_epoch = 0;
}

bool fill_batch(Loader& L, std::vector<uint32_t>& out) {
  const size_t row = static_cast<size_t>(L.seq) + 1;
  out.resize(static_cast<size_t>(L.batch) * row);
  std::vector<uint64_t> picks(L.batch);
  {
    std::lock_guard<std::mutex> lk(L.prod_mu);
    for (int b = 0; b < L.batch; ++b) {
      if (L.next_in_epoch >= L.order.size()) {
        ++L.epoch;
        reshuffle_locked(L);
        if (L.order.empty()) return false;
      }
      picks[b] = L.order[L.next_in_epoch++];
    }
  }
  for (int b = 0; b < L.batch; ++b) {
    size_t start = picks[b] * static_cast<size_t>(L.seq);
    copy_range(L, start, row, out.data() + static_cast<size_t>(b) * row);
  }
  return true;
}

void worker_main(Loader* L) {
  while (!L->stop.load()) {
    std::vector<uint32_t> batch;
    if (!fill_batch(*L, batch)) {
      // Exhausted (empty host slice): wake consumers so xsky_dl_next
      // returns -1 instead of waiting forever.
      L->stop.store(true);
      std::lock_guard<std::mutex> lk(L->mu);
      L->cv_ready.notify_all();
      L->cv_space.notify_all();
      return;
    }
    std::unique_lock<std::mutex> lk(L->mu);
    L->cv_space.wait(lk, [L] {
      return L->stop.load() || L->ready.size() < L->max_ready;
    });
    if (L->stop.load()) return;
    L->ready.push_back(std::move(batch));
    L->cv_ready.notify_one();
  }
}

}  // namespace

extern "C" {

// Returns an opaque handle (>0) or 0 on failure.
void* xsky_dl_open(const char** paths, int n_paths, int batch, int seq,
                   long long seed, int n_workers, int host_rank,
                   int num_hosts) {
  if (n_paths <= 0 || batch <= 0 || seq <= 0 || num_hosts <= 0 ||
      host_rank < 0 || host_rank >= num_hosts)
    return nullptr;
  auto* L = new Loader();
  L->batch = batch;
  L->seq = seq;
  L->seed = seed;
  L->host_rank = host_rank;
  L->num_hosts = num_hosts;
  for (int i = 0; i < n_paths; ++i) {
    Shard s;
    s.fd = open(paths[i], O_RDONLY);
    if (s.fd < 0) { delete L; return nullptr; }
    struct stat st;
    if (fstat(s.fd, &st) != 0 || st.st_size < 4) {
      close(s.fd); delete L; return nullptr;
    }
    s.map_bytes = static_cast<size_t>(st.st_size);
    void* m = mmap(nullptr, s.map_bytes, PROT_READ, MAP_PRIVATE,
                   s.fd, 0);
    if (m == MAP_FAILED) { close(s.fd); delete L; return nullptr; }
    // Samples are read at shuffled offsets: random advice avoids
    // readahead churn on multi-GB shards.
    madvise(m, s.map_bytes, MADV_RANDOM);
    s.tokens = static_cast<const uint32_t*>(m);
    s.n_tokens = s.map_bytes / sizeof(uint32_t);
    L->shard_offset.push_back(L->total_tokens);
    L->total_tokens += s.n_tokens;
    L->shards.push_back(s);
  }
  if (L->total_tokens < static_cast<size_t>(seq) + 1) {
    delete L;
    return nullptr;
  }
  L->n_samples = (L->total_tokens - 1) / static_cast<size_t>(seq);
  {
    std::lock_guard<std::mutex> lk(L->prod_mu);
    reshuffle_locked(*L);
    if (L->order.empty()) {
      // This host's strided slice is empty (fewer samples than
      // hosts): fail fast rather than hang the gang.
      delete L;
      return nullptr;
    }
  }
  if (n_workers < 1) n_workers = 1;
  for (int i = 0; i < n_workers; ++i)
    L->workers.emplace_back(worker_main, L);
  return L;
}

// Blocking: copies one [batch, seq+1] uint32 batch into out.
// Returns 0 on success, -1 if the loader is stopped/exhausted.
int xsky_dl_next(void* handle, uint32_t* out) {
  auto* L = static_cast<Loader*>(handle);
  std::vector<uint32_t> batch;
  {
    std::unique_lock<std::mutex> lk(L->mu);
    L->cv_ready.wait(lk, [L] {
      return L->stop.load() || !L->ready.empty();
    });
    if (L->ready.empty()) return -1;
    batch = std::move(L->ready.front());
    L->ready.pop_front();
    L->cv_space.notify_one();
  }
  std::memcpy(out, batch.data(), batch.size() * sizeof(uint32_t));
  return 0;
}

long long xsky_dl_num_samples(void* handle) {
  return static_cast<long long>(
      static_cast<Loader*>(handle)->n_samples);
}

void xsky_dl_close(void* handle) {
  auto* L = static_cast<Loader*>(handle);
  {
    // Under mu: a worker between its predicate check and blocking
    // would otherwise miss the notify and deadlock the join.
    std::lock_guard<std::mutex> lk(L->mu);
    L->stop.store(true);
    L->cv_ready.notify_all();
    L->cv_space.notify_all();
  }
  for (auto& t : L->workers) t.join();
  delete L;
}

}  // extern "C"
