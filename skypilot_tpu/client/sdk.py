"""Python SDK (twin of sky/client/sdk.py).

Two transports:
  * local (default): calls the engine in-process;
  * remote: posts to an API server (``XSKY_API_SERVER`` env or config key
    ``api_server.endpoint``) and polls the request until done — the
    async request-id model of the reference (sky/client/sdk.py:360,1689).
"""
from __future__ import annotations

import os
import shlex
from typing import Any, Dict, List, Optional, Tuple, Union

from skypilot_tpu import config as config_lib
from skypilot_tpu import dag as dag_lib
from skypilot_tpu import exceptions
from skypilot_tpu import task as task_lib


def api_server_endpoint() -> Optional[str]:
    return os.environ.get('XSKY_API_SERVER') or config_lib.get_nested(
        ('api_server', 'endpoint'))


def _remote():
    endpoint = api_server_endpoint()
    if endpoint is None:
        return None
    from skypilot_tpu.client import remote_client
    return remote_client.RemoteClient(endpoint)


# ---- verbs ----------------------------------------------------------------


def launch(task: Union[task_lib.Task, dag_lib.Dag],
           cluster_name: Optional[str] = None,
           retry_until_up: bool = False,
           idle_minutes_to_autostop: Optional[int] = None,
           down: bool = False,
           dryrun: bool = False,
           detach_run: bool = False,
           no_setup: bool = False) -> Tuple[Optional[int], Optional[Any]]:
    remote = _remote()
    if remote is not None:
        return remote.launch(task, cluster_name=cluster_name,
                             retry_until_up=retry_until_up,
                             idle_minutes_to_autostop=(
                                 idle_minutes_to_autostop),
                             down=down, dryrun=dryrun,
                             detach_run=detach_run, no_setup=no_setup)
    from skypilot_tpu import execution
    return execution.launch(task, cluster_name=cluster_name,
                            retry_until_up=retry_until_up,
                            idle_minutes_to_autostop=(
                                idle_minutes_to_autostop),
                            down=down, dryrun=dryrun,
                            detach_run=detach_run, no_setup=no_setup)


def exec(task: task_lib.Task,  # pylint: disable=redefined-builtin
         cluster_name: str,
         detach_run: bool = False,
         dryrun: bool = False) -> Tuple[Optional[int], Optional[Any]]:
    remote = _remote()
    if remote is not None:
        return remote.exec(task, cluster_name, detach_run=detach_run,
                           dryrun=dryrun)
    from skypilot_tpu import execution
    return execution.exec(task, cluster_name, detach_run=detach_run,
                          dryrun=dryrun)


def _local_or_remote(name: str, *args, **kwargs):
    remote = _remote()
    if remote is not None:
        return getattr(remote, name)(*args, **kwargs)
    from skypilot_tpu import core
    return getattr(core, name)(*args, **kwargs)


def status(cluster_names: Optional[List[str]] = None,
           refresh: bool = False,
           limit: Optional[int] = None,
           offset: int = 0) -> List[Dict[str, Any]]:
    """limit/offset page the fleet listing server-side (stable order:
    newest launch first, then name) — at 5k clusters the full listing
    is a debugging tool, not a default."""
    return _local_or_remote('status', cluster_names=cluster_names,
                            refresh=refresh, limit=limit, offset=offset)


def start(cluster_name: str,
          idle_minutes_to_autostop: Optional[int] = None,
          down: bool = False) -> None:
    return _local_or_remote('start', cluster_name,
                            idle_minutes_to_autostop=(
                                idle_minutes_to_autostop), down=down)


def stop(cluster_name: str) -> None:
    return _local_or_remote('stop', cluster_name)


def down(cluster_name: str, purge: bool = False) -> None:
    return _local_or_remote('down', cluster_name, purge=purge)


def autostop(cluster_name: str, idle_minutes: int,
             down: bool = False) -> None:  # noqa: A002
    return _local_or_remote('autostop', cluster_name, idle_minutes,
                            down_on_idle=down)


def queue(cluster_name: str) -> List[Dict[str, Any]]:
    return _local_or_remote('queue', cluster_name)


def cluster_hosts(cluster_name: str) -> List[Dict[str, Any]]:
    """Per-host inventory (live provider status when reachable)."""
    return _local_or_remote('cluster_hosts', cluster_name)


def profile_capture(cluster_name: str, job_id: Optional[int] = None,
                    duration_s: float = 1.0) -> Dict[int, Dict[str, Any]]:
    """On-demand deep device capture on every host (dispatch RTT,
    device step time, compile probe, HBM stats + a jax.profiler trace
    left on each host): {rank: summary}, recorded for `xsky profile`."""
    return _local_or_remote('profile_capture', cluster_name,
                            job_id=job_id, duration_s=duration_s)


def goodput_report(cluster_name: Optional[str] = None,
                   fleet: bool = False,
                   limit: int = 1000) -> Dict[str, Any]:
    """Goodput attribution: a live per-incarnation ledger for one
    cluster (every wall-clock second decomposed by cause), or the
    fleet rollup of the latest persisted ledgers."""
    return _local_or_remote('goodput_report', cluster_name,
                            fleet=fleet, limit=limit)


def metrics_list(prefix: Optional[str] = None,
                 since: Optional[float] = None,
                 limit: int = 200,
                 offset: int = 0) -> List[Dict[str, Any]]:
    """Recorded metric series (names, label sets, point counts) from
    the metrics history plane."""
    return _local_or_remote('metrics_list', prefix=prefix, since=since,
                            limit=limit, offset=offset)


def metrics_query(name: str,
                  labels: Optional[Dict[str, Any]] = None,
                  since: Optional[float] = None,
                  until: Optional[float] = None,
                  step: Optional[float] = None,
                  agg: str = 'avg',
                  res: Optional[str] = None) -> Dict[str, Any]:
    """Trend query over recorded metric points: bucketed avg/min/max/
    sum/count/last, counter-aware rate, windowed histogram quantiles
    (p50/p90/p95/p99)."""
    return _local_or_remote('metrics_query', name, labels=labels,
                            since=since, until=until, step=step,
                            agg=agg, res=res)


def endpoints(cluster_name: str,
              port: Optional[int] = None) -> Dict[int, str]:
    """port → URL for the cluster's opened ports."""
    return _local_or_remote('endpoints', cluster_name, port=port)


def storage_ls_objects(storage_name: str, prefix: str = '',
                       limit: int = 100) -> List[str]:
    """First `limit` object keys of a storage's primary store."""
    return _local_or_remote('storage_ls_objects', storage_name,
                            prefix=prefix, limit=limit)


def cancel(cluster_name: str, job_ids: Optional[List[int]] = None,
           all_jobs: bool = False) -> None:
    return _local_or_remote('cancel', cluster_name, job_ids=job_ids,
                            all_jobs=all_jobs)


def tail_logs(cluster_name: str, job_id: Optional[int] = None,
              follow: bool = False, all_ranks: bool = False) -> str:
    return _local_or_remote('tail_logs', cluster_name, job_id=job_id,
                            follow=follow, all_ranks=all_ranks)


def sync_down_logs(cluster_name: str, job_id: Optional[int] = None,
                   local_dir: Optional[str] = None) -> str:
    """Download a cluster's job logs to this machine."""
    remote = _remote()
    if remote is not None:
        # File transfer to the *client* machine needs direct runner
        # access; the API server only relays JSON. Run against a local
        # server (xsky api start) or unset the remote endpoint.
        raise exceptions.NotSupportedError(
            'logs --sync-down is not supported through a remote API '
            'server; run it on the API-server host.')
    from skypilot_tpu import core as core_lib
    return core_lib.sync_down_logs(cluster_name, job_id=job_id,
                                   local_dir=local_dir)


def _ssh_argv_for_runner(runner, command: Optional[List[str]]
                         ) -> Tuple[List[str], Optional[str]]:
    from skypilot_tpu.utils import command_runner as runner_lib
    if isinstance(runner, runner_lib.LocalProcessCommandRunner):
        argv = ['bash']
        if command:
            argv += ['-c', ' '.join(shlex.quote(c)
                                    for c in command)]
        return argv, runner.host_root
    if isinstance(runner, runner_lib.SSHCommandRunner):
        # Reuse the runner's option set (key, port, known-hosts,
        # keepalives, jump-host ProxyCommand) — interactive sessions
        # must reach the host the same way lifecycle ops do.
        argv = runner.ssh_base()
        if not runner.ssh_proxy_command:
            endpoint = api_server_endpoint()
            if endpoint:
                # No provisioner jump host: ride the API server's
                # CONNECT tunnel (heads without public IPs).
                import sys
                proxy = (f'{shlex.quote(sys.executable)} -m '
                         f'skypilot_tpu.templates.tunnel_proxy %h %p '
                         f'--server {endpoint}')
                argv += ['-o', f'ProxyCommand={proxy}']
        argv.append(f'{runner.ssh_user}@{runner.ip}')
        if command:
            # The remote shell re-splits whatever ssh sends: quote each
            # word so 'echo a b' and literal '&&' survive intact (same
            # contract as the local-runner path above).
            argv.append(' '.join(shlex.quote(c) for c in command))
        return argv, None
    if isinstance(runner, runner_lib.KubernetesCommandRunner):
        base = runner.kubectl_base() + ['exec']
        if command:
            return (base + ['-c', runner.container, runner.pod_name,
                            '--'] + list(command), None)
        return (base + ['-it', '-c', runner.container, runner.pod_name,
                        '--', 'bash'], None)
    raise exceptions.NotSupportedError(
        f'ssh not supported for {type(runner).__name__}.')


def ssh_command(cluster_name: str,
                command: Optional[List[str]] = None
                ) -> Tuple[List[str], Optional[str]]:
    """(argv, cwd) opening a shell (or running `command`) on the head.

    Twin of `sky ssh`: direct ssh when the head is reachable; with a
    remote API server configured, the connection rides the server's
    CONNECT tunnel via ProxyCommand (templates/tunnel_proxy). Local/fake
    clusters get a bash rooted at the host's scratch dir so the verb is
    exercisable in tests.

    Remote-server mode requires the cluster's ssh key to exist on this
    machine (keys are not transferred over the API).
    """
    from skypilot_tpu import state as state_lib
    record = state_lib.get_cluster_from_name(cluster_name)
    if record is None or record['handle'] is None:
        if _remote() is not None:
            raise exceptions.NotSupportedError(
                f'Cluster {cluster_name!r} is not in the local state '
                'DB. `xsky ssh` against a remote API server needs the '
                'cluster record (and its ssh key) on this machine — '
                'run it on the API-server host, or launch from here.')
        raise exceptions.ClusterDoesNotExist(cluster_name)
    if record['status'] != state_lib.ClusterStatus.UP:
        raise exceptions.ClusterNotUpError(
            f'Cluster {cluster_name!r} is {record["status"].value}.',
            cluster_status=record['status'])
    return _ssh_argv_for_runner(record['handle'].head_runner(), command)


def check(quiet: bool = False) -> Dict[str, Any]:
    return _local_or_remote('check', quiet=quiet)


def cost_report() -> List[Dict[str, Any]]:
    return _local_or_remote('cost_report')


def storage_ls() -> List[Dict[str, Any]]:
    return _local_or_remote('storage_ls')


def storage_delete(storage_name: str) -> None:
    return _local_or_remote('storage_delete', storage_name)


# ---- managed jobs ----------------------------------------------------------


def jobs_launch(task, name: Optional[str] = None,
                priority: int = 0) -> int:
    """task: one Task, or a sequence of Tasks (pipeline chain).
    ``priority``: fleet-scheduler admission priority (higher first)."""
    remote = _remote()
    if remote is not None:
        return remote.jobs_launch(task, name=name, priority=priority)
    from skypilot_tpu.jobs import core as jobs_core
    return jobs_core.launch(task, name=name, priority=priority)


def jobs_queue() -> List[Dict[str, Any]]:
    remote = _remote()
    if remote is not None:
        return remote.jobs_queue()
    from skypilot_tpu.jobs import core as jobs_core
    return jobs_core.queue()


def jobs_cancel(job_id: int) -> None:
    remote = _remote()
    if remote is not None:
        return remote.jobs_cancel(job_id)
    from skypilot_tpu.jobs import core as jobs_core
    return jobs_core.cancel(job_id)


def jobs_logs(job_id: int) -> str:
    remote = _remote()
    if remote is not None:
        return remote.jobs_logs(job_id)
    from skypilot_tpu.jobs import core as jobs_core
    return jobs_core.tail_logs(job_id)


def jobs_watch_logs(job_id: int, offset: int = 0) -> Dict[str, Any]:
    """One incremental managed-job log poll → {status, offset, data,
    epoch} (epoch changes when recovery swaps the task cluster)."""
    remote = _remote()
    if remote is not None:
        return remote._call('jobs.watch_logs',
                            {'job_id': job_id, 'offset': offset})
    from skypilot_tpu.jobs import core as jobs_core
    return jobs_core.watch_logs(job_id, offset=offset)


# ---- serve -----------------------------------------------------------------


def serve_up(task: task_lib.Task,
             service_name: Optional[str] = None) -> str:
    remote = _remote()
    if remote is not None:
        return remote.serve_up(task, service_name=service_name)
    from skypilot_tpu.serve import core as serve_core
    return serve_core.up(task, service_name)


def serve_update(task: task_lib.Task, service_name: str,
                 mode: str = 'rolling') -> int:
    """Update a live service (rolling | blue_green); returns the new
    version."""
    remote = _remote()
    if remote is not None:
        return remote.serve_update(task, service_name, mode=mode)
    from skypilot_tpu.serve import core as serve_core
    return serve_core.update(task, service_name, mode=mode)


def serve_status(service_names: Optional[List[str]] = None
                 ) -> List[Dict[str, Any]]:
    remote = _remote()
    if remote is not None:
        return remote.serve_status(service_names)
    from skypilot_tpu.serve import core as serve_core
    return serve_core.status(service_names)


def serve_logs(service_name: str, replica_id: int,
               job_id: Optional[int] = None) -> str:
    remote = _remote()
    if remote is not None:
        return remote._call('serve.logs', {
            'service_name': service_name, 'replica_id': replica_id,
            'job_id': job_id})
    from skypilot_tpu.serve import core as serve_core
    return serve_core.tail_logs(service_name, replica_id, job_id=job_id)


def serve_controller_logs(service_name: str) -> str:
    """The service controller's own stdout/stderr (crash diagnostics)."""
    remote = _remote()
    if remote is not None:
        return remote._call('serve.controller_logs',
                            {'service_name': service_name})
    from skypilot_tpu.serve import core as serve_core
    return serve_core.controller_logs(service_name)


def serve_history(service_name: str,
                  limit: int = 720) -> List[Dict[str, Any]]:
    """Per-tick QPS / autoscaler-target / ready-replica trend."""
    remote = _remote()
    if remote is not None:
        return remote._call('serve.history', {
            'service_name': service_name, 'limit': limit})
    from skypilot_tpu.serve import core as serve_core
    return serve_core.metrics_history(service_name, limit=limit)


def accelerators(name_filter: Optional[str] = None,
                 gpus_only: bool = False) -> List[Dict[str, Any]]:
    """Accelerator offerings across all catalogs (show-gpus twin)."""
    remote = _remote()
    if remote is not None:
        return remote._call('accelerators', {
            'name_filter': name_filter, 'gpus_only': gpus_only})
    from skypilot_tpu import core as core_lib
    return core_lib.list_accelerators(name_filter=name_filter,
                                      gpus_only=gpus_only)


def serve_watch_logs(service_name: str, replica_id: int,
                     offset: int = 0) -> Dict[str, Any]:
    """One incremental replica-log poll → {status, offset, data,
    epoch, done} (same contract as jobs_watch_logs)."""
    remote = _remote()
    if remote is not None:
        return remote._call('serve.watch_logs', {
            'service_name': service_name, 'replica_id': replica_id,
            'offset': offset})
    from skypilot_tpu.serve import core as serve_core
    return serve_core.watch_replica_logs(service_name, replica_id,
                                         offset=offset)


def serve_down(service_name: str) -> None:
    remote = _remote()
    if remote is not None:
        return remote.serve_down(service_name)
    from skypilot_tpu.serve import core as serve_core
    return serve_core.down(service_name)


# ---- users / workspaces ----------------------------------------------------


def _module_local_or_remote(module_path: str, fn: str, remote_method: str,
                            *args, **kwargs):
    remote = _remote()
    if remote is not None:
        return getattr(remote, remote_method)(*args, **kwargs)
    import importlib
    mod = importlib.import_module(module_path)
    return getattr(mod, fn)(*args, **kwargs)


def users_list() -> List[Dict[str, Any]]:
    return _module_local_or_remote('skypilot_tpu.users.core', 'list_users',
                                   'users_list')


def users_create(name: str, password: str, role: str = 'user'):
    return _module_local_or_remote('skypilot_tpu.users.core',
                                   'create_user', 'users_create', name,
                                   password, role)


def users_delete(name: str):
    return _module_local_or_remote('skypilot_tpu.users.core',
                                   'delete_user', 'users_delete', name)


def users_set_role(name: str, role: str):
    return _module_local_or_remote('skypilot_tpu.users.core', 'set_role',
                                   'users_set_role', name, role)


def users_token_create(name: str, label: str = 'default'):
    """Mint a bearer token for API auth (plaintext returned once)."""
    return _module_local_or_remote('skypilot_tpu.users.core',
                                   'create_token', 'users_token_create',
                                   name, label)


def users_token_list(name: Optional[str] = None):
    return _module_local_or_remote('skypilot_tpu.users.core',
                                   'list_tokens', 'users_token_list',
                                   name)


def users_token_revoke(name: str, label: str):
    return _module_local_or_remote('skypilot_tpu.users.core',
                                   'revoke_token', 'users_token_revoke',
                                   name, label)


def workspaces_list() -> List[str]:
    return _module_local_or_remote('skypilot_tpu.workspaces.core',
                                   'get_workspaces', 'workspaces_list')


def workspaces_create(name: str):
    return _module_local_or_remote('skypilot_tpu.workspaces.core',
                                   'create_workspace', 'workspaces_create',
                                   name)


def workspaces_delete(name: str):
    return _module_local_or_remote('skypilot_tpu.workspaces.core',
                                   'delete_workspace', 'workspaces_delete',
                                   name)


def workspaces_add_member(workspace: str, user_name: str):
    return _module_local_or_remote('skypilot_tpu.workspaces.core',
                                   'add_member', 'workspaces_add_member',
                                   workspace, user_name)


def workspaces_remove_member(workspace: str, user_name: str):
    return _module_local_or_remote(
        'skypilot_tpu.workspaces.core', 'remove_member',
        'workspaces_remove_member', workspace, user_name)


def workspaces_members(workspace: str) -> List[str]:
    return _module_local_or_remote('skypilot_tpu.workspaces.core',
                                   'list_members', 'workspaces_members',
                                   workspace)


def workspaces_set_config(workspace: str, config: Dict[str, Any]):
    return _module_local_or_remote('skypilot_tpu.workspaces.core',
                                   'set_config', 'workspaces_set_config',
                                   workspace, config)


def workspaces_get_config(workspace: str) -> Dict[str, Any]:
    return _module_local_or_remote('skypilot_tpu.workspaces.core',
                                   'get_config', 'workspaces_get_config',
                                   workspace)


def api_info() -> Dict[str, Any]:
    """Server URL, health and identity (twin of `sky api info`,
    sky/client/cli/command.py:5156)."""
    remote = _remote()
    if remote is not None:
        info = remote.health()
        info.setdefault('status', 'unknown')
        info['url'] = remote.endpoint
        info['mode'] = 'remote'
        return info
    from skypilot_tpu import version
    from skypilot_tpu.server import app as server_app
    return {'url': None, 'mode': 'local', 'status': 'healthy',
            'version': version.__version__,
            'api_version': server_app.API_VERSION,
            'auth_required': False, 'user': None}


def ssh_up(infra: Optional[str] = None) -> Dict[str, Any]:
    """Bring up SSH node pool(s) (twin of `sky ssh up`)."""
    return _module_local_or_remote('skypilot_tpu.clouds.ssh', 'pool_up',
                                   'ssh_up', infra)


def ssh_down(infra: Optional[str] = None) -> Dict[str, Any]:
    """Tear down SSH node pool(s) (twin of `sky ssh down`)."""
    return _module_local_or_remote('skypilot_tpu.clouds.ssh', 'pool_down',
                                   'ssh_down', infra)
