"""xskylint: the control plane's static-analysis engine.

One ``ast.parse`` per file; every rule runs as a visitor over the
shared tree. The rules encode the distributed-systems contracts the
orchestrator survives by — gang-shaped fan-out, lease heartbeats,
bounded observability tables, never-raise recording paths, the env-var
registry, WAL-pool DB discipline — so every future PR is checked
against them mechanically instead of by reviewer memory.

Entry points::

    python -m tools.xskylint [paths...] [--json]
    xsky lint [paths...] [--json]

Suppression syntax (reason mandatory)::

    offending_line()   # xskylint: disable=<rule-id> -- <why exempt>

See docs/static-analysis.md for the rule catalog.
"""
from tools.xskylint.engine import (Finding, LintEngine, Rule, lint_paths,
                                   main)
from tools.xskylint.rules import all_rules

__all__ = ['Finding', 'LintEngine', 'Rule', 'all_rules', 'lint_paths',
           'main']
