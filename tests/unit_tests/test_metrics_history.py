"""Metrics history plane tests: the bounded multi-resolution
`metric_points` table (round trip, per-tier retention, torn-row
immunity, non-vacuous never-raise), downsampling math (gauge
avg/min/max, counter window-end values, 10m-from-1m folds, cursor
recovery), the trend query layer (bucketed aggs, counter-aware rate
across incarnation resets, windowed histogram quantiles, subset label
folds), the recorder tick over the REAL /metrics surface (snapshot and
text paths agree; TTFT-p99 and dispatch-gap series — the autoscaler
arc's inputs — retrievable), the journalled anomaly detectors
(transitions, chaos-forced arms, trace linkage), the CLI surfaces
(metrics list/query table+json+sparkline, top/slo --trend, the shared
duration parser), the `/metrics?name=` filter, the xskylint surface
over the new table, and the `tools/bench_metrics_history.py --smoke`
subprocess gate (recorder overhead at cardinality + the fake-cloud
anomaly drill)."""
import json
import os
import subprocess
import sys
import textwrap
import time
import urllib.request

import pytest

from skypilot_tpu.utils import chaos
from skypilot_tpu.utils import metrics as metrics_lib
from skypilot_tpu.utils import metrics_history

REPO_ROOT = os.path.abspath(
    os.path.join(os.path.dirname(__file__), '..', '..'))

T0 = 1_700_000_000.0 // 600 * 600   # minute- and 10m-aligned anchor


@pytest.fixture(autouse=True)
def _clean():
    metrics_lib.reset_for_test()
    metrics_history.reset_for_test()
    chaos.clear()
    yield
    metrics_lib.reset_for_test()
    metrics_history.reset_for_test()
    chaos.clear()


@pytest.fixture
def tmp_state(monkeypatch, tmp_path):
    from skypilot_tpu import state
    monkeypatch.setenv('XSKY_STATE_DB', str(tmp_path / 'state.db'))
    state.reset_for_test()
    yield state
    state.reset_for_test()


@pytest.fixture
def tmp_serve_db(monkeypatch, tmp_path):
    monkeypatch.setenv('XSKY_SERVE_DB', str(tmp_path / 'serve.db'))
    yield


def _gauge_points(state, name, values, labels=None, t0=None, dt=15.0,
                  kind='gauge'):
    t0 = T0 if t0 is None else t0
    state.record_metric_points(
        [{'ts': t0 + i * dt, 'name': name, 'labels': labels or {},
          'kind': kind, 'value': v} for i, v in enumerate(values)])


# ---- state table ------------------------------------------------------------


class TestMetricPointsTable:

    def test_round_trip_and_filters(self, tmp_state):
        tmp_state.record_metric_points([
            {'ts': T0, 'name': 'm_a', 'labels': {'rank': 0},
             'kind': 'gauge', 'value': 1.5},
            {'ts': T0 + 10, 'name': 'm_a', 'labels': {'rank': 1},
             'kind': 'gauge', 'value': 2.5},
            {'ts': T0 + 20, 'name': 'm_b', 'labels': {},
             'kind': 'counter', 'value': 7.0},
        ])
        rows = tmp_state.get_metric_points(name='m_a')
        assert [r['value'] for r in rows] == [1.5, 2.5]   # ts order
        assert rows[0]['labels'] == {'rank': '0'}
        only = tmp_state.get_metric_points(name='m_a',
                                           labels={'rank': 1})
        assert [r['value'] for r in only] == [2.5]
        since = tmp_state.get_metric_points(since=T0 + 15)
        assert [r['name'] for r in since] == ['m_b']
        listed = tmp_state.list_metric_series()
        assert {(s['name'], s['points']) for s in listed} == {
            ('m_a', 1), ('m_a', 1), ('m_b', 1)} or len(listed) == 3
        prefixed = tmp_state.list_metric_series(prefix='m_a')
        assert {s['name'] for s in prefixed} == {'m_a'}

    def test_canonical_labels_one_spelling(self, tmp_state):
        # Insertion order and value types must not mint new series.
        a = tmp_state.canonical_labels({'b': 1, 'a': 'x'})
        b = tmp_state.canonical_labels({'a': 'x', 'b': '1'})
        assert a == b

    def test_per_tier_age_retention_first_batch(self, tmp_state,
                                                monkeypatch):
        monkeypatch.setattr(tmp_state, '_metric_point_inserts', 0)
        now = T0 + 10_000
        tmp_state.record_metric_points(
            [{'ts': now - 5000, 'name': 'old', 'labels': {},
              'kind': 'gauge', 'value': 1.0},
             {'ts': now - 10, 'name': 'new', 'labels': {},
              'kind': 'gauge', 'value': 2.0}],
            ts=now, retention_s={'raw': 600.0})
        names = {r['name'] for r in tmp_state.get_metric_points()}
        # FIRST batch pruned the expired raw row already (short-lived
        # writers never reach an amortized gate).
        assert names == {'new'}

    def test_global_row_cap(self, tmp_state, monkeypatch):
        monkeypatch.setattr(tmp_state, '_MAX_METRIC_POINTS', 10)
        monkeypatch.setattr(tmp_state, '_metric_point_inserts', 0)
        tmp_state.record_metric_points(
            [{'ts': T0 + j, 'name': 'cap', 'labels': {'j': j},
              'kind': 'gauge', 'value': 1.0} for j in range(30)])
        rows = tmp_state.get_metric_points(name='cap')
        # First-batch prune enforces the cap; the newest rows survive.
        assert len(rows) == 10
        assert rows[-1]['labels'] == {'j': '29'}

    def test_torn_rows_cannot_poison_queries(self, tmp_state):
        _gauge_points(tmp_state, 'ok_metric', [1.0, 2.0])
        conn = tmp_state._get_conn()   # pylint: disable=protected-access
        with tmp_state._lock:          # pylint: disable=protected-access
            conn.execute(
                "INSERT INTO metric_points (ts, res, name, labels, "
                "kind, value, vmin, vmax, count) VALUES "
                "(?, 'raw', 'ok_metric', '{\"torn', 'gauge', 3.0, "
                '3.0, 3.0, 1)', (T0 + 30,))
            conn.execute(
                "INSERT INTO metric_points (ts, res, name, labels, "
                "kind, value, vmin, vmax, count) VALUES "
                "(?, 'raw', 'ok_metric', '{}', 'gauge', NULL, "
                'NULL, NULL, 1)', (T0 + 45,))
            conn.commit()
        rows = tmp_state.get_metric_points(name='ok_metric')
        assert [r['value'] for r in rows] == [1.0, 2.0]
        series = metrics_history.series(
            'ok_metric', since=T0, until=T0 + 60, step=60, agg='avg',
            res='raw')
        assert series[0][1] == pytest.approx(1.5)

    def test_record_never_raises_on_db_failure(self, tmp_state,
                                               monkeypatch, tmp_path):
        # Non-vacuous: the DB path's parent is a FILE, so every
        # connect genuinely fails (the PR 11 pattern — a missing
        # directory would just be created).
        blocker = tmp_path / 'blocker'
        blocker.write_text('not a directory')
        monkeypatch.setenv('XSKY_STATE_DB',
                           str(blocker / 'no' / 'such' / 'x.db'))
        tmp_state.reset_for_test()
        tmp_state.record_metric_points(
            [{'name': 'x', 'labels': {}, 'kind': 'gauge',
              'value': 1.0}])
        metrics_history.record_points(
            [{'name': 'x', 'labels': {}, 'kind': 'gauge',
              'value': 1.0}])
        assert metrics_history.series('x') == []
        assert metrics_history.detect_anomalies() == []


# ---- downsampling -----------------------------------------------------------


class TestDownsampling:

    def test_gauge_window_avg_min_max_exact(self, tmp_state):
        values = [1.0, 5.0, 3.0, 9.0]
        _gauge_points(tmp_state, 'g', values)
        metrics_history.record_points([], ts=T0 + 120)
        rows = tmp_state.get_metric_points(name='g', res='1m')
        assert len(rows) == 1
        assert rows[0]['value'] == sum(values) / len(values)
        assert rows[0]['vmin'] == 1.0 and rows[0]['vmax'] == 9.0
        assert rows[0]['count'] == 4
        assert rows[0]['ts'] == T0   # window START, minute aligned

    def test_counter_window_end_value(self, tmp_state):
        _gauge_points(tmp_state, 'c_total', [10.0, 20.0, 30.0],
                      kind='counter')
        metrics_history.record_points([], ts=T0 + 120)
        rows = tmp_state.get_metric_points(name='c_total', res='1m')
        assert rows[0]['value'] == 30.0      # window-end cumulative
        assert rows[0]['vmin'] == 10.0

    def test_10m_folds_from_1m(self, tmp_state):
        _gauge_points(tmp_state, 'g', [2.0, 4.0], dt=60.0)
        metrics_history.record_points([], ts=T0 + 1200)
        one_m = tmp_state.get_metric_points(name='g', res='1m')
        ten_m = tmp_state.get_metric_points(name='g', res='10m')
        assert len(one_m) == 2
        assert len(ten_m) == 1
        assert ten_m[0]['value'] == 3.0
        assert ten_m[0]['ts'] % 600 == 0

    def test_cursor_recovery_never_double_folds(self, tmp_state):
        _gauge_points(tmp_state, 'g', [1.0, 3.0])
        metrics_history.record_points([], ts=T0 + 120)
        # A fresh process (cursor state lost) ticks again: the cursor
        # recovers from the table's MAX(ts) and must not re-fold.
        metrics_history.reset_for_test()
        metrics_history.record_points([], ts=T0 + 180)
        rows = tmp_state.get_metric_points(name='g', res='1m')
        assert len(rows) == 1

    def test_incomplete_window_not_folded(self, tmp_state):
        _gauge_points(tmp_state, 'g', [1.0])
        metrics_history.record_points([], ts=T0 + 30)   # window open
        assert tmp_state.get_metric_points(name='g', res='1m') == []


# ---- query layer ------------------------------------------------------------


class TestSeriesQueries:

    def test_bucketed_aggs_and_gaps(self, tmp_state):
        _gauge_points(tmp_state, 'g', [1.0, 3.0], dt=10.0)
        _gauge_points(tmp_state, 'g', [7.0], t0=T0 + 90)
        out = metrics_history.series('g', since=T0, until=T0 + 120,
                                     step=30, agg='avg', res='raw')
        assert out[0] == (T0, 2.0)
        assert out[1][1] is None                  # gap, not interpolation
        assert out[3][1] == 7.0
        assert metrics_history.series(
            'g', since=T0, until=T0 + 30, step=30, agg='max',
            res='raw')[0][1] == 3.0
        assert metrics_history.series(
            'g', since=T0, until=T0 + 30, step=30, agg='count',
            res='raw')[0][1] == 2.0

    def test_rate_is_counter_aware_across_incarnation_reset(
            self, tmp_state):
        # 10 → 20 → 30, then the incarnation restarts the counter at
        # 5 → 15: the drop must read as a reset (increase 5), never a
        # negative rate.
        _gauge_points(tmp_state, 'c_total', [10, 20, 30, 5, 15],
                      dt=10.0, kind='counter')
        out = metrics_history.series(
            'c_total', since=T0, until=T0 + 50, step=10, agg='rate',
            res='raw')
        values = [v for _, v in out]
        assert values[0] is None                  # baseline sample
        assert values[1] == 1.0 and values[2] == 1.0
        assert values[3] == 0.5                   # reset: increase=5
        assert values[4] == 1.0
        assert all(v is None or v >= 0 for v in values)

    def test_rate_divides_by_covered_time_not_step(self, tmp_state):
        # Samples spaced 60s apart queried at step=30s: each delta of
        # 30 covers 60s → 0.5/s in its landing bucket, NOT delta/step
        # (which would read 1.0/s — the promql covered-time contract).
        _gauge_points(tmp_state, 'c_total', [0, 30, 60], dt=60.0,
                      kind='counter')
        out = metrics_history.series(
            'c_total', since=T0, until=T0 + 180, step=30, agg='rate',
            res='raw')
        populated = [v for _, v in out if v is not None]
        assert populated == [0.5, 0.5]

    def test_fetch_pages_past_default_row_limit(self, tmp_state):
        # 25k points of one series: a single-call read would silently
        # truncate at the 20k default and drop the NEWEST buckets.
        n = 25000
        tmp_state.record_metric_points(
            [{'ts': T0 + i, 'name': 'big', 'labels': {},
              'kind': 'gauge', 'value': float(i)} for i in range(n)])
        out = metrics_history.series(
            'big', since=T0, until=T0 + n, step=float(n), agg='count',
            res='raw')
        assert out[0][1] == float(n)
        last = metrics_history.series(
            'big', since=T0 + n - 10, until=T0 + n, step=10,
            agg='max', res='raw')
        assert last[0][1] == float(n - 1)   # newest points intact

    def test_rate_sums_across_matching_series(self, tmp_state):
        for rank in (0, 1):
            _gauge_points(tmp_state, 'c_total', [0, 10, 20],
                          labels={'rank': rank}, dt=10.0,
                          kind='counter')
        out = metrics_history.series(
            'c_total', since=T0, until=T0 + 30, step=10, agg='rate',
            res='raw')
        assert out[1][1] == 2.0   # 1/s per rank, summed

    def test_windowed_quantiles_track_regression(self, tmp_state):
        for i in range(6):
            metrics_lib.observe('lat_seconds', 'h',
                                0.2 if i < 3 else 0.8)
            metrics_history.record_tick(now=T0 + i * 15)
        early = metrics_history.series(
            'lat_seconds', since=T0, until=T0 + 45, step=45,
            agg='p50', res='raw')
        late = metrics_history.series(
            'lat_seconds', since=T0 + 45, until=T0 + 90, step=45,
            agg='p50', res='raw')
        assert early[0][1] is not None and late[0][1] is not None
        assert late[0][1] > early[0][1] * 2
        assert 0.1 <= early[0][1] <= 0.25
        assert 0.5 <= late[0][1] <= 1.0

    def test_query_validates_agg_and_res(self, tmp_state):
        with pytest.raises(ValueError):
            metrics_history.query('g', agg='p42')
        with pytest.raises(ValueError):
            metrics_history.query('g', res='5m')
        out = metrics_history.query('g', agg='avg', res='raw')
        assert out['points'] == [] or isinstance(out['points'], list)
        assert out['res'] == 'raw'

    def test_res_picked_by_window_span(self, tmp_state, monkeypatch):
        monkeypatch.setenv(metrics_history.ENV_RAW_RETENTION, '100')
        monkeypatch.setenv(metrics_history.ENV_1M_RETENTION, '1000')
        now = time.time()
        assert metrics_history.query(
            'g', since=now - 50)['res'] == 'raw'
        assert metrics_history.query(
            'g', since=now - 500)['res'] == '1m'
        assert metrics_history.query(
            'g', since=now - 5000)['res'] == '10m'

    def test_sparkline_shape(self):
        spark = metrics_history.sparkline([0.0, None, 1.0, 0.5])
        assert len(spark) == 4
        assert spark[1] == ' '
        assert spark[0] == '▁' and spark[2] == '█'
        assert metrics_history.sparkline([]) == ''
        assert metrics_history.sparkline([None, None]) == '  '


# ---- recorder tick over the real /metrics surface ---------------------------


class TestRecorderTick:

    def test_snapshot_and_text_paths_mint_identical_series(
            self, tmp_state):
        metrics_lib.inc_counter('xsky_t_total', 'h', 2.0,
                                cluster='a', rank=3)
        metrics_lib.observe('xsky_t_seconds', 'h', 0.2)
        from skypilot_tpu.utils import metrics as m
        text = ('# TYPE xsky_t_total counter\n'
                '# TYPE xsky_t_seconds histogram\n'
                + m.render_registry())
        structural = metrics_history.sample_points(now=T0)
        parsed = metrics_history.sample_points(now=T0, text=text)
        from skypilot_tpu import state
        as_keys = lambda pts: {        # noqa: E731
            (p['name'], p['kind'],
             p['labels'] if isinstance(p['labels'], str)
             else state.canonical_labels(p['labels']), p['value'])
            for p in pts if p['name'].startswith('xsky_t_')}
        # The structural fast path and the text-parse path must mint
        # IDENTICAL series (name, kind, canonical labels, value) for
        # the registry — drift here would fork series identity
        # between a recorder restart and a text-fed test.
        assert as_keys(structural) == as_keys(parsed)
        assert as_keys(structural), 'registry series must be sampled'

    def test_acceptance_series_ttft_and_dispatch_gap(
            self, tmp_state, tmp_serve_db):
        """The autoscaler/LB arc's two contract series must be
        retrievable through series() after recording the REAL
        /metrics render: per-replica TTFT p99 and per-rank dispatch
        gap."""
        from skypilot_tpu.serve import state as serve_state
        tmp_state.add_or_update_cluster('trainc', None, ready=True)
        tmp_state.record_profiles('trainc', 1, [
            {'rank': 0, 'kind': 'summary', 'dispatch_gap_ratio': 0.8,
             'hbm_bytes_in_use': 1 << 30}])
        serve_state.add_service(
            'svc', {'service': {'slo': {'ttft_p99_ms': 100}}}, 12345)
        tmp_state.record_serve_slo('svc', [
            {'kind': 'replica', 'replica_id': 1,
             'endpoint': '127.0.0.1:9001', 'ttft_p99_ms': 42.0},
            {'kind': 'service', 'replica_id': None,
             'burns': {'300': {'ttft_p99_ms': 2.0}},
             'verdict': 'breach'},
        ])
        now = time.time()
        metrics_history.record_tick(now=now)
        ttft = metrics_history.series(
            'xsky_serve_replica_ttft_p99_seconds',
            labels={'service': 'svc', 'replica': 1},
            since=now - 60, until=now + 1)
        assert any(v == pytest.approx(0.042)
                   for _, v in ttft if v is not None)
        gap = metrics_history.series(
            'xsky_dispatch_gap_ratio',
            labels={'cluster': 'trainc', 'job': 1, 'rank': 0},
            since=now - 60, until=now + 1)
        assert any(v == pytest.approx(0.8)
                   for _, v in gap if v is not None)
        burn = metrics_history.series(
            'xsky_serve_slo_burn_rate',
            labels={'service': 'svc', 'window': '300'},
            since=now - 60, until=now + 1)
        assert any(v == 2.0 for _, v in burn if v is not None)

    def test_cardinality_clamp(self, tmp_state, monkeypatch):
        monkeypatch.setenv(metrics_history.ENV_MAX_SERIES, '10')
        for i in range(50):
            metrics_lib.inc_counter('xsky_card_total', 'h', 1.0,
                                    i=str(i))
        points = metrics_history.sample_points(now=T0)
        assert len(points) == 10

    def test_clamp_preserves_gauge_plane_over_registry(
            self, tmp_state, monkeypatch):
        # A registry label explosion must truncate REGISTRY series,
        # never the bounded-by-construction scrape-time gauges the
        # detectors read.
        monkeypatch.setenv(metrics_history.ENV_MAX_SERIES, '20')
        tmp_state.add_or_update_cluster('trainc', None, ready=True)
        tmp_state.record_profiles('trainc', 1, [
            {'rank': 0, 'kind': 'summary',
             'dispatch_gap_ratio': 0.7}])
        for i in range(100):
            metrics_lib.inc_counter('xsky_explosion_total', 'h', 1.0,
                                    i=str(i))
        points = metrics_history.sample_points(now=T0)
        assert len(points) == 20
        names = {p['name'] for p in points}
        assert 'xsky_dispatch_gap_ratio' in names

    def test_tick_records_under_span_and_counts(self, tmp_state):
        metrics_lib.inc_counter('xsky_t_total', 'h', 1.0)
        out = metrics_history.record_tick(now=time.time())
        assert out['points'] >= 1
        spans = tmp_state.get_spans_by_name(['metrics.record'])
        # Spans flush on root exit; force it.
        from skypilot_tpu.utils import tracing
        tracing.flush()
        spans = tmp_state.get_spans_by_name(['metrics.record'])
        assert spans, 'recorder tick must land on the trace plane'


# ---- recorder failover ------------------------------------------------------


class TestRecorderFailover:
    """Lease-elected recorder dies mid-tick (SIGKILL: lease row live,
    pid dead): the successor must win ``hold_recorder_lease()``
    immediately, journal a trace-linked takeover, and resume each
    rollup cursor from the tier's MAX(ts) — fold-once through the
    failover, because ``rollup_metric_points`` itself has no
    idempotence guard BY DESIGN (election is the guard)."""

    @staticmethod
    def _dead_pid():
        proc = subprocess.Popen(['true'])
        proc.wait()
        return proc.pid

    def test_successor_resumes_cursor_without_double_fold(
            self, tmp_state):
        from skypilot_tpu.utils import ownership

        ownership.reset_for_test()
        # Three completed 1m windows of raw data...
        _gauge_points(tmp_state, 'g', [1.0, 3.0, 5.0, 7.0, 9.0, 11.0],
                      dt=30.0)
        # ...of which the victim recorder folded exactly the first
        # before dying (now=T0+60: only the T0 window is complete).
        metrics_history.record_points([], ts=T0 + 60)
        assert len(tmp_state.get_metric_points(name='g',
                                               res='1m')) == 1
        # The SIGKILL shape: role lease TTL still far in the future,
        # holder pid dead. No release, no cleanup.
        tmp_state.heartbeat_lease(ownership.RECORDER_ROLE_SCOPE,
                                  owner='victim-server',
                                  pid=self._dead_pid(), ttl_s=3600)

        # Successor = a fresh process: in-memory rollup cursors gone.
        metrics_history.reset_for_test()
        # Election does NOT wait out the TTL — the dead pid is
        # observable and the role flips on the first attempt.
        assert metrics_history.hold_recorder_lease()
        role = tmp_state.get_lease(ownership.RECORDER_ROLE_SCOPE)
        assert role['owner'] == ownership.server_id()
        takeovers = tmp_state.get_recovery_events(
            event_type='reconcile.role_takeover')
        assert len(takeovers) == 1
        assert takeovers[0]['detail']['from'] == 'victim-server'
        assert takeovers[0]['trace_id'], \
            'takeover row must resolve through `xsky trace`'

        # The successor's first tick folds the REMAINING two windows:
        # cursor recovered from the 1m tier's MAX(ts), so the window
        # the victim already folded is not re-folded.
        metrics_history.record_points([], ts=T0 + 240)
        rows = tmp_state.get_metric_points(name='g', res='1m')
        assert len(rows) == 3
        assert len({r['ts'] for r in rows}) == 3, \
            'a 1m window was folded twice across the failover'
        assert [r['value'] for r in sorted(rows,
                                           key=lambda r: r['ts'])] == \
            [2.0, 6.0, 10.0]
        # Re-election by the SAME holder is a renewal, not another
        # takeover — no second journal row.
        assert metrics_history.hold_recorder_lease()
        assert len(tmp_state.get_recovery_events(
            event_type='reconcile.role_takeover')) == 1


# ---- anomaly detectors ------------------------------------------------------


class TestDetectors:

    def _now(self):
        return time.time()

    def test_burn_rate_accel_fires_and_clears(self, tmp_state):
        now = self._now()
        labels = {'service': 'svc', 'window': '300'}
        _gauge_points(tmp_state, 'xsky_serve_slo_burn_rate',
                      [0.2, 1.5, 2.0], labels=labels, t0=now - 30,
                      dt=15.0)
        found = metrics_history.detect_anomalies(now=now)
        assert any(f['detector'] == 'burn_rate_accel' for f in found)
        events = tmp_state.get_recovery_events(
            event_type='metrics.anomaly')
        assert len(events) == 1
        assert events[0]['cause'] == 'burn_rate_accel'
        assert events[0]['scope'].startswith(
            'metrics/burn_rate_accel/')
        # Second tick, still burning: no duplicate journal row.
        metrics_history.detect_anomalies(now=now + 1)
        assert len(tmp_state.get_recovery_events(
            event_type='metrics.anomaly')) == 1
        # Burn decays: cleared journalled with the anomaly duration.
        _gauge_points(tmp_state, 'xsky_serve_slo_burn_rate',
                      [0.4, 0.2], labels=labels, t0=now + 10, dt=5.0)
        metrics_history.detect_anomalies(now=now + 20)
        cleared = tmp_state.get_recovery_events(
            event_type='metrics.anomaly_cleared')
        assert len(cleared) == 1
        assert cleared[0]['latency_s'] == pytest.approx(20, abs=1)
        assert not metrics_history.active_anomalies()

    def test_heartbeat_age_drift(self, tmp_state):
        now = self._now()
        dead = {'cluster': 'c', 'job': '1', 'rank': '0'}
        live = {'cluster': 'c', 'job': '1', 'rank': '1'}
        name = 'xsky_workload_last_heartbeat_age_seconds'
        # Dead rank: age climbs at wall-clock slope; live rank: flat.
        _gauge_points(tmp_state, name, [15.0, 30.0, 45.0, 60.0],
                      labels=dead, t0=now - 45, dt=15.0)
        _gauge_points(tmp_state, name, [2.0, 3.0, 2.0, 3.0],
                      labels=live, t0=now - 45, dt=15.0)
        found = metrics_history.detect_anomalies(now=now)
        drifts = [f for f in found
                  if f['detector'] == 'heartbeat_age_drift']
        assert len(drifts) == 1
        assert drifts[0]['labels']['rank'] == '0'

    def test_dispatch_gap_trend(self, tmp_state):
        now = self._now()
        rising = {'cluster': 'c', 'job': '1', 'rank': '0'}
        steady = {'cluster': 'c', 'job': '1', 'rank': '1'}
        _gauge_points(tmp_state, 'xsky_dispatch_gap_ratio',
                      [0.2, 0.25, 0.6, 0.7, 0.8, 0.9],
                      labels=rising, t0=now - 75, dt=15.0)
        _gauge_points(tmp_state, 'xsky_dispatch_gap_ratio',
                      [0.85, 0.9, 0.88, 0.9, 0.87, 0.9],
                      labels=steady, t0=now - 75, dt=15.0)
        found = metrics_history.detect_anomalies(now=now)
        trends = [f for f in found
                  if f['detector'] == 'dispatch_gap_trend']
        # Steady-high is the profiler verdict's business, not a TREND
        # anomaly: only the rising rank fires.
        assert [f['labels']['rank'] for f in trends] == ['0']

    def test_step_time_regression_vs_trailing_baseline(
            self, tmp_state, monkeypatch):
        now = time.time()
        for i in range(8):
            metrics_lib.observe('xsky_workload_step_seconds', 'h',
                                0.1 if i < 4 else 0.9)
            metrics_history.record_tick(now=now - (7 - i) * 15)
        found = metrics_history.detect_anomalies(now=now)
        regressions = [f for f in found
                       if f['detector'] == 'step_time_regression']
        assert regressions, found
        assert regressions[0]['value'] > regressions[0]['baseline']
        # A huge factor silences it (env-tunable threshold).
        metrics_history.reset_for_test()
        monkeypatch.setenv(metrics_history.ENV_ANOMALY_FACTOR, '100')
        found = metrics_history.detect_anomalies(now=now)
        assert not [f for f in found
                    if f['detector'] == 'step_time_regression']

    def test_chaos_forces_each_arm(self, tmp_state):
        now = self._now()
        chaos.load_plan({'points': {'metrics.detector': {
            'force': 'anomaly',
            'match': {'detector': 'burn_rate_accel'}}}})
        found = metrics_history.detect_anomalies(now=now)
        forced = [f for f in found
                  if f['detector'] == 'burn_rate_accel']
        assert forced and forced[0]['labels'] == {'forced': '1'}
        assert tmp_state.get_recovery_events(
            event_type='metrics.anomaly')
        # The clear arm: chaos suppresses every finding of the
        # detector, closing the forced incident.
        chaos.load_plan({'points': {'metrics.detector': {
            'force': 'clear',
            'match': {'detector': 'burn_rate_accel'}}}})
        metrics_history.detect_anomalies(now=now + 5)
        assert tmp_state.get_recovery_events(
            event_type='metrics.anomaly_cleared')

    def test_anomaly_is_trace_linked_through_record_tick(
            self, tmp_state):
        now = time.time()
        labels = {'service': 'svc', 'window': '300'}
        _gauge_points(tmp_state, 'xsky_serve_slo_burn_rate',
                      [1.5, 2.0], labels=labels, t0=now - 15,
                      dt=15.0)
        metrics_history.record_tick(now=now)
        events = tmp_state.get_recovery_events(
            event_type='metrics.anomaly')
        assert events and events[-1]['trace_id'], \
            'anomaly must cross-link to the metrics.record span'


# ---- CLI surfaces -----------------------------------------------------------


class TestCliSurfaces:

    def _seed(self, tmp_state):
        now = time.time()
        _gauge_points(tmp_state, 'xsky_demo_ratio',
                      [0.1, 0.5, 0.9], labels={'rank': '0'},
                      t0=now - 30, dt=15.0)
        return now

    def test_metrics_list_table_and_json(self, tmp_state):
        from click.testing import CliRunner

        from skypilot_tpu.client import cli as cli_mod
        self._seed(tmp_state)
        table = CliRunner().invoke(cli_mod.cli, ['metrics', 'list'])
        assert table.exit_code == 0, table.output
        assert 'xsky_demo_ratio' in table.output
        assert 'rank=0' in table.output
        as_json = CliRunner().invoke(
            cli_mod.cli, ['metrics', 'list', '--json'])
        rows = [json.loads(l) for l in as_json.output.splitlines()
                if l.startswith('{')]
        assert rows[0]['name'] == 'xsky_demo_ratio'
        assert rows[0]['points'] == 3

    def test_metrics_query_table_sparkline_and_json(self, tmp_state):
        from click.testing import CliRunner

        from skypilot_tpu.client import cli as cli_mod
        self._seed(tmp_state)
        table = CliRunner().invoke(cli_mod.cli, [
            'metrics', 'query', 'xsky_demo_ratio', '--since', '5m',
            '--step', '15s', '--label', 'rank=0'])
        assert table.exit_code == 0, table.output
        assert 'agg=avg' in table.output
        assert any(g in table.output for g in '▁▂▃▄▅▆▇█')
        assert 'min=0.1' in table.output and 'max=0.9' in table.output
        as_json = CliRunner().invoke(cli_mod.cli, [
            'metrics', 'query', 'xsky_demo_ratio', '--since', '5m',
            '--json'])
        out = json.loads(as_json.output)
        assert out['name'] == 'xsky_demo_ratio'
        assert any(p[1] is not None for p in out['points'])

    def test_metrics_query_rejects_bad_step(self, tmp_state):
        from click.testing import CliRunner

        from skypilot_tpu.client import cli as cli_mod
        result = CliRunner().invoke(cli_mod.cli, [
            'metrics', 'query', 'x', '--step', 'bogus'])
        assert result.exit_code != 0
        assert '--step' in result.output

    def test_shared_duration_parser(self):
        from skypilot_tpu.utils import common_utils
        assert common_utils.parse_duration_s('90') == 90.0
        assert common_utils.parse_duration_s('5m') == 300.0
        assert common_utils.parse_duration_s('2H') == 7200.0
        assert common_utils.parse_duration_s('1d') == 86400.0
        assert common_utils.parse_duration_s(1.5) == 1.5
        with pytest.raises(ValueError):
            common_utils.parse_duration_s('abc')

    def test_events_since_relative_duration(self, tmp_state):
        from click.testing import CliRunner

        from skypilot_tpu.client import cli as cli_mod
        tmp_state.record_recovery_event('demo.old', scope='x/1')
        conn = tmp_state._get_conn()   # pylint: disable=protected-access
        with tmp_state._lock:          # pylint: disable=protected-access
            conn.execute('UPDATE recovery_events SET ts = ts - 3600')
            conn.commit()
        tmp_state.record_recovery_event('demo.new', scope='x/2')
        result = CliRunner().invoke(cli_mod.cli,
                                    ['events', '--since', '5m'])
        assert result.exit_code == 0, result.output
        assert 'demo.new' in result.output
        assert 'demo.old' not in result.output

    def test_top_trend_column(self, tmp_state):
        from click.testing import CliRunner

        from skypilot_tpu.client import cli as cli_mod
        now = time.time()
        tmp_state.record_workload_telemetry('trainc', 1, [
            {'rank': 0, 'phase': 'step', 'step': 10,
             'step_time_ema_s': 0.1, 'hb_ts': now,
             'last_progress_ts': now, 'started_ts': now - 100}])
        _gauge_points(tmp_state, 'xsky_dispatch_gap_ratio',
                      [0.2, 0.5, 0.8],
                      labels={'cluster': 'trainc', 'job': '1',
                              'rank': '0'},
                      t0=now - 30, dt=15.0)
        plain = CliRunner().invoke(cli_mod.cli, ['top'])
        assert plain.exit_code == 0, plain.output
        assert 'TREND' not in plain.output
        trend = CliRunner().invoke(cli_mod.cli, ['top', '--trend'])
        assert trend.exit_code == 0, trend.output
        assert 'TREND' in trend.output
        assert any(g in trend.output for g in '▁▂▃▄▅▆▇█')

    def test_slo_trend_sparklines(self, tmp_state, tmp_serve_db):
        from click.testing import CliRunner

        from skypilot_tpu.client import cli as cli_mod
        from skypilot_tpu.serve import state as serve_state
        serve_state.add_service(
            'svc', {'service': {'slo': {'ttft_p99_ms': 100}}}, 12345)
        tmp_state.record_serve_slo('svc', [
            {'kind': 'replica', 'replica_id': 1,
             'endpoint': '127.0.0.1:9001', 'ttft_p99_ms': 42.0},
            {'kind': 'service', 'replica_id': None,
             'burns': {'300': {'ttft_p99_ms': 2.0}},
             'verdict': 'breach'},
        ])
        now = time.time()
        _gauge_points(tmp_state, 'xsky_serve_slo_burn_rate',
                      [0.5, 1.0, 2.0],
                      labels={'service': 'svc', 'window': '300'},
                      t0=now - 30, dt=15.0)
        _gauge_points(tmp_state,
                      'xsky_serve_replica_ttft_p99_seconds',
                      [0.02, 0.04, 0.08],
                      labels={'service': 'svc', 'replica': '1'},
                      t0=now - 30, dt=15.0)
        result = CliRunner().invoke(cli_mod.cli, ['slo', '--trend'])
        assert result.exit_code == 0, result.output
        assert 'TREND' in result.output
        assert any(g in result.output for g in '▁▂▃▄▅▆▇█')


# ---- /metrics?name= filter --------------------------------------------------


class TestMetricsEndpointFilter:

    def test_render_prefix_filters_sections(self, tmp_state):
        from skypilot_tpu.server import metrics as server_metrics
        server_metrics.reset_for_test()
        server_metrics.observe_http('/health', 200)
        server_metrics.observe_request('status', 'ok', 0.1)
        metrics_lib.inc_counter('xsky_chaos_fires_total', 'h', 1.0,
                                point='x')
        full = server_metrics.render()
        assert 'xsky_http_requests_total' in full
        assert 'xsky_chaos_fires_total' in full
        filtered = server_metrics.render('xsky_chaos')
        assert 'xsky_chaos_fires_total' in filtered
        assert 'xsky_http_requests_total' not in filtered
        assert 'xsky_requests_total' not in filtered
        # A histogram child prefix still selects its parent.
        child = server_metrics.render(
            'xsky_request_duration_seconds_bucket')
        assert 'xsky_request_duration_seconds_bucket' in child
        assert 'xsky_requests_total{' not in child

    def test_filter_is_per_series_within_a_section(self, tmp_state):
        # The lease SECTION renders two metrics; asking for one must
        # not emit its sibling ('only matching series', not 'only
        # matching sections').
        tmp_state.heartbeat_lease('job/1', 'tester')
        from skypilot_tpu.server import metrics as server_metrics
        out = server_metrics.render('xsky_leases_live')
        assert 'xsky_leases_live' in out
        assert 'xsky_lease_expires_in_seconds' not in out

    def test_filter_skips_gauge_section_recomputation(
            self, tmp_state, monkeypatch):
        from skypilot_tpu.server import metrics as server_metrics
        calls = []
        monkeypatch.setattr(
            server_metrics, '_GAUGE_SECTIONS',
            ((lambda: calls.append('lease') or [],
              ('xsky_lease_expires_in_seconds',)),
             (lambda: calls.append('slo') or [],
              ('xsky_serve_slo_burn_rate',))))
        server_metrics.render('xsky_serve_slo')
        assert calls == ['slo'], \
            'non-matching gauge sections must not be recomputed'

    def test_http_endpoint_name_param(self, tmp_state, monkeypatch,
                                      tmp_path):
        monkeypatch.setenv('XSKY_SERVER_DB',
                           str(tmp_path / 'requests.db'))
        from skypilot_tpu.server import app as app_mod
        from skypilot_tpu.server import requests_db
        requests_db.reset_for_test()
        server, port = app_mod.run_in_thread()
        try:
            metrics_lib.inc_counter('xsky_chaos_fires_total', 'h',
                                    1.0, point='y')
            with urllib.request.urlopen(
                    f'http://127.0.0.1:{port}/metrics'
                    '?name=xsky_chaos', timeout=10) as resp:
                body = resp.read().decode()
            assert 'xsky_chaos_fires_total' in body
            assert 'xsky_http_requests_total' not in body
            with urllib.request.urlopen(
                    f'http://127.0.0.1:{port}/metrics',
                    timeout=10) as resp:
                body = resp.read().decode()
            assert 'xsky_http_requests_total' in body
        finally:
            server.shutdown()


# ---- lint surface -----------------------------------------------------------


class TestLintSurface:
    """The static-analysis CI job lints state.py automatically; these
    pin that the retention/never-raise contracts actually grew to
    cover the new plane (satellite: 'should be automatic — assert it
    with a test')."""

    def test_retention_rule_covers_metric_points(self):
        from tools.xskylint.rules import observability as obs_rules
        rule = obs_rules.RetentionBoundRule
        assert rule.BOUNDED['metric_points'] == '_MAX_METRIC_POINTS'
        assert rule.OBSERVABILITY_RE.search('metric_points')

    def test_unbounded_points_table_is_a_finding(self, tmp_path):
        pkg = tmp_path / 'skypilot_tpu'
        pkg.mkdir()
        (pkg / 'state.py').write_text(textwrap.dedent('''\
            import sqlite3


            def create(conn):
                conn.executescript("""
                    CREATE TABLE IF NOT EXISTS rogue_points (
                        row_id INTEGER PRIMARY KEY,
                        value REAL
                    );
                """)
        '''))
        from tools.xskylint import engine
        result = engine.lint_paths(str(tmp_path), ['.'],
                                   rule_ids=['retention-bound'])
        findings = [f for f in result.unsuppressed
                    if f.rule == 'retention-bound']
        assert findings, ('a new *_points observability table without '
                          'a bound must fail the lint')

    def test_never_raise_contract_covers_recorder(self):
        from tools.xskylint.rules import observability as obs_rules
        entry = obs_rules.NeverRaiseRule.REQUIRED[
            'skypilot_tpu/utils/metrics_history.py']
        assert set(entry) == {'record_points', 'detect_anomalies',
                              'series'}

    def test_span_sites_cover_recorder_entry_points(self):
        from tools.xskylint.rules import observability as obs_rules
        sites = obs_rules.SpanProfilerRule.PROFILER_SITES
        assert {'record_points', 'detect_anomalies',
                'series'} <= sites


# ---- bench gate -------------------------------------------------------------


class TestBenchMetricsHistoryGate:
    """Tier-1 gates: recorder overhead <2% of the record interval at
    cardinality, exact downsampling arithmetic, and the fake-cloud
    anomaly drill (an lb.proxy-slowed replica must produce a
    trace-linked journalled metrics.anomaly visible in `xsky metrics
    query --json` and clear on recovery) — `tools/
    bench_metrics_history.py --smoke` in a clean subprocess (the
    bench_profile/bench_fleet gate pattern)."""

    def test_bench_smoke_gate(self):
        proc = subprocess.run(
            [sys.executable,
             os.path.join(REPO_ROOT, 'tools',
                          'bench_metrics_history.py'), '--smoke'],
            capture_output=True, text=True, timeout=480, check=False)
        assert proc.returncode == 0, proc.stdout + proc.stderr
        result = json.loads(proc.stdout.strip().splitlines()[-1])
        assert result['pass'] is True
        assert result['overhead']['overhead_pct'] < \
            result['overhead']['max_overhead_pct']
        assert all(result['downsampling']['checks'].values())
        drill = result['drill']
        assert drill['journalled_anomaly'] is True
        assert drill['anomaly_trace_linked'] is True
        assert drill['cli_query_points'] > 0
        assert drill['cli_query_peak_burn'] >= 1.0
        assert drill['anomaly_cleared'] is True
