"""Qwen model family: QKV-bias (Qwen-2) and QK-norm (Qwen-3) variants,
chunked-CE head, trainer integration on the 8-device mesh."""
import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from skypilot_tpu import models
from skypilot_tpu.models import qwen
from skypilot_tpu.parallel import mesh as mesh_lib


pytestmark = pytest.mark.slow  # heavy tier: subprocess e2e / jit compiles


@pytest.fixture(scope='module')
def tiny2():
    return qwen.QWEN_TINY


@pytest.fixture(scope='module')
def tiny3():
    return qwen.QWEN3_TINY


@pytest.fixture(scope='module')
def params2(tiny2):
    return qwen.init(tiny2, jax.random.PRNGKey(0))


@pytest.fixture(scope='module')
def params3(tiny3):
    return qwen.init(tiny3, jax.random.PRNGKey(0))


class TestQwenForward:

    def test_logits_shape_and_dtype(self, tiny2, params2):
        tokens = jnp.zeros((2, 16), jnp.int32)
        logits = qwen.forward(tiny2, params2, tokens)
        assert logits.shape == (2, 16, tiny2.vocab_size)
        assert logits.dtype == jnp.float32

    def test_variant_param_sets(self, tiny2, tiny3, params2, params3):
        # Qwen-2: biases, no qk norms; Qwen-3: the reverse.
        assert {'bq', 'bk', 'bv'} <= set(params2['layers'])
        assert 'q_norm' not in params2['layers']
        assert {'q_norm', 'k_norm'} <= set(params3['layers'])
        assert 'bq' not in params3['layers']
        # Both count their params consistently with their pytree.
        for c, p in ((tiny2, params2), (tiny3, params3)):
            n = sum(x.size for x in jax.tree.leaves(p))
            assert n == c.num_params()

    @pytest.mark.parametrize('variant', ['tiny2', 'tiny3'])
    def test_causality(self, variant, request):
        c = request.getfixturevalue(variant)
        p = request.getfixturevalue('params' + variant[-1])
        t1 = jnp.zeros((1, 8), jnp.int32)
        t2 = t1.at[0, 7].set(5)
        l1 = qwen.forward(c, p, t1)
        l2 = qwen.forward(c, p, t2)
        np.testing.assert_allclose(np.asarray(l1[0, :7]),
                                   np.asarray(l2[0, :7]), atol=1e-5)

    def test_qk_norm_changes_output(self, tiny3, params3):
        """Scaling k_norm must change logits (the norm is live)."""
        tokens = jax.random.randint(jax.random.PRNGKey(1), (1, 8), 0,
                                    tiny3.vocab_size)
        base = qwen.forward(tiny3, params3, tokens)
        bumped = jax.tree_util.tree_map(lambda x: x, params3)
        bumped = {**params3, 'layers': {**params3['layers'],
                                        'k_norm':
                                        params3['layers']['k_norm'] * 2.0}}
        out = qwen.forward(tiny3, bumped, tokens)
        assert float(jnp.abs(out - base).max()) > 1e-4

    @pytest.mark.parametrize('variant', ['tiny2', 'tiny3'])
    def test_loss_decreases_under_sgd(self, variant, request):
        c = request.getfixturevalue(variant)
        params = qwen.init(c, jax.random.PRNGKey(3))
        tokens = jax.random.randint(jax.random.PRNGKey(4), (2, 16), 0,
                                    c.vocab_size)
        targets = jnp.roll(tokens, -1, axis=1)
        loss0, grads = jax.value_and_grad(
            lambda p: qwen.loss_fn(c, p, tokens, targets))(params)
        params2 = jax.tree.map(
            lambda p, g: (p - 0.5 * g.astype(p.dtype)), params, grads)
        loss1 = qwen.loss_fn(c, params2, tokens, targets)
        assert float(loss1) < float(loss0)

    def test_chunked_ce_matches_whole(self, tiny2, params2):
        """ce_chunk smaller than seq must not change the loss."""
        tokens = jax.random.randint(jax.random.PRNGKey(5), (2, 16), 0,
                                    tiny2.vocab_size)
        targets = jnp.roll(tokens, -1, axis=1)
        whole = qwen.loss_fn(tiny2, params2, tokens, targets)
        chunked_cfg = dataclasses.replace(tiny2, ce_chunk=4)
        chunked = qwen.loss_fn(chunked_cfg, params2, tokens, targets)
        np.testing.assert_allclose(float(whole), float(chunked),
                                   rtol=1e-5)

    def test_registry_dispatch(self, tiny2):
        assert models.module_for(tiny2) is qwen
        assert models.get_config('qwen3-8b') is qwen.QWEN3_8B
        from skypilot_tpu.models import llama
        assert models.module_for(llama.LLAMA_TINY) is llama


class TestQwenSharded:

    def test_trainer_step_on_mesh(self, tiny3):
        from skypilot_tpu.train import trainer as trainer_lib
        plan = mesh_lib.MeshPlan(data=2, fsdp=2, tensor=2)
        config = trainer_lib.TrainConfig(
            model=dataclasses.replace(tiny3, remat=True),
            global_batch_size=4, seq_len=32,
            optimizer='adafactor', warmup_steps=1,
            mesh_plan=plan)
        trainer = trainer_lib.Trainer(config)
        state = trainer.init_state()
        batch = trainer.synthetic_batch(0)
        state, metrics = trainer.step(state, batch)
        state, metrics = trainer.step(state, batch)
        loss_a = float(metrics['loss'])
        state, metrics = trainer.step(state, batch)
        assert float(metrics['loss']) < loss_a

    def test_sharded_matches_single_device(self, tiny2, params2):
        tokens = jax.random.randint(jax.random.PRNGKey(6), (4, 16), 0,
                                    tiny2.vocab_size)
        targets = jnp.roll(tokens, -1, axis=1)
        ref = qwen.loss_fn(tiny2, params2, tokens, targets)
        mesh = mesh_lib.build_mesh(
            mesh_lib.MeshPlan(data=2, fsdp=2, tensor=2).resolve(8))
        sharded = qwen.loss_fn(tiny2, params2, tokens, targets, mesh=mesh)
        np.testing.assert_allclose(float(ref), float(sharded), rtol=2e-3)
