"""IBM Cloud VPC provisioner op-set.

Behavioral twin of sky/provision/ibm.py + the legacy node provider
(sky/skylet/providers/ibm/) with this repo's conventions: the VPC API
carries no freeform instance tags (tagging is a separate global
service), so cluster membership rides the instance NAME
(`<cluster>-<index>`) exactly like the Lambda provisioner — any process
reconstructs the cluster from ListInstances cold.

Platform facts encoded here:
  * instances need a VPC + zonal subnet + SSH key id at create; the
    provisioner resolves (or creates) an `xsky-vpc` with one subnet per
    zone and registers the user's public key once;
  * only the head node gets a floating IP (public); workers are
    reached over the VPC — same pattern the reference uses
    (one FIP per cluster head);
  * stop/start are instance actions; `deleting` instances linger in
    listings until gone;
  * profiles encode shape (gx2-8x64x1v100 = 8 vCPU, 64 GiB, 1×V100);
    there is no spot market on VPC gen2.
"""
from __future__ import annotations

import time
from typing import Any, Dict, List, Optional

from skypilot_tpu import exceptions
from skypilot_tpu import sky_logging
from skypilot_tpu.provision import common
from skypilot_tpu.provision.ibm import rest

logger = sky_logging.init_logger(__name__)

_transport_factory = rest.Transport


def set_transport_factory(factory) -> None:
    global _transport_factory
    _transport_factory = factory


def _transport(provider_config: Dict[str, Any]) -> Any:
    region = (provider_config or {}).get('region', 'us-south')
    return _transport_factory(region)


_STATE_MAP = {
    'pending': 'PENDING',
    'starting': 'PENDING',
    'restarting': 'PENDING',
    'running': 'RUNNING',
    'stopping': 'STOPPING',
    'stopped': 'STOPPED',
    'deleting': None,
    'failed': None,
}

_VPC_NAME = 'xsky-vpc'
_KEY_NAME = 'xsky-key'


def _instance_name(cluster_name: str, index: int) -> str:
    return f'{cluster_name}-{index}'


def _cluster_instances(t, cluster_name: str,
                       include_deleting: bool = False
                       ) -> List[Dict[str, Any]]:
    out = []
    for inst in t.paged('/instances', 'instances'):
        name = inst.get('name') or ''
        prefix, _, idx = name.rpartition('-')
        if prefix != cluster_name or not idx.isdigit():
            continue
        if not include_deleting and \
                inst.get('status') in ('deleting', 'failed'):
            continue
        out.append(inst)
    return sorted(out, key=lambda i: int(i['name'].rsplit('-', 1)[1]))


def _ensure_vpc(t, provider_config: Dict[str, Any]) -> str:
    vpc_id = (provider_config or {}).get('vpc_id')
    if vpc_id:
        return vpc_id
    for vpc in t.paged('/vpcs', 'vpcs'):
        if vpc.get('name') == _VPC_NAME:
            return vpc['id']
    vpc = t.call('POST', '/vpcs', body={'name': _VPC_NAME})
    return vpc['id']


def _ensure_subnet(t, vpc_id: str, zone: str,
                   provider_config: Dict[str, Any]) -> str:
    subnet_id = (provider_config or {}).get('subnet_id')
    if subnet_id:
        return subnet_id
    for s in t.paged('/subnets', 'subnets'):
        if s.get('vpc', {}).get('id') == vpc_id and \
                s.get('zone', {}).get('name') == zone:
            return s['id']
    subnet = t.call('POST', '/subnets', body={
        'name': f'xsky-subnet-{zone}',
        'vpc': {'id': vpc_id},
        'zone': {'name': zone},
        'total_ipv4_address_count': 256,
    })
    return subnet['id']


def _ensure_key(t, public_key: Optional[str]) -> str:
    for k in t.paged('/keys', 'keys'):
        if k.get('name') == _KEY_NAME:
            return k['id']
    if public_key is None:
        import os
        from skypilot_tpu import authentication
        _, public_key_path = authentication.get_or_generate_keys()
        with open(os.path.expanduser(public_key_path),
                  encoding='utf-8') as f:
            public_key = f.read().strip()
    key = t.call('POST', '/keys', body={'name': _KEY_NAME,
                                        'public_key': public_key,
                                        'type': 'rsa'})
    return key['id']


def _resolve_image(t, node_config: Dict[str, Any]) -> str:
    image = node_config.get('image_id')
    if image:
        return image
    images = [
        img for img in t.paged('/images', 'images',
                              query={'status': 'available'})
        if img.get('operating_system', {}).get('name',
                                               '').startswith('ubuntu')
        and img.get('operating_system', {}).get('architecture') ==
        'amd64'
    ]
    if not images:
        raise exceptions.ProvisionError('No Ubuntu VPC image found.')
    return sorted(images, key=lambda i: i.get('name', ''))[-1]['id']


def _primary_nic_id(inst: Dict[str, Any]) -> Optional[str]:
    nic = inst.get('primary_network_interface') or {}
    return nic.get('id')


def _ensure_head_fip(t, inst: Dict[str, Any], cluster_name: str) -> None:
    """Attach a floating IP to the head's primary NIC (idempotent)."""
    nic_id = _primary_nic_id(inst)
    if nic_id is None:
        return
    fip_name = f'xsky-fip-{cluster_name}'
    for fip in t.paged('/floating_ips', 'floating_ips'):
        if fip.get('name') == fip_name:
            if (fip.get('target') or {}).get('id') != nic_id:
                t.call('PATCH', f'/floating_ips/{fip["id"]}',
                       body={'target': {'id': nic_id}})
            return
    t.call('POST', '/floating_ips',
           body={'name': fip_name, 'target': {'id': nic_id}})


def _head_fip(t, cluster_name: str) -> Optional[str]:
    for fip in t.paged('/floating_ips', 'floating_ips'):
        if fip.get('name') == f'xsky-fip-{cluster_name}':
            return fip.get('address')
    return None


def run_instances(region: str, zone: Optional[str], cluster_name: str,
                  config: common.ProvisionConfig) -> common.ProvisionRecord:
    t = _transport(dict(config.provider_config or {}, region=region))
    node_cfg = config.node_config
    zone = zone or f'{region}-1'
    try:
        existing = _cluster_instances(t, cluster_name)
        resumed: List[str] = []
        for inst in existing:
            if inst.get('status') == 'stopped':
                t.call('POST', f'/instances/{inst["id"]}/actions',
                       body={'type': 'start'})
                resumed.append(inst['id'])
        taken = {int(i['name'].rsplit('-', 1)[1]) for i in existing}
        missing = sorted(set(range(config.count)) - taken)
        created: List[str] = []
        if missing:
            vpc_id = _ensure_vpc(t, config.provider_config)
            subnet_id = _ensure_subnet(t, vpc_id, zone,
                                       config.provider_config)
            key_id = _ensure_key(t, node_cfg.get('ssh_public_key'))
            image_id = _resolve_image(t, node_cfg)
            for node in missing:
                body: Dict[str, Any] = {
                    'name': _instance_name(cluster_name, node),
                    'zone': {'name': zone},
                    'profile': {'name': node_cfg['instance_type']},
                    'image': {'id': image_id},
                    'vpc': {'id': vpc_id},
                    'primary_network_interface': {
                        'name': 'eth0',
                        'subnet': {'id': subnet_id},
                    },
                    'keys': [{'id': key_id}],
                    'boot_volume_attachment': {
                        'volume': {
                            'capacity': node_cfg.get('disk_size', 100),
                            'profile': {'name': 'general-purpose'},
                        },
                        'delete_volume_on_instance_delete': True,
                    },
                }
                rg = (config.provider_config or {}).get(
                    'resource_group_id')
                if rg:
                    body['resource_group'] = {'id': rg}
                inst = t.call('POST', '/instances', body=body)
                created.append(inst['id'])
        # Head public reachability: floating IP on node 0.
        for inst in _cluster_instances(t, cluster_name):
            if inst['name'].endswith('-0'):
                _ensure_head_fip(t, inst, cluster_name)
                head = inst['id']
                break
        else:
            head = None
    except rest.IbmApiError as e:
        raise rest.classify_error(e, region) from e
    return common.ProvisionRecord(
        provider_name='ibm', cluster_name=cluster_name, region=region,
        zone=zone, resumed_instance_ids=resumed,
        created_instance_ids=created,
        head_instance_id=head)


def wait_instances(region: str, cluster_name: str, state: str,
                   provider_config: Optional[Dict[str, Any]] = None,
                   timeout_s: float = 900.0,
                   poll_interval_s: float = 5.0) -> None:
    t = _transport(dict(provider_config or {}, region=region))
    deadline = time.time() + timeout_s
    while time.time() < deadline:
        instances = _cluster_instances(t, cluster_name,
                                       include_deleting=True)
        states = [_STATE_MAP.get(i.get('status', ''), 'PENDING')
                  for i in instances]
        if any(s is None for s in states):
            raise exceptions.CapacityError(
                f'Instance(s) of {cluster_name!r} died while waiting '
                f'for {state}.')
        if instances and all(s == state for s in states):
            return
        time.sleep(poll_interval_s)
    raise exceptions.ProvisionError(
        f'IBM cluster {cluster_name!r} did not reach {state} within '
        f'{timeout_s}s.')


def stop_instances(cluster_name: str,
                   provider_config: Dict[str, Any]) -> None:
    t = _transport(provider_config)
    try:
        for inst in _cluster_instances(t, cluster_name):
            if inst.get('status') == 'running':
                t.call('POST', f'/instances/{inst["id"]}/actions',
                       body={'type': 'stop'})
    except rest.IbmApiError as e:
        raise rest.classify_error(e) from e


def terminate_instances(cluster_name: str,
                        provider_config: Dict[str, Any]) -> None:
    t = _transport(provider_config)
    try:
        for inst in _cluster_instances(t, cluster_name):
            t.call('DELETE', f'/instances/{inst["id"]}')
        # Release the head floating IP with the cluster.
        for fip in t.paged('/floating_ips', 'floating_ips'):
            if fip.get('name') == f'xsky-fip-{cluster_name}':
                t.call('DELETE', f'/floating_ips/{fip["id"]}')
    except rest.IbmApiError as e:
        raise rest.classify_error(e) from e


def query_instances(cluster_name: str, provider_config: Dict[str, Any]
                    ) -> Dict[str, Optional[str]]:
    t = _transport(provider_config)
    return {
        i['id']: _STATE_MAP.get(i.get('status', ''), 'PENDING')
        for i in _cluster_instances(t, cluster_name,
                                    include_deleting=True)
    }


def get_cluster_info(region: str, cluster_name: str,
                     provider_config: Dict[str, Any]
                     ) -> common.ClusterInfo:
    t = _transport(dict(provider_config or {}, region=region))
    head_fip = _head_fip(t, cluster_name)
    instances: Dict[str, common.InstanceInfo] = {}
    head_id = None
    for inst in _cluster_instances(t, cluster_name):
        index = int(inst['name'].rsplit('-', 1)[1])
        nic = inst.get('primary_network_interface') or {}
        private_ip = (nic.get('primary_ip') or {}).get('address', '')
        state = _STATE_MAP.get(inst.get('status', ''), 'PENDING')
        instances[inst['id']] = common.InstanceInfo(
            instance_id=inst['id'],
            internal_ip=private_ip,
            external_ip=head_fip if index == 0 else None,
            status=state or 'TERMINATED',
            tags={'cluster': cluster_name, 'node_index': str(index)},
            slice_id=inst['id'],
            host_index=0,
        )
        if index == 0:
            head_id = inst['id']
    return common.ClusterInfo(
        instances=instances, head_instance_id=head_id,
        provider_name='ibm',
        provider_config=dict(provider_config or {}),
        ssh_user='ubuntu')


def open_ports(cluster_name: str, ports: List[str],
               provider_config: Dict[str, Any]) -> None:
    """Add inbound rules to the VPC's default security group."""
    t = _transport(provider_config)
    try:
        vpc_id = _ensure_vpc(t, provider_config)
        vpc = t.call('GET', f'/vpcs/{vpc_id}')
        sg_id = (vpc.get('default_security_group') or {}).get('id')
        if not sg_id:
            raise exceptions.ProvisionError(
                'IBM VPC has no default security group to open ports.')
        existing = t.call(
            'GET', f'/security_groups/{sg_id}/rules').get('rules', [])
        have = {(r.get('port_min'), r.get('port_max'))
                for r in existing if r.get('direction') == 'inbound'}
        for spec in ports:
            lo, _, hi = str(spec).partition('-')
            lo, hi = int(lo), int(hi or lo)
            if (lo, hi) in have:
                continue
            t.call('POST', f'/security_groups/{sg_id}/rules', body={
                'direction': 'inbound', 'protocol': 'tcp',
                'port_min': lo, 'port_max': hi,
                'remote': {'cidr_block': '0.0.0.0/0'}})
    except rest.IbmApiError as e:
        raise rest.classify_error(e) from e


def cleanup_ports(cluster_name: str,
                  provider_config: Dict[str, Any]) -> None:
    # Rules live on the shared xsky VPC default SG; clusters share it,
    # so per-cluster cleanup would break neighbors. No-op by design.
    del cluster_name, provider_config
