"""Cudo Compute: marketplace GPU VMs for cross-cloud optimization.

Lean twin of sky/clouds/cudo.py — catalog-backed feasibility via
CatalogCloud, deploy variables for the 'cudo' provisioner. Platform
facts: data centers as regions (gb-bournemouth-1 etc.), stop/start
supported, all ports open, no spot market; instance type grammar
`<machine_type>_<gpus>x<GPU>` carries both the host class and the GPU
fit, with vcpus/memory resolved from the catalog row.
"""
from __future__ import annotations

import os
import typing
from typing import Any, Dict, Optional, Tuple

from skypilot_tpu.clouds import catalog_cloud
from skypilot_tpu.clouds import cloud as cloud_lib
from skypilot_tpu.utils import registry

if typing.TYPE_CHECKING:
    from skypilot_tpu import resources as resources_lib


@registry.CLOUD_REGISTRY.register()
class Cudo(catalog_cloud.CatalogCloud):
    _REPR = 'Cudo'

    _UNSUPPORTED = {
        cloud_lib.CloudImplementationFeatures.SPOT_INSTANCE:
            'Cudo has no spot market.',
        cloud_lib.CloudImplementationFeatures.OPEN_PORTS:
            'Cudo VMs expose all ports; none to manage.',
        cloud_lib.CloudImplementationFeatures.CUSTOM_DISK_TIER:
            'Cudo boot disks have a single tier.',
    }

    @property
    def provisioner_module(self) -> str:
        return 'cudo'

    def unsupported_features_for_resources(
            self, resources: 'resources_lib.Resources'
    ) -> Dict[cloud_lib.CloudImplementationFeatures, str]:
        return dict(self._UNSUPPORTED)

    def make_deploy_resources_variables(
            self, resources: 'resources_lib.Resources', cluster_name: str,
            region: str, zone: Optional[str]) -> Dict[str, Any]:
        itype = resources.instance_type
        vars: Dict[str, Any] = {
            'cluster_name': cluster_name,
            'region': region,
            'zone': None,
            'instance_type': itype,
            'image_id': resources.image_id,
            'disk_size': resources.disk_size,
            'use_spot': False,
        }
        # The create call needs explicit vcpus/memory; take them from
        # the catalog row so billing matches the optimizer's estimate.
        for e in self._match_entries(itype, None, region, None):
            vars.update({'vcpus': int(e.vcpus),
                         'memory_gib': int(e.memory_gib)})
            break
        if resources.accelerators:
            name, count = next(iter(resources.accelerators.items()))
            vars.update({'gpu_type': name, 'gpu_count': count})
        return vars

    def provider_config_overrides(
            self, node_config: Dict[str, Any]) -> Dict[str, Any]:
        del node_config
        return {}

    def check_credentials(self) -> Tuple[bool, Optional[str]]:
        from skypilot_tpu.provision.cudo import rest
        if rest.load_credentials() is not None:
            return True, None
        return False, (
            'Cudo credentials not found. Set $CUDO_API_KEY + '
            f'$CUDO_PROJECT_ID or populate {rest.CREDENTIALS_PATH} '
            '(key/project).')

    def get_credential_file_mounts(self) -> Dict[str, str]:
        from skypilot_tpu.provision.cudo import rest
        if os.path.exists(os.path.expanduser(rest.CREDENTIALS_PATH)):
            return {rest.CREDENTIALS_PATH: rest.CREDENTIALS_PATH}
        return {}

    def get_egress_cost(self, num_gigabytes: float) -> float:
        return 0.0
