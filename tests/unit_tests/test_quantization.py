"""Int8 weight-only quantization (ops/quantization.py).

Parity is asserted against the bf16 path for all four families' serve
stacks plus the slot engine end-to-end; the HBM claim (half the bytes)
is asserted on the quantized pytree directly.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from skypilot_tpu.ops import quantization as qops


class TestQuantizedTensor:

    def test_roundtrip_error_bound(self):
        w = jax.random.normal(jax.random.PRNGKey(0), (64, 32),
                              jnp.float32)
        qt = qops.quantize(w)
        back = qops.dequantize(qt, jnp.float32)
        # Symmetric int8: per-channel error ≤ scale/2 = max|w|/254.
        err = jnp.abs(back - w)
        bound = jnp.max(jnp.abs(w), axis=0) / 254 + 1e-6
        assert bool(jnp.all(err <= bound[None, :]))

    def test_matmul_parity(self):
        k1, k2 = jax.random.split(jax.random.PRNGKey(1))
        x = jax.random.normal(k1, (4, 64), jnp.float32)
        w = jax.random.normal(k2, (64, 32), jnp.float32)
        exact = x @ w
        approx = qops.matmul(x, qops.quantize(w))
        rel = (jnp.linalg.norm(approx - exact) /
               jnp.linalg.norm(exact))
        assert float(rel) < 0.01
        # Plain arrays pass through exactly.
        np.testing.assert_array_equal(np.asarray(qops.matmul(x, w)),
                                      np.asarray(exact))

    def test_embed_rows_parity(self):
        table = jax.random.normal(jax.random.PRNGKey(2), (100, 16),
                                  jnp.float32)
        qt = qops.quantize(table, axis=-1)
        tokens = jnp.array([3, 7, 99])
        exact = table[tokens]
        approx = qops.embed_rows(qt, tokens)
        assert float(jnp.max(jnp.abs(approx - exact))) < 0.02
        np.testing.assert_array_equal(
            np.asarray(qops.embed_rows(table, tokens)),
            np.asarray(exact))

    def test_scan_slices_stay_paired(self):
        """A stacked [L, in, out] QuantizedTensor scans layer-by-layer
        (q and scale slice together; axis=-2 stays valid)."""
        w = jax.random.normal(jax.random.PRNGKey(3), (3, 16, 8),
                              jnp.float32)
        qt = qops.quantize(w)
        x = jax.random.normal(jax.random.PRNGKey(4), (2, 16), jnp.float32)

        def body(carry, layer_w):
            return carry, qops.matmul(x, layer_w)

        _, outs = jax.lax.scan(body, 0, qt)
        assert outs.shape == (3, 2, 8)
        exact = jnp.einsum('bi,lio->lbo', x, w)
        rel = jnp.linalg.norm(outs - exact) / jnp.linalg.norm(exact)
        assert float(rel) < 0.01

    def test_quantize_params_structure_and_bytes(self):
        from skypilot_tpu.models import llama
        c = llama.LLAMA_TINY
        params = llama.init(c, jax.random.PRNGKey(0))
        qparams = qops.quantize_params(params)
        # Norms stay full precision; weights become QuantizedTensor.
        assert isinstance(qparams['layers']['wq'], qops.QuantizedTensor)
        assert isinstance(qparams['embed'], qops.QuantizedTensor)
        assert qparams['embed'].axis == -1
        assert not isinstance(qparams['layers']['attn_norm'],
                              qops.QuantizedTensor)
        assert not isinstance(qparams['final_norm'],
                              qops.QuantizedTensor)
        # ~half the HBM (int8 vs bf16; scales are a rounding error).
        ratio = (qops.params_nbytes(qparams) /
                 qops.params_nbytes(params))
        assert 0.45 < ratio < 0.62
        # Idempotent.
        again = qops.quantize_params(qparams)
        assert again['layers']['wq'] is qparams['layers']['wq']


def _family_logits(model_lib, config, params, tokens):
    """Serve-path logits: prefill_hidden → lm_logits."""
    hidden, _ = model_lib.prefill_hidden(
        config, params, tokens, jnp.int32(tokens.shape[1]))
    return model_lib.lm_logits(config, params, hidden)


@pytest.mark.parametrize('family', ['llama', 'qwen', 'gemma', 'moe'])
def test_family_serve_parity(family):
    """Quantized-weight logits track bf16 logits closely enough that
    greedy decoding is unaffected on a random tiny model."""
    from skypilot_tpu import models as models_pkg
    from skypilot_tpu.models import gemma, llama, moe, qwen
    cfg = {'llama': llama.LLAMA_TINY, 'qwen': qwen.QWEN_TINY,
           'gemma': gemma.GEMMA_TINY, 'moe': moe.MOE_TINY}[family]
    model_lib = models_pkg.module_for(cfg)
    params = model_lib.init(cfg, jax.random.PRNGKey(0))
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0,
                                cfg.vocab_size)
    exact = _family_logits(model_lib, cfg, params, tokens)
    approx = _family_logits(model_lib, cfg,
                            qops.quantize_params(params), tokens)
    rel = (jnp.linalg.norm(approx - exact) /
           jnp.linalg.norm(exact))
    assert float(rel) < 0.05, f'{family}: rel logit error {rel}'


def test_synthetic_quantized_params_serve():
    """The bench's direct-to-int8 initializer (no bf16 tree is ever
    materialized) produces a tree the serve path runs on."""
    import functools
    from skypilot_tpu.models import llama
    cfg = llama.LLAMA_TINY
    shapes = jax.eval_shape(functools.partial(llama.init, cfg),
                            jax.random.PRNGKey(0))
    params = qops.synthetic_quantized_params(shapes, jax.random.PRNGKey(1))
    assert isinstance(params['layers']['wq'], qops.QuantizedTensor)
    assert params['layers']['wq'].q.dtype == jnp.int8
    # Same tree structure as a real init (so sharding rules etc. apply).
    real = jax.tree_util.tree_structure(
        qops.quantize_params(llama.init(cfg, jax.random.PRNGKey(0))))
    assert jax.tree_util.tree_structure(params) == real
    tokens = jnp.zeros((1, 8), jnp.int32)
    logits = _family_logits(llama, cfg, params, tokens)
    assert bool(jnp.all(jnp.isfinite(logits)))


def test_engine_int8_weights_decode_parity():
    """End-to-end slot engine: int8 weights produce the same greedy
    tokens as bf16 weights on a tiny model."""
    from skypilot_tpu.infer import engine as engine_lib
    from skypilot_tpu.models import llama

    cfg_model = llama.LLAMA_TINY
    params = llama.init(cfg_model, jax.random.PRNGKey(0))
    prompt = list(range(2, 10))

    def greedy_tokens(weight_dtype):
        config = engine_lib.EngineConfig(
            model=cfg_model, max_slots=2, max_target_len=64,
            prefill_buckets=(16,), weight_dtype=weight_dtype)
        engine = engine_lib.InferenceEngine(config, params)
        state = engine.init_decode_state()
        first, kv, true_len = engine.prefill(jnp.array(prompt))
        state = engine.insert(state, kv, first, true_len, slot=0)
        out = [int(jax.device_get(first))]
        for _ in range(8):
            state, sampled = engine.decode_step(state)
            out.append(int(jax.device_get(sampled[0])))
        return out

    bf16 = greedy_tokens(jnp.bfloat16)
    int8 = greedy_tokens(jnp.int8)
    # Random tiny models have near-flat logits, so allow one divergence
    # step; on real checkpoints the margin is far larger.
    agree = sum(a == b for a, b in zip(bf16, int8))
    assert agree >= len(bf16) - 1, (bf16, int8)


class TestInt4:
    """Packed-nibble int4 with group-wise scales (Quantized4Tensor)."""

    def test_pack_unpack_roundtrip(self):
        q = jax.random.randint(jax.random.PRNGKey(0), (8, 64, 32),
                               -8, 8, jnp.int8)
        packed = qops._pack4(q, -2)
        assert packed.shape == (8, 32, 32)
        back = qops._unpack4(packed, -2)
        assert bool(jnp.all(back == q))

    def test_quantize4_error_bound(self):
        w = jax.random.normal(jax.random.PRNGKey(1), (256, 32),
                              jnp.float32)
        qt = qops.quantize4(w, group=128)
        assert qt.q_packed.shape == (128, 32)
        assert qt.scale.shape == (2, 32)
        back = qops.dequantize4(qt, jnp.float32)
        # Symmetric int4: error ≤ scale/2 per group (+1 LSB for the
        # clip at -8).
        err = jnp.abs(back - w)
        bound = jnp.repeat(qt.scale, 128, axis=0)
        assert bool(jnp.all(err <= bound * 0.75 + 1e-6))

    def test_matmul_parity(self):
        k1, k2 = jax.random.split(jax.random.PRNGKey(2))
        x = jax.random.normal(k1, (4, 256), jnp.float32)
        w = jax.random.normal(k2, (256, 64), jnp.float32)
        out_q = qops.matmul(x, qops.quantize4(w))
        out_ref = x @ w
        # int4 carries ~16x the int8 step size; the bound is loose but
        # excludes layout/sign bugs (those produce O(1) errors).
        rel = float(jnp.max(jnp.abs(out_q - out_ref)) /
                    jnp.max(jnp.abs(out_ref)))
        assert rel < 0.15, rel

    def test_scan_slices_stay_paired(self):
        """Stacked [L, in, out] weights under lax.scan: q_packed and
        scale must slice together (pytree registration)."""
        w = jax.random.normal(jax.random.PRNGKey(3), (3, 256, 16),
                              jnp.float32)
        qt = qops.quantize4(w)

        def body(carry, layer_qt):
            return carry, qops.matmul(carry, layer_qt)

        x = jax.random.normal(jax.random.PRNGKey(4), (2, 256),
                              jnp.float32)
        _, outs = jax.lax.scan(body, x, qt)
        refs = jnp.stack([x @ qops.dequantize4(
            qops.quantize4(w[i]), jnp.float32) for i in range(3)])
        assert bool(jnp.allclose(outs, refs, atol=1e-4))

    def test_quantize_params_int4_mixed_tree(self):
        from skypilot_tpu.models import llama
        params = llama.init(llama.LLAMA_TINY, jax.random.PRNGKey(0))
        q4 = qops.quantize_params_int4(params)
        # Dense matmul weights → int4; embedding stays int8 (per-row
        # gather); norms untouched.
        assert isinstance(q4['layers']['wq'], qops.Quantized4Tensor)
        assert isinstance(q4['lm_head'], qops.Quantized4Tensor)
        assert isinstance(q4['embed'], qops.QuantizedTensor)
        assert q4['final_norm'].dtype == params['final_norm'].dtype
        # Idempotent.
        again = qops.quantize_params_int4(q4)
        assert again['layers']['wq'] is q4['layers']['wq']
        # ~half the int8 bytes for the int4-eligible leaves.
        int8_tree = qops.quantize_params(params)
        assert (qops.params_nbytes(q4) <
                0.75 * qops.params_nbytes(int8_tree))

    def test_engine_int4_weights_decode(self):
        from skypilot_tpu.infer import engine as engine_lib
        from skypilot_tpu.infer import orchestrator as orch_lib
        from skypilot_tpu.models import llama
        params = llama.init(llama.LLAMA_TINY, jax.random.PRNGKey(0))
        config = engine_lib.EngineConfig(
            model=llama.LLAMA_TINY, max_slots=2, max_target_len=32,
            prefill_buckets=(16,), weight_dtype='int4')
        engine = engine_lib.InferenceEngine(config, params)
        out = orch_lib.Orchestrator(engine).generate(
            [[3, 1, 4, 1, 5]], max_new_tokens=6)
        assert len(out[0]) == 6
        assert all(0 <= t < llama.LLAMA_TINY.vocab_size for t in out[0])

    def test_synthetic_quantized4_params_serve(self):
        import functools
        from skypilot_tpu.infer import engine as engine_lib
        from skypilot_tpu.infer import orchestrator as orch_lib
        from skypilot_tpu.models import llama
        shapes = jax.eval_shape(
            functools.partial(llama.init, llama.LLAMA_TINY),
            jax.random.PRNGKey(0))
        params = qops.synthetic_quantized4_params(
            shapes, jax.random.PRNGKey(0))
        assert isinstance(params['layers']['w_up'],
                          qops.Quantized4Tensor)
        config = engine_lib.EngineConfig(
            model=llama.LLAMA_TINY, max_slots=2, max_target_len=32,
            prefill_buckets=(16,), weight_dtype='int4',
            kv_dtype=jnp.int8)
        engine = engine_lib.InferenceEngine(config, params)
        out = orch_lib.Orchestrator(engine).generate(
            [[1, 2, 3]], max_new_tokens=4)
        assert len(out[0]) == 4
