"""Host-side page allocator for the paged (blocked) KV cache.

The dense slot cache reserves ``max_target_len`` rows per slot up
front, so concurrency is capped by the WORST-case sequence length long
before HBM is: a 128-token chat completion on a 2048-row slot pins 16x
the KV it will ever touch ("Exploring the limits of Concurrency"
framing, PAPERS.md). The paged cache (vLLM-style) slices the KV arena
into fixed-size pages; each slot owns a block table mapping its
logical KV blocks to physical pages, and admission is gated by FREE
PAGES for the request's actual budget (prompt + max_new_tokens), not
by slot count.

Reservation policy: a slot's pages for its full token budget are
reserved at admission. That keeps the decode loop allocation-free (the
fused on-device loop can never outrun its pages mid-batch, so there is
no preemption/swap path to build or test) while still admitting by
true KV need — the concurrency win over dense reservation is
budget/max_target_len per request.

Everything here is plain-Python bookkeeping on the admission/release
path — sets and lists, no device work, no blocking primitives (the
allocator sits under the orchestrator's hot-path purity contract).

The sentinel page index ``num_pages`` marks unallocated block-table
entries: device-side scatters to it are DROPPED (JAX out-of-bounds
update semantics), and the paged attention kernels clamp it before
indexing — a released slot still ticking inside a fused decode batch
can therefore never write into a page that was re-issued to a new
request.
"""
from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np


class PageAllocator:
    """Free-list allocator mapping decode slots to KV-cache pages.

    One instance covers every layer: the cache layout is
    [L, num_pages, page_size, ...], so a "page" here is the same
    physical page in all L layers and one table serves the whole stack.
    """

    def __init__(self, num_pages: int, page_size: int,
                 blocks_per_slot: int) -> None:
        if num_pages <= 0 or page_size <= 0 or blocks_per_slot <= 0:
            raise ValueError(
                f'PageAllocator needs positive sizes, got '
                f'num_pages={num_pages} page_size={page_size} '
                f'blocks_per_slot={blocks_per_slot}')
        self.num_pages = num_pages
        self.page_size = page_size
        self.blocks_per_slot = blocks_per_slot
        # LIFO free list: recently-released pages are re-issued first
        # (their rows are hottest in whatever cache level still holds
        # them, and reuse keeps the touched footprint small).
        self._free: List[int] = list(range(num_pages - 1, -1, -1))
        self._owned: Dict[int, List[int]] = {}

    # ---- queries ----

    @property
    def sentinel(self) -> int:
        """Block-table value meaning "no page": device writes to it are
        dropped, kernel reads clamp it."""
        return self.num_pages

    @property
    def free_pages(self) -> int:
        return len(self._free)

    @property
    def used_pages(self) -> int:
        return self.num_pages - len(self._free)

    def pages_for(self, tokens: int) -> int:
        """Pages needed to hold `tokens` KV rows."""
        return -(-max(int(tokens), 0) // self.page_size)

    def can_admit(self, tokens: int) -> bool:
        """Whether a request needing `tokens` total KV rows fits the
        free list AND a slot's block table right now."""
        need = self.pages_for(tokens)
        return need <= len(self._free) and need <= self.blocks_per_slot

    def slot_pages(self, slot: int) -> Optional[List[int]]:
        pages = self._owned.get(slot)
        return None if pages is None else list(pages)

    # ---- allocate / release ----

    def allocate(self, slot: int, tokens: int) -> bool:
        """Reserve pages covering `tokens` KV rows for `slot`.

        False (and no state change) when the free list or the slot's
        block table cannot cover it — the caller defers admission.
        Double allocation of a live slot is a scheduler bug, not a
        recoverable condition.
        """
        if slot in self._owned:
            raise ValueError(f'slot {slot} already holds '
                             f'{len(self._owned[slot])} pages')
        need = self.pages_for(tokens)
        if need > len(self._free) or need > self.blocks_per_slot:
            return False
        self._owned[slot] = [self._free.pop() for _ in range(need)]
        return True

    def release(self, slot: int) -> None:
        """Return a slot's pages to the free list (idempotent: release
        of a slot that holds nothing is a no-op, so every
        finish/cancel/failure path can call it unconditionally)."""
        pages = self._owned.pop(slot, None)
        if pages:
            self._free.extend(reversed(pages))

    def release_all(self) -> None:
        for slot in list(self._owned):
            self.release(slot)

    # ---- block-table rows ----

    def table_row(self, slot: int) -> np.ndarray:
        """The slot's full block-table row [blocks_per_slot] int32:
        physical page per logical block, sentinel beyond the
        reservation (and everywhere for an unallocated slot)."""
        row = np.full((self.blocks_per_slot,), self.sentinel, np.int32)
        pages = self._owned.get(slot)
        if pages:
            row[:len(pages)] = pages
        return row
