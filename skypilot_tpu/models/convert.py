"""HuggingFace checkpoint → in-tree param-tree conversion.

The reference serves public checkpoints through vLLM/torch recipes
(llm/vllm/serve.yaml, llm/llama-3_1-finetuning); here the framework
owns its models, so it owns the weight import too:

    from skypilot_tpu.models import convert
    config, params = convert.from_hf('/ckpts/Llama-3.1-8B')

or from the CLI (saves an orbax dir the trainer/server can load):

    python -m skypilot_tpu.models.convert \
        --src /ckpts/Llama-3.1-8B --out /ckpts/llama31-xsky

Supported families: Llama/Mistral (LlamaConfig), Qwen-2/3 (QwenConfig,
qkv biases + qk-norm), Gemma (tied head, (1+w) norms — weights map
directly since the in-tree gemma uses the same convention). Safetensors
shards are streamed tensor-by-tensor (an 8B never needs a torch model
instantiated); `.bin` checkpoints fall back to torch.load. Layer
weights stack to the in-tree `[L, in, out]` scan layout with the
contraction transposed from torch's `[out, in]`.

Numeric parity with the HF implementations is test-pinned
(tests/unit_tests/test_hf_convert.py): logits from converted weights
match transformers' forward on the same tokens.
"""
from __future__ import annotations

import argparse
import dataclasses
import json
import os
import sys
from typing import Any, Callable, Dict, Tuple

import numpy as np

Params = Dict[str, Any]


class _TensorSource:
    """Uniform tensor access over safetensors shards / torch bins /
    an in-memory transformers model's state_dict."""

    def __init__(self, src) -> None:
        self._get: Callable[[str], np.ndarray]
        if not isinstance(src, (str, os.PathLike)):
            state = {k: v.detach().cpu().float().numpy()
                     for k, v in src.state_dict().items()}
            # transformers state_dicts may or may not carry the
            # 'model.' prefix depending on how the module was built.
            self._keys = set(state)
            self._get = state.__getitem__
            self.config = json.loads(src.config.to_json_string())
            return
        src = str(src)
        with open(os.path.join(src, 'config.json'),
                  encoding='utf-8') as f:
            self.config = json.load(f)
        st_files = sorted(
            f for f in os.listdir(src) if f.endswith('.safetensors'))
        if st_files:
            from safetensors import safe_open
            self._handles = [safe_open(os.path.join(src, f),
                                       framework='numpy')
                             for f in st_files]
            self._where = {}
            for handle in self._handles:
                for key in handle.keys():
                    self._where[key] = handle
            self._keys = set(self._where)
            self._get = lambda k: np.asarray(
                self._where[k].get_tensor(k), np.float32)
            return
        import torch
        bins = sorted(f for f in os.listdir(src)
                      if f.endswith('.bin') and 'pytorch_model' in f)
        if not bins:
            raise FileNotFoundError(
                f'{src}: no *.safetensors or pytorch_model*.bin')
        state = {}
        for b in bins:
            state.update(torch.load(os.path.join(src, b),
                                    map_location='cpu',
                                    weights_only=True))
        state = {k: v.float().numpy() for k, v in state.items()}
        self._keys = set(state)
        self._get = state.__getitem__

    def __contains__(self, key: str) -> bool:
        return key in self._keys or f'model.{key}' in self._keys

    def get(self, key: str) -> np.ndarray:
        if key in self._keys:
            return np.asarray(self._get(key), np.float32)
        return np.asarray(self._get(f'model.{key}'), np.float32)


def _stack(source: _TensorSource, template: str, n_layers: int,
           transpose: bool) -> np.ndarray:
    rows = []
    for i in range(n_layers):
        t = source.get(template.format(i=i))
        rows.append(t.T if transpose else t)
    return np.stack(rows)


def _detect_family(hf_config: dict) -> str:
    mt = hf_config.get('model_type', '')
    if mt in ('qwen2', 'qwen3'):
        return 'qwen'
    if mt in ('gemma', 'gemma2'):
        return 'gemma'
    if mt in ('llama', 'mistral'):
        return 'llama'
    if mt == 'mixtral':
        return 'moe'
    raise ValueError(f'Unsupported HF model_type {mt!r} (supported: '
                     'llama, mistral, qwen2, qwen3, gemma, mixtral)')


def _common_layers(source: _TensorSource, n_layers: int) -> Params:
    p = 'layers.{i}.'
    return {
        'wq': _stack(source, p + 'self_attn.q_proj.weight', n_layers,
                     transpose=True),
        'wk': _stack(source, p + 'self_attn.k_proj.weight', n_layers,
                     transpose=True),
        'wv': _stack(source, p + 'self_attn.v_proj.weight', n_layers,
                     transpose=True),
        'wo': _stack(source, p + 'self_attn.o_proj.weight', n_layers,
                     transpose=True),
        'w_gate': _stack(source, p + 'mlp.gate_proj.weight', n_layers,
                         transpose=True),
        'w_up': _stack(source, p + 'mlp.up_proj.weight', n_layers,
                       transpose=True),
        'w_down': _stack(source, p + 'mlp.down_proj.weight', n_layers,
                         transpose=True),
        'attn_norm': _stack(source, p + 'input_layernorm.weight',
                            n_layers, transpose=False),
        'mlp_norm': _stack(source,
                           p + 'post_attention_layernorm.weight',
                           n_layers, transpose=False),
    }


def _lm_head(source: _TensorSource, hf: dict) -> np.ndarray:
    if not hf.get('tie_word_embeddings', False):
        if 'lm_head.weight' not in source:
            # Falling back to the tied embedding here would produce
            # wrong logits with no error — fail loudly like the rest of
            # the converter does for unsupported variants.
            raise ValueError(
                'checkpoint declares tie_word_embeddings=false but has '
                'no lm_head.weight tensor; refusing to silently reuse '
                'the embedding as the output head')
        return source.get('lm_head.weight').T
    return source.get('embed_tokens.weight').T


def _rope_scaling_tuple(hf: dict):
    """HF rope_scaling → the in-tree (factor, low, high, orig_ctx)
    tuple; None when absent/default; raise on schemes the in-tree RoPE
    does not implement (silently dropping one changes attention)."""
    rs = hf.get('rope_scaling')
    if not rs:
        return None
    rope_type = rs.get('rope_type') or rs.get('type')
    if rope_type in (None, 'default'):
        return None
    if rope_type == 'llama3':
        return (float(rs['factor']),
                float(rs.get('low_freq_factor', 1.0)),
                float(rs.get('high_freq_factor', 4.0)),
                int(rs['original_max_position_embeddings']))
    raise ValueError(f'Unsupported rope_scaling type {rope_type!r} '
                     "(supported: 'llama3', 'default').")


def _check_head_dim(hf: dict) -> None:
    derived = hf['hidden_size'] // hf['num_attention_heads']
    explicit = hf.get('head_dim')
    if explicit is not None and explicit != derived:
        raise ValueError(
            f"checkpoint head_dim {explicit} != hidden_size/num_heads "
            f'{derived}; this family config derives head_dim, so the '
            'converted weights would not reshape (e.g. Mistral-Nemo).')


def _convert_llama(source: _TensorSource, dtype):
    import jax.numpy as jnp
    from skypilot_tpu.models import llama
    hf = source.config
    n_layers = hf['num_hidden_layers']
    _check_head_dim(hf)
    config = llama.LlamaConfig(
        vocab_size=hf['vocab_size'],
        d_model=hf['hidden_size'],
        n_layers=n_layers,
        n_heads=hf['num_attention_heads'],
        n_kv_heads=hf.get('num_key_value_heads',
                          hf['num_attention_heads']),
        d_ff=hf['intermediate_size'],
        max_seq_len=hf.get('max_position_embeddings', 8192),
        rope_theta=float(hf.get('rope_theta', 10_000.0)),
        norm_eps=float(hf.get('rms_norm_eps', 1e-5)),
        sliding_window=hf.get('sliding_window'),
        rope_scaling=_rope_scaling_tuple(hf),
        dtype=dtype,
    )
    cast = lambda a: jnp.asarray(a, dtype)
    params = {
        'embed': cast(source.get('embed_tokens.weight')),
        'layers': {k: cast(v) for k, v in
                   _common_layers(source, n_layers).items()},
        'final_norm': cast(source.get('norm.weight')),
        'lm_head': cast(_lm_head(source, hf)),
    }
    return config, params


def _convert_qwen(source: _TensorSource, dtype):
    import jax.numpy as jnp
    from skypilot_tpu.models import qwen
    hf = source.config
    n_layers = hf['num_hidden_layers']
    if _rope_scaling_tuple(hf) is not None:
        raise ValueError('rope_scaling is not supported for qwen '
                         'conversion yet.')
    qkv_bias = 'layers.0.self_attn.q_proj.bias' in source
    qk_norm = 'layers.0.self_attn.q_norm.weight' in source
    config = qwen.QwenConfig(
        vocab_size=hf['vocab_size'],
        d_model=hf['hidden_size'],
        n_layers=n_layers,
        n_heads=hf['num_attention_heads'],
        n_kv_heads=hf.get('num_key_value_heads',
                          hf['num_attention_heads']),
        head_dim=hf.get('head_dim', hf['hidden_size'] //
                        hf['num_attention_heads']),
        d_ff=hf['intermediate_size'],
        max_seq_len=hf.get('max_position_embeddings', 8192),
        rope_theta=float(hf.get('rope_theta', 1e6)),
        norm_eps=float(hf.get('rms_norm_eps', 1e-6)),
        qkv_bias=qkv_bias,
        qk_norm=qk_norm,
        dtype=dtype,
    )
    cast = lambda a: jnp.asarray(a, dtype)
    layers = {k: cast(v) for k, v in
              _common_layers(source, n_layers).items()}
    p = 'layers.{i}.'
    if qkv_bias:
        layers['bq'] = cast(_stack(source, p + 'self_attn.q_proj.bias',
                                   n_layers, transpose=False))
        layers['bk'] = cast(_stack(source, p + 'self_attn.k_proj.bias',
                                   n_layers, transpose=False))
        layers['bv'] = cast(_stack(source, p + 'self_attn.v_proj.bias',
                                   n_layers, transpose=False))
    if qk_norm:
        layers['q_norm'] = cast(_stack(
            source, p + 'self_attn.q_norm.weight', n_layers,
            transpose=False))
        layers['k_norm'] = cast(_stack(
            source, p + 'self_attn.k_norm.weight', n_layers,
            transpose=False))
    params = {
        'embed': cast(source.get('embed_tokens.weight')),
        'layers': layers,
        'final_norm': cast(source.get('norm.weight')),
        'lm_head': cast(_lm_head(source, hf)),
    }
    return config, params


def _convert_gemma(source: _TensorSource, dtype):
    import jax.numpy as jnp
    from skypilot_tpu.models import gemma
    hf = source.config
    n_layers = hf['num_hidden_layers']
    gemma2 = hf.get('model_type') == 'gemma2'
    if _rope_scaling_tuple(hf) is not None:
        raise ValueError('rope_scaling is not supported for gemma '
                         'conversion yet.')
    attn_scale = None
    if gemma2:
        scalar = hf.get('query_pre_attn_scalar')
        if scalar:
            attn_scale = float(scalar) ** -0.5
    config = gemma.GemmaConfig(
        vocab_size=hf['vocab_size'],
        d_model=hf['hidden_size'],
        n_layers=n_layers,
        n_heads=hf['num_attention_heads'],
        n_kv_heads=hf.get('num_key_value_heads',
                          hf['num_attention_heads']),
        head_dim=hf.get('head_dim', hf['hidden_size'] //
                        hf['num_attention_heads']),
        d_ff=hf['intermediate_size'],
        max_seq_len=hf.get('max_position_embeddings', 8192),
        rope_theta=float(hf.get('rope_theta', 10_000.0)),
        norm_eps=float(hf.get('rms_norm_eps', 1e-6)),
        final_logit_softcap=hf.get('final_logit_softcapping'),
        gemma2=gemma2,
        attn_logit_softcap=(hf.get('attn_logit_softcapping')
                            if gemma2 else None),
        attn_scale=attn_scale,
        sliding_window=hf.get('sliding_window') if gemma2 else None,
        dtype=dtype,
    )
    cast = lambda a: jnp.asarray(a, dtype)
    # Gemma norms share the (1 + w) convention with the in-tree model,
    # so weights map directly; the head is tied to the embedding.
    layers = {k: cast(v) for k, v in
              _common_layers(source, n_layers).items()}
    if gemma2:
        p = 'layers.{i}.'
        # Gemma-2 renames: input_layernorm stays the pre-attention
        # norm; post_attention_layernorm becomes an OUTPUT norm; the
        # pre-MLP norm is pre_feedforward_layernorm.
        layers['post_attn_norm'] = layers.pop('mlp_norm')
        layers['mlp_norm'] = cast(_stack(
            source, p + 'pre_feedforward_layernorm.weight', n_layers,
            transpose=False))
        layers['post_ffw_norm'] = cast(_stack(
            source, p + 'post_feedforward_layernorm.weight', n_layers,
            transpose=False))
    params = {
        'embed': cast(source.get('embed_tokens.weight')),
        'layers': layers,
        'final_norm': cast(source.get('norm.weight')),
    }
    return config, params


def _convert_mixtral(source: _TensorSource, dtype):
    import jax.numpy as jnp
    from skypilot_tpu.models import moe
    hf = source.config
    n_layers = hf['num_hidden_layers']
    n_experts = hf['num_local_experts']
    _check_head_dim(hf)
    config = moe.MoEConfig(
        vocab_size=hf['vocab_size'],
        d_model=hf['hidden_size'],
        n_layers=n_layers,
        n_heads=hf['num_attention_heads'],
        n_kv_heads=hf.get('num_key_value_heads',
                          hf['num_attention_heads']),
        d_ff=hf['intermediate_size'],
        max_seq_len=hf.get('max_position_embeddings', 8192),
        rope_theta=float(hf.get('rope_theta', 1e6)),
        norm_eps=float(hf.get('rms_norm_eps', 1e-5)),
        sliding_window=hf.get('sliding_window'),
        rope_scaling=_rope_scaling_tuple(hf),
        n_experts=n_experts,
        experts_per_token=hf.get('num_experts_per_tok', 2),
        dtype=dtype,
    )
    cast = lambda a: jnp.asarray(a, dtype)
    p = 'layers.{i}.'

    def expert_stack(name: str) -> np.ndarray:
        # [L, E, in, out]: HF stores each expert's [out, in] matrix
        # separately; w1 = gate (silu input), w3 = up, w2 = down —
        # routing weights already match (softmax → top-k → renorm).
        return np.stack([
            np.stack([source.get(
                p.format(i=i) +
                f'block_sparse_moe.experts.{e}.{name}.weight').T
                for e in range(n_experts)])
            for i in range(n_layers)])

    layers = {
        'wq': cast(_stack(source, p + 'self_attn.q_proj.weight',
                          n_layers, transpose=True)),
        'wk': cast(_stack(source, p + 'self_attn.k_proj.weight',
                          n_layers, transpose=True)),
        'wv': cast(_stack(source, p + 'self_attn.v_proj.weight',
                          n_layers, transpose=True)),
        'wo': cast(_stack(source, p + 'self_attn.o_proj.weight',
                          n_layers, transpose=True)),
        # Router stays fp32 (routing decisions are precision-sensitive,
        # matching the in-tree init).
        'router': jnp.asarray(
            _stack(source, p + 'block_sparse_moe.gate.weight',
                   n_layers, transpose=True), jnp.float32),
        'w_gate': cast(expert_stack('w1')),
        'w_up': cast(expert_stack('w3')),
        'w_down': cast(expert_stack('w2')),
        'attn_norm': cast(_stack(source, p + 'input_layernorm.weight',
                                 n_layers, transpose=False)),
        'mlp_norm': cast(_stack(
            source, p + 'post_attention_layernorm.weight', n_layers,
            transpose=False)),
    }
    params = {
        'embed': cast(source.get('embed_tokens.weight')),
        'layers': layers,
        'final_norm': cast(source.get('norm.weight')),
        'lm_head': cast(_lm_head(source, hf)),
    }
    return config, params


def from_hf(src, dtype=None) -> Tuple[Any, Params]:
    """(config, params) from a local HF checkpoint directory or an
    in-memory transformers model. `dtype` defaults to bfloat16."""
    import jax.numpy as jnp
    dtype = dtype or jnp.bfloat16
    source = _TensorSource(src)
    family = _detect_family(source.config)
    return {
        'llama': _convert_llama,
        'qwen': _convert_qwen,
        'gemma': _convert_gemma,
        'moe': _convert_mixtral,
    }[family](source, dtype)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        description='Convert a local HF checkpoint to the in-tree '
                    'param layout (orbax).')
    parser.add_argument('--src', required=True,
                        help='HF checkpoint dir (config.json + '
                             'safetensors or pytorch_model*.bin)')
    parser.add_argument('--out', required=True,
                        help='Output orbax checkpoint dir')
    parser.add_argument('--dtype', default='bf16',
                        choices=['bf16', 'f32'])
    args = parser.parse_args(argv)
    import jax.numpy as jnp
    import orbax.checkpoint as ocp
    config, params = from_hf(
        args.src, jnp.bfloat16 if args.dtype == 'bf16' else jnp.float32)
    ckptr = ocp.StandardCheckpointer()
    ckptr.save(os.path.abspath(args.out), params)
    ckptr.wait_until_finished()
    meta = dataclasses.asdict(config)
    meta['dtype'] = args.dtype
    meta['family'] = type(config).__name__
    with open(os.path.join(args.out, 'xsky_model.json'), 'w',
              encoding='utf-8') as f:
        json.dump(meta, f, indent=1, default=str)
    print(json.dumps({'out': args.out,
                      'family': meta['family'],
                      'params': int(config.num_params())}))
    return 0


if __name__ == '__main__':
    sys.exit(main())
