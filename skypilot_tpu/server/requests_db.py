"""API-server request table (twin of sky/server/requests/requests.py).

Every API call becomes a persisted request row; clients poll by id.
DB: ``~/.xsky/server/requests.db`` (XSKY_SERVER_DB overrides for tests).
"""
from __future__ import annotations

import enum
import json
import os
import pickle
import sqlite3
import threading
import time
import uuid
from typing import Any, Dict, List, Optional

# Same writer/reader split as skypilot_tpu/state.py: one write
# connection under _lock; reads go to per-thread WAL connections
# (db_utils.WalReadPool — the one shared implementation) so a fleet
# of pollers never queues behind a finish() commit. The
# XSKY_STATE_READ_POOL / XSKY_STATE_READ_WORKERS knobs are shared
# with state.py (one config surface, measured by
# tools/bench_controlplane.py).
_lock = threading.RLock()
_conn: Optional[sqlite3.Connection] = None
_conn_path: Optional[str] = None

_reader = None


class RequestStatus(enum.Enum):
    PENDING = 'PENDING'
    RUNNING = 'RUNNING'
    SUCCEEDED = 'SUCCEEDED'
    FAILED = 'FAILED'
    CANCELLED = 'CANCELLED'

    def is_terminal(self) -> bool:
        return self in (RequestStatus.SUCCEEDED, RequestStatus.FAILED,
                        RequestStatus.CANCELLED)


def _db_path() -> str:
    return os.path.expanduser(
        os.environ.get('XSKY_SERVER_DB', '~/.xsky/server/requests.db'))


def log_path(request_id: str) -> str:
    """Per-request captured-output file (`xsky api logs` reads it;
    twin of the reference's per-request log files,
    sky/server/requests/requests.py)."""
    return os.path.join(os.path.dirname(_db_path()), 'request_logs',
                        f'{request_id}.log')


def read_log(request_id: str, max_bytes: int = 1 << 20) -> str:
    path = log_path(request_id)
    if not os.path.exists(path):
        return ''
    size = os.path.getsize(path)
    with open(path, 'rb') as f:
        if size > max_bytes:
            f.seek(size - max_bytes)
        return f.read().decode('utf-8', errors='replace')


def _lock_retry_deadline_s() -> float:
    """Total time one write spends waiting out a peer's sqlite lock."""
    try:
        return float(os.environ.get('XSKY_DB_LOCK_RETRY_S', 5.0))
    except ValueError:
        return 5.0


def _retry_locked(fn, conn: Optional[sqlite3.Connection] = None):
    """Run a write, absorbing transient ``database is locked`` /
    ``database is busy`` OperationalErrors with jittered backoff.

    N API-server processes share one requests DB in multi-server mode
    (tools/bench_controlplane.py --multi-server), so the one-writer-
    per-process assumption no longer holds: the WAL conversion in
    :func:`_get_conn` and every enqueue/commit can lose a race for the
    sqlite write lock. Before this helper that surfaced as a raw
    OperationalError in the CLIENT's lap (a 500 on `xsky launch`).
    Bounded: a few attempts under ``XSKY_DB_LOCK_RETRY_S`` total — a
    wedged peer (not a transient race) still raises, and the original
    OperationalError is re-raised so callers' except clauses are
    unchanged. Jitter matters here: the losing writers are
    synchronized by construction (they all just lost the same lock).
    Pass ``conn`` so a transaction left half-open by a failed commit is
    rolled back before the next attempt re-runs the statements.

    The module writer lock is taken PER ATTEMPT, inside this helper:
    backing off while holding ``_lock`` would stall every other writer
    thread in this process for the whole cross-process wait.
    """
    from skypilot_tpu.utils import chaos
    from skypilot_tpu.utils import common_utils
    from skypilot_tpu.utils import resilience

    def _attempt():
        # Outside the writer lock: injection may journal to the state
        # DB, and a fault plan targeting this point wants to starve
        # the WRITE, not wedge every writer thread.
        chaos.inject('requests_db.write')
        with _lock:
            try:
                return fn()
            except sqlite3.OperationalError as e:
                msg = str(e).lower()
                if 'locked' in msg or 'busy' in msg:
                    if conn is not None:
                        try:
                            conn.rollback()
                        except sqlite3.Error:
                            pass
                    raise resilience.TransientError(str(e)) from e
                raise

    try:
        return resilience.retry_transient(
            _attempt,
            max_attempts=8,
            backoff=common_utils.Backoff(initial=0.02, factor=2.0,
                                         cap=0.5, jitter=0.5),
            deadline=resilience.Deadline(_lock_retry_deadline_s()))
    except resilience.TransientError as e:
        raise e.__cause__  # the original sqlite3.OperationalError


def _get_conn() -> sqlite3.Connection:
    global _conn, _conn_path
    path = _db_path()
    with _lock:
        if _conn is not None and _conn_path == path:
            return _conn
    # Built OUTSIDE the writer lock: schema init retries the WAL
    # conversion with backoff (_retry_locked takes the lock around
    # each attempt), and holding _lock across that wait would block
    # every writer thread behind one slow peer process. Losing a
    # same-process build race is handled below.
    os.makedirs(os.path.dirname(path), exist_ok=True)
    # xskylint: disable=db-discipline -- the requests DB is
    # per-API-server-LOCAL by design (each replica owns its
    # in-flight queue; leases arbitrate cross-replica work),
    # so it must not pick up db_utils.connect's XSKY_DB_URL
    # postgres routing; reads still go through StateReader.
    conn = sqlite3.connect(path, check_same_thread=False)

    def _init_schema() -> None:
        # WAL conversion takes the db lock exclusively — with
        # N server processes opening the same DB at startup
        # this is the most contended statement in the module,
        # so the whole init runs under _retry_locked.
        conn.execute('PRAGMA journal_mode=WAL')
        from skypilot_tpu.utils import db_utils
        conn.execute(
            f'PRAGMA synchronous={db_utils.sqlite_synchronous()}')
        conn.execute("""
            CREATE TABLE IF NOT EXISTS requests (
                request_id TEXT PRIMARY KEY,
                name TEXT,
                user TEXT,
                status TEXT,
                body TEXT,
                result BLOB,
                error TEXT,
                created_at REAL,
                finished_at REAL
            )""")
        try:
            # The request-scoped trace id, minted at
            # acceptance: `xsky trace <request-id>` resolves
            # through this column while the request is still
            # in flight (its root span is only written at
            # completion).
            conn.execute(
                'ALTER TABLE requests ADD COLUMN trace_id TEXT')
        except sqlite3.OperationalError as e:
            if 'duplicate column' not in str(e).lower():
                raise  # 'database is locked' must reach retry
        # list_inflight / fail_stale_inflight filter on status
        # and gc_finished range-scans finished_at under a
        # status filter — both were full table scans before
        # this index.
        conn.execute(
            'CREATE INDEX IF NOT EXISTS '
            'idx_requests_status_finished'
            ' ON requests (status, finished_at)')
        # list_requests orders newest-first; without this the
        # sort re-scans every row per listing page.
        conn.execute(
            'CREATE INDEX IF NOT EXISTS idx_requests_created '
            'ON requests (created_at)')
        conn.commit()

    _retry_locked(_init_schema, conn)
    with _lock:
        if _conn is None or _conn_path != path:
            _conn, _conn_path = conn, path
        elif conn is not _conn:
            conn.close()   # lost a same-process build race
        return _conn


def _ensure_writer() -> None:
    if _conn is None or _conn_path != _db_path():
        _get_conn()   # create the DB + table (once, under _lock)


def _get_reader():
    global _reader
    if _reader is None:
        from skypilot_tpu.utils import db_utils
        # Double-checked under _lock (see state._get_reader).
        with _lock:
            if _reader is None:
                _reader = db_utils.StateReader(_db_path, _ensure_writer,
                                               _get_conn, _lock)
    return _reader


def _read(sql: str, args=()):
    """One SELECT + fetchall off the write lock (pool on, the
    default); under it on the shared connection otherwise."""
    return _get_reader().fetchall(sql, args)


def _read_one(sql: str, args=()):
    return _get_reader().fetchone(sql, args)


def reset_for_test() -> None:
    global _conn, _conn_path
    with _lock:
        if _conn is not None:
            _conn.close()
        _conn = None
        _conn_path = None
        if _reader is not None:
            _reader.invalidate()   # lazily drop per-thread read conns


def create(name: str, user: str, body: Dict[str, Any],
           trace_id: Optional[str] = None) -> str:
    request_id = uuid.uuid4().hex
    conn = _get_conn()

    def _enqueue() -> None:
        conn.execute(
            'INSERT INTO requests (request_id, name, user, status, body, '
            'created_at, trace_id) VALUES (?, ?, ?, ?, ?, ?, ?)',
            (request_id, name, user, RequestStatus.PENDING.value,
             json.dumps(body, default=str), time.time(), trace_id))
        conn.commit()

    _retry_locked(_enqueue, conn)
    return request_id


def get_trace_id(request_id: str) -> Optional[str]:
    """The trace minted for this request at acceptance, or None."""
    row = _read_one('SELECT trace_id FROM requests WHERE request_id=?',
                    (request_id,))
    return row[0] if row else None


def set_trace_id(request_id: str, trace_id: Optional[str]) -> None:
    """Re-point the request at a new trace (requeue after a server
    crash: the fresh run's story must be the one the request id
    resolves to, not the dead server's)."""
    conn = _get_conn()

    def _write() -> None:
        conn.execute('UPDATE requests SET trace_id=? WHERE request_id=?',
                     (trace_id, request_id))
        conn.commit()

    _retry_locked(_write, conn)


def set_status(request_id: str, status: RequestStatus) -> None:
    conn = _get_conn()

    def _write() -> None:
        conn.execute('UPDATE requests SET status=? WHERE request_id=?',
                     (status.value, request_id))
        conn.commit()

    _retry_locked(_write, conn)


def finish(request_id: str, result: Any = None,
           error: Optional[Dict[str, Any]] = None) -> None:
    conn = _get_conn()
    status = RequestStatus.FAILED if error else RequestStatus.SUCCEEDED

    def _write() -> None:
        # Guard: a concurrent cancel must not be overwritten (the work
        # may have completed anyway, but CANCELLED is the user-visible
        # truth about what they asked for).
        conn.execute(
            'UPDATE requests SET status=?, result=?, error=?, '
            "finished_at=? WHERE request_id=? AND status IN "
            "('PENDING', 'RUNNING')",
            (status.value, pickle.dumps(result),
             json.dumps(error) if error else None, time.time(),
             request_id))
        conn.commit()

    _retry_locked(_write, conn)


def get_status(request_id: str) -> Optional[Dict[str, Any]]:
    """The poll fast path: status + identity WITHOUT body/result/error.

    ``get()`` json-parses the body and unpickles the result on every
    call — for a client polling a RUNNING launch (and the watchdog
    sweeping every in-flight row each tick) that deserialization buys
    nothing. This query reads only the cheap TEXT/REAL columns; callers
    upgrade to :func:`get` once the row is terminal and the
    result/error is actually needed.
    """
    row = _read_one(
        'SELECT request_id, name, user, status, created_at, '
        'finished_at, trace_id FROM requests WHERE request_id=?',
        (request_id,))
    if row is None:
        return None
    return {
        'request_id': row[0],
        'name': row[1],
        'user': row[2],
        'status': RequestStatus(row[3]),
        'created_at': row[4],
        'finished_at': row[5],
        'trace_id': row[6],
    }


def get(request_id: str) -> Optional[Dict[str, Any]]:
    row = _read_one(
        'SELECT request_id, name, user, status, body, result, error, '
        'created_at, finished_at, trace_id FROM requests '
        'WHERE request_id=?',
        (request_id,))
    if row is None:
        return None
    return {
        'request_id': row[0],
        'name': row[1],
        'user': row[2],
        'status': RequestStatus(row[3]),
        'body': json.loads(row[4] or '{}'),
        'result': pickle.loads(row[5]) if row[5] else None,
        'error': json.loads(row[6]) if row[6] else None,
        'created_at': row[7],
        'finished_at': row[8],
        'trace_id': row[9],
    }


def list_requests(limit: int = 100,
                  offset: int = 0) -> List[Dict[str, Any]]:
    """Newest requests first (request_id breaks created_at ties so
    pages are stable); served by the created_at index."""
    rows = _read(
        'SELECT request_id, name, user, status, created_at, '
        'finished_at FROM requests '
        'ORDER BY created_at DESC, request_id LIMIT ? OFFSET ?',
        (int(limit), max(int(offset), 0)))
    return [{
        'request_id': r[0], 'name': r[1], 'user': r[2], 'status': r[3],
        'created_at': r[4], 'finished_at': r[5],
    } for r in rows]


# Finished requests are kept this long before GC reclaims the row and
# its log file. Long enough for post-mortems and `xsky api logs`; short
# enough that a busy API server's DB and request_logs/ stay bounded.
_RETENTION_HOURS_ENV = 'XSKY_REQUEST_RETENTION_HOURS'
_DEFAULT_RETENTION_HOURS = 72.0
# Rows reclaimed per GC sweep (bounds one sweep's unlink + delete work).
_GC_BATCH = 5000


def gc_finished(now: Optional[float] = None) -> int:
    """Delete finished requests (and their log files) older than the
    retention window. Returns the number of rows reclaimed.

    Called opportunistically from the executor (every Nth submission)
    — a dedicated daemon would be one more thing to supervise for a
    sweep that takes milliseconds. PENDING/RUNNING rows are never
    touched regardless of age.
    """
    try:
        hours = float(os.environ.get(_RETENTION_HOURS_ENV,
                                     _DEFAULT_RETENTION_HOURS))
    except ValueError:
        hours = _DEFAULT_RETENTION_HOURS
    if hours <= 0:       # retention disabled
        return 0
    cutoff = (now if now is not None else time.time()) - hours * 3600
    terminal = tuple(s.value for s in RequestStatus if s.is_terminal())
    # Batched sweep (served by the (status, finished_at) index): one
    # opportunistic call deletes at most _GC_BATCH rows + log files so
    # a huge backlog cannot charge an unbounded sweep to the short
    # pool; the next sweep continues where this one stopped.
    rows = _read(
        'SELECT request_id FROM requests WHERE finished_at IS NOT '
        'NULL AND finished_at < ? AND status IN '
        f"({','.join('?' * len(terminal))}) "
        'ORDER BY finished_at LIMIT ?',
        (cutoff, *terminal, _GC_BATCH))
    ids = [r[0] for r in rows]
    if not ids:
        return 0
    # Log files first, rows after: a crash between the two leaves a
    # still-selectable row for the next sweep, whereas committing the
    # deletes first would orphan the files forever.
    for request_id in ids:
        try:
            os.remove(log_path(request_id))
        except OSError:
            pass
    conn = _get_conn()

    def _write() -> None:
        conn.executemany('DELETE FROM requests WHERE request_id=?',
                         [(i,) for i in ids])
        conn.commit()

    _retry_locked(_write, conn)
    return len(ids)


def list_inflight() -> List[Dict[str, Any]]:
    """PENDING/RUNNING rows with the fields reconciliation needs."""
    # full-scan ok: bounded by the executor's admission capacity (the
    # reconciler must see EVERY stranded row); the status filter is
    # served by the (status, finished_at) index.
    rows = _read(
        'SELECT request_id, name, user, status, body, created_at '
        'FROM requests WHERE status IN (?, ?) ORDER BY created_at',
        (RequestStatus.PENDING.value,
         RequestStatus.RUNNING.value))
    return [{
        'request_id': r[0], 'name': r[1], 'user': r[2],
        'status': RequestStatus(r[3]), 'body': json.loads(r[4] or '{}'),
        'created_at': r[5],
    } for r in rows]


def fail_request(request_id: str, message: str,
                 error_type: str = 'ServerRestart') -> bool:
    """Fail-abort one in-flight row with an explicit reason (terminal
    rows are left alone — repairs must be idempotent)."""
    conn = _get_conn()

    def _write() -> int:
        cur = conn.execute(
            "UPDATE requests SET status='FAILED', finished_at=?, "
            'error=? WHERE request_id=? AND status IN (?, ?)',
            (time.time(),
             json.dumps({'type': error_type, 'message': message}),
             request_id, RequestStatus.PENDING.value,
             RequestStatus.RUNNING.value))
        conn.commit()
        return cur.rowcount

    return _retry_locked(_write, conn) == 1


def fail_stale_inflight() -> int:
    """Fail-abort in-flight rows whose executor is provably gone.

    A crash/restart strands PENDING/RUNNING rows with finished_at=NULL
    — they would dodge retention GC forever and lie to pollers that
    the work is still running (no executor will ever finish them).
    Lease-aware: a row whose ``request/<id>`` liveness lease is still
    live belongs to a healthy executor (another API-server process on
    THIS host, or this process's own worker) and is left alone. Lease
    liveness probes local pids, so cross-host replicas sharing one DB
    are outside this guarantee — same single-host assumption as the
    scheduler's controller_pid checks.

    One code path with the reconciler (abort-only, no acceptance
    grace: the caller asserts nothing in this process has accepted
    work yet) so the two can never drift."""
    from skypilot_tpu import reconciler
    repairs = reconciler.reconcile_requests(requeue=False, grace_s=0)
    return sum(1 for r in repairs if r['action'] == 'request_aborted')


def mark_cancelled(request_id: str) -> bool:
    conn = _get_conn()

    def _write() -> int:
        cur = conn.execute(
            "UPDATE requests SET status='CANCELLED', finished_at=? "
            "WHERE request_id=? AND status IN ('PENDING', 'RUNNING')",
            (time.time(), request_id))
        conn.commit()
        return cur.rowcount

    return _retry_locked(_write, conn) == 1
