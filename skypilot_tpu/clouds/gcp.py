"""GCP cloud with TPU slices as first-class offerings.

Twin of sky/clouds/gcp.py (TPU deploy vars :495-527, stop-unsupported for
TPU pods :216-226), redesigned: instead of forcing host vCPU/mem overrides
onto a VM abstraction (sky/clouds/gcp.py:688-739), TPU slices are their own
catalog rows whose host layout comes from the topology database.
"""
from __future__ import annotations

import os
import typing
from typing import Any, Dict, Optional, Tuple

from skypilot_tpu import exceptions
from skypilot_tpu.clouds import catalog_cloud
from skypilot_tpu.clouds import cloud as cloud_lib
from skypilot_tpu.utils import docker_utils
from skypilot_tpu.utils import registry
from skypilot_tpu.utils import tpu_topology

if typing.TYPE_CHECKING:
    from skypilot_tpu import resources as resources_lib

_Features = cloud_lib.CloudImplementationFeatures

DEFAULT_CREDENTIAL_PATHS = (
    '~/.config/gcloud/application_default_credentials.json',
    os.environ.get('GOOGLE_APPLICATION_CREDENTIALS', ''),
)


def resolve_project_id() -> typing.Optional[str]:
    """GCP project id: $GOOGLE_CLOUD_PROJECT → config gcp.project_id →
    the ADC file's quota_project_id/project_id. Shared by provisioning
    (provider_config_overrides) and the GCS object client."""
    project = os.environ.get('GOOGLE_CLOUD_PROJECT')
    if project:
        return project
    from skypilot_tpu import config as config_lib
    project = config_lib.get_nested(('gcp', 'project_id'))
    if project:
        return project
    import json
    for path in DEFAULT_CREDENTIAL_PATHS:
        if not path:
            continue
        adc = os.path.expanduser(path)
        if not os.path.exists(adc):
            continue
        try:
            with open(adc, encoding='utf-8') as f:
                blob = json.load(f)
            # User ADC carries quota_project_id; service-account keys
            # carry project_id.
            project = blob.get('quota_project_id') or \
                blob.get('project_id')
        except (OSError, ValueError):
            project = None
        if project:
            return project
    return None


@registry.CLOUD_REGISTRY.register(aliases=['google'])
class GCP(catalog_cloud.CatalogCloud):
    _REPR = 'GCP'
    _MAX_CLUSTER_NAME_LEN_LIMIT = 35  # TPU node names are length-limited

    def unsupported_features_for_resources(
        self, resources: 'resources_lib.Resources'
    ) -> Dict[_Features, str]:
        unsupported: Dict[_Features, str] = {}
        topo = self.tpu_topology_of(resources)
        if topo is not None:
            if topo.is_pod or topo.is_multislice:
                # Multi-host TPU slices cannot be stopped, only deleted
                # (reference: sky/clouds/gcp.py:216-226).
                unsupported[_Features.STOP] = (
                    'Multi-host TPU slices cannot be stopped, only torn down.')
                unsupported[_Features.AUTOSTOP] = (
                    'Autostop on multi-host TPU slices performs teardown '
                    'instead of stop.')
        return unsupported

    def make_deploy_resources_variables(
            self, resources: 'resources_lib.Resources', cluster_name: str,
            region: str, zone: Optional[str]) -> Dict[str, Any]:
        from skypilot_tpu import authentication
        vars: Dict[str, Any] = {
            'cluster_name': cluster_name,
            'region': region,
            'zone': zone,
            'instance_type': resources.instance_type,
            'use_spot': resources.use_spot,
            'disk_size': resources.disk_size,
            'ports': resources.ports,
            'labels': dict(resources.labels or {}),
            # docker: image_ids are a task CONTAINER on a default-image
            # VM (backend docker runtime), never a VM source image.
            'image_id': (None if docker_utils.is_docker_image(
                resources.image_id) else resources.image_id),
            # Our keypair rides the `ssh-keys` metadata entry (both the
            # compute and TPU create bodies forward node_config
            # metadata) so freshly created hosts are reachable without
            # OS Login / project-wide keys.
            'ssh_user': authentication.DEFAULT_SSH_USER,
            'metadata': {
                'ssh-keys': authentication.gcp_ssh_keys_metadata()},
            # Copies: the provisioner annotates volume dicts (full
            # source paths) and must never mutate Resources._volumes.
            'volumes': [dict(v) for v in resources.volumes or []],
        }
        topo = self.tpu_topology_of(resources)
        if topo is not None:
            args = resources.accelerator_args or {}
            vars.update({
                'tpu_vm': True,
                'tpu_accelerator_type': topo.gcp_accelerator_type(),
                'tpu_topology': topo.topology_str,
                'tpu_runtime_version': topo.runtime_version(
                    args.get('runtime_version')),
                'tpu_num_slices': topo.num_slices,
                'tpu_num_hosts': topo.num_hosts,
                'tpu_chips_per_host': topo.chips_per_host,
                # Queued resources are the modern capacity-request path
                # (absent from the reference; greenfield per SURVEY §2.3).
                'tpu_use_queued_resources': bool(
                    args.get('use_queued_resources', topo.is_multislice)),
            })
            self._apply_tpu_capacity_model(vars, args)
        elif resources.accelerators:
            name, count = next(iter(resources.accelerators.items()))
            vars.update({'gpu_type': name, 'gpu_count': count})
            self._apply_gpu_capacity_model(
                vars, resources.accelerator_args or {})
        return vars

    @staticmethod
    def _apply_gpu_capacity_model(vars: Dict[str, Any],
                                  args: Dict[str, Any]) -> None:
        """GPU VM twin of the TPU capacity model (reference:
        sky/provision/gcp/mig_utils.py DWS MIGs + reservation-aware
        placement): 'reserved' pins a specific reservation on the VM
        body; 'flex-start' provisions through a DWS MIG resize request
        instead of failing immediately on stockout."""
        model = args.get('provisioning_model', 'standard')
        known = ('standard', 'spot', 'reserved', 'flex-start', 'auto')
        if model not in known:
            raise exceptions.InvalidRequestError(
                f'Unknown provisioning_model {model!r}; expected one '
                f'of {known}.')
        if model == 'spot':
            vars['use_spot'] = True
        elif model == 'reserved':
            if not args.get('reservation'):
                raise exceptions.InvalidRequestError(
                    "provisioning_model 'reserved' requires "
                    "accelerator_args.reservation")
            vars['use_spot'] = False
        elif model == 'flex-start':
            vars['gpu_dws'] = True
            vars['provision_timeout_s'] = float(
                args.get('provision_timeout', 1800))
            if args.get('dws_run_duration'):
                vars['dws_run_duration_s'] = float(
                    args['dws_run_duration'])
        if args.get('reservation') and model in ('standard', 'reserved'):
            vars['reservation'] = args['reservation']

    @staticmethod
    def _apply_tpu_capacity_model(vars: Dict[str, Any],
                                  args: Dict[str, Any]) -> None:
        """Reservations + DWS depth the reference lacks for TPUs
        (sky/provision/gcp/instance_utils.py:1475 notes TPU nodes have
        no reservation plumbing; DWS exists only for MIGs,
        sky/provision/gcp/mig_utils.py:210): here reservations ride the
        node/queued-resource scheduling config and DWS flex-start rides
        a queued resource with a validUntilDuration window.

        accelerator_args:
          provisioning_model: standard | spot | reserved | flex-start
              ('auto' is expanded by the optimizer before deploy)
          reservation: <name>        (required for 'reserved')
          provision_timeout: <sec>   (DWS window; default 1800 for
                                      flex-start)
        """
        model = args.get('provisioning_model', 'standard')
        known = ('standard', 'spot', 'reserved', 'flex-start', 'auto')
        if model not in known:
            raise exceptions.InvalidRequestError(
                f'Unknown provisioning_model {model!r}; expected one '
                f'of {known}.')
        if model == 'spot':
            vars['use_spot'] = True
        elif model == 'reserved':
            if not args.get('reservation'):
                raise exceptions.InvalidRequestError(
                    "provisioning_model 'reserved' requires "
                    "accelerator_args.reservation")
            vars['use_spot'] = False
        elif model == 'flex-start':
            # DWS: request capacity through the queue with a bounded
            # wait window instead of failing immediately on stockout.
            vars['tpu_use_queued_resources'] = True
            vars['provision_timeout_s'] = float(
                args.get('provision_timeout', 1800))
        if args.get('reservation') and model in ('standard', 'reserved'):
            vars['reservation'] = args['reservation']
        if 'provision_timeout_s' not in vars and \
                args.get('provision_timeout'):
            vars['provision_timeout_s'] = float(args['provision_timeout'])

    def provider_config_overrides(
            self, node_config: Dict[str, Any]) -> Dict[str, Any]:
        """Thread the GCP project into provider_config for every
        lifecycle op (run/wait/query/terminate all need it).

        Sources: $GOOGLE_CLOUD_PROJECT, config key gcp.project_id, then
        the ADC file's quota_project_id.
        """
        overrides: Dict[str, Any] = {}
        if node_config.get('volumes'):
            # get_cluster_info builds the mount commands from the
            # persisted provider_config — thread volumes through it.
            overrides['volumes'] = node_config['volumes']
        project = resolve_project_id()
        if project:
            overrides['project_id'] = project
        return overrides

    def check_credentials(self) -> Tuple[bool, Optional[str]]:
        for path in DEFAULT_CREDENTIAL_PATHS:
            if path and os.path.exists(os.path.expanduser(path)):
                return True, None
        return False, (
            'GCP credentials not found. Run `gcloud auth application-default '
            'login`, or set GOOGLE_APPLICATION_CREDENTIALS.')

    def get_credential_file_mounts(self) -> Dict[str, str]:
        mounts = {}
        for path in DEFAULT_CREDENTIAL_PATHS:
            if path and os.path.exists(os.path.expanduser(path)):
                mounts[path] = path
        return mounts

    def get_egress_cost(self, num_gigabytes: float) -> float:
        # Simplified tiered egress pricing (reference models this per cloud).
        if num_gigabytes <= 0:
            return 0.0
        return 0.12 * num_gigabytes
