"""Device mesh construction + logical-axis sharding rules.

The scaling-book recipe, in code: pick a mesh (dp × fsdp × tp × sp × ep ×
stage over ICI, an outer dcn axis across slices), annotate arrays with
*logical* axis names, map logical → physical via rules, and let XLA insert
the collectives. All parallelism strategies the reference orchestrates via
recipes (SURVEY §2.12: DP/TP/PP/EP/SP/FSDP) are expressible as MeshPlans.

Reference parity note: the reference injects env for torchrun+NCCL
(sky/backends/cloud_vm_ray_backend.py:606-670); here the same role is played
by `skypilot_tpu.parallel.distributed` which derives
`jax.distributed.initialize` args from gang-launcher env, and this module
which shapes the devices into a mesh.
"""
from __future__ import annotations

import dataclasses
import math
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

# Canonical mesh axis order: outermost (slowest, DCN-friendly) first.
# data/stage tolerate DCN latency (gradient reduce / p2p activations);
# fsdp/sequence/expert/tensor need ICI bandwidth.
MESH_AXES = ('data', 'stage', 'fsdp', 'sequence', 'expert', 'tensor')


@dataclasses.dataclass(frozen=True)
class MeshPlan:
    """Degree of each parallelism axis. -1 on `data` means 'absorb rest'."""
    data: int = -1
    stage: int = 1      # pipeline stages
    fsdp: int = 1       # param/grad/optimizer sharding (ZeRO-3 twin)
    sequence: int = 1   # context parallelism (ring attention axis)
    expert: int = 1     # MoE expert parallelism
    tensor: int = 1     # megatron-style tensor parallelism

    def resolve(self, num_devices: int) -> 'MeshPlan':
        sizes = dataclasses.asdict(self)
        fixed = math.prod(v for v in sizes.values() if v != -1)
        free = [k for k, v in sizes.items() if v == -1]
        if len(free) > 1:
            raise ValueError('At most one mesh axis may be -1.')
        if free:
            if num_devices % fixed:
                raise ValueError(
                    f'{num_devices} devices not divisible by fixed axes '
                    f'product {fixed} ({sizes}).')
            sizes[free[0]] = num_devices // fixed
        elif fixed != num_devices:
            raise ValueError(
                f'Mesh plan {sizes} needs {fixed} devices, got '
                f'{num_devices}.')
        return MeshPlan(**sizes)

    def axis_sizes(self) -> Tuple[int, ...]:
        d = dataclasses.asdict(self)
        return tuple(d[a] for a in MESH_AXES)


def build_mesh(plan: Optional[MeshPlan] = None,
               devices: Optional[Sequence[jax.Device]] = None,
               num_slices: int = 1) -> Mesh:
    """Build a Mesh over devices.

    Within one slice, `mesh_utils.create_device_mesh` arranges devices so
    adjacent mesh coordinates are ICI neighbors. With num_slices > 1, the
    'data' axis is laid out across slices first so only gradient reduction
    rides DCN (megascale).
    """
    if devices is None:
        devices = jax.devices()
    devices = list(devices)
    plan = (plan or MeshPlan()).resolve(len(devices))
    shape = plan.axis_sizes()
    if num_slices > 1:
        if plan.data % num_slices:
            raise ValueError(
                f'data axis ({plan.data}) must be a multiple of num_slices '
                f'({num_slices}) for multislice layout.')
        if len(devices) % num_slices:
            raise ValueError(
                f'{len(devices)} devices not divisible into '
                f'{num_slices} slices.')
        from jax.experimental import mesh_utils
        per_slice = len(devices) // num_slices
        dcn_shape = (num_slices, 1, 1, 1, 1, 1)
        ici_shape = (plan.data // num_slices,) + shape[1:]
        if hasattr(devices[0], 'slice_index'):
            device_array = mesh_utils.create_hybrid_device_mesh(
                ici_shape, dcn_shape, devices=devices)
        else:
            # Virtual devices (CPU dry runs) carry no slice topology:
            # partition the ordered device list into contiguous
            # "slices", lay each out as its own ICI mesh, and stack so
            # the slice index becomes the outermost (slowest-varying)
            # stride of the 'data' axis — the same data-outermost
            # layout create_hybrid_device_mesh produces, so collectives
            # compile identically to the real multislice case.
            slabs = []
            for s in range(num_slices):
                group = devices[s * per_slice:(s + 1) * per_slice]
                try:
                    slab = mesh_utils.create_device_mesh(
                        ici_shape, devices=group)
                except (ValueError, AssertionError):
                    slab = np.asarray(group).reshape(ici_shape)
                slabs.append(slab)
            device_array = np.concatenate(slabs, axis=0)
    else:
        try:
            from jax.experimental import mesh_utils
            device_array = mesh_utils.create_device_mesh(
                shape, devices=devices)
        except (ValueError, AssertionError):
            device_array = np.asarray(devices).reshape(shape)
    return Mesh(device_array, MESH_AXES)


# ---- logical axis rules ---------------------------------------------------
# Arrays are annotated with logical axis names; these rules map them onto
# mesh axes (first matching rule wins). MaxText-style layout.

LogicalRules = Tuple[Tuple[str, Any], ...]

DEFAULT_RULES: LogicalRules = (
    ('batch', ('data', 'fsdp')),          # activations: batch over dp+fsdp
    ('activation_length', 'sequence'),    # context parallelism
    ('activation_embed', None),
    ('activation_heads', 'tensor'),
    ('activation_kv', None),
    ('activation_mlp', 'tensor'),
    ('embed', 'fsdp'),                    # params: embed dim over fsdp
    ('heads', 'tensor'),
    ('kv', None),
    ('mlp', 'tensor'),
    ('vocab', 'tensor'),
    ('expert', 'expert'),
    ('layers', None),                     # scanned-layer leading axis
    ('stage', 'stage'),
)

# Pipeline-parallel layout: the stacked layer axis is sharded over the
# 'stage' mesh axis so each pipeline stage holds (and updates) only its
# own block of layers. Everything else is unchanged.
PIPELINE_RULES: LogicalRules = tuple(
    ('layers', 'stage') if name == 'layers' else (name, target)
    for name, target in DEFAULT_RULES)


def logical_to_spec(logical_axes: Sequence[Optional[str]],
                    rules: LogicalRules = DEFAULT_RULES) -> PartitionSpec:
    rule_map = dict(rules)
    spec: List[Any] = []
    used: set = set()
    for name in logical_axes:
        target = rule_map.get(name) if name is not None else None
        if target is None:
            spec.append(None)
            continue
        targets = target if isinstance(target, tuple) else (target,)
        # A mesh axis may shard at most one array dimension.
        targets = tuple(t for t in targets if t not in used)
        used.update(targets)
        if not targets:
            spec.append(None)
        elif len(targets) == 1:
            spec.append(targets[0])
        else:
            spec.append(targets)
    return PartitionSpec(*spec)


def named_sharding(mesh: Mesh, logical_axes: Sequence[Optional[str]],
                   rules: LogicalRules = DEFAULT_RULES) -> NamedSharding:
    return NamedSharding(mesh, logical_to_spec(logical_axes, rules))


def shard_logical(x, mesh: Mesh, logical_axes: Sequence[Optional[str]],
                  rules: LogicalRules = DEFAULT_RULES):
    """with_sharding_constraint by logical axis names (inside jit)."""
    return jax.lax.with_sharding_constraint(
        x, named_sharding(mesh, logical_axes, rules))


def tree_shardings(mesh: Mesh, logical_tree,
                   rules: LogicalRules = DEFAULT_RULES):
    """Map a pytree of logical-axis tuples to a pytree of NamedShardings."""
    return jax.tree.map(
        lambda axes: named_sharding(mesh, axes, rules),
        logical_tree,
        is_leaf=lambda x: isinstance(x, tuple) and all(
            a is None or isinstance(a, str) for a in x))
