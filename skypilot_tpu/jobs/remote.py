"""Remote jobs-controller mode: controllers on a provisioned cluster.

Twin of the reference's jobs-controller-as-a-cluster
(sky/templates/jobs-controller.yaml.j2:1-30 + sky/jobs/utils.py
ManagedJobCodeGen): the API server provisions a dedicated controller
cluster once, then forwards every jobs verb to it by running
``python -m skypilot_tpu.jobs.remote_exec <verb>`` on the controller
head over the backend command runner. The managed-jobs DB, the
scheduler, and all controller processes live on that cluster; the local
host only relays requests.

Enabled with XSKY_JOBS_CONTROLLER_REMOTE=1 (or =<cluster-name>).
Controller sizing comes from config key jobs.controller.resources.
"""
from __future__ import annotations

import json
import os
import shlex
import tempfile
import time
from typing import Any, Dict, List, Optional

from skypilot_tpu import config as config_lib
from skypilot_tpu import exceptions
from skypilot_tpu import sky_logging
from skypilot_tpu import task as task_lib

logger = sky_logging.init_logger(__name__)

_DEFAULT_CLUSTER = 'xsky-jobs-controller'


def cluster_name() -> str:
    value = os.environ.get('XSKY_JOBS_CONTROLLER_REMOTE', '')
    if value in ('', '0', '1'):
        return _DEFAULT_CLUSTER
    return value


def _controller_task() -> task_lib.Task:
    from skypilot_tpu import resources as resources_lib
    overrides = config_lib.get_nested(
        ('jobs', 'controller', 'resources'), {}) or {}
    t = task_lib.Task('jobs-controller')
    t.set_resources(resources_lib.Resources.from_yaml_config(overrides))
    return t


def ensure_controller_cluster(provision: bool = True) -> Any:
    """Return the controller cluster's handle.

    provision=True (mutating verbs: launch) brings the cluster up if
    needed; read verbs pass False and get ClusterNotUpError instead of
    provisioning infrastructure as a side effect.
    """
    from skypilot_tpu import execution
    from skypilot_tpu import state as state_lib
    name = cluster_name()
    record = state_lib.get_cluster_from_name(name)
    if record is not None and record['status'] == state_lib.ClusterStatus.UP:
        return record['handle']
    if not provision:
        raise exceptions.ClusterNotUpError(
            f'Jobs controller cluster {name!r} is not UP; launch a '
            'managed job first.',
            cluster_status=record['status'] if record else None)
    _, handle = execution.launch(_controller_task(), cluster_name=name)
    return handle


def _backend_and_handle(provision: bool):
    from skypilot_tpu.backends import tpu_gang_backend
    handle = ensure_controller_cluster(provision)
    return tpu_gang_backend.TpuGangBackend(), handle


def _call(verb: str, *args: str,
          payload_file: Optional[str] = None,
          provision: bool = False) -> Any:
    """Run remote_exec on the controller head, parse its JSON reply."""
    backend, handle = _backend_and_handle(provision)
    remote_args = list(args)
    if payload_file is not None:
        # Home-relative so every runner flavor (local host-root, ssh
        # $HOME, k8s /root) resolves it consistently for both the rsync
        # and the remote open().
        remote_path = (f'.xsky/managed_tasks/'
                       f'{os.path.basename(payload_file)}')
        runner = handle.head_runner()
        runner.run(f'mkdir -p {shlex.quote(os.path.dirname(remote_path))}')
        runner.rsync(payload_file, remote_path, up=True)
        remote_args.append(remote_path)
    rc, stdout, stderr = backend.run_module_on_head(
        handle, 'skypilot_tpu.jobs.remote_exec', verb, *remote_args)
    if rc != 0:
        raise exceptions.CommandError(
            rc, f'jobs.remote_exec {verb}',
            f'remote jobs controller failed: {stderr.strip()}')
    # remote_exec prints exactly one JSON line last.
    line = stdout.strip().splitlines()[-1]
    return json.loads(line)


def launch(task: task_lib.Task, name: Optional[str] = None,
           wait: bool = False, timeout_s: float = 600.0) -> int:
    with tempfile.NamedTemporaryFile(
            'w', suffix='.yaml', prefix='xsky-mjob-',
            delete=False) as f:
        f.write(json.dumps(task.to_yaml_config()))
        local_path = f.name
    try:
        reply = _call('submit', *(['--name', name] if name else []),
                      payload_file=local_path, provision=True)
    finally:
        os.unlink(local_path)
    job_id = int(reply['job_id'])
    if wait:
        deadline = time.time() + timeout_s
        while time.time() < deadline:
            row = _call('get', str(job_id))
            if row and row.get('terminal'):
                return job_id
            time.sleep(1.0)
        raise TimeoutError(f'Managed job {job_id} not terminal '
                           f'after {timeout_s}s')
    return job_id


def queue() -> List[Dict[str, Any]]:
    return _call('queue')


def cancel(job_id: int) -> None:
    _call('cancel', str(job_id))


def tail_logs(job_id: int) -> str:
    return _call('logs', str(job_id))['logs']
