"""Round-hygiene reaper: leaked framework processes are found + killed,
and report mode tells `owned` (a record claims the process) from
`leaked` (nothing in the control plane knows it)."""
import os
import subprocess
import sys
import time

from skypilot_tpu.utils import reaper


def _spawn_decoy(marker: str = 'skypilot_tpu.agent.job_runner',
                 *args: str) -> subprocess.Popen:
    """A detached process whose cmdline carries a framework marker —
    stands in for a leaked daemon without needing a cluster. Extra
    args land in argv after the marker (ownership lookups parse the
    token following the module name)."""
    return subprocess.Popen(
        [sys.executable, '-c', 'import time; time.sleep(120)',
         marker, *args],
        start_new_session=True)


def test_find_and_reap_leaked():
    proc = _spawn_decoy()
    try:
        time.sleep(0.3)
        leaked = reaper.find_leaked()
        assert any(r['pid'] == proc.pid for r in leaked), leaked
        reaper.reap(grace_s=3.0)
        # Reaped: the decoy is gone.
        deadline = time.time() + 5
        while time.time() < deadline and proc.poll() is None:
            time.sleep(0.1)
        assert proc.poll() is not None
        assert not any(r['pid'] == proc.pid
                       for r in reaper.find_leaked())
    finally:
        if proc.poll() is None:
            proc.kill()
        proc.wait()


def test_own_tree_excluded():
    """A reap run from inside a framework process must not eat its own
    ancestry (find_leaked excludes the caller's process tree)."""
    leaked = reaper.find_leaked(patterns=('pytest',))
    assert not any(r['pid'] == os.getpid() for r in leaked)


def test_cli_reap_reports(capsys):
    from click.testing import CliRunner
    from skypilot_tpu.client import cli as cli_mod
    proc = _spawn_decoy()
    try:
        time.sleep(0.3)
        runner = CliRunner()
        result = runner.invoke(cli_mod.cli, ['reap'])
        assert result.exit_code == 0, result.output
        assert str(proc.pid) in result.output
        result = runner.invoke(cli_mod.cli, ['reap', '--kill'])
        assert result.exit_code == 0, result.output
        assert 'killed' in result.output
    finally:
        if proc.poll() is None:
            proc.kill()
        proc.wait()


class TestOwnedVsLeaked:
    """Report mode consults cluster/job/service records: a process a
    live record claims is `owned`; everything else is `leaked`, and
    --leaked-only kills only the latter."""

    def test_jobs_controller_classification(self, monkeypatch,
                                            tmp_path):
        from skypilot_tpu.jobs import state as jobs_state
        monkeypatch.setenv('XSKY_JOBS_DB',
                           str(tmp_path / 'managed_jobs.db'))
        job_id = jobs_state.add_job('mine', {'run': 'echo x'})
        jobs_state.set_status(job_id,
                              jobs_state.ManagedJobStatus.RUNNING)
        owned = _spawn_decoy('skypilot_tpu.jobs.controller',
                             str(job_id))
        leaked = _spawn_decoy('skypilot_tpu.jobs.controller', '424242')
        jobs_state.set_controller_pid(job_id, owned.pid)
        try:
            time.sleep(0.3)
            by_pid = {r['pid']: r for r in reaper.classify()}
            assert by_pid[owned.pid]['owned'], by_pid[owned.pid]
            assert by_pid[owned.pid]['owner'] == f'job/{job_id}'
            assert not by_pid[leaked.pid]['owned']
            # --leaked-only spares the record-owned controller.
            swept = reaper.reap(grace_s=3.0, leaked_only=True)
            swept_pids = {r['pid'] for r in swept}
            assert leaked.pid in swept_pids
            assert owned.pid not in swept_pids
            assert owned.poll() is None   # still running
        finally:
            for proc in (owned, leaked):
                if proc.poll() is None:
                    proc.kill()
                proc.wait()

    def test_terminal_job_controller_is_leaked(self, monkeypatch,
                                               tmp_path):
        """A controller whose job already finished holds nothing: its
        record is terminal, so the process is a leak."""
        from skypilot_tpu.jobs import state as jobs_state
        monkeypatch.setenv('XSKY_JOBS_DB',
                           str(tmp_path / 'managed_jobs.db'))
        job_id = jobs_state.add_job('done', {'run': 'echo x'})
        jobs_state.set_status(job_id,
                              jobs_state.ManagedJobStatus.SUCCEEDED)
        proc = _spawn_decoy('skypilot_tpu.jobs.controller', str(job_id))
        jobs_state.set_controller_pid(job_id, proc.pid)
        try:
            time.sleep(0.3)
            by_pid = {r['pid']: r for r in reaper.classify()}
            assert not by_pid[proc.pid]['owned']
        finally:
            proc.kill()
            proc.wait()

    def test_cli_reap_annotates_and_filters(self, monkeypatch,
                                            tmp_path):
        from click.testing import CliRunner
        from skypilot_tpu.client import cli as cli_mod
        monkeypatch.setenv('XSKY_JOBS_DB',
                           str(tmp_path / 'managed_jobs.db'))
        proc = _spawn_decoy()   # record-less job runner → leaked
        try:
            time.sleep(0.3)
            runner = CliRunner()
            result = runner.invoke(cli_mod.cli, ['reap'])
            assert result.exit_code == 0, result.output
            line = next(l for l in result.output.splitlines()
                        if str(proc.pid) in l)
            assert 'LEAKED' in line
            result = runner.invoke(cli_mod.cli,
                                   ['reap', '--leaked-only'])
            assert str(proc.pid) in result.output
        finally:
            if proc.poll() is None:
                proc.kill()
            proc.wait()
